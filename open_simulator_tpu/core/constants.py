"""Simulator constants: annotations, labels, stop reasons, env knobs.

Mirrors the constant surface of the reference (/root/reference/pkg/type/const.go:7-43 and
pkg/utils/const.go:3-17) so configs and annotated YAML written for the reference load
unchanged.
"""

# --- scheduler identity -------------------------------------------------------------------
DefaultSchedulerName = "default-scheduler"
SimonPluginName = "Simon"
OpenLocalPluginName = "Open-Local"
OpenGpuSharePluginName = "Open-Gpu-Share"

# --- annotations & labels (pkg/type/const.go) ---------------------------------------------
AnnoWorkloadKind = "simon/workload-kind"
AnnoWorkloadName = "simon/workload-name"
AnnoNodeLocalStorage = "simon/node-local-storage"
AnnoPodLocalStorage = "simon/pod-local-storage"
AnnoNodeGpuShare = "simon/node-gpu-share"
AnnoPodProvisioner = "simon/pod-provisioned-by"
AnnoWorkloadNamespace = "simon/workload-namespace"

LabelNewNode = "simon/new-node"
LabelAppName = "simon/app-name"
LabelDaemonSetFromCluster = "simon/daemonset-from-cluster"

# --- workload kinds -----------------------------------------------------------------------
Pod = "Pod"
Deployment = "Deployment"
ReplicaSet = "ReplicaSet"
ReplicationController = "ReplicationController"
StatefulSet = "StatefulSet"
DaemonSet = "DaemonSet"
Job = "Job"
CronJob = "CronJob"
Service = "Service"
PodDisruptionBudget = "PodDisruptionBudget"
StorageClass = "StorageClass"
PersistentVolumeClaim = "PersistentVolumeClaim"
ConfigMap = "ConfigMap"
Node = "Node"

WorkloadKinds = (Deployment, ReplicaSet, ReplicationController, StatefulSet, DaemonSet, Job, CronJob)

# --- gpu-share annotations (pkg/type/open-gpu-share/utils/const.go:3-9) -------------------
AnnoGpuMem = "alibabacloud.com/gpu-mem"            # pod: per-GPU memory request (ResourceName)
AnnoGpuCount = "alibabacloud.com/gpu-count"        # pod: number of GPUs wanted (CountName)
AnnoGpuIndex = "alibabacloud.com/gpu-index"        # pod: assigned device id(s), e.g. "0-2"
AnnoGpuAssumeTime = "alibabacloud.com/assume-time" # pod: set at Reserve
AnnoGpuModel = "alibabacloud.com/gpu-card-model"   # node label: card model
ResourceGpuMem = "alibabacloud.com/gpu-mem"        # node capacity: total sharable GPU mem
ResourceGpuCount = "alibabacloud.com/gpu-count"    # node capacity: whole-GPU count

# --- fake node factory (pkg/type/const.go:11, pkg/utils/utils.go:885-915) -----------------
NewNodeNamePrefix = "simon"

# --- stop reasons (pkg/simulator/simulator.go:449-468) ------------------------------------
StopReasonSuccess = "Success"
StopReasonUnschedulable = "Unschedulable"
PodReasonUnschedulable = "Unschedulable"

CreatePodError = "failed to create pod"
DeletePodError = "failed to delete pod"

# --- env knobs (pkg/apply/apply.go:694-719) -----------------------------------------------
EnvMaxCPU = "MaxCPU"
EnvMaxMemory = "MaxMemory"
EnvMaxVG = "MaxVG"
EnvLogLevel = "LogLevel"

# --- well-known k8s label/taint keys ------------------------------------------------------
LabelHostname = "kubernetes.io/hostname"
LabelTopologyZone = "topology.kubernetes.io/zone"
LabelTopologyZoneBeta = "failure-domain.beta.kubernetes.io/zone"
LabelTopologyRegion = "topology.kubernetes.io/region"
TaintNodeUnschedulable = "node.kubernetes.io/unschedulable"

# --- open-local storage class names (pkg/utils/const.go) ----------------------------------
OpenLocalSCNameLVM = "open-local-lvm"
OpenLocalSCNameDeviceHDD = "open-local-device-hdd"
OpenLocalSCNameDeviceSSD = "open-local-device-ssd"
OpenLocalSCNameMountPointHDD = "open-local-mountpoint-hdd"
OpenLocalSCNameMountPointSSD = "open-local-mountpoint-ssd"
YodaSCNameLVM = "yoda-lvm-default"
YodaSCNameDeviceHDD = "yoda-device-hdd"
YodaSCNameDeviceSSD = "yoda-device-ssd"
YodaSCNameMountPointHDD = "yoda-mountpoint-hdd"
YodaSCNameMountPointSSD = "yoda-mountpoint-ssd"
