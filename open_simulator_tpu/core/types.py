"""Core result/resource types of the simulator's public API.

Mirrors /root/reference/pkg/simulator/core.go:19-57 (`SimulateResult`, `UnscheduledPod`,
`NodeStatus`, `ResourceTypes`, `AppResource`) — but objects are plain Python dicts parsed
from YAML (the k8s JSON shape), not generated client types. Accessors in
`open_simulator_tpu.utils.objutil` provide the typed views.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List


@dataclass
class ResourceTypes:
    """Bucketed k8s objects making up a cluster or an app (core.go:36-50)."""

    pods: List[dict] = field(default_factory=list)
    nodes: List[dict] = field(default_factory=list)
    deployments: List[dict] = field(default_factory=list)
    replica_sets: List[dict] = field(default_factory=list)
    replication_controllers: List[dict] = field(default_factory=list)
    stateful_sets: List[dict] = field(default_factory=list)
    daemon_sets: List[dict] = field(default_factory=list)
    jobs: List[dict] = field(default_factory=list)
    cron_jobs: List[dict] = field(default_factory=list)
    services: List[dict] = field(default_factory=list)
    pod_disruption_budgets: List[dict] = field(default_factory=list)
    storage_classes: List[dict] = field(default_factory=list)
    persistent_volume_claims: List[dict] = field(default_factory=list)
    config_maps: List[dict] = field(default_factory=list)

    def extend(self, other: "ResourceTypes") -> None:
        for f in self.__dataclass_fields__:
            getattr(self, f).extend(getattr(other, f))

    def copy(self) -> "ResourceTypes":
        out = ResourceTypes()
        for f in self.__dataclass_fields__:
            setattr(out, f, list(getattr(self, f)))
        return out


@dataclass
class AppResource:
    """One application to deploy, in order (core.go:52-57)."""

    name: str
    resource: ResourceTypes


@dataclass
class UnscheduledPod:
    """A pod the scheduler could not place, with a k8s-style reason message (core.go:25-29)."""

    pod: dict
    reason: str


@dataclass
class NodeStatus:
    """Per-node placement: the node object and every pod bound to it (core.go:31-34)."""

    node: dict
    pods: List[dict] = field(default_factory=list)


@dataclass
class SimulateResult:
    """Outcome of one simulation (core.go:19-23).

    `backend_path` (extension, simonguard): the JAX backends the run executed
    on, in order — `["tpu"]` for a clean run, `["tpu", "cpu"]` after a
    mid-run device-failure failover. A degraded run changes this field and
    the guard metrics, never silently just the numbers."""

    unscheduled_pods: List[UnscheduledPod] = field(default_factory=list)
    node_status: List[NodeStatus] = field(default_factory=list)
    backend_path: List[str] = field(default_factory=list)

    @property
    def all_scheduled(self) -> bool:
        return not self.unscheduled_pods

    def node_map(self) -> Dict[str, NodeStatus]:
        return {ns.node["metadata"]["name"]: ns for ns in self.node_status}


# Kind string → ResourceTypes field name (yamlio uses this to bucket decoded docs).
KIND_TO_FIELD = {
    "Pod": "pods",
    "Node": "nodes",
    "Deployment": "deployments",
    "ReplicaSet": "replica_sets",
    "ReplicationController": "replication_controllers",
    "StatefulSet": "stateful_sets",
    "DaemonSet": "daemon_sets",
    "Job": "jobs",
    "CronJob": "cron_jobs",
    "Service": "services",
    "PodDisruptionBudget": "pod_disruption_budgets",
    "StorageClass": "storage_classes",
    "PersistentVolumeClaim": "persistent_volume_claims",
    "ConfigMap": "config_maps",
}
