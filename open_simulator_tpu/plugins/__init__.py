"""Extended-resource plugins: GPU-share memory bin-packing and Open-Local storage.

These mirror the reference's out-of-tree scheduler plugins
(/root/reference/pkg/simulator/plugin/), re-designed for the batched TPU engine:
feasibility/score terms are evaluated as dense per-node tensors inside the scan
kernel, while a host-side ledger replays allocations to assign device ids / volume
groups and maintain the report annotations.
"""
