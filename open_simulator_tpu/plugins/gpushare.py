"""GPU-share: GPU-memory-sharing simulation (the Open-Gpu-Share plugin).

Mirrors /root/reference/pkg/simulator/plugin/open-gpu-share.go and
pkg/type/open-gpu-share/{cache,utils}: pods request `alibabacloud.com/gpu-mem`
(memory PER GPU) + `alibabacloud.com/gpu-count` via annotations; nodes advertise
total sharable GPU memory and whole-GPU count in status.capacity.

Split of responsibilities in the TPU build:
- The FILTER (node has enough total + per-device memory, open-gpu-share.go:51-81)
  runs inside the batched kernel as dense [N, MAXDEV] tensor math (ops/kernels.py).
- The ALLOCATOR (device-id assignment: tightest-fit for 1 GPU, two-pointer greedy
  for multi-GPU — gpunodeinfo.go:232-290) is replayed here on the host for each
  committed pod, producing the `gpu-index` annotation, the `simon/node-gpu-share`
  node annotation, and the whole-GPU allocatable update exactly like Reserve
  (open-gpu-share.go:147-188). Device-side dev_used and the host ledger follow the
  same deterministic algorithm, so they never diverge.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core import constants as C
from ..utils.objutil import annotations_of, name_of, namespace_of
from ..utils.quantity import format_quantity, parse_quantity


# --------------------------------------------------------------- pod annotations ----


def pod_gpu_mem(pod: dict) -> int:
    """GetGpuMemoryFromPodAnnotation: per-GPU memory request, 0 when absent."""
    raw = annotations_of(pod).get(C.AnnoGpuMem)
    if raw is None:
        return 0
    try:
        return int(parse_quantity(raw))
    except ValueError:
        return 0


def pod_gpu_count(pod: dict) -> int:
    """GetGpuCountFromPodAnnotation: number of GPUs, 0 when absent/invalid."""
    raw = annotations_of(pod).get(C.AnnoGpuCount)
    try:
        v = int(str(raw))
    except (TypeError, ValueError):
        return 0
    return v if v >= 0 else 0


def pod_gpu_index(pod: dict) -> str:
    return annotations_of(pod).get(C.AnnoGpuIndex, "")


def gpu_id_str_to_list(id_str: str) -> List[int]:
    """GpuIdStrToIntList: "2-3-4" -> [2, 3, 4]; raises ValueError on junk."""
    if not id_str:
        return []
    return [int(tok) for tok in id_str.split("-")]


# ----------------------------------------------------------------- node capacity ----


def node_total_gpu_memory(node: dict) -> int:
    """GetTotalGpuMemory reads status.CAPACITY (not allocatable)."""
    cap = (node.get("status") or {}).get("capacity") or {}
    raw = cap.get(C.ResourceGpuMem)
    if raw is None:
        return 0
    try:
        return int(parse_quantity(raw))
    except ValueError:
        return 0


def node_gpu_count(node: dict) -> int:
    cap = (node.get("status") or {}).get("capacity") or {}
    raw = cap.get(C.ResourceGpuCount)
    if raw is None:
        return 0
    try:
        return int(parse_quantity(raw))
    except ValueError:
        return 0


def node_gpu_model(node: dict) -> str:
    lbls = ((node.get("metadata") or {}).get("labels")) or {}
    return lbls.get(C.AnnoGpuModel, "N/A")


# -------------------------------------------------------------------- allocator -----


def allocate_gpu_ids(
    dev_total: List[int], dev_used: List[int], mem: int, num: int,
    preassigned: str = "",
) -> Tuple[str, bool]:
    """AllocateGpuId (gpunodeinfo.go:232-290). Returns ("i-j-k", found)."""
    if mem <= 0 or num <= 0:
        return "", False
    n_devs = len(dev_total)
    idle = [dev_total[i] - dev_used[i] for i in range(n_devs)]
    if n_devs <= 0:
        return "", False

    if preassigned:
        try:
            if gpu_id_str_to_list(preassigned):
                return preassigned, True
        except ValueError:
            pass

    if num == 1:
        cand, cand_mem = -1, 0
        for dev in range(n_devs):
            if idle[dev] >= mem and (cand < 0 or idle[dev] < cand_mem):
                cand, cand_mem = dev, idle[dev]
        return (str(cand), True) if cand >= 0 else ("", False)

    ids: List[int] = []
    dev = 0
    while dev < n_devs and len(ids) < num:
        if idle[dev] >= mem:
            ids.append(dev)
            idle[dev] -= mem
        else:
            dev += 1
    if len(ids) == num:
        return "-".join(str(i) for i in ids), True
    return "", False


# ------------------------------------------------------------------ host ledger -----


class GpuNodeState:
    """Per-node device ledger (GpuNodeInfo + DeviceInfo)."""

    def __init__(self, node: dict) -> None:
        self.node = node
        self.model = node_gpu_model(node)
        self.gpu_count = node_gpu_count(node)
        self.total_mem = node_total_gpu_memory(node)
        per_dev = self.total_mem // self.gpu_count if self.gpu_count else 0
        self.dev_total = [per_dev] * self.gpu_count
        self.dev_used = [0] * self.gpu_count
        self.dev_pods: List[List[dict]] = [[] for _ in range(self.gpu_count)]

    def add_pod(self, pod: dict) -> None:
        """addOrUpdatePod: account the pod's gpu-index against its devices."""
        mem = pod_gpu_mem(pod)
        try:
            idl = gpu_id_str_to_list(pod_gpu_index(pod))
        except ValueError:
            return
        for idx in idl:
            if 0 <= idx < self.gpu_count:
                if all(p is not pod for p in self.dev_pods[idx]):
                    self.dev_pods[idx].append(pod)
                self.dev_used[idx] += mem

    def export_info(self) -> dict:
        """ExportGpuNodeInfoAsNodeGpuInfo → the ffjson field layout the reference
        writes into the simon/node-gpu-share annotation (gpunodeinfo.go:345-368).
        Quantities are Mi-truncated strings, like the Go code's %dMi round-trip."""
        gpu_allocatable = self.gpu_count
        devs_brief: Dict[str, dict] = {}
        num_pods = 0
        for idx in range(self.gpu_count):
            used = self.dev_used[idx]
            total = self.dev_total[idx]
            if used >= total and total > 0:
                gpu_allocatable -= 1
            pod_list = [
                f"{namespace_of(p)}:{name_of(p)}" for p in sorted(
                    self.dev_pods[idx], key=lambda p: (namespace_of(p), name_of(p))
                )
            ]
            devs_brief[str(idx)] = {
                "PodList": pod_list,
                "GpuTotalMemory": _mi(total),
                "GpuUsedMemory": _mi(used),
            }
            num_pods += len(pod_list)
        return {
            "DevsBrief": devs_brief,
            "GpuCount": self.gpu_count,
            "GpuAllocatable": gpu_allocatable,
            "GpuModel": self.model,
            "GpuTotalMemory": _mi(self.total_mem),
            "NumPods": num_pods,
        }


def _mi(v: int) -> str:
    return f"{v // (1 << 20)}Mi"


class GpuShareHost:
    """The host half of the plugin: replays allocations for committed pods."""

    def __init__(self, nodes: List[dict]) -> None:
        store = getattr(nodes, "store", None)  # simulator/store.py LazyNodeSeq
        if store is not None and not store.may_have_gpu:
            # columnar fast path: no block template advertises GPU memory, so
            # the per-node dict scan would materialize N dicts to learn that
            self.states: List[Optional[GpuNodeState]] = [None] * len(nodes)
        else:
            self.states = [
                GpuNodeState(n) if node_total_gpu_memory(n) > 0 else None
                for n in nodes
            ]
        self.max_devs = max((s.gpu_count for s in self.states if s), default=0)
        self._assume_seq = 0
        # nodes whose annotation/allocatable writeback is pending: the ledger
        # updates per pod, but the JSON rewrite happens once per node per
        # schedule_pods call (engine flushes) instead of once per commit
        self._dirty: set = set()

    @property
    def enabled(self) -> bool:
        return self.max_devs > 0

    def dev_total_matrix(self, max_devs: int) -> np.ndarray:
        """[N, max_devs] per-device total memory (0 = absent device)."""
        out = np.zeros((len(self.states), max_devs), np.float32)
        for i, s in enumerate(self.states):
            if s:
                out[i, : s.gpu_count] = s.dev_total
        return out

    def dev_used_matrix(self, max_devs: int) -> np.ndarray:
        out = np.zeros((len(self.states), max_devs), np.float32)
        for i, s in enumerate(self.states):
            if s:
                out[i, : s.gpu_count] = s.dev_used
        return out

    def reserve(self, pod: dict, node_i: int) -> bool:
        """The Reserve path for one committed pod: allocate ids, annotate the pod,
        refresh the node annotation + whole-GPU allocatable. Returns False when the
        pod needs no GPU."""
        mem = pod_gpu_mem(pod)
        if mem <= 0:
            return False
        state = self.states[node_i]
        if state is None:
            return False  # kernel filter should prevent this
        ids, found = allocate_gpu_ids(
            state.dev_total, list(state.dev_used), mem, pod_gpu_count(pod),
            pod_gpu_index(pod),
        )
        if not found:
            return False
        anns = pod.setdefault("metadata", {}).setdefault("annotations", {})
        anns[C.AnnoGpuIndex] = ids
        self._assume_seq += 1
        anns[C.AnnoGpuAssumeTime] = str(self._assume_seq)
        state.add_pod(pod)
        self._dirty.add(node_i)
        return True

    def _refresh_node(self, state: GpuNodeState) -> None:
        import json

        info = state.export_info()
        md = state.node.setdefault("metadata", {})
        md.setdefault("annotations", {})[C.AnnoNodeGpuShare] = json.dumps(info)
        alloc = state.node.setdefault("status", {}).setdefault("allocatable", {})
        alloc[C.ResourceGpuCount] = str(info["GpuAllocatable"])

    def release(self, pod: dict, node_i: int) -> None:
        """Undo one committed pod's allocation (preemption eviction): subtract
        its memory from the devices named by its gpu-index annotation and drop
        it from the per-device pod lists. The reference has no release path —
        a deleted pod's share lingers in its cache — but leaving it here would
        desync the ledger from pods_on_node, which this build treats as the
        single source of truth (see simulator/preemption.py)."""
        mem = pod_gpu_mem(pod)
        state = self.states[node_i]
        if mem <= 0 or state is None:
            return
        try:
            idl = gpu_id_str_to_list(pod_gpu_index(pod))
        except ValueError:
            return
        for idx in idl:
            if 0 <= idx < state.gpu_count:
                state.dev_used[idx] -= mem
                state.dev_pods[idx] = [p for p in state.dev_pods[idx] if p is not pod]
        self._dirty.add(node_i)

    def snapshot(self):
        """Copy of all mutable ledger state + the node fields this plugin owns
        (annotation + whole-GPU allocatable), for preemption rewind."""
        states = []
        for s in self.states:
            if s is None:
                states.append(None)
                continue
            anns = (s.node.get("metadata") or {}).get("annotations") or {}
            alloc = (s.node.get("status") or {}).get("allocatable") or {}
            states.append((
                list(s.dev_used), [list(dp) for dp in s.dev_pods],
                anns.get(C.AnnoNodeGpuShare), alloc.get(C.ResourceGpuCount),
            ))
        return states, self._assume_seq, set(self._dirty)

    def restore(self, snap) -> None:
        states, self._assume_seq, self._dirty = snap[0], snap[1], set(snap[2])
        for s, rec in zip(self.states, states):
            if s is None or rec is None:
                continue
            s.dev_used = list(rec[0])
            s.dev_pods = [list(dp) for dp in rec[1]]
            anns = s.node.setdefault("metadata", {}).setdefault("annotations", {})
            if rec[2] is None:
                anns.pop(C.AnnoNodeGpuShare, None)
            else:
                anns[C.AnnoNodeGpuShare] = rec[2]
            alloc = s.node.setdefault("status", {}).setdefault("allocatable", {})
            if rec[3] is None:
                alloc.pop(C.ResourceGpuCount, None)
            else:
                alloc[C.ResourceGpuCount] = rec[3]

    def seed_pod(self, pod: dict, node_i: int) -> None:
        """Account one already-bound pod carrying a gpu-index annotation
        (live-cluster snapshots); O(1) per pod."""
        state = self.states[node_i]
        if state is None:
            return
        if pod_gpu_index(pod) and pod_gpu_mem(pod) > 0:
            state.add_pod(pod)
            self._dirty.add(node_i)

    def flush(self) -> None:
        """Write the pending node annotations + whole-GPU allocatable (the
        writeback half of Reserve, open-gpu-share.go:147-188) for every node
        touched since the last flush."""
        for node_i in self._dirty:
            self._refresh_node(self.states[node_i])
        self._dirty.clear()

    def seed_from_pods(self, pods_on_node: List[List[dict]]) -> None:
        """Account already-bound pods carrying gpu-index annotations."""
        for node_i, pods in enumerate(pods_on_node):
            for pod in pods:
                self.seed_pod(pod, node_i)


def gpu_report_rows(node: dict, pods: List[dict]) -> List[List[str]]:
    """Rows for the applier's 'GPU Node Resource' table, reading the node
    annotation the way reportClusterInfo does (apply.go:445-500)."""
    import json

    raw = annotations_of(node).get(C.AnnoNodeGpuShare)
    if not raw:
        return []
    try:
        info = json.loads(raw)
    except json.JSONDecodeError:
        return []
    total = parse_quantity(info.get("GpuTotalMemory", "0"))
    used = sum(pod_gpu_mem(p) * pod_gpu_count(p) for p in pods)
    pct = int(used / total * 100) if total else 0
    rows = [[
        f"{name_of(node)} ({info.get('GpuModel', '')})",
        f"{info.get('GpuCount', 0)} GPUs",
        f"{format_quantity(used, binary=True)}/{format_quantity(total, binary=True)}({pct}%)",
        f"{info.get('NumPods', 0)} Pods",
    ]]
    devs = info.get("DevsBrief") or {}
    for idx in sorted(devs, key=lambda k: (0, int(k)) if str(k).isdigit() else (1, str(k))):
        dev = devs[idx]
        dcap = parse_quantity(dev.get("GpuTotalMemory", "0"))
        if dcap <= 0:
            continue
        duse = parse_quantity(dev.get("GpuUsedMemory", "0"))
        dpct = int(duse / dcap * 100) if dcap else 0
        rows.append([
            f"{name_of(node)} ({info.get('GpuModel', '')})",
            str(idx),
            f"{format_quantity(duse, binary=True)}/{format_quantity(dcap, binary=True)}({dpct}%)",
            ", ".join(dev.get("PodList") or []),
        ])
    return rows
