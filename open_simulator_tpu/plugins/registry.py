"""Out-of-tree plugin extension point.

The reference's library API accepts extra scheduler-framework registries
(`WithFrameworkOutOfTreeRegistry`, /root/reference/pkg/simulator/
simulator.go:190-213 + the `extraRegistry` option :471-500) so embedders can
add their own filter/score plugins. The TPU-native equivalent: a plugin
contributes a per-(pod-template, node) FILTER verdict and/or a raw SCORE,
evaluated once per scheduling group at encode time and folded into the static
device tables — zero cost per scheduling step, and batched/wave/mesh paths all
honor it automatically.

Boundary (documented, deliberate): verdicts may depend only on the pod
template and the node object — not on placement state. Every state-dependent
plugin the reference ships (Simon, Open-Local, Open-Gpu-Share, the default
set) is already built into the kernels; the out-of-tree surface exists for
custom extended resources, label policies, and cost models, which are
(pod, node)-static in the reference's registry users too.

Usage::

    class FpgaPlugin(SimulatorPlugin):
        name = "example.com/fpga"
        def filter(self, pod, node):
            want = int(pod_requests(pod).get("example.com/fpga", 0))
            have = int(allocatable(node).get("example.com/fpga", 0))
            return want <= have
        def score(self, pod, node):
            return 100.0 - usage_pct(node)

    simulate(cluster, apps, extra_plugins=[FpgaPlugin()])
"""

from __future__ import annotations


class SimulatorPlugin:
    """Base class for out-of-tree plugins. Override `filter` and/or `score`.

    - `filter(pod, node) -> bool`: False removes the node for this pod
      (reported as "filtered out by an out-of-tree plugin" in FitErrors).
    - `score(pod, node) -> float`: raw score added to the node's total,
      multiplied by `weight`. Convention: 0..100 like framework plugins.
    """

    name: str = "out-of-tree"
    weight: float = 1.0

    def filter(self, pod: dict, node: dict) -> bool:  # pragma: no cover - default
        return True

    def score(self, pod: dict, node: dict) -> float:  # pragma: no cover - default
        return 0.0
