"""Open-Local: LVM volume-group + exclusive-device local-storage simulation.

Mirrors /root/reference/pkg/simulator/plugin/open-local.go and the vendored
alibaba/open-local algorithms (vendor/.../scheduler/algorithm/algo/common.go):

- Pods carry a `simon/pod-local-storage` VolumeRequest annotation (synthesized from
  StatefulSet volumeClaimTemplates by SetStorageAnnotationOnPods, utils.go:249-292).
- Nodes carry `simon/node-local-storage` with VGs (shared, bytes) and Devices
  (exclusive, media-typed).
- Filter: every LVM volume must fit a VG (named VG exact, unnamed → Binpack
  tightest-fit by free space, common.go:59-130); every device volume needs a free
  device of its media type with enough capacity (ssd checked before hdd; volumes
  and devices matched in ascending size order, common.go:290-350,393-447).
- Score (Binpack strategy): LVM = avg over used VGs of used/capacity × 10;
  Device = avg over units of requested/allocated × 10; both ints, summed, then
  min-max normalized by the plugin's NormalizeScore (open-local.go:140-172).
- Bind: adds the allocations into the node annotation (open-local.go:175-254).

The batched engine evaluates filter+score as [N, MAXVG]/[N, MAXSDEV] tensor math
with the running requested/allocated state in the scan carry (ops/kernels.py);
this module owns the string world: volume parsing, SC resolution, the host ledger
that replays allocations for committed pods, and the annotation writeback.

Media type resolution follows the reference strictly: the StorageClass object's
`parameters.mediaType` decides ssd/hdd; volumes whose SC is missing or has no
(or an unrecognized) mediaType are silently dropped from the device checks — the
reference's demo_1 `sc-device-ssd.yaml` even ships a "sdd" typo relying on this.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from ..core import constants as C
from ..utils.objutil import name_of
from ..utils.storage import (
    NodeStorage,
    get_node_storage,
    get_pod_local_volumes,
    set_node_storage,
)

MAX_SCORE = 10  # open-local algo MaxScore (common.go:34)

LVM_SC_NAMES = (C.OpenLocalSCNameLVM, C.YodaSCNameLVM)


class OpenLocalVolume:
    """One volume demand, fully resolved: kind, size, vg name (may be ""), media."""

    def __init__(self, size: int, kind: str, sc_name: str, vg_name: str, media: str) -> None:
        self.size = size
        self.kind = kind          # "LVM" | "SSD" | "HDD" (annotation Kind)
        self.sc_name = sc_name
        self.vg_name = vg_name    # SC parameters.vgName, "" = unnamed (Binpack)
        self.media = media        # SC parameters.mediaType: "ssd" | "hdd" | ""


def resolve_pod_volumes(
    pod: dict, storage_classes: List[dict]
) -> Tuple[List[OpenLocalVolume], List[OpenLocalVolume]]:
    """(lvm_volumes, device_volumes) for a pod, in the reference's processing
    order. Routing follows GetPodLocalPVCs (utils.go:580-623) exactly: any volume
    whose Kind is LVM/HDD/SSD is accepted, and the LVM-vs-device split is by the
    STORAGE CLASS NAME (open-local-lvm / yoda-lvm-default → LVM; everything else →
    device, media from the SC object's parameters.mediaType, unknown media
    dropped). LVM: named-VG first then unnamed (input order, DivideLVMPVCs);
    devices: ssd-before-hdd, each ascending by size (ProcessDevicePVC +
    CheckExclusiveResourceMeetsPVCSize sorts)."""
    sc_map = {name_of(sc): sc for sc in storage_classes}
    lvm_named: List[OpenLocalVolume] = []
    lvm_unnamed: List[OpenLocalVolume] = []
    dev_ssd: List[OpenLocalVolume] = []
    dev_hdd: List[OpenLocalVolume] = []
    for vol in get_pod_local_volumes(pod):
        if vol.kind not in ("LVM", "HDD", "SSD"):
            continue  # unsupported kind, logged-and-skipped by the reference
        sc = sc_map.get(vol.sc_name)
        params = (sc or {}).get("parameters") or {}
        if vol.sc_name in LVM_SC_NAMES:
            v = OpenLocalVolume(vol.size, vol.kind, vol.sc_name, params.get("vgName", ""), "")
            (lvm_named if v.vg_name else lvm_unnamed).append(v)
        else:
            media = params.get("mediaType", "")
            v = OpenLocalVolume(vol.size, vol.kind, vol.sc_name, "", media)
            if media == "ssd":
                dev_ssd.append(v)
            elif media == "hdd":
                dev_hdd.append(v)
            # else: dropped, like DividePVCAccordingToMediaType with unknown media
    dev_ssd.sort(key=lambda v: v.size)
    dev_hdd.sort(key=lambda v: v.size)
    return lvm_named + lvm_unnamed, dev_ssd + dev_hdd


# ------------------------------------------------------------------ allocation ------


def allocate_lvm(
    vgs: List, volumes: List[OpenLocalVolume]
) -> Tuple[bool, List[Tuple[int, int]]]:
    """Sequentially place LVM volumes onto VGs. Returns (fits, [(vg_idx, size)]).
    Named VG → exact match; unnamed → Binpack: tightest fit by free space
    (ascending-free first-fit ≡ smallest free ≥ size; ties → lowest index)."""
    free = [vg.capacity - vg.requested for vg in vgs]
    units: List[Tuple[int, int]] = []
    for vol in volumes:
        if vol.vg_name:
            idx = next((i for i, vg in enumerate(vgs) if vg.name == vol.vg_name), -1)
            if idx < 0 or free[idx] < vol.size:
                return False, units
        else:
            cands = [i for i in range(len(vgs)) if free[i] >= vol.size and vgs[i].capacity > 0]
            if not cands:
                return False, units
            idx = min(cands, key=lambda i: (free[i], i))
        free[idx] -= vol.size
        units.append((idx, vol.size))
    return True, units


def allocate_devices(
    devices: List, volumes: List[OpenLocalVolume]
) -> Tuple[bool, List[Tuple[int, int]]]:
    """Match device volumes (pre-sorted ssd-asc then hdd-asc) to free devices of
    the same media type, reproducing ProcessDevicePVC +
    CheckExclusiveResourceMeetsPVCSize (common.go:290-350,393-447) INCLUDING its
    quirks:
    - per-media count pre-check: free devices < requested volumes → fail;
    - one merge pass over (devices asc-capacity, volumes asc-size): a volume fails
      the node only when the scan reaches the LAST device and it is too small;
    - when devices run out mid-scan (last device already consumed), the remaining
      volumes are silently dropped and the node still fits — a reference bug we
      keep for placement parity.
    Returns (fits, [(device_idx, size)])."""
    taken = [d.is_allocated for d in devices]
    units: List[Tuple[int, int]] = []
    for media in ("ssd", "hdd"):  # ssd processed before hdd (ProcessDevicePVC)
        vols = [v for v in volumes if v.media == media]
        if not vols:
            continue
        order = sorted(
            (i for i, d in enumerate(devices) if d.media_type == media and not taken[i]),
            key=lambda i: (devices[i].capacity, i),
        )
        if len(order) < len(vols):
            return False, units
        j = 0
        for vol in vols:
            assigned = False
            while j < len(order):
                idx = order[j]
                if devices[idx].capacity < vol.size:
                    if j == len(order) - 1:
                        return False, units
                    j += 1
                    continue
                taken[idx] = True
                units.append((idx, vol.size))
                j += 1
                assigned = True
                break
            if not assigned:
                break  # devices exhausted: rest silently dropped (reference bug)
    return True, units


def score_binpack(
    vgs: List, lvm_units: List[Tuple[int, int]],
    devices: List, dev_units: List[Tuple[int, int]],
) -> int:
    """ScoreLVM (Binpack) + ScoreDevice (common.go:660-724): integers, summed."""
    score = 0
    if lvm_units:
        used: Dict[int, int] = {}
        for idx, size in lvm_units:
            used[idx] = used.get(idx, 0) + size
        acc = sum(u / vgs[i].capacity for i, u in used.items() if vgs[i].capacity)
        score += int(acc / len(used) * MAX_SCORE)
    if dev_units:
        acc = sum(size / devices[i].capacity for i, size in dev_units if devices[i].capacity)
        score += int(acc / len(dev_units) * MAX_SCORE)
    return score


# ------------------------------------------------------------------ host ledger -----


class OpenLocalHost:
    """Host half: per-node NodeStorage ledgers; replays Bind for committed pods."""

    def __init__(self, nodes: List[dict]) -> None:
        self.nodes = nodes
        store = getattr(nodes, "store", None)  # simulator/store.py LazyNodeSeq
        if store is not None and not store.may_have_local_storage:
            # columnar fast path: no block template carries the node-local-
            # storage annotation — skip the N-dict materializing scan
            self.states: List[Optional[NodeStorage]] = [None] * len(nodes)
        else:
            self.states = [get_node_storage(n) for n in nodes]
        self.vg_names: Dict[str, int] = {}  # name -> id (1-based; 0 = unnamed)
        for st in self.states:
            if st:
                for vg in st.vgs:
                    self.vg_names.setdefault(vg.name, len(self.vg_names) + 1)
        self.max_vgs = max((len(st.vgs) for st in self.states if st), default=0)
        self.max_devs = max((len(st.devices) for st in self.states if st), default=0)
        # id(pod) → (node_i, lvm_units, dev_units): the exact units reserve()
        # granted, so a preemption eviction can release precisely those
        self._alloc: Dict[int, tuple] = {}

    @property
    def enabled(self) -> bool:
        return self.max_vgs > 0 or self.max_devs > 0

    def vg_name_id(self, name: str) -> int:
        return self.vg_names.setdefault(name, len(self.vg_names) + 1)

    def reserve(self, pod: dict, node_i: int, storage_classes: List[dict]) -> bool:
        """The Bind writeback (open-local.go:215-250): allocate, bump VG requested,
        mark devices allocated, refresh the node annotation."""
        lvm, dev = resolve_pod_volumes(pod, storage_classes)
        if not lvm and not dev:
            return False
        state = self.states[node_i]
        if state is None:
            return False
        ok_l, lvm_units = allocate_lvm(state.vgs, lvm)
        ok_d, dev_units = allocate_devices(state.devices, dev)
        if not (ok_l and ok_d):
            # The kernel filter (f32) admitted a placement the exact-integer host
            # allocator rejects — possible only at f32 precision edges (~16KiB at
            # 100Gi scales). Surface it: a silent skip would desync the node
            # annotation from the device-side carry.
            logging.warning(
                "open-local: host allocation failed for committed pod %s on node %s "
                "(f32/int precision edge); node annotation left unchanged",
                name_of(pod), name_of(self.nodes[node_i]),
            )
            return False
        for idx, size in lvm_units:
            state.vgs[idx].requested += size
        for idx, _ in dev_units:
            state.devices[idx].is_allocated = True
        self._alloc[id(pod)] = (node_i, lvm_units, dev_units)
        set_node_storage(self.nodes[node_i], state)
        return True

    def release(self, pod: dict, node_i: int) -> None:
        """Undo reserve() for one pod (preemption eviction), returning exactly
        the units it was granted. No reference analog (see gpushare.release)."""
        rec = self._alloc.pop(id(pod), None)
        if rec is None or rec[0] != node_i:
            return
        state = self.states[node_i]
        if state is None:
            return
        for idx, size in rec[1]:
            state.vgs[idx].requested -= size
        for idx, _ in rec[2]:
            state.devices[idx].is_allocated = False
        set_node_storage(self.nodes[node_i], state)

    def snapshot(self):
        """Copy of VG/device ledgers + the node annotation this plugin owns."""
        from ..utils.objutil import annotations_of

        states = []
        for st, node in zip(self.states, self.nodes):
            if st is None:
                states.append(None)
                continue
            states.append((
                [vg.requested for vg in st.vgs],
                [d.is_allocated for d in st.devices],
                annotations_of(node).get(C.AnnoNodeLocalStorage),
            ))
        return states, dict(self._alloc)

    def restore(self, snap) -> None:
        states, self._alloc = snap[0], dict(snap[1])
        for st, node, rec in zip(self.states, self.nodes, states):
            if st is None or rec is None:
                continue
            for vg, req in zip(st.vgs, rec[0]):
                vg.requested = req
            for d, alloc in zip(st.devices, rec[1]):
                d.is_allocated = alloc
            anns = node.setdefault("metadata", {}).setdefault("annotations", {})
            if rec[2] is None:
                anns.pop(C.AnnoNodeLocalStorage, None)
            else:
                anns[C.AnnoNodeLocalStorage] = rec[2]

    # ---- tensorization ---------------------------------------------------------

    def vg_matrices(self, max_vgs: int):
        import numpy as np

        N = len(self.states)
        cap = np.zeros((N, max_vgs), np.float32)
        nid = np.zeros((N, max_vgs), np.int32)
        req = np.zeros((N, max_vgs), np.float32)
        for i, st in enumerate(self.states):
            if not st:
                continue
            for j, vg in enumerate(st.vgs[:max_vgs]):
                cap[i, j] = vg.capacity
                nid[i, j] = self.vg_name_id(vg.name)
                req[i, j] = vg.requested
        return cap, nid, req

    def device_matrices(self, max_devs: int):
        import numpy as np

        N = len(self.states)
        cap = np.zeros((N, max_devs), np.float32)
        media = np.zeros((N, max_devs), np.int32)  # 0 none, 1 hdd, 2 ssd
        alloc = np.zeros((N, max_devs), bool)
        for i, st in enumerate(self.states):
            if not st:
                continue
            for j, d in enumerate(st.devices[:max_devs]):
                cap[i, j] = d.capacity
                media[i, j] = 2 if d.media_type == "ssd" else (1 if d.media_type == "hdd" else 0)
                alloc[i, j] = d.is_allocated
        return cap, media, alloc
