"""simonflow: CFG + intraprocedural dataflow over the simonlint AST model.

simonlint's original rules are single-statement pattern matchers; simonaudit
sees the compiled artifact. Neither can answer flow questions — "does this
value ever reach that call?", "is this attribute ever touched off-lock?" —
which is exactly the class the two worst shipped concurrency bugs (the PR 14
torn-scrape histogram race, the PR 5 thread-local config-scope escape)
belonged to. This module is the third tier's foundation:

  * `build_cfg(fn)` — an intraprocedural control-flow graph over a function
    (or module) body: if/while/for with back edges, try/except/finally with
    conservative exception edges, with-blocks inline, break/continue/return/
    raise terminators. Nested defs/lambdas are opaque statements (separate
    execution contexts with their own CFGs).
  * `dataflow_forward(cfg, ...)` — a worklist fixpoint solver for forward
    may-analyses (facts join by union at block entries).
  * the **entropy taint pass** (`entropy-into-report`, WARNING): ambient
    entropy sources (wall clocks, unseeded `random`, `os.urandom`, `id()`,
    set iteration order) flowing into deterministic report sinks
    (json.dump/json.dumps — the sweep reports, golden writers, journals, and
    trace files every byte-identical-report suite depends on). Taint
    propagates through assignments on the CFG and one level deep through
    module-local helper calls (an `entropy-returning` function summary).

The lock-discipline and thread-escape passes built on the same foundation
live in threads.py. All passes register as ordinary rules, so `simon lint`,
the LintCache, suppressions, and both output formats work unchanged.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from .base import Finding, Severity, register
from .context import ModuleContext

# --------------------------------------------------------------------- CFG ----


class Block:
    """One basic block: a straight-line run of statements and its successor
    edges. `label` is a construction hint ("if.then", "while.head", ...) for
    tests and debugging only."""

    __slots__ = ("id", "label", "stmts", "succs")

    def __init__(self, bid: int, label: str = "") -> None:
        self.id = bid
        self.label = label
        self.stmts: List[ast.stmt] = []
        self.succs: List["Block"] = []

    def link(self, other: "Block") -> None:
        if other is not self and other not in self.succs:
            self.succs.append(other)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Block({self.id}, {self.label!r}, "
                f"stmts={len(self.stmts)}, "
                f"succs={[b.id for b in self.succs]})")


class CFG:
    """Entry/exit plus every block of one function (or module) body."""

    def __init__(self, fn: ast.AST) -> None:
        self.fn = fn
        self.blocks: List[Block] = []
        self.entry: Block = self._new("entry")
        self.exit: Block = self._new("exit")

    def _new(self, label: str = "") -> Block:
        b = Block(len(self.blocks), label)
        self.blocks.append(b)
        return b

    def preds(self) -> Dict[int, List[Block]]:
        out: Dict[int, List[Block]] = {b.id: [] for b in self.blocks}
        for b in self.blocks:
            for s in b.succs:
                out[s.id].append(b)
        return out


def build_cfg(fn: ast.AST) -> CFG:
    """CFG over `fn.body` (a FunctionDef, or any node with a stmt-list body
    — an ast.Module works). Nested function/class definitions are recorded
    as plain statements, never descended into."""
    cfg = CFG(fn)
    builder = _Builder(cfg)
    end = builder.seq(list(getattr(fn, "body", [])), cfg.entry,
                      loops=[], handlers=[])
    if end is not None:
        end.link(cfg.exit)
    return cfg


class _Builder:
    def __init__(self, cfg: CFG) -> None:
        self.cfg = cfg

    def seq(self, stmts: Sequence[ast.stmt], cur: Optional[Block],
            loops: List[Tuple[Block, Block]],
            handlers: List[Block]) -> Optional[Block]:
        """Thread `stmts` through blocks starting at `cur`; returns the open
        block after the last statement, or None when control cannot fall
        through (return/raise/break/continue on every path)."""
        for stmt in stmts:
            if cur is None:
                # dead code after a terminator still gets a (preds-free)
                # block so walkers and per-statement facts can see it
                cur = self.cfg._new("dead")
            if isinstance(stmt, ast.If):
                cur = self._if(stmt, cur, loops, handlers)
            elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
                cur = self._loop(stmt, cur, loops, handlers)
            elif isinstance(stmt, ast.Try):
                cur = self._try(stmt, cur, loops, handlers)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                # with-blocks are straight-line: the item exprs (and any
                # `as` targets) evaluate in the current block, the body
                # continues inline
                cur.stmts.append(stmt)
                cur = self.seq(stmt.body, cur, loops, handlers)
            elif isinstance(stmt, ast.Return):
                cur.stmts.append(stmt)
                cur.link(self.cfg.exit)
                cur = None
            elif isinstance(stmt, ast.Raise):
                cur.stmts.append(stmt)
                for h in handlers:
                    cur.link(h)
                cur.link(self.cfg.exit)
                cur = None
            elif isinstance(stmt, ast.Break):
                cur.stmts.append(stmt)
                if loops:
                    cur.link(loops[-1][1])
                cur = None
            elif isinstance(stmt, ast.Continue):
                cur.stmts.append(stmt)
                if loops:
                    cur.link(loops[-1][0])
                cur = None
            else:
                # plain statement — including nested FunctionDef/ClassDef,
                # which are definitions here, not control flow
                cur.stmts.append(stmt)
        return cur

    def _if(self, stmt: ast.If, cur: Block, loops, handlers) -> Block:
        cur.stmts.append(stmt)  # the test expression evaluates here
        after = self.cfg._new("if.after")
        then = self.cfg._new("if.then")
        cur.link(then)
        t_end = self.seq(stmt.body, then, loops, handlers)
        if t_end is not None:
            t_end.link(after)
        if stmt.orelse:
            els = self.cfg._new("if.else")
            cur.link(els)
            e_end = self.seq(stmt.orelse, els, loops, handlers)
            if e_end is not None:
                e_end.link(after)
        else:
            cur.link(after)
        return after

    def _loop(self, stmt, cur: Block, loops, handlers) -> Block:
        head = self.cfg._new("loop.head")
        cur.link(head)
        head.stmts.append(stmt)  # test / iter+target evaluate per iteration
        after = self.cfg._new("loop.after")
        body = self.cfg._new("loop.body")
        head.link(body)
        head.link(after)
        b_end = self.seq(stmt.body, body, loops + [(head, after)], handlers)
        if b_end is not None:
            b_end.link(head)
        if stmt.orelse:
            els = self.cfg._new("loop.else")
            head.link(els)
            e_end = self.seq(stmt.orelse, els, loops, handlers)
            if e_end is not None:
                e_end.link(after)
        return after

    def _try(self, stmt: ast.Try, cur: Block, loops, handlers) -> Optional[Block]:
        after = self.cfg._new("try.after")
        h_entries = [self.cfg._new(f"except.{i}")
                     for i, _ in enumerate(stmt.handlers)]
        body = self.cfg._new("try.body")
        cur.link(body)
        watermark = len(self.cfg.blocks)
        b_end = self.seq(stmt.body, body, loops, handlers + h_entries)
        # conservative exception edges: any block of the protected body may
        # raise into any handler (a may-analysis over-approximates safely)
        for blk in [body] + self.cfg.blocks[watermark:]:
            for h in h_entries:
                blk.link(h)
        fin: Optional[Block] = None
        fin_end: Optional[Block] = None
        if stmt.finalbody:
            fin = self.cfg._new("finally")
            fin_end = self.seq(stmt.finalbody, fin, loops, handlers)
            if fin_end is not None:
                fin_end.link(after)
                # the exceptional continuation: finally runs, then re-raises
                fin_end.link(self.cfg.exit)
                for h in handlers:
                    fin_end.link(h)
        tail = fin if fin is not None else after
        if b_end is not None:
            if stmt.orelse:
                els = self.cfg._new("try.else")
                b_end.link(els)
                e_end = self.seq(stmt.orelse, els, loops, handlers + h_entries)
                if e_end is not None:
                    e_end.link(tail)
            else:
                b_end.link(tail)
        for i, handler in enumerate(stmt.handlers):
            h_end = self.seq(handler.body, h_entries[i], loops, handlers)
            if h_end is not None:
                h_end.link(tail)
        if fin is not None and not fin_end and not stmt.finalbody:
            fin.link(after)
        return after


# ---------------------------------------------------------------- dataflow ----

Fact = Dict[str, Tuple[str, int]]  # name -> (source label, source line)


def dataflow_forward(cfg: CFG,
                     transfer: Callable[[ast.stmt, Fact], Fact],
                     init: Optional[Fact] = None,
                     max_iters: int = 100) -> Dict[int, Fact]:
    """Worklist fixpoint for a forward may-analysis: block-entry facts join
    by dict-union (first writer of a name wins — stable, deterministic), the
    per-statement `transfer` threads facts through each block in order.
    Returns {block id -> entry fact}. Blocks unreachable from entry keep the
    bottom fact ({})."""
    preds = cfg.preds()
    entry_facts: Dict[int, Fact] = {cfg.entry.id: dict(init or {})}

    def block_out(b: Block) -> Fact:
        fact = dict(entry_facts.get(b.id, {}))
        for stmt in b.stmts:
            fact = transfer(stmt, fact)
        return fact

    work = [cfg.entry]
    iters = 0
    while work and iters < max_iters * max(1, len(cfg.blocks)):
        iters += 1
        b = work.pop(0)
        out = block_out(b)
        for s in b.succs:
            cur = entry_facts.get(s.id)
            merged = dict(out) if cur is None else dict(cur)
            if cur is not None:
                for k, v in out.items():
                    merged.setdefault(k, v)
            if merged != cur:
                entry_facts[s.id] = merged
                if s not in work:
                    work.append(s)
    return entry_facts


# ------------------------------------------------------------ entropy taint ----

# Ambient entropy: every call here returns a value that differs run to run.
ENTROPY_CALLS = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.process_time",
    "os.urandom", "os.getpid",
    "uuid.uuid1", "uuid.uuid4",
    "secrets.token_hex", "secrets.token_bytes", "secrets.token_urlsafe",
    "random.random", "random.randint", "random.randrange", "random.uniform",
    "random.choice", "random.choices", "random.sample", "random.shuffle",
    "random.gauss", "random.getrandbits", "random.randbytes",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.date.today",
}

# Deterministic report sinks: the serializers every byte-identical artifact
# (sweep reports, goldens, journals, traces) funnels through.
SINK_CALLS = {"json.dump", "json.dumps"}

_SET_FACTORIES = {"set", "frozenset"}
_TAINT_MUTATORS = {"append", "add", "extend", "insert", "update",
                   "setdefault", "appendleft"}


def _is_builtin(ctx: ModuleContext, node: ast.expr, name: str) -> bool:
    return (isinstance(node, ast.Name) and node.id == name
            and name not in ctx.aliases)


class _TaintEngine:
    """Per-module entropy-taint machinery. `entropy_fns` is the set of
    module-local function names whose RETURN value is tainted assuming
    untainted arguments (the one-level helper summary); `setish` tracks
    names bound to set()/frozenset()/set-literal values so only their
    ITERATION (the order hazard), not membership tests, taints."""

    def __init__(self, ctx: ModuleContext, entropy_fns: Set[str]) -> None:
        self.ctx = ctx
        self.entropy_fns = entropy_fns
        self.sink_hits: List[Tuple[ast.Call, str, Tuple[str, int]]] = []
        self.return_taints: List[Tuple[str, int]] = []
        self.setish: Set[str] = set()
        # sink scanning is the expensive half of transfer() and only the
        # post-fixpoint replay needs it — off during worklist iteration
        self.scan_enabled = False

    # ---- expression taint ----------------------------------------------------

    def expr_taint(self, expr: Optional[ast.expr],
                   fact: Fact) -> Optional[Tuple[str, int]]:
        if expr is None:
            return None
        if isinstance(expr, ast.Name):
            return fact.get(expr.id)
        if isinstance(expr, ast.Call):
            r = self.ctx.resolve(expr.func)
            if r in ENTROPY_CALLS:
                return (r, expr.lineno)
            if _is_builtin(self.ctx, expr.func, "id"):
                return ("id()", expr.lineno)
            # sorted(...) neutralizes ORDER taint: sorted(set(x)) is clean,
            # but value taint (time flowing through sorted) survives below
            neutralized = _is_builtin(self.ctx, expr.func, "sorted")
            if not neutralized and isinstance(expr.func, ast.Name) \
                    and expr.func.id in self.entropy_fns:
                return (f"{expr.func.id}() [entropy-returning helper]",
                        expr.lineno)
            if not neutralized and isinstance(expr.func, ast.Attribute) \
                    and expr.func.attr in self.entropy_fns:
                return (f"{expr.func.attr}() [entropy-returning helper]",
                        expr.lineno)
            for sub in list(expr.args) + [k.value for k in expr.keywords]:
                t = self.expr_taint(sub, fact)
                if t is not None and not (
                        neutralized and t[0] == "set-iteration-order"):
                    return t
            t = self.expr_taint(expr.func if isinstance(expr.func, ast.Attribute)
                                else None, fact)
            return t
        if isinstance(expr, ast.Attribute):
            return self.expr_taint(expr.value, fact)
        if isinstance(expr, (ast.Lambda, ast.FunctionDef)):
            return None
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                t = self.expr_taint(child, fact)
                if t is not None:
                    return t
        return None

    def _iter_order_taint(self, iter_expr: ast.expr,
                          fact: Fact) -> Optional[Tuple[str, int]]:
        """Taint from ITERATING `iter_expr`: a set literal, a direct
        set()/frozenset() call, or a name bound to one — the iteration order
        is hash-seed-dependent and differs across processes."""
        e = iter_expr
        if isinstance(e, ast.Set):
            return ("set-iteration-order", e.lineno)
        if isinstance(e, ast.Call) and any(
                _is_builtin(self.ctx, e.func, n) for n in _SET_FACTORIES):
            return ("set-iteration-order", e.lineno)
        if isinstance(e, ast.Name) and e.id in self.setish:
            return ("set-iteration-order", e.lineno)
        return None

    def _is_setish_value(self, value: ast.expr) -> bool:
        if isinstance(value, ast.Set):
            return True
        if isinstance(value, ast.Call):
            return any(_is_builtin(self.ctx, value.func, n)
                       for n in _SET_FACTORIES)
        if isinstance(value, ast.Name):
            return value.id in self.setish
        return False

    # ---- statement transfer --------------------------------------------------

    def transfer(self, stmt: ast.stmt, fact: Fact) -> Fact:
        fact = dict(fact)
        self._scan_sinks(stmt, fact)
        # container mutation propagates taint into the container: report
        # rows accumulate via rows.append(tainted)
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if (isinstance(call.func, ast.Attribute)
                    and call.func.attr in _TAINT_MUTATORS
                    and isinstance(call.func.value, ast.Name)):
                for sub in list(call.args) + [k.value for k in call.keywords]:
                    t = self.expr_taint(sub, fact)
                    if t is not None:
                        fact.setdefault(call.func.value.id, t)
                        break
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        elif isinstance(stmt, ast.AugAssign):
            t = self.expr_taint(stmt.value, fact)
            for name in _target_names(stmt.target):
                if t is not None:
                    fact.setdefault(name, t)
            return fact
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            t = (self.expr_taint(stmt.iter, fact)
                 or self._iter_order_taint(stmt.iter, fact))
            for name in _target_names(stmt.target):
                if t is not None:
                    fact[name] = t
                else:
                    fact.pop(name, None)
            return fact
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is None:
                    continue
                t = self.expr_taint(item.context_expr, fact)
                for name in _target_names(item.optional_vars):
                    if t is not None:
                        fact[name] = t
                    else:
                        fact.pop(name, None)
            return fact
        elif isinstance(stmt, ast.Return):
            t = self.expr_taint(stmt.value, fact)
            if t is not None:
                self.return_taints.append(t)
            return fact
        if value is not None:
            t = self.expr_taint(value, fact)
            setish = self._is_setish_value(value)
            for tgt in targets:
                for name in _target_names(tgt):
                    if t is not None:
                        fact[name] = t
                    else:
                        fact.pop(name, None)
                    if setish:
                        self.setish.add(name)
                    else:
                        self.setish.discard(name)
        return fact

    def _scan_sinks(self, stmt: ast.stmt, fact: Fact) -> None:
        """Record every sink call in `stmt` fed by a tainted argument. Walks
        the whole statement (sinks hide in returns, nested calls, f-strings)
        but never into nested defs."""
        if not self.scan_enabled:
            return
        for node in _walk_stmt_exprs(stmt):
            if not isinstance(node, ast.Call):
                continue
            r = self.ctx.resolve(node.func)
            if r not in SINK_CALLS:
                continue
            args = list(node.args)
            if r == "json.dump" and len(args) >= 2:
                # the stream argument carries no CONTENT taint (a pid- or
                # time-suffixed tmp filename is still a deterministic record)
                args = args[:1]
            for arg in args + [k.value for k in node.keywords
                               if k.arg not in ("fp", "default")]:
                t = self.expr_taint(arg, fact)
                if t is None and isinstance(arg, (ast.Name,)):
                    t = self._iter_order_taint(arg, fact)
                if t is not None:
                    self.sink_hits.append((node, r, t))
                    break


def _target_names(tgt: ast.expr) -> List[str]:
    return [n.id for n in ast.walk(tgt) if isinstance(n, ast.Name)]


def _walk_stmt_exprs(stmt: ast.stmt):
    """Every node of a statement, skipping nested function/class bodies."""
    stack: List[ast.AST] = [stmt]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _analyze_function(ctx: ModuleContext, fn: ast.AST,
                      entropy_fns: Set[str],
                      want_sinks: bool = True) -> _TaintEngine:
    eng = _TaintEngine(ctx, entropy_fns)
    cfg = build_cfg(fn)
    entry_facts = dataflow_forward(cfg, eng.transfer)
    # return taints collected during the fixpoint are already sound (entry
    # facts grow monotonically, so the last visit of a returning block saw
    # its converged fact) — summary computation stops here
    if not want_sinks:
        return eng
    # fixpoint reached: replay each block once from its final entry fact so
    # sink hits and return taints reflect the converged solution
    eng.scan_enabled = True
    eng.sink_hits = []
    eng.return_taints = []
    eng.setish = set()
    for b in cfg.blocks:
        fact = dict(entry_facts.get(b.id, {}))
        for stmt in b.stmts:
            fact = eng.transfer(stmt, fact)
    return eng


def entropy_returning_functions(ctx: ModuleContext) -> Set[str]:
    """Module-local functions whose return value carries entropy taint given
    untainted arguments — the summary that lets taint cross ONE call level
    (`stamp = _now_ms()` into a report is the same hazard as inlining the
    clock read). Iterated to a fixpoint so helper chains resolve."""
    out: Set[str] = set()
    for _ in range(len(ctx.functions) + 1):
        grew = False
        for fname in sorted(ctx.functions):
            if fname in out:
                continue
            for fn in ctx.functions[fname]:
                eng = _analyze_function(ctx, fn, out, want_sinks=False)
                if eng.return_taints:
                    out.add(fname)
                    grew = True
                    break
        if not grew:
            break
    return out


@register(
    "entropy-into-report", Severity.WARNING,
    "A value derived from ambient entropy (wall clock, unseeded random, "
    "os.urandom, id(), set iteration order) flows into a deterministic "
    "report sink (json.dump/json.dumps). Every byte-identical-report suite "
    "— sweep reports, golden writers, replay journals — depends on these "
    "serializations being pure functions of their seeded inputs; one "
    "timestamp or hash-order leak breaks the contract in a way the suites "
    "only catch per-artifact, after the fact. Thread the value through the "
    "seeded inputs (or sort the iteration), or whitelist a deliberately "
    "wall-clocked record with `# simonlint: ignore[entropy-into-report] -- "
    "<why>` naming the artifact that tolerates it.",
)
def rule_entropy_into_report(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    summaries = entropy_returning_functions(ctx)
    seen: Set[Tuple[int, int]] = set()
    units: List[ast.AST] = [ctx.tree]
    for defs in ctx.functions.values():
        units.extend(defs)
    for unit in units:
        eng = _analyze_function(ctx, unit, summaries)
        for call, sink, (label, src_line) in eng.sink_hits:
            key = (call.lineno, call.col_offset)
            if key in seen:
                continue
            seen.add(key)
            out.append(Finding(
                "entropy-into-report", Severity.WARNING, ctx.path,
                call.lineno, call.col_offset,
                f"{sink}(...) receives a value tainted by {label} "
                f"(source at line {src_line}) — entropy in a deterministic "
                f"report sink breaks the byte-identical-artifact contract; "
                f"derive the value from seeded inputs or waive with the "
                f"artifact that tolerates wall-clock fields",
            ))
    return out
