"""The simonlint rule set: JAX/TPU hazards this codebase has been bitten by.

Rule ids (stable — they appear in suppression comments and CI output):

  host-sync-in-jit   device->host sync inside a traced function
  recompile-trigger  static-looking jit parameter not declared static
  dtype-drift        64-bit dtype on a TPU-targeted path
  carry-contract     lax.scan carry without (or violating) a NamedTuple contract
  contract-spec      malformed @shaped contract annotation
  metric-in-jit      metrics-registry mutation or wall-clock read under trace
  swallowed-exception  broad except that neither re-raises, returns, logs, nor counts
  naked-dispatch     device-computation call site bypassing the simonguard watchdog
  fetch-in-wave-loop device->host fetch inside a per-segment/epoch/round loop body
  unsharded-transfer shardingless device_put / jit dispatch in a mesh-aware hot path
  config-scope-across-thread  jax config scope entered in one thread, work
                     submitted to another inside it
  suppression-reason a `simonlint: ignore[...]` waiver without its `-- reason`
  per-pod-host-loop  O(pods) Python `for` over a pod batch in a module that
                     adopted the columnar PodStore
  collective-in-scan-body  cross-shard collective (psum/pmax/all_gather/...)
                     inside a scan/while/fori body — per-iteration latency
                     that should be batched to the loop boundary
  unattributed-dispatch  hot-kernel dispatch under guard.supervised with no
                     obs.record_dispatch in its attribution path — invisible
                     to the compile-cache census and the simonpulse ledger

Every rule is a pure function ModuleContext -> List[Finding]; file IO,
suppressions, and exit-code policy live in runner.py.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..ops.contracts import parse_spec
from .base import _REASON_RE, _SUPPRESS_RE, Finding, Severity, register
from .context import JIT_NAMES, PARTIAL_NAMES, ModuleContext

# ----------------------------------------------------------------- helpers ----


def _names_in(expr: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def _local_walk(fn: ast.FunctionDef):
    """Walk a function body without descending into nested defs/lambdas
    (those are separate traced contexts with their own taint sets)."""
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _taint_set(fn: ast.FunctionDef, statics: Set[str]) -> Set[str]:
    """Names whose values derive from TRACED arguments: the non-static
    parameters, propagated through simple assignments / loop targets to a
    fixpoint. Conservative in the safe direction (a tainted name may in fact
    hold a static value; an untainted one never holds a traced one unless it
    came from a closure, which we don't track)."""
    a = fn.args
    params = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    tainted: Set[str] = {p for p in params if p not in statics}
    for _ in range(10):
        grew = False
        for node in _local_walk(fn):
            targets: List[ast.expr] = []
            value: Optional[ast.AST] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AugAssign):
                targets, value = [node.target], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            elif isinstance(node, ast.For):
                targets, value = [node.target], node.iter
            if value is None or not (_names_in(value) & tainted):
                continue
            for t in targets:
                for name in _names_in(t):
                    if name not in tainted:
                        tainted.add(name)
                        grew = True
        if not grew:
            break
    return tainted


# ---------------------------------------------------------- host-sync-in-jit --

_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
_SYNC_CALLS = {"numpy.asarray", "numpy.array", "jax.device_get"}
_SYNC_BUILTINS = {"float", "int", "bool", "print"}


@register(
    "host-sync-in-jit", Severity.ERROR,
    "Device->host synchronization (.item()/np.asarray/float()/print/...) on a "
    "traced value inside jit/pjit or a lax.scan|while_loop body. Under trace "
    "these either raise ConcretizationTypeError at runtime or, worse, silently "
    "pull the value at trace time and bake a stale constant into the compiled "
    "program.",
)
def rule_host_sync(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    for fn, statics in ctx.traced_functions().items():
        tainted = _taint_set(fn, statics)
        for node in _local_walk(fn):
            if not isinstance(node, ast.Call):
                continue
            hazard: Optional[str] = None
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SYNC_ATTRS
                    and _names_in(node.func.value) & tainted):
                hazard = f".{node.func.attr}()"
            else:
                target = ctx.resolve(node.func)
                arg_names: Set[str] = set()
                for argn in list(node.args) + [k.value for k in node.keywords]:
                    arg_names |= _names_in(argn)
                if target in _SYNC_CALLS and arg_names & tainted:
                    hazard = target
                elif (isinstance(node.func, ast.Name)
                        and node.func.id in _SYNC_BUILTINS
                        and node.func.id not in ctx.aliases
                        and arg_names & tainted):
                    hazard = f"{node.func.id}()"
            if hazard:
                out.append(Finding(
                    "host-sync-in-jit", Severity.ERROR, ctx.path,
                    node.lineno, node.col_offset,
                    f"{hazard} on a value derived from traced arguments of "
                    f"'{fn.name}' — forces a host sync (or a stale trace-time "
                    f"constant) inside a compiled function",
                ))
    return out


# --------------------------------------------------------- recompile-trigger --

_STATICISH_ANNOTATIONS = {"int", "bool", "str", "tuple"}


def _annotation_is_staticish(ctx: ModuleContext, ann: Optional[ast.expr]) -> Optional[str]:
    if ann is None:
        return None
    base = ann.value if isinstance(ann, ast.Subscript) else ann
    r = ctx.resolve(base)
    if r in _STATICISH_ANNOTATIONS:
        return r
    if r in ("typing.Tuple", "typing.Literal"):
        return r.split(".")[-1]
    return None


@register(
    "recompile-trigger", Severity.WARNING,
    "A jit-compiled function takes a parameter that is plainly host-side "
    "configuration (int/bool/str/tuple annotation or scalar default) without "
    "declaring it in static_argnums/static_argnames. Used in Python control "
    "flow or shape arithmetic it aborts tracing; silently traced, every "
    "structurally distinct value risks a fresh compilation.",
)
def rule_recompile(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    for fn, info in ctx.jit.items():
        a = fn.args
        params = list(a.posonlyargs + a.args)
        defaults = [None] * (len(params) - len(a.defaults)) + list(a.defaults)
        params_kw = list(a.kwonlyargs)
        defaults_kw = list(a.kw_defaults)
        for p, d in zip(params + params_kw, defaults + defaults_kw):
            if p.arg in info.static_names or p.arg in ("self", "cls"):
                continue
            why = _annotation_is_staticish(ctx, p.annotation)
            if why is None and isinstance(d, ast.Constant) and isinstance(
                    d.value, (int, bool, str)) and not isinstance(d.value, float):
                why = type(d.value).__name__
            if why is None and isinstance(d, ast.Tuple):
                why = "tuple"
            if why is not None:
                out.append(Finding(
                    "recompile-trigger", Severity.WARNING, ctx.path,
                    p.lineno, p.col_offset,
                    f"parameter '{p.arg}' of jit-compiled '{fn.name}' looks "
                    f"static ({why}) but is not in static_argnums/"
                    f"static_argnames — declare it static or pass a device "
                    f"array",
                ))
    return out


# -------------------------------------------------------------- dtype-drift --

_WIDE_DTYPES = {
    "numpy.float64", "numpy.int64", "numpy.uint64", "numpy.longdouble",
    "jax.numpy.float64", "jax.numpy.int64", "jax.numpy.uint64",
}
_WIDE_STRS = {"float64", "int64", "uint64"}
_ARRAY_FACTORIES = {
    "array", "asarray", "zeros", "ones", "full", "empty", "arange",
    "fromiter", "astype", "frombuffer", "linspace",
}


@register(
    "dtype-drift", Severity.WARNING,
    "64-bit dtype (float64/int64) referenced on a TPU-targeted module. JAX "
    "runs with x64 disabled: the value is silently downcast when it crosses "
    "the device boundary, so 64-bit staging is only sound host-side — "
    "whitelist intentional host buffers with "
    "`# simonlint: ignore[dtype-drift] -- <why>`.",
)
def rule_dtype_drift(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Attribute):
            r = ctx.resolve(node)
            if r in _WIDE_DTYPES:
                out.append(Finding(
                    "dtype-drift", Severity.WARNING, ctx.path,
                    node.lineno, node.col_offset,
                    f"{r.split('.')[-1]} staging ({r}): 64-bit values are "
                    f"downcast at the device boundary (JAX x64 is off) — keep "
                    f"host-side and whitelist, or use an explicit 32-bit dtype",
                ))
        elif isinstance(node, ast.Call):
            fname = (node.func.attr if isinstance(node.func, ast.Attribute)
                     else node.func.id if isinstance(node.func, ast.Name) else "")
            if fname not in _ARRAY_FACTORIES:
                continue
            for cand in list(node.args) + [k.value for k in node.keywords
                                           if k.arg in (None, "dtype")]:
                if isinstance(cand, ast.Constant) and cand.value in _WIDE_STRS:
                    out.append(Finding(
                        "dtype-drift", Severity.WARNING, ctx.path,
                        cand.lineno, cand.col_offset,
                        f'string dtype "{cand.value}" passed to {fname}(): '
                        f"64-bit values are downcast at the device boundary — "
                        f"use a 32-bit dtype or whitelist the host staging",
                    ))
    return out


# ------------------------------------------------------------ carry-contract --


def _carry_annotation(ctx: ModuleContext, body: ast.FunctionDef,
                      carry_index: int) -> Optional[ast.arg]:
    params = body.args.posonlyargs + body.args.args
    if carry_index >= len(params):
        return None
    return params[carry_index]


def _returned_carry_exprs(body: ast.FunctionDef) -> List[ast.expr]:
    """First tuple element of every `return (carry, y)` in the body (local
    scope only). A bare non-tuple return is itself taken as the carry."""
    out: List[ast.expr] = []
    for node in _local_walk(body):
        if isinstance(node, ast.Return) and node.value is not None:
            v = node.value
            out.append(v.elts[0] if isinstance(v, ast.Tuple) and v.elts else v)
    return out


@register(
    "carry-contract", Severity.ERROR,
    "Every lax.scan body must declare its carry with a NamedTuple contract "
    "(annotated carry parameter) and return that same contract from every "
    "branch: a carry whose pytree structure, leaf shapes, or dtypes shift "
    "between branches recompiles per step or fails deep inside XLA with no "
    "source location.",
)
def rule_carry_contract(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    for site in ctx.scans:
        if site.kind != "scan":
            continue
        call_line, call_col = site.call.lineno, site.call.col_offset
        if site.body is None:
            out.append(Finding(
                "carry-contract", Severity.ERROR, ctx.path, call_line, call_col,
                "lax.scan body is not a statically resolvable function "
                "(lambda or imported name) — declare a local body function "
                "with a NamedTuple-annotated carry",
            ))
            continue
        body = site.body
        carry = _carry_annotation(ctx, body, site.carry_index)
        if carry is None or carry.annotation is None:
            out.append(Finding(
                "carry-contract", Severity.ERROR, ctx.path,
                body.lineno, body.col_offset,
                f"scan body '{body.name}' has no carry contract: annotate its "
                f"carry parameter with a NamedTuple type",
            ))
            continue
        ann = carry.annotation
        ann_name = ann.id if isinstance(ann, ast.Name) else None
        if ann_name is None:
            out.append(Finding(
                "carry-contract", Severity.ERROR, ctx.path,
                carry.lineno, carry.col_offset,
                f"carry of scan body '{body.name}' is annotated with a "
                f"non-NamedTuple type expression — use a NamedTuple class",
            ))
            continue
        fields = ctx.namedtuples.get(ann_name)  # None => imported; trusted

        # initial carry should be constructed with the same contract
        init = site.init
        if isinstance(init, ast.Tuple):
            out.append(Finding(
                "carry-contract", Severity.ERROR, ctx.path,
                init.lineno, init.col_offset,
                f"initial carry of lax.scan is a bare tuple but body "
                f"'{body.name}' declares contract {ann_name} — construct "
                f"{ann_name}(...) so the pytree structures match",
            ))

        # every return branch must yield the same contract
        aliases_ok: Set[str] = {carry.arg}
        for node in _local_walk(body):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                v = node.value
                if _carry_expr_ok(ctx, v, ann_name, aliases_ok):
                    aliases_ok.add(node.targets[0].id)
        for rexpr in _returned_carry_exprs(body):
            if not _carry_expr_ok(ctx, rexpr, ann_name, aliases_ok):
                out.append(Finding(
                    "carry-contract", Severity.ERROR, ctx.path,
                    rexpr.lineno, rexpr.col_offset,
                    f"scan body '{body.name}' returns a carry that is not "
                    f"its declared contract {ann_name} on this branch",
                ))
            elif (isinstance(rexpr, ast.Call) and isinstance(rexpr.func, ast.Name)
                    and rexpr.func.id == ann_name and fields is not None
                    and rexpr.args and not rexpr.keywords
                    and len(rexpr.args) != len(fields)):
                out.append(Finding(
                    "carry-contract", Severity.ERROR, ctx.path,
                    rexpr.lineno, rexpr.col_offset,
                    f"{ann_name}(...) constructed with {len(rexpr.args)} "
                    f"positional leaves but the contract declares "
                    f"{len(fields)} fields",
                ))
    return out


def _carry_expr_ok(ctx: ModuleContext, expr: ast.expr, ann_name: str,
                   aliases_ok: Set[str]) -> bool:
    if isinstance(expr, ast.Name):
        return expr.id in aliases_ok
    if isinstance(expr, ast.Call):
        f = expr.func
        if isinstance(f, ast.Name):
            if f.id == ann_name:
                return True
            if f.id in ctx.namedtuples:
                return False  # a DIFFERENT contract constructor: the exact bug
            return True  # unknown callable — can't verify statically, trust it
        if isinstance(f, ast.Attribute) and f.attr == "_replace":
            return bool(_names_in(f.value) & aliases_ok) or isinstance(f.value, ast.Call)
    return False


# -------------------------------------------------------------- metric-in-jit --

# wall-clock reads: meaningless under trace (they'd run once at trace time and
# bake a constant timestamp into the compiled program)
_CLOCK_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic", "time.process_time",
    "time.perf_counter_ns", "time.monotonic_ns", "time.time_ns",
}
# registry mutation methods. `.set(...)` is deliberately ABSENT: traced code
# is full of `arr.at[i].set(v)`, and a gauge .set under trace is caught by the
# factory/import half below whenever the metric came from obs.metrics.
_METRIC_MUTATORS = {"inc", "observe"}
# obs.metrics surface: constructing or fetching a metric under trace is as
# wrong as mutating one
_METRIC_FACTORIES = {
    "open_simulator_tpu.obs.metrics.counter",
    "open_simulator_tpu.obs.metrics.gauge",
    "open_simulator_tpu.obs.metrics.histogram",
}


@register(
    "metric-in-jit", Severity.ERROR,
    "Metrics-registry mutation (.inc()/.observe()/obs.metrics factories) or "
    "wall-clock read (time.perf_counter()/time.time()/...) inside jit/pjit or "
    "a lax.scan|while_loop body. Instrumentation must stay on the host side "
    "of the device boundary: under trace these run ONCE at trace time — the "
    "counter moves per compile instead of per dispatch and the timestamp is "
    "a baked constant — or force a host sync mid-kernel.",
)
def rule_metric_in_jit(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    for fn in ctx.traced_functions():
        for node in _local_walk(fn):
            if not isinstance(node, ast.Call):
                continue
            hazard: Optional[str] = None
            target = ctx.resolve(node.func)
            if target in _CLOCK_CALLS:
                hazard = f"{target}()"
            elif target is not None and (
                    target in _METRIC_FACTORIES
                    or target.startswith("open_simulator_tpu.obs.")):
                hazard = f"{target}(...)"
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_MUTATORS):
                hazard = f".{node.func.attr}()"
            if hazard:
                out.append(Finding(
                    "metric-in-jit", Severity.ERROR, ctx.path,
                    node.lineno, node.col_offset,
                    f"{hazard} inside traced '{fn.name}' — instrumentation "
                    f"must stay host-side of the device boundary (move the "
                    f"registry update / clock read to the dispatch site)",
                ))
    return out


# -------------------------------------------------------- swallowed-exception --

_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log", "log_message"}
_COUNT_METHODS = {"inc", "observe", "set", "labels"}
_REPORT_CALLS = {"print"}  # plus sys.exit / os._exit via resolve below
_EXIT_CALLS = {"sys.exit", "os._exit", "os.abort"}


def _walk_no_defs(stmts):
    """Walk statements without descending into nested defs/lambdas (a nested
    function that raises is a definition, not handling)."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _handler_is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    elems = t.elts if isinstance(t, ast.Tuple) else [t]
    return any(isinstance(e, ast.Name) and e.id in ("Exception", "BaseException")
               for e in elems)


def _handler_handles(ctx: ModuleContext, handler: ast.ExceptHandler) -> bool:
    for node in _walk_no_defs(handler.body):
        if isinstance(node, (ast.Raise, ast.Return)):
            return True
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id in _REPORT_CALLS:
            return True
        if isinstance(f, ast.Attribute) and (
                f.attr in _LOG_METHODS or f.attr in _COUNT_METHODS):
            return True
        if (ctx.resolve(f) or "") in _EXIT_CALLS:
            return True
    return False


@register(
    "swallowed-exception", Severity.WARNING,
    "A broad exception handler (bare except / except Exception/BaseException) "
    "that neither re-raises, returns, logs, nor moves a metric. Silent "
    "swallowing is how retryable failures, injected faults, and corrupted "
    "state disappear from every observability surface — handle narrowly, or "
    "whitelist deliberate best-effort blocks with "
    "`# simonlint: ignore[swallowed-exception] -- <why>`.",
)
def rule_swallowed_exception(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Try):
            continue
        for handler in node.handlers:
            if not _handler_is_broad(handler):
                continue
            if _handler_handles(ctx, handler):
                continue
            what = ("bare except:" if handler.type is None
                    else "except Exception" if not isinstance(handler.type, ast.Tuple)
                    else "broad except tuple")
            out.append(Finding(
                "swallowed-exception", Severity.WARNING, ctx.path,
                handler.lineno, handler.col_offset,
                f"{what} swallows the error: the handler neither re-raises, "
                f"returns, logs, nor counts — failures vanish silently "
                f"(narrow the type, or log/count and whitelist)",
            ))
    return out


# -------------------------------------------------------------- naked-dispatch --

# The compiled scheduling/probe kernels whose dispatch (or the fetch of whose
# results) can block forever on a wedged backend. Every call site in hot-path
# code must run under guard.supervised so the watchdog can contain it.
_DISPATCH_KERNELS = {
    "schedule_batch", "schedule_wave", "schedule_affinity_wave",
    "schedule_group_serial", "probe_serial_fanout",
    "probe_group_serial_fanout", "probe_wave_fanout",
    "probe_affinity_wave_fanout", "serve_whatif_fanout",
    "serve_wave_fanout", "sweep_wave_fanout", "sweep_whatif_fanout",
    "feasibility_jit", "explain_jit",
}


def _is_kernel_dispatch(ctx: ModuleContext, call: ast.Call) -> Optional[str]:
    """The kernel name when `call` invokes a dispatch kernel of the kernels
    module (attribute form `kernels.X(...)` via any alias, or a name imported
    absolutely from open_simulator_tpu.ops.kernels), else None."""
    r = ctx.resolve(call.func)
    if r is None:
        return None
    parts = r.split(".")
    if parts[-1] not in _DISPATCH_KERNELS:
        return None
    if "kernels" in parts[:-1]:
        return parts[-1]
    return None


def _supervised_functions(ctx: ModuleContext) -> Set[ast.AST]:
    """Function/lambda nodes whose BODY is executed under guard.supervised:
    the first argument of a supervised(...) call, resolved through a direct
    name, a functools.partial wrapper, or a method attribute."""
    out: Set[ast.AST] = set()

    def mark(expr: Optional[ast.expr]) -> None:
        if expr is None:
            return
        if isinstance(expr, ast.Lambda):
            out.add(expr)
            return
        fn = ctx.lookup_function(expr)
        if fn is not None:
            out.add(fn)
            return
        if isinstance(expr, ast.Call):
            r = ctx.resolve(expr.func) or ""
            if r in PARTIAL_NAMES or r.endswith(".partial"):
                mark(expr.args[0] if expr.args else None)
            return
        if isinstance(expr, ast.Attribute):
            # self._dispatch_round and friends: methods register by name
            for fn in ctx.functions.get(expr.attr, []):
                out.add(fn)

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        r = ctx.resolve(node.func) or ""
        if r == "supervised" or r.endswith(".supervised"):
            mark(node.args[0] if node.args else None)
    return out


@register(
    "naked-dispatch", Severity.WARNING,
    "A compiled scheduling/probe kernel is dispatched directly, outside "
    "guard.supervised (resilience/guard.py). An unsupervised dispatch on a "
    "wedged backend blocks the process forever — the exact failure mode the "
    "dispatch watchdog exists to contain. Route the call through "
    "guard.supervised (directly, via functools.partial, or by passing the "
    "enclosing function), or whitelist deliberate harness/offline code with "
    "`# simonlint: ignore[naked-dispatch] -- <why>`.",
)
def rule_naked_dispatch(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    guarded = _supervised_functions(ctx)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        kernel = _is_kernel_dispatch(ctx, node)
        if kernel is None:
            continue
        covered = False
        cur: Optional[ast.AST] = node
        while cur is not None:
            if cur in guarded:
                covered = True
                break
            if isinstance(cur, ast.Call):
                r = ctx.resolve(cur.func) or ""
                if r == "supervised" or r.endswith(".supervised"):
                    covered = True
                    break
            cur = ctx.parents.get(cur)
        if not covered:
            out.append(Finding(
                "naked-dispatch", Severity.WARNING, ctx.path,
                node.lineno, node.col_offset,
                f"kernels.{kernel}(...) dispatched outside guard.supervised "
                f"— a wedged backend would hang here with no watchdog, "
                f"quarantine, or failover (wrap the dispatch, or whitelist "
                f"non-hot-path harness code)",
            ))
    return out


# ----------------------------------------------------- unattributed-dispatch --


def _wrapped_dispatch_targets(
        ctx: ModuleContext, call: ast.Call,
        encl: Optional[ast.AST]) -> tuple:
    """(function_nodes, kernel_name) for a specific supervised(...) call —
    the per-call-site companion of _supervised_functions. Resolves the first
    argument through a direct name, a functools.partial wrapper, a method
    attribute, or one level of local assignment in the enclosing function
    (`call = functools.partial(...); supervised(call, ...)`). kernel_name is
    set when the wrapped callable IS a dispatch kernel (partial-of-kernel,
    the engine's hottest form), independent of function_nodes."""
    fns: List[ast.AST] = []
    kernel: List[Optional[str]] = [None]

    def add(expr: Optional[ast.expr], depth: int = 0) -> None:
        if expr is None or depth > 4:
            return
        if isinstance(expr, ast.Lambda):
            fns.append(expr)
            return
        r = ctx.resolve(expr)
        if r is not None and r.split(".")[-1] in _DISPATCH_KERNELS:
            kernel[0] = r.split(".")[-1]
            return
        fn = ctx.lookup_function(expr)
        if fn is not None:
            fns.append(fn)
            return
        if isinstance(expr, ast.Call):
            cr = ctx.resolve(expr.func) or ""
            if cr in PARTIAL_NAMES or cr.endswith(".partial"):
                add(expr.args[0] if expr.args else None, depth + 1)
            return
        if isinstance(expr, ast.Name) and encl is not None:
            for node in ast.walk(encl):
                if (isinstance(node, ast.Assign)
                        and any(isinstance(t, ast.Name) and t.id == expr.id
                                for t in node.targets)):
                    add(node.value, depth + 1)
            return
        if isinstance(expr, ast.Attribute):
            fns.extend(ctx.functions.get(expr.attr, []))

    add(call.args[0] if call.args else None)
    return fns, kernel[0]


def _has_record_dispatch(ctx: ModuleContext,
                         scope: Optional[ast.AST]) -> bool:
    if scope is None:
        return False
    for n in ast.walk(scope):
        if isinstance(n, ast.Call):
            r = ctx.resolve(n.func) or ""
            if r == "record_dispatch" or r.endswith(".record_dispatch"):
                return True
    return False


@register(
    "unattributed-dispatch", Severity.WARNING,
    "A hot kernel is dispatched under guard.supervised with no "
    "obs.record_dispatch(...) in its attribution path. record_dispatch is "
    "the single definition of 'one dispatch happened': it keys the "
    "compile-cache hit/miss census AND parks the simonpulse ledger note "
    "that guard.supervised commits after the unit returns — without it the "
    "dispatch is invisible to both. Call obs.record_dispatch(kernel, "
    "**dims) at the supervised call site (engine pattern) or inside the "
    "wrapped function body (probe pattern), or whitelist deliberate "
    "harness/offline code with "
    "`# simonlint: ignore[unattributed-dispatch] -- <why>`.",
)
def rule_unattributed_dispatch(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        r = ctx.resolve(node.func) or ""
        if not (r == "supervised" or r.endswith(".supervised")):
            continue
        encl: Optional[ast.AST] = ctx.parents.get(node)
        while encl is not None and not isinstance(
                encl, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            encl = ctx.parents.get(encl)
        wrapped, kernel = _wrapped_dispatch_targets(ctx, node, encl)
        if kernel is None:
            for fn in wrapped:
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Call):
                        k = _is_kernel_dispatch(ctx, sub)
                        if k is not None:
                            kernel = k
                            break
                if kernel is not None:
                    break
        if kernel is None:
            continue  # supervised fetch/host work — not a kernel dispatch
        # attribution path 1 (probe pattern): record_dispatch runs inside
        # the wrapped body, so the note lands in the worker's context
        if any(_has_record_dispatch(ctx, fn) for fn in wrapped):
            continue
        # attribution path 2 (engine pattern): record_dispatch at the
        # supervised call site, before the unit is handed to the watchdog
        if _has_record_dispatch(ctx, encl if encl is not None else ctx.tree):
            continue
        out.append(Finding(
            "unattributed-dispatch", Severity.WARNING, ctx.path,
            node.lineno, node.col_offset,
            f"kernels.{kernel}(...) runs under guard.supervised with no "
            f"record_dispatch in its attribution path — the dispatch is "
            f"invisible to the compile-cache census and lands in the "
            f"simonpulse ledger with no kernel/bucket attribution (call "
            f"obs.record_dispatch at the call site or inside the wrapped "
            f"body, or whitelist offline harness code)",
        ))
    return out


# ---------------------------------------------------------- fetch-in-wave-loop --

# Loop-name fragments marking per-segment / per-epoch / per-round dispatch
# loops (the engine's `for seg in segs:` dispatch loop, the wave kernels'
# epoch machinery mirrored on the host, capacity-search rounds). A fetch
# inside such a body pays one full device round trip PER ITERATION — the
# exact tunnel-latency hazard the PR 3 "fetch ONE concatenated result at the
# end" rewrite removed, and the one xray-style instrumentation most easily
# reintroduces.
_WAVE_LOOP_NAMES = ("seg", "epoch", "round", "wave")

# Resolved call targets that force a device→host sync when applied to a
# device value. jnp.* stays device-side and is deliberately absent.
_FETCH_CALLS = {
    "numpy.asarray", "numpy.array", "jax.device_get",
    "jax.block_until_ready",
}
_FETCH_ATTRS = {"block_until_ready", "device_get"}


def _loopish_names(node: ast.AST) -> Set[str]:
    """Lower-cased identifier names in a loop's target/iter (For) or test
    (While) — the signal for 'this iterates segments/epochs/rounds'."""
    if isinstance(node, ast.For):
        src: List[ast.AST] = [node.target, node.iter]
    elif isinstance(node, ast.While):
        src = [node.test]
    else:
        return set()
    out: Set[str] = set()
    for expr in src:
        out |= {n.lower() for n in _names_in(expr)}
    return out


@register(
    "fetch-in-wave-loop", Severity.WARNING,
    "A device->host fetch (np.asarray / jax.device_get / block_until_ready) "
    "sits inside a per-segment/per-epoch/per-round loop body. Each "
    "iteration then pays a full device round trip — behind an accelerator "
    "tunnel that turns milliseconds of device work into seconds of waiting "
    "(the engine's dispatch loop collects results and fetches ONE "
    "concatenated array after the loop for exactly this reason). Move the "
    "fetch to a post-loop spill point, or whitelist a deliberate blocking "
    "site with `# simonlint: ignore[fetch-in-wave-loop] -- <why>`.",
)
def rule_fetch_in_wave_loop(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    seen: Set[tuple] = set()  # nested wave-named loops report a site once
    for loop in ast.walk(ctx.tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        names = _loopish_names(loop)
        if not any(frag in name for name in names
                   for frag in _WAVE_LOOP_NAMES):
            continue
        for sub in ast.walk(loop):
            if sub is loop or not isinstance(sub, ast.Call):
                continue
            if (sub.lineno, sub.col_offset) in seen:
                continue
            r = ctx.resolve(sub.func) or ""
            leaf = r.split(".")[-1]
            if r not in _FETCH_CALLS and leaf not in _FETCH_ATTRS:
                continue
            seen.add((sub.lineno, sub.col_offset))
            out.append(Finding(
                "fetch-in-wave-loop", Severity.WARNING, ctx.path,
                sub.lineno, sub.col_offset,
                f"{r or leaf}(...) inside a "
                f"per-{'/'.join(sorted(names & set(_WAVE_LOOP_NAMES)) or ['segment'])} "
                f"loop body forces one device round trip per iteration — "
                f"collect device values and fetch once after the loop "
                f"(designated spill point)",
            ))
    return out


# -------------------------------------------------------------- contract-spec --


@register(
    "contract-spec", Severity.ERROR,
    "An @shaped(...) kernel contract names a parameter the function does not "
    "have, or a spec string that does not parse ('[DIMS] dtype', e.g. "
    "'[N, R] f32'). Broken contracts are worse than none: simonlint and "
    "readers both trust them.",
)
def rule_contract_spec(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    for defs in ctx.functions.values():
        for fn in defs:
            for dec in fn.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                r = ctx.resolve(dec.func) or ""
                if not (r == "shaped" or r.endswith(".shaped")):
                    continue
                a = fn.args
                params = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
                for kw in dec.keywords:
                    if kw.arg is None:
                        continue
                    if kw.arg not in params and kw.arg not in ("ret", "returns"):
                        out.append(Finding(
                            "contract-spec", Severity.ERROR, ctx.path,
                            kw.value.lineno, kw.value.col_offset,
                            f"@shaped names '{kw.arg}' which is not a "
                            f"parameter of '{fn.name}'",
                        ))
                        continue
                    if isinstance(kw.value, ast.Constant) and isinstance(
                            kw.value.value, str):
                        try:
                            parse_spec(kw.value.value)
                        except ValueError as e:
                            out.append(Finding(
                                "contract-spec", Severity.ERROR, ctx.path,
                                kw.value.lineno, kw.value.col_offset,
                                f"@shaped spec for '{kw.arg}' does not parse: {e}",
                            ))
    return out


# ---------------------------------------------------------- unsharded-transfer --

# The sharded dispatch chain (parallel/mesh.py ShardedKernels) only stays
# reshard-free when every transfer and every jitted dispatch in a mesh-aware
# hot path declares its layout. A naked jax.device_put lands wherever the
# default device policy says (then the first sharded consumer pays a
# reshard); a jit over a dispatch kernel without in_shardings lets GSPMD
# re-infer per call.


def _module_is_mesh_aware(ctx: ModuleContext) -> bool:
    """True when the module imports the parallel (mesh/sharding) machinery —
    engine.py, probe.py, and parallel/ itself qualify via their (possibly
    function-local, possibly relative) `from ..parallel.mesh import ...`."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if "parallel" in mod.split("."):
                return True
        elif isinstance(node, ast.Import):
            if any("parallel" in a.name.split(".") for a in node.names):
                return True
    return False


@register(
    "unsharded-transfer", Severity.WARNING,
    "In a mesh-aware hot path (a module importing parallel/), a "
    "jax.device_put without an explicit sharding/device argument or a "
    "jax.jit over a dispatch kernel without in_shardings breaks the "
    "end-to-end sharding contract: the array lands in the default layout "
    "(or GSPMD re-infers one per call) and the next chained dispatch pays a "
    "reshard — the exact regression simon_reshard_bytes_total exists to "
    "catch at runtime. Pass the sharding explicitly (table_shardings / "
    "carry_shardings / fanout_shardings), route the dispatch through "
    "parallel.mesh.sharded_kernels, or whitelist a deliberate host-layout "
    "transfer with `# simonlint: ignore[unsharded-transfer] -- <why>`.",
)
def rule_unsharded_transfer(ctx: ModuleContext) -> List[Finding]:
    if not _module_is_mesh_aware(ctx):
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        r = ctx.resolve(node.func) or ""
        if r == "jax.device_put":
            # only a TARGET placement counts: a `src=` keyword names where
            # the array comes from, committing no output layout at all
            has_target = len(node.args) >= 2 or any(
                kw.arg == "device" for kw in node.keywords)
            if not has_target:
                out.append(Finding(
                    "unsharded-transfer", Severity.WARNING, ctx.path,
                    node.lineno, node.col_offset,
                    "jax.device_put without an explicit sharding commits the "
                    "array to the default device layout; the first sharded "
                    "consumer then reshards it — pass the NamedSharding "
                    "(table_shardings/carry_shardings/fanout_shardings)",
                ))
        elif r in JIT_NAMES and node.args:
            target = ctx.resolve(node.args[0]) or ""
            if target.split(".")[-1] not in _DISPATCH_KERNELS:
                continue
            if not any(kw.arg == "in_shardings" for kw in node.keywords):
                out.append(Finding(
                    "unsharded-transfer", Severity.WARNING, ctx.path,
                    node.lineno, node.col_offset,
                    f"jax.jit({target.split('.')[-1]}, ...) in a mesh-aware "
                    f"module without in_shardings lets GSPMD re-infer the "
                    f"layout per call — declare in_shardings/out_shardings "
                    f"(or reuse parallel.mesh.sharded_kernels)",
                ))
    return out


# ------------------------------------------------ config-scope-across-thread --

# JAX config context managers whose effect is THREAD-LOCAL: entering one and
# then handing work to another thread silently drops the scope for that work
# (jax's config stack lives in a per-thread structure that copy_context()
# does not carry). This is the exact PR 5 failure class: a post-failover
# dispatch wrapped in `with jax.default_device(cpu)` kept landing on the
# quarantined backend because the dispatch ran in the watchdog's worker
# thread. The fix — re-entering the scope INSIDE the worker (guard.supervised
# does this) — leaves no `with` wrapping a cross-thread submission, so a
# clean tree has zero findings.
_JAX_SCOPE_CMS = {
    "jax.default_device", "jax.disable_jit", "jax.default_matmul_precision",
    "jax.transfer_guard", "jax.log_compiles", "jax.debug_nans",
    "jax.checking_leaks", "jax.enable_checks",
}
# a constructed Thread/Timer/Process runs its target on another thread even
# if .start() happens later; to_thread/run_in_executor submit directly
_THREAD_FACTORIES = {
    "threading.Thread", "threading.Timer", "multiprocessing.Process",
    "asyncio.to_thread",
}
_SUBMIT_ATTRS = {"submit", "run_in_executor", "apply_async", "map_async"}


@register(
    "config-scope-across-thread", Severity.ERROR,
    "A jax config context manager (jax.default_device / disable_jit / "
    "default_matmul_precision / ...) is entered in one thread while work is "
    "submitted to another inside the scope (executor.submit, "
    "threading.Thread/Timer targets, asyncio.to_thread). JAX config scopes "
    "are thread-local and are NOT carried by copy_context(): the submitted "
    "work runs with the scope silently absent — the post-failover "
    "wrong-backend dispatch bug. Re-enter the scope inside the worker "
    "(the guard.supervised pattern), or whitelist work that provably never "
    "touches jax with `# simonlint: ignore[config-scope-across-thread] -- "
    "<why>`.",
)
def rule_config_scope_across_thread(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        scope: Optional[str] = None
        for item in node.items:
            expr = item.context_expr
            target = expr.func if isinstance(expr, ast.Call) else expr
            r = ctx.resolve(target)
            if r in _JAX_SCOPE_CMS:
                scope = r
                break
        if scope is None:
            continue
        for sub in _walk_no_defs(node.body):
            if not isinstance(sub, ast.Call):
                continue
            r = ctx.resolve(sub.func) or ""
            hazard: Optional[str] = None
            if r in _THREAD_FACTORIES:
                hazard = f"{r}(...)"
            elif (isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _SUBMIT_ATTRS):
                hazard = f".{sub.func.attr}(...)"
            if hazard:
                out.append(Finding(
                    "config-scope-across-thread", Severity.ERROR, ctx.path,
                    sub.lineno, sub.col_offset,
                    f"{hazard} inside `with {scope}(...)`: jax config scopes "
                    f"are thread-local, so the submitted work runs with the "
                    f"scope silently dropped — re-enter the scope inside the "
                    f"worker (guard.supervised pattern)",
                ))
    return out


# ------------------------------------------------------- span-outside-guard --

# Span-like context managers whose wall-time measurement is the concern:
# utils/trace.Span and the simonscope live-span context managers.
_SPAN_ATTRS = {"span", "request_span"}


def _is_span_ctx(ctx: ModuleContext, expr: ast.expr) -> Optional[str]:
    """The span-context name when `expr` (a with-item context expression)
    opens a tracing span: utils/trace Span(...) via any import form, or a
    scope span method (`sc.span(...)` / `sc.request_span(...)`)."""
    if not isinstance(expr, ast.Call):
        return None
    r = ctx.resolve(expr.func)
    if r is not None and (r == "Span" or r.endswith(".Span")):
        return r
    f = expr.func
    if isinstance(f, ast.Attribute) and f.attr in _SPAN_ATTRS:
        return f".{f.attr}(...)"
    return None


@register(
    "span-outside-guard", Severity.WARNING,
    "A tracing Span (utils/trace.Span or a simonscope span) is opened around "
    "a kernel dispatch site that is not inside guard.supervised. The span "
    "then measures wall time the watchdog can abandon: on a wedged backend "
    "the unsupervised dispatch blocks forever INSIDE the span, so the trace "
    "never records the phase at all (and the process hangs with it). Wrap "
    "the dispatch in guard.supervised — the span may stay around the "
    "supervised call — or whitelist deliberate offline/harness timing with "
    "`# simonlint: ignore[span-outside-guard] -- <why>`.",
)
def rule_span_outside_guard(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    guarded = _supervised_functions(ctx)

    def covered(call: ast.Call) -> bool:
        cur: Optional[ast.AST] = call
        while cur is not None:
            if cur in guarded:
                return True
            if isinstance(cur, ast.Call):
                r = ctx.resolve(cur.func) or ""
                if r == "supervised" or r.endswith(".supervised"):
                    return True
            cur = ctx.parents.get(cur)
        return False

    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        span_name = None
        for item in node.items:
            span_name = _is_span_ctx(ctx, item.context_expr)
            if span_name is not None:
                break
        if span_name is None:
            continue
        for sub in _walk_no_defs(node.body):
            if not isinstance(sub, ast.Call):
                continue
            kernel = _is_kernel_dispatch(ctx, sub)
            if kernel is None or covered(sub):
                continue
            out.append(Finding(
                "span-outside-guard", Severity.WARNING, ctx.path,
                sub.lineno, sub.col_offset,
                f"kernels.{kernel}(...) dispatched inside `with "
                f"{span_name}` but outside guard.supervised — the span "
                f"records wall time the watchdog can abandon (a wedge "
                f"hangs inside the span and the phase is never traced); "
                f"supervise the dispatch",
            ))
    return out


# ---------------------------------------------------- collective-in-scan-body --

# Cross-shard collectives: one launch per loop ITERATION when called from a
# scan/while/fori body. Each costs a cross-device round trip, so a loop that
# reduces per round pays latency x rounds where a stacked operand reduced once
# per loop entry (or once per epoch) pays it once.
_COLLECTIVE_NAMES = {
    "jax.lax.psum", "jax.lax.pmax", "jax.lax.pmin", "jax.lax.pmean",
    "jax.lax.all_gather", "jax.lax.all_to_all", "jax.lax.ppermute",
    "jax.lax.psum_scatter", "jax.lax.pshuffle",
}


@register(
    "collective-in-scan-body", Severity.WARNING,
    "A cross-shard collective (psum / pmax / all_gather / ...) executes inside "
    "a lax.scan / while_loop / fori_loop body, directly or through a locally "
    "defined helper. The collective then launches once per ITERATION: its "
    "cross-device latency multiplies by the trip count, which is exactly the "
    "pattern that kept the sharded hard-predicate wave at 0.1x of serial. "
    "Stack the per-round operands and reduce ONCE per loop entry (max-space "
    "packing handles mins: -max(-x) == min(x) exactly in f32), or hoist the "
    "collective to the epoch boundary. A deliberate epoch-amortized collective "
    "— one reduction per outer-loop iteration over a stacked operand — is the "
    "fix, not a violation; waive it with "
    "`# simonlint: ignore[collective-in-scan-body] -- <why>`.",
)
def rule_collective_in_scan_body(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    seen_sites: Set[tuple] = set()

    for site in ctx.scans:
        if site.body is None:
            continue
        # Walk the body transitively through locally-called helpers: kernels
        # factor loop bodies into `front(...)` / `tail(...)` functions, and the
        # collective usually lives in the helper, not the body literal.
        visited = {site.body}
        frontier = [site.body]
        while frontier:
            fn = frontier.pop()
            for sub in _walk_no_defs(fn.body):
                if not isinstance(sub, ast.Call):
                    continue
                r = ctx.resolve(sub.func)
                if r in _COLLECTIVE_NAMES:
                    key = (sub.lineno, sub.col_offset)
                    if key in seen_sites:
                        continue
                    seen_sites.add(key)
                    out.append(Finding(
                        "collective-in-scan-body", Severity.WARNING, ctx.path,
                        sub.lineno, sub.col_offset,
                        f"{r}(...) runs inside a {site.kind} body (via "
                        f"`{site.body.name}`): one cross-shard launch per "
                        f"iteration — stack the operands and reduce once per "
                        f"loop entry, or hoist to the epoch boundary",
                    ))
                    continue
                callee = ctx.lookup_function(sub.func)
                if callee is not None and callee not in visited:
                    visited.add(callee)
                    frontier.append(callee)
    return out


# ---------------------------------------------------------- suppression-reason --


def _waiver_anchor(lines: List[str], lineno: int) -> int:
    """The code line a waiver at `lineno` binds to, mirroring
    base.suppressions_for: a trailing comment binds to its own line, a
    comment-only line carries forward to the first code line below. The
    finding anchors THERE so a reasoned ignore[suppression-reason] waiver
    covers it through the normal suppression mechanics."""
    if not lines[lineno - 1].lstrip().startswith("#"):
        return lineno
    for i in range(lineno + 1, len(lines) + 1):
        stripped = lines[i - 1].strip()
        if stripped and not stripped.startswith("#"):
            return i
    return lineno


@register(
    "suppression-reason", Severity.WARNING,
    "A `# simonlint: ignore[...]` waiver without its `-- reason` text. Every "
    "suppression is a claim that a hazard is deliberate; the reason is the "
    "evidence reviewers audit. Bare waivers rot: nobody can tell a sanctioned "
    "device boundary from a silenced bug. (This finding is itself only "
    "waivable by an explicit reasoned `ignore[suppression-reason]` — a bare "
    "`ignore[*]` does not cover it.)",
)
def rule_suppression_reason(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    for lineno, raw in enumerate(ctx.lines, start=1):
        m = _SUPPRESS_RE.search(raw)
        if m is None:
            continue
        if _REASON_RE.match(raw[m.end():]):  # the same test base.py applies
            continue
        anchor = _waiver_anchor(ctx.lines, lineno)
        where = "" if anchor == lineno else f" (waiver at line {lineno})"
        out.append(Finding(
            "suppression-reason", Severity.WARNING, ctx.path,
            anchor, m.start(),
            f"waiver ignore[{m.group(1).strip()}] carries no `-- reason` "
            f"text{where} — state why the hazard is deliberate so reviewers "
            f"can audit it",
        ))
    return out


# --------------------------------------------------------- per-pod-host-loop --

# Modules that have adopted the columnar pod store (simulator/store.py) are
# held to its contract: batch-sized work is array ops over the store's
# columns, and a Python `for` over the pod batch is the O(pods) host loop the
# store exists to remove (the 1M-pod row spent ~60% of wall in exactly two
# such loops before the rewrite). Applicability is structural — the module
# imports `.store` / `..simulator.store` — so adopting the store opts a
# module into the fence, and fallback loops that must remain (dict batches,
# armed preemption, gpu/storage ledgers) carry reasoned waivers naming the
# columnar path that replaces them.
_POD_BATCH_NAMES = {"pods", "to_schedule", "batch", "request_pods"}


def _module_imports_store(ctx: ModuleContext) -> bool:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.split(".")[-1] == "store" or any(
                    a.name == "store" for a in node.names):
                return True
        elif isinstance(node, ast.Import):
            if any(a.name.split(".")[-1] == "store" for a in node.names):
                return True
    return False


@register(
    "per-pod-host-loop", Severity.WARNING,
    "A Python `for` over a pod batch (pods / to_schedule / batch) in a "
    "module that has adopted the columnar PodStore. Each iteration is host "
    "work that scales with the batch — the O(pods) dict traversal the "
    "struct-of-arrays store exists to replace (encode is one gather per "
    "template, commit is one bulk array pass). Vectorize over the store's "
    "columns, or whitelist a deliberate fallback with "
    "`# simonlint: ignore[per-pod-host-loop] -- <why>` naming the columnar "
    "path that covers the hot case.",
)
def rule_per_pod_host_loop(ctx: ModuleContext) -> List[Finding]:
    if not _module_imports_store(ctx):
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.For):
            continue
        hits = _names_in(node.iter) & _POD_BATCH_NAMES
        if not hits:
            continue
        out.append(Finding(
            "per-pod-host-loop", Severity.WARNING, ctx.path,
            node.lineno, node.col_offset,
            f"`for` over {'/'.join(sorted(hits))} runs O(pods) Python in a "
            f"store-adopted hot module — vectorize over the PodStore columns "
            f"(EncodedRows gather / bulk commit) or waive the deliberate "
            f"fallback with its reason",
        ))
    return out


# ------------------------------------------------------------ unbounded-queue --

# The serving tier's memory-safety discipline (simonha, serve/ha.py): every
# producer/consumer channel in a long-lived process is a memory hazard unless
# its depth is bounded — a stalled consumer turns an unbounded queue into an
# OOM kill with no 429 ever sent. stdlib spellings of "unbounded":
# queue.Queue/LifoQueue/PriorityQueue with no maxsize (or an explicit
# maxsize=0), SimpleQueue (never bounded), and collections.deque with no
# maxlen.
_QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue"}


def _is_zero(node: Optional[ast.AST]) -> bool:
    return (isinstance(node, ast.Constant) and isinstance(node.value, int)
            and not isinstance(node.value, bool) and node.value == 0)


@register(
    "unbounded-queue", Severity.WARNING,
    "A queue.Queue()/LifoQueue/PriorityQueue without a positive maxsize, a "
    "SimpleQueue (unboundable by construction), or a collections.deque() "
    "without maxlen. In a long-lived serving process an unbounded channel is "
    "deferred OOM: a stalled or slow consumer absorbs the backlog into heap "
    "instead of shedding it at admission (simonha's bounded-queue + 429 "
    "discipline). Pass maxsize=/maxlen=, or waive a deliberately unbounded "
    "channel with `# simonlint: ignore[unbounded-queue] -- <why it is "
    "bounded elsewhere>`.",
)
def rule_unbounded_queue(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute):
            name = func.attr
        elif isinstance(func, ast.Name):
            name = func.id
        else:
            continue
        hazard: Optional[str] = None
        if name == "SimpleQueue":
            hazard = ("SimpleQueue has no maxsize at all — use "
                      "queue.Queue(maxsize=N)")
        elif name in _QUEUE_CTORS:
            maxsize = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "maxsize"),
                None)
            if maxsize is None or _is_zero(maxsize):
                hazard = (f"{name}() without a positive maxsize accepts an "
                          f"unbounded backlog")
        elif name == "deque":
            # deque(iterable, maxlen): a second positional IS the bound
            maxlen = node.args[1] if len(node.args) > 1 else next(
                (kw.value for kw in node.keywords if kw.arg == "maxlen"),
                None)
            if maxlen is None:
                hazard = "deque() without maxlen grows with its producer"
        if hazard is None:
            continue
        out.append(Finding(
            "unbounded-queue", Severity.WARNING, ctx.path,
            node.lineno, node.col_offset,
            f"{hazard} — bound the channel and shed at admission, or waive "
            f"with the reason the depth is bounded elsewhere",
        ))
    return out


# ------------------------------------------ unclassified-network-error --

# The live tier's error taxonomy (simulator/live.py, live/sync.py): every
# network failure routes to exactly one of AuthError (fatal, never
# retried), TransientError (reconnect under the seeded RetryPolicy), or
# ProtocolError (bounded teardown; code=410 triggers relist
# reconciliation). A bare `except OSError: return None` in live code
# silently converts a dropped connection into wrong control flow — the
# retry/breaker/relist machinery never sees the failure, so the watch
# neither reconnects nor reconciles. Scope is structural: modules living
# in a `live` package directory or with a `live*` basename. Non-network
# uses of OSError in live modules (bookmark-file reads, best-effort
# close()) carry reasoned waivers.
_NETWORK_EXC = {
    "OSError", "IOError", "ConnectionError", "ConnectionResetError",
    "ConnectionRefusedError", "ConnectionAbortedError", "BrokenPipeError",
    "TimeoutError", "socket.error", "socket.timeout", "socket.gaierror",
    "socket.herror", "ssl.SSLError", "ssl.SSLEOFError",
    "urllib.error.URLError", "urllib.error.HTTPError",
    "http.client.HTTPException",
}
_NETWORK_EXC_PREFIXES = ("http.client.", "socket.")
_ERROR_TAXONOMY = {"AuthError", "TransientError", "ProtocolError"}


def _is_live_module(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return "live" in parts[:-1] or parts[-1].startswith("live")


def _caught_network_names(ctx: ModuleContext,
                          handler: ast.ExceptHandler) -> Set[str]:
    typ = handler.type
    if typ is None:
        return set()
    elts = typ.elts if isinstance(typ, ast.Tuple) else [typ]
    hits: Set[str] = set()
    for e in elts:
        name = ctx.resolve(e)
        if name is None:
            continue
        if name in _NETWORK_EXC or name.startswith(_NETWORK_EXC_PREFIXES):
            hits.add(name)
    return hits


def _routes_to_taxonomy(ctx: ModuleContext,
                        handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if not isinstance(node, ast.Raise):
            continue
        if node.exc is None:
            return True  # bare re-raise hands the error upward intact
        exc = node.exc
        if isinstance(exc, ast.Call):
            exc = exc.func
        name = ctx.resolve(exc)
        if name and name.split(".")[-1] in _ERROR_TAXONOMY:
            return True
    return False


@register(
    "unclassified-network-error", Severity.WARNING,
    "A network-error catch (OSError family, socket.*, urllib.error.*, "
    "http.client.*) in a live-cluster module whose handler neither raises "
    "one of the typed taxonomy errors (AuthError / TransientError / "
    "ProtocolError) nor bare-re-raises. Unrouted network failures bypass "
    "the retry/breaker/relist machinery entirely: the watch loop can't "
    "reconnect on what it never sees. Classify the failure, or waive a "
    "genuinely non-network OSError site with `# simonlint: "
    "ignore[unclassified-network-error] -- <why it is not a network "
    "path>`.",
)
def rule_unclassified_network_error(ctx: ModuleContext) -> List[Finding]:
    if not _is_live_module(ctx.path):
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        hits = _caught_network_names(ctx, node)
        if not hits or _routes_to_taxonomy(ctx, node):
            continue
        out.append(Finding(
            "unclassified-network-error", Severity.WARNING, ctx.path,
            node.lineno, node.col_offset,
            f"except {'/'.join(sorted(hits))} in live code swallows a "
            f"network failure the retry/breaker/relist machinery never "
            f"sees — raise AuthError/TransientError/ProtocolError (or "
            f"bare-re-raise), or waive with why this is not a network "
            f"path",
        ))
    return out
