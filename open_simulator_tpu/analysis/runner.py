"""simonlint driver: file walking, suppression filtering, caching, output,
exit policy.

Entry points:
  * ``python -m open_simulator_tpu.cli lint [paths]``  (cli/main.py)
  * ``python -m open_simulator_tpu.analysis [paths]``  (__main__.py)
  * ``tools/run_analysis.py``                          (CI + bench record)

The optional per-file cache (``--cache``, default file .simonlint_cache.json,
git-ignored) keys on each file's content hash plus a digest of the analyzer's
own sources, so the warm pass costs one sha256 per unchanged file instead of
a full AST walk — the mechanism that keeps the pass inside the 10s
BENCH_ANALYSIS.json budget as the tree grows. Cached entries always hold the
FULL rule set's findings; ``--select`` filters on read."""

from __future__ import annotations

import ast
import hashlib
import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from . import flow as _flow  # noqa: F401  (imported for rule registration)
from . import rules as _rules  # noqa: F401  (imported for rule registration)
from . import threads as _threads  # noqa: F401  (imported for rule registration)
from .base import RULE_REGISTRY, Finding, Severity, is_suppressed, suppressions_for
from .context import ModuleContext

DEFAULT_CACHE_PATH = ".simonlint_cache.json"


@dataclass
class FileResult:
    path: str
    findings: List[Finding] = field(default_factory=list)
    error: Optional[str] = None  # syntax/read error, reported as its own finding


_ANALYSIS_DIR = os.path.dirname(os.path.abspath(__file__))
# every source whose behavior the cached findings depend on: the rule/engine
# modules, the contract grammar rules.py imports, and this driver (it owns
# the cache entry schema and the --select filtering of cached results)
_DIGEST_SOURCES = (
    os.path.join(_ANALYSIS_DIR, "base.py"),
    os.path.join(_ANALYSIS_DIR, "context.py"),
    os.path.join(_ANALYSIS_DIR, "flow.py"),
    os.path.join(_ANALYSIS_DIR, "rules.py"),
    os.path.join(_ANALYSIS_DIR, "runner.py"),
    os.path.join(_ANALYSIS_DIR, "threads.py"),
    os.path.join(os.path.dirname(_ANALYSIS_DIR), "ops", "contracts.py"),
)


def ruleset_digest() -> str:
    """Content hash of the analyzer's own sources (_DIGEST_SOURCES), so any
    rule/engine/cache-schema change invalidates every cache entry (a stale
    finding set is worse than a slow pass)."""
    h = hashlib.sha256()
    for path in _DIGEST_SOURCES:
        with open(path, "rb") as fh:
            h.update(fh.read())
    return h.hexdigest()[:16]


class LintCache:
    """Per-file content-hash cache for analyze_paths. JSON on disk:
    {"ruleset": digest, "files": {path: {"sha": ..., "error": ...,
    "findings": [Finding.to_json()]}}}. Lookups are by (path, sha) so moves
    and edits both miss; severities rebuild from labels on load."""

    def __init__(self, path: str = DEFAULT_CACHE_PATH) -> None:
        self.path = path
        self.ruleset = ruleset_digest()
        self.files: Dict[str, dict] = {}
        self.hits = 0
        self.misses = 0
        self._dirty = False
        self._load()

    def _load(self) -> None:
        try:
            with open(self.path, "r", encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return
        if doc.get("ruleset") == self.ruleset and isinstance(
                doc.get("files"), dict):
            self.files = doc["files"]

    def get(self, path: str, sha: str) -> Optional[FileResult]:
        rec = self.files.get(path)
        if not rec or rec.get("sha") != sha:
            self.misses += 1
            return None
        self.hits += 1
        fr = FileResult(path=path, error=rec.get("error"))
        for d in rec.get("findings", []):
            fr.findings.append(Finding(
                rule=d["rule"], severity=Severity[d["severity"].upper()],
                path=path, line=d["line"], col=d["col"],
                message=d["message"], suppressed=d["suppressed"]))
        return fr

    def put(self, path: str, sha: str, fr: FileResult) -> None:
        self.files[path] = {
            "sha": sha,
            "error": fr.error,
            "findings": [f.to_json() for f in fr.findings],
        }
        self._dirty = True

    def save(self) -> None:
        if not self._dirty:
            return
        # prune entries whose file vanished (deletes, renames, branch
        # switches) so the cache doesn't grow monotonically across history
        dead = [p for p in self.files if not os.path.exists(p)]
        for p in dead:
            del self.files[p]
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"ruleset": self.ruleset, "files": self.files}, fh)
        os.replace(tmp, self.path)
        self._dirty = False


@dataclass
class Report:
    files: List[FileResult]
    elapsed_s: float
    selected_rules: List[str]
    cache_hits: int = 0
    cache_misses: int = 0

    @property
    def findings(self) -> List[Finding]:
        return [f for fr in self.files for f in fr.findings]

    def active(self, threshold: Severity = Severity.WARNING) -> List[Finding]:
        return [f for f in self.findings
                if not f.suppressed and f.severity >= threshold]

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {r: 0 for r in sorted(RULE_REGISTRY)}
        for f in self.findings:
            if not f.suppressed:
                out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def suppressed_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            if f.suppressed:
                out[f.rule] = out.get(f.rule, 0) + 1
        return out


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                out.extend(os.path.join(root, n) for n in sorted(names)
                           if n.endswith(".py"))
        else:
            raise FileNotFoundError(p)
    return out


def analyze_file(path: str, select: Optional[Sequence[str]] = None,
                 _source: Optional[bytes] = None) -> FileResult:
    fr = FileResult(path=path)
    try:
        if _source is None:
            with open(path, "rb") as fh:
                _source = fh.read()
        source = _source.decode("utf-8")
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError, ValueError) as e:
        fr.error = str(e)
        fr.findings.append(Finding(
            "parse-error", Severity.ERROR, path,
            getattr(e, "lineno", 1) or 1, 0, f"cannot analyze: {e}"))
        return fr

    ctx = ModuleContext(path, source, tree)
    supp = suppressions_for(ctx.lines)
    for rule_id, rule in sorted(RULE_REGISTRY.items()):
        if select and rule_id not in select:
            continue
        for f in rule.check(ctx):
            f.severity = rule.severity
            f.suppressed = is_suppressed(f, supp)
            fr.findings.append(f)
    fr.findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return fr


def _filter_select(fr: FileResult, select: Optional[Sequence[str]]) -> FileResult:
    if not select:
        return fr
    out = FileResult(path=fr.path, error=fr.error)
    out.findings = [f for f in fr.findings
                    if f.rule in select or f.rule == "parse-error"]
    return out


def analyze_paths(paths: Sequence[str],
                  select: Optional[Sequence[str]] = None,
                  cache: Optional[LintCache] = None) -> Report:
    t0 = time.perf_counter()
    files: List[FileResult] = []
    for p in iter_python_files(paths):
        if cache is None:
            files.append(analyze_file(p, select))
            continue
        try:
            with open(p, "rb") as fh:
                blob = fh.read()
        except OSError:
            files.append(analyze_file(p, select))  # reports the read error
            continue
        sha = hashlib.sha256(blob).hexdigest()
        fr = cache.get(p, sha)
        if fr is None:
            # cache entries always hold the FULL rule set so later --select
            # runs can filter on read instead of re-analyzing
            fr = analyze_file(p, None, _source=blob)
            cache.put(p, sha, fr)
        files.append(_filter_select(fr, select))
    if cache is not None:
        cache.save()
    return Report(
        files=files,
        elapsed_s=time.perf_counter() - t0,
        selected_rules=sorted(select) if select else sorted(RULE_REGISTRY),
        cache_hits=cache.hits if cache else 0,
        cache_misses=cache.misses if cache else 0,
    )


def format_human(report: Report, show_suppressed: bool = False) -> str:
    lines: List[str] = []
    for f in report.findings:
        if f.suppressed and not show_suppressed:
            continue
        tag = "  (suppressed)" if f.suppressed else ""
        lines.append(f.human() + tag)
    counts = report.counts()
    total = sum(counts.values())
    supp_total = sum(report.suppressed_counts().values())
    per_rule = ", ".join(f"{k}={v}" for k, v in counts.items() if v)
    cache = ""
    if report.cache_hits or report.cache_misses:
        cache = (f", cache {report.cache_hits} hit(s) / "
                 f"{report.cache_misses} miss(es)")
    lines.append(
        f"simonlint: {total} finding(s) ({per_rule or 'none'}), "
        f"{supp_total} suppressed, {len(report.files)} file(s) "
        f"in {report.elapsed_s:.2f}s{cache}")
    return "\n".join(lines)


def format_json(report: Report) -> str:
    return json.dumps({
        "findings": [f.to_json() for f in report.findings],
        "counts": report.counts(),
        "suppressed": report.suppressed_counts(),
        "files": len(report.files),
        "elapsed_s": round(report.elapsed_s, 4),
        "rules": report.selected_rules,
    }, indent=2)


def run_lint(argv: Optional[Sequence[str]] = None) -> int:
    """The `simon lint` command. Exit 0 = clean (modulo suppressions)."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="simon lint",
        description="simonlint: JAX/TPU-hazard static analysis "
                    "(rules: %s)" % ", ".join(sorted(RULE_REGISTRY)),
    )
    parser.add_argument("paths", nargs="*", default=["open_simulator_tpu"],
                        help="files or directories (default: open_simulator_tpu)")
    parser.add_argument("--format", choices=("human", "json"), default="human")
    parser.add_argument("--select", default="",
                        help="comma-separated rule ids to run (default: all)")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings")
    parser.add_argument("--fail-on", choices=("note", "warning", "error", "never"),
                        default="warning",
                        help="lowest severity that fails the build")
    parser.add_argument("--bench-out", default="", metavar="FILE",
                        help="also write a BENCH_ANALYSIS.json-style record")
    parser.add_argument("--cache", default=None, metavar="FILE",
                        help="per-file content-hash cache file (conventional "
                             f"name: {DEFAULT_CACHE_PATH}, git-ignored); "
                             "unchanged files reuse their stored findings")
    args = parser.parse_args(list(argv) if argv is not None else None)

    select = [s.strip() for s in args.select.split(",") if s.strip()] or None
    if select:
        unknown = [s for s in select if s not in RULE_REGISTRY]
        if unknown:
            parser.error(f"unknown rule id(s): {', '.join(unknown)}")
    cache = LintCache(args.cache) if args.cache else None
    report = analyze_paths(args.paths or ["open_simulator_tpu"], select, cache)

    print(format_json(report) if args.format == "json"
          else format_human(report, args.show_suppressed))

    if args.bench_out:
        write_bench(report, args.bench_out)
    if args.fail_on == "never":
        return 0
    threshold = {"note": Severity.NOTE, "warning": Severity.WARNING,
                 "error": Severity.ERROR}[args.fail_on]
    return 1 if report.active(threshold) else 0


def write_bench(report: Report, path: str,
                warm: Optional[Report] = None,
                extra: Optional[dict] = None) -> None:
    """Record analyzer wall time + finding counts so future PRs can assert the
    pass stays fast (budget: <10s on the full tree) and watch finding drift.
    With `warm` (a second cache-backed pass over the same tree), the record
    carries cold/warm timings and the warm hit rate. `extra` merges
    additional sub-records (the flow-pass timings) into the document."""
    rec = {
        "tool": "simonlint",
        "files": len(report.files),
        "elapsed_s": round(report.elapsed_s, 4),
        "budget_s": 10.0,
        "within_budget": report.elapsed_s < 10.0,
        "counts_unsuppressed": report.counts(),
        "counts_suppressed": report.suppressed_counts(),
    }
    if warm is not None:
        rec["elapsed_cold_s"] = round(report.elapsed_s, 4)
        rec["elapsed_warm_s"] = round(warm.elapsed_s, 4)
        rec["warm_cache_hits"] = warm.cache_hits
        rec["warm_cache_misses"] = warm.cache_misses
    if extra:
        rec.update(extra)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(rec, fh, indent=2)
        fh.write("\n")
