"""simonlint driver: file walking, suppression filtering, output, exit policy.

Entry points:
  * ``python -m open_simulator_tpu.cli lint [paths]``  (cli/main.py)
  * ``python -m open_simulator_tpu.analysis [paths]``  (__main__.py)
  * ``tools/run_analysis.py``                          (CI + bench record)
"""

from __future__ import annotations

import ast
import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from . import rules as _rules  # noqa: F401  (imported for rule registration)
from .base import RULE_REGISTRY, Finding, Severity, is_suppressed, suppressions_for
from .context import ModuleContext


@dataclass
class FileResult:
    path: str
    findings: List[Finding] = field(default_factory=list)
    error: Optional[str] = None  # syntax/read error, reported as its own finding


@dataclass
class Report:
    files: List[FileResult]
    elapsed_s: float
    selected_rules: List[str]

    @property
    def findings(self) -> List[Finding]:
        return [f for fr in self.files for f in fr.findings]

    def active(self, threshold: Severity = Severity.WARNING) -> List[Finding]:
        return [f for f in self.findings
                if not f.suppressed and f.severity >= threshold]

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {r: 0 for r in sorted(RULE_REGISTRY)}
        for f in self.findings:
            if not f.suppressed:
                out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def suppressed_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            if f.suppressed:
                out[f.rule] = out.get(f.rule, 0) + 1
        return out


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                out.extend(os.path.join(root, n) for n in sorted(names)
                           if n.endswith(".py"))
        else:
            raise FileNotFoundError(p)
    return out


def analyze_file(path: str, select: Optional[Sequence[str]] = None) -> FileResult:
    fr = FileResult(path=path)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError, ValueError) as e:
        fr.error = str(e)
        fr.findings.append(Finding(
            "parse-error", Severity.ERROR, path,
            getattr(e, "lineno", 1) or 1, 0, f"cannot analyze: {e}"))
        return fr

    ctx = ModuleContext(path, source, tree)
    supp = suppressions_for(ctx.lines)
    for rule_id, rule in sorted(RULE_REGISTRY.items()):
        if select and rule_id not in select:
            continue
        for f in rule.check(ctx):
            f.severity = rule.severity
            f.suppressed = is_suppressed(f, supp)
            fr.findings.append(f)
    fr.findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return fr


def analyze_paths(paths: Sequence[str],
                  select: Optional[Sequence[str]] = None) -> Report:
    t0 = time.perf_counter()
    files = [analyze_file(p, select) for p in iter_python_files(paths)]
    return Report(
        files=files,
        elapsed_s=time.perf_counter() - t0,
        selected_rules=sorted(select) if select else sorted(RULE_REGISTRY),
    )


def format_human(report: Report, show_suppressed: bool = False) -> str:
    lines: List[str] = []
    for f in report.findings:
        if f.suppressed and not show_suppressed:
            continue
        tag = "  (suppressed)" if f.suppressed else ""
        lines.append(f.human() + tag)
    counts = report.counts()
    total = sum(counts.values())
    supp_total = sum(report.suppressed_counts().values())
    per_rule = ", ".join(f"{k}={v}" for k, v in counts.items() if v)
    lines.append(
        f"simonlint: {total} finding(s) ({per_rule or 'none'}), "
        f"{supp_total} suppressed, {len(report.files)} file(s) "
        f"in {report.elapsed_s:.2f}s")
    return "\n".join(lines)


def format_json(report: Report) -> str:
    return json.dumps({
        "findings": [f.to_json() for f in report.findings],
        "counts": report.counts(),
        "suppressed": report.suppressed_counts(),
        "files": len(report.files),
        "elapsed_s": round(report.elapsed_s, 4),
        "rules": report.selected_rules,
    }, indent=2)


def run_lint(argv: Optional[Sequence[str]] = None) -> int:
    """The `simon lint` command. Exit 0 = clean (modulo suppressions)."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="simon lint",
        description="simonlint: JAX/TPU-hazard static analysis "
                    "(rules: %s)" % ", ".join(sorted(RULE_REGISTRY)),
    )
    parser.add_argument("paths", nargs="*", default=["open_simulator_tpu"],
                        help="files or directories (default: open_simulator_tpu)")
    parser.add_argument("--format", choices=("human", "json"), default="human")
    parser.add_argument("--select", default="",
                        help="comma-separated rule ids to run (default: all)")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings")
    parser.add_argument("--fail-on", choices=("note", "warning", "error", "never"),
                        default="warning",
                        help="lowest severity that fails the build")
    parser.add_argument("--bench-out", default="", metavar="FILE",
                        help="also write a BENCH_ANALYSIS.json-style record")
    args = parser.parse_args(list(argv) if argv is not None else None)

    select = [s.strip() for s in args.select.split(",") if s.strip()] or None
    if select:
        unknown = [s for s in select if s not in RULE_REGISTRY]
        if unknown:
            parser.error(f"unknown rule id(s): {', '.join(unknown)}")
    report = analyze_paths(args.paths or ["open_simulator_tpu"], select)

    print(format_json(report) if args.format == "json"
          else format_human(report, args.show_suppressed))

    if args.bench_out:
        write_bench(report, args.bench_out)
    if args.fail_on == "never":
        return 0
    threshold = {"note": Severity.NOTE, "warning": Severity.WARNING,
                 "error": Severity.ERROR}[args.fail_on]
    return 1 if report.active(threshold) else 0


def write_bench(report: Report, path: str) -> None:
    """Record analyzer wall time + finding counts so future PRs can assert the
    pass stays fast (budget: <10s on the full tree) and watch finding drift."""
    rec = {
        "tool": "simonlint",
        "files": len(report.files),
        "elapsed_s": round(report.elapsed_s, 4),
        "budget_s": 10.0,
        "within_budget": report.elapsed_s < 10.0,
        "counts_unsuppressed": report.counts(),
        "counts_suppressed": report.suppressed_counts(),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(rec, fh, indent=2)
        fh.write("\n")
