"""`python -m open_simulator_tpu.analysis [paths]` → simonlint."""

import sys

from .runner import run_lint

sys.exit(run_lint())
