"""simonlint core: findings, severities, the rule registry, and suppressions.

The analyzer is a plain-AST pass (no imports of the analyzed code, no JAX
dependency) so it can run in CI on a box with no accelerator and finish in
well under the ~10s budget tracked by BENCH_ANALYSIS.json.

Suppression syntax, modeled on `# type: ignore` / `# noqa`:

    x = np.asarray(y)  # simonlint: ignore[host-sync-in-jit] -- reason

A comment-only line suppresses the next code line instead, so multi-clause
statements can carry the waiver above them:

    # simonlint: ignore[dtype-drift] -- host-side staging buffer
    req = requests.astype(np.float64).copy()

Rule ids are kebab-case; `ignore[a,b]` lists several; the `-- reason` text is
ENFORCED: a waiver without it is a WARNING-severity `suppression-reason`
finding (rules.py), which a bare `ignore[*]` deliberately cannot cover.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Callable, Dict, List, Sequence


class Severity(IntEnum):
    """Ordering matters: findings at or above the runner's threshold fail."""

    NOTE = 0
    WARNING = 1
    ERROR = 2

    def label(self) -> str:
        return self.name.lower()


@dataclass
class Finding:
    """One diagnostic, anchored to a source position."""

    rule: str
    severity: Severity
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False

    def human(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.severity.label()}[{self.rule}] {self.message}")

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity.label(),
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "suppressed": self.suppressed,
        }


@dataclass
class Rule:
    """A registered rule: id, default severity, and a per-module check.

    `check(module_context) -> List[Finding]`; the runner owns file IO,
    suppression filtering, and exit-code policy so rules stay pure.
    """

    id: str
    severity: Severity
    doc: str
    check: Callable[["object"], List[Finding]] = field(repr=False, default=None)  # type: ignore[assignment]


RULE_REGISTRY: Dict[str, Rule] = {}


def register(rule_id: str, severity: Severity, doc: str):
    """Decorator: register `fn(ctx) -> List[Finding]` as a rule."""

    def deco(fn: Callable) -> Callable:
        if rule_id in RULE_REGISTRY:
            raise ValueError(f"duplicate simonlint rule id: {rule_id}")
        RULE_REGISTRY[rule_id] = Rule(id=rule_id, severity=severity, doc=doc, check=fn)
        return fn

    return deco


_SUPPRESS_RE = re.compile(r"#\s*simonlint:\s*ignore\[([A-Za-z0-9_\-,\s*]+)\]")
_REASON_RE = re.compile(r"\s*--\s*\S")


def suppressions_for(source_lines: Sequence[str]) -> Dict[int, frozenset]:
    """Map 1-based line number -> suppressed rule-id set.

    A trailing comment suppresses its own line; a comment-only line
    suppresses the next line (chains of comment-only lines all bind to the
    first code line below them). `*` suppresses every rule. A waiver WITHOUT
    its `-- reason` text cannot suppress `suppression-reason` — the hygiene
    finding that flags it — even by naming it explicitly.
    """
    out: Dict[int, set] = {}
    pending: set = set()
    for i, raw in enumerate(source_lines, start=1):
        m = _SUPPRESS_RE.search(raw)
        stripped = raw.strip()
        here = {r.strip() for r in m.group(1).split(",") if r.strip()} if m else set()
        if m and not _REASON_RE.match(raw[m.end():]):
            here.discard("suppression-reason")
        if stripped.startswith("#") or (not stripped and pending):
            # comment-only waivers (and any blank lines after them) carry
            # forward to the next code line
            pending |= here
            continue
        if here or pending:
            out.setdefault(i, set()).update(here | pending)
            pending = set()
    return {k: frozenset(v) for k, v in out.items()}


def is_suppressed(finding: Finding, supp: Dict[int, frozenset]) -> bool:
    rules = supp.get(finding.line)
    if not rules:
        return False
    if finding.rule == "suppression-reason":
        # the waiver-hygiene rule is only waivable by an explicit REASONED
        # ignore[suppression-reason] (suppressions_for drops it from bare
        # waivers); a bare `ignore[*]` would otherwise self-suppress the
        # very finding that flags it
        return finding.rule in rules
    return finding.rule in rules or "*" in rules
