"""simonaudit: compile-time dispatch certificates for every hot kernel.

simonlint (rules.py) proves source-level invariants; this module proves the
COMPILED ARTIFACT. Every kernel in ops.kernels.HOT_KERNELS is abstractly
traced at canonical shape buckets x mesh shapes (1/2/8 shards), lowered via
jit(...).lower() on CPU (no accelerator needed — `.compile()` runs the full
XLA SPMD partitioner, which is where collectives are born), and reduced to a
**dispatch certificate**:

  * collective census — count and estimated byte volume of every all-reduce /
    all-gather / reduce-scatter / collective-permute / all-to-all in the
    optimized HLO (static occurrences: a collective inside a while body is
    counted once per textual occurrence, i.e. per epoch/round of the loop);
  * escape census — custom_call targets and host callbacks (a host round trip
    hiding inside a "compiled" kernel is the tunnel-latency hazard);
  * donation effectiveness — how many of the declared donate_argnums carry
    buffers XLA actually aliased into outputs (silent donation loss is
    invisible until device memory blows up at scale);
  * carry dtype promotions — output carry leaves whose dtype differs from the
    input contract (a promotion recompiles every chained dispatch);
  * the static-argument digest that keys recompiles — statics + abstract
    input signature + mesh; instability means the warm-path cache is lying.

Certificates are golden-filed under tests/golden/audit/ with a budget block;
`simon audit --check` fails on any new collective kind, count growth past the
budget, dropped donation, new custom_call/host-callback escape, or digest
drift; `--update` regenerates the goldens with a human-reviewable diff.

The executables audited here are built by the SAME code path the engine's
dispatch wrappers use (parallel.mesh.ShardedKernels._kernel_jit, via
`lowerable`), with identical shardings, statics, and donation — equivalent
by construction to the artifact production traffic runs (the audit
instantiates its own ShardedKernels so certification never mutates the
engine's cached executable set).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import re
import sys
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

SCHEMA = 1
S_LANES = 8          # candidate lanes in every probe fan-out audit
K_SEGS = 4           # wave-segment chain depth in the sweep fan-out audit
DEFAULT_SHARDS = (1, 2, 8)
CHAIN_TARGET = "schedule_wave_chain2"
EPOCH_TARGET = "schedule_affinity_epoch"
FIXTURE_TARGET = "fixture-extra-collective"  # CI negative control, opt-in

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

_SHAPE_RE = re.compile(
    r"\b(pred|bf16|f16|f32|f64|s8|s16|s32|s64|u8|u16|u32|u64|c64|c128)"
    r"\[([0-9,]*)\]")
# one def line per op: `%name = <result-type> all-reduce(...)`; operand
# references (`%all-reduce.5, ...`) never put a `(` right after the op name,
# and `-done` halves of async pairs fail the `(?:-start)?\(` tail.
_COLL_RE = re.compile(
    r"=\s+((?:\([^)]*\))|\S+)\s+(" + "|".join(_COLLECTIVES) +
    r")(?:-start)?\(")
_ALIAS_ENTRY_RE = re.compile(r"\{[0-9,\s]*\}:\s*\(\d+")
_CUSTOM_CALL_RE = re.compile(r'custom_call_target="([^"]+)"')


class Bucket(NamedTuple):
    """One canonical encode: a synthetic cluster/workload mix that populates
    the table families a kernel family reads (zones -> spread/DNS rows,
    anti -> carrier/anti rows), sized for fast CPU lowering."""

    nodes: int
    pods: int
    zones: int
    anti: bool = False


BUCKETS: Dict[str, Bucket] = {
    # small: the default CI gate — spread pods populate DNS/topo tables
    "s16x32": Bucket(nodes=16, pods=32, zones=2),
    # medium: adds required anti-affinity (carrier rows live) + more zones
    "m48x96": Bucket(nodes=48, pods=96, zones=4, anti=True),
}
DEFAULT_BUCKETS = ("s16x32", "m48x96")


# --------------------------------------------------------------- encoding ----

_ENCODE_CACHE: Dict[str, object] = {}


def _encode_bucket(bucket_key: str):
    """BatchTables for a canonical bucket (cached per process). Uses the real
    encoder so certificate shapes can never drift from production encodes."""
    bt = _ENCODE_CACHE.get(bucket_key)
    if bt is not None:
        return bt
    from ..simulator.engine import Simulator
    from ..utils.synth import synth_node, synth_pod

    b = BUCKETS[bucket_key]
    nodes = [synth_node(i, n_zones=b.zones) for i in range(b.nodes)]
    pods = []
    for i in range(b.pods):
        anti = b.anti and i % 5 == 4
        pods.append(synth_pod(
            i,
            labels={"app": "anti" if anti else "synth"},
            anti_affinity_on="anti" if anti else None,
            spread_zone=(i % 3 == 0) and not anti,
        ))
    sim = Simulator(nodes, use_mesh=False)
    bt = sim.encode_batch(pods)
    _ENCODE_CACHE[bucket_key] = bt
    return bt


def _abs_of(x):
    import numpy as np

    import jax

    a = np.asarray(x)
    return jax.ShapeDtypeStruct(a.shape, a.dtype)


def _sds(shape, dtype):
    import numpy as np

    import jax

    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))


def _abstract_head(btp, fanout: bool):
    """(tables, carry[, active_s]) as ShapeDtypeStructs from a padded
    BatchTables; fan-out carries grow the leading [S] candidate axis."""
    from ..ops import kernels
    from ..parallel.mesh import tables_from_batch

    tables = kernels.Tables(*(_abs_of(v) for v in tables_from_batch(btp)))
    seeds = dict(
        requested=btp.seed_requested, nonzero=btp.seed_nonzero,
        port_used=btp.seed_port_used, counter=btp.seed_counter,
        carrier=btp.seed_carrier, dev_used=btp.seed_dev_used,
        vg_req=btp.seed_vg_req, sdev_alloc=btp.seed_sdev_alloc)
    if fanout:
        import numpy as np

        carry = kernels.Carry(**{
            k: _sds((S_LANES,) + np.asarray(v).shape, np.asarray(v).dtype)
            for k, v in seeds.items()})
        active = _sds((S_LANES, btp.seed_requested.shape[0]), bool)
        return (tables, carry, active)
    return (tables, kernels.Carry(**{k: _abs_of(v) for k, v in seeds.items()}))


def _dyn_abs(token: str, P: int):
    import numpy as np

    kinds = {
        "g": ((), np.int32), "m": ((), np.int32), "forced": ((), np.int32),
        "cap1": ((), np.bool_), "valid1": ((), np.bool_),
        "valid_p": ((P,), np.bool_),
        "valid_sp": ((S_LANES, P), np.bool_),  # serve fan-out per-lane masks
        "g_s": ((S_LANES,), np.int32), "m_s": ((S_LANES,), np.int32),
        "cap1_s": ((S_LANES,), np.bool_),      # serve wave per-lane (g, m)
        "pod_group": ((P,), np.int32), "forced_node": ((P,), np.int32),
        # sweep fan-out: per-lane wave-segment chains and per-lane pod rows
        "g_sk": ((S_LANES, K_SEGS), np.int32),
        "m_sk": ((S_LANES, K_SEGS), np.int32),
        "cap1_sk": ((S_LANES, K_SEGS), np.bool_),
        "pod_group_s": ((S_LANES, P), np.int32),
        "forced_node_s": ((S_LANES, P), np.int32),
    }
    shape, dtype = kinds[token]
    return _sds(shape, dtype)


def _mesh_for(fanout: bool, shards: int):
    import numpy as np

    import jax

    from ..parallel.mesh import (
        NODE_AXIS, SCENARIO_AXIS, make_node_mesh, make_scenario_mesh)

    if not fanout:
        return make_node_mesh(shards), f"nodes{shards}"
    if shards == 1:
        # make_scenario_mesh(1) collapses to a 1-D node mesh; the fan-out
        # head needs the scenario axis present even at one shard
        from jax.sharding import Mesh

        devs = np.asarray(jax.devices()[:1]).reshape(1, 1)
        return Mesh(devs, (SCENARIO_AXIS, NODE_AXIS)), "scenarios1"
    return make_scenario_mesh(shards), f"scenarios{shards}"


# ------------------------------------------------------------- extraction ----


def _shape_bytes(result_tok: str) -> int:
    total = 0
    for dt, dims in _SHAPE_RE.findall(result_tok):
        n = 1
        for d in dims.split(","):
            if d.strip():
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def collective_census(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """{op: {count, bytes}} over the optimized HLO module text. Bytes are the
    summed result-shape sizes (async -start tuples include the aliased input
    halves — an over-estimate, flagged by the schema as 'estimated')."""
    out: Dict[str, Dict[str, int]] = {}
    for m in _COLL_RE.finditer(hlo_text):
        kind = m.group(2)
        rec = out.setdefault(kind, {"count": 0, "bytes": 0})
        rec["count"] += 1
        rec["bytes"] += _shape_bytes(m.group(1))
    return out


def _split_computations(hlo_text: str) -> Dict[str, str]:
    """{computation name: body text} over an optimized HLO module. Headers
    are non-indented `%name (args) -> result {` lines (ENTRY included);
    bodies run to the column-0 closing brace."""
    comps: Dict[str, List[str]] = {}
    cur: Optional[List[str]] = None
    for line in hlo_text.splitlines():
        if not line.startswith((" ", "\t")) and line.rstrip().endswith("{"):
            m = re.match(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*\(", line)
            if m:
                cur = comps.setdefault(m.group(1), [])
                continue
        if line.startswith("}"):
            cur = None
        elif cur is not None:
            cur.append(line)
    return {k: "\n".join(v) for k, v in comps.items()}


_CALLEE_RE = re.compile(
    r"(?:to_apply|body|condition|calls)=%?([\w.\-]+)"
    r"|branch_computations=\{([^}]*)\}")


def while_body_census(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """{while-body computation: transitive collective census} for every
    `while` op in the module — the PER-ITERATION collective cost of each
    loop (nested to_apply/calls/branch computations included). This is what
    the epoch-amortization contract pins: collective_census() counts a
    collective inside a loop body once per textual occurrence, but only the
    body attribution says whether the loop pays it every round."""
    comps = _split_computations(hlo_text)
    callees: Dict[str, set] = {}
    for name, body in comps.items():
        refs = set()
        for m in _CALLEE_RE.finditer(body):
            if m.group(1):
                refs.add(m.group(1))
            elif m.group(2):
                refs.update(r.strip().lstrip("%")
                            for r in m.group(2).split(",") if r.strip())
        callees[name] = refs

    def census_of(name: str, seen: set) -> Dict[str, int]:
        if name in seen:
            return {}
        seen.add(name)
        out: Dict[str, int] = {}
        for m in _COLL_RE.finditer(comps.get(name, "")):
            out[m.group(2)] = out.get(m.group(2), 0) + 1
        for ref in callees.get(name, ()):
            for k, v in census_of(ref, seen).items():
                out[k] = out.get(k, 0) + v
        return out

    out: Dict[str, Dict[str, int]] = {}
    for body in comps.values():
        for line in body.splitlines():
            if " while(" not in line:
                continue
            bm = re.search(r"\bbody=%?([\w.\-]+)", line)
            if bm:
                out[bm.group(1)] = census_of(bm.group(1), set())
    return out


def _alias_block(hlo_text: str) -> str:
    """The module header's input_output_alias block text (nested braces:
    balance by hand, regexes can't), or '' when absent."""
    head = hlo_text.split("\n", 1)[0]
    start = head.find("input_output_alias={")
    if start < 0:
        return ""
    i = head.index("{", start)
    depth = 0
    for j in range(i, len(head)):
        if head[j] == "{":
            depth += 1
        elif head[j] == "}":
            depth -= 1
            if depth == 0:
                return head[i:j + 1]
    return ""


def _alias_count(hlo_text: str) -> int:
    """Aliased buffer count from the module header's input_output_alias block."""
    return len(_ALIAS_ENTRY_RE.findall(_alias_block(hlo_text)))


def image_alias_count(lowered, n_image_params: int) -> int:
    """Donated leaves inside the shared-image table range: the first
    `n_image_params` flattened argument leaves (the `tables` head is always
    argument 0) of the lowered artifact's args_info. jax.stages.Lowered
    records per-leaf donation EXACTLY as declared to XLA (donated_invars),
    and unlike the optimized HLO's input_output_alias header it is immune to
    unused-parameter pruning renumbering the entries.

    The serving subsystem keeps one long-lived device-resident cluster image
    that every dispatch reads; donating any of its leaves would let a
    watchdog-abandoned zombie dispatch keep writing into buffers every other
    request still reads (the PR 9 hazard, now on shared state). The carry is
    the ONLY legal donation target, so a donated table leaf is a
    certification failure — on every kernel, since the engine's tables are
    equally long-lived across segments."""
    import jax

    leaves = jax.tree_util.tree_leaves(
        lowered.args_info, is_leaf=lambda x: hasattr(x, "donated"))
    return sum(1 for a in leaves[:n_image_params] if a.donated)


def escape_census(hlo_text: str) -> Tuple[List[str], List[str]]:
    """(custom_calls, host_callbacks): every custom_call target, split into
    host-callback escapes (python callbacks, infeed/outfeed) vs the rest."""
    targets = sorted(set(_CUSTOM_CALL_RE.findall(hlo_text)))
    host = [t for t in targets
            if "callback" in t.lower() or "infeed" in t.lower()
            or "outfeed" in t.lower()]
    if re.search(r"\b(?:infeed|outfeed)\(", hlo_text):
        host.append("infeed/outfeed-op")
    return [t for t in targets if t not in host], sorted(set(host))


def _digest(name: str, statics, abs_args, mesh_label: str,
            donate: Sequence[int]) -> str:
    """The stable identity of one compiled dispatch: everything jax keys the
    executable cache on that the engine controls. A drift here without a
    reviewed golden update means the warm path silently recompiles."""
    import jax

    leaves = jax.tree_util.tree_leaves(abs_args)
    payload = {
        "kernel": name,
        "statics": repr(statics),
        "in": [f"{tuple(a.shape)}:{a.dtype}" for a in leaves],
        "mesh": mesh_label,
        "donate": sorted(donate),
    }
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()).hexdigest()[:16]


def dispatch_digest(kernel: str, dims) -> str:
    """The runtime sibling of `_digest`: the stable identity of one live
    dispatch from its `obs.record_dispatch` signature (kernel + the static
    shape/config dims the compile cache keys on). simonpulse keys its
    performance ledger on this — two records sharing a digest ran the same
    executable, so a wall-time delta between them is environmental; a digest
    change means the executable itself changed. Same construction as
    `_digest` (sha256 over a sorted-json payload, 16 hex chars) so ledger
    keys and audit certificates read as one digest family. No jax: dims are
    host scalars by the record_dispatch contract."""
    payload = {
        "kernel": kernel,
        "dims": {str(k): repr(v) for k, v in dims.items()},
    }
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()).hexdigest()[:16]


def cost_census(compiled) -> dict:
    """FLOPs / bytes-accessed of one compiled executable, normalized across
    jax versions (dict vs one-element list; 'bytes accessed' vs per-operand
    keys). The roofline source: simonaudit embeds this as the certificate's
    `cost` field, simonpulse turns it into model-optimal seconds. Returns
    zeros when the backend offers no cost model — the field stays present so
    goldens keep a stable shape (check_cert never inspects it; drift here is
    informational, printed by --update only)."""
    try:
        raw = compiled.cost_analysis()
    # simonlint: ignore[swallowed-exception] -- diagnostics-only harvest: a
    # backend without a cost model must not fail certification of the
    # artifact's real contracts (collectives/donation/escapes)
    except Exception:
        raw = None
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else None
    if not isinstance(raw, dict):
        return {"flops": 0.0, "bytes_accessed": 0.0}
    flops = float(raw.get("flops", 0.0) or 0.0)
    by = raw.get("bytes accessed", raw.get("bytes_accessed"))
    if by is None:
        by = sum(float(v) for k, v in raw.items()
                 if isinstance(k, str) and k.startswith("bytes accessed"))
    return {"flops": flops, "bytes_accessed": float(by or 0.0)}


def _carry_promotions(name: str, spec, statics, head_abs, dyn_abs):
    """Output-carry leaves whose dtype left the input contract."""
    import jax

    from ..ops import kernels
    from ..parallel.mesh import _unwrap

    if spec.out is None:
        return []
    raw = _unwrap(getattr(kernels, name))
    out = jax.eval_shape(lambda *dyn: raw(*dyn, *statics), *head_abs, *dyn_abs)
    out_carry = out[0]
    in_carry = head_abs[1]
    return [
        {"leaf": f, "in": str(i.dtype), "out": str(o.dtype)}
        for f, i, o in zip(kernels.Carry._fields, in_carry, out_carry)
        if i.dtype != o.dtype
    ]


# ------------------------------------------------------------ certificates ----


def _budget_for(cert: dict) -> dict:
    """The machine-checked contract regenerated at --update time: 'no worse
    than this artifact'. Hand-tighten in the golden file to pin a stronger
    invariant (e.g. the ROADMAP affinity-epoch collective budget)."""
    budget = {
        "max_collective_count": sum(
            c["count"] for c in cert["collectives"].values()),
        "forbid_new_custom_calls": True,
    }
    if cert["donation"]["declared"]:
        budget["require_donation"] = cert["donation"]["held"]
    if "boundary_collectives" in cert:
        budget["max_boundary_collectives"] = 0
    if "epoch_contract_held" in cert:
        budget["require_epoch_contract"] = True
    return budget


def audit_kernel(name: str, bucket_key: str, shards: int) -> dict:
    """Lower + compile one registered hot kernel at (bucket, mesh) and
    extract its dispatch certificate."""
    from ..ops import kernels
    from ..parallel.mesh import ShardedKernels, pad_batch_tables

    spec = kernels.HOT_KERNELS[name]
    bt = _encode_bucket(bucket_key)
    mesh, mesh_label = _mesh_for(spec.fanout, shards)
    # fan-out tables live on the scenario mesh's node axis (size 1 at S>1)
    node_shards = mesh.shape["nodes"]
    btp = pad_batch_tables(bt, max(node_shards, 1))
    P = int(btp.pod_group.shape[0])

    # certify the DONATED artifact — the accelerator production executable.
    # Built directly (not via the sharded_kernels factory, which downgrades
    # donation on multi-device CPU meshes for RUNTIME safety): lowering
    # never executes anything, and the donation-effectiveness field exists
    # precisely to certify the aliasing of the donated program.
    sk = ShardedKernels(mesh)
    jfn, spec, meta = sk.lowerable(name, n_zones=int(btp.n_zones))
    head_abs = _abstract_head(btp, spec.fanout)
    dyn_abs = tuple(_dyn_abs(tok, P) for tok in spec.dyn)
    statics = meta["statics"]
    args = head_abs + dyn_abs + statics

    lowered = jfn.lower(*args)
    compiled = lowered.compile()
    text = compiled.as_text()
    colls = collective_census(text)
    custom, host = escape_census(text)
    declared = len(kernels.Carry._fields) if meta["donate_argnums"] else 0
    aliased = _alias_count(text)
    cert = {
        "schema": SCHEMA,
        "kernel": name,
        "bucket": bucket_key,
        "mesh": mesh_label,
        "static_digest": _digest(name, statics, head_abs + dyn_abs,
                                 mesh_label, meta["donate_argnums"]),
        "collectives": {k: colls[k] for k in sorted(colls)},
        "collective_count": sum(c["count"] for c in colls.values()),
        "collective_bytes": sum(c["bytes"] for c in colls.values()),
        "custom_calls": custom,
        "host_callbacks": host,
        "donation": {
            "declared": declared,
            "aliased": aliased,
            "held": aliased >= declared,
            # the cluster-image/table head must NEVER be donated into an
            # output: structural non-donatability of shared state (serve/)
            "image_leaf_aliased": image_alias_count(
                lowered, len(kernels.Tables._fields)),
        },
        "carry_promotions": _carry_promotions(
            name, spec, statics, head_abs, dyn_abs),
        # roofline source (simonpulse): model-optimal seconds derive from
        # these at the configured peak rates; never checked by check_cert
        "cost": cost_census(compiled),
    }
    cert["budget"] = _budget_for(cert)
    return cert


def audit_wave_chain(bucket_key: str, shards: int) -> dict:
    """The PR 8 invariant as a certificate: two chained schedule_wave
    dispatches under the SAME in/out shardings may contain at most 2x one
    dispatch's collectives — the dispatch boundary itself inserts ZERO
    resharding collectives (the static proof behind reshard_bytes == 0) —
    and the chain still aliases its donated carry."""
    import jax

    from ..ops import kernels
    from ..parallel.mesh import (
        _unwrap, carry_shardings, make_node_mesh, pad_batch_tables,
        table_shardings)

    bt = _encode_bucket(bucket_key)
    mesh = make_node_mesh(shards)
    mesh_label = f"nodes{shards}"
    btp = pad_batch_tables(bt, shards)
    head_abs = _abstract_head(btp, False)
    dyn_abs = tuple(_dyn_abs(tok, 0) for tok in ("g", "m", "cap1"))
    statics = kernels.HOT_KERNELS["schedule_wave"].statics(int(btp.n_zones))
    # trailing mesh static: the kernel-internal shard_map epoch loop (the
    # same value ShardedKernels._wave_mesh passes on a node-sharding mesh)
    statics = statics + (mesh if shards > 1 else None,)
    raw = _unwrap(kernels.schedule_wave)

    def single(tb, cry, g, m, cap1):
        return raw(tb, cry, g, m, cap1, *statics)

    def chain(tb, cry, g, m, cap1):
        c1, j1, p1 = raw(tb, cry, g, m, cap1, *statics)
        c2, j2, p2 = raw(tb, c1, g, m, cap1, *statics)
        return c2, j1 + j2, p1 + p2

    from jax.sharding import NamedSharding, PartitionSpec as P

    ts, cs = table_shardings(mesh), carry_shardings(mesh)
    rep = NamedSharding(mesh, P())
    node_sh = NamedSharding(mesh, P("nodes"))
    kw = dict(in_shardings=(ts, cs, rep, rep, rep),
              out_shardings=(cs, node_sh, rep), donate_argnums=(1,))
    args = head_abs + dyn_abs
    t1 = jax.jit(single, **kw).lower(*args).compile().as_text()
    low2 = jax.jit(chain, **kw).lower(*args)
    t2 = low2.compile().as_text()
    c1 = collective_census(t1)
    c2 = collective_census(t2)
    n1 = sum(c["count"] for c in c1.values())
    n2 = sum(c["count"] for c in c2.values())
    custom, host = escape_census(t2)
    declared = len(kernels.Carry._fields)
    aliased = _alias_count(t2)
    cert = {
        "schema": SCHEMA,
        "kernel": CHAIN_TARGET,
        "bucket": bucket_key,
        "mesh": mesh_label,
        "static_digest": _digest(CHAIN_TARGET, statics, args, mesh_label,
                                 (1,)),
        "collectives": {k: c2[k] for k in sorted(c2)},
        "collective_count": n2,
        "collective_bytes": sum(c["bytes"] for c in c2.values()),
        "single_collective_count": n1,
        "boundary_collectives": max(0, n2 - 2 * n1),
        "custom_calls": custom,
        "host_callbacks": host,
        "donation": {"declared": declared, "aliased": aliased,
                     "held": aliased >= declared,
                     "image_leaf_aliased": image_alias_count(
                         low2, len(kernels.Tables._fields))},
        "carry_promotions": [],
    }
    cert["budget"] = _budget_for(cert)
    return cert


def audit_affinity_epoch(bucket_key: str, shards: int) -> dict:
    """The epoch-amortization contract as a certificate: on a node-sharding
    mesh, each wave kernel's epoch while-loop pays exactly ONE all-reduce
    (every normalizer reduction batched into one stacked max-space operand)
    plus ONE all-gather (the score-table payload — the cross-shard argmax at
    the epoch boundary) per epoch, and NO other loop in either module
    contains a collective. At one shard the loops contain no collectives at
    all. collective_census() alone cannot pin this — a prologue collective
    and a per-round collective count the same there; while_body_census()
    attributes them to the loop that pays them every iteration."""
    from ..ops import kernels
    from ..parallel.mesh import ShardedKernels, pad_batch_tables

    bt = _encode_bucket(bucket_key)
    epoch: Dict[str, dict] = {}
    total: Dict[str, Dict[str, int]] = {}
    custom_u: set = set()
    host_u: set = set()
    held = True
    digest_args: list = []
    mesh_label = f"nodes{shards}"
    for name in ("schedule_wave", "schedule_affinity_wave"):
        spec = kernels.HOT_KERNELS[name]
        mesh, mesh_label = _mesh_for(spec.fanout, shards)
        btp = pad_batch_tables(bt, max(mesh.shape["nodes"], 1))
        P = int(btp.pod_group.shape[0])
        sk = ShardedKernels(mesh)
        jfn, spec, meta = sk.lowerable(name, n_zones=int(btp.n_zones))
        head_abs = _abstract_head(btp, spec.fanout)
        dyn_abs = tuple(_dyn_abs(tok, P) for tok in spec.dyn)
        text = jfn.lower(
            *(head_abs + dyn_abs + meta["statics"])).compile().as_text()
        bodies = {k: dict(sorted(v.items()))
                  for k, v in while_body_census(text).items() if v}
        # loop keys, not raw computation names: XLA pass pipelines rename
        # computations freely, and a golden keyed on them would churn on
        # every toolchain bump without any semantic change
        epoch[name] = {f"loop{i}": v for i, (_, v)
                       in enumerate(sorted(bodies.items()))}
        if shards > 1:
            held &= (len(bodies) == 1
                     and next(iter(bodies.values()))
                     == {"all-gather": 1, "all-reduce": 1})
        else:
            held &= not bodies
        for k, rec in collective_census(text).items():
            t = total.setdefault(k, {"count": 0, "bytes": 0})
            t["count"] += rec["count"]
            t["bytes"] += rec["bytes"]
        custom, host = escape_census(text)
        custom_u.update(custom)
        host_u.update(host)
        digest_args.append((meta["statics"], head_abs + dyn_abs))
    cert = {
        "schema": SCHEMA,
        "kernel": EPOCH_TARGET,
        "bucket": bucket_key,
        "mesh": mesh_label,
        "static_digest": _digest(
            EPOCH_TARGET, tuple(repr(s) for s, _ in digest_args),
            tuple(a for _, args in digest_args for a in args), mesh_label,
            ()),
        "collectives": {k: total[k] for k in sorted(total)},
        "collective_count": sum(c["count"] for c in total.values()),
        "collective_bytes": sum(c["bytes"] for c in total.values()),
        "epoch_census": epoch,
        "epoch_contract_held": bool(held),
        "custom_calls": sorted(custom_u),
        "host_callbacks": sorted(host_u),
        "donation": {"declared": 0, "aliased": 0, "held": True},
        "carry_promotions": [],
    }
    cert["budget"] = _budget_for(cert)
    return cert


def audit_fixture(shards: int = 8) -> dict:
    """Deliberately collective-heavy toy kernel — NOT a product kernel. CI
    checks it against a doctored golden (one all-reduce fewer than reality)
    to prove the --check gate actually fails on a new collective."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import NODE_AXIS, make_node_mesh

    mesh = make_node_mesh(shards)
    sh = NamedSharding(mesh, P(NODE_AXIS))
    rep = NamedSharding(mesh, P())

    def fx(x):
        x = x - jnp.mean(x)       # cross-shard all-reduce #1
        return jnp.max(jnp.abs(x))  # cross-shard all-reduce #2 (the "extra")

    jfn = jax.jit(fx, in_shardings=(sh,), out_shardings=rep)
    arg = _sds((16 * shards,), np.float32)
    text = jfn.lower(arg).compile().as_text()
    colls = collective_census(text)
    custom, host = escape_census(text)
    mesh_label = f"nodes{shards}"
    cert = {
        "schema": SCHEMA,
        "kernel": FIXTURE_TARGET,
        "bucket": "fixture",
        "mesh": mesh_label,
        "static_digest": _digest(FIXTURE_TARGET, (), (arg,), mesh_label, ()),
        "collectives": {k: colls[k] for k in sorted(colls)},
        "collective_count": sum(c["count"] for c in colls.values()),
        "collective_bytes": sum(c["bytes"] for c in colls.values()),
        "custom_calls": custom,
        "host_callbacks": host,
        "donation": {"declared": 0, "aliased": 0, "held": True},
        "carry_promotions": [],
    }
    cert["budget"] = _budget_for(cert)
    return cert


# ---------------------------------------------------------------- targets ----


def target_names() -> List[str]:
    from ..ops import kernels

    return list(kernels.HOT_KERNELS) + [CHAIN_TARGET, EPOCH_TARGET]


def run_targets(select: Optional[Sequence[str]], buckets: Sequence[str],
                shards_list: Sequence[int], log=None) -> List[dict]:
    """Certificates for the selected targets over buckets x shards. The
    wave-chain target audits at the largest multi-shard mesh only (its
    budget is the cross-dispatch boundary, meaningless at one shard);
    the CI fixture runs only when explicitly selected."""
    names = list(select) if select else target_names()
    certs: List[dict] = []
    multi = [s for s in shards_list if s > 1]
    for name in names:
        if name == FIXTURE_TARGET:
            certs.append(audit_fixture(max(shards_list)))
            if log:
                log(certs[-1])
            continue
        for bucket in buckets:
            if name == CHAIN_TARGET:
                if multi:
                    certs.append(audit_wave_chain(bucket, max(multi)))
                    if log:
                        log(certs[-1])
                continue
            if name == EPOCH_TARGET:
                for shards in shards_list:
                    certs.append(audit_affinity_epoch(bucket, shards))
                    if log:
                        log(certs[-1])
                continue
            for shards in shards_list:
                certs.append(audit_kernel(name, bucket, shards))
                if log:
                    log(certs[-1])
    return certs


# ------------------------------------------------------------- golden files ----


def _cert_key(cert: dict) -> str:
    return f"{cert['bucket']}/{cert['mesh']}"


def golden_path(golden_dir: str, kernel: str) -> str:
    return os.path.join(golden_dir, f"{kernel}.json")


def load_golden(golden_dir: str, kernel: str) -> Optional[dict]:
    path = golden_path(golden_dir, kernel)
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


def _merge_budget(old: Optional[dict], new: dict) -> dict:
    """--update must never silently LOOSEN a hand-tightened golden budget:
    keep the stricter of each bound (smaller max_*, sticky require_*/
    forbid_*). Loosening a pinned contract takes a hand edit of the golden
    file, in a reviewed diff."""
    if not old:
        return new
    out = dict(new)
    for key in ("max_collective_count", "max_boundary_collectives"):
        if key in old and old[key] < out.get(key, old[key] + 1):
            out[key] = old[key]
    for key in ("require_donation", "forbid_new_custom_calls"):
        if old.get(key):
            out[key] = True
    for key in ("note",):  # hand-written rationale survives regeneration
        if key in old:
            out[key] = old[key]
    return out


def write_goldens(golden_dir: str, certs: Sequence[dict],
                  full: bool = False) -> List[str]:
    """Write certificates into per-kernel golden files. Partial runs
    (--select / subset shards) MERGE into existing docs; `full` (the default
    --update matrix) REGENERATES — stale cert keys and golden files for
    kernels no longer in the live set are pruned, so the goldens never
    advertise coverage that no longer runs. In both modes, hand-tightened
    budget bounds in the existing goldens are preserved (_merge_budget)."""
    os.makedirs(golden_dir, exist_ok=True)
    by_kernel: Dict[str, Dict[str, dict]] = {}
    for c in certs:
        by_kernel.setdefault(c["kernel"], {})[_cert_key(c)] = c
    written = []
    for kernel, cmap in sorted(by_kernel.items()):
        prev = load_golden(golden_dir, kernel)
        doc = (None if full else prev) or {
            "schema": SCHEMA, "kernel": kernel, "certs": {}}
        for key, cert in cmap.items():
            old = (prev or {}).get("certs", {}).get(key)
            cert = dict(cert)
            cert["budget"] = _merge_budget(
                (old or {}).get("budget"), cert["budget"])
            doc["certs"][key] = cert
        doc["certs"] = {k: doc["certs"][k] for k in sorted(doc["certs"])}
        path = golden_path(golden_dir, kernel)
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=1, sort_keys=True)
            fh.write("\n")
        written.append(path)
    if full:
        keep = {f"{k}.json" for k in by_kernel}
        for fn in sorted(os.listdir(golden_dir)):
            if fn.endswith(".json") and fn not in keep:
                os.remove(os.path.join(golden_dir, fn))
                print(f"  pruned stale golden {fn}")
    return written


def check_cert(live: dict, golden: dict) -> List[str]:
    """Regressions of `live` vs its golden: new collective kinds, counts
    past the golden budget, dropped donation, new escapes, digest drift,
    fresh carry promotions, a non-zero chain boundary."""
    out: List[str] = []
    where = f"{live['kernel']} {_cert_key(live)}"
    if live["static_digest"] != golden["static_digest"]:
        out.append(
            f"{where}: dispatch signature drift "
            f"{golden['static_digest']} -> {live['static_digest']} "
            f"(statics/shapes changed: review + `simon audit --update`)")
    budget = golden.get("budget", {})
    gcolls = golden.get("collectives", {})
    for kind, rec in live["collectives"].items():
        if kind not in gcolls:
            out.append(f"{where}: NEW collective kind {kind} "
                       f"(x{rec['count']}, ~{rec['bytes']}B)")
        elif rec["count"] > gcolls[kind]["count"]:
            out.append(f"{where}: {kind} count grew "
                       f"{gcolls[kind]['count']} -> {rec['count']}")
    maxc = budget.get("max_collective_count")
    if maxc is not None and live["collective_count"] > maxc:
        out.append(f"{where}: collective total {live['collective_count']} "
                   f"exceeds budget {maxc}")
    if budget.get("forbid_new_custom_calls", True):
        for field in ("custom_calls", "host_callbacks"):
            new = set(live[field]) - set(golden.get(field, []))
            if new:
                out.append(f"{where}: new {field.replace('_', ' ')} escape: "
                           f"{sorted(new)}")
    gdon = golden.get("donation", {})
    ldon = live["donation"]
    if ldon["aliased"] < gdon.get("aliased", 0):
        out.append(f"{where}: donation dropped — {ldon['aliased']}/"
                   f"{ldon['declared']} buffers aliased "
                   f"(golden {gdon.get('aliased')})")
    if budget.get("require_donation") and not ldon["held"]:
        out.append(f"{where}: donation no longer held "
                   f"({ldon['aliased']}/{ldon['declared']} aliased)")
    if ldon.get("image_leaf_aliased", 0):
        # unconditional (no golden opt-out): a table/cluster-image leaf
        # aliased into an output means a dispatch can write into shared
        # long-lived state — the serve zombie-write hazard, never budgetable
        out.append(f"{where}: {ldon['image_leaf_aliased']} shared-image "
                   f"table leaf(s) aliased into outputs — image/table "
                   f"buffers are structurally non-donatable")
    gprom = {p["leaf"] for p in golden.get("carry_promotions", [])}
    for p in live.get("carry_promotions", []):
        if p["leaf"] not in gprom:
            out.append(f"{where}: carry dtype promotion on '{p['leaf']}' "
                       f"{p['in']} -> {p['out']}")
    mbc = budget.get("max_boundary_collectives")
    if mbc is not None and live.get("boundary_collectives", 0) > mbc:
        out.append(f"{where}: dispatch boundary inserted "
                   f"{live['boundary_collectives']} collectives (budget {mbc})")
    if budget.get("require_epoch_contract") \
            and not live.get("epoch_contract_held", True):
        out.append(f"{where}: epoch collective contract broken — a loop "
                   f"body strayed from one all-reduce + one all-gather per "
                   f"epoch: {live.get('epoch_census')}")
    return out


def check_certs(certs: Sequence[dict], golden_dir: str) -> Tuple[List[str], List[str]]:
    """(regressions, notes). Missing goldens are regressions — an unaudited
    hot kernel is exactly what the gate exists to prevent."""
    regressions: List[str] = []
    notes: List[str] = []
    for live in certs:
        doc = load_golden(golden_dir, live["kernel"])
        golden = (doc or {}).get("certs", {}).get(_cert_key(live))
        if golden is None:
            regressions.append(
                f"{live['kernel']} {_cert_key(live)}: no golden certificate "
                f"in {golden_dir} (run `simon audit --update`)")
            continue
        regressions.extend(check_cert(live, golden))
        if live["collective_count"] < golden["collective_count"]:
            notes.append(
                f"{live['kernel']} {_cert_key(live)}: collectives improved "
                f"{golden['collective_count']} -> {live['collective_count']} "
                f"(tighten with `simon audit --update`)")
    return regressions, notes


def diff_cert(live: dict, golden: Optional[dict]) -> List[str]:
    """Human-reviewable field diff for --update output."""
    if golden is None:
        return [f"  NEW {live['kernel']} {_cert_key(live)}: "
                f"{live['collective_count']} collective(s), donation "
                f"{live['donation']['aliased']}/{live['donation']['declared']}"]
    out = []
    for field in ("static_digest", "collectives", "collective_count",
                  "collective_bytes", "custom_calls", "host_callbacks",
                  "donation", "carry_promotions", "boundary_collectives",
                  "epoch_census", "epoch_contract_held", "budget"):
        if field in live or field in golden:
            a, b = golden.get(field), live.get(field)
            if a != b:
                out.append(f"  {live['kernel']} {_cert_key(live)}: "
                           f"{field} {a} -> {b}")
    return out


# ---------------------------------------------------------------------- CLI ----


def _default_golden_dir() -> str:
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(pkg_root, "tests", "golden", "audit")


def _human_line(cert: dict) -> str:
    colls = ", ".join(f"{k} x{v['count']}"
                      for k, v in cert["collectives"].items()) or "none"
    don = cert["donation"]
    extra = ""
    if "boundary_collectives" in cert:
        extra = f" boundary={cert['boundary_collectives']}"
    esc = ""
    if cert["custom_calls"] or cert["host_callbacks"]:
        esc = (f" escapes={cert['custom_calls'] + cert['host_callbacks']}")
    return (f"{cert['kernel']:<28} {cert['bucket']:>7}/{cert['mesh']:<10} "
            f"collectives: {colls} (~{cert['collective_bytes']}B) "
            f"donation {don['aliased']}/{don['declared']}{extra}{esc} "
            f"digest {cert['static_digest'][:8]}")


def run_audit(argv: Optional[Sequence[str]] = None) -> int:
    """The `simon audit` command."""
    parser = argparse.ArgumentParser(
        prog="simon audit",
        description="simonaudit: compile-time dispatch certificates — "
                    "collective census, donation effectiveness, host-callback "
                    "escapes, and recompile-keying digests for every "
                    "registered hot kernel, lowered on CPU at canonical "
                    "shape buckets x mesh shapes.",
    )
    mode = parser.add_mutually_exclusive_group()
    mode.add_argument("--check", action="store_true",
                      help="diff live certificates against the goldens; "
                           "exit 1 on any regression (the CI gate)")
    mode.add_argument("--update", action="store_true",
                      help="regenerate the golden certificates and print a "
                           "human-reviewable diff")
    parser.add_argument("--select", default="",
                        help="comma-separated target names (default: every "
                             "registered hot kernel + the wave-chain target; "
                             "the CI fixture only runs when named here)")
    parser.add_argument("--buckets", default=",".join(DEFAULT_BUCKETS),
                        help=f"comma-separated shape buckets "
                             f"(known: {', '.join(BUCKETS)})")
    parser.add_argument("--shards", default="1,2,8",
                        help="comma-separated mesh shard counts")
    parser.add_argument("--golden-dir", default=_default_golden_dir(),
                        help="golden certificate directory "
                             "(default: tests/golden/audit)")
    parser.add_argument("--format", choices=("human", "json"),
                        default="human")
    args = parser.parse_args(list(argv) if argv is not None else None)

    try:
        shards_list = tuple(
            int(s) for s in args.shards.split(",") if s.strip())
    except ValueError:
        parser.error(f"--shards must be comma-separated integers "
                     f"(got {args.shards!r})")
    if not shards_list or any(s < 1 for s in shards_list):
        parser.error(f"--shards needs at least one positive shard count "
                     f"(got {args.shards!r})")
    buckets = tuple(b.strip() for b in args.buckets.split(",") if b.strip())
    unknown = [b for b in buckets if b not in BUCKETS]
    if unknown:
        parser.error(f"unknown bucket(s): {', '.join(unknown)}")
    select = [s.strip() for s in args.select.split(",") if s.strip()] or None
    if select:
        known = set(target_names()) | {FIXTURE_TARGET}
        bad = [s for s in select if s not in known]
        if bad:
            parser.error(f"unknown target(s): {', '.join(bad)}")
        if CHAIN_TARGET in select and not any(s > 1 for s in shards_list):
            # never silently drop an explicitly requested target: the chain
            # invariant is meaningless at one shard, so refuse loudly
            parser.error(f"{CHAIN_TARGET} needs a multi-shard mesh in "
                         f"--shards (got {args.shards})")
    if select is None and not any(s > 1 for s in shards_list):
        # the default target list includes the chain invariant; dropping it
        # because --shards has no multi-shard mesh must be visible, not a
        # silently-narrower green gate
        print(f"note: {CHAIN_TARGET} skipped — no multi-shard mesh in "
              f"--shards (got {args.shards})", file=sys.stderr)

    # the 8-shard meshes need 8 virtual CPU devices BEFORE backend init
    from ..utils.devices import force_cpu_platform, request_cpu_devices

    request_cpu_devices(max(shards_list))
    force_cpu_platform()
    import jax

    if len(jax.devices()) < max(shards_list):
        print(f"audit error: need {max(shards_list)} devices, have "
              f"{len(jax.devices())} (the JAX backend initialized before "
              f"the virtual-CPU flag could be set)", file=sys.stderr)
        return 2

    human = args.format == "human"
    certs = run_targets(
        select, buckets, shards_list,
        log=(lambda c: print(_human_line(c), flush=True)) if human and not args.update
        else None)
    if not certs:
        # a gate that checked nothing must not report green (e.g. the chain
        # target selected with only single-shard meshes)
        print("audit error: the selection produced no certificates "
              "(schedule_wave_chain2 needs a multi-shard mesh in --shards)",
              file=sys.stderr)
        return 2

    full_matrix = (select is None
                   and set(buckets) == set(DEFAULT_BUCKETS)
                   and set(shards_list) == set(DEFAULT_SHARDS))
    if args.update:
        diffs: List[str] = []
        for c in certs:
            doc = load_golden(args.golden_dir, c["kernel"])
            golden = (doc or {}).get("certs", {}).get(_cert_key(c))
            diffs.extend(diff_cert(c, golden))
        written = write_goldens(args.golden_dir, certs, full=full_matrix)
        print("\n".join(diffs) if diffs
              else "  goldens unchanged (certificates identical)")
        print(f"simonaudit: wrote {len(written)} golden file(s), "
              f"{len(certs)} certificate(s) -> {args.golden_dir}")
        return 0

    if args.check:
        regressions, notes = check_certs(certs, args.golden_dir)
        for n in notes:
            print(f"note: {n}")
        for r in regressions:
            print(f"REGRESSION: {r}", file=sys.stderr)
        verdict = ("FAIL" if regressions else "ok")
        print(f"simonaudit --check: {len(certs)} certificate(s), "
              f"{len(regressions)} regression(s) — {verdict}")
        return 1 if regressions else 0

    if args.format == "json":
        print(json.dumps(certs, indent=1, sort_keys=True))
    else:
        total = sum(c["collective_count"] for c in certs)
        print(f"simonaudit: {len(certs)} certificate(s), {total} "
              f"collective(s) total (use --check against "
              f"{args.golden_dir}, --update to regenerate)")
    return 0
