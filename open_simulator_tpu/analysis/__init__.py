"""simonlint: first-party static analysis for JAX/TPU hazards.

The scheduling engine's parity with the vendored kube-scheduler rests on
invariants the runtime never checks — static-vs-traced jit arguments, fixed
scan-carry pytrees, no host syncs inside compiled paths, 32-bit dtypes at the
device boundary. This package enforces them on every PR:

    python -m open_simulator_tpu.cli lint open_simulator_tpu/

See README.md ("Static analysis: simon lint") for the rule catalog and
suppression syntax; rules live in rules.py, the driver in runner.py.
"""

from .base import RULE_REGISTRY, Finding, Rule, Severity
from .context import ModuleContext
from .runner import Report, analyze_file, analyze_paths, run_lint, write_bench

__all__ = [
    "RULE_REGISTRY",
    "Finding",
    "Rule",
    "Severity",
    "ModuleContext",
    "Report",
    "analyze_file",
    "analyze_paths",
    "run_lint",
    "write_bench",
]
