"""simonrace: lock-discipline, lock-order, and thread-ownership passes.

Built on the flow.py CFG tier but mostly lexical: lock scopes in this
codebase are `with`-blocks, so "which locks are held at this node" is a
syntactic property, and the interesting analysis is the MODEL — which names
are locks, which attributes they guard, which classes other threads can
actually reach, and which lock is acquired while which is held.

The model, per module (cross-file analysis would poison the per-file
LintCache, and every shipped lock structure here is module-local):

  * **locks** — module-level `NAME = threading.Lock()/RLock()/Condition()`
    assignments, class attributes `self.X = threading.Lock()` (any method),
    and cross-object locks reached through a typed attribute chain
    (`self._family._lock` canonicalizes via the `__init__` annotation
    `family: "MetricFamily"`). Lock-ish names that cannot be canonicalized
    still count as "a lock is held" (race pass, FP control) but are excluded
    from the order graph (a "?" node would fabricate cycles).
  * **guarded attributes** — `self.X` written under any held lock in any
    non-dunder method is guarded; writes include item/slice stores, `del`,
    and the standard mutator methods (`.append`, `.update`, ...).
  * **thread reachability** — a class is multi-thread-reachable when it owns
    a lock (locks exist to be contended) or it escapes: a bound method or a
    locally-constructed instance reaches `threading.Thread/Timer`,
    `executor.submit`-style dispatch, or a `guard.supervised` worker.
  * **acquires-while-holding** — the digraph whose edges are inner `with`
    acquisitions and calls-under-lock into module functions whose transitive
    acquire set is known; any cycle is a deadlock order violation.

Three rules ride the model: `race-unguarded-attr` (ERROR, off-lock access of
a guarded attribute in a reachable method, both sites cited),
`lock-order-cycle` (ERROR, witness chain), and `thread-owner` (WARNING,
every started Thread must be daemon-with-name or joined in-module).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .base import Finding, Severity, register
from .context import ModuleContext

LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
}
THREAD_FACTORIES = {"threading.Thread", "threading.Timer"}
SUBMIT_ATTRS = {"submit", "run_in_executor", "apply_async", "map_async"}

# Mutating calls on a container attribute count as writes for guarded-attr
# inference: `self._queue.append(x)` under the lock guards `_queue`.
_MUTATORS = {
    "append", "extend", "insert", "add", "remove", "discard", "pop",
    "popleft", "appendleft", "clear", "update", "setdefault", "sort",
    "reverse", "put", "put_nowait",
}

_LOCKISH_RE = re.compile(r"(?:^|_)(?:lock|locks|cv|cond|condition|mutex)$",
                         re.IGNORECASE)

_DUNDER_SKIP = {"__init__", "__new__", "__del__", "__enter__", "__exit__"}


# -------------------------------------------------------------------- model --


@dataclass
class GuardSite:
    """First observed guarded write of one attribute."""

    lock: str
    line: int
    cls: str
    method: str


@dataclass
class ClassInfo:
    name: str
    node: ast.ClassDef
    lock_attrs: Dict[str, int] = field(default_factory=dict)
    attr_types: Dict[str, str] = field(default_factory=dict)
    methods: Dict[str, ast.FunctionDef] = field(default_factory=dict)
    guarded: Dict[str, GuardSite] = field(default_factory=dict)
    escape_lines: List[int] = field(default_factory=list)

    @property
    def reachable(self) -> bool:
        return bool(self.lock_attrs) or bool(self.escape_lines)


@dataclass
class ModuleConcurrency:
    module_locks: Dict[str, int] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    # module-wide attr name -> guard site, for foreign-object accesses
    # (`child._counts` read in MetricFamily.samples matches _HistChild's
    # guarded `_counts`) and for module-global discipline
    guarded_attrs: Dict[str, GuardSite] = field(default_factory=dict)
    guarded_globals: Dict[str, GuardSite] = field(default_factory=dict)


def _iter_with_items(node):
    if isinstance(node, (ast.With, ast.AsyncWith)):
        return node.items
    return []


def _walk_no_defs(stmts):
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _attr_chain(expr: ast.expr) -> Optional[List[str]]:
    """["self", "_family", "_lock"] for self._family._lock, else None."""
    parts: List[str] = []
    cur = expr
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if not isinstance(cur, ast.Name):
        return None
    parts.append(cur.id)
    return list(reversed(parts))


def _canon_lock(ctx: ModuleContext, mc: ModuleConcurrency,
                cls: Optional[ClassInfo], expr: ast.expr) -> Optional[str]:
    """Canonical name for a lock expression, or None when it is not lock-like.

    Canonical forms: `MODULE.NAME` for module-level locks (resolved through
    import aliases, so `guard._STATE_LOCK` keeps one identity), `Class.attr`
    for instance locks, following ONE typed attribute hop
    (`self._family._lock` -> `MetricFamily._lock` when `__init__` annotates
    the `_family` param). Lock-ish names that cannot be canonicalized return
    `"?<name>"`: held for the race pass, excluded from the order graph.
    """
    chain = _attr_chain(expr)
    if chain is None:
        return None
    if len(chain) == 1:
        name = chain[0]
        if name in mc.module_locks:
            return name
        return f"?{name}" if _LOCKISH_RE.search(name) else None
    if chain[0] == "self" and cls is not None:
        if len(chain) == 2:
            if chain[1] in cls.lock_attrs:
                return f"{cls.name}.{chain[1]}"
            return (f"?{cls.name}.{chain[1]}"
                    if _LOCKISH_RE.search(chain[1]) else None)
        if len(chain) == 3:
            # one typed hop: self.<attr: T>.<lock>
            tname = cls.attr_types.get(chain[1])
            target = mc.classes.get(tname) if tname else None
            if target is not None and chain[2] in target.lock_attrs:
                return f"{target.name}.{chain[2]}"
            return (f"?{cls.name}.{chain[1]}.{chain[2]}"
                    if _LOCKISH_RE.search(chain[2]) else None)
        return (f"?{'.'.join(chain)}"
                if _LOCKISH_RE.search(chain[-1]) else None)
    # module-qualified: resolve through import aliases
    r = ctx.resolve(expr)
    if r is not None and _LOCKISH_RE.search(r.rsplit(".", 1)[-1]):
        return r
    return None


def _held_map(ctx: ModuleContext, mc: ModuleConcurrency,
              cls: Optional[ClassInfo],
              body: List[ast.stmt]) -> Dict[ast.AST, frozenset]:
    """id-keyed map: every node in `body` -> frozenset of held lock names.
    Lexical (with-block nesting); nested defs are separate execution
    contexts and are not entered."""
    held_at: Dict[ast.AST, frozenset] = {}

    def visit(node: ast.AST, held: frozenset) -> None:
        held_at[node] = held
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                # the lock expression itself evaluates BEFORE acquisition
                visit(item, held)
                ln = _canon_lock(ctx, mc, cls, item.context_expr)
                if ln is not None:
                    inner = inner | {ln}
            for child in node.body:
                visit(child, inner)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in body:
        visit(stmt, frozenset())
    return held_at


def _write_targets(node: ast.AST) -> List[ast.Attribute]:
    """Attribute nodes WRITTEN by this statement/expression: assignment
    targets, item/slice stores (`self.x[k] = v`), `del self.x[...]`, and
    mutator calls (`self.x.append(v)`)."""
    out: List[ast.Attribute] = []
    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for t in targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Attribute):
                    out.append(sub)
                    break  # outermost attribute of this target only
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            base = t.value if isinstance(t, ast.Subscript) else t
            if isinstance(base, ast.Attribute):
                out.append(base)
    elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if node.func.attr in _MUTATORS and isinstance(node.func.value,
                                                      ast.Attribute):
            out.append(node.func.value)
    elif isinstance(node, ast.Subscript) and isinstance(node.ctx,
                                                        (ast.Store, ast.Del)):
        if isinstance(node.value, ast.Attribute):
            out.append(node.value)
    return out


def _is_self_attr(node: ast.Attribute) -> bool:
    return isinstance(node.value, ast.Name) and node.value.id == "self"


def _class_of(ctx: ModuleContext, node: ast.AST) -> Optional[ast.ClassDef]:
    cur = ctx.parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur
        if isinstance(cur, ast.FunctionDef) and not isinstance(
                ctx.parents.get(cur), ast.ClassDef):
            # a method's nested worker def belongs to the method's class;
            # keep climbing only through function scopes
            pass
        cur = ctx.parents.get(cur)
    return None


def module_concurrency(ctx: ModuleContext) -> ModuleConcurrency:
    """Build (and memoize on the ctx) the per-module concurrency model."""
    cached = getattr(ctx, "_simonrace_model", None)
    if cached is not None:
        return cached
    mc = ModuleConcurrency()

    # module-level locks
    for stmt in ctx.tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            if ctx.resolve(stmt.value.func) in LOCK_FACTORIES:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        mc.module_locks[t.id] = stmt.lineno

    # classes: methods, lock attrs, typed attrs
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        ci = ClassInfo(name=node.name, node=node)
        for item in node.body:
            if isinstance(item, ast.FunctionDef):
                ci.methods.setdefault(item.name, item)
        init = ci.methods.get("__init__")
        ann: Dict[str, str] = {}
        if init is not None:
            for p in init.args.posonlyargs + init.args.args + init.args.kwonlyargs:
                if p.annotation is not None:
                    if isinstance(p.annotation, ast.Constant) and isinstance(
                            p.annotation.value, str):
                        ann[p.arg] = p.annotation.value
                    elif isinstance(p.annotation, ast.Name):
                        ann[p.arg] = p.annotation.id
        for m in ci.methods.values():
            for sub in _walk_no_defs(m.body):
                if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1):
                    continue
                t = sub.targets[0]
                if not (isinstance(t, ast.Attribute) and _is_self_attr(t)):
                    continue
                if isinstance(sub.value, ast.Call):
                    r = ctx.resolve(sub.value.func)
                    if r in LOCK_FACTORIES:
                        ci.lock_attrs.setdefault(t.attr, sub.lineno)
                        continue
                    if isinstance(sub.value.func, ast.Name):
                        ci.attr_types.setdefault(t.attr, sub.value.func.id)
                if isinstance(sub.value, ast.Name) and sub.value.id in ann:
                    ci.attr_types.setdefault(t.attr, ann[sub.value.id])
        mc.classes[node.name] = ci

    # guarded-attr inference (needs every class's lock_attrs complete first)
    for cname in sorted(mc.classes):
        ci = mc.classes[cname]
        for mname in sorted(ci.methods):
            if mname in _DUNDER_SKIP:
                continue
            method = ci.methods[mname]
            held_at = _held_map(ctx, mc, ci, method.body)
            for sub in _walk_no_defs(method.body):
                held = held_at.get(sub, frozenset())
                if not held:
                    continue
                for attr in _write_targets(sub):
                    if not _is_self_attr(attr) or attr.attr in ci.lock_attrs:
                        continue
                    site = GuardSite(sorted(held)[0], attr.lineno,
                                     cname, mname)
                    ci.guarded.setdefault(attr.attr, site)
                    mc.guarded_attrs.setdefault(attr.attr, site)

    # module-global discipline: `global NAME` writes / NAME.mutator() calls
    # under a module-level lock guard that global
    for fname in sorted(ctx.functions):
        for fn in ctx.functions[fname]:
            if _class_of(ctx, fn) is not None:
                continue
            held_at = _held_map(ctx, mc, None, fn.body)
            declared = {n for sub in _walk_no_defs(fn.body)
                        if isinstance(sub, ast.Global) for n in sub.names}
            for sub in _walk_no_defs(fn.body):
                held = held_at.get(sub, frozenset())
                mod_held = [h for h in held if h in mc.module_locks]
                if not mod_held:
                    continue
                names: List[Tuple[str, int]] = []
                if isinstance(sub, ast.Assign):
                    names = [(t.id, t.lineno) for t in sub.targets
                             if isinstance(t, ast.Name) and t.id in declared]
                elif isinstance(sub, ast.Call) and isinstance(
                        sub.func, ast.Attribute):
                    v = sub.func.value
                    if (sub.func.attr in _MUTATORS and isinstance(v, ast.Name)
                            and v.id not in mc.module_locks):
                        names = [(v.id, v.lineno)]
                elif isinstance(sub, ast.Subscript) and isinstance(
                        sub.ctx, (ast.Store, ast.Del)):
                    if isinstance(sub.value, ast.Name):
                        names = [(sub.value.id, sub.value.lineno)]
                for name, line in names:
                    if name.isupper() or name in declared:
                        mc.guarded_globals.setdefault(
                            name, GuardSite(sorted(mod_held)[0], line,
                                            "<module>", fname))

    _collect_escapes(ctx, mc)
    ctx._simonrace_model = mc  # type: ignore[attr-defined]
    return mc


def _collect_escapes(ctx: ModuleContext, mc: ModuleConcurrency) -> None:
    """Mark classes whose instances/bound methods reach another thread."""
    method_owner: Dict[str, List[str]] = {}
    for cname, ci in mc.classes.items():
        for mname in ci.methods:
            method_owner.setdefault(mname, []).append(cname)

    def local_types(site: ast.AST) -> Dict[str, str]:
        fn = ctx.enclosing_function(site)
        out: Dict[str, str] = {}
        if fn is None:
            return out
        for sub in _walk_no_defs(fn.body):
            if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Name)
                    and isinstance(sub.value, ast.Call)
                    and isinstance(sub.value.func, ast.Name)
                    and sub.value.func.id in mc.classes):
                out[sub.targets[0].id] = sub.value.func.id
        return out

    def mark(expr: Optional[ast.expr], site: ast.AST) -> None:
        if expr is None:
            return
        line = getattr(expr, "lineno", getattr(site, "lineno", 0))
        if isinstance(expr, (ast.Tuple, ast.List)):
            for el in expr.elts:
                mark(el, site)
            return
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id == "self":
                cls = _class_of(ctx, site)
                if cls is not None and cls.name in mc.classes:
                    mc.classes[cls.name].escape_lines.append(line)
                return
            if isinstance(base, ast.Name):
                t = local_types(site).get(base.id)
                if t is None:
                    owners = method_owner.get(expr.attr, [])
                    t = owners[0] if len(owners) == 1 else None
                if t in mc.classes:
                    mc.classes[t].escape_lines.append(line)
            return
        if isinstance(expr, ast.Name):
            t = local_types(site).get(expr.id)
            if t in mc.classes:
                mc.classes[t].escape_lines.append(line)

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        r = ctx.resolve(node.func) or ""
        is_thread = r in THREAD_FACTORIES
        is_submit = (isinstance(node.func, ast.Attribute)
                     and node.func.attr in SUBMIT_ATTRS)
        is_supervised = r == "supervised" or r.endswith(".supervised")
        if is_thread:
            for kw in node.keywords:
                if kw.arg in ("target", "args", "kwargs"):
                    mark(kw.value, node)
            for a in node.args[:2]:
                mark(a, node)
        elif is_submit or is_supervised:
            for a in node.args:
                mark(a, node)
            for kw in node.keywords:
                mark(kw.value, node)


# ---------------------------------------------------- race-unguarded-attr --


@register(
    "race-unguarded-attr", Severity.ERROR,
    "An attribute consistently written under a lock is read or written "
    "OFF-lock in a method of a multi-thread-reachable class (one that owns "
    "a lock or escapes to threading.Thread/Timer, executor.submit, or a "
    "guard.supervised worker). The PR 14 torn-scrape bug was exactly this "
    "shape: histogram child state mutated under the family lock, then read "
    "bucket-by-bucket off-lock by samples(), yielding rows whose sum/count "
    "never co-occurred. Take the lock (or copy under it), or waive a "
    "deliberate racy fast path with `# simonlint: ignore[race-unguarded-"
    "attr] -- <why>` naming the happens-before argument.",
)
def rule_race_unguarded_attr(ctx: ModuleContext) -> List[Finding]:
    mc = module_concurrency(ctx)
    out: List[Finding] = []
    base = os.path.basename(ctx.path)
    for cname in sorted(mc.classes):
        ci = mc.classes[cname]
        if not ci.reachable:
            continue
        for mname in sorted(ci.methods):
            # `*_locked` is this repo's caller-holds-lock contract (xray's
            # _reindex_locked): the method is only entered with the lock
            # held, so its lexically off-lock accesses are guarded
            if mname in _DUNDER_SKIP or mname.endswith("_locked"):
                continue
            method = ci.methods[mname]
            held_at = _held_map(ctx, mc, ci, method.body)
            reported: Set[Tuple[str, bool]] = set()
            for sub in _walk_no_defs(method.body):
                if not isinstance(sub, ast.Attribute):
                    continue
                if held_at.get(sub, frozenset()):
                    continue
                is_self = _is_self_attr(sub)
                if is_self:
                    site = ci.guarded.get(sub.attr)
                    if site is None or sub.attr in ci.lock_attrs:
                        continue
                else:
                    site = mc.guarded_attrs.get(sub.attr)
                    if site is None or site.cls == cname:
                        continue
                    # only object-attribute loads, not module attrs
                    if not isinstance(sub.value, ast.Name):
                        continue
                    if sub.value.id in ctx.aliases:
                        continue
                key = (sub.attr, is_self)
                if key in reported:
                    continue
                reported.add(key)
                kind = ("written" if isinstance(sub.ctx, (ast.Store, ast.Del))
                        else "read")
                where = (f"'{cname}.{mname}'" if is_self
                         else f"'{cname}.{mname}' via "
                              f"'{ast.unparse(sub.value)}.{sub.attr}'")
                out.append(Finding(
                    "race-unguarded-attr", Severity.ERROR, ctx.path,
                    sub.lineno, sub.col_offset,
                    f"attribute '{sub.attr}' is guarded by {site.lock} "
                    f"(written under it at {base}:{site.line} in "
                    f"'{site.cls}.{site.method}') but {kind} off-lock in "
                    f"{where} — torn or stale state once another thread "
                    f"holds the lock; acquire it, copy under it, or waive "
                    f"with the happens-before argument",
                ))

    # module-global discipline: guarded globals read/written off-lock in
    # module-level functions (guard._EVENTS / faults._PLAN shape)
    if mc.guarded_globals:
        for fname in sorted(ctx.functions):
            for fn in ctx.functions[fname]:
                if _class_of(ctx, fn) is not None or fname in _DUNDER_SKIP:
                    continue
                held_at = _held_map(ctx, mc, None, fn.body)
                locals_: Set[str] = {
                    t.id for sub in _walk_no_defs(fn.body)
                    if isinstance(sub, ast.Assign)
                    for t in sub.targets if isinstance(t, ast.Name)}
                declared = {n for sub in _walk_no_defs(fn.body)
                            if isinstance(sub, ast.Global)
                            for n in sub.names}
                reported_g: Set[str] = set()
                for sub in _walk_no_defs(fn.body):
                    if not isinstance(sub, ast.Name):
                        continue
                    name = sub.id
                    site = mc.guarded_globals.get(name)
                    if site is None or name in reported_g:
                        continue
                    if name in locals_ and name not in declared:
                        continue  # a local shadows the global
                    if held_at.get(sub, frozenset()):
                        continue
                    if site.method == fname:
                        pass  # same function can still misuse it off-lock
                    reported_g.add(name)
                    kind = ("written"
                            if isinstance(sub.ctx, (ast.Store, ast.Del))
                            else "read")
                    out.append(Finding(
                        "race-unguarded-attr", Severity.ERROR, ctx.path,
                        sub.lineno, sub.col_offset,
                        f"module global '{name}' is guarded by {site.lock} "
                        f"(written under it at {base}:{site.line} in "
                        f"'{site.method}') but {kind} off-lock in "
                        f"'{fname}' — acquire the lock or waive with the "
                        f"happens-before argument",
                    ))
    return out


# ------------------------------------------------------- lock-order-cycle --


def _function_class(ctx: ModuleContext,
                    mc: ModuleConcurrency,
                    fn: ast.FunctionDef) -> Optional[ClassInfo]:
    cls = _class_of(ctx, fn)
    return mc.classes.get(cls.name) if cls is not None else None


def _acquire_summaries(ctx: ModuleContext,
                       mc: ModuleConcurrency) -> Dict[str, Set[str]]:
    """function name -> transitive set of canonical locks it may acquire.
    Name-keyed (collisions merge conservatively); resolved through direct
    calls `f()` and method calls `self.m()` / `obj.m()` by name."""
    direct: Dict[str, Set[str]] = {}
    calls: Dict[str, Set[str]] = {}
    for fname, defs in ctx.functions.items():
        acq: Set[str] = set()
        callees: Set[str] = set()
        for fn in defs:
            ci = _function_class(ctx, mc, fn)
            for sub in _walk_no_defs(fn.body):
                for item in _iter_with_items(sub):
                    ln = _canon_lock(ctx, mc, ci, item.context_expr)
                    if ln is not None and not ln.startswith("?"):
                        acq.add(ln)
                if isinstance(sub, ast.Call):
                    if isinstance(sub.func, ast.Name):
                        callees.add(sub.func.id)
                    elif isinstance(sub.func, ast.Attribute):
                        callees.add(sub.func.attr)
        direct[fname] = acq
        calls[fname] = callees & set(ctx.functions)
    out = {f: set(a) for f, a in direct.items()}
    for _ in range(len(out) + 1):
        changed = False
        for f in out:
            for c in calls[f]:
                extra = out.get(c, set()) - out[f]
                if extra:
                    out[f] |= extra
                    changed = True
        if not changed:
            break
    return out


@register(
    "lock-order-cycle", Severity.ERROR,
    "The acquires-while-holding graph of this module has a cycle: two code "
    "paths take the same locks in opposite orders (directly nested `with` "
    "blocks, or a call made under one lock into a function that takes "
    "another). Two threads interleaving those paths deadlock, and on the "
    "serving path that means a wedged dispatcher with live watchdogs. Break "
    "the cycle by ordering the acquisitions consistently or by copying "
    "state out of the inner lock before taking the outer one; waive only "
    "with `# simonlint: ignore[lock-order-cycle] -- <why>` proving the "
    "paths cannot run concurrently.",
)
def rule_lock_order_cycle(ctx: ModuleContext) -> List[Finding]:
    mc = module_concurrency(ctx)
    summaries = _acquire_summaries(ctx, mc)
    # adj[a][b] = (line, description) for the first a->b edge witnessed
    adj: Dict[str, Dict[str, Tuple[int, str]]] = {}

    def edge(a: str, b: str, line: int, desc: str) -> None:
        if a == b:
            return  # re-entrant acquisition (RLock) — not an order fact
        adj.setdefault(a, {}).setdefault(b, (line, desc))

    for fname in sorted(ctx.functions):
        for fn in ctx.functions[fname]:
            ci = _function_class(ctx, mc, fn)
            held_at = _held_map(ctx, mc, ci, fn.body)
            for sub in _walk_no_defs(fn.body):
                held = {h for h in held_at.get(sub, frozenset())
                        if not h.startswith("?")}
                if not held:
                    continue
                for item in _iter_with_items(sub):
                    ln = _canon_lock(ctx, mc, ci, item.context_expr)
                    if ln is None or ln.startswith("?"):
                        continue
                    for h in sorted(held):
                        edge(h, ln, sub.lineno,
                             f"with-block in '{fname}'")
                if isinstance(sub, ast.Call):
                    callee = None
                    if isinstance(sub.func, ast.Name):
                        callee = sub.func.id
                    elif isinstance(sub.func, ast.Attribute):
                        callee = sub.func.attr
                    if callee is None or callee not in summaries:
                        continue
                    for ln in sorted(summaries[callee]):
                        if ln in held:
                            continue
                        for h in sorted(held):
                            edge(h, ln, sub.lineno,
                                 f"call to '{callee}' in '{fname}'")

    out: List[Finding] = []
    seen_cycles: Set[frozenset] = set()
    for start in sorted(adj):
        # BFS back to `start` through the edge set
        parent: Dict[str, str] = {}
        queue = [start]
        found: Optional[List[str]] = None
        visited: Set[str] = set()
        while queue and found is None:
            a = queue.pop(0)
            for b in sorted(adj.get(a, {})):
                if b == start:
                    path = [a]
                    while path[-1] != start and path[-1] in parent:
                        path.append(parent[path[-1]])
                    found = list(reversed(path)) + [start]
                    break
                if b not in visited:
                    visited.add(b)
                    parent[b] = a
                    queue.append(b)
        if found is None:
            continue
        key = frozenset(found)
        if key in seen_cycles:
            continue
        seen_cycles.add(key)
        base = os.path.basename(ctx.path)
        hops = []
        first_line = None
        for a, b in zip(found, found[1:]):
            line, desc = adj[a][b]
            if first_line is None:
                first_line = line
            hops.append(f"{a} -> {b} ({base}:{line}, {desc})")
        out.append(Finding(
            "lock-order-cycle", Severity.ERROR, ctx.path,
            first_line or 1, 0,
            "lock-order cycle — two interleaved threads deadlock: "
            + "; ".join(hops)
            + "; order the acquisitions consistently or copy state out of "
              "the inner lock first",
        ))
    return out


# ------------------------------------------------------------ thread-owner --


@register(
    "thread-owner", Severity.WARNING,
    "A threading.Thread/Timer is started without an owner: it is neither "
    "daemon-with-a-name (the documented fire-and-forget convention — the "
    "name is how `simon top`, the sampler, and a stack dump attribute it) "
    "nor joined on any code path in this module. Anonymous threads are "
    "exactly how the scope-sampler leak class happens: shutdown paths "
    "cannot find them. Name it and set daemon=True, join it on a shutdown "
    "path, or waive with `# simonlint: ignore[thread-owner] -- <why>` "
    "naming the owner.",
)
def rule_thread_owner(ctx: ModuleContext) -> List[Finding]:
    out: List[Finding] = []
    joined: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"):
            chain = _attr_chain(node.func.value)
            if chain is not None:
                joined.add(chain[-1])
                joined.add(".".join(chain))
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if ctx.resolve(node.func) not in THREAD_FACTORIES:
            continue
        kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
        daemon = kwargs.get("daemon")
        is_daemon = (isinstance(daemon, ast.Constant)
                     and daemon.value is True)
        has_name = "name" in kwargs
        if is_daemon and has_name:
            continue
        # joined? — the constructed thread must be bound to a name/attr that
        # some path in this module joins
        target_names: Set[str] = set()
        parent = ctx.parents.get(node)
        while isinstance(parent, (ast.Attribute, ast.Call)):
            parent = ctx.parents.get(parent)
        if isinstance(parent, ast.Assign):
            for t in parent.targets:
                chain = _attr_chain(t)
                if chain is not None:
                    target_names.add(chain[-1])
                    target_names.add(".".join(chain))
        if target_names & joined:
            continue
        why = ("started as daemon but anonymous (no name= for attribution)"
               if is_daemon else
               "neither daemon-with-name nor joined in this module")
        out.append(Finding(
            "thread-owner", Severity.WARNING, ctx.path,
            node.lineno, node.col_offset,
            f"thread has no owner: {why} — name it and set daemon=True, "
            f"join it on a shutdown path, or waive with the owner named",
        ))
    return out
