"""Per-module AST model shared by every simonlint rule.

One `ModuleContext` is built per analyzed file. It answers the questions the
JAX-hazard rules all need:

  * what does this name resolve to? (import-alias canonicalization: `jnp`
    -> `jax.numpy`, `partial` -> `functools.partial`, ...)
  * which functions are jit roots (decorator form, `partial(jax.jit, ...)`
    form, or the `g = jax.jit(f, static_argnames=...)` assignment form), and
    which of their parameters are declared static?
  * which functions are `lax.scan` / `while_loop` / `fori_loop` bodies, and
    — transitively, via lexical nesting — which code is *traced*?
  * which classes are NamedTuple carry contracts?
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

JIT_NAMES = {
    "jax.jit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
}
PARTIAL_NAMES = {"functools.partial"}
SCAN_NAMES = {"jax.lax.scan"}
WHILE_NAMES = {"jax.lax.while_loop"}
FORI_NAMES = {"jax.lax.fori_loop"}

FuncDef = ast.FunctionDef  # async defs never appear in traced code; ignored


@dataclass
class JitInfo:
    """How a function is jit-compiled and which params are static."""

    static_names: Set[str] = field(default_factory=set)
    site_line: int = 0


@dataclass
class ScanSite:
    """One lax.scan/while_loop/fori_loop call and its resolved body."""

    call: ast.Call
    kind: str                      # "scan" | "while" | "fori"
    body: Optional[FuncDef]        # None when unresolvable (lambda, import)
    body_expr: ast.expr
    carry_index: int               # param index of the carry in `body`
    init: Optional[ast.expr]       # the initial-carry expression


class ModuleContext:
    def __init__(self, path: str, source: str, tree: Optional[ast.Module] = None):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree if tree is not None else ast.parse(source)
        self.aliases: Dict[str, str] = {}
        self.functions: Dict[str, List[FuncDef]] = {}
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.namedtuples: Dict[str, List[str]] = {}
        self.jit: Dict[FuncDef, JitInfo] = {}
        self.scans: List[ScanSite] = []
        self._collect()

    # ------------------------------------------------------------- resolution --
    def resolve(self, node: ast.expr) -> Optional[str]:
        """Canonical dotted name for a Name/Attribute chain, or None."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        head = self.aliases.get(cur.id, cur.id)
        parts.append(head)
        return ".".join(reversed(parts))

    def _collect(self) -> None:
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for a in node.names:
                    self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"
            elif isinstance(node, ast.FunctionDef):
                self.functions.setdefault(node.name, []).append(node)
            elif isinstance(node, ast.ClassDef):
                self._maybe_namedtuple(node)

        # second pass needs functions + aliases complete
        for node in ast.walk(self.tree):
            if isinstance(node, ast.FunctionDef):
                self._check_jit_decorators(node)
            elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                self._check_jit_assignment(node.value)
            elif isinstance(node, ast.Call):
                self._check_loop_call(node)

    # ------------------------------------------------------------ namedtuples --
    def _maybe_namedtuple(self, node: ast.ClassDef) -> None:
        for b in node.bases:
            r = self.resolve(b)
            if r in ("typing.NamedTuple", "NamedTuple"):
                fields = [
                    s.target.id for s in node.body
                    if isinstance(s, ast.AnnAssign) and isinstance(s.target, ast.Name)
                ]
                self.namedtuples[node.name] = fields
                return

    # -------------------------------------------------------------------- jit --
    def _param_names(self, fn: FuncDef) -> List[str]:
        a = fn.args
        return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]

    def _statics_from_call(self, call: ast.Call, fn: FuncDef) -> Set[str]:
        names: Set[str] = set()
        params = self._param_names(fn)
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for el in ast.walk(kw.value):
                    if isinstance(el, ast.Constant) and isinstance(el.value, str):
                        names.add(el.value)
            elif kw.arg == "static_argnums":
                for el in ast.walk(kw.value):
                    if isinstance(el, ast.Constant) and isinstance(el.value, int):
                        if 0 <= el.value < len(params):
                            names.add(params[el.value])
        return names

    def _mark_jit(self, fn: FuncDef, statics: Set[str], line: int) -> None:
        info = self.jit.setdefault(fn, JitInfo())
        info.static_names |= statics
        info.site_line = info.site_line or line

    def _check_jit_decorators(self, fn: FuncDef) -> None:
        for dec in fn.decorator_list:
            if self.resolve(dec) in JIT_NAMES:
                self._mark_jit(fn, set(), dec.lineno)
            elif isinstance(dec, ast.Call):
                target = self.resolve(dec.func)
                if target in JIT_NAMES:
                    self._mark_jit(fn, self._statics_from_call(dec, fn), dec.lineno)
                elif target in PARTIAL_NAMES and dec.args:
                    if self.resolve(dec.args[0]) in JIT_NAMES:
                        self._mark_jit(fn, self._statics_from_call(dec, fn), dec.lineno)

    def _check_jit_assignment(self, call: ast.Call) -> None:
        # `feasibility_jit = jax.jit(feasibility, static_argnames=(...))`
        if self.resolve(call.func) in JIT_NAMES and call.args:
            fn = self.lookup_function(call.args[0])
            if fn is not None:
                self._mark_jit(fn, self._statics_from_call(call, fn), call.lineno)

    # ------------------------------------------------------------- loop bodies --
    def lookup_function(self, expr: ast.expr) -> Optional[FuncDef]:
        """Resolve a Name to its FunctionDef, preferring the definition whose
        enclosing function also encloses the reference (several kernels nest
        a local `body`/`cond`; plain name-matching would cross-wire them)."""
        if not isinstance(expr, ast.Name):
            return None
        defs = self.functions.get(expr.id)
        if not defs:
            return None
        if len(defs) > 1:
            scope_chain = []
            cur: Optional[ast.AST] = self.parents.get(expr)
            while cur is not None:
                scope_chain.append(cur)
                cur = self.parents.get(cur)
            for scope in scope_chain:  # innermost first
                for fn in defs:
                    if self.parents.get(fn) is scope:
                        return fn
        return defs[0]

    def _resolve_body(self, expr: ast.expr) -> Tuple[Optional[FuncDef], int]:
        """(function def, #positional args pre-bound by functools.partial)."""
        fn = self.lookup_function(expr)
        if fn is not None:
            return fn, 0
        if isinstance(expr, ast.Call) and self.resolve(expr.func) in PARTIAL_NAMES:
            if expr.args:
                inner = self.lookup_function(expr.args[0])
                if inner is not None:
                    return inner, len(expr.args) - 1
        return None, 0

    def _check_loop_call(self, call: ast.Call) -> None:
        target = self.resolve(call.func)
        if target in SCAN_NAMES and len(call.args) >= 2:
            body, bound = self._resolve_body(call.args[0])
            self.scans.append(ScanSite(
                call=call, kind="scan", body=body, body_expr=call.args[0],
                carry_index=bound, init=call.args[1]))
        elif target in WHILE_NAMES and len(call.args) >= 3:
            for i, kind in ((0, "while"), (1, "while")):
                body, bound = self._resolve_body(call.args[i])
                if body is not None:
                    self.scans.append(ScanSite(
                        call=call, kind=kind, body=body, body_expr=call.args[i],
                        carry_index=bound, init=call.args[2]))
        elif target in FORI_NAMES and len(call.args) >= 4:
            body, bound = self._resolve_body(call.args[2])
            if body is not None:
                # fori body is (i, carry): carry is one past the index param
                self.scans.append(ScanSite(
                    call=call, kind="fori", body=body, body_expr=call.args[2],
                    carry_index=bound + 1, init=call.args[3]))

    # ---------------------------------------------------------------- tracing --
    def traced_functions(self) -> Dict[FuncDef, Set[str]]:
        """Every function whose body executes under a JAX trace, mapped to the
        set of its parameters that are STATIC (concrete Python values at trace
        time). Loop bodies and functions lexically nested inside a traced
        function are traced with no static params."""
        traced: Dict[FuncDef, Set[str]] = {}
        for fn, info in self.jit.items():
            traced[fn] = set(info.static_names)
        for site in self.scans:
            if site.body is not None and site.body not in traced:
                traced[site.body] = set()

        changed = True
        while changed:
            changed = False
            for defs in self.functions.values():
                for fn in defs:
                    if fn in traced:
                        continue
                    anc = self.parents.get(fn)
                    while anc is not None:
                        if isinstance(anc, ast.FunctionDef) and anc in traced:
                            traced[fn] = set()
                            changed = True
                            break
                        anc = self.parents.get(anc)
        return traced

    def enclosing_function(self, node: ast.AST) -> Optional[FuncDef]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.FunctionDef):
                return cur
            cur = self.parents.get(cur)
        return None
