"""The Applier: apply-mode orchestration + capacity planning.

Mirrors /root/reference/pkg/apply/apply.go:
- config load + validation (NewApplier :61-101, validate :269-306)
- cluster from customConfig dir or kubeconfig (Run step 1, :114-127)
- app list from raw YAML dirs or helm charts (step 2, :129-152)
- newNode template (+ node-name-matched local-storage JSON) (step 3, :156-168)
- the add-node loop (step 4, :203-259) — interactively prompting like the reference's
  survey menu, or (non-interactive extension) automatically searching the minimal
  node count that schedules everything within the MaxCPU/MaxMemory/MaxVG envelope
  (satisfyResourceSetting :689-775). The reference asks the user for each node count;
  the auto-search is this build's capacity-planning mode (deviation, documented).
- report tables (report* :309-687) as plain aligned-text tables instead of pterm.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, TextIO

from ..api.v1alpha1 import ConfigError, SimonConfig, parse_simon_config, validate_config
from ..core import constants as C
from ..obs import instruments as obs
from ..core.types import AppResource, NodeStatus, ResourceTypes, SimulateResult
from ..models.fakenode import new_fake_nodes
from ..resilience import guard
from ..resilience.policy import Deadline, check_deadline
from ..simulator.core import simulate
from ..utils.objutil import annotations_of, labels_of, name_of, namespace_of, pod_resource_requests
from ..utils.quantity import format_quantity, parse_milli, parse_quantity
from ..utils.storage import NodeStorage
from ..utils.yamlio import (
    load_cluster_from_directory,
    load_resources_from_directory,
    match_and_set_local_storage_annotation,
)

MAX_AUTO_NODES = 10_000  # auto-search upper bound before giving up
PROBE_FANOUT = 8  # candidates per incremental-probe dispatch (one vmap lane each)


def _grid(lo: int, hi: int, k: int) -> List[int]:
    """Up to k evenly spaced ints covering [lo, hi], endpoints included."""
    if hi <= lo:
        return [max(lo, hi)]
    if hi - lo + 1 <= k:
        return list(range(lo, hi + 1))
    return sorted({lo + round(i * (hi - lo) / (k - 1)) for i in range(k)})


def _interior(lo: int, hi: int, k: int) -> List[int]:
    """Up to k evenly spaced ints strictly inside (lo, hi)."""
    if hi - lo <= 1:
        return []
    if hi - lo - 1 <= k:
        return list(range(lo + 1, hi))
    return sorted({lo + max(1, round(i * (hi - lo) / (k + 1))) for i in range(1, k + 1)})


class CapacityPlanner:
    """Fast add-node search: expand the workload ONCE, probe candidate node
    counts with non-mutating device runs (Simulator.probe_pods), and start from
    an arithmetic lower bound below which scheduling provably fails.

    The reference's loop re-simulates the whole workload per candidate
    (apply.go:203-259); here a probe skips pod regeneration, placement
    materialization, and failure diagnosis — the expensive host work — and the
    authoritative full simulation runs only at the chosen answer (the Applier
    re-validates it and falls back to the full-simulation search on any
    divergence).

    Only built when the probe is provably equivalent: no DaemonSets (their pod
    sets depend on the candidate node list), no open-local storage (the
    envelope check would need VG accounting), and no pre-bound pod AFTER an
    unbound one (probe_pods commits all bound pods first, which could steal
    capacity an earlier unbound pod would have taken in the serial order).
    `try_build` returns None otherwise and the Applier keeps the original
    loop."""

    def __init__(self, base_nodes: List[dict], new_node: dict, pods: List[dict],
                 cluster_objects: Optional[ResourceTypes] = None,
                 app_objects: Optional[List[ResourceTypes]] = None,
                 sched_config=None) -> None:
        self.base_nodes = base_nodes
        self.new_node = new_node
        self.pods = pods
        self.cluster_objects = cluster_objects
        self.app_objects = app_objects or []
        self.sched_config = sched_config
        # filled by search(): path ("incremental"/"fresh"), probes (candidate
        # evaluations), dispatches (device round-trips), encode_s (one-time
        # pod-encoding wall), encodes (must stay 1 on the incremental path),
        # journal_hits (verdicts replayed from --resume-journal)
        self.stats: Dict[str, object] = {}
        # crash-consistent probe-verdict journal (resilience/guard.py
        # SearchJournal), attached via attach_journal for --resume-journal
        self.journal = None

    @classmethod
    def try_build(cls, cluster: ResourceTypes, apps: List[AppResource],
                  new_node: Optional[dict], patch_funcs,
                  sched_config=None) -> Optional["CapacityPlanner"]:
        from ..models.workloads import expand_workloads_excluding_daemonsets
        from ..algo.queues import sort_affinity, sort_toleration

        if new_node is None:
            return None
        if cluster.daemon_sets or any(a.resource.daemon_sets for a in apps):
            return None
        nodes = cluster.nodes + [new_node]
        if any(annotations_of(n).get(C.AnnoNodeLocalStorage) for n in nodes):
            return None
        cluster2 = cluster.copy()
        pods = expand_workloads_excluding_daemonsets(cluster2)
        for app in apps:
            from ..models.workloads import generate_valid_pods_from_app

            app_pods = generate_valid_pods_from_app(app.name, app.resource, cluster.nodes)
            app_pods = sort_toleration(sort_affinity(app_pods))
            for patch in patch_funcs or []:
                patch(app_pods)
            pods.extend(app_pods)
        seen_unbound = False
        for p in pods:
            if (p.get("spec") or {}).get("nodeName"):
                if seen_unbound:
                    return None  # bound-after-unbound: probe order-inequivalent
            else:
                seen_unbound = True
        return cls(cluster.nodes, new_node, pods,
                   cluster_objects=cluster, app_objects=[a.resource for a in apps],
                   sched_config=sched_config)

    # --------------------------------------------------------------- journal ----

    def options_digest(self) -> str:
        """Canonical digest of everything that determines this search's
        verdicts: the FULL base-node and template-node objects (allocatable,
        labels, taints — not just names), every pod's identity + full spec
        (requests, affinity, priority, binding), the scheduler config's
        semantic fields (sorted — never repr, whose set ordering is
        hash-randomized across processes), and the envelope percentages.
        The journal's header guard — a journal whose digest differs belongs
        to a DIFFERENT search and must not steer this one
        (guard.SearchJournal rejects it)."""
        h = hashlib.sha256()

        def upd(obj) -> None:
            h.update(json.dumps(obj, sort_keys=True, default=str).encode())
            h.update(b"\x00")

        for n in sorted(self.base_nodes, key=name_of):
            upd(n)
        upd(self.new_node)
        for p in self.pods:  # incremental: no giant host string at 100k pods
            upd((namespace_of(p), name_of(p), p.get("spec") or {}))
        sc = self.sched_config
        upd({
            "weights": sc.weight_kwargs() if sc is not None else None,
            "kernel_filters": sorted(
                getattr(sc, "disabled_kernel_filters", None) or ()),
            "encoder_filters": sorted(
                getattr(sc, "disabled_encoder_filters", None) or ()),
            "preemption_disabled": bool(
                getattr(sc, "preemption_disabled", False)),
        })
        upd({"max_cpu": self._env_pct(C.EnvMaxCPU),
             "max_memory": self._env_pct(C.EnvMaxMemory)})
        return "sha256:" + h.hexdigest()

    def attach_journal(self, path: str) -> None:
        """Open (or resume) the fsync'd probe-verdict journal at `path`.
        Raises guard.JournalMismatch when the file was written by a search
        with different options."""
        self.journal = guard.SearchJournal.open(path, self.options_digest())

    def _journal_lookup(self, n: int):
        if self.journal is None:
            return None
        hit = self.journal.lookup(n)
        if hit is not None:
            self.stats["journal_hits"] = int(
                self.stats.get("journal_hits") or 0) + 1
        return hit

    def _journal_record(self, n: int, ok: bool, nf: int) -> None:
        if self.journal is not None:
            self.journal.record(n, ok, nf)

    # ------------------------------------------------------------ arithmetic ----

    def _totals(self):
        """Request totals over the pods the simulation will actually account:
        pods bound to unknown nodes are dropped from every report (the engine's
        homeless handling), so they must not inflate the lower bound either."""
        known = {name_of(n) for n in self.base_nodes}
        cpu_used = mem_used = 0.0
        n_pods = 0
        for p in self.pods:
            nn = (p.get("spec") or {}).get("nodeName")
            if nn and nn not in known:
                continue
            req = pod_resource_requests(p)
            cpu_used += req.get("cpu", 0.0)
            mem_used += req.get("memory", 0.0)
            n_pods += 1
        return cpu_used, mem_used, n_pods

    @staticmethod
    def _node_caps(node: dict):
        alloc = (node.get("status") or {}).get("allocatable") or {}
        return (parse_milli(alloc.get("cpu", 0)), parse_quantity(alloc.get("memory", 0)),
                parse_quantity(alloc.get("pods", 0)))

    @staticmethod
    def _env_pct(name: str) -> int:
        """Lenient variant of satisfy_resource_setting's env parse: probes never
        raise — an unparsable env falls to 100 and the authoritative run (which
        keeps the reference's ConfigError) reports it."""
        s = os.environ.get(name, "")
        try:
            v = int(s) if s else 100
        except ValueError:
            return 100
        return v if 0 <= v <= 100 else 100

    @classmethod
    def _envelope_ok(cls, cpu_used, cpu_alloc, mem_used, mem_alloc) -> bool:
        """satisfy_resource_setting's integer occupancy-rate check
        (apply.go:689-775) on aggregate totals — the single copy the probe and
        the lower bound both use."""
        cpu_rate = int(cpu_used / cpu_alloc * 100) if cpu_alloc else 0
        mem_rate = int(mem_used / mem_alloc * 100) if mem_alloc else 0
        return (cpu_rate <= cls._env_pct(C.EnvMaxCPU)
                and mem_rate <= cls._env_pct(C.EnvMaxMemory))

    def lower_bound(self, totals=None) -> int:
        """Smallest n passing the NECESSARY conditions: per-resource totals fit
        AND the MaxCPU/MaxMemory integer-rate envelope of
        satisfy_resource_setting holds. Any n below provably fails, so the
        probe search starts here. Monotone in n -> binary search, no device.
        `totals` overrides the (cpu_used, mem_used, n_pods) host scan — the
        incremental session derives the same sums from its encoded groups
        without the per-pod loop (ProbeSession.batch_totals)."""
        cpu_used, mem_used, n_pods = totals if totals is not None else self._totals()
        base = [self._node_caps(n) for n in self.base_nodes]
        b_cpu = sum(c for c, _, _ in base)
        b_mem = sum(m for _, m, _ in base)
        b_pods = sum(p for _, _, p in base)
        n_cpu, n_mem, n_podcap = self._node_caps(self.new_node)

        def necessary_ok(n: int) -> bool:
            cpu_a = b_cpu + n * n_cpu
            mem_a = b_mem + n * n_mem
            pods_a = b_pods + n * n_podcap
            if cpu_used > cpu_a or mem_used > mem_a or n_pods > pods_a:
                return False
            return self._envelope_ok(cpu_used, cpu_a, mem_used, mem_a)

        if necessary_ok(0):
            return 0
        lo, hi = 0, MAX_AUTO_NODES + 1
        while lo + 1 < hi:
            mid = (lo + hi) // 2
            if necessary_ok(mid):
                hi = mid
            else:
                lo = mid
        return hi

    # --------------------------------------------------------------- probing ----

    def probe(self, n: int):
        """(all_ok, n_failed) for base + n new nodes, via one non-mutating
        device run plus the envelope check on the resulting carry totals."""
        from ..simulator.engine import Simulator

        trial = self.base_nodes + new_fake_nodes(self.new_node, n)
        sim = Simulator(trial, sched_config=self.sched_config)
        if self.cluster_objects is not None:
            sim.register_cluster_objects(self.cluster_objects)
        for rt in self.app_objects:
            sim.register_app_objects(rt)
        scheduled, total = sim.probe_pods(self.pods)
        n_failed = total - scheduled
        if n_failed:
            return False, n_failed
        u = sim.probe_utilization()
        ok = self._envelope_ok(u["cpu_used"], u["cpu_alloc"],
                               u["mem_used"], u["mem_alloc"])
        return ok, 0

    def search(self):
        """(found, best_n, history) — the incremental encode-once probe session
        when the workload qualifies (one pod encoding + device transfer for the
        WHOLE search, candidates evaluated as multi-candidate fan-out
        dispatches, and the final answer re-validated by one fresh-Simulator
        probe), else the fresh-probe doubling + binary refinement.
        history = [(n, n_failed)] for the give-up diagnostics. found=False
        means no-progress/max-exhausted."""
        self.stats = {"path": "fresh", "probes": 0, "dispatches": 0,
                      "encode_s": 0.0, "encodes": 0, "journal_hits": 0}
        try:
            try:
                out = self._search_incremental()
            except BaseException as e:
                # simonguard containment: a wedged backend / device OOM inside
                # the encode-once session is not fatal to the SEARCH — the
                # backend is quarantined (wedge) and the fresh-probe fallback
                # re-runs on the surviving backend, journal verdicts intact
                # (placements are backend-invariant). Anything non-containable
                # (deadline expiry, real bugs) propagates.
                cause = guard.containment_cause(e)
                if cause is None:
                    raise
                guard.count_failover(cause, "capacity_search")
                logging.getLogger("open_simulator_tpu").warning(
                    "capacity search contained a device failure (%s); falling "
                    "back to fresh-Simulator probes", cause)
                out = None
            if out is None:
                out = self._search_fresh()
        finally:
            # the journal holds an fd for crash-consistent appends during the
            # search only; its lookups keep serving from memory after close
            if self.journal is not None:
                self.journal.close()
        # registry mirror of the stats dict: search accounting survives the
        # planner object, so server /metrics and CLI snapshots report it
        obs.CAPACITY_SEARCHES.labels(path=str(self.stats.get("path"))).inc()
        obs.CAPACITY_ROUNDS.inc(int(self.stats.get("dispatches") or 0))
        return out

    # ----------------------------------------------- incremental fan-out ----

    def _search_incremental(self):
        """Encode-once search over a ProbeSession, or None when the session's
        equivalence gates reject the workload (the caller then runs the
        fresh-probe search). The answer itself is re-validated ABOVE this
        layer: the Applier's _plan runs one full fresh-Simulator simulation at
        n and falls back to the reference-style full search on divergence —
        the existing provable-equivalence guard, unchanged."""
        from ..simulator.probe import ProbeSession

        session = ProbeSession.try_build(
            self.base_nodes, self.new_node, self.pods,
            cluster_objects=self.cluster_objects, app_objects=self.app_objects,
            sched_config=self.sched_config, n_new=2, fanout=PROBE_FANOUT)
        if session is None:
            return None
        # the session's group encoding already holds the request totals: skip
        # lower_bound's per-pod host scan (measurable at 100k pods)
        lb = self.lower_bound(totals=session.batch_totals())
        self.stats.update(path="incremental", encode_s=session.encode_s,
                          encodes=session.encodes)
        if lb > MAX_AUTO_NODES:
            return False, MAX_AUTO_NODES, []
        m = max(lb, 1)

        def eval_many(cands):
            # every probe round re-checks the --deadline budget: a search that
            # cannot finish dies between dispatches, never mid-kernel
            check_deadline("capacity_search")
            out = {}
            # resumed-journal verdicts satisfy candidates without a dispatch
            todo = []
            for n in cands:
                hit = self._journal_lookup(n)
                if hit is not None:
                    out[n] = hit
                else:
                    todo.append(n)
            if not todo:
                return out
            session.ensure_capacity(max(todo))
            res = session.probe_many(todo)
            self.stats["probes"] += len(res)
            self.stats["dispatches"] += 1
            for n, (scheduled, total, u) in res.items():
                nf = total - scheduled
                ok = nf == 0 and self._envelope_ok(
                    u["cpu_used"], u["cpu_alloc"], u["mem_used"], u["mem_alloc"])
                out[n] = (ok, nf)
                # verdict journaled (fsync) BEFORE the next dispatch: a crash
                # loses at most the round in flight
                self._journal_record(n, ok, nf)
            return out

        # The arithmetic bound is frequently EXACT (homogeneous workloads), so
        # the first dispatch probes it alone — one lane, no fan-out waste; if
        # it passes, minimality is already proven (everything below lb fails).
        first = lb if lb > 0 else 0
        res = eval_many([first])
        ok, nf = res[first]
        if ok:
            return True, first, []
        hist: List[tuple] = [(first, nf)]
        lo_fail = first
        hi_ok = None
        # Doubling collapsed into fan-out rounds: round r grids (2^r m, 2^(r+1) m]
        # with interior points, so the first passing round already brackets
        # tightly.
        round_lo = first + 1
        round_hi = min(2 * m, MAX_AUTO_NODES)
        while hi_ok is None:
            if round_lo > round_hi:
                return False, MAX_AUTO_NODES, hist
            cands = _grid(round_lo, round_hi, PROBE_FANOUT)
            res = eval_many(cands)
            for n in cands:  # increasing; feasibility is monotone in n
                ok, nf = res[n]
                if ok:
                    hi_ok = n
                    break
                lo_fail = max(lo_fail, n)
                hist.append((n, nf))
            if hi_ok is not None:
                break
            # 4x capacity with no progress: remaining pods unfixable by nodes
            last_n, last_nf = hist[-1]
            for n1, nf1 in hist:
                if nf1 > 0 and last_n >= 4 * n1 and last_nf >= nf1:
                    return False, last_n, hist
            if round_hi >= MAX_AUTO_NODES:
                return False, MAX_AUTO_NODES, hist
            round_lo, round_hi = round_hi + 1, min(round_hi * 2, MAX_AUTO_NODES)
        # (PROBE_FANOUT+1)-ary refinement of (lo_fail, hi_ok]
        while hi_ok - lo_fail > 1:
            cands = _interior(lo_fail, hi_ok, PROBE_FANOUT)
            res = eval_many(cands)
            for n in cands:
                ok, _ = res[n]
                if ok:
                    hi_ok = n
                    break
                lo_fail = n
        return True, hi_ok, hist

    # ---------------------------------------------------- fresh fallback ----

    def _search_fresh(self):
        """The original fresh-Simulator probe loop: doubling from the lower
        bound, then binary refinement — one fresh probe per candidate."""
        for key, v in (("probes", 0), ("dispatches", 0), ("encode_s", 0.0),
                       ("encodes", 0)):
            self.stats.setdefault(key, v)
        self.stats["path"] = "fresh"

        def probe(n):
            check_deadline("capacity_search")  # per-candidate budget check
            hit = self._journal_lookup(n)
            if hit is not None:
                return hit
            self.stats["probes"] += 1
            self.stats["dispatches"] += 1
            ok, nf = self.probe(n)
            self._journal_record(n, ok, nf)
            return ok, nf

        lb = self.lower_bound()
        if lb == 0:
            ok, nf = probe(0)
            if ok:
                return True, 0, []
            lb = 1
        hist = []
        lo, hi = max(0, lb - 1), max(lb, 1)  # everything below lb provably fails
        while hi <= MAX_AUTO_NODES:
            ok, nf = probe(hi)
            if ok:
                break
            hist.append((hi, nf))
            # 4x capacity with no progress: remaining pods unfixable by nodes
            if len(hist) >= 3 and hist[-1][1] >= hist[-3][1] > 0:
                return False, hi, hist
            lo, hi = hi, hi * 2
        else:
            return False, MAX_AUTO_NODES, hist
        while lo + 1 < hi:
            mid = (lo + hi) // 2
            ok, _ = probe(mid)
            if ok:
                hi = mid
            else:
                lo = mid
        return True, hi, hist


@dataclass
class Options:
    simon_config: str = ""
    default_scheduler_config: str = ""
    use_greed: bool = False
    interactive: bool = False
    extended_resources: List[str] = field(default_factory=list)
    output_file: str = ""
    # wall-clock budget for the whole run (0 = unbounded): the capacity
    # search and every full simulation slice it via the Deadline contextvar
    deadline: float = 0.0
    # crash-consistent capacity-search journal (simonguard): probe verdicts
    # are fsync'd here and a re-run resumes, skipping completed probes; a
    # digest mismatch (different search options) is rejected loudly
    resume_journal: str = ""


class Applier:
    def __init__(self, opts: Options) -> None:
        self.opts = opts
        self.cfg: SimonConfig = parse_simon_config(opts.simon_config)
        validate_config(self.cfg, opts.default_scheduler_config)
        # parse --default-scheduler-config for real (GetAndSetSchedulerConfig,
        # pkg/simulator/utils.go:303-381): plugin enable/disable + score
        # weights; unsupported fields raise ConfigError here, loudly
        if opts.default_scheduler_config:
            from ..api.schedconfig import parse_scheduler_config

            self.sched_config = parse_scheduler_config(opts.default_scheduler_config)
        else:
            self.sched_config = None
        self.out: TextIO = sys.stdout

    # ------------------------------------------------------------------ inputs ----

    def _load_cluster(self) -> ResourceTypes:
        c = self.cfg.spec.cluster
        if c.kube_config:
            from ..simulator.live import create_cluster_resource_from_client

            return create_cluster_resource_from_client(c.kube_config)
        return load_cluster_from_directory(c.custom_cluster)

    def _load_apps(self) -> List[AppResource]:
        apps: List[AppResource] = []
        for app in self.cfg.spec.app_list:
            if app.chart:
                from ..chart.render import process_chart

                docs = process_chart(app.name, app.path)
                from ..utils.yamlio import bucket_objects

                rt = bucket_objects(docs)
            else:
                rt = load_resources_from_directory(app.path)
            apps.append(AppResource(name=app.name, resource=rt))
        return apps

    def _load_new_node(self) -> Optional[dict]:
        path = self.cfg.spec.new_node
        if not path:
            return None
        rt = load_resources_from_directory(path)
        if not rt.nodes:
            return None
        match_and_set_local_storage_annotation(rt.nodes, path)
        return rt.nodes[0]

    # ------------------------------------------------------------------- run ------

    def run(self) -> Optional[SimulateResult]:
        if self.opts.deadline > 0:
            with Deadline(self.opts.deadline):
                return self._run_with_output()
        return self._run_with_output()

    def _run_with_output(self) -> Optional[SimulateResult]:
        # The output file is opened (and closed) per run so a reused Applier never
        # writes to a closed stream; without --output-file, self.out stays stdout.
        if self.opts.output_file:
            prev = self.out
            with open(self.opts.output_file, "w") as f:
                self.out = f
                try:
                    return self._run()
                finally:
                    self.out = prev
        return self._run()

    def _run(self) -> Optional[SimulateResult]:
        cluster = self._load_cluster()
        apps = self._load_apps()
        if self.opts.interactive:
            apps = self._select_apps(apps)
        new_node = self._load_new_node()

        patch_funcs = []
        if self.opts.use_greed:
            from ..algo.queues import sort_greed

            def greed_patch(pods, _cluster=cluster):
                pods[:] = sort_greed(pods, _cluster.nodes)

            patch_funcs.append(greed_patch)

        result, n_added = self._plan(cluster, apps, new_node, patch_funcs)
        if result is None:
            return None

        self._println("Simulation success!")
        if n_added:
            self._println(f"(added {n_added} node(s) to make everything schedulable)")
        if len(result.backend_path) > 1:
            # no silent degradation: a mid-run failover is part of the report
            self._println("(degraded run: backend path "
                          + " -> ".join(result.backend_path) + ")")
        self.report(result.node_status, [a.name for a in apps])
        return result

    def _simulate_with(self, cluster, apps, new_node, n, patch_funcs) -> SimulateResult:
        check_deadline("simulate")  # full runs slice the --deadline budget too
        trial = cluster.copy()
        trial.nodes = list(trial.nodes) + new_fake_nodes(new_node, n)
        return simulate(trial, apps, patch_pod_funcs=patch_funcs,
                        sched_config=self.sched_config)

    def _plan(self, cluster, apps, new_node, patch_funcs):
        """Returns (result, nodes_added) or (None, 0) when the user exits / search
        fails. Interactive: the reference's survey loop. Non-interactive: auto-search
        the minimal node count — via CapacityPlanner probes when the workload
        qualifies (the answer is re-validated by one full simulation; any
        divergence falls back to the original loop), else the reference-style
        full-simulation doubling + binary search (apply.go:203-259)."""
        if self.opts.interactive:
            return self._plan_interactive(cluster, apps, new_node, patch_funcs)

        def ok(res: SimulateResult) -> bool:
            satisfied, _ = satisfy_resource_setting(res.node_status)
            return not res.unscheduled_pods and satisfied

        planner = CapacityPlanner.try_build(cluster, apps, new_node, patch_funcs,
                                            sched_config=self.sched_config)
        if self.opts.resume_journal:
            if planner is not None:
                # JournalMismatch propagates: a stale journal must stop the
                # run, not silently steer a different search
                planner.attach_journal(self.opts.resume_journal)
            else:
                self._println(
                    "note: --resume-journal ignored (workload does not "
                    "qualify for the probe search; full simulations are "
                    "not journaled)")
        if planner is not None:
            found, n, hist = planner.search()
            if found:
                res = self._simulate_with(cluster, apps, new_node, n, patch_funcs)
                if ok(res):
                    return res, n
                # probe/simulation divergence: fall back to the full search
            elif hist:
                # no-progress give-up: one full simulation at the last probe
                # reproduces the reference-style diagnostics without replaying
                # the whole search with full simulations
                res_hi = self._simulate_with(cluster, apps, new_node, n, patch_funcs)
                if ok(res_hi):
                    return res_hi, n  # divergence in the passing direction
                if res_hi.unscheduled_pods:
                    for up in res_hi.unscheduled_pods:
                        self._println(f"  {namespace_of(up.pod)}/{name_of(up.pod)}: {up.reason}")
                    self._println(
                        f"{len(res_hi.unscheduled_pods)} pod(s) still unschedulable "
                        f"after adding {n} nodes with no improvement; they cannot "
                        "be fixed by capacity"
                    )
                    return None, 0
                # probes said unschedulable but the full run disagrees on the
                # envelope only: fall back to the full search
            else:
                self._println(f"gave up after {MAX_AUTO_NODES} added nodes")
                return None, 0

        res = self._simulate_with(cluster, apps, new_node, 0, patch_funcs)
        if ok(res):
            return res, 0
        if new_node is None:
            for up in res.unscheduled_pods:
                self._println(f"  {namespace_of(up.pod)}/{name_of(up.pod)}: {up.reason}")
            self._println(
                f"{len(res.unscheduled_pods)} pod(s) unschedulable and no newNode "
                "spec configured; cannot add capacity"
            )
            return None, 0

        fails = {0: len(res.unscheduled_pods)}
        lo, hi, res_hi = 0, 1, None
        while hi <= MAX_AUTO_NODES:
            res_hi = self._simulate_with(cluster, apps, new_node, hi, patch_funcs)
            if ok(res_hi):
                break
            fails[hi] = len(res_hi.unscheduled_pods)
            # Give up when 4x capacity brought no progress: the remaining pods fail
            # for reasons new nodes cannot fix (bad selectors, impossible affinity).
            ref = fails.get(max(hi // 4, 0))
            if hi >= 4 and ref is not None and fails[hi] >= ref > 0:
                for up in res_hi.unscheduled_pods:
                    self._println(f"  {namespace_of(up.pod)}/{name_of(up.pod)}: {up.reason}")
                self._println(
                    f"{fails[hi]} pod(s) still unschedulable after adding {hi} "
                    "nodes with no improvement; they cannot be fixed by capacity"
                )
                return None, 0
            lo, hi = hi, hi * 2
        else:
            self._println(f"gave up after {MAX_AUTO_NODES} added nodes")
            return None, 0

        best_n, best = hi, res_hi
        while lo + 1 < hi:
            mid = (lo + hi) // 2
            res_mid = self._simulate_with(cluster, apps, new_node, mid, patch_funcs)
            if ok(res_mid):
                hi, best_n, best = mid, mid, res_mid
            else:
                lo = mid
        return best, best_n

    def _plan_interactive(self, cluster, apps, new_node, patch_funcs):
        n = 0
        res = self._simulate_with(cluster, apps, new_node, n, patch_funcs)
        while True:
            satisfied, reason = satisfy_resource_setting(res.node_status)
            if not res.unscheduled_pods and satisfied:
                return res, n
            if not res.unscheduled_pods:
                self._println(reason)
            msg = (
                f"there are still {len(res.unscheduled_pods)} pod(s) that can not be "
                f"scheduled when add {n} nodes, you can:"
            )
            choice = self._ask(
                msg,
                ["show error event of unscheduled pods", "add node(s)", "exit"],
            )
            if choice == 0:
                for i, up in enumerate(res.unscheduled_pods):
                    self._println(
                        f"{i:4d} {namespace_of(up.pod)}/{name_of(up.pod)}: {up.reason}"
                    )
                continue  # no re-simulation, like the reference's SurveyShowResults
            if choice == 1:
                try:
                    n = int(input("input node number: "))
                except (ValueError, EOFError):
                    n = 0
                res = self._simulate_with(cluster, apps, new_node, n, patch_funcs)
                continue
            return None, 0

    def _select_apps(self, apps: List[AppResource]) -> List[AppResource]:
        if not apps:
            return apps
        self._println("Confirm your apps (comma-separated indices, empty = all):")
        for i, a in enumerate(apps):
            self._println(f"  [{i}] {a.name}")
        try:
            line = input("> ").strip()
        except EOFError:
            return apps
        if not line:
            return apps
        picked = []
        for tok in line.split(","):
            tok = tok.strip()
            if tok.isdigit() and int(tok) < len(apps):
                picked.append(apps[int(tok)])
        return picked or apps

    def _ask(self, msg: str, options: List[str]) -> int:
        self._println(msg)
        for i, o in enumerate(options):
            self._println(f"  [{i}] {o}")
        try:
            line = input("> ").strip()
        except EOFError:
            return len(options) - 1  # exit
        return int(line) if line.isdigit() and int(line) < len(options) else 0

    # ----------------------------------------------------------------- report -----

    def _println(self, s: str = "") -> None:
        print(s, file=self.out)

    def report(self, node_statuses: List[NodeStatus], app_names: List[str]) -> None:
        ext = self.opts.extended_resources
        self._report_cluster(node_statuses, ext)
        self._report_apps(node_statuses, app_names)

    def _report_cluster(self, node_statuses: List[NodeStatus], ext: List[str]) -> None:
        self._println("Node Info")
        header = ["Node", "CPU Allocatable", "CPU Requests", "Memory Allocatable",
                  "Memory Requests", "Pod Count", "New Node"]
        rows = [header]
        for st in node_statuses:
            alloc = (st.node.get("status") or {}).get("allocatable") or {}
            cpu_alloc = parse_milli(alloc.get("cpu", 0))
            mem_alloc = parse_quantity(alloc.get("memory", 0))
            cpu_req = sum(pod_resource_requests(p).get("cpu", 0.0) for p in st.pods)
            mem_req = sum(pod_resource_requests(p).get("memory", 0.0) for p in st.pods)
            cpu_frac = int(cpu_req / cpu_alloc * 100) if cpu_alloc else 0
            mem_frac = int(mem_req / mem_alloc * 100) if mem_alloc else 0
            is_new = "√" if C.LabelNewNode in labels_of(st.node) else ""
            rows.append([
                name_of(st.node),
                _fmt_cpu(cpu_alloc),
                f"{_fmt_cpu(cpu_req)}({cpu_frac}%)",
                format_quantity(mem_alloc, binary=True),
                f"{format_quantity(mem_req, binary=True)}({mem_frac}%)",
                str(len(st.pods)),
                is_new,
            ])
        self._render_table(rows)
        self._println()
        if any("open-local" in e for e in ext):
            self._report_local_storage(node_statuses)
        if any("gpu" in e for e in ext):
            self._report_gpu(node_statuses)

    def _report_local_storage(self, node_statuses: List[NodeStatus]) -> None:
        self._println("Node Local Storage")
        rows = [["Node", "Storage Kind", "Storage Name", "Storage Allocatable",
                 "Storage Requests"]]
        for st in node_statuses:
            raw = annotations_of(st.node).get(C.AnnoNodeLocalStorage)
            if not raw:
                continue
            try:
                storage = NodeStorage.from_json(raw)
            except (json.JSONDecodeError, TypeError):
                continue
            for vg in storage.vgs:
                pct = int(vg.requested / vg.capacity * 100) if vg.capacity else 0
                rows.append([name_of(st.node), "VG", vg.name,
                             format_quantity(vg.capacity, binary=True),
                             f"{format_quantity(vg.requested, binary=True)}({pct}%)"])
            for dev in storage.devices:
                rows.append([name_of(st.node), f"Device({dev.media_type})",
                             dev.device,
                             format_quantity(dev.capacity, binary=True),
                             "used" if dev.is_allocated else "unused"])
        self._render_table(rows)
        self._println()

    def _report_gpu(self, node_statuses: List[NodeStatus]) -> None:
        from ..plugins.gpushare import gpu_report_rows, pod_gpu_index

        self._println("GPU Node Resource")
        rows = [["Node", "GPU ID", "GPU Request/Capacity", "Pod List"]]
        all_pods: List[dict] = []
        for st in node_statuses:
            rows.extend(gpu_report_rows(st.node, st.pods))
            all_pods.extend(st.pods)
        self._render_table(rows)
        self._println()
        # Pod -> Node map (apply.go:502-524)
        self._println("Pod -> Node Map")
        rows = [["Pod", "CPU Req", "Mem Req", "GPU Req", "Host Node", "GPU IDX"]]
        for p in sorted(all_pods, key=name_of):
            req = pod_resource_requests(p)
            rows.append([
                name_of(p),
                _fmt_cpu(req.get("cpu", 0.0)),
                format_quantity(req.get("memory", 0.0), binary=True),
                format_quantity(_pod_gpu_mem(p), binary=True),
                (p.get("spec") or {}).get("nodeName", ""),
                pod_gpu_index(p),
            ])
        self._render_table(rows)
        self._println()

    def _report_apps(self, node_statuses: List[NodeStatus], app_names: List[str]) -> None:
        self._println("App Info")
        rows = [["App", "Pod Count", "Nodes"]]
        for app in app_names:
            nodes: Dict[str, int] = {}
            count = 0
            for st in node_statuses:
                for p in st.pods:
                    if labels_of(p).get(C.LabelAppName) == app:
                        count += 1
                        nodes[name_of(st.node)] = nodes.get(name_of(st.node), 0) + 1
            spread = ", ".join(f"{k}({v})" for k, v in sorted(nodes.items()))
            rows.append([app, str(count), spread])
        self._render_table(rows)
        self._println()

    def _render_table(self, rows: List[List[str]]) -> None:
        if not rows:
            return
        widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
        for r in rows:
            self._println("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())


def _fmt_cpu(milli: float) -> str:
    """CPU quantities print in cores when whole, else milli (resource.Quantity.String)."""
    if milli % 1000 == 0:
        return str(int(milli // 1000))
    return f"{int(milli)}m"


def _pod_gpu_mem(pod: dict) -> float:
    """Total GPU memory request: per-GPU mem × count (apply.go:377-380)."""
    from ..plugins.gpushare import pod_gpu_count, pod_gpu_mem

    return float(pod_gpu_mem(pod) * pod_gpu_count(pod))


def satisfy_resource_setting(node_statuses: List[NodeStatus]):
    """satisfyResourceSetting (apply.go:689-775): average cpu/mem (and local-storage
    VG) occupancy must not exceed the MaxCPU/MaxMemory/MaxVG env percentages."""
    def env_pct(name: str) -> int:
        s = os.environ.get(name, "")
        if not s:
            return 100
        try:
            v = int(s)
        except ValueError:
            raise ConfigError(f"failed to convert env {name} to int: {s!r}")
        return v if 0 <= v <= 100 else 100

    maxcpu, maxmem, maxvg = env_pct(C.EnvMaxCPU), env_pct(C.EnvMaxMemory), env_pct(C.EnvMaxVG)

    cpu_alloc = mem_alloc = cpu_used = mem_used = 0.0
    vg_cap = vg_req = 0.0
    for st in node_statuses:
        alloc = (st.node.get("status") or {}).get("allocatable") or {}
        cpu_alloc += parse_milli(alloc.get("cpu", 0))
        mem_alloc += parse_quantity(alloc.get("memory", 0))
        for p in st.pods:
            req = pod_resource_requests(p)
            cpu_used += req.get("cpu", 0.0)
            mem_used += req.get("memory", 0.0)
        raw = annotations_of(st.node).get(C.AnnoNodeLocalStorage)
        if raw:
            try:
                storage = NodeStorage.from_json(raw)
            except (json.JSONDecodeError, TypeError):
                return False, f"error when unmarshal json data, node is {name_of(st.node)}"
            for vg in storage.vgs:
                vg_cap += vg.capacity
                vg_req += vg.requested

    cpu_rate = int(cpu_used / cpu_alloc * 100) if cpu_alloc else 0
    mem_rate = int(mem_used / mem_alloc * 100) if mem_alloc else 0
    if cpu_rate > maxcpu:
        return False, (f"the average occupancy rate({cpu_rate}%) of cpu goes beyond "
                       f"the env setting({maxcpu}%)")
    if mem_rate > maxmem:
        return False, (f"the average occupancy rate({mem_rate}%) of memory goes "
                       f"beyond the env setting({maxmem}%)")
    if vg_cap:
        vg_rate = int(vg_req / vg_cap * 100)
        if vg_rate > maxvg:
            return False, (f"the average occupancy rate({vg_rate}%) of vg goes "
                           f"beyond the env setting({maxvg}%)")
    return True, ""
