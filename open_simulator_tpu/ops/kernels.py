"""Device kernels: the batched scheduling engine.

One `lax.scan` step = one scheduleOne cycle of the vendored scheduler
(scheduler.go:441): filter every node in parallel, score the feasible ones with the
v1.20 default plugin set + the Simon bin-packing plugin, pick the winner, commit
capacity/counter updates into the carry. The serial pod order of the reference
(pkg/simulator/simulator.go:309-348 schedules one pod per channel handshake) is preserved
exactly — but each step is a fused [N]-wide tensor program on the accelerator instead of
a goroutine round-trip, and whole apps run as one compiled scan.

Plugin parity notes (all semantics cross-checked against the vendored sources):
- Filters: NodeResourcesFit, NodePorts (node_ports.go), NodeUnschedulable/TaintToleration/
  NodeAffinity/NodeName (pre-folded into the static group mask by the encoder),
  InterPodAffinity incl. the bootstrap special case and the existing-pods anti-affinity
  direction (filtering.go:226-280), PodTopologySpread DoNotSchedule with critical-path
  min over eligible domains (filtering.go:200-241).
- Scores (weights from algorithmprovider/registry.go:118-137 + SelectorSpread appended by
  applyFeatureGates:161-171): LeastAllocated(1), BalancedAllocation(1), ImageLocality(1),
  InterPodAffinity(1), NodeAffinity(1), NodePreferAvoidPods(10000), PodTopologySpread(2),
  TaintToleration(1), SelectorSpread(1), and Simon(1) with its min-max NormalizeScore
  (plugin/simon.go:76-101). Integer truncation points and the zero-initialized min/max
  quirks of the upstream normalizers are reproduced with explicit floors.
- selectHost tie-break: upstream picks uniformly at random among max-score nodes
  (generic_scheduler.go:188); we deterministically pick the lowest node index. This is
  the one intentional divergence (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .contracts import shaped
from .resources import CPU_I, MEM_I

class ScoreWeights(NamedTuple):
    """Per-score-plugin weights, default = the v1.20 provider registry
    (registry.go:118-137; Simon/OpenLocal/GpuShare default to weight 1 via the
    framework's zero->1 rule for enabled score plugins). Passed as a STATIC jit
    argument so custom --default-scheduler-config weights fold into the
    compiled program as constants; a disabled score plugin is weight 0."""

    least: float = 1.0       # NodeResourcesLeastAllocated
    balanced: float = 1.0    # NodeResourcesBalancedAllocation
    image: float = 1.0       # ImageLocality
    interpod: float = 1.0    # InterPodAffinity
    nodeaff: float = 1.0     # NodeAffinity
    avoid: float = 10000.0   # NodePreferAvoidPods
    pts: float = 2.0         # PodTopologySpread
    taint: float = 1.0       # TaintToleration
    ss: float = 1.0          # SelectorSpread
    simon: float = 1.0       # Simon bin-packing
    # Open-Gpu-Share's Score (open-gpu-share.go:86-110) is the same max-share
    # formula and min-max normalization as Simon's — its contribution is
    # exactly a second Simon term with its own weight.
    gpushare: float = 1.0
    openlocal: float = 1.0   # Open-Local


class FilterFlags(NamedTuple):
    """Enable flags for the filter plugins evaluated inside the kernel (the
    statically-folded ones — taints/unschedulable/node-affinity — are disabled
    at encode time instead; see Encoder.filter_disabled). STATIC jit args."""

    fit: bool = True         # NodeResourcesFit
    ports: bool = True       # NodePorts
    interpod: bool = True    # InterPodAffinity
    spread: bool = True      # PodTopologySpread


DEFAULT_WEIGHTS = ScoreWeights()
DEFAULT_FILTERS = FilterFlags()

_F32 = jnp.float32


class Tables(NamedTuple):
    """Scan-invariant device tables (see encode.BatchTables for field docs)."""

    alloc: jax.Array
    node_zone: jax.Array
    static_mask: jax.Array
    mask_taint: jax.Array
    mask_unsched: jax.Array
    mask_aff: jax.Array
    mask_extra: jax.Array  # [G, N] bool: out-of-tree plugin filters (static)
    simon_raw: jax.Array
    nodeaff_raw: jax.Array
    taint_raw: jax.Array
    avoid_raw: jax.Array
    image_raw: jax.Array
    extra_raw: jax.Array  # [G, N] f32: out-of-tree plugin score sum (static)
    grp_requests: jax.Array
    grp_nonzero: jax.Array
    grp_unknown: jax.Array
    grp_ports: jax.Array
    counter_dom: jax.Array
    counter_sel_match_g: jax.Array
    req_aff_t: jax.Array
    grp_aff_self: jax.Array
    req_anti_t: jax.Array
    pref_t: jax.Array
    pref_w: jax.Array
    dns_t: jax.Array
    dns_maxskew: jax.Array
    dns_self: jax.Array
    dns_edom: jax.Array
    sa_t: jax.Array
    sa_maxskew: jax.Array
    sa_self: jax.Array
    ss_t: jax.Array
    ss_skip: jax.Array
    carr_dom: jax.Array
    carr_anti_t: jax.Array  # [G, Ca] i32: anti-use carrier ids matching g (-1 pad)
    carr_w_t: jax.Array     # [G, Cw] i32: carrier ids with interpod weight for g
    carr_w_w: jax.Array     # [G, Cw] f32: those weights (hard=1 / signed pref)
    grp_carries: jax.Array
    # GPU-share (open-gpu-share.go Filter; per-device ledger in the carry)
    grp_gpu_mem: jax.Array   # [G] f32: per-GPU memory request (0 = no GPU)
    grp_gpu_num: jax.Array   # [G] f32: number of GPUs requested
    grp_gpu_pre: jax.Array   # [G] bool: valid pre-assigned gpu-index present
    grp_gpu_take: jax.Array  # [G, MAXDEV] f32: unit counts per device when pre-assigned
    dev_total: jax.Array     # [N, MAXDEV] f32: per-device total memory (0 = absent)
    # Open-Local storage (plugins/openlocal.py; VG/device state in the carry)
    grp_lvm_size: jax.Array   # [G, SL] f32: LVM volume sizes (0 = unused slot)
    grp_lvm_vg: jax.Array     # [G, SL] i32: VG name id (0 = unnamed → Binpack)
    grp_sdev_size: jax.Array  # [G, SD] f32: device volume sizes (ssd-asc then hdd-asc)
    grp_sdev_media: jax.Array  # [G, SD] i32: 1 hdd / 2 ssd (0 = unused)
    vg_cap: jax.Array         # [N, MAXVG] f32 (0 = absent VG)
    vg_nameid: jax.Array      # [N, MAXVG] i32
    sdev_cap: jax.Array       # [N, MAXSD] f32
    sdev_media: jax.Array     # [N, MAXSD] i32


class Carry(NamedTuple):
    """Mutable cluster state threaded through the scan."""

    requested: jax.Array    # [N, R] f32
    nonzero: jax.Array      # [N, 2] f32
    port_used: jax.Array    # [N, PORT+1] bool
    counter: jax.Array      # [T, D+1] f32
    carrier: jax.Array      # [Tc, D+1] f32
    dev_used: jax.Array     # [N, MAXDEV] f32: per-GPU-device used memory
    vg_req: jax.Array       # [N, MAXVG] f32: LVM volume-group requested bytes
    sdev_alloc: jax.Array   # [N, MAXSD] f32: 1.0 = exclusive device allocated


class SerialState(NamedTuple):
    """Scan-carry contract for schedule_group_serial's fused step: the ONLY
    state a single-group serial run can mutate. Leaf shapes/dtypes are fixed
    for the whole scan — simonlint's carry-contract rule holds every branch
    of the body to this declaration."""

    j: jax.Array       # [N] i32: per-node copies placed so far
    cnt: jax.Array     # [Sd, D+1] f32: live DoNotSchedule counter rows
    cnt_sa: jax.Array  # [Ss, D+1] f32: live ScheduleAnyway counter rows


def _flr(x):
    return jnp.floor(x)


@shaped(pernode="[N] f32", F="[N] bool", zones="[N] i32", ret="[N] f32")
def selector_spread_score(pernode, F, zones, Z: int, maxN=None):
    """SelectorSpread (selector_spread.go:104-160): per-node count score with
    2/3 zone blending, over the feasible set F. THE single source of this
    formula — scores() and the ss_live fused scan must stay bit-identical,
    since wave==serial parity rides on it. Returns the unfloored blend; the
    caller applies skip/has_ss gating and _flr. `maxN` lets scores() reuse
    its stacked-reduction maximum (same float by construction)."""
    if maxN is None:
        maxN = jnp.maximum(jnp.max(jnp.where(F, pernode, -jnp.inf)), 0.0)
    node_score = jnp.where(maxN > 0, 100.0 * (maxN - pernode) / maxN, 100.0)
    nz_count = jnp.where(F, pernode, 0.0)
    zone_sums = jnp.zeros((Z,), _F32).at[zones].add(nz_count)
    maxZ = jnp.max(zone_sums.at[0].set(0.0))
    have_zones = jnp.any(F & (zones > 0))
    zscore = jnp.where(maxZ > 0, 100.0 * (maxZ - zone_sums[zones]) / maxZ, 100.0)
    return jnp.where(have_zones & (zones > 0),
                     node_score * (1.0 / 3.0) + zscore * (2.0 / 3.0), node_score)


@shaped(cnt_sa="[Ss, N] f32", relevantF="[N] bool", dom_rows="[Ss, N] i32",
        svalid="[Ss] bool", maxskew="[Ss] f32", ret="[N] f32")
def schedule_anyway_score(cnt_sa, relevantF, dom_rows, svalid, maxskew, D: int):
    """PodTopologySpread ScheduleAnyway scoring (scoring.go:108-200) from the
    per-term per-node counts: ln(topology size + 2) weights, maxSkew - 1
    offsets, integer floor, then the plugin's (max + min - raw) * 100 / max
    normalization over the relevant feasible set. THE single source of this
    formula — scores() and the sa_live fused scan must stay bit-identical."""
    Ss = dom_rows.shape[0]
    marks = jnp.zeros((Ss, D + 1), _F32).at[
        jnp.arange(Ss)[:, None], dom_rows
    ].max(jnp.broadcast_to(relevantF.astype(_F32), dom_rows.shape))
    topo_size = jnp.sum(marks[:, :D], axis=1)
    tpw = jnp.log(topo_size + 2.0)
    contrib = cnt_sa * tpw[:, None] + (maxskew[:, None] - 1.0)
    sa_raw = _flr(jnp.sum(jnp.where(svalid[:, None], contrib, 0.0), axis=0))
    sa_max = jnp.maximum(jnp.max(jnp.where(relevantF, sa_raw, -jnp.inf)), 0.0)
    sa_min_raw = jnp.min(jnp.where(relevantF, sa_raw, jnp.inf))
    sa_min = jnp.where(jnp.isfinite(sa_min_raw), sa_min_raw, 0.0)
    return jnp.where(
        ~relevantF,
        0.0,
        jnp.where(sa_max > 0, _flr((sa_max + sa_min - sa_raw) * 100.0 / sa_max), 100.0),
    )


def carrier_rows_at(tb: Tables, cry: Carry, ids):
    """Selective carrier-row gather by static per-group slot ids (same idiom
    as counter_rows_at): returns per-node values [k, N]."""
    return jnp.take_along_axis(cry.carrier[ids], tb.carr_dom[ids], axis=1)


def counter_rows_at(tb: Tables, cry: Carry, ids):
    """Selectively gather counter rows by static slot indices: returns
    (rows [k, D+1], per-node values [k, N], key_present [k, N], dom [k, N]).
    THE shared idiom for every plugin that reads a handful of counters —
    never gather the full [T, N] table; T grows with every service/affinity
    selector."""
    rows = cry.counter[ids]                             # [k, D+1]
    dom = tb.counter_dom[ids]                           # [k, N]
    D = cry.counter.shape[1] - 1
    return rows, jnp.take_along_axis(rows, dom, axis=1), dom < D, dom


@shaped(g="[] i32", ret="[N] f32")
def interpod_raw(tb: Tables, cry: Carry, g):
    """InterPodAffinity raw score (scoring.go): incoming preferred terms plus
    existing pods' required (HardPodAffinityWeight=1) and preferred terms,
    via selective slot gathers. Single source for scores() and
    _wave_statics() — their serial-equality contract needs identical ip_raw
    floats."""
    pref_ids = tb.pref_t[g]
    pvalid = pref_ids >= 0
    pw = tb.pref_w[g]
    _, pref_at, _, _ = counter_rows_at(tb, cry, jnp.maximum(pref_ids, 0))
    ip_raw = jnp.sum(jnp.where(pvalid[:, None], pw[:, None] * pref_at, 0.0), axis=0)
    cw_ids = tb.carr_w_t[g]
    cw_valid = cw_ids >= 0
    cw_at = carrier_rows_at(tb, cry, jnp.maximum(cw_ids, 0))
    return ip_raw + jnp.sum(
        jnp.where(cw_valid[:, None], tb.carr_w_w[g][:, None] * cw_at, 0.0), axis=0)


def least_balanced(used_c, used_m, a_c, a_m):
    """NodeResourcesLeastAllocated (least_allocated.go:93-115, integer divisions
    floored) + NodeResourcesBalancedAllocation (balanced_allocation.go:96-120)
    for broadcast-compatible cpu/mem usage and allocatable arrays. The single
    source of these formulas for scores(), the wave score table, and the fused
    group-serial scan — their serial-equality proofs require floor-for-floor
    identical math."""
    def least_one(u, a):
        return jnp.where((a > 0) & (u <= a), _flr((a - u) * 100.0 / a), 0.0)

    least = _flr((least_one(used_c, a_c) + least_one(used_m, a_m)) / 2.0)
    cf = jnp.where(a_c > 0, used_c / a_c, 1.0)
    mf = jnp.where(a_m > 0, used_m / a_m, 1.0)
    balanced = jnp.where((cf >= 1.0) | (mf >= 1.0), 0.0,
                         _flr((1.0 - jnp.abs(cf - mf)) * 100.0))
    return least, balanced


@shaped(g="[] i32")
def storage_alloc(tb: Tables, cry: Carry, g):
    """Simulate Open-Local allocation of group g's volumes on EVERY node at once.

    Sequential semantics per volume slot (named-VG exact / unnamed Binpack
    tightest-fit; devices: smallest fitting free device of the media type), with a
    small unrolled loop over the (bucketed, tiny) slot axes. Returns a dict with:
    ok [N], lvm_add [N,V], dev_add [N,Dv] (one-hot allocations), raw score [N]
    (int LVM + int device, Binpack strategy), has_storage (scalar bool).

    Called from feasibility, scores, and commit with identical inputs — XLA's CSE
    collapses the three evaluations into one inside the fused scan step.
    """
    N, V = tb.vg_cap.shape
    Dv = tb.sdev_cap.shape[1]
    SL = tb.grp_lvm_size.shape[1]
    SD = tb.grp_sdev_size.shape[1]

    ok = jnp.ones(N, bool)
    lvm_add = jnp.zeros((N, V), _F32)
    for s in range(SL):
        size = tb.grp_lvm_size[g, s]
        nid = tb.grp_lvm_vg[g, s]
        active = size > 0
        free = tb.vg_cap - (cry.vg_req + lvm_add)
        named = nid > 0
        slot_named = tb.vg_nameid == nid
        named_fit = jnp.any(slot_named & (free >= size), axis=1)
        t_named = jnp.argmax(slot_named, axis=1)
        cand = (tb.vg_cap > 0) & (free >= size)
        un_fit = jnp.any(cand, axis=1)
        t_un = jnp.argmin(jnp.where(cand, free, jnp.inf), axis=1)
        fit = jnp.where(named, named_fit, un_fit)
        tgt = jnp.where(named, t_named, t_un)
        take = (jnp.arange(V)[None, :] == tgt[:, None]).astype(_F32)
        lvm_add = lvm_add + take * size * (fit & active)[:, None]
        ok &= fit | ~active

    # Device matching reproduces CheckExclusiveResourceMeetsPVCSize's single merge
    # pass (common.go:290-350) including its quirks: per-media COUNT pre-check;
    # a volume only fails the node when the scan reaches the LAST (largest) still-
    # free device and it is too small; if the last device was consumed earlier the
    # remaining volumes are silently dropped (reference bug kept for parity).
    dev_add = jnp.zeros((N, Dv), _F32)
    dev_acc = jnp.zeros(N, _F32)
    dev_units = jnp.float32(0.0)
    free_start = {}
    last_idx = {}
    for m in (1, 2):
        fs = (tb.sdev_media == m) & (cry.sdev_alloc < 0.5) & (tb.sdev_cap > 0)
        free_start[m] = fs
        caps = jnp.where(fs, tb.sdev_cap, -1.0)
        maxcap = jnp.max(caps, axis=1, keepdims=True)
        is_max = fs & (tb.sdev_cap == maxcap)
        # "last" in the ascending (capacity, index) sort = highest index among maxima
        last_idx[m] = jnp.argmax(is_max * (jnp.arange(Dv)[None, :] + 1), axis=1)
        n_free = jnp.sum(fs.astype(_F32), axis=1)
        n_vols = jnp.sum(
            ((tb.grp_sdev_media[g] == m) & (tb.grp_sdev_size[g] > 0)).astype(_F32)
        )
        ok &= (n_free >= n_vols) | (n_vols == 0)
    for s in range(SD):
        size = tb.grp_sdev_size[g, s]
        media = tb.grp_sdev_media[g, s]
        active = size > 0
        fs1 = jnp.where(media == 2, free_start[2], free_start[1])
        li = jnp.where(media == 2, last_idx[2], last_idx[1])
        free_now = fs1 & (dev_add < 0.5)
        fit_mask = free_now & (tb.sdev_cap >= size)
        fit = jnp.any(fit_mask, axis=1)
        tgt = jnp.argmin(jnp.where(fit_mask, tb.sdev_cap, jnp.inf), axis=1)
        take = (jnp.arange(Dv)[None, :] == tgt[:, None]).astype(_F32)
        take = take * (fit & active)[:, None]
        dev_add = dev_add + take
        last_free = jnp.take_along_axis(free_now, li[:, None], axis=1)[:, 0]
        ok &= ~(active & ~fit & last_free)
        chosen_cap = jnp.sum(take * tb.sdev_cap, axis=1)
        dev_acc += jnp.where(active & fit, size / jnp.maximum(chosen_cap, 1.0), 0.0)
        dev_units += jnp.where(active & fit, 1.0, 0.0)  # only assigned units score

    has_lvm = jnp.any(tb.grp_lvm_size[g] > 0)
    has_dev = jnp.any(tb.grp_sdev_size[g] > 0)
    has_storage = has_lvm | has_dev

    # ScoreLVM (Binpack): avg over used VGs of used/capacity × 10, int-truncated
    used_mask = lvm_add > 0
    vg_frac = jnp.where(used_mask & (tb.vg_cap > 0), lvm_add / jnp.maximum(tb.vg_cap, 1.0), 0.0)
    n_used = jnp.sum(used_mask.astype(_F32), axis=1)
    lvm_raw = jnp.where(
        has_lvm & (n_used > 0),
        _flr(jnp.sum(vg_frac, axis=1) / jnp.maximum(n_used, 1.0) * 10.0),
        0.0,
    )
    dev_raw = jnp.where(
        has_dev & (dev_units > 0), _flr(dev_acc / jnp.maximum(dev_units, 1.0) * 10.0), 0.0
    )
    return {
        "ok": ok | ~has_storage,
        "lvm_add": lvm_add,
        "dev_add": dev_add,
        "raw": lvm_raw + dev_raw,
        "has_storage": has_storage,
    }


@shaped(g="[] i32", forced="[] i32", valid="[] bool")
def feasibility(
    tb: Tables, cry: Carry, g, forced, valid,
    enable_gpu: bool = True, enable_storage: bool = True,
    include_dns: bool = True, filters: FilterFlags = DEFAULT_FILTERS,
) -> Tuple[jax.Array, dict]:
    """[N] feasibility mask for one pod, plus named per-stage masks for diagnostics.

    `enable_gpu`/`enable_storage` are STATIC: when a batch contains no gpu/storage
    demands the whole plugin subgraph is excluded at trace time (the inert tensor
    math would otherwise cost ~35% of each scan step). `include_dns=False` (also
    static) drops the PodTopologySpread DoNotSchedule filter — used by the live-
    spread wave path, which re-evaluates that filter against its own running
    counters each wave iteration (schedule_group_serial). `filters` (static)
    carries --default-scheduler-config per-plugin disables."""
    N = tb.alloc.shape[0]
    D = cry.counter.shape[1] - 1

    req = tb.grp_requests[g]
    smask = tb.static_mask[g]

    # NodeResourcesFit (noderesources/fit.go): only requested resources are checked.
    if filters.fit:
        eps = tb.alloc * 1e-6  # absorb f32 noise; never enough to overcommit
        new_req = cry.requested + req[None, :]
        fit_each = (new_req <= tb.alloc + eps) | (req[None, :] == 0)
        fit = jnp.all(fit_each, axis=1) & ~tb.grp_unknown[g]
    else:
        fit_each = jnp.ones((N, tb.alloc.shape[1]), bool)
        fit = jnp.ones(N, bool)

    # NodePorts
    if filters.ports:
        pids = tb.grp_ports[g]
        conflict = jnp.any(cry.port_used[:, pids] & (pids > 0)[None, :], axis=1)
    else:
        conflict = jnp.zeros(N, bool)

    # Counter rows are gathered SELECTIVELY by the static slot indices each
    # plugin carries ([A]/[B]/[Sd] small), never as the full [T, N] table —
    # T grows with every service/affinity selector in the cluster, and a
    # serial step paying T×N gathers for a handful of rows was the dominant
    # cost on service-heavy workloads.
    # InterPodAffinity: required affinity (filtering.go satisfyPodAffinity)
    if filters.interpod:
        aff_ids = tb.req_aff_t[g]
        avalid = aff_ids >= 0
        aids = jnp.maximum(aff_ids, 0)
        aff_rows, aff_at, aff_key, _ = counter_rows_at(tb, cry, aids)
        sat = (aff_key & (aff_at > 0)) | ~avalid[:, None]
        aff_all = jnp.all(sat, axis=0)
        has_aff = jnp.any(avalid)
        totals_aff = jnp.sum(aff_rows[:, :D], axis=1)                      # [A]
        total_aff = jnp.sum(jnp.where(avalid, totals_aff, 0.0))
        bootstrap = has_aff & (total_aff == 0.0) & tb.grp_aff_self[g]
        aff_ok = jnp.where(bootstrap, jnp.ones_like(aff_all), aff_all)

        # incoming required anti-affinity (satisfyPodAntiAffinity)
        anti_ids = tb.req_anti_t[g]
        bvalid = anti_ids >= 0
        bids = jnp.maximum(anti_ids, 0)
        _, anti_at, _, _ = counter_rows_at(tb, cry, bids)
        blocked_in = jnp.any((anti_at > 0) & bvalid[:, None], axis=0)

        # existing pods' required anti-affinity (satisfyExistingPodsAntiAffinity)
        ca_ids = tb.carr_anti_t[g]
        ca_valid = ca_ids >= 0
        ca_at = carrier_rows_at(tb, cry, jnp.maximum(ca_ids, 0))
        blocked_ex = jnp.any((ca_at > 0) & ca_valid[:, None], axis=0)
    else:
        aff_ok = jnp.ones(N, bool)
        blocked_in = jnp.zeros(N, bool)
        blocked_ex = jnp.zeros(N, bool)

    # PodTopologySpread DoNotSchedule (filtering.go Filter)
    if include_dns and filters.spread:
        dns_ids = tb.dns_t[g]
        dvalid = dns_ids >= 0
        dids = jnp.maximum(dns_ids, 0)
        edom = tb.dns_edom[g]                                              # [Sd, D+1]
        cdom, dns_at, dns_key, _ = counter_rows_at(tb, cry, dids)
        min_cnt = jnp.min(jnp.where(edom, cdom, jnp.inf), axis=1)
        min_cnt = jnp.where(jnp.isfinite(min_cnt), min_cnt, 0.0)
        skew = dns_at + tb.dns_self[g][:, None] - min_cnt[:, None]
        dns_ok_each = dns_key & (skew <= tb.dns_maxskew[g][:, None])
        dns_ok = jnp.all(dns_ok_each | ~dvalid[:, None], axis=0)
    else:
        dns_ok = jnp.ones(N, bool)

    # Open-Gpu-Share Filter (open-gpu-share.go:51-81): node total memory must cover
    # the per-GPU request AND the devices must fit all requested units. A device can
    # host multiple units (two-pointer greedy packs units onto one GPU), so the
    # feasibility condition is sum(floor(idle/mem)) >= num.
    if enable_gpu:
        gmem = tb.grp_gpu_mem[g]
        gnum = tb.grp_gpu_num[g]
        has_gpu = gmem > 0
        safe_mem = jnp.maximum(gmem, 1.0)
        gidle = tb.dev_total - cry.dev_used                                # [N, MAXDEV]
        gunits = jnp.where(tb.dev_total > 0, jnp.floor(gidle / safe_mem), 0.0)
        gunits = jnp.maximum(gunits, 0.0)
        node_gpu_total = jnp.sum(tb.dev_total, axis=1)
        gpu_fit = (node_gpu_total >= gmem) & (jnp.sum(gunits, axis=1) >= gnum) & (gnum > 0)
        # pre-assigned gpu-index: AllocateGpuId returns the id without checking
        # device fit (gpunodeinfo.go:247-253); only the node-total check and
        # device existence apply.
        gpu_pre_fit = (node_gpu_total >= gmem) & (gnum > 0) & jnp.any(tb.dev_total > 0, axis=1)
        gpu_fit = jnp.where(tb.grp_gpu_pre[g], gpu_pre_fit, gpu_fit)
        gpu_ok = jnp.where(has_gpu, gpu_fit, jnp.ones_like(gpu_fit))
    else:
        gpu_ok = jnp.ones(N, bool)

    # Open-Local Filter (open-local.go:51-92)
    if enable_storage:
        storage_ok = storage_alloc(tb, cry, g)["ok"]
    else:
        storage_ok = jnp.ones(N, bool)

    feasible = (smask & fit & ~conflict & aff_ok & ~blocked_in & ~blocked_ex
                & dns_ok & gpu_ok & storage_ok)
    feasible &= valid
    iota = jnp.arange(N)
    feasible = jnp.where(forced >= 0, feasible & (iota == forced), feasible)

    stages = {
        "static": smask,
        "taint": tb.mask_taint[g],
        "unsched": tb.mask_unsched[g],
        "affinity": tb.mask_aff[g],
        "extra": tb.mask_extra[g],
        "fit": fit,
        "fit_each": fit_each,
        "ports": ~conflict,
        "pod_affinity": aff_ok,
        "pod_anti": ~(blocked_in | blocked_ex),
        "spread": dns_ok,
        "gpu": gpu_ok,
        "storage": storage_ok,
    }
    return feasible, stages


@shaped(g="[] i32", feasible="[N] bool", ret="[N] f32")
def scores(
    tb: Tables, cry: Carry, g, feasible, n_zones: int, enable_storage: bool = True,
    w: ScoreWeights = DEFAULT_WEIGHTS,
) -> jax.Array:
    """Weighted sum of all normalized plugin scores over the feasible set ([N] f32).
    `w` is STATIC (--default-scheduler-config weights fold in as constants)."""
    F = feasible
    alloc_cm = tb.alloc[:, (CPU_I, MEM_I)]
    used = cry.nonzero + tb.grp_nonzero[g][None, :]
    least, balanced = least_balanced(used[:, 0], used[:, 1], alloc_cm[:, 0], alloc_cm[:, 1])

    simon_s = _flr(100.0 * tb.simon_raw[g])
    na_raw = tb.nodeaff_raw[g]
    t_raw = tb.taint_raw[g]

    # InterPodAffinity raw (scoring.go): incoming preferred terms + existing pods'
    # required (HardPodAffinityWeight=1) and preferred terms. Counter AND
    # carrier rows are gathered selectively by per-group static slot indices.
    ip_raw = interpod_raw(tb, cry, g)

    ss_id = tb.ss_t[g]
    has_ss = ss_id >= 0
    ss_idx = jnp.maximum(ss_id, 0)
    pernode = counter_rows_at(tb, cry, ss_idx[None])[1][0]

    # All F-masked normalizer extrema in TWO stacked reductions (each reduction
    # is a separate pass per scan step; floats identical to separate reductions)
    maxes = jnp.max(jnp.where(F[None, :],
                              jnp.stack([simon_s, na_raw, t_raw, ip_raw, pernode]),
                              -jnp.inf), axis=1)
    mins = jnp.min(jnp.where(F[None, :], jnp.stack([simon_s, ip_raw]), jnp.inf),
                   axis=1)

    # Simon max-share + min-max normalize (plugin/simon.go:45-101)
    hi, lo = maxes[0], mins[0]
    rng = hi - lo
    simon = jnp.where((rng > 0) & jnp.isfinite(rng), _flr((simon_s - lo) * 100.0 / rng), 0.0)

    # NodeAffinity preferred (helper.DefaultNormalizeScore, reverse=false)
    na_max = jnp.maximum(maxes[1], 0.0)
    nodeaff = jnp.where(na_max > 0, _flr(na_raw * 100.0 / na_max), 0.0)

    # TaintToleration (DefaultNormalizeScore reverse=true: all-100 when max==0)
    t_max = jnp.maximum(maxes[2], 0.0)
    taint = jnp.where(t_max > 0, 100.0 - _flr(t_raw * 100.0 / t_max), 100.0)

    # InterPodAffinity normalize: zero-initialized min/max (scoring.go)
    ip_max = jnp.maximum(maxes[3], 0.0)
    ip_min = jnp.minimum(mins[1], 0.0)
    ip_rng = ip_max - ip_min
    interpod = jnp.where(ip_rng > 0, _flr(100.0 * (ip_raw - ip_min) / ip_rng), 0.0)

    # SelectorSpread: shared single-source formula (zone sums over feasible
    # nodes only — NormalizeScore iterates scored nodes)
    blended = selector_spread_score(pernode, F, tb.node_zone, max(2, n_zones),
                                    maxN=jnp.maximum(maxes[4], 0.0))
    selector_spread = jnp.where(
        tb.ss_skip[g], 0.0, jnp.where(has_ss, _flr(blended), 100.0)
    )

    # PodTopologySpread ScheduleAnyway scoring: shared single-source formula
    D = cry.counter.shape[1] - 1
    sa_ids = tb.sa_t[g]
    svalid = sa_ids >= 0
    sidx = jnp.maximum(sa_ids, 0)
    _, sa_at, sa_key, sa_dom = counter_rows_at(tb, cry, sidx)
    ignored = jnp.any(svalid[:, None] & ~sa_key, axis=0)
    relevantF = F & ~ignored
    pts = schedule_anyway_score(sa_at, relevantF, sa_dom,
                                svalid, tb.sa_maxskew[g], D)

    # Open-Local Score (open-local.go:94-172): Binpack LVM + device ints, then the
    # plugin's own min-max NormalizeScore. Pods without volumes raw-score 0 on
    # every node → constant → normalizes to 0 (inert).
    if enable_storage:
        st = storage_alloc(tb, cry, g)
        st_raw = st["raw"]
        st_hi = jnp.maximum(jnp.max(jnp.where(F, st_raw, -jnp.inf)), 0.0)
        st_lo_raw = jnp.min(jnp.where(F, st_raw, jnp.inf))
        st_lo = jnp.where(jnp.isfinite(st_lo_raw), st_lo_raw, 0.0)
        st_rng = st_hi - st_lo
        openlocal = jnp.where(
            st["has_storage"] & (st_rng > 0), _flr((st_raw - st_lo) * 100.0 / st_rng), 0.0
        )
    else:
        openlocal = 0.0

    total = (
        w.least * least
        + w.balanced * balanced
        + w.openlocal * openlocal
        + (w.simon + w.gpushare) * simon  # Open-Gpu-Share Score ≡ Simon Score
        + w.nodeaff * nodeaff
        + w.taint * taint
        + w.interpod * interpod
        + w.ss * selector_spread
        + w.pts * pts
        + w.avoid * tb.avoid_raw[g]
        + w.image * tb.image_raw[g]
        + tb.extra_raw[g]  # out-of-tree plugins, pre-weighted at encode time
    )
    return total


@shaped(g="[] i32", choice="[] i32", do="[] bool")
def commit(
    tb: Tables, cry: Carry, g, choice, do,
    enable_gpu: bool = True, enable_storage: bool = True,
) -> Carry:
    """Apply one placement to the carry (the Reserve+Bind of the cycle)."""
    T = cry.counter.shape[0]
    Tc = cry.carrier.shape[0]
    D = cry.counter.shape[1] - 1
    c = jnp.maximum(choice, 0)
    dof = do.astype(_F32)

    requested = cry.requested.at[c].add(tb.grp_requests[g] * dof)
    nonzero = cry.nonzero.at[c].add(tb.grp_nonzero[g] * dof)
    pids = tb.grp_ports[g]
    port_used = cry.port_used.at[c, pids].max((pids > 0) & do)

    dom_col = tb.counter_dom[:, c]
    inc = tb.counter_sel_match_g[:, g].astype(_F32) * (dom_col < D) * dof
    counter = cry.counter.at[jnp.arange(T), dom_col].add(inc)

    cdom_col = tb.carr_dom[:, c]
    cinc = tb.grp_carries[g] * (cdom_col < D) * dof
    carrier = cry.carrier.at[jnp.arange(Tc), cdom_col].add(cinc)

    # GPU device allocation (AllocateGpuId, gpunodeinfo.go:232-290): tightest-fit
    # for a single GPU; in-order greedy (multiple units may pack onto one device)
    # for multi-GPU. Mirrored exactly by the host ledger in plugins/gpushare.py.
    if enable_gpu:
        gmem = tb.grp_gpu_mem[g]
        gnum = tb.grp_gpu_num[g]
        safe_mem = jnp.maximum(gmem, 1.0)
        dev_total_c = tb.dev_total[c]                               # [MAXDEV]
        idle_c = dev_total_c - cry.dev_used[c]
        units_c = jnp.maximum(jnp.where(dev_total_c > 0, jnp.floor(idle_c / safe_mem), 0.0), 0.0)
        # multi-GPU: first `gnum` units in device order
        cum = jnp.cumsum(units_c)
        take_multi = jnp.clip(gnum - (cum - units_c), 0.0, units_c)
        # single GPU: lowest-index tightest fit
        fit_dev = (idle_c >= gmem) & (dev_total_c > 0)
        cand = jnp.argmin(jnp.where(fit_dev, idle_c, jnp.inf))
        take_one = (jnp.arange(idle_c.shape[0]) == cand).astype(_F32)
        take = jnp.where(gnum == 1, take_one, take_multi)
        # pre-assigned ids charge exactly the annotated devices (host add_pod)
        take = jnp.where(tb.grp_gpu_pre[g], tb.grp_gpu_take[g], take)
        gdo = dof * (gmem > 0)
        dev_used = cry.dev_used.at[c].add(take * gmem * gdo)
    else:
        dev_used = cry.dev_used

    # Open-Local Bind: bump VG requested, mark devices allocated (open-local.go:215-250)
    if enable_storage:
        st = storage_alloc(tb, cry, g)
        sdo = dof * st["has_storage"].astype(_F32)
        vg_req = cry.vg_req.at[c].add(st["lvm_add"][c] * sdo)
        sdev_alloc = cry.sdev_alloc.at[c].add(st["dev_add"][c] * sdo)
    else:
        vg_req, sdev_alloc = cry.vg_req, cry.sdev_alloc

    return Carry(requested, nonzero, port_used, counter, carrier, dev_used,
                 vg_req, sdev_alloc)


def _step(tb: Tables, cry: Carry, xs, n_zones: int, enable_gpu: bool, enable_storage: bool,
          w: ScoreWeights = DEFAULT_WEIGHTS, filters: FilterFlags = DEFAULT_FILTERS):
    g, forced, valid = xs
    feasible, _ = feasibility(tb, cry, g, forced, valid, enable_gpu, enable_storage,
                              filters=filters)
    any_f = jnp.any(feasible)
    sc = scores(tb, cry, g, feasible, n_zones, enable_storage, w=w)
    masked = jnp.where(feasible, sc, -jnp.inf)
    choice = jnp.argmax(masked).astype(jnp.int32)  # first max → lowest node index
    choice = jnp.where(any_f, choice, jnp.int32(-1))
    new_cry = commit(tb, cry, g, choice, any_f, enable_gpu, enable_storage)
    return new_cry, choice


# Module-level jit so repeated diagnostic calls hit the compile cache.
feasibility_jit = jax.jit(
    feasibility,
    static_argnames=("enable_gpu", "enable_storage", "include_dns", "filters"),
)


# ------------------------------------------------------------------ wave kernel -------
#
# A run of identical pods (one scheduling group) whose only self-interaction is
# capacity — no storage state, no spread terms, no selector-spread, and no
# affinity/anti-affinity term matching the group itself (hostname-topology
# self-anti-affinity and host ports allowed: each is exactly a per-node
# capacity-1 clamp, with the aggregate commit claiming the port bits) — can be
# committed in *waves* while reproducing the serial one-pod-per-step process
# bit-for-bit. The engine proves eligibility on the host
# (Simulator._wave_eligibility); this kernel proves each wave equals that many
# serial argmax picks:
#
#   * With per-node placement counts j fixed, node n's score is
#     static(n) + least/balanced(usage_n + j_n·req) + norm(F) where every
#     normalization term (Simon/NodeAffinity/TaintToleration/InterPodAffinity
#     min-max) depends only on the feasible SET F — not on j directly. So the
#     score of the (k+1)-th copy on node n is a closed form in k: a score TABLE
#     s[n, k], k < B, computable without placing anything.
#   * Serial scheduling of this group is greedy selection over per-node "heads":
#     repeatedly take max_n s[n, j_n] under the deterministic tie-break (lowest
#     node index — _step's first-max argmax). When each node's score column is
#     non-increasing in k, the greedy's first m picks are EXACTLY the m largest
#     table entries under the key (score desc, node index asc), each node
#     consuming a prefix of its column — i.e. one stable sort of the flattened
#     table schedules up to N·B pods at once. Non-monotone columns (possible:
#     BalancedAllocation can rise as usage evens out) are masked past the first
#     violation and simply defer to the next iteration.
#   * Normalizers stay valid only while the feasible set F is unchanged, and F
#     changes exactly when a node exhausts its capacity. A node's capacity-
#     exhausting entry may therefore be taken only as the LAST pick of a wave —
#     unless removing all exhausted nodes provably leaves every normalizer value
#     unchanged (min/max over a shrinking set is monotone, so end-equality
#     implies invariance throughout), in which case the wave runs to m.
#
# Each while-loop iteration costs one [N,B] elementwise table + an O(NB log NB)
# sort — and typically places min(m, N·B) pods, collapsing the 1-pod-per-scan-
# step bottleneck that capped round 1 at ~15k pods/s (simulator.go:309-348 is
# the serial loop being replaced at scale).

WAVE_BLOCK = 64  # B: max score-table depth = max copies per node per wave iteration


def wave_block_for(m: int, n: int) -> int:
    """Static score-table depth for an m-pod wave over n nodes: a pow2 in
    [8, WAVE_BLOCK] covering ~8× the mean per-node take, so a 1000-pod segment
    over 5000 nodes sorts an [N, 8] table instead of [N, 64] (the sort is the
    wave's dominant cost) while a 100k-pod headline still gets full depth.
    Pow2 bucketing keeps the number of distinct compiled wave kernels small."""
    b = 8
    target = (8 * m + max(n, 1) - 1) // max(n, 1)
    while b < min(WAVE_BLOCK, target):
        b *= 2
    return b


def _wave_statics(tb: Tables, cry: Carry, g, w: ScoreWeights = DEFAULT_WEIGHTS):
    """Per-segment constants: ip_raw (counters can't change during the wave) and
    the static score vectors, exactly as scores() computes them. The stacked
    forms let _wave_norms run as TWO masked reductions instead of six — inside
    the group-serial scan each reduction is a separate pass over [N], so this
    is a per-scheduled-pod cost."""
    ip_raw = interpod_raw(tb, cry, g)
    simon_s = _flr(100.0 * tb.simon_raw[g])
    na_raw = tb.nodeaff_raw[g]
    t_raw = tb.taint_raw[g]
    return {
        "ip_raw": ip_raw,
        "simon_s": simon_s,
        "na_raw": na_raw,
        "t_raw": t_raw,
        "max_stack": jnp.stack([simon_s, na_raw, t_raw, ip_raw]),   # [4, N]
        "min_stack": jnp.stack([simon_s, ip_raw]),                  # [2, N]
        "static": (w.avoid * tb.avoid_raw[g] + w.image * tb.image_raw[g]
                   + tb.extra_raw[g]),
    }


def _wave_norms(st: dict, F):
    """The feasible-set-dependent normalizer values (must match scores() —
    the stacked reductions produce the same floats as six separate ones)."""
    maxes = jnp.max(jnp.where(F[None, :], st["max_stack"], -jnp.inf), axis=1)
    mins = jnp.min(jnp.where(F[None, :], st["min_stack"], jnp.inf), axis=1)
    simon_hi = maxes[0]
    simon_lo = mins[0]
    na_max = jnp.maximum(maxes[1], 0.0)
    t_max = jnp.maximum(maxes[2], 0.0)
    ip_max = jnp.maximum(maxes[3], 0.0)
    ip_min = jnp.minimum(mins[1], 0.0)
    return (simon_hi, simon_lo, na_max, t_max, ip_max, ip_min)


def _wave_score_table(tb: Tables, cry: Carry, st: dict, norms, g, j,
                      w: ScoreWeights = DEFAULT_WEIGHTS, block: int = WAVE_BLOCK):
    """[N, B+1] score table: entry (n, k) = score of placing the (j_n+k+1)-th copy
    of group g on node n given current usage. Formulas mirror scores() term by
    term; the constant-on-F plugins (SelectorSpread=100, PodTopologySpread=100,
    OpenLocal=0) are dropped — a uniform shift never changes the ordering the
    wave consumes."""
    simon_hi, simon_lo, na_max, t_max, ip_max, ip_min = norms
    B = block + 1  # one extra column: the exact first-hidden-entry bound
    copies = j.astype(_F32)[:, None, None] + jnp.arange(1, B + 1, dtype=_F32)[None, :, None]
    alloc_cm = tb.alloc[:, (CPU_I, MEM_I)]                            # [N, 2]
    used = cry.nonzero[:, None, :] + tb.grp_nonzero[g][None, None, :] * copies  # [N,B,2]
    least, balanced = least_balanced(
        used[:, :, 0], used[:, :, 1], alloc_cm[:, None, 0], alloc_cm[:, None, 1])

    rng = simon_hi - simon_lo
    simon = jnp.where((rng > 0) & jnp.isfinite(rng),
                      _flr((st["simon_s"] - simon_lo) * 100.0 / rng), 0.0)
    nodeaff = jnp.where(na_max > 0, _flr(st["na_raw"] * 100.0 / na_max), 0.0)
    taint = jnp.where(t_max > 0, 100.0 - _flr(st["t_raw"] * 100.0 / t_max), 100.0)
    ip_rng = ip_max - ip_min
    interpod = jnp.where(ip_rng > 0, _flr(100.0 * (st["ip_raw"] - ip_min) / ip_rng), 0.0)
    static_n = ((w.simon + w.gpushare) * simon + w.nodeaff * nodeaff
                + w.taint * taint + w.interpod * interpod + st["static"])
    return w.least * least + w.balanced * balanced + static_n[:, None]


@shaped(g="[] i32", cap1="[] bool", ret="[N] i32")
def _wave_capacity(tb: Tables, cry: Carry, g, cap1):
    """[N] i32: how many MORE copies of group g each node can take, from the
    closed-form NodeResourcesFit bound (same eps slack as feasibility())."""
    req = tb.grp_requests[g]
    eps = tb.alloc * 1e-6
    room = tb.alloc + eps - cry.requested
    per_res = jnp.where(req[None, :] > 0, jnp.floor(room / jnp.maximum(req[None, :], 1e-30)), jnp.inf)
    cap = jnp.clip(jnp.min(per_res, axis=1), 0.0, 2_147_483_000.0).astype(jnp.int32)
    return jnp.where(cap1, jnp.minimum(cap, 1), cap)


def _wave_gpu_params(tb: Tables, g):
    gmem = tb.grp_gpu_mem[g]
    gnum = jnp.maximum(tb.grp_gpu_num[g], 1.0)
    safe_mem = jnp.maximum(gmem, 1.0)
    return gmem, gnum, safe_mem


def _gpu_capacity(tb: Tables, cry: Carry, g, capacity):
    """Clamp per-node copy capacity by GPU units. Depletion is exactly
    unit-countable: every copy consumes `num` device-units and floor(idle/mem)
    per device is invariant under any single-unit take, so capacity is the
    closed form floor(total_units / num)."""
    gmem, gnum, safe_mem = _wave_gpu_params(tb, g)
    gidle0 = tb.dev_total - cry.dev_used
    gunits0 = jnp.maximum(
        jnp.where(tb.dev_total > 0, jnp.floor(gidle0 / safe_mem), 0.0), 0.0)
    gpu_cap = jnp.floor(jnp.sum(gunits0, axis=1) / gnum).astype(jnp.int32)
    return jnp.where(gmem > 0, jnp.minimum(capacity, gpu_cap), capacity)


def _aggregate_commit(tb: Tables, cry: Carry, g, j, gpu_live: bool) -> Carry:
    """The sum of `sum(j)` serial commit() calls for group g (j = per-node
    placement counts). With gpu_live, replays commit()'s per-copy device
    allocation (tightest-fit / in-order greedy, gpunodeinfo.go:232-290) one
    copy per step for every node in parallel, so the carry's per-device ledger
    matches the serial path bit for bit (j is small: bounded by GPU units)."""
    jf = j.astype(_F32)
    T = cry.counter.shape[0]
    Tc = cry.carrier.shape[0]
    D = cry.counter.shape[1] - 1
    requested = cry.requested + tb.grp_requests[g][None, :] * jf[:, None]
    nonzero = cry.nonzero + tb.grp_nonzero[g][None, :] * jf[:, None]
    # host ports: a placed copy claims the group's port ids on its node (the
    # serial commit's port_used writes). With NodePorts enabled, ports groups
    # ride cap1 so j <= 1; with it disabled j may exceed 1 and the bits —
    # idempotent — are never read.
    pids = tb.grp_ports[g]
    port_used = cry.port_used.at[:, pids].max(
        ((pids > 0)[None, :]) & (j > 0)[:, None])
    cinc = tb.counter_sel_match_g[:, g, None].astype(_F32) * (tb.counter_dom < D) * jf[None, :]
    counter = cry.counter.at[jnp.arange(T)[:, None], tb.counter_dom].add(cinc)
    rinc = tb.grp_carries[g][:, None] * (tb.carr_dom < D) * jf[None, :]
    carrier = cry.carrier.at[jnp.arange(Tc)[:, None], tb.carr_dom].add(rinc)
    dev_used = cry.dev_used
    if gpu_live:
        gmem, gnum, safe_mem = _wave_gpu_params(tb, g)

        def gpu_step(state):
            used, rem = state
            idle = tb.dev_total - used
            units = jnp.maximum(
                jnp.where(tb.dev_total > 0, jnp.floor(idle / safe_mem), 0.0), 0.0)
            cum = jnp.cumsum(units, axis=1)
            take_multi = jnp.clip(gnum - (cum - units), 0.0, units)
            fit_dev = (idle >= gmem) & (tb.dev_total > 0)
            cand = jnp.argmin(jnp.where(fit_dev, idle, jnp.inf), axis=1)
            take_one = (jnp.arange(tb.dev_total.shape[1])[None, :] == cand[:, None]).astype(_F32)
            take = jnp.where(tb.grp_gpu_num[g] == 1, take_one, take_multi)
            do = (rem > 0).astype(_F32)
            return used + take * gmem * do[:, None], rem - (rem > 0).astype(rem.dtype)

        dev_used, _ = jax.lax.while_loop(
            lambda s: jnp.any(s[1] > 0), gpu_step,
            (dev_used, jnp.where(gmem > 0, j, 0)))
    return Carry(requested, nonzero, port_used, counter, carrier,
                 dev_used, cry.vg_req, cry.sdev_alloc)



def _wave_candidates(tb: Tables, cry: Carry, st: dict, g, j, avail, F,
                     w: ScoreWeights, B: int, iota_n):
    """Shared wave-iteration front half: normalizers for the current feasible
    set, the [N, B+1] score table, the usable-entry mask (capacity, monotone
    prefix, hidden-continuation guard — see schedule_wave's body comments for
    the exactness argument), and the flattened stable sort. Single source for
    schedule_wave and schedule_spread_wave; the callers differ only in how
    much of the sorted order they may take. Returns
    (norms, table, idx_srt, ex_srt, flat_s)."""
    N = tb.alloc.shape[0]
    norms = _wave_norms(st, F)
    table_ext = _wave_score_table(tb, cry, st, norms, g, j, w, B)  # [N, B+1]
    table = table_ext[:, :B]
    ks = jnp.arange(B, dtype=jnp.int32)[None, :]
    in_cap = ks < avail[:, None]
    mono = jnp.cumprod(
        jnp.concatenate(
            [jnp.ones((N, 1), jnp.int32),
             (table[:, 1:] <= table[:, :-1]).astype(jnp.int32)], axis=1),
        axis=1) > 0
    usable = in_cap & mono & F[:, None]

    # hidden-continuation guard: an entry is takeable only if its key
    # (score desc, index asc) strictly beats every OTHER node's first hidden
    # entry (beyond depth B or past a monotonicity break)
    first_bad = jnp.min(jnp.where(mono, B, ks), axis=1)
    k_hid = jnp.minimum(first_bad, B)
    has_hidden = (k_hid < avail) & F
    bound = jnp.where(
        has_hidden,
        jnp.take_along_axis(table_ext, k_hid[:, None], axis=1)[:, 0],
        -jnp.inf,
    )
    b1 = jnp.max(bound)
    i1 = jnp.argmax(bound)  # first max = lowest index among score ties
    bound2 = bound.at[i1].set(-jnp.inf)
    b2 = jnp.max(bound2)
    i2 = jnp.argmax(bound2)
    cut_s = jnp.where(iota_n == i1, b2, b1)
    cut_i = jnp.where(iota_n == i1, i2, i1).astype(jnp.int32)
    beats = (table > cut_s[:, None]) | (
        (table == cut_s[:, None]) & (iota_n[:, None] < cut_i[:, None])
    )
    usable &= beats

    flat_s = jnp.where(usable, table, -jnp.inf).reshape(-1)
    flat_idx = jnp.broadcast_to(iota_n[:, None], (N, B)).reshape(-1)
    exhaust = (ks == (avail[:, None] - 1)) & usable        # entry that empties n
    flat_ex = exhaust.reshape(-1)
    neg_s_srt, idx_srt, ex_srt = jax.lax.sort(
        (-flat_s, flat_idx, flat_ex.astype(jnp.int32)), num_keys=2,
        is_stable=True,
    )
    return norms, table, idx_srt, ex_srt, flat_s


@partial(jax.jit, static_argnames=("gpu_live", "w", "filters", "block"))
@shaped(g="[] i32", m="[] i32", cap1="[] bool")
def schedule_wave(tb: Tables, cry: Carry, g, m, cap1, gpu_live: bool = False,
                  w: ScoreWeights = DEFAULT_WEIGHTS,
                  filters: FilterFlags = DEFAULT_FILTERS,
                  block: int = WAVE_BLOCK):
    """Place up to m pods of wave-eligible group g, exactly reproducing m serial
    _step placements. Returns (new carry, per-node counts [N] i32, placed i32).

    cap1: the group carries hostname-topology required anti-affinity matching
    itself, so every node takes at most one pod of this segment (the tensor
    equivalent of satisfyPodAntiAffinity's self-blocking direction).

    gpu_live (static): the group requests shared GPU memory (no pre-assigned
    gpu-index). Score inputs stay static (the Open-Gpu-Share score is Simon's
    formula); capacity and the device-ledger commit are exact — see
    _gpu_capacity and _aggregate_commit.

    block (static): score-table depth (wave_block_for). Correctness never
    depends on it — entries past the depth are exactly what the
    hidden-continuation guard defers to later iterations — only the
    table/sort size vs iteration-count trade-off does."""
    N = tb.alloc.shape[0]
    B = block
    iota_n = jnp.arange(N, dtype=jnp.int32)
    base_feas, _ = feasibility(
        tb, cry, g, jnp.int32(-1), jnp.asarray(True),
        enable_gpu=gpu_live, enable_storage=False, filters=filters,
    )
    st = _wave_statics(tb, cry, g, w)
    capacity = jnp.where(base_feas, _wave_capacity(tb, cry, g, cap1), 0)
    if not filters.fit:
        # resources unbounded, but cap1 (ports / self-anti-affinity) survives
        capacity = jnp.where(base_feas, 2_147_483_000, 0)
        capacity = jnp.where(cap1, jnp.minimum(capacity, 1), capacity)
    if gpu_live:
        capacity = _gpu_capacity(tb, cry, g, capacity)

    def body(state):
        j, placed, _ = state
        avail = capacity - j                                   # copies left per node
        F = base_feas & (avail > 0)
        norms, table, idx_srt, ex_srt, flat_s = _wave_candidates(
            tb, cry, st, g, j, avail, F, w, B, iota_n)
        pos = jnp.arange(N * B, dtype=jnp.int32)
        n_finite = jnp.sum(jnp.isfinite(flat_s).astype(jnp.int32))
        m_rem = (m - placed).astype(jnp.int32)
        m_cand = jnp.minimum(m_rem, n_finite)

        # exhausted nodes within the candidate range; fine to keep them mid-wave
        # only when every normalizer value provably survives their removal
        counts0 = jnp.zeros(N, jnp.int32).at[idx_srt].add((pos < m_cand).astype(jnp.int32))
        leaves = counts0 >= jnp.maximum(avail, 1)
        F_end = F & ~leaves
        norms_end = _wave_norms(st, F_end)
        same = jnp.array(True)
        for a, b in zip(norms, norms_end):
            same &= a == b  # ±inf compare equal to themselves; no NaN can arise
        p_ex = jnp.min(jnp.where((ex_srt > 0) & (pos < m_cand), pos, N * B))
        m_take = jnp.where(same, m_cand, jnp.minimum(m_cand, p_ex + 1))

        counts = jnp.zeros(N, jnp.int32).at[idx_srt].add((pos < m_take).astype(jnp.int32))

        # Guaranteed progress: the hidden-continuation guard can mask every
        # entry (e.g. a rising column whose bound dominates the whole table).
        # Serial's next pick is always the best HEAD (each node's k=0 entry),
        # so placing exactly that one pod is unconditionally serial-correct.
        heads = jnp.where(F, table[:, 0], -jnp.inf)
        any_head = jnp.any(F)
        head_pick = jnp.zeros(N, jnp.int32).at[jnp.argmax(heads)].set(1)
        use_head = (m_take == 0) & any_head & (m_rem > 0)
        counts = jnp.where(use_head, head_pick, counts)
        m_take = jnp.where(use_head, jnp.int32(1), m_take)
        return (j + counts, placed + m_take, m_take)

    def cond(state):
        _, placed, last_w = state
        return (last_w > 0) & (placed < m)

    j0 = jnp.zeros(N, jnp.int32)
    j, placed, _ = jax.lax.while_loop(cond, body, (j0, jnp.int32(0), jnp.int32(1)))
    return _aggregate_commit(tb, cry, g, j, gpu_live), j, placed


@partial(jax.jit, static_argnames=("w", "filters", "block"))
@shaped(g="[] i32", m="[] i32", cap1="[] bool")
def schedule_spread_wave(tb: Tables, cry: Carry, g, m, cap1,
                         w: ScoreWeights = DEFAULT_WEIGHTS,
                         filters: FilterFlags = DEFAULT_FILTERS,
                         block: int = WAVE_BLOCK):
    """Epoch-batched wave for groups whose ONLY live self-interaction is
    DoNotSchedule topology spread (no SelectorSpread counter, no
    ScheduleAnyway terms, no GPU/storage) — the serial process in far fewer
    device iterations than one-pod-per-scan-step.

    Exactness argument, extending schedule_wave's: between F-changing events,
    the feasible set and every normalizer are constant, so serial's picks are
    exactly the sorted score-table prefix (per-node columns consumed in
    order). The DNS filter adds three event kinds beyond node-capacity
    exhaustion, each with a closed-form position in the sorted order under a
    min frozen at epoch start (filtering.go:200-241 semantics):

      * A SELF-matching term's domain d admits q = maxSkew - 1 + min - cnt[d]
        + 1 more placements before cnt[d] + 1 - min exceeds maxSkew; the
        entry consuming the q-th is the last allowed — the epoch cuts AFTER
        it (the domain then blocks, shrinking F). Non-self terms' counters
        never move during the run, so they contribute only the static q >= 1
        feasibility gate, never budget consumption.
      * min rises the moment every min-count eligible domain has gained a
        placement; the entry completing that is exact to take, and the epoch
        cuts AFTER it (budgets and blocked domains must be recomputed).
      * node capacity exhaustion cuts after the exhausting entry, as in
        schedule_wave (without the norm-invariance extension).

    Each epoch therefore takes min(candidates, first-event cut) pods — with
    Z eligible domains typically ~Z placements per iteration instead of 1 —
    and the head fallback guarantees progress when the guard masks
    everything. Returns (new carry, per-node counts [N] i32, placed i32)."""
    N = tb.alloc.shape[0]
    B = block
    D = cry.counter.shape[1] - 1
    iota_n = jnp.arange(N, dtype=jnp.int32)
    INF_P = jnp.int32(N * B + 1)
    base_feas, _ = feasibility(
        tb, cry, g, jnp.int32(-1), jnp.asarray(True),
        enable_gpu=False, enable_storage=False, include_dns=False, filters=filters,
    )
    st = _wave_statics(tb, cry, g, w)
    capacity = jnp.where(base_feas, _wave_capacity(tb, cry, g, cap1), 0)
    if not filters.fit:
        capacity = jnp.where(base_feas, 2_147_483_000, 0)
        capacity = jnp.where(cap1, jnp.minimum(capacity, 1), capacity)

    dids_raw = tb.dns_t[g]                                 # [Sd]
    dvalid = dids_raw >= 0
    dids = jnp.maximum(dids_raw, 0)
    dom_rows = tb.counter_dom[dids]                        # [Sd, N]
    key_present = dom_rows < D
    edom = tb.dns_edom[g]                                  # [Sd, D+1]
    dself = tb.dns_self[g]                                 # [Sd] f32 (1.0 = self)
    dskew = tb.dns_maxskew[g]                              # [Sd]
    live = dvalid & (tb.counter_sel_match_g[dids, g]) & (dself > 0)  # [Sd]
    cnt0 = cry.counter[dids]                               # [Sd, D+1]
    Sd = dids.shape[0]

    if not filters.spread:
        # DNS filter disabled by scheduler config: plain-wave semantics
        live = jnp.zeros_like(live)
        dvalid = jnp.zeros_like(dvalid)

    def body(state):
        j, cnt, placed, _ = state
        avail = capacity - j
        # frozen-min budgets: q[s, d] = remaining placements domain d admits
        min_c = jnp.min(jnp.where(edom, cnt, jnp.inf), axis=1)
        min_c = jnp.where(jnp.isfinite(min_c), min_c, 0.0)     # [Sd]
        q = dskew[:, None] - dself[:, None] + min_c[:, None] - cnt + 1.0
        q = jnp.maximum(q, 0.0)                                # [Sd, D+1]
        # per-node DNS feasibility: every valid term has key + budget >= 1
        q_at = jnp.take_along_axis(q, dom_rows, axis=1)        # [Sd, N]
        dns_ok = jnp.all((key_present & (q_at >= 1.0)) | ~dvalid[:, None], axis=0)
        F = base_feas & (avail > 0) & dns_ok
        norms, table, idx_srt, ex_srt, flat_s = _wave_candidates(
            tb, cry, st, g, j, avail, F, w, B, iota_n)
        pos = jnp.arange(N * B, dtype=jnp.int32)
        n_finite = jnp.sum(jnp.isfinite(flat_s).astype(jnp.int32))
        m_rem = (m - placed).astype(jnp.int32)
        m_cand = jnp.minimum(m_rem, n_finite)
        valid_pos = pos < m_cand

        # node-capacity cut: after the first exhausting entry
        p_ex = jnp.min(jnp.where((ex_srt > 0) & valid_pos, pos, INF_P))

        # Per-SELF-term domain bookkeeping along the sorted order. Everything
        # here is LINEAR in NB and D — no [NB, D] one-hot, because hostname
        # topologies have D ~ N and this kernel is routed exactly to
        # high-cardinality topologies.
        dom_srt = dom_rows[:, idx_srt]                          # [Sd, NB]
        NB = N * B
        p_dom_ex = INF_P
        p_viol = INF_P
        p_rise = INF_P
        at_min = edom & (cnt == min_c[:, None])                 # [Sd, D+1]
        within_budget = jnp.ones(N * B, bool)
        for s in range(Sd):
            dom_row = dom_srt[s]
            dkey = jnp.where(valid_pos, dom_row, D)             # invalid → sentinel
            # occ_before: rank of each entry among same-domain entries in
            # score order, via one (domain, position) sort + run ranking
            d2, p2 = jax.lax.sort((dkey, pos), num_keys=2, is_stable=True)
            run_start = jnp.concatenate(
                [jnp.ones((1,), bool), d2[1:] != d2[:-1]])
            seg_start = jax.lax.associative_scan(
                jnp.maximum, jnp.where(run_start, pos, 0))
            occ = jnp.zeros(NB, _F32).at[p2].set((pos - seg_start).astype(_F32))
            q_row = q[s][dom_row]                               # [NB]
            act = live[s] & valid_pos
            within_budget &= jnp.where(act, occ + 1.0 <= q_row, True)
            # the q-th take exhausts its domain → cut after; a q+1-th entry is
            # a violation (possible when another term still had budget) → cut
            # before
            p_dom_ex = jnp.minimum(p_dom_ex, jnp.min(
                jnp.where(act & (occ + 1.0 == q_row), pos, INF_P)))
            p_viol = jnp.minimum(p_viol, jnp.min(
                jnp.where(act & (occ + 1.0 > q_row), pos, INF_P)))
            # min-rise cut: the position where the LAST min-count eligible
            # domain receives its first placement (INF if any never does)
            first_occ = jnp.full((D + 1,), INF_P).at[dkey].min(
                jnp.where(valid_pos, pos, INF_P))
            rise = jnp.max(jnp.where(at_min[s], first_occ, -1))
            unreached = jnp.any(at_min[s] & (first_occ >= INF_P))
            p_rise = jnp.minimum(p_rise, jnp.where(
                live[s] & ~unreached & (rise >= 0), rise, INF_P))

        # Conservative epoch: stop at the first F-changing event.
        m_take_cons = jnp.minimum(m_cand, jnp.minimum(p_ex + 1, p_viol))
        m_take_cons = jnp.minimum(m_take_cons,
                                  jnp.minimum(p_dom_ex + 1, p_rise + 1))
        counts_cons = jnp.zeros(N, jnp.int32).at[idx_srt].add(
            (pos < m_take_cons).astype(jnp.int32))

        # Skipping epoch: with min frozen and every normalizer INVARIANT,
        # serial just skips over-budget / capacity-exhausted entries and keeps
        # consuming the same order — so take the first m_rem in-cap,
        # within-budget entries up to the min-rise cut. Valid only when
        # removing every node that leaves F during the prefix (capacity
        # exhausted or domain blocked) provably changes no normalizer —
        # checked on the end state exactly like schedule_wave's check.
        # Only positions whose budgets were evaluated (valid_pos = pos <
        # m_cand) may be taken — tail entries past m_cand have UNCHECKED
        # budgets and must wait for the next epoch's accounting.
        takeable = valid_pos & within_budget & (pos <= p_rise)
        take_rank = jax.lax.associative_scan(
            jnp.add, takeable.astype(jnp.int32))                # 1-based
        taken = takeable & (take_rank <= m_rem)
        m_take_skip = jnp.minimum(m_rem, take_rank[-1])
        counts_skip = jnp.zeros(N, jnp.int32).at[idx_srt].add(
            taken.astype(jnp.int32))

        leaves_cap = counts_skip >= jnp.maximum(avail, 1)
        # nodes whose any live term's domain budget is fully consumed
        used_budget = jnp.zeros((Sd, D + 1), _F32).at[
            jnp.arange(Sd)[:, None], dom_srt
        ].add(taken.astype(_F32)[None, :] * live[:, None].astype(_F32))
        dom_blocked = used_budget >= q                          # [Sd, D+1]
        node_blocked = jnp.any(
            jnp.take_along_axis(dom_blocked, dom_rows, axis=1)
            & live[:, None], axis=0)                            # [N]
        F_end = F & ~leaves_cap & ~node_blocked
        norms_end = _wave_norms(st, F_end)
        same = jnp.array(True)
        for a, b in zip(norms, norms_end):
            same &= a == b

        # The skip path's per-term occ counts every same-domain entry, taken
        # or not; with TWO+ live terms an entry skipped for term A still
        # consumes term B's occ, under-estimating B's real remaining budget —
        # serial would not consume it. One live term has no such interaction
        # (its own over-budget entries are exactly the ones serial skips,
        # consuming nothing), so the skip path is sound only there.
        use_skip = same & (jnp.sum(live.astype(jnp.int32)) <= 1)
        m_take = jnp.where(use_skip, m_take_skip, m_take_cons)
        counts = jnp.where(use_skip, counts_skip, counts_cons)

        # head fallback: serial's single next pick is always exact
        heads = jnp.where(F, table[:, 0], -jnp.inf)
        any_head = jnp.any(F)
        head_pick = jnp.zeros(N, jnp.int32).at[jnp.argmax(heads)].set(1)
        use_head = (m_take == 0) & any_head & (m_rem > 0)
        counts = jnp.where(use_head, head_pick, counts)
        m_take = jnp.where(use_head, jnp.int32(1), m_take)

        # fold the taken placements into the live terms' counters
        inc = jnp.zeros((Sd, D + 1), _F32)
        inc = inc.at[jnp.arange(Sd)[:, None], dom_rows].add(
            counts.astype(_F32)[None, :] * live[:, None])
        # sentinel column never counts (commit() masks dom >= D)
        inc = inc * (jnp.arange(D + 1)[None, :] < D)
        cnt = cnt + inc
        return (j + counts, cnt, placed + m_take, m_take)

    def cond(state):
        _, _, placed, last = state
        return (last > 0) & (placed < m)

    j0 = jnp.zeros(N, jnp.int32)
    j, _, placed, _ = jax.lax.while_loop(
        cond, body, (j0, cnt0, jnp.int32(0), jnp.int32(1)))
    return _aggregate_commit(tb, cry, g, j, False), j, placed


@partial(jax.jit, static_argnames=("w", "filters", "ss_live", "sa_live", "n_zones"))
@shaped(g="[] i32", valid="[P] bool", cap1="[] bool")
def schedule_group_serial(tb: Tables, cry: Carry, g, valid, cap1,
                          w: ScoreWeights = DEFAULT_WEIGHTS,
                          filters: FilterFlags = DEFAULT_FILTERS,
                          ss_live: bool = False, sa_live: bool = False,
                          n_zones: int = 2):
    """Serial scheduling of one group whose placements feed back into its own
    scoring/filtering through per-node copy counts — self-matching
    DoNotSchedule topology-spread constraints and/or a live SelectorSpread
    counter (a service-backed workload spreading against itself: the most
    common real-cluster app shape) — as a FUSED scan: exactly the reference's
    one-pod-per-cycle process (same per-step feasible set and scores as
    _step/scores()), but each step is specialized to what can actually change
    within a single-group run — per-node copy counts and the group's own
    spread/selector counters. Everything else (taints, affinity counters,
    carriers, normalizer *inputs*, static score vectors) is provably constant
    and hoisted out, so a step costs a few [N]-wide ops + an [Sd, D+1] reduce
    instead of the general scan step's [T, N] gathers and [T, D+1] scatters
    (the reason spread-heavy workloads crawled at ~400 pods/s before this
    kernel).

    `valid` is a [P] bool mask (padded scan length); returns
    (new carry, per-node counts [N] i32, placed i32).

    ss_live (static): compute the SelectorSpread score live — per-node count
    plus 2/3-zone blending (selector_spread.go:104-160) over base counts + j.
    n_zones (static): zone-table size for the blend, as in scores().
    sa_live (static): compute the PodTopologySpread ScheduleAnyway score live
    — the group carries soft spread terms, whose counters (for self-matching
    selectors) and relevant-set normalizers move with every placement.

    Dropped-constant notes (argmax-invariant, same as _wave_score_table):
    SelectorSpread when NOT ss_live (ss_skip => 0 for explicit-constraint
    pods), PodTopologySpread score when NOT sa_live (no ScheduleAnyway terms
    => 100 on F), OpenLocal (0)."""
    N = tb.alloc.shape[0]
    D = cry.counter.shape[1] - 1
    base_feas, _ = feasibility(
        tb, cry, g, jnp.int32(-1), jnp.asarray(True),
        enable_gpu=False, enable_storage=False, include_dns=False, filters=filters,
    )
    st = _wave_statics(tb, cry, g, w)
    capacity = jnp.where(base_feas, _wave_capacity(tb, cry, g, cap1), 0)
    if not filters.fit:
        # resources unbounded, but cap1 (ports / self-anti-affinity) survives
        capacity = jnp.where(base_feas, 2_147_483_000, 0)
        capacity = jnp.where(cap1, jnp.minimum(capacity, 1), capacity)

    dids_raw = tb.dns_t[g]                                 # [Sd]
    dvalid = dids_raw >= 0
    dids = jnp.maximum(dids_raw, 0)
    dom_rows = tb.counter_dom[dids]                        # [Sd, N]
    key_present = dom_rows < D
    edom = tb.dns_edom[g]                                  # [Sd, D+1]
    dself = tb.dns_self[g][:, None]
    dskew = tb.dns_maxskew[g][:, None]
    dmatch = (tb.counter_sel_match_g[dids, g] & dvalid).astype(_F32)  # [Sd]
    cnt0 = cry.counter[dids]                               # [Sd, D+1]
    Sd = dids.shape[0]
    alloc_cm = tb.alloc[:, (CPU_I, MEM_I)]                 # [N, 2]
    gnz = tb.grp_nonzero[g]
    if ss_live:
        # SelectorSpread live state: the group's own counter is hostname-
        # topology (encode.py ss_counter), so per-node counts are exactly
        # base counts + j; zone sums re-aggregate per step over current F
        ss_id = jnp.maximum(tb.ss_t[g], 0)
        # one row's gather, not the [T, N] cnt_at scores() needs for interpod
        base_pernode = counter_rows_at(tb, cry, ss_id[None])[1][0]     # [N]
        zones = tb.node_zone
        Z = max(2, n_zones)
    if sa_live:
        # ScheduleAnyway live state: per-term counter rows; counts move for
        # self-matching selectors, the relevant-set normalizers move with F
        sa_ids = tb.sa_t[g]                                # [Ss]
        svalid = sa_ids >= 0
        sidx = jnp.maximum(sa_ids, 0)
        sa_dom_rows = tb.counter_dom[sidx]                 # [Ss, N]
        sa_ignored = jnp.any(svalid[:, None] & (sa_dom_rows >= D), axis=0)
        sa_match = (tb.counter_sel_match_g[sidx, g] & svalid).astype(_F32)
        sa_maxskew = tb.sa_maxskew[g]
        cnt_sa0 = cry.counter[sidx]                        # [Ss, D+1]
        Ss = sidx.shape[0]
    else:
        cnt_sa0 = jnp.zeros((1, D + 1), _F32)              # inert carry slot

    # Precompute the count-dependent score column OUTSIDE the scan: entry
    # (n, k) = w.least*least + w.balanced*balanced for the (k+1)-th copy on
    # node n — identical f32 expressions to the in-step math, so the gathered
    # values are bit-equal. j_n < P always, so K = P covers every reachable
    # count. Skipped (None) for pathological sizes where the [N, P] table
    # would dominate memory; the step then computes the pair inline.
    N_, P_ = tb.alloc.shape[0], valid.shape[0]
    if N_ * P_ <= 64_000_000:
        copies_k = jnp.arange(1, P_ + 1, dtype=_F32)                   # [P]
        used_k = (cry.nonzero[:, None, :]
                  + gnz[None, None, :] * copies_k[None, :, None])      # [N, P, 2]
        lst, bal = least_balanced(used_k[:, :, 0], used_k[:, :, 1],
                                  alloc_cm[:, None, 0], alloc_cm[:, None, 1])
        lb_table = w.least * lst + w.balanced * bal                    # [N, P]
    else:
        lb_table = None

    def step(state: SerialState, ok):
        j, cnt, cnt_sa = state
        # live DoNotSchedule filter, mirroring feasibility() term for term
        cnt_at = jnp.take_along_axis(cnt, dom_rows, axis=1)           # [Sd, N]
        min_c = jnp.min(jnp.where(edom, cnt, jnp.inf), axis=1)
        min_c = jnp.where(jnp.isfinite(min_c), min_c, 0.0)
        dns_ok_each = key_present & (cnt_at + dself - min_c[:, None] <= dskew)
        dns_ok = jnp.all(dns_ok_each | ~dvalid[:, None], axis=0)
        F = base_feas & (capacity - j > 0) & dns_ok
        any_f = jnp.any(F) & ok
        # scores: least/balanced move with j; the rest normalize over F. The
        # candidate pod itself counts toward its own usage (scores() adds
        # grp_nonzero once), hence j + 1.
        if lb_table is None:
            used = cry.nonzero + gnz[None, :] * (j + 1).astype(_F32)[:, None]
            least, balanced = least_balanced(
                used[:, 0], used[:, 1], alloc_cm[:, 0], alloc_cm[:, 1])
            lb = w.least * least + w.balanced * balanced
        else:
            lb = jnp.take_along_axis(lb_table, j[:, None], axis=1)[:, 0]
        simon_hi, simon_lo, na_max, t_max, ip_max, ip_min = _wave_norms(st, F)
        rng = simon_hi - simon_lo
        simon = jnp.where((rng > 0) & jnp.isfinite(rng),
                          _flr((st["simon_s"] - simon_lo) * 100.0 / rng), 0.0)
        nodeaff = jnp.where(na_max > 0, _flr(st["na_raw"] * 100.0 / na_max), 0.0)
        taint = jnp.where(t_max > 0, 100.0 - _flr(st["t_raw"] * 100.0 / t_max), 100.0)
        ip_rng = ip_max - ip_min
        interpod = jnp.where(ip_rng > 0,
                             _flr(100.0 * (st["ip_raw"] - ip_min) / ip_rng), 0.0)
        score = (lb + (w.simon + w.gpushare) * simon + w.nodeaff * nodeaff
                 + w.taint * taint + w.interpod * interpod + st["static"])
        if ss_live:
            # live SelectorSpread: shared formula with pernode = base + j
            pernode = base_pernode + j.astype(_F32)
            score = score + w.ss * _flr(
                selector_spread_score(pernode, F, zones, Z))
        if sa_live:
            # live ScheduleAnyway: shared formula over current counts + F
            cnt_at_sa = jnp.take_along_axis(cnt_sa, sa_dom_rows, axis=1)
            score = score + w.pts * schedule_anyway_score(
                cnt_at_sa, F & ~sa_ignored, sa_dom_rows, svalid, sa_maxskew, D)
        choice = jnp.argmax(jnp.where(F, score, -jnp.inf)).astype(jnp.int32)
        do = any_f.astype(jnp.int32)
        j = j.at[choice].add(do)
        cnt = cnt.at[jnp.arange(Sd), dom_rows[:, choice]].add(dmatch * do)
        if sa_live:
            # sentinel-masked like commit(): a pod may land on a node missing
            # the SA topology key (score-only plugin, unlike the DNS filter)
            sa_dom_c = sa_dom_rows[:, choice]
            cnt_sa = cnt_sa.at[jnp.arange(Ss), sa_dom_c].add(
                sa_match * (sa_dom_c < D) * do)
        return SerialState(j, cnt, cnt_sa), do

    final_state, dos = jax.lax.scan(
        step, SerialState(jnp.zeros(N, jnp.int32), cnt0, cnt_sa0), valid)
    j = final_state.j
    placed = jnp.sum(dos)
    return _aggregate_commit(tb, cry, g, j, False), j, placed


@partial(jax.jit, static_argnames=("n_zones", "enable_gpu", "enable_storage", "w", "filters"))
@shaped(pod_group="[P] i32", forced_node="[P] i32", valid="[P] bool")
def schedule_batch(
    tb: Tables, cry: Carry, pod_group, forced_node, valid, n_zones: int,
    enable_gpu: bool = True, enable_storage: bool = True,
    w: ScoreWeights = DEFAULT_WEIGHTS, filters: FilterFlags = DEFAULT_FILTERS,
):
    """Scan the whole batch; returns (final carry, placements[P] int32, -1=unschedulable)."""

    def body(c: Carry, xs):
        return _step(tb, c, xs, n_zones, enable_gpu, enable_storage, w, filters)

    final, choices = jax.lax.scan(body, cry, (pod_group, forced_node, valid))
    return final, choices


# ---------------------------------------------------------------------------
# Multi-candidate capacity probing: evaluate S node-active masks in ONE
# dispatch. The capacity planner's doubling/refinement search asks "would this
# batch schedule on base + n template nodes?" for several n at once; each
# candidate differs only in which node columns are active, so the fan-out is a
# vmap over (carry, active) with the tables closed over — `active` folds into
# static_mask, making an inactive node exactly a pad_batch_tables phantom
# (infeasible everywhere, excluded from every normalizer, zero counts). Under
# a ('scenarios', 'nodes') mesh (parallel/mesh.py) the vmapped axis shards as
# data parallelism, one candidate lane per device.
# ---------------------------------------------------------------------------


def _mask_active(tb: Tables, active) -> Tables:
    """Fold a [N] node-active mask into the static group mask (the single
    feasibility root every filter ANDs into)."""
    return tb._replace(static_mask=tb.static_mask & active[None, :])


@partial(jax.jit, static_argnames=("gpu_live", "w", "filters", "block"))
@shaped(active_s="[S, N] bool", g="[] i32", m="[] i32", cap1="[] bool")
def probe_wave_fanout(tb: Tables, cry_s: Carry, active_s, g, m, cap1,
                      gpu_live: bool = False,
                      w: ScoreWeights = DEFAULT_WEIGHTS,
                      filters: FilterFlags = DEFAULT_FILTERS,
                      block: int = WAVE_BLOCK):
    """schedule_wave over S candidate node-active masks in one dispatch.
    cry_s is a Carry whose leaves carry a leading [S] axis. Returns
    (carry_s, placed_s [S] i32)."""

    def one(cry: Carry, active):
        c2, _, placed = schedule_wave(
            _mask_active(tb, active), cry, g, m, cap1,
            gpu_live=gpu_live, w=w, filters=filters, block=block)
        return c2, placed

    return jax.vmap(one)(cry_s, active_s)


@partial(jax.jit, static_argnames=("w", "filters", "ss_live", "sa_live", "n_zones"))
@shaped(active_s="[S, N] bool", g="[] i32", valid="[P] bool", cap1="[] bool")
def probe_group_serial_fanout(tb: Tables, cry_s: Carry, active_s, g, valid, cap1,
                              w: ScoreWeights = DEFAULT_WEIGHTS,
                              filters: FilterFlags = DEFAULT_FILTERS,
                              ss_live: bool = False, sa_live: bool = False,
                              n_zones: int = 2):
    """schedule_group_serial over S candidate node-active masks in one
    dispatch. Returns (carry_s, placed_s [S] i32)."""

    def one(cry: Carry, active):
        c2, _, placed = schedule_group_serial(
            _mask_active(tb, active), cry, g, valid, cap1,
            w=w, filters=filters, ss_live=ss_live, sa_live=sa_live,
            n_zones=n_zones)
        return c2, placed

    return jax.vmap(one)(cry_s, active_s)


@partial(jax.jit, static_argnames=("n_zones", "enable_gpu", "enable_storage", "w", "filters"))
@shaped(active_s="[S, N] bool", pod_group="[P] i32", forced_node="[P] i32", valid="[P] bool")
def probe_serial_fanout(tb: Tables, cry_s: Carry, active_s, pod_group,
                        forced_node, valid, n_zones: int,
                        enable_gpu: bool = True, enable_storage: bool = True,
                        w: ScoreWeights = DEFAULT_WEIGHTS,
                        filters: FilterFlags = DEFAULT_FILTERS):
    """schedule_batch over S candidate node-active masks in one dispatch.
    Returns (carry_s, placed_s [S] i32) — the probe only needs counts, so the
    per-pod choices stay on device and reduce to a sum per lane."""

    def one(cry: Carry, active):
        c2, choices = schedule_batch(
            _mask_active(tb, active), cry, pod_group, forced_node, valid,
            n_zones=n_zones, enable_gpu=enable_gpu,
            enable_storage=enable_storage, w=w, filters=filters)
        return c2, jnp.sum((choices >= 0).astype(jnp.int32))

    return jax.vmap(one)(cry_s, active_s)
