"""Device kernels: the batched scheduling engine.

One `lax.scan` step = one scheduleOne cycle of the vendored scheduler
(scheduler.go:441): filter every node in parallel, score the feasible ones with the
v1.20 default plugin set + the Simon bin-packing plugin, pick the winner, commit
capacity/counter updates into the carry. The serial pod order of the reference
(pkg/simulator/simulator.go:309-348 schedules one pod per channel handshake) is preserved
exactly — but each step is a fused [N]-wide tensor program on the accelerator instead of
a goroutine round-trip, and whole apps run as one compiled scan.

Plugin parity notes (all semantics cross-checked against the vendored sources):
- Filters: NodeResourcesFit, NodePorts (node_ports.go), NodeUnschedulable/TaintToleration/
  NodeAffinity/NodeName (pre-folded into the static group mask by the encoder),
  InterPodAffinity incl. the bootstrap special case and the existing-pods anti-affinity
  direction (filtering.go:226-280), PodTopologySpread DoNotSchedule with critical-path
  min over eligible domains (filtering.go:200-241).
- Scores (weights from algorithmprovider/registry.go:118-137 + SelectorSpread appended by
  applyFeatureGates:161-171): LeastAllocated(1), BalancedAllocation(1), ImageLocality(1),
  InterPodAffinity(1), NodeAffinity(1), NodePreferAvoidPods(10000), PodTopologySpread(2),
  TaintToleration(1), SelectorSpread(1), and Simon(1) with its min-max NormalizeScore
  (plugin/simon.go:76-101). Integer truncation points and the zero-initialized min/max
  quirks of the upstream normalizers are reproduced with explicit floors.
- selectHost tie-break: upstream picks uniformly at random among max-score nodes
  (generic_scheduler.go:188); we deterministically pick the lowest node index. This is
  the one intentional divergence (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import os
from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec

from .contracts import shaped
from .resources import CPU_I, MEM_I

class ScoreWeights(NamedTuple):
    """Per-score-plugin weights, default = the v1.20 provider registry
    (registry.go:118-137; Simon/OpenLocal/GpuShare default to weight 1 via the
    framework's zero->1 rule for enabled score plugins). Passed as a STATIC jit
    argument so custom --default-scheduler-config weights fold into the
    compiled program as constants; a disabled score plugin is weight 0."""

    least: float = 1.0       # NodeResourcesLeastAllocated
    balanced: float = 1.0    # NodeResourcesBalancedAllocation
    image: float = 1.0       # ImageLocality
    interpod: float = 1.0    # InterPodAffinity
    nodeaff: float = 1.0     # NodeAffinity
    avoid: float = 10000.0   # NodePreferAvoidPods
    pts: float = 2.0         # PodTopologySpread
    taint: float = 1.0       # TaintToleration
    ss: float = 1.0          # SelectorSpread
    simon: float = 1.0       # Simon bin-packing
    # Open-Gpu-Share's Score (open-gpu-share.go:86-110) is the same max-share
    # formula and min-max normalization as Simon's — its contribution is
    # exactly a second Simon term with its own weight.
    gpushare: float = 1.0
    openlocal: float = 1.0   # Open-Local


class FilterFlags(NamedTuple):
    """Enable flags for the filter plugins evaluated inside the kernel (the
    statically-folded ones — taints/unschedulable/node-affinity — are disabled
    at encode time instead; see Encoder.filter_disabled). STATIC jit args."""

    fit: bool = True         # NodeResourcesFit
    ports: bool = True       # NodePorts
    interpod: bool = True    # InterPodAffinity
    spread: bool = True      # PodTopologySpread


DEFAULT_WEIGHTS = ScoreWeights()
DEFAULT_FILTERS = FilterFlags()

_F32 = jnp.float32


class Tables(NamedTuple):
    """Scan-invariant device tables (see encode.BatchTables for field docs)."""

    alloc: jax.Array
    node_zone: jax.Array
    static_mask: jax.Array
    mask_taint: jax.Array
    mask_unsched: jax.Array
    mask_aff: jax.Array
    mask_extra: jax.Array  # [G, N] bool: out-of-tree plugin filters (static)
    simon_raw: jax.Array
    nodeaff_raw: jax.Array
    taint_raw: jax.Array
    avoid_raw: jax.Array
    image_raw: jax.Array
    extra_raw: jax.Array  # [G, N] f32: out-of-tree plugin score sum (static)
    grp_requests: jax.Array
    grp_nonzero: jax.Array
    grp_unknown: jax.Array
    grp_ports: jax.Array
    counter_dom: jax.Array
    counter_topo: jax.Array  # [T] i32: unique-topology row id per counter
    topo_dom: jax.Array      # [U, N] i32: node→domain per unique topology key
    counter_sel_match_g: jax.Array
    req_aff_t: jax.Array
    grp_aff_self: jax.Array
    req_anti_t: jax.Array
    pref_t: jax.Array
    pref_w: jax.Array
    dns_t: jax.Array
    dns_maxskew: jax.Array
    dns_self: jax.Array
    dns_edom: jax.Array
    sa_t: jax.Array
    sa_maxskew: jax.Array
    sa_self: jax.Array
    ss_t: jax.Array
    ss_skip: jax.Array
    carr_dom: jax.Array
    carr_topo: jax.Array    # [Tc] i32: unique-topology row id per carrier
    carr_anti_t: jax.Array  # [G, Ca] i32: anti-use carrier ids matching g (-1 pad)
    carr_w_t: jax.Array     # [G, Cw] i32: carrier ids with interpod weight for g
    carr_w_w: jax.Array     # [G, Cw] f32: those weights (hard=1 / signed pref)
    grp_carries: jax.Array
    # GPU-share (open-gpu-share.go Filter; per-device ledger in the carry)
    grp_gpu_mem: jax.Array   # [G] f32: per-GPU memory request (0 = no GPU)
    grp_gpu_num: jax.Array   # [G] f32: number of GPUs requested
    grp_gpu_pre: jax.Array   # [G] bool: valid pre-assigned gpu-index present
    grp_gpu_take: jax.Array  # [G, MAXDEV] f32: unit counts per device when pre-assigned
    dev_total: jax.Array     # [N, MAXDEV] f32: per-device total memory (0 = absent)
    # Open-Local storage (plugins/openlocal.py; VG/device state in the carry)
    grp_lvm_size: jax.Array   # [G, SL] f32: LVM volume sizes (0 = unused slot)
    grp_lvm_vg: jax.Array     # [G, SL] i32: VG name id (0 = unnamed → Binpack)
    grp_sdev_size: jax.Array  # [G, SD] f32: device volume sizes (ssd-asc then hdd-asc)
    grp_sdev_media: jax.Array  # [G, SD] i32: 1 hdd / 2 ssd (0 = unused)
    vg_cap: jax.Array         # [N, MAXVG] f32 (0 = absent VG)
    vg_nameid: jax.Array      # [N, MAXVG] i32
    sdev_cap: jax.Array       # [N, MAXSD] f32
    sdev_media: jax.Array     # [N, MAXSD] i32


class Carry(NamedTuple):
    """Mutable cluster state threaded through the scan."""

    requested: jax.Array    # [N, R] f32
    nonzero: jax.Array      # [N, 2] f32
    port_used: jax.Array    # [N, PORT+1] bool
    counter: jax.Array      # [T, D+1] f32
    carrier: jax.Array      # [Tc, D+1] f32
    dev_used: jax.Array     # [N, MAXDEV] f32: per-GPU-device used memory
    vg_req: jax.Array       # [N, MAXVG] f32: LVM volume-group requested bytes
    sdev_alloc: jax.Array   # [N, MAXSD] f32: 1.0 = exclusive device allocated


class SerialState(NamedTuple):
    """Scan-carry contract for schedule_group_serial's fused step: the ONLY
    state a single-group serial run can mutate. Leaf shapes/dtypes are fixed
    for the whole scan — simonlint's carry-contract rule holds every branch
    of the body to this declaration."""

    j: jax.Array       # [N] i32: per-node copies placed so far
    cnt: jax.Array     # [Sd, D+1] f32: live DoNotSchedule counter rows
    cnt_sa: jax.Array  # [Ss, D+1] f32: live ScheduleAnyway counter rows


def _flr(x):
    return jnp.floor(x)


@shaped(pernode="[N] f32", F="[N] bool", zones="[N] i32", ret="[N] f32")
def selector_spread_score(pernode, F, zones, Z: int, maxN=None):
    """SelectorSpread (selector_spread.go:104-160): per-node count score with
    2/3 zone blending, over the feasible set F. THE single source of this
    formula — scores() and the ss_live fused scan must stay bit-identical,
    since wave==serial parity rides on it. Returns the unfloored blend; the
    caller applies skip/has_ss gating and _flr. `maxN` lets scores() reuse
    its stacked-reduction maximum (same float by construction)."""
    if maxN is None:
        maxN = jnp.maximum(jnp.max(jnp.where(F, pernode, -jnp.inf)), 0.0)
    node_score = jnp.where(maxN > 0, 100.0 * (maxN - pernode) / maxN, 100.0)
    nz_count = jnp.where(F, pernode, 0.0)
    zone_sums = jnp.zeros((Z,), _F32).at[zones].add(nz_count)
    maxZ = jnp.max(zone_sums.at[0].set(0.0))
    have_zones = jnp.any(F & (zones > 0))
    zscore = jnp.where(maxZ > 0, 100.0 * (maxZ - zone_sums[zones]) / maxZ, 100.0)
    return jnp.where(have_zones & (zones > 0),
                     node_score * (1.0 / 3.0) + zscore * (2.0 / 3.0), node_score)


@shaped(cnt_sa="[Ss, N] f32", relevantF="[N] bool", dom_rows="[Ss, N] i32",
        svalid="[Ss] bool", maxskew="[Ss] f32", ret="[N] f32")
def schedule_anyway_score(cnt_sa, relevantF, dom_rows, svalid, maxskew, D: int):
    """PodTopologySpread ScheduleAnyway scoring (scoring.go:108-200) from the
    per-term per-node counts: ln(topology size + 2) weights, maxSkew - 1
    offsets, integer floor, then the plugin's (max + min - raw) * 100 / max
    normalization over the relevant feasible set. THE single source of this
    formula — scores() and the sa_live fused scan must stay bit-identical."""
    Ss = dom_rows.shape[0]
    marks = jnp.zeros((Ss, D + 1), _F32).at[
        jnp.arange(Ss)[:, None], dom_rows
    ].max(jnp.broadcast_to(relevantF.astype(_F32), dom_rows.shape))
    topo_size = jnp.sum(marks[:, :D], axis=1)
    tpw = jnp.log(topo_size + 2.0)
    contrib = cnt_sa * tpw[:, None] + (maxskew[:, None] - 1.0)
    sa_raw = _flr(jnp.sum(jnp.where(svalid[:, None], contrib, 0.0), axis=0))
    sa_max = jnp.maximum(jnp.max(jnp.where(relevantF, sa_raw, -jnp.inf)), 0.0)
    sa_min_raw = jnp.min(jnp.where(relevantF, sa_raw, jnp.inf))
    sa_min = jnp.where(jnp.isfinite(sa_min_raw), sa_min_raw, 0.0)
    return jnp.where(
        ~relevantF,
        0.0,
        jnp.where(sa_max > 0, _flr((sa_max + sa_min - sa_raw) * 100.0 / sa_max), 100.0),
    )


def carrier_rows_at(tb: Tables, cry: Carry, ids):
    """Selective carrier-row gather by static per-group slot ids (same idiom
    as counter_rows_at): returns per-node values [k, N]."""
    return jnp.take_along_axis(cry.carrier[ids], tb.carr_dom[ids], axis=1)


def counter_rows_at(tb: Tables, cry: Carry, ids):
    """Selectively gather counter rows by static slot indices: returns
    (rows [k, D+1], per-node values [k, N], key_present [k, N], dom [k, N]).
    THE shared idiom for every plugin that reads a handful of counters —
    never gather the full [T, N] table; T grows with every service/affinity
    selector."""
    rows = cry.counter[ids]                             # [k, D+1]
    dom = tb.counter_dom[ids]                           # [k, N]
    D = cry.counter.shape[1] - 1
    return rows, jnp.take_along_axis(rows, dom, axis=1), dom < D, dom


@shaped(g="[] i32", ret="[N] f32")
def interpod_raw(tb: Tables, cry: Carry, g):
    """InterPodAffinity raw score (scoring.go): incoming preferred terms plus
    existing pods' required (HardPodAffinityWeight=1) and preferred terms,
    via selective slot gathers. Single source for scores() and
    _wave_statics() — their serial-equality contract needs identical ip_raw
    floats."""
    pref_ids = tb.pref_t[g]
    pvalid = pref_ids >= 0
    pw = tb.pref_w[g]
    _, pref_at, _, _ = counter_rows_at(tb, cry, jnp.maximum(pref_ids, 0))
    ip_raw = jnp.sum(jnp.where(pvalid[:, None], pw[:, None] * pref_at, 0.0), axis=0)
    cw_ids = tb.carr_w_t[g]
    cw_valid = cw_ids >= 0
    cw_at = carrier_rows_at(tb, cry, jnp.maximum(cw_ids, 0))
    return ip_raw + jnp.sum(
        jnp.where(cw_valid[:, None], tb.carr_w_w[g][:, None] * cw_at, 0.0), axis=0)


def least_balanced(used_c, used_m, a_c, a_m):
    """NodeResourcesLeastAllocated (least_allocated.go:93-115, integer divisions
    floored) + NodeResourcesBalancedAllocation (balanced_allocation.go:96-120)
    for broadcast-compatible cpu/mem usage and allocatable arrays. The single
    source of these formulas for scores(), the wave score table, and the fused
    group-serial scan — their serial-equality proofs require floor-for-floor
    identical math."""
    def least_one(u, a):
        return jnp.where((a > 0) & (u <= a), _flr((a - u) * 100.0 / a), 0.0)

    least = _flr((least_one(used_c, a_c) + least_one(used_m, a_m)) / 2.0)
    cf = jnp.where(a_c > 0, used_c / a_c, 1.0)
    mf = jnp.where(a_m > 0, used_m / a_m, 1.0)
    balanced = jnp.where((cf >= 1.0) | (mf >= 1.0), 0.0,
                         _flr((1.0 - jnp.abs(cf - mf)) * 100.0))
    return least, balanced


@shaped(g="[] i32")
def storage_alloc(tb: Tables, cry: Carry, g):
    """Simulate Open-Local allocation of group g's volumes on EVERY node at once.

    Sequential semantics per volume slot (named-VG exact / unnamed Binpack
    tightest-fit; devices: smallest fitting free device of the media type), with a
    small unrolled loop over the (bucketed, tiny) slot axes. Returns a dict with:
    ok [N], lvm_add [N,V], dev_add [N,Dv] (one-hot allocations), raw score [N]
    (int LVM + int device, Binpack strategy), has_storage (scalar bool).

    Called from feasibility, scores, and commit with identical inputs — XLA's CSE
    collapses the three evaluations into one inside the fused scan step.
    """
    N, V = tb.vg_cap.shape
    Dv = tb.sdev_cap.shape[1]
    SL = tb.grp_lvm_size.shape[1]
    SD = tb.grp_sdev_size.shape[1]

    ok = jnp.ones(N, bool)
    lvm_add = jnp.zeros((N, V), _F32)
    for s in range(SL):
        size = tb.grp_lvm_size[g, s]
        nid = tb.grp_lvm_vg[g, s]
        active = size > 0
        free = tb.vg_cap - (cry.vg_req + lvm_add)
        named = nid > 0
        slot_named = tb.vg_nameid == nid
        named_fit = jnp.any(slot_named & (free >= size), axis=1)
        t_named = jnp.argmax(slot_named, axis=1)
        cand = (tb.vg_cap > 0) & (free >= size)
        un_fit = jnp.any(cand, axis=1)
        t_un = jnp.argmin(jnp.where(cand, free, jnp.inf), axis=1)
        fit = jnp.where(named, named_fit, un_fit)
        tgt = jnp.where(named, t_named, t_un)
        take = (jnp.arange(V)[None, :] == tgt[:, None]).astype(_F32)
        lvm_add = lvm_add + take * size * (fit & active)[:, None]
        ok &= fit | ~active

    # Device matching reproduces CheckExclusiveResourceMeetsPVCSize's single merge
    # pass (common.go:290-350) including its quirks: per-media COUNT pre-check;
    # a volume only fails the node when the scan reaches the LAST (largest) still-
    # free device and it is too small; if the last device was consumed earlier the
    # remaining volumes are silently dropped (reference bug kept for parity).
    dev_add = jnp.zeros((N, Dv), _F32)
    dev_acc = jnp.zeros(N, _F32)
    dev_units = jnp.float32(0.0)
    free_start = {}
    last_idx = {}
    for m in (1, 2):
        fs = (tb.sdev_media == m) & (cry.sdev_alloc < 0.5) & (tb.sdev_cap > 0)
        free_start[m] = fs
        caps = jnp.where(fs, tb.sdev_cap, -1.0)
        maxcap = jnp.max(caps, axis=1, keepdims=True)
        is_max = fs & (tb.sdev_cap == maxcap)
        # "last" in the ascending (capacity, index) sort = highest index among maxima
        last_idx[m] = jnp.argmax(is_max * (jnp.arange(Dv)[None, :] + 1), axis=1)
        n_free = jnp.sum(fs.astype(_F32), axis=1)
        n_vols = jnp.sum(
            ((tb.grp_sdev_media[g] == m) & (tb.grp_sdev_size[g] > 0)).astype(_F32)
        )
        ok &= (n_free >= n_vols) | (n_vols == 0)
    for s in range(SD):
        size = tb.grp_sdev_size[g, s]
        media = tb.grp_sdev_media[g, s]
        active = size > 0
        fs1 = jnp.where(media == 2, free_start[2], free_start[1])
        li = jnp.where(media == 2, last_idx[2], last_idx[1])
        free_now = fs1 & (dev_add < 0.5)
        fit_mask = free_now & (tb.sdev_cap >= size)
        fit = jnp.any(fit_mask, axis=1)
        tgt = jnp.argmin(jnp.where(fit_mask, tb.sdev_cap, jnp.inf), axis=1)
        take = (jnp.arange(Dv)[None, :] == tgt[:, None]).astype(_F32)
        take = take * (fit & active)[:, None]
        dev_add = dev_add + take
        last_free = jnp.take_along_axis(free_now, li[:, None], axis=1)[:, 0]
        ok &= ~(active & ~fit & last_free)
        chosen_cap = jnp.sum(take * tb.sdev_cap, axis=1)
        dev_acc += jnp.where(active & fit, size / jnp.maximum(chosen_cap, 1.0), 0.0)
        dev_units += jnp.where(active & fit, 1.0, 0.0)  # only assigned units score

    has_lvm = jnp.any(tb.grp_lvm_size[g] > 0)
    has_dev = jnp.any(tb.grp_sdev_size[g] > 0)
    has_storage = has_lvm | has_dev

    # ScoreLVM (Binpack): avg over used VGs of used/capacity × 10, int-truncated
    used_mask = lvm_add > 0
    vg_frac = jnp.where(used_mask & (tb.vg_cap > 0), lvm_add / jnp.maximum(tb.vg_cap, 1.0), 0.0)
    n_used = jnp.sum(used_mask.astype(_F32), axis=1)
    lvm_raw = jnp.where(
        has_lvm & (n_used > 0),
        _flr(jnp.sum(vg_frac, axis=1) / jnp.maximum(n_used, 1.0) * 10.0),
        0.0,
    )
    dev_raw = jnp.where(
        has_dev & (dev_units > 0), _flr(dev_acc / jnp.maximum(dev_units, 1.0) * 10.0), 0.0
    )
    return {
        "ok": ok | ~has_storage,
        "lvm_add": lvm_add,
        "dev_add": dev_add,
        "raw": lvm_raw + dev_raw,
        "has_storage": has_storage,
    }


@shaped(g="[] i32", forced="[] i32", valid="[] bool")
def feasibility(
    tb: Tables, cry: Carry, g, forced, valid,
    enable_gpu: bool = True, enable_storage: bool = True,
    include_dns: bool = True, include_interpod: bool = True,
    filters: FilterFlags = DEFAULT_FILTERS,
) -> Tuple[jax.Array, dict]:
    """[N] feasibility mask for one pod, plus named per-stage masks for diagnostics.

    `enable_gpu`/`enable_storage` are STATIC: when a batch contains no gpu/storage
    demands the whole plugin subgraph is excluded at trace time (the inert tensor
    math would otherwise cost ~35% of each scan step). `include_dns=False` (also
    static) drops the PodTopologySpread DoNotSchedule filter — used by the live-
    spread wave paths, which re-evaluate that filter against their own running
    counters each wave iteration (schedule_group_serial). `include_interpod=False`
    (static) likewise drops the InterPodAffinity filters — schedule_affinity_wave
    re-evaluates affinity/anti-affinity gates per epoch from its live counter
    rows. `filters` (static) carries --default-scheduler-config per-plugin
    disables."""
    N = tb.alloc.shape[0]
    D = cry.counter.shape[1] - 1

    req = tb.grp_requests[g]
    smask = tb.static_mask[g]

    # NodeResourcesFit (noderesources/fit.go): only requested resources are checked.
    if filters.fit:
        eps = tb.alloc * 1e-6  # absorb f32 noise; never enough to overcommit
        new_req = cry.requested + req[None, :]
        fit_each = (new_req <= tb.alloc + eps) | (req[None, :] == 0)
        fit = jnp.all(fit_each, axis=1) & ~tb.grp_unknown[g]
    else:
        fit_each = jnp.ones((N, tb.alloc.shape[1]), bool)
        fit = jnp.ones(N, bool)

    # NodePorts
    if filters.ports:
        pids = tb.grp_ports[g]
        conflict = jnp.any(cry.port_used[:, pids] & (pids > 0)[None, :], axis=1)
    else:
        conflict = jnp.zeros(N, bool)

    # Counter rows are gathered SELECTIVELY by the static slot indices each
    # plugin carries ([A]/[B]/[Sd] small), never as the full [T, N] table —
    # T grows with every service/affinity selector in the cluster, and a
    # serial step paying T×N gathers for a handful of rows was the dominant
    # cost on service-heavy workloads.
    # InterPodAffinity: required affinity (filtering.go satisfyPodAffinity)
    if include_interpod and filters.interpod:
        aff_ids = tb.req_aff_t[g]
        avalid = aff_ids >= 0
        aids = jnp.maximum(aff_ids, 0)
        aff_rows, aff_at, aff_key, _ = counter_rows_at(tb, cry, aids)
        sat = (aff_key & (aff_at > 0)) | ~avalid[:, None]
        aff_all = jnp.all(sat, axis=0)
        has_aff = jnp.any(avalid)
        totals_aff = jnp.sum(aff_rows[:, :D], axis=1)                      # [A]
        total_aff = jnp.sum(jnp.where(avalid, totals_aff, 0.0))
        bootstrap = has_aff & (total_aff == 0.0) & tb.grp_aff_self[g]
        aff_ok = jnp.where(bootstrap, jnp.ones_like(aff_all), aff_all)

        # incoming required anti-affinity (satisfyPodAntiAffinity)
        anti_ids = tb.req_anti_t[g]
        bvalid = anti_ids >= 0
        bids = jnp.maximum(anti_ids, 0)
        _, anti_at, _, _ = counter_rows_at(tb, cry, bids)
        blocked_in = jnp.any((anti_at > 0) & bvalid[:, None], axis=0)

        # existing pods' required anti-affinity (satisfyExistingPodsAntiAffinity)
        ca_ids = tb.carr_anti_t[g]
        ca_valid = ca_ids >= 0
        ca_at = carrier_rows_at(tb, cry, jnp.maximum(ca_ids, 0))
        blocked_ex = jnp.any((ca_at > 0) & ca_valid[:, None], axis=0)
    else:
        aff_ok = jnp.ones(N, bool)
        blocked_in = jnp.zeros(N, bool)
        blocked_ex = jnp.zeros(N, bool)

    # PodTopologySpread DoNotSchedule (filtering.go Filter)
    if include_dns and filters.spread:
        dns_ids = tb.dns_t[g]
        dvalid = dns_ids >= 0
        dids = jnp.maximum(dns_ids, 0)
        edom = tb.dns_edom[g]                                              # [Sd, D+1]
        cdom, dns_at, dns_key, _ = counter_rows_at(tb, cry, dids)
        min_cnt = jnp.min(jnp.where(edom, cdom, jnp.inf), axis=1)
        min_cnt = jnp.where(jnp.isfinite(min_cnt), min_cnt, 0.0)
        skew = dns_at + tb.dns_self[g][:, None] - min_cnt[:, None]
        dns_ok_each = dns_key & (skew <= tb.dns_maxskew[g][:, None])
        dns_ok = jnp.all(dns_ok_each | ~dvalid[:, None], axis=0)
    else:
        dns_ok = jnp.ones(N, bool)

    # Open-Gpu-Share Filter (open-gpu-share.go:51-81): node total memory must cover
    # the per-GPU request AND the devices must fit all requested units. A device can
    # host multiple units (two-pointer greedy packs units onto one GPU), so the
    # feasibility condition is sum(floor(idle/mem)) >= num.
    if enable_gpu:
        gmem = tb.grp_gpu_mem[g]
        gnum = tb.grp_gpu_num[g]
        has_gpu = gmem > 0
        safe_mem = jnp.maximum(gmem, 1.0)
        gidle = tb.dev_total - cry.dev_used                                # [N, MAXDEV]
        gunits = jnp.where(tb.dev_total > 0, jnp.floor(gidle / safe_mem), 0.0)
        gunits = jnp.maximum(gunits, 0.0)
        node_gpu_total = jnp.sum(tb.dev_total, axis=1)
        gpu_fit = (node_gpu_total >= gmem) & (jnp.sum(gunits, axis=1) >= gnum) & (gnum > 0)
        # pre-assigned gpu-index: AllocateGpuId returns the id without checking
        # device fit (gpunodeinfo.go:247-253); only the node-total check and
        # device existence apply.
        gpu_pre_fit = (node_gpu_total >= gmem) & (gnum > 0) & jnp.any(tb.dev_total > 0, axis=1)
        gpu_fit = jnp.where(tb.grp_gpu_pre[g], gpu_pre_fit, gpu_fit)
        gpu_ok = jnp.where(has_gpu, gpu_fit, jnp.ones_like(gpu_fit))
    else:
        gpu_ok = jnp.ones(N, bool)

    # Open-Local Filter (open-local.go:51-92)
    if enable_storage:
        storage_ok = storage_alloc(tb, cry, g)["ok"]
    else:
        storage_ok = jnp.ones(N, bool)

    feasible = (smask & fit & ~conflict & aff_ok & ~blocked_in & ~blocked_ex
                & dns_ok & gpu_ok & storage_ok)
    feasible &= valid
    iota = jnp.arange(N)
    feasible = jnp.where(forced >= 0, feasible & (iota == forced), feasible)

    stages = {
        "static": smask,
        "taint": tb.mask_taint[g],
        "unsched": tb.mask_unsched[g],
        "affinity": tb.mask_aff[g],
        "extra": tb.mask_extra[g],
        "fit": fit,
        "fit_each": fit_each,
        "ports": ~conflict,
        "pod_affinity": aff_ok,
        "pod_anti": ~(blocked_in | blocked_ex),
        "spread": dns_ok,
        "gpu": gpu_ok,
        "storage": storage_ok,
    }
    return feasible, stages


# Per-plugin score components in the EXACT summation order of the original
# fused total (left-associated adds of weighted terms): summing the dict's
# entries in this order reproduces the historical `scores` expression tree
# bit for bit, so the refactor cannot drift placements. simonxray
# (obs/xray.py) reads the same dict per node for its decision records.
COMPONENT_ORDER = (
    "least", "balanced", "openlocal", "simon", "nodeaff", "taint",
    "interpod", "selector_spread", "topology_spread", "avoid", "image",
    "extra",
)


def components_total(comp: dict) -> jax.Array:
    """Fold per-plugin components into the total, preserving the summation
    order (and therefore the f32 rounding) of the pre-refactor `scores`."""
    total = comp[COMPONENT_ORDER[0]]
    for key in COMPONENT_ORDER[1:]:
        total = total + comp[key]
    return total


@shaped(g="[] i32", feasible="[N] bool")
def score_components(
    tb: Tables, cry: Carry, g, feasible, n_zones: int, enable_storage: bool = True,
    w: ScoreWeights = DEFAULT_WEIGHTS,
) -> dict:
    """All normalized, WEIGHTED plugin score terms over the feasible set —
    {name: [N] f32} in COMPONENT_ORDER. `w` is STATIC
    (--default-scheduler-config weights fold in as constants). The engine's
    scheduling paths consume the sum (`scores` below, unchanged semantics);
    the xray flight recorder fetches the dict itself for per-plugin
    breakdowns of the chosen node and its runner-ups."""
    F = feasible
    alloc_cm = tb.alloc[:, (CPU_I, MEM_I)]
    used = cry.nonzero + tb.grp_nonzero[g][None, :]
    least, balanced = least_balanced(used[:, 0], used[:, 1], alloc_cm[:, 0], alloc_cm[:, 1])

    simon_s = _flr(100.0 * tb.simon_raw[g])
    na_raw = tb.nodeaff_raw[g]
    t_raw = tb.taint_raw[g]

    # InterPodAffinity raw (scoring.go): incoming preferred terms + existing pods'
    # required (HardPodAffinityWeight=1) and preferred terms. Counter AND
    # carrier rows are gathered selectively by per-group static slot indices.
    ip_raw = interpod_raw(tb, cry, g)

    ss_id = tb.ss_t[g]
    has_ss = ss_id >= 0
    ss_idx = jnp.maximum(ss_id, 0)
    pernode = counter_rows_at(tb, cry, ss_idx[None])[1][0]

    # All F-masked normalizer extrema in TWO stacked reductions (each reduction
    # is a separate pass per scan step; floats identical to separate reductions)
    maxes = jnp.max(jnp.where(F[None, :],
                              jnp.stack([simon_s, na_raw, t_raw, ip_raw, pernode]),
                              -jnp.inf), axis=1)
    mins = jnp.min(jnp.where(F[None, :], jnp.stack([simon_s, ip_raw]), jnp.inf),
                   axis=1)

    # Simon max-share + min-max normalize (plugin/simon.go:45-101)
    hi, lo = maxes[0], mins[0]
    rng = hi - lo
    simon = jnp.where((rng > 0) & jnp.isfinite(rng), _flr((simon_s - lo) * 100.0 / rng), 0.0)

    # NodeAffinity preferred (helper.DefaultNormalizeScore, reverse=false)
    na_max = jnp.maximum(maxes[1], 0.0)
    nodeaff = jnp.where(na_max > 0, _flr(na_raw * 100.0 / na_max), 0.0)

    # TaintToleration (DefaultNormalizeScore reverse=true: all-100 when max==0)
    t_max = jnp.maximum(maxes[2], 0.0)
    taint = jnp.where(t_max > 0, 100.0 - _flr(t_raw * 100.0 / t_max), 100.0)

    # InterPodAffinity normalize: zero-initialized min/max (scoring.go)
    ip_max = jnp.maximum(maxes[3], 0.0)
    ip_min = jnp.minimum(mins[1], 0.0)
    ip_rng = ip_max - ip_min
    interpod = jnp.where(ip_rng > 0, _flr(100.0 * (ip_raw - ip_min) / ip_rng), 0.0)

    # SelectorSpread: shared single-source formula (zone sums over feasible
    # nodes only — NormalizeScore iterates scored nodes)
    blended = selector_spread_score(pernode, F, tb.node_zone, max(2, n_zones),
                                    maxN=jnp.maximum(maxes[4], 0.0))
    selector_spread = jnp.where(
        tb.ss_skip[g], 0.0, jnp.where(has_ss, _flr(blended), 100.0)
    )

    # PodTopologySpread ScheduleAnyway scoring: shared single-source formula
    D = cry.counter.shape[1] - 1
    sa_ids = tb.sa_t[g]
    svalid = sa_ids >= 0
    sidx = jnp.maximum(sa_ids, 0)
    _, sa_at, sa_key, sa_dom = counter_rows_at(tb, cry, sidx)
    ignored = jnp.any(svalid[:, None] & ~sa_key, axis=0)
    relevantF = F & ~ignored
    pts = schedule_anyway_score(sa_at, relevantF, sa_dom,
                                svalid, tb.sa_maxskew[g], D)

    # Open-Local Score (open-local.go:94-172): Binpack LVM + device ints, then the
    # plugin's own min-max NormalizeScore. Pods without volumes raw-score 0 on
    # every node → constant → normalizes to 0 (inert).
    if enable_storage:
        st = storage_alloc(tb, cry, g)
        st_raw = st["raw"]
        st_hi = jnp.maximum(jnp.max(jnp.where(F, st_raw, -jnp.inf)), 0.0)
        st_lo_raw = jnp.min(jnp.where(F, st_raw, jnp.inf))
        st_lo = jnp.where(jnp.isfinite(st_lo_raw), st_lo_raw, 0.0)
        st_rng = st_hi - st_lo
        openlocal = jnp.where(
            st["has_storage"] & (st_rng > 0), _flr((st_raw - st_lo) * 100.0 / st_rng), 0.0
        )
    else:
        openlocal = 0.0

    return {
        "least": w.least * least,
        "balanced": w.balanced * balanced,
        "openlocal": w.openlocal * openlocal,
        "simon": (w.simon + w.gpushare) * simon,  # Open-Gpu-Share Score ≡ Simon Score
        "nodeaff": w.nodeaff * nodeaff,
        "taint": w.taint * taint,
        "interpod": w.interpod * interpod,
        "selector_spread": w.ss * selector_spread,
        "topology_spread": w.pts * pts,
        "avoid": w.avoid * tb.avoid_raw[g],
        "image": w.image * tb.image_raw[g],
        "extra": tb.extra_raw[g],  # out-of-tree plugins, pre-weighted at encode time
    }


@shaped(g="[] i32", feasible="[N] bool", ret="[N] f32")
def scores(
    tb: Tables, cry: Carry, g, feasible, n_zones: int, enable_storage: bool = True,
    w: ScoreWeights = DEFAULT_WEIGHTS,
) -> jax.Array:
    """Weighted sum of all normalized plugin scores over the feasible set
    ([N] f32) — `components_total` over `score_components`, summed in the
    historical order so the split-out components cannot drift the total."""
    return components_total(
        score_components(tb, cry, g, feasible, n_zones, enable_storage, w=w))


@shaped(g="[] i32", choice="[] i32", do="[] bool")
def commit(
    tb: Tables, cry: Carry, g, choice, do,
    enable_gpu: bool = True, enable_storage: bool = True,
) -> Carry:
    """Apply one placement to the carry (the Reserve+Bind of the cycle)."""
    T = cry.counter.shape[0]
    Tc = cry.carrier.shape[0]
    D = cry.counter.shape[1] - 1
    c = jnp.maximum(choice, 0)
    dof = do.astype(_F32)

    requested = cry.requested.at[c].add(tb.grp_requests[g] * dof)
    nonzero = cry.nonzero.at[c].add(tb.grp_nonzero[g] * dof)
    pids = tb.grp_ports[g]
    port_used = cry.port_used.at[c, pids].max((pids > 0) & do)

    dom_col = tb.counter_dom[:, c]
    inc = tb.counter_sel_match_g[:, g].astype(_F32) * (dom_col < D) * dof
    counter = cry.counter.at[jnp.arange(T), dom_col].add(inc)

    cdom_col = tb.carr_dom[:, c]
    cinc = tb.grp_carries[g] * (cdom_col < D) * dof
    carrier = cry.carrier.at[jnp.arange(Tc), cdom_col].add(cinc)

    # GPU device allocation (AllocateGpuId, gpunodeinfo.go:232-290): tightest-fit
    # for a single GPU; in-order greedy (multiple units may pack onto one device)
    # for multi-GPU. Mirrored exactly by the host ledger in plugins/gpushare.py.
    if enable_gpu:
        gmem = tb.grp_gpu_mem[g]
        gnum = tb.grp_gpu_num[g]
        safe_mem = jnp.maximum(gmem, 1.0)
        dev_total_c = tb.dev_total[c]                               # [MAXDEV]
        idle_c = dev_total_c - cry.dev_used[c]
        units_c = jnp.maximum(jnp.where(dev_total_c > 0, jnp.floor(idle_c / safe_mem), 0.0), 0.0)
        # multi-GPU: first `gnum` units in device order
        cum = jnp.cumsum(units_c)
        take_multi = jnp.clip(gnum - (cum - units_c), 0.0, units_c)
        # single GPU: lowest-index tightest fit
        fit_dev = (idle_c >= gmem) & (dev_total_c > 0)
        cand = jnp.argmin(jnp.where(fit_dev, idle_c, jnp.inf))
        take_one = (jnp.arange(idle_c.shape[0]) == cand).astype(_F32)
        take = jnp.where(gnum == 1, take_one, take_multi)
        # pre-assigned ids charge exactly the annotated devices (host add_pod)
        take = jnp.where(tb.grp_gpu_pre[g], tb.grp_gpu_take[g], take)
        gdo = dof * (gmem > 0)
        dev_used = cry.dev_used.at[c].add(take * gmem * gdo)
    else:
        dev_used = cry.dev_used

    # Open-Local Bind: bump VG requested, mark devices allocated (open-local.go:215-250)
    if enable_storage:
        st = storage_alloc(tb, cry, g)
        sdo = dof * st["has_storage"].astype(_F32)
        vg_req = cry.vg_req.at[c].add(st["lvm_add"][c] * sdo)
        sdev_alloc = cry.sdev_alloc.at[c].add(st["dev_add"][c] * sdo)
    else:
        vg_req, sdev_alloc = cry.vg_req, cry.sdev_alloc

    return Carry(requested, nonzero, port_used, counter, carrier, dev_used,
                 vg_req, sdev_alloc)


def _step(tb: Tables, cry: Carry, xs, n_zones: int, enable_gpu: bool, enable_storage: bool,
          w: ScoreWeights = DEFAULT_WEIGHTS, filters: FilterFlags = DEFAULT_FILTERS):
    g, forced, valid = xs
    feasible, _ = feasibility(tb, cry, g, forced, valid, enable_gpu, enable_storage,
                              filters=filters)
    any_f = jnp.any(feasible)
    sc = scores(tb, cry, g, feasible, n_zones, enable_storage, w=w)
    masked = jnp.where(feasible, sc, -jnp.inf)
    choice = jnp.argmax(masked).astype(jnp.int32)  # first max → lowest node index
    choice = jnp.where(any_f, choice, jnp.int32(-1))
    new_cry = commit(tb, cry, g, choice, any_f, enable_gpu, enable_storage)
    return new_cry, choice


# Module-level jit so repeated diagnostic calls hit the compile cache.
feasibility_jit = jax.jit(
    feasibility,
    static_argnames=("enable_gpu", "enable_storage", "include_dns",
                     "include_interpod", "filters"),
)


@shaped(g="[] i32", forced="[] i32", valid="[] bool")
def explain_pod(
    tb: Tables, cry: Carry, g, forced, valid, n_zones: int,
    enable_gpu: bool = True, enable_storage: bool = True,
    w: ScoreWeights = DEFAULT_WEIGHTS, filters: FilterFlags = DEFAULT_FILTERS,
):
    """One fused diagnostics dispatch for the xray flight recorder: the
    per-stage filter masks, the total score, and the per-plugin score
    components for one scheduling group against a carry — everything a
    decision record needs, fetched once per (group, segment) instead of once
    per pod. Returns (feasible [N] bool, stages {name: [N] bool},
    total [N] f32, components {name: [N] f32})."""
    feasible, stages = feasibility(
        tb, cry, g, forced, valid, enable_gpu, enable_storage, filters=filters)
    comp = score_components(tb, cry, g, feasible, n_zones, enable_storage, w=w)
    return feasible, stages, components_total(comp), comp


explain_jit = jax.jit(
    explain_pod,
    static_argnames=("n_zones", "enable_gpu", "enable_storage", "w",
                     "filters"),
)


# ------------------------------------------------------------------ wave kernel -------
#
# A run of identical pods (one scheduling group) whose only self-interaction is
# capacity — no storage state, no spread terms, no selector-spread, and no
# affinity/anti-affinity term matching the group itself (hostname-topology
# self-anti-affinity and host ports allowed: each is exactly a per-node
# capacity-1 clamp, with the aggregate commit claiming the port bits) — can be
# committed in *waves* while reproducing the serial one-pod-per-step process
# bit-for-bit. The engine proves eligibility on the host
# (Simulator._wave_eligibility); this kernel proves each wave equals that many
# serial argmax picks:
#
#   * With per-node placement counts j fixed, node n's score is
#     static(n) + least/balanced(usage_n + j_n·req) + norm(F) where every
#     normalization term (Simon/NodeAffinity/TaintToleration/InterPodAffinity
#     min-max) depends only on the feasible SET F — not on j directly. So the
#     score of the (k+1)-th copy on node n is a closed form in k: a score TABLE
#     s[n, k], k < B, computable without placing anything.
#   * Serial scheduling of this group is greedy selection over per-node "heads":
#     repeatedly take max_n s[n, j_n] under the deterministic tie-break (lowest
#     node index — _step's first-max argmax). When each node's score column is
#     non-increasing in k, the greedy's first m picks are EXACTLY the m largest
#     table entries under the key (score desc, node index asc), each node
#     consuming a prefix of its column — i.e. one stable sort of the flattened
#     table schedules up to N·B pods at once. Non-monotone columns (possible:
#     BalancedAllocation can rise as usage evens out) are masked past the first
#     violation and simply defer to the next iteration.
#   * Normalizers stay valid only while the feasible set F is unchanged, and F
#     changes exactly when a node exhausts its capacity. A node's capacity-
#     exhausting entry may therefore be taken only as the LAST pick of a wave —
#     unless removing all exhausted nodes provably leaves every normalizer value
#     unchanged (min/max over a shrinking set is monotone, so end-equality
#     implies invariance throughout), in which case the wave runs to m.
#
# Each while-loop iteration costs one [N,B] elementwise table + an O(NB log NB)
# sort — and typically places min(m, N·B) pods, collapsing the 1-pod-per-scan-
# step bottleneck that capped round 1 at ~15k pods/s (simulator.go:309-348 is
# the serial loop being replaced at scale).

WAVE_BLOCK = 64  # B: max score-table depth = max copies per node per wave iteration
# Score-table entry budget (N*B) above which wave_block_for damps the depth:
# past ~2M entries the per-iteration sort dominates the dispatch (and the
# sharded gather replicates it per shard). 2^21 leaves every <=10k-node
# shape untouched and caps the 100k/1M-node rows at a sort XLA can chew.
_WAVE_TABLE_BUDGET = 1 << 21

# Node-count ceiling for the epoch-amortized sharded wave path. Below it,
# the epoch loop runs as ONE shard_map region paying exactly two collectives
# per epoch (the stacked normalizer all-reduce + the table all-gather) with
# the selection tail replicated on every shard — the right trade in the
# collective-LATENCY regime the hard-predicate wave lives in (small node
# axis, many epochs, each round otherwise paying a cross-device trip).
# Above it, the replicated tail's O(N*B) redundancy outweighs any latency
# saved, so the loop stays on the GSPMD per-round path where XLA shards the
# tail's compute (the 100k/1M-node mesh rows regress ~50% if forced through
# the amortized path). N is static at trace time, so this is a compile-time
# branch — both forms stay bit-identical to serial either way.
_EPOCH_AMORTIZE_MAX_N = int(os.environ.get(
    "OPEN_SIMULATOR_EPOCH_AMORTIZE_MAX_NODES", "2048"))


def wave_block_for(m: int, n: int) -> int:
    """Static score-table depth for an m-pod wave over n nodes: a pow2 in
    [8, WAVE_BLOCK] covering ~8× the mean per-node take, so a 1000-pod segment
    over 5000 nodes builds an [N, 8] table instead of [N, 64] while a
    100k-pod headline still gets full depth. Correctness never depends on
    the depth (hidden entries defer to later iterations), only iteration
    count does — the 8× headroom over the mean take keeps one iteration the
    common case, and the floor of 8 keeps the hidden-continuation bound
    BELOW the flat floor-quantized score runs (~3 copies wide at millicore
    granularity; a depth-2 bound lands inside the run, equal to every
    visible score, and stalls takes to the head fallback). Pow2 bucketing
    keeps the number of distinct compiled wave kernels small.

    Planet-scale damping: the [N, B] table is sorted (top_k ~ full sort on
    CPU) every iteration, and under GSPMD sharding the sort's gather
    replicates that work per shard — at 100k+ nodes the 8x-headroom table
    made the sort THE wall clock of the mesh8_1m row (block 64 -> 16 cut
    the warm 1M-pod dispatch 15.5s -> 5.4s, bit-identical placements).
    Above _WAVE_TABLE_BUDGET entries the depth halves toward the floor of
    8: correctness is depth-independent (see above), and the extra
    iterations at floor depth are cheap next to a 4x smaller sort. Every
    shape with N*B within budget (all the <=10k-node rows) keeps its exact
    old block."""
    b = 8
    target = (8 * m + max(n, 1) - 1) // max(n, 1)
    while b < min(WAVE_BLOCK, target):
        b *= 2
    while b > 8 and n * b > _WAVE_TABLE_BUDGET:
        b //= 2
    return b


def _wave_statics(tb: Tables, cry: Carry, g, w: ScoreWeights = DEFAULT_WEIGHTS):
    """Per-segment constants: ip_raw (counters can't change during the wave) and
    the static score vectors, exactly as scores() computes them. The stacked
    forms let _wave_norms run as TWO masked reductions instead of six — inside
    the group-serial scan each reduction is a separate pass over [N], so this
    is a per-scheduled-pod cost."""
    ip_raw = interpod_raw(tb, cry, g)
    simon_s = _flr(100.0 * tb.simon_raw[g])
    na_raw = tb.nodeaff_raw[g]
    t_raw = tb.taint_raw[g]
    return {
        "ip_raw": ip_raw,
        "simon_s": simon_s,
        "na_raw": na_raw,
        "t_raw": t_raw,
        "max_stack": jnp.stack([simon_s, na_raw, t_raw, ip_raw]),   # [4, N]
        "min_stack": jnp.stack([simon_s, ip_raw]),                  # [2, N]
        "static": (w.avoid * tb.avoid_raw[g] + w.image * tb.image_raw[g]
                   + tb.extra_raw[g]),
    }


def _wave_norms(st: dict, F):
    """The feasible-set-dependent normalizer values (must match scores() —
    the stacked reductions produce the same floats as six separate ones)."""
    maxes = jnp.max(jnp.where(F[None, :], st["max_stack"], -jnp.inf), axis=1)
    mins = jnp.min(jnp.where(F[None, :], st["min_stack"], jnp.inf), axis=1)
    simon_hi = maxes[0]
    simon_lo = mins[0]
    na_max = jnp.maximum(maxes[1], 0.0)
    t_max = jnp.maximum(maxes[2], 0.0)
    ip_max = jnp.maximum(maxes[3], 0.0)
    ip_min = jnp.minimum(mins[1], 0.0)
    return (simon_hi, simon_lo, na_max, t_max, ip_max, ip_min)


def _wave_score_table_rows(alloc_cm, nonzero, grp_nz, st: dict, norms, j,
                           w: ScoreWeights = DEFAULT_WEIGHTS,
                           block: int = WAVE_BLOCK):
    """[rows, B+1] score table from per-node rows: entry (n, k) = score of
    placing the (j_n+k+1)-th copy of group g on node n given current usage.
    Every op is per-node elementwise, so the rows may be the full [N] arrays
    (the unsharded path) or ONE mesh shard's contiguous node block — the
    floats are bit-identical either way, which is what lets the sharded
    epoch loop build its table block-locally and all-gather the result.
    Formulas mirror scores() term by term; the constant-on-F plugins
    (SelectorSpread=100, PodTopologySpread=100, OpenLocal=0) are dropped —
    a uniform shift never changes the ordering the wave consumes."""
    simon_hi, simon_lo, na_max, t_max, ip_max, ip_min = norms
    B = block + 1  # one extra column: the exact first-hidden-entry bound
    copies = j.astype(_F32)[:, None, None] + jnp.arange(1, B + 1, dtype=_F32)[None, :, None]
    used = nonzero[:, None, :] + grp_nz[None, None, :] * copies  # [n,B,2]
    least, balanced = least_balanced(
        used[:, :, 0], used[:, :, 1], alloc_cm[:, None, 0], alloc_cm[:, None, 1])

    rng = simon_hi - simon_lo
    simon = jnp.where((rng > 0) & jnp.isfinite(rng),
                      _flr((st["simon_s"] - simon_lo) * 100.0 / rng), 0.0)
    nodeaff = jnp.where(na_max > 0, _flr(st["na_raw"] * 100.0 / na_max), 0.0)
    taint = jnp.where(t_max > 0, 100.0 - _flr(st["t_raw"] * 100.0 / t_max), 100.0)
    ip_rng = ip_max - ip_min
    interpod = jnp.where(ip_rng > 0, _flr(100.0 * (st["ip_raw"] - ip_min) / ip_rng), 0.0)
    static_n = ((w.simon + w.gpushare) * simon + w.nodeaff * nodeaff
                + w.taint * taint + w.interpod * interpod + st["static"])
    return w.least * least + w.balanced * balanced + static_n[:, None]


def _wave_score_table(tb: Tables, cry: Carry, st: dict, norms, g, j,
                      w: ScoreWeights = DEFAULT_WEIGHTS, block: int = WAVE_BLOCK):
    """[N, B+1] score table over the full node set (see
    _wave_score_table_rows for the per-node formulas)."""
    return _wave_score_table_rows(
        tb.alloc[:, (CPU_I, MEM_I)], cry.nonzero, tb.grp_nonzero[g],
        st, norms, j, w, block)


@shaped(g="[] i32", cap1="[] bool", ret="[N] i32")
def _wave_capacity(tb: Tables, cry: Carry, g, cap1):
    """[N] i32: how many MORE copies of group g each node can take, from the
    closed-form NodeResourcesFit bound (same eps slack as feasibility())."""
    req = tb.grp_requests[g]
    eps = tb.alloc * 1e-6
    room = tb.alloc + eps - cry.requested
    per_res = jnp.where(req[None, :] > 0, jnp.floor(room / jnp.maximum(req[None, :], 1e-30)), jnp.inf)
    cap = jnp.clip(jnp.min(per_res, axis=1), 0.0, 2_147_483_000.0).astype(jnp.int32)
    return jnp.where(cap1, jnp.minimum(cap, 1), cap)


def _wave_gpu_params(tb: Tables, g):
    gmem = tb.grp_gpu_mem[g]
    gnum = jnp.maximum(tb.grp_gpu_num[g], 1.0)
    safe_mem = jnp.maximum(gmem, 1.0)
    return gmem, gnum, safe_mem


def _gpu_capacity(tb: Tables, cry: Carry, g, capacity):
    """Clamp per-node copy capacity by GPU units. Depletion is exactly
    unit-countable: every copy consumes `num` device-units and floor(idle/mem)
    per device is invariant under any single-unit take, so capacity is the
    closed form floor(total_units / num)."""
    gmem, gnum, safe_mem = _wave_gpu_params(tb, g)
    gidle0 = tb.dev_total - cry.dev_used
    gunits0 = jnp.maximum(
        jnp.where(tb.dev_total > 0, jnp.floor(gidle0 / safe_mem), 0.0), 0.0)
    gpu_cap = jnp.floor(jnp.sum(gunits0, axis=1) / gnum).astype(jnp.int32)
    return jnp.where(gmem > 0, jnp.minimum(capacity, gpu_cap), capacity)


def _aggregate_commit(tb: Tables, cry: Carry, g, j, gpu_live: bool) -> Carry:
    """The sum of `sum(j)` serial commit() calls for group g (j = per-node
    placement counts). With gpu_live, replays commit()'s per-copy device
    allocation (tightest-fit / in-order greedy, gpunodeinfo.go:232-290) one
    copy per step for every node in parallel, so the carry's per-device ledger
    matches the serial path bit for bit (j is small: bounded by GPU units)."""
    jf = j.astype(_F32)
    D = cry.counter.shape[1] - 1
    requested = cry.requested + tb.grp_requests[g][None, :] * jf[:, None]
    nonzero = cry.nonzero + tb.grp_nonzero[g][None, :] * jf[:, None]
    # host ports: a placed copy claims the group's port ids on its node (the
    # serial commit's port_used writes). With NodePorts enabled, ports groups
    # ride cap1 so j <= 1; with it disabled j may exceed 1 and the bits —
    # idempotent — are never read.
    pids = tb.grp_ports[g]
    port_used = cry.port_used.at[:, pids].max(
        ((pids > 0)[None, :]) & (j > 0)[:, None])
    # Counter/carrier rows sharing a topology key share their whole domain
    # row, so the per-node counts segment-reduce ONCE per unique topology
    # ([U, N] scatter, U = a handful) and broadcast to the [T]/[Tc] rows as
    # cheap elementwise adds. The old per-row form scattered T×N + Tc×N
    # updates — ~12ms per wave segment at 5k nodes, the dominant fixed cost.
    U = tb.topo_dom.shape[0]
    seg = jnp.zeros((U, D + 1), _F32).at[
        jnp.arange(U)[:, None], tb.topo_dom
    ].add(jf[None, :] * (tb.topo_dom < D))
    counter = (cry.counter
               + tb.counter_sel_match_g[:, g, None].astype(_F32)
               * seg[tb.counter_topo])
    carrier = cry.carrier + tb.grp_carries[g][:, None] * seg[tb.carr_topo]
    dev_used = cry.dev_used
    if gpu_live:
        gmem, gnum, safe_mem = _wave_gpu_params(tb, g)

        def gpu_step(state):
            used, rem = state
            idle = tb.dev_total - used
            units = jnp.maximum(
                jnp.where(tb.dev_total > 0, jnp.floor(idle / safe_mem), 0.0), 0.0)
            cum = jnp.cumsum(units, axis=1)
            take_multi = jnp.clip(gnum - (cum - units), 0.0, units)
            fit_dev = (idle >= gmem) & (tb.dev_total > 0)
            cand = jnp.argmin(jnp.where(fit_dev, idle, jnp.inf), axis=1)
            take_one = (jnp.arange(tb.dev_total.shape[1])[None, :] == cand[:, None]).astype(_F32)
            take = jnp.where(tb.grp_gpu_num[g] == 1, take_one, take_multi)
            do = (rem > 0).astype(_F32)
            return used + take * gmem * do[:, None], rem - (rem > 0).astype(rem.dtype)

        dev_used, _ = jax.lax.while_loop(
            lambda s: jnp.any(s[1] > 0), gpu_step,
            (dev_used, jnp.where(gmem > 0, j, 0)))
    return Carry(requested, nonzero, port_used, counter, carrier,
                 dev_used, cry.vg_req, cry.sdev_alloc)



def wave_kmax(m: int, n: int, block: int) -> int:
    """Static top-k width for a wave dispatch: a pow2 ≥ the segment length
    (one iteration can never take more than m entries), capped at the full
    table size. lax.top_k at a bounded k replaces the full N·B stable sort —
    the sort was ~14ms per iteration at 5k nodes where top_k(1024) is
    ~0.6ms — and pow2 bucketing bounds the compiled variants."""
    cap = max(1, n * block)
    k = 256
    while k < min(m, cap):
        k *= 2
    return min(k, cap)


def _mesh_axis_shards(mesh):
    """(axis_name, shard_count) of a 1-D node mesh, or (None, 1) for any
    mesh the wave kernels treat as unsharded (None, scenario, single-shard).
    The kernels take `mesh` as a STATIC arg, so this resolves at trace time
    and the unsharded path compiles byte-identically to the mesh=None form."""
    if mesh is None or len(mesh.axis_names) != 1:
        return None, 1
    ax = mesh.axis_names[0]
    return ax, int(mesh.shape[ax])


def _wave_candidates_from(table_ext, avail, F, B: int, iota_n, kmax: int):
    """Shared wave-iteration candidate half, given the epoch's [N, B+1]
    score table: the usable-entry mask (capacity, monotone prefix,
    hidden-continuation guard — see schedule_wave's body comments for the
    exactness argument) and the top-kmax candidates in serial's exact pick
    order (score desc, node asc, copy asc — lax.top_k breaks ties by
    ascending flat index, which IS that order on the n-major table). Entries
    beyond kmax rank strictly worse than every visible candidate, so
    truncation only caps one iteration's take — the next iteration (or the
    head fallback) sees them with identical state. Runs on full arrays in
    both the unsharded and the sharded epoch path (post-gather). Returns
    (table, idx_srt, ex_srt, vals) with the last three [kmax]-wide."""
    N = table_ext.shape[0]
    table = table_ext[:, :B]
    ks = jnp.arange(B, dtype=jnp.int32)[None, :]
    in_cap = ks < avail[:, None]
    mono = jnp.cumprod(
        jnp.concatenate(
            [jnp.ones((N, 1), jnp.int32),
             (table[:, 1:] <= table[:, :-1]).astype(jnp.int32)], axis=1),
        axis=1) > 0
    usable = in_cap & mono & F[:, None]

    # hidden-continuation guard: an entry is takeable only if its key
    # (score desc, index asc) strictly beats every OTHER node's first hidden
    # entry (beyond depth B or past a monotonicity break)
    first_bad = jnp.min(jnp.where(mono, B, ks), axis=1)
    k_hid = jnp.minimum(first_bad, B)
    has_hidden = (k_hid < avail) & F
    bound = jnp.where(
        has_hidden,
        jnp.take_along_axis(table_ext, k_hid[:, None], axis=1)[:, 0],
        -jnp.inf,
    )
    b1 = jnp.max(bound)
    i1 = jnp.argmax(bound)  # first max = lowest index among score ties
    bound2 = bound.at[i1].set(-jnp.inf)
    b2 = jnp.max(bound2)
    i2 = jnp.argmax(bound2)
    cut_s = jnp.where(iota_n == i1, b2, b1)
    cut_i = jnp.where(iota_n == i1, i2, i1).astype(jnp.int32)
    beats = (table > cut_s[:, None]) | (
        (table == cut_s[:, None]) & (iota_n[:, None] < cut_i[:, None])
    )
    usable &= beats

    flat_s = jnp.where(usable, table, -jnp.inf).reshape(-1)
    exhaust = (ks == (avail[:, None] - 1)) & usable        # entry that empties n
    vals, flat_pos = jax.lax.top_k(flat_s, kmax)
    idx_srt = (flat_pos // B).astype(jnp.int32)
    ex_srt = exhaust.reshape(-1)[flat_pos].astype(jnp.int32)
    return table, idx_srt, ex_srt, vals


@partial(jax.jit, static_argnames=("gpu_live", "w", "filters", "block", "kmax",
                                   "mesh"))
@shaped(g="[] i32", m="[] i32", cap1="[] bool")
def schedule_wave(tb: Tables, cry: Carry, g, m, cap1, gpu_live: bool = False,
                  w: ScoreWeights = DEFAULT_WEIGHTS,
                  filters: FilterFlags = DEFAULT_FILTERS,
                  block: int = WAVE_BLOCK, kmax: int = 0, mesh=None):
    """Place up to m pods of wave-eligible group g, exactly reproducing m serial
    _step placements. Returns (new carry, per-node counts [N] i32, placed i32).

    cap1: the group carries hostname-topology required anti-affinity matching
    itself, so every node takes at most one pod of this segment (the tensor
    equivalent of satisfyPodAntiAffinity's self-blocking direction).

    gpu_live (static): the group requests shared GPU memory (no pre-assigned
    gpu-index). Score inputs stay static (the Open-Gpu-Share score is Simon's
    formula); capacity and the device-ledger commit are exact — see
    _gpu_capacity and _aggregate_commit.

    block (static): score-table depth (wave_block_for). Correctness never
    depends on it — entries past the depth are exactly what the
    hidden-continuation guard defers to later iterations — only the
    table/sort size vs iteration-count trade-off does. kmax (static, 0 =
    full table): top-k truncation width (wave_kmax); also purely a
    performance knob (tail entries defer to later iterations).

    mesh (static): a 1-D node mesh routes the epoch loop through an explicit
    shard_map region with exactly ONE all-gather per epoch (the score-table
    block merge): each shard builds its own [N/shards, B+1] table block and
    the selection phase runs replicated on the gathered table — placements
    bit-identical to mesh=None because the per-node table arithmetic and the
    post-gather selection are the same floats in the same order. Under GSPMD
    propagation the same loop paid O(10) collectives per EPOCH-internal
    reduction; see schedule_affinity_wave for the all-reduce variant and the
    simonaudit `schedule_affinity_epoch` certificate that pins the census."""
    N = tb.alloc.shape[0]
    B = block
    K = kmax if kmax else N * B
    iota_n = jnp.arange(N, dtype=jnp.int32)
    base_feas, _ = feasibility(
        tb, cry, g, jnp.int32(-1), jnp.asarray(True),
        enable_gpu=gpu_live, enable_storage=False, filters=filters,
    )
    st = _wave_statics(tb, cry, g, w)
    capacity = jnp.where(base_feas, _wave_capacity(tb, cry, g, cap1), 0)
    if not filters.fit:
        # resources unbounded, but cap1 (ports / self-anti-affinity) survives
        capacity = jnp.where(base_feas, 2_147_483_000, 0)
        capacity = jnp.where(cap1, jnp.minimum(capacity, 1), capacity)
    if gpu_live:
        capacity = _gpu_capacity(tb, cry, g, capacity)

    def body_tail(j, placed, m_, norms, table_ext, F, avail, st_full):
        """Selection back half of one epoch, on full-width arrays (the
        sharded path enters here post-gather, replicated on every shard).
        avail may arrive clamped to B+1: every comparison against it in this
        phase has a left side <= B, so the clamp never changes a branch."""
        table, idx_srt, ex_srt, vals = _wave_candidates_from(
            table_ext, avail, F, B, iota_n, K)
        pos = jnp.arange(K, dtype=jnp.int32)
        n_finite = jnp.sum(jnp.isfinite(vals).astype(jnp.int32))
        m_rem = (m_ - placed).astype(jnp.int32)
        m_cand = jnp.minimum(m_rem, n_finite)

        # exhausted nodes within the candidate range; fine to keep them mid-wave
        # only when every normalizer value provably survives their removal
        counts0 = jnp.zeros(N, jnp.int32).at[idx_srt].add((pos < m_cand).astype(jnp.int32))
        leaves = counts0 >= jnp.maximum(avail, 1)
        F_end = F & ~leaves
        norms_end = _wave_norms(st_full, F_end)
        same = jnp.array(True)
        for a, b in zip(norms, norms_end):
            same &= a == b  # ±inf compare equal to themselves; no NaN can arise
        p_ex = jnp.min(jnp.where((ex_srt > 0) & (pos < m_cand), pos, N * B))
        m_take = jnp.where(same, m_cand, jnp.minimum(m_cand, p_ex + 1))

        counts = jnp.zeros(N, jnp.int32).at[idx_srt].add((pos < m_take).astype(jnp.int32))

        # Guaranteed progress: the hidden-continuation guard can mask every
        # entry (e.g. a rising column whose bound dominates the whole table).
        # Serial's next pick is always the best HEAD (each node's k=0 entry),
        # so placing exactly that one pod is unconditionally serial-correct.
        heads = jnp.where(F, table[:, 0], -jnp.inf)
        any_head = jnp.any(F)
        head_pick = jnp.zeros(N, jnp.int32).at[jnp.argmax(heads)].set(1)
        use_head = (m_take == 0) & any_head & (m_rem > 0)
        counts = jnp.where(use_head, head_pick, counts)
        m_take = jnp.where(use_head, jnp.int32(1), m_take)
        return (j + counts, placed + m_take, m_take)

    def cond(state):
        _, placed, last_w = state
        return (last_w > 0) & (placed < m)

    j0 = jnp.zeros(N, jnp.int32)
    ax, shards = _mesh_axis_shards(mesh)
    if (ax is not None and shards > 1 and N % shards == 0
            and N <= _EPOCH_AMORTIZE_MAX_N):
        # ---- epoch-amortized sharded path: the whole loop is ONE shard_map
        # region; each epoch pays exactly one all-reduce (the stacked
        # normalizer pmax) and one all-gather (the table-block merge).
        NL = N // shards
        alloc_cm = tb.alloc[:, (CPU_I, MEM_I)]
        st_norm = {k: st[k] for k in ("max_stack", "min_stack")}

        def loop_sharded(cap_l, feas_l, alloc_l, nz_l, st_l, st_f, grp_nz, m_):
            def body(state):
                j, placed, _ = state
                shard = jax.lax.axis_index(ax)
                j_l = jax.lax.dynamic_slice_in_dim(j, shard * NL, NL)
                avail_l = cap_l - j_l
                F_l = feas_l & (avail_l > 0)
                # one stacked masked reduction in max space (mins ride
                # negated: -max(-x) == min(x) exactly, ±inf included), so the
                # six per-epoch normalizers cost ONE cross-shard all-reduce
                mx = jnp.max(
                    jnp.where(F_l[None, :], st_l["max_stack"], -jnp.inf), axis=1)
                mn = jnp.max(
                    jnp.where(F_l[None, :], -st_l["min_stack"], -jnp.inf), axis=1)
                # simonlint: ignore[collective-in-scan-body] -- epoch-hoisted:
                # the one amortized all-reduce the schedule_affinity_epoch
                # audit certificate pins per epoch body
                red = jax.lax.pmax(jnp.concatenate([mx, mn]), ax)
                norms = (red[0], -red[4], jnp.maximum(red[1], 0.0),
                         jnp.maximum(red[2], 0.0), jnp.maximum(red[3], 0.0),
                         jnp.minimum(-red[5], 0.0))
                table_l = _wave_score_table_rows(
                    alloc_l, nz_l, grp_nz, st_l, norms, j_l, w, B)
                # candidate-merge payload: the table block plus the per-node
                # rows the replicated selection phase reads. avail is clamped
                # to B+1 so it packs exactly into the f32 payload (every
                # comparison against it caps at B).
                pay = jnp.concatenate(
                    [table_l.T, F_l[None].astype(_F32),
                     jnp.minimum(avail_l, B + 1)[None].astype(_F32)], axis=0)
                # simonlint: ignore[collective-in-scan-body] -- epoch-hoisted:
                # the one cross-shard candidate merge per epoch (the
                # "argmax at epoch boundaries" collective)
                full = jax.lax.all_gather(pay, ax, axis=1, tiled=True)
                table_ext = full[:B + 1].T
                F = full[B + 1] > 0
                avail = full[B + 2].astype(jnp.int32)
                return body_tail(j, placed, m_, norms, table_ext, F, avail,
                                 st_f)

            def cond_s(state):
                _, placed, last_w = state
                return (last_w > 0) & (placed < m_)

            return jax.lax.while_loop(
                cond_s, body, (j0, jnp.int32(0), jnp.int32(1)))

        Pn = PartitionSpec(ax)
        j, placed, _ = shard_map(
            loop_sharded, mesh=mesh,
            in_specs=(Pn, Pn, PartitionSpec(ax, None), PartitionSpec(ax, None),
                      {k: (PartitionSpec(None, ax) if v.ndim == 2 else Pn)
                       for k, v in st.items()},
                      {k: PartitionSpec() for k in st_norm},
                      PartitionSpec(), PartitionSpec()),
            out_specs=(PartitionSpec(),) * 3, check_rep=False,
        )(capacity, base_feas, alloc_cm, cry.nonzero, st, st_norm,
          tb.grp_nonzero[g], m)
    else:
        def body(state):
            j, placed, _ = state
            avail = capacity - j                           # copies left per node
            F = base_feas & (avail > 0)
            norms = _wave_norms(st, F)
            table_ext = _wave_score_table(tb, cry, st, norms, g, j, w, B)
            return body_tail(j, placed, m, norms, table_ext, F, avail, st)

        j, placed, _ = jax.lax.while_loop(
            cond, body, (j0, jnp.int32(0), jnp.int32(1)))
    return _aggregate_commit(tb, cry, g, j, gpu_live), j, placed


class AffinityWaveState(NamedTuple):
    """Epoch-loop carry contract for schedule_affinity_wave: the ONLY state an
    epoch may mutate. Leaf shapes/dtypes are fixed for the whole while_loop —
    simonlint's carry-contract rule holds every branch to this declaration."""

    j: jax.Array         # [N] i32: per-node copies placed so far
    cnt_dns: jax.Array   # [Sd, D+1] f32: DoNotSchedule counter rows
    cnt_aff: jax.Array   # [A, D+1] f32: required-affinity counter rows
    cnt_anti: jax.Array  # [B, D+1] f32: incoming anti-affinity counter rows
    cnt_car: jax.Array   # [Ca, D+1] f32: existing-pods-anti carrier rows
    cnt_cw: jax.Array    # [Cw, D+1] f32: weighted (hard) carrier rows
    cnt_ss: jax.Array    # [1, D+1] f32: SelectorSpread counter row
    placed: jax.Array    # [] i32
    last: jax.Array      # [] i32: last epoch's take (progress flag)
    ep_stats: jax.Array  # [3] i32: (epochs run, head-fallback epochs,
    #                      multi-rounds that took >= 1 entry) — the xray /
    #                      segment-timing attribution counters; three scalar
    #                      adds per epoch, negligible against the [N, B] table


@partial(jax.jit,
         static_argnames=("ss_live", "w", "filters", "block", "n_zones",
                          "stats", "mesh"))
@shaped(g="[] i32", m="[] i32", cap1="[] bool")
def schedule_affinity_wave(tb: Tables, cry: Carry, g, m, cap1,
                           ss_live: bool = False,
                           w: ScoreWeights = DEFAULT_WEIGHTS,
                           filters: FilterFlags = DEFAULT_FILTERS,
                           block: int = WAVE_BLOCK, n_zones: int = 2,
                           stats: bool = False, mesh=None):
    """Epoch-batched wave for groups whose hard predicates read their OWN
    running placements: self-matching DoNotSchedule spread at ANY topology
    cardinality (zone-level included), required InterPodAffinity (incl. the
    bootstrap special case), required anti-affinity in both directions
    (incoming terms and existing-pods carriers) on non-hostname topologies,
    and a live SelectorSpread score — the serial one-pod-per-cycle process
    reproduced bit-for-bit in a few device iterations per segment instead of
    one scan step per pod. Returns (new carry, per-node counts [N] i32,
    placed i32); with `stats=True` (static — a distinct compiled program, so
    the engine keys its dispatch signature on it) also a [3] i32 of
    (epochs, head-fallback epochs, productive multi-rounds) for the xray /
    Chrome-trace attribution of the fast path.

    Exactness architecture (generalizing schedule_wave's argument):

      * Live-predicate state is compact: per-term counter/carrier rows
        ([slots, D+1]) kept in the epoch carry (AffinityWaveState) and
        updated by segment-reduced counts — never the full [T, N] gather of
        the general scan step.
      * Per epoch, one [N, B] score table is built with the normalizers of
        serial's CURRENT feasible set F_start and stable-sorted under
        serial's exact tie-break key (score desc, node asc). Required
        affinity and static anti terms gate F as in feasibility(); live
        budget terms (self DNS spread, self anti-affinity) instead meter
        consumption along the sorted order.
      * The MULTI-ROUND inner loop then consumes that one sorted order
        across many frozen-min rounds: each round takes, in position order,
        the per-domain budget prefixes (DNS: q = maxSkew - self + min - cnt
        + 1; anti: q = 1 while the domain count is 0) up to the min-rise cut
        (the entry giving the last min-count eligible domain its first
        placement), then recomputes budgets — so a zone-spread segment
        places its whole run under one table+sort where the old epoch wave
        paid a sort per ~Z pods. Per-domain consumption is always a prefix
        of that domain's sorted entries, so a [D+1] taken-counter per round
        replaces per-entry bookkeeping.
      * Soundness of the big take is PROVED per epoch by a normalizer
        sandwich: every intermediate feasible set F_t satisfies
        S_lo ⊆ F_t ⊆ S_hi, where S_hi ignores live gates and S_lo further
        removes every node that exhausted capacity or was ever budget-
        blocked; min/max normalizers are monotone under set inclusion, so
        norm equality at both ends pins them at every step. InterPodAffinity
        score liveness (the group's own hard carrier) is contained the same
        way: the take is accepted only when ip_raw is uniform over S_hi and
        each live carrier's domain is single-valued there (then the min-max
        normalized term is identically 0 throughout); SelectorSpread
        liveness by freezing maxN (per-node depth caps keep counts at or
        below it) and cutting when zone sums could move.
      * Whenever any proof obligation fails — a bootstrap placement, a
        normalizer that would move, zoned SelectorSpread — the epoch falls
        back to serial's literal next pick (the best head over F_start),
        which is unconditionally exact and guarantees progress.

    block (static): score-table depth, as in schedule_wave. ss_live /
    n_zones (static): live SelectorSpread scoring, as in
    schedule_group_serial."""
    N = tb.alloc.shape[0]
    B = block
    NB = N * B
    K_EP = min(NB, 2048)  # static per-round working-set width (see below)
    LMAX = 32             # min-rise levels batched per multi-level round
    D = cry.counter.shape[1] - 1
    iota_n = jnp.arange(N, dtype=jnp.int32)
    iota_d = jnp.arange(D + 1)
    pos_k = jnp.arange(K_EP, dtype=jnp.int32)
    INF_P = jnp.int32(NB + 1)
    base_feas, _ = feasibility(
        tb, cry, g, jnp.int32(-1), jnp.asarray(True),
        enable_gpu=False, enable_storage=False, include_dns=False,
        include_interpod=False, filters=filters,
    )
    st0 = _wave_statics(tb, cry, g, w)
    capacity = jnp.where(base_feas, _wave_capacity(tb, cry, g, cap1), 0)
    if not filters.fit:
        # resources unbounded, but cap1 (ports / self-anti-affinity) survives
        capacity = jnp.where(base_feas, 2_147_483_000, 0)
        capacity = jnp.where(cap1, jnp.minimum(capacity, 1), capacity)

    # ---- term slots: static ids/doms, live flags, seed rows ----------------
    dids_raw = tb.dns_t[g]                                 # [Sd]
    dvalid = dids_raw >= 0
    dids = jnp.maximum(dids_raw, 0)
    dom_dns = tb.counter_dom[dids]                         # [Sd, N]
    dns_key = dom_dns < D
    edom = tb.dns_edom[g]                                  # [Sd, D+1]
    dself = tb.dns_self[g]
    dskew = tb.dns_maxskew[g]
    live_dns = dvalid & tb.counter_sel_match_g[dids, g] & (dself > 0)
    if not filters.spread:
        dvalid = jnp.zeros_like(dvalid)
        live_dns = jnp.zeros_like(live_dns)
    cnt_dns0 = cry.counter[dids]                           # [Sd, D+1]
    Sd = dids.shape[0]

    aids_raw = tb.req_aff_t[g]                             # [A]
    avalid = aids_raw >= 0
    aids = jnp.maximum(aids_raw, 0)
    dom_aff = tb.counter_dom[aids]                         # [A, N]
    live_aff = avalid & tb.counter_sel_match_g[aids, g]
    cnt_aff0 = cry.counter[aids]
    A = aids.shape[0]

    bids_raw = tb.req_anti_t[g]                            # [Ba]
    bvalid = bids_raw >= 0
    bids = jnp.maximum(bids_raw, 0)
    dom_anti = tb.counter_dom[bids]                        # [Ba, N]
    live_anti = bvalid & tb.counter_sel_match_g[bids, g]
    cnt_anti0 = cry.counter[bids]
    Ba = bids.shape[0]

    ca_raw = tb.carr_anti_t[g]                             # [Ca]
    cavalid = ca_raw >= 0
    ca_ids = jnp.maximum(ca_raw, 0)
    dom_car = tb.carr_dom[ca_ids]                          # [Ca, N]
    car_inc = tb.grp_carries[g][ca_ids]                    # 1.0 when g carries it
    live_car = cavalid & (car_inc > 0)
    cnt_car0 = cry.carrier[ca_ids]
    Ca = ca_ids.shape[0]

    cw_raw = tb.carr_w_t[g]                                # [Cw]
    cwvalid = cw_raw >= 0
    cw_ids = jnp.maximum(cw_raw, 0)
    dom_cw = tb.carr_dom[cw_ids]                           # [Cw, N]
    cw_w = tb.carr_w_w[g]
    cw_inc = tb.grp_carries[g][cw_ids]
    live_cw = cwvalid & (cw_inc > 0)
    cnt_cw0 = cry.carrier[cw_ids]
    Cw = cw_ids.shape[0]

    if not filters.interpod:
        avalid = jnp.zeros_like(avalid)
        live_aff = jnp.zeros_like(live_aff)
        bvalid = jnp.zeros_like(bvalid)
        live_anti = jnp.zeros_like(live_anti)
        cavalid = jnp.zeros_like(cavalid)
        live_car = jnp.zeros_like(live_car)

    # static ip part: preferred terms (a self-matching preferred counter
    # routes to the serial scan, so these rows never move during the segment)
    pref_ids = tb.pref_t[g]
    pvalid = pref_ids >= 0
    pw = tb.pref_w[g]
    _, pref_at, _, _ = counter_rows_at(tb, cry, jnp.maximum(pref_ids, 0))
    ip_pref = jnp.sum(jnp.where(pvalid[:, None], pw[:, None] * pref_at, 0.0),
                      axis=0)                              # [N]

    ss_idx = jnp.maximum(tb.ss_t[g], 0)
    dom_ss = tb.counter_dom[ss_idx][None]                  # [1, N]
    cnt_ss0 = cry.counter[ss_idx][None]                    # [1, D+1]
    ss_match = (tb.counter_sel_match_g[ss_idx, g]
                & (tb.ss_t[g] >= 0)).astype(_F32)[None]    # [1]
    if ss_live:
        zones = tb.node_zone
        Z = max(2, n_zones)

    # counter increments one group placement applies (commit() semantics)
    inc_dns = (tb.counter_sel_match_g[dids, g] & dvalid).astype(_F32)
    inc_aff = (tb.counter_sel_match_g[aids, g] & avalid).astype(_F32)
    inc_anti = (tb.counter_sel_match_g[bids, g] & bvalid).astype(_F32)
    inc_car = car_inc * cavalid.astype(_F32)
    inc_cw = cw_inc * cwvalid.astype(_F32)

    # live budget terms (consume per-domain budgets along the sorted order).
    # The multi-round path composes: exactly ONE live DNS term, or ANY number
    # of live anti terms sharing one topology (identical domain rows — the
    # ubiquitous both-directions self-anti pair composes into one combined
    # meter: a domain is consumable iff every term's count is 0, and one take
    # blocks it under all of them).
    n_dns = jnp.sum(live_dns.astype(jnp.int32))
    n_anti = (jnp.sum(live_anti.astype(jnp.int32))
              + jnp.sum(live_car.astype(jnp.int32)))
    n_budget = n_dns + n_anti
    has_budget = n_budget >= 1

    def sel(live, rows):
        """Sum per-slot rows over live slots (the callers divide by the live
        count or prove the slots identical, so sums are exact where used)."""
        return jnp.sum(jnp.where(live[:, None], rows, 0), axis=0)

    dom_sum = (sel(live_dns, dom_dns) + sel(live_anti, dom_anti)
               + sel(live_car, dom_car))
    # identical dom rows under budget_composes ⇒ the mean IS the row
    dom_live = (dom_sum // jnp.maximum(n_budget, 1)).astype(jnp.int32)   # [N]
    doms_same = (jnp.all(~live_dns[:, None] | (dom_dns == dom_live[None, :]))
                 & jnp.all(~live_anti[:, None] | (dom_anti == dom_live[None, :]))
                 & jnp.all(~live_car[:, None] | (dom_car == dom_live[None, :])))
    budget_composes = (n_budget <= 1) | ((n_dns == 0) & doms_same)
    edom_live = sel(live_dns, edom.astype(_F32)) > 0             # [D+1]
    skew_live = jnp.sum(jnp.where(live_dns, dskew, 0.0))
    self_live = jnp.sum(jnp.where(live_dns, dself, 0.0))
    is_dns_live = jnp.any(live_dns)
    # combined count units one take adds to the composed meter
    inc_live = (jnp.sum(jnp.where(live_dns, inc_dns, 0.0))
                + jnp.sum(jnp.where(live_anti, inc_anti, 0.0))
                + jnp.sum(jnp.where(live_car, inc_car, 0.0)))
    # live DNS terms demand the topology key (static per node)
    dns_key_live_ok = jnp.all(dns_key | ~live_dns[:, None], axis=0)

    def norm_stacks(nd, ip_raw, pernode0):
        rows = [nd["simon_s"], nd["na_raw"], nd["t_raw"], ip_raw]
        if ss_live:
            rows.append(pernode0)
        return jnp.stack(rows), jnp.stack([nd["simon_s"], ip_raw])

    def norm_vals(max_stack, min_stack, F):
        maxes = jnp.max(jnp.where(F[None, :], max_stack, -jnp.inf), axis=1)
        mins = jnp.min(jnp.where(F[None, :], min_stack, jnp.inf), axis=1)
        return maxes, mins

    def norms_eq(a, b):
        same = jnp.array(True)
        for x, y in zip(a, b):
            same &= jnp.all(x == y)  # ±inf compare equal; no NaN can arise
        return same

    aff_self = tb.grp_aff_self[g]
    # node-axis inputs of the per-epoch front half. The sharded path feeds
    # one contiguous shard block of each into epoch_head — every op there is
    # per-node elementwise or a gather from replicated [slots, D+1] rows, so
    # a block computes exactly the full-width slice of the same floats.
    nd_full = {
        "feas": base_feas, "cap": capacity,
        "alloc_cm": tb.alloc[:, (CPU_I, MEM_I)], "nonzero": cry.nonzero,
        "simon_s": st0["simon_s"], "na_raw": st0["na_raw"],
        "t_raw": st0["t_raw"], "static": st0["static"], "ip_pref": ip_pref,
        "dom_dns": dom_dns, "dom_aff": dom_aff, "dom_anti": dom_anti,
        "dom_car": dom_car, "dom_cw": dom_cw, "dom_ss": dom_ss,
    }
    # replicated prologue values, threaded as explicit arguments because the
    # sharded loop lives inside a shard_map region (which cannot close over
    # traced values); the serial path reads the same dict so both fronts and
    # the shared tail consume one source of truth.
    repl = {
        "m": m, "grp_nz": tb.grp_nonzero[g], "aff_self": aff_self,
        "edom": edom, "dself": dself, "dskew": dskew, "dvalid": dvalid,
        "avalid": avalid, "bvalid": bvalid, "cavalid": cavalid,
        "cwvalid": cwvalid, "cw_w": cw_w, "live_dns": live_dns,
        "live_anti": live_anti, "live_car": live_car, "live_cw": live_cw,
        "inc_dns": inc_dns, "inc_aff": inc_aff, "inc_anti": inc_anti,
        "inc_car": inc_car, "inc_cw": inc_cw, "ss_match": ss_match,
        "dom_live": dom_live, "edom_live": edom_live,
        "skew_live": skew_live, "self_live": self_live,
        "is_dns_live": is_dns_live, "has_budget": has_budget,
        "inc_live": inc_live, "budget_composes": budget_composes,
        "dom_dns": dom_dns, "dom_aff": dom_aff, "dom_anti": dom_anti,
        "dom_car": dom_car, "dom_cw": dom_cw, "dom_ss": dom_ss,
        "st_simon": st0["simon_s"], "st_na": st0["na_raw"],
        "st_t": st0["t_raw"],
    }
    if ss_live:
        repl["zones"] = zones

    def epoch_head(j_w, cnts, nd, rp):
        """Width-agnostic epoch front half: live gates, feasible sets and
        live-score stacks from the epoch-start counter rows. `nd` may hold
        the full [N] node arrays or one mesh shard's contiguous block —
        identical floats either way (see nd_full)."""
        cnt_dns, cnt_aff, cnt_anti, cnt_car, cnt_cw, cnt_ss = cnts
        dom_dns = nd["dom_dns"]; dom_aff = nd["dom_aff"]
        dom_anti = nd["dom_anti"]; dom_car = nd["dom_car"]
        dom_cw = nd["dom_cw"]; dom_ss = nd["dom_ss"]
        base_feas = nd["feas"]; ip_pref = nd["ip_pref"]
        edom = rp["edom"]; dself = rp["dself"]; dskew = rp["dskew"]
        dvalid = rp["dvalid"]; avalid = rp["avalid"]; bvalid = rp["bvalid"]
        cavalid = rp["cavalid"]; cwvalid = rp["cwvalid"]; cw_w = rp["cw_w"]
        live_dns = rp["live_dns"]; live_anti = rp["live_anti"]
        live_car = rp["live_car"]
        dns_key = dom_dns < D
        dns_key_live_ok = jnp.all(dns_key | ~live_dns[:, None], axis=0)
        avail = nd["cap"] - j_w

        # ---- live gates from epoch-start rows (feasibility() term for term)
        cnt_at_d = jnp.take_along_axis(cnt_dns, dom_dns, axis=1)     # [Sd, N]
        min_d = jnp.min(jnp.where(edom, cnt_dns, jnp.inf), axis=1)
        min_d = jnp.where(jnp.isfinite(min_d), min_d, 0.0)
        skew_ok = dns_key & (cnt_at_d + dself[:, None] - min_d[:, None]
                             <= dskew[:, None])
        dns_ok = jnp.all(skew_ok | ~dvalid[:, None], axis=0)
        dns_ok_static = jnp.all(skew_ok | ~dvalid[:, None] | live_dns[:, None],
                                axis=0)

        at_a = jnp.take_along_axis(cnt_aff, dom_aff, axis=1)         # [A, N]
        sat = ((dom_aff < D) & (at_a > 0)) | ~avalid[:, None]
        aff_all = jnp.all(sat, axis=0)
        has_aff = jnp.any(avalid)
        totals_a = jnp.sum(cnt_aff[:, :D], axis=1)
        total_aff = jnp.sum(jnp.where(avalid, totals_a, 0.0))
        bootstrap = has_aff & (total_aff == 0.0) & rp["aff_self"]
        aff_ok = jnp.where(bootstrap, jnp.ones_like(aff_all), aff_all)

        at_b = jnp.take_along_axis(cnt_anti, dom_anti, axis=1)       # [Ba, N]
        blocked_in = jnp.any((at_b > 0) & bvalid[:, None], axis=0)
        blocked_in_st = jnp.any((at_b > 0) & bvalid[:, None]
                                & ~live_anti[:, None], axis=0)
        at_c = jnp.take_along_axis(cnt_car, dom_car, axis=1)         # [Ca, N]
        blocked_ex = jnp.any((at_c > 0) & cavalid[:, None], axis=0)
        blocked_ex_st = jnp.any((at_c > 0) & cavalid[:, None]
                                & ~live_car[:, None], axis=0)

        # F_start: serial's CURRENT feasible set. F_hi: live budget gates
        # lifted — the sandwich's upper set (every F_t during the epoch is
        # between F_lo and F_hi; live-gated nodes re-enter as min rises).
        room = base_feas & (avail > 0) & aff_ok
        F_start = room & dns_ok & ~blocked_in & ~blocked_ex
        F_hi = (room & dns_ok_static & ~blocked_in_st & ~blocked_ex_st
                & dns_key_live_ok)

        # ---- live scores: ip_raw from live carrier rows; ss pernode
        cw_at = jnp.take_along_axis(cnt_cw, dom_cw, axis=1)          # [Cw, N]
        ip_raw = ip_pref + jnp.sum(
            jnp.where(cwvalid[:, None], cw_w[:, None] * cw_at, 0.0), axis=0)
        pernode0 = jnp.take_along_axis(cnt_ss, dom_ss, axis=1)[0]    # [N]
        max_stack, min_stack = norm_stacks(nd, ip_raw, pernode0)
        return (avail, F_start, F_hi, bootstrap, ip_raw, pernode0,
                max_stack, min_stack)

    def front_full(j, cnts):
        """Serial epoch front: epoch_head on the full node set plus direct
        normalizer reductions and the full-width table build — byte-for-byte
        the ops of the pre-mesh kernel."""
        (avail, F_start, F_hi, bootstrap, ip_raw, pernode0, max_stack,
         min_stack) = epoch_head(j, cnts, nd_full, repl)
        maxes_s, mins_s = norm_vals(max_stack, min_stack, F_start)
        maxes_h, mins_h = norm_vals(max_stack, min_stack, F_hi)
        norms6 = (maxes_s[0], mins_s[0], jnp.maximum(maxes_s[1], 0.0),
                  jnp.maximum(maxes_s[2], 0.0), jnp.maximum(maxes_s[3], 0.0),
                  jnp.minimum(mins_s[1], 0.0))
        # Uniform normalizer inputs (simon/nodeaff/taint/ip identical across
        # F_hi — identical-node clusters, the common fleet shape): every
        # normalized term is then the same CONSTANT on every non-empty
        # feasible subset, and F_t always contains the node being placed, so
        # norms are pinned without any sandwich — blocking/unblocking cannot
        # move them. This is what keeps the multi-round path on for workloads
        # where every domain cycles through a budget block (the sandwich's
        # lower set would be empty there).
        base_hi_min = jnp.min(jnp.where(F_hi[None, :], max_stack[:4], jnp.inf),
                              axis=1)
        uniform_base = jnp.all(maxes_h[:4] == base_hi_min) & jnp.any(F_hi)

        # ip-liveness containment: the group's own hard carrier moves ip_raw
        # with every placement. The frozen table stays exact only when the
        # normalized term is pinned at 0 throughout: ip_raw uniform over F_hi
        # AND each live carrier's domain single-valued there (so it STAYS
        # uniform as counts grow).
        has_live_cw = jnp.any(live_cw)
        anyF = jnp.any(F_hi)
        dmax = jnp.max(jnp.where(F_hi[None, :], dom_cw, -1), axis=1)
        dmin = jnp.min(jnp.where(F_hi[None, :], dom_cw, D + 2), axis=1)
        dom_same = jnp.all(~live_cw | (dmax == dmin))
        ip_hi = jnp.max(jnp.where(F_hi, ip_raw, -jnp.inf))
        ip_lo = jnp.min(jnp.where(F_hi, ip_raw, jnp.inf))
        ip_safe = ~has_live_cw | ~anyF | (dom_same & (ip_hi == ip_lo))

        # ---- score table under serial's current normalizers --------------
        st_ep = dict(st0)
        st_ep["ip_raw"] = ip_raw
        table_ext = _wave_score_table(tb, cry, st_ep, norms6, g, j, w, B)
        table_ext, k_cap, ss_multi_ok = apply_zone(
            table_ext, maxes_s, pernode0, F_start,
            zones if ss_live else None)
        return (avail, F_start, F_hi, table_ext, k_cap, ss_multi_ok,
                max_stack, min_stack, maxes_s, mins_s, maxes_h, mins_h,
                uniform_base, bootstrap, ip_safe)

    def apply_zone(table_ext, maxes_s, pernode0, F_start, zones_f):
        """Replicated full-width zone blend + depth caps (ss_live). On the
        sharded path this runs POST-gather: zone sums are cross-node
        scatters, and doing them replicated keeps the scatter order — and
        therefore the floats — identical to serial, with no extra
        collective."""
        if ss_live:
            # live SelectorSpread, selector_spread_score term for term with
            # maxN/zone sums frozen at epoch start; column c = c prior takes
            # on the node this epoch, so pernode = row count + c
            maxN = jnp.maximum(maxes_s[4], 0.0)
            pernode_k = pernode0[:, None] + jnp.arange(B + 1, dtype=_F32)[None, :]
            node_score = jnp.where(maxN > 0, 100.0 * (maxN - pernode_k) / maxN,
                                   100.0)
            nz_count = jnp.where(F_start, pernode0, 0.0)
            zone_sums = jnp.zeros((Z,), _F32).at[zones_f].add(nz_count)
            maxZ = jnp.max(zone_sums.at[0].set(0.0))
            have_zones = jnp.any(F_start & (zones_f > 0))
            zscore = jnp.where(maxZ > 0, 100.0 * (maxZ - zone_sums[zones_f]) / maxZ,
                               100.0)
            blended = jnp.where(
                (have_zones & (zones_f > 0))[:, None],
                node_score * (1.0 / 3.0) + zscore[:, None] * (2.0 / 3.0),
                node_score)
            table_ext = table_ext + w.ss * _flr(blended)
            # depth cap: a take pushing a count past frozen maxN would move
            # it — such entries are hidden (next epoch re-freezes maxN)
            k_cap = jnp.clip(maxN - pernode0, 0.0, float(B)).astype(jnp.int32)
            ss_multi_ok = ~have_zones  # zone sums move with every zoned take
        else:
            k_cap = jnp.full(N, B, jnp.int32)
            ss_multi_ok = jnp.array(True)
        return table_ext, k_cap, ss_multi_ok

    def epoch_tail(state, fo, rp):
        """Selection / multi-round / commit back half of one epoch, shared
        verbatim by both paths: the sharded front enters here post-gather
        with every input replicated full-width, so the two paths run the
        same floats by construction."""
        (j, cnt_dns, cnt_aff, cnt_anti, cnt_car, cnt_cw, cnt_ss, placed, _,
         ep_stats) = state
        (avail, F_start, F_hi, table_ext, k_cap, ss_multi_ok, max_stack,
         min_stack, maxes_s, mins_s, maxes_h, mins_h, uniform_base,
         bootstrap, ip_safe) = fo
        dom_live = rp["dom_live"]; edom_live = rp["edom_live"]
        skew_live = rp["skew_live"]; self_live = rp["self_live"]
        is_dns_live = rp["is_dns_live"]; has_budget = rp["has_budget"]
        inc_live = rp["inc_live"]; budget_composes = rp["budget_composes"]
        live_dns = rp["live_dns"]; live_anti = rp["live_anti"]
        live_car = rp["live_car"]; ss_match = rp["ss_match"]
        dom_dns = rp["dom_dns"]; dom_aff = rp["dom_aff"]
        dom_anti = rp["dom_anti"]; dom_car = rp["dom_car"]
        dom_cw = rp["dom_cw"]; dom_ss = rp["dom_ss"]
        inc_dns = rp["inc_dns"]; inc_aff = rp["inc_aff"]
        inc_anti = rp["inc_anti"]; inc_car = rp["inc_car"]
        inc_cw = rp["inc_cw"]
        m_rem = (rp["m"] - placed).astype(jnp.int32)
        table = table_ext[:, :B]

        # ---- candidates: capacity, monotone prefix, hidden-continuation ---
        ks = jnp.arange(B, dtype=jnp.int32)[None, :]
        in_cap = ks < jnp.minimum(avail, k_cap.astype(avail.dtype))[:, None]
        mono = jnp.cumprod(
            jnp.concatenate(
                [jnp.ones((N, 1), jnp.int32),
                 (table[:, 1:] <= table[:, :-1]).astype(jnp.int32)], axis=1),
            axis=1) > 0
        usable = in_cap & mono & F_hi[:, None]
        first_bad = jnp.min(jnp.where(mono, B, ks), axis=1)
        k_hid = jnp.minimum(jnp.minimum(first_bad, B), k_cap)
        has_hidden = (k_hid < avail) & F_hi
        bound = jnp.where(
            has_hidden,
            jnp.take_along_axis(table_ext, k_hid[:, None], axis=1)[:, 0],
            -jnp.inf)
        b1 = jnp.max(bound)
        i1 = jnp.argmax(bound)
        bound2 = bound.at[i1].set(-jnp.inf)
        b2 = jnp.max(bound2)
        i2 = jnp.argmax(bound2)
        cut_s = jnp.where(iota_n == i1, b2, b1)
        cut_i = jnp.where(iota_n == i1, i2, i1).astype(jnp.int32)
        beats = (table > cut_s[:, None]) | (
            (table == cut_s[:, None]) & (iota_n[:, None] < cut_i[:, None]))
        usable &= beats

        flat_s = jnp.where(usable, table, -jnp.inf).reshape(-1)
        # Rounds only ever consume from the TOP of the candidate order:
        # lax.top_k at a static K replaces the full N·B stable sort (ties
        # break by ascending flat index = score desc, node asc, copy asc —
        # serial's exact pick order on the n-major table). Sound for any K —
        # tail entries rank strictly worse than every visible entry, so
        # serial reaches them only once no visible entry is consumable (the
        # next epoch, or the head fallback, with identical state) — the same
        # argument as the per-node depth guard. K also bounds round cost at
        # O(K + D) instead of O(N·B).
        vals_k, flat_pos = jax.lax.top_k(flat_s, K_EP)
        idx_srt = (flat_pos // B).astype(jnp.int32)
        cand = jnp.isfinite(vals_k)
        dom_srt = dom_live[idx_srt]                                  # [K]
        # occ_all: rank among same-domain visible candidates in sorted order
        # (one sort + run ranking; per-domain consumption is always a prefix)
        dkey_srt = jnp.where(cand, dom_srt, D + 1)
        d2, p2 = jax.lax.sort((dkey_srt, pos_k), num_keys=2, is_stable=True)
        run_start = jnp.concatenate([jnp.ones((1,), bool), d2[1:] != d2[:-1]])
        seg_start = jax.lax.associative_scan(
            jnp.maximum, jnp.where(run_start, pos_k, 0))
        occ_all = jnp.zeros(K_EP, _F32).at[p2].set(
            (pos_k - seg_start).astype(_F32))

        cnt_live = (sel(live_dns, cnt_dns) + sel(live_anti, cnt_anti)
                    + sel(live_car, cnt_car))                        # [D+1]
        pre_norms_ok = (uniform_base
                        | norms_eq((maxes_s[:4], mins_s), (maxes_h[:4], mins_h)))
        if ss_live:
            # the frozen maxN must also hold for gate-lifted nodes (a blocked
            # node with a higher count would move it when re-admitted)
            pre_norms_ok &= maxes_s[4] == maxes_h[4]
        use_multi_pre = (budget_composes & ~bootstrap & ip_safe & ss_multi_ok
                         & pre_norms_ok)

        # ---- multi-round consumption of the one sorted order --------------
        # taken_d counts ENTRIES consumed per domain; cnt units scale by the
        # composed increment (inc_live) where counts are compared
        def round_cond(rs):
            _, _, got, last_r, _, _ = rs
            return use_multi_pre & (last_r > 0) & (got < m_rem)

        def round_body(rs):
            taken_d, counts_ep, got, _, everb, rounds = rs
            cnt_now = cnt_live + taken_d * inc_live
            min_c = jnp.min(jnp.where(edom_live, cnt_now, jnp.inf))
            min_c = jnp.where(jnp.isfinite(min_c), min_c, 0.0)
            # entry budgets at the CURRENT min: DNS adds 1 count per entry;
            # composed anti terms admit one entry while every count is 0
            q_dns = jnp.maximum(skew_live - self_live + min_c - cnt_now + 1.0,
                                0.0)
            q_anti = jnp.where(cnt_now > 0, 0.0, 1.0)
            q = jnp.where(is_dns_live, q_dns, q_anti)                # [D+1]
            q = jnp.where(has_budget, q, jnp.inf)
            q = q.at[D].set(jnp.inf)       # absent-key nodes are never metered
            t_e = taken_d[dom_srt]
            q_e = q[dom_srt]
            r_e = occ_all - t_e            # within-round rank (entry units)
            remaining = cand & (r_e >= 0)
            consumable = remaining & (r_e < q_e)
            m_left = m_rem - got

            # ---- multi-LEVEL take: process up to LMAX min-rises at once.
            # Closed forms per entry: it becomes legal at level
            # l_e = max(1, rank - budget + 2) (budgets grow by 1 per rise),
            # and consuming it raises its domain's count to min + lc_e —
            # i.e. it is exactly the entry whose take completes rise lc_e
            # for that domain. Rise l completes when every eligible domain
            # reaches min + l, so p_rise_l = max over needed entries with
            # lc == l of their position (each is legal at its own level and
            # precedes its p_rise by construction); a needed level some
            # domain cannot provide caps the ladder. An entry is then taken
            # by level L iff l_e <= L and pos <= p_rise_L (p_rise is
            # monotone in l), and everything it skips stays over-budget
            # through level L — the single-rise argument applied per level.
            dom_cnt_e = cnt_now[dom_srt]
            l_e = jnp.maximum(1.0, r_e - q_e + 2.0)
            lc_e = dom_cnt_e + r_e + 1.0 - min_c
            elig_e = edom_live[dom_srt]
            lc_ok = (remaining & elig_e & (lc_e >= 1.0)
                     & (lc_e <= float(LMAX)))
            lc_i = jnp.clip(lc_e, 0.0, float(LMAX + 1)).astype(jnp.int32)
            prise = jnp.full((LMAX + 2,), -1, jnp.int32).at[lc_i].max(
                jnp.where(lc_ok, pos_k, -1))
            provided = jnp.zeros((LMAX + 2,), _F32).at[lc_i].add(
                lc_ok.astype(_F32))
            # needed_l = #eligible domains still below min + l
            delta = jnp.where(edom_live, cnt_now - min_c, jnp.inf)
            hist = jnp.zeros((LMAX + 2,), _F32).at[
                jnp.clip(delta, 0.0, float(LMAX + 1)).astype(jnp.int32)
            ].add(edom_live.astype(_F32))
            needed = jnp.cumsum(hist)  # needed for level l = hist[< l] summed
            lvl = jnp.arange(LMAX + 2)
            # L_used: longest prefix of levels whose every needed domain
            # provided its rise-completing entry
            ok_l = jnp.where((lvl >= 1) & (lvl <= LMAX),
                             (provided == needed[jnp.maximum(lvl - 1, 0)])
                             .astype(_F32), 1.0)
            L_used = jnp.sum(((jnp.cumprod(ok_l) > 0)
                              & (lvl >= 1) & (lvl <= LMAX)).astype(jnp.int32))
            prise_cum = jax.lax.associative_scan(jnp.maximum, prise)
            P_L = prise_cum[L_used]
            take_full = (remaining & (l_e <= L_used.astype(_F32))
                         & (pos_k <= P_L))
            n_full = jnp.sum(take_full.astype(jnp.int32))
            use_full = (is_dns_live & (L_used >= 1) & (n_full <= m_left)
                        & (n_full > 0))

            # ---- single-rise take (the exact chronological tail/partial
            # round, and the anti/composed path)
            at_min = edom_live & (cnt_now == min_c) & is_dns_live
            first_pos = jnp.full((D + 1,), INF_P, jnp.int32).at[dom_srt].min(
                jnp.where(consumable, pos_k, INF_P))
            rise = jnp.max(jnp.where(at_min, first_pos, -1))
            unreached = jnp.any(at_min & (first_pos >= INF_P))
            p_rise = jnp.where(jnp.any(at_min) & ~unreached, rise, INF_P)
            take_pre = consumable & (pos_k <= p_rise)
            rank = jax.lax.associative_scan(jnp.add, take_pre.astype(jnp.int32))
            take_one = take_pre & (rank <= m_left)
            n_one = jnp.minimum(m_left, rank[-1])

            take = jnp.where(use_full, take_full, take_one)
            n_take = jnp.where(use_full, n_full, n_one)
            counts_r = jnp.zeros(N, jnp.int32).at[idx_srt].add(
                take.astype(jnp.int32))
            consumed_d = jnp.zeros(D + 1, _F32).at[dom_srt].add(
                take.astype(_F32))
            # sandwich bookkeeping: any node whose live domain was blocked at
            # the round start or fully consumed this round left F mid-epoch;
            # a multi-level round cycles most domains through a block, so it
            # marks every eligible/touched domain (conservative — the uniform
            # shortcut is what keeps the fast path on)
            blocked_d = (q < 1.0) | ((consumed_d >= q) & jnp.isfinite(q))
            blocked_d |= use_full & (edom_live | (consumed_d > 0))
            everb = everb | (blocked_d[dom_live] & has_budget)
            taken_d = taken_d + consumed_d * (iota_d < D)
            return (taken_d, counts_ep + counts_r, got + n_take, n_take, everb,
                    rounds + (n_take > 0).astype(jnp.int32))

        def round_chain(rs):
            # 4 rounds per device iteration: a drained round is a no-op (zero
            # take leaves the state fixed), so over-running is harmless and
            # the while-loop bookkeeping amortizes 4×
            for _ in range(4):
                rs = round_body(rs)
            return rs

        rs0 = (jnp.zeros(D + 1, _F32), jnp.zeros(N, jnp.int32), jnp.int32(0),
               jnp.int32(1), jnp.zeros(N, bool), jnp.int32(0))
        _, counts_multi, placed_multi, _, everb, rounds_run = jax.lax.while_loop(
            round_cond, round_chain, rs0)

        # normalizer sandwich: S_lo ⊆ every F_t ⊆ F_hi ⇒ equality at both
        # ends pins every intermediate normalizer (min/max are monotone).
        # Uniform inputs skip it (norms constant on every non-empty subset).
        exhausted = counts_multi >= avail
        F_lo = F_hi & ~everb & ~exhausted
        maxes_l, mins_l = norm_vals(max_stack, min_stack, F_lo)
        lo_norms_ok = (uniform_base
                       | norms_eq((maxes_h[:4], mins_h), (maxes_l[:4], mins_l)))
        if ss_live:
            lo_norms_ok &= maxes_h[4] == maxes_l[4]
        use_multi = use_multi_pre & (placed_multi > 0) & lo_norms_ok

        # head fallback: serial's single next pick is always exact
        heads = jnp.where(F_start, table[:, 0], -jnp.inf)
        any_head = jnp.any(F_start)
        head_pick = jnp.zeros(N, jnp.int32).at[jnp.argmax(heads)].set(1)
        use_head = ~use_multi & any_head & (m_rem > 0)
        counts = jnp.where(use_multi, counts_multi,
                           jnp.where(use_head, head_pick, 0))
        m_take = jnp.where(use_multi, placed_multi,
                           jnp.where(use_head, jnp.int32(1), jnp.int32(0)))

        # fold the takes into every live counter/carrier row (sentinel column
        # never counts — commit() masks dom >= D)
        cf = counts.astype(_F32)
        col_real = (iota_d[None, :] < D)

        def upd(rows, doms, incs):
            S = rows.shape[0]
            add = jnp.zeros_like(rows).at[
                jnp.arange(S)[:, None], doms].add(cf[None, :] * incs[:, None])
            return rows + add * col_real

        return AffinityWaveState(
            j + counts,
            upd(cnt_dns, dom_dns, inc_dns),
            upd(cnt_aff, dom_aff, inc_aff),
            upd(cnt_anti, dom_anti, inc_anti),
            upd(cnt_car, dom_car, inc_car),
            upd(cnt_cw, dom_cw, inc_cw),
            upd(cnt_ss, dom_ss, ss_match),
            placed + m_take, m_take,
            ep_stats + jnp.stack([jnp.int32(1),
                                  use_head.astype(jnp.int32),
                                  jnp.where(use_multi, rounds_run,
                                            jnp.int32(0))]))

    def cond(state: AffinityWaveState):
        return (state.last > 0) & (state.placed < m)

    def body(state: AffinityWaveState):
        cnts = (state.cnt_dns, state.cnt_aff, state.cnt_anti, state.cnt_car,
                state.cnt_cw, state.cnt_ss)
        return epoch_tail(state, front_full(state.j, cnts), repl)

    init = AffinityWaveState(
        jnp.zeros(N, jnp.int32), cnt_dns0, cnt_aff0, cnt_anti0, cnt_car0,
        cnt_cw0, cnt_ss0, jnp.int32(0), jnp.int32(1),
        jnp.zeros(3, jnp.int32))
    ax, shards = _mesh_axis_shards(mesh)
    if (ax is not None and shards > 1 and N % shards == 0
            and N <= _EPOCH_AMORTIZE_MAX_N):
        NL = N // shards

        def front_sharded(j, cnts, ndl, rp):
            """Sharded epoch front: epoch_head on this shard's node block,
            then exactly TWO collectives for the whole epoch — one pmax
            carrying every normalizer reduction in max space (mins ride
            negated: -max(-x) == min(x) exactly, ±inf included) and one
            all_gather of the score-table block + per-node epoch rows. The
            selection tail then runs replicated on the gathered full-width
            arrays, i.e. the serial floats."""
            shard = jax.lax.axis_index(ax)
            j_l = jax.lax.dynamic_slice_in_dim(j, shard * NL, NL)
            (avail_l, F_start_l, F_hi_l, bootstrap, ip_raw_l, pernode0_l,
             max_stack_l, min_stack_l) = epoch_head(j_l, cnts, ndl, rp)

            def mred(stack, Fm):
                return jnp.max(jnp.where(Fm[None, :], stack, -jnp.inf),
                               axis=1)

            dom_cw_f = ndl["dom_cw"].astype(_F32)  # exact: doms < 2**24
            parts = jnp.concatenate([
                mred(max_stack_l, F_start_l), mred(-min_stack_l, F_start_l),
                mred(max_stack_l, F_hi_l), mred(-min_stack_l, F_hi_l),
                mred(-max_stack_l[:4], F_hi_l),
                jnp.max(jnp.where(F_hi_l[None, :], dom_cw_f, -1.0), axis=1),
                jnp.max(jnp.where(F_hi_l[None, :], -dom_cw_f,
                                  -float(D + 2)), axis=1),
            ])
            # ONE all-reduce per epoch: every reduction the old lowering paid
            # per round, batched into a single stacked max-space operand
            red = jax.lax.pmax(parts, ax)  # simonlint: ignore[collective-in-scan-body] -- the epoch-amortized collective itself
            ns = 5 if ss_live else 4
            o = 0
            maxes_s = red[o:o + ns]; o += ns
            mins_s = -red[o:o + 2]; o += 2
            maxes_h = red[o:o + ns]; o += ns
            mins_h = -red[o:o + 2]; o += 2
            base_hi_min = -red[o:o + 4]; o += 4
            dmax = red[o:o + Cw]; o += Cw
            dmin = -red[o:o + Cw]
            norms6 = (maxes_s[0], mins_s[0], jnp.maximum(maxes_s[1], 0.0),
                      jnp.maximum(maxes_s[2], 0.0),
                      jnp.maximum(maxes_s[3], 0.0),
                      jnp.minimum(mins_s[1], 0.0))
            st_ep_l = {"simon_s": ndl["simon_s"], "na_raw": ndl["na_raw"],
                       "t_raw": ndl["t_raw"], "static": ndl["static"],
                       "ip_raw": ip_raw_l}
            table_l = _wave_score_table_rows(
                ndl["alloc_cm"], ndl["nonzero"], rp["grp_nz"], st_ep_l,
                norms6, j_l, w, B)
            rows = [table_l.T, F_start_l[None].astype(_F32),
                    F_hi_l[None].astype(_F32),
                    # avail clamps to B+1: packs exactly in f32, and every
                    # tail comparison has a left side <= B so order is kept
                    jnp.minimum(avail_l, B + 1)[None].astype(_F32),
                    ip_raw_l[None]]
            if ss_live:
                rows.append(pernode0_l[None])
            pay = jnp.concatenate(rows, axis=0)
            # ONE all-gather per epoch: the cross-shard argmax at the epoch
            # boundary, generalized — gathering the [B+2+k, NL] payload
            # replicates the table so the tail's argmax/top_k tie-breaks run
            # bit-identical to serial instead of via a lossy packed argmax
            full = jax.lax.all_gather(pay, ax, axis=1, tiled=True)  # simonlint: ignore[collective-in-scan-body] -- the epoch-amortized collective itself
            table_ext = full[:B + 1].T
            F_start = full[B + 1] > 0
            F_hi = full[B + 2] > 0
            avail = full[B + 3].astype(jnp.int32)
            ip_raw_f = full[B + 4]
            pernode0_f = full[B + 5] if ss_live else None
            srows = [rp["st_simon"], rp["st_na"], rp["st_t"], ip_raw_f]
            if ss_live:
                srows.append(pernode0_f)
            max_stack_f = jnp.stack(srows)
            min_stack_f = jnp.stack([rp["st_simon"], ip_raw_f])
            uniform_base = jnp.all(maxes_h[:4] == base_hi_min) & jnp.any(F_hi)
            dom_same = jnp.all(~rp["live_cw"] | (dmax == dmin))
            # ip_hi/ip_lo ARE maxes_h[3]/mins_h[1] (the same reduction of the
            # same row — serial merely computes them twice)
            ip_safe = (~jnp.any(rp["live_cw"]) | ~jnp.any(F_hi)
                       | (dom_same & (maxes_h[3] == mins_h[1])))
            table_ext, k_cap, ss_multi_ok = apply_zone(
                table_ext, maxes_s, pernode0_f, F_start,
                rp["zones"] if ss_live else None)
            return (avail, F_start, F_hi, table_ext, k_cap, ss_multi_ok,
                    max_stack_f, min_stack_f, maxes_s, mins_s, maxes_h,
                    mins_h, uniform_base, bootstrap, ip_safe)

        def loop_sharded(ndl, rp, state0):
            def body_s(state):
                cnts = (state.cnt_dns, state.cnt_aff, state.cnt_anti,
                        state.cnt_car, state.cnt_cw, state.cnt_ss)
                return epoch_tail(
                    state, front_sharded(state.j, cnts, ndl, rp), rp)

            def cond_s(state):
                return (state.last > 0) & (state.placed < rp["m"])

            return jax.lax.while_loop(cond_s, body_s, state0)

        _row2 = ("alloc_cm", "nonzero")  # [N, 2]: node axis FIRST

        def nd_spec(k, v):
            if v.ndim == 1:
                return PartitionSpec(ax)
            return (PartitionSpec(ax, None) if k in _row2
                    else PartitionSpec(None, ax))

        state_specs = AffinityWaveState(*((PartitionSpec(),) * 10))
        final = shard_map(
            loop_sharded, mesh=mesh,
            in_specs=({k: nd_spec(k, v) for k, v in nd_full.items()},
                      {k: PartitionSpec() for k in repl}, state_specs),
            out_specs=state_specs, check_rep=False,
        )(nd_full, repl, init)
    else:
        final = jax.lax.while_loop(cond, body, init)
    out = (_aggregate_commit(tb, cry, g, final.j, False), final.j,
           final.placed)
    return out + (final.ep_stats,) if stats else out


@partial(jax.jit, static_argnames=("w", "filters", "ss_live", "sa_live", "n_zones"))
@shaped(g="[] i32", valid="[P] bool", cap1="[] bool")
def schedule_group_serial(tb: Tables, cry: Carry, g, valid, cap1,
                          w: ScoreWeights = DEFAULT_WEIGHTS,
                          filters: FilterFlags = DEFAULT_FILTERS,
                          ss_live: bool = False, sa_live: bool = False,
                          n_zones: int = 2):
    """Serial scheduling of one group whose placements feed back into its own
    scoring/filtering through per-node copy counts — self-matching
    DoNotSchedule topology-spread constraints and/or a live SelectorSpread
    counter (a service-backed workload spreading against itself: the most
    common real-cluster app shape) — as a FUSED scan: exactly the reference's
    one-pod-per-cycle process (same per-step feasible set and scores as
    _step/scores()), but each step is specialized to what can actually change
    within a single-group run — per-node copy counts and the group's own
    spread/selector counters. Everything else (taints, affinity counters,
    carriers, normalizer *inputs*, static score vectors) is provably constant
    and hoisted out, so a step costs a few [N]-wide ops + an [Sd, D+1] reduce
    instead of the general scan step's [T, N] gathers and [T, D+1] scatters
    (the reason spread-heavy workloads crawled at ~400 pods/s before this
    kernel).

    `valid` is a [P] bool mask (padded scan length); returns
    (new carry, per-node counts [N] i32, placed i32).

    ss_live (static): compute the SelectorSpread score live — per-node count
    plus 2/3-zone blending (selector_spread.go:104-160) over base counts + j.
    n_zones (static): zone-table size for the blend, as in scores().
    sa_live (static): compute the PodTopologySpread ScheduleAnyway score live
    — the group carries soft spread terms, whose counters (for self-matching
    selectors) and relevant-set normalizers move with every placement.

    Dropped-constant notes (argmax-invariant, same as _wave_score_table):
    SelectorSpread when NOT ss_live (ss_skip => 0 for explicit-constraint
    pods), PodTopologySpread score when NOT sa_live (no ScheduleAnyway terms
    => 100 on F), OpenLocal (0)."""
    N = tb.alloc.shape[0]
    D = cry.counter.shape[1] - 1
    base_feas, _ = feasibility(
        tb, cry, g, jnp.int32(-1), jnp.asarray(True),
        enable_gpu=False, enable_storage=False, include_dns=False, filters=filters,
    )
    st = _wave_statics(tb, cry, g, w)
    capacity = jnp.where(base_feas, _wave_capacity(tb, cry, g, cap1), 0)
    if not filters.fit:
        # resources unbounded, but cap1 (ports / self-anti-affinity) survives
        capacity = jnp.where(base_feas, 2_147_483_000, 0)
        capacity = jnp.where(cap1, jnp.minimum(capacity, 1), capacity)

    dids_raw = tb.dns_t[g]                                 # [Sd]
    dvalid = dids_raw >= 0
    dids = jnp.maximum(dids_raw, 0)
    dom_rows = tb.counter_dom[dids]                        # [Sd, N]
    key_present = dom_rows < D
    edom = tb.dns_edom[g]                                  # [Sd, D+1]
    dself = tb.dns_self[g][:, None]
    dskew = tb.dns_maxskew[g][:, None]
    dmatch = (tb.counter_sel_match_g[dids, g] & dvalid).astype(_F32)  # [Sd]
    cnt0 = cry.counter[dids]                               # [Sd, D+1]
    Sd = dids.shape[0]
    alloc_cm = tb.alloc[:, (CPU_I, MEM_I)]                 # [N, 2]
    gnz = tb.grp_nonzero[g]
    if ss_live:
        # SelectorSpread live state: the group's own counter is hostname-
        # topology (encode.py ss_counter), so per-node counts are exactly
        # base counts + j; zone sums re-aggregate per step over current F
        ss_id = jnp.maximum(tb.ss_t[g], 0)
        # one row's gather, not the [T, N] cnt_at scores() needs for interpod
        base_pernode = counter_rows_at(tb, cry, ss_id[None])[1][0]     # [N]
        zones = tb.node_zone
        Z = max(2, n_zones)
    if sa_live:
        # ScheduleAnyway live state: per-term counter rows; counts move for
        # self-matching selectors, the relevant-set normalizers move with F
        sa_ids = tb.sa_t[g]                                # [Ss]
        svalid = sa_ids >= 0
        sidx = jnp.maximum(sa_ids, 0)
        sa_dom_rows = tb.counter_dom[sidx]                 # [Ss, N]
        sa_ignored = jnp.any(svalid[:, None] & (sa_dom_rows >= D), axis=0)
        sa_match = (tb.counter_sel_match_g[sidx, g] & svalid).astype(_F32)
        sa_maxskew = tb.sa_maxskew[g]
        cnt_sa0 = cry.counter[sidx]                        # [Ss, D+1]
        Ss = sidx.shape[0]
    else:
        cnt_sa0 = jnp.zeros((1, D + 1), _F32)              # inert carry slot

    # Precompute the count-dependent score column OUTSIDE the scan: entry
    # (n, k) = w.least*least + w.balanced*balanced for the (k+1)-th copy on
    # node n — identical f32 expressions to the in-step math, so the gathered
    # values are bit-equal. j_n < P always, so K = P covers every reachable
    # count. Skipped (None) for pathological sizes where the [N, P] table
    # would dominate memory; the step then computes the pair inline.
    N_, P_ = tb.alloc.shape[0], valid.shape[0]
    if N_ * P_ <= 64_000_000:
        copies_k = jnp.arange(1, P_ + 1, dtype=_F32)                   # [P]
        used_k = (cry.nonzero[:, None, :]
                  + gnz[None, None, :] * copies_k[None, :, None])      # [N, P, 2]
        lst, bal = least_balanced(used_k[:, :, 0], used_k[:, :, 1],
                                  alloc_cm[:, None, 0], alloc_cm[:, None, 1])
        lb_table = w.least * lst + w.balanced * bal                    # [N, P]
    else:
        lb_table = None

    def step(state: SerialState, ok):
        j, cnt, cnt_sa = state
        # live DoNotSchedule filter, mirroring feasibility() term for term
        cnt_at = jnp.take_along_axis(cnt, dom_rows, axis=1)           # [Sd, N]
        min_c = jnp.min(jnp.where(edom, cnt, jnp.inf), axis=1)
        min_c = jnp.where(jnp.isfinite(min_c), min_c, 0.0)
        dns_ok_each = key_present & (cnt_at + dself - min_c[:, None] <= dskew)
        dns_ok = jnp.all(dns_ok_each | ~dvalid[:, None], axis=0)
        F = base_feas & (capacity - j > 0) & dns_ok
        any_f = jnp.any(F) & ok
        # scores: least/balanced move with j; the rest normalize over F. The
        # candidate pod itself counts toward its own usage (scores() adds
        # grp_nonzero once), hence j + 1.
        if lb_table is None:
            used = cry.nonzero + gnz[None, :] * (j + 1).astype(_F32)[:, None]
            least, balanced = least_balanced(
                used[:, 0], used[:, 1], alloc_cm[:, 0], alloc_cm[:, 1])
            lb = w.least * least + w.balanced * balanced
        else:
            lb = jnp.take_along_axis(lb_table, j[:, None], axis=1)[:, 0]
        simon_hi, simon_lo, na_max, t_max, ip_max, ip_min = _wave_norms(st, F)
        rng = simon_hi - simon_lo
        simon = jnp.where((rng > 0) & jnp.isfinite(rng),
                          _flr((st["simon_s"] - simon_lo) * 100.0 / rng), 0.0)
        nodeaff = jnp.where(na_max > 0, _flr(st["na_raw"] * 100.0 / na_max), 0.0)
        taint = jnp.where(t_max > 0, 100.0 - _flr(st["t_raw"] * 100.0 / t_max), 100.0)
        ip_rng = ip_max - ip_min
        interpod = jnp.where(ip_rng > 0,
                             _flr(100.0 * (st["ip_raw"] - ip_min) / ip_rng), 0.0)
        score = (lb + (w.simon + w.gpushare) * simon + w.nodeaff * nodeaff
                 + w.taint * taint + w.interpod * interpod + st["static"])
        if ss_live:
            # live SelectorSpread: shared formula with pernode = base + j
            pernode = base_pernode + j.astype(_F32)
            score = score + w.ss * _flr(
                selector_spread_score(pernode, F, zones, Z))
        if sa_live:
            # live ScheduleAnyway: shared formula over current counts + F
            cnt_at_sa = jnp.take_along_axis(cnt_sa, sa_dom_rows, axis=1)
            score = score + w.pts * schedule_anyway_score(
                cnt_at_sa, F & ~sa_ignored, sa_dom_rows, svalid, sa_maxskew, D)
        choice = jnp.argmax(jnp.where(F, score, -jnp.inf)).astype(jnp.int32)
        do = any_f.astype(jnp.int32)
        j = j.at[choice].add(do)
        cnt = cnt.at[jnp.arange(Sd), dom_rows[:, choice]].add(dmatch * do)
        if sa_live:
            # sentinel-masked like commit(): a pod may land on a node missing
            # the SA topology key (score-only plugin, unlike the DNS filter)
            sa_dom_c = sa_dom_rows[:, choice]
            cnt_sa = cnt_sa.at[jnp.arange(Ss), sa_dom_c].add(
                sa_match * (sa_dom_c < D) * do)
        return SerialState(j, cnt, cnt_sa), do

    final_state, dos = jax.lax.scan(
        step, SerialState(jnp.zeros(N, jnp.int32), cnt0, cnt_sa0), valid)
    j = final_state.j
    placed = jnp.sum(dos)
    return _aggregate_commit(tb, cry, g, j, False), j, placed


@partial(jax.jit, static_argnames=("n_zones", "enable_gpu", "enable_storage", "w", "filters"))
@shaped(pod_group="[P] i32", forced_node="[P] i32", valid="[P] bool")
def schedule_batch(
    tb: Tables, cry: Carry, pod_group, forced_node, valid, n_zones: int,
    enable_gpu: bool = True, enable_storage: bool = True,
    w: ScoreWeights = DEFAULT_WEIGHTS, filters: FilterFlags = DEFAULT_FILTERS,
):
    """Scan the whole batch; returns (final carry, placements[P] int32, -1=unschedulable)."""

    def body(c: Carry, xs):
        return _step(tb, c, xs, n_zones, enable_gpu, enable_storage, w, filters)

    final, choices = jax.lax.scan(body, cry, (pod_group, forced_node, valid))
    return final, choices


# ---------------------------------------------------------------------------
# Multi-candidate capacity probing: evaluate S node-active masks in ONE
# dispatch. The capacity planner's doubling/refinement search asks "would this
# batch schedule on base + n template nodes?" for several n at once; each
# candidate differs only in which node columns are active, so the fan-out is a
# vmap over (carry, active) with the tables closed over — `active` folds into
# static_mask, making an inactive node exactly a pad_batch_tables phantom
# (infeasible everywhere, excluded from every normalizer, zero counts). Under
# a ('scenarios', 'nodes') mesh (parallel/mesh.py) the vmapped axis shards as
# data parallelism, one candidate lane per device.
# ---------------------------------------------------------------------------


def _mask_active(tb: Tables, active) -> Tables:
    """Fold a [N] node-active mask into the static group mask (the single
    feasibility root every filter ANDs into)."""
    return tb._replace(static_mask=tb.static_mask & active[None, :])


@partial(jax.jit, static_argnames=("gpu_live", "w", "filters", "block", "kmax"))
@shaped(active_s="[S, N] bool", g="[] i32", m="[] i32", cap1="[] bool")
def probe_wave_fanout(tb: Tables, cry_s: Carry, active_s, g, m, cap1,
                      gpu_live: bool = False,
                      w: ScoreWeights = DEFAULT_WEIGHTS,
                      filters: FilterFlags = DEFAULT_FILTERS,
                      block: int = WAVE_BLOCK, kmax: int = 0):
    """schedule_wave over S candidate node-active masks in one dispatch.
    cry_s is a Carry whose leaves carry a leading [S] axis. Returns
    (carry_s, placed_s [S] i32)."""

    def one(cry: Carry, active):
        c2, _, placed = schedule_wave(
            _mask_active(tb, active), cry, g, m, cap1,
            gpu_live=gpu_live, w=w, filters=filters, block=block, kmax=kmax)
        return c2, placed

    return jax.vmap(one)(cry_s, active_s)


@partial(jax.jit, static_argnames=("w", "filters", "ss_live", "sa_live", "n_zones"))
@shaped(active_s="[S, N] bool", g="[] i32", valid="[P] bool", cap1="[] bool")
def probe_group_serial_fanout(tb: Tables, cry_s: Carry, active_s, g, valid, cap1,
                              w: ScoreWeights = DEFAULT_WEIGHTS,
                              filters: FilterFlags = DEFAULT_FILTERS,
                              ss_live: bool = False, sa_live: bool = False,
                              n_zones: int = 2):
    """schedule_group_serial over S candidate node-active masks in one
    dispatch. Returns (carry_s, placed_s [S] i32)."""

    def one(cry: Carry, active):
        c2, _, placed = schedule_group_serial(
            _mask_active(tb, active), cry, g, valid, cap1,
            w=w, filters=filters, ss_live=ss_live, sa_live=sa_live,
            n_zones=n_zones)
        return c2, placed

    return jax.vmap(one)(cry_s, active_s)


@partial(jax.jit, static_argnames=("ss_live", "w", "filters", "block", "n_zones"))
@shaped(active_s="[S, N] bool", g="[] i32", m="[] i32", cap1="[] bool")
def probe_affinity_wave_fanout(tb: Tables, cry_s: Carry, active_s, g, m, cap1,
                               ss_live: bool = False,
                               w: ScoreWeights = DEFAULT_WEIGHTS,
                               filters: FilterFlags = DEFAULT_FILTERS,
                               block: int = WAVE_BLOCK, n_zones: int = 2):
    """schedule_affinity_wave over S candidate node-active masks in one
    dispatch. Returns (carry_s, placed_s [S] i32)."""

    def one(cry: Carry, active):
        c2, _, placed = schedule_affinity_wave(
            _mask_active(tb, active), cry, g, m, cap1, ss_live=ss_live,
            w=w, filters=filters, block=block, n_zones=n_zones)
        return c2, placed

    return jax.vmap(one)(cry_s, active_s)


@partial(jax.jit, static_argnames=("n_zones", "enable_gpu", "enable_storage", "w", "filters"))
@shaped(active_s="[S, N] bool", pod_group="[P] i32", forced_node="[P] i32", valid="[P] bool")
def probe_serial_fanout(tb: Tables, cry_s: Carry, active_s, pod_group,
                        forced_node, valid, n_zones: int,
                        enable_gpu: bool = True, enable_storage: bool = True,
                        w: ScoreWeights = DEFAULT_WEIGHTS,
                        filters: FilterFlags = DEFAULT_FILTERS):
    """schedule_batch over S candidate node-active masks in one dispatch.
    Returns (carry_s, placed_s [S] i32) — the probe only needs counts, so the
    per-pod choices stay on device and reduce to a sum per lane."""

    def one(cry: Carry, active):
        c2, choices = schedule_batch(
            _mask_active(tb, active), cry, pod_group, forced_node, valid,
            n_zones=n_zones, enable_gpu=enable_gpu,
            enable_storage=enable_storage, w=w, filters=filters)
        return c2, jnp.sum((choices >= 0).astype(jnp.int32))

    return jax.vmap(one)(cry_s, active_s)


@partial(jax.jit, static_argnames=("n_zones", "enable_gpu", "enable_storage", "w", "filters"))
@shaped(active_s="[S, N] bool", pod_group="[P] i32", forced_node="[P] i32",
        valid_s="[S, P] bool")
def serve_whatif_fanout(tb: Tables, cry_s: Carry, active_s, pod_group,
                        forced_node, valid_s, n_zones: int,
                        enable_gpu: bool = True, enable_storage: bool = True,
                        w: ScoreWeights = DEFAULT_WEIGHTS,
                        filters: FilterFlags = DEFAULT_FILTERS):
    """schedule_batch over S independent what-if REQUESTS in one dispatch —
    simonserve's micro-batching kernel (serve/batch.py). Unlike the capacity
    probe fan-outs, the lanes are heterogeneous: they share one union-encoded
    pod batch but differ in BOTH the node-active mask (the shared image's
    live-node mask minus request-local drains) and a per-lane `valid` mask
    selecting only that request's rows out of the union. An invalid scan step
    is a provable no-op (choices -1, zero carry commit), so lane i is exactly
    the serial schedule_batch of request i's own pods, in order, against the
    shared cluster image — union padding can never change a placement.
    Returns (carry_s, placed_s [S] i32); per-pod choices stay on device."""

    def one(cry: Carry, active, valid):
        c2, choices = schedule_batch(
            _mask_active(tb, active), cry, pod_group, forced_node, valid,
            n_zones=n_zones, enable_gpu=enable_gpu,
            enable_storage=enable_storage, w=w, filters=filters)
        return c2, jnp.sum((choices >= 0).astype(jnp.int32))

    return jax.vmap(one)(cry_s, active_s, valid_s)


@partial(jax.jit, static_argnames=("w", "filters", "block", "kmax"))
@shaped(active_s="[S, N] bool", g_s="[S] i32", m_s="[S] i32",
        cap1_s="[S] bool")
def serve_wave_fanout(tb: Tables, cry_s: Carry, active_s, g_s, m_s, cap1_s,
                      w: ScoreWeights = DEFAULT_WEIGHTS,
                      filters: FilterFlags = DEFAULT_FILTERS,
                      block: int = WAVE_BLOCK, kmax: int = 0):
    """schedule_wave over S uniform-replica what-if REQUESTS in one dispatch
    — simonserve's fast lane. The dominant what-if shape ("deploy/scale m
    more replicas of template T") is one wave-eligible group per request, so
    each lane runs ONE fused feasibility/score pass + top-k commit instead
    of m padded serial scan steps: the lane is provably identical to m
    serial placements (the schedule_wave contract), with its own (group,
    replica count, cap1, node-active overlay). Returns (carry_s,
    placed_s [S] i32)."""

    def one(cry: Carry, active, g, m, cap1):
        c2, _, placed = schedule_wave(
            _mask_active(tb, active), cry, g, m, cap1,
            gpu_live=False, w=w, filters=filters, block=block, kmax=kmax)
        return c2, placed

    return jax.vmap(one)(cry_s, active_s, g_s, m_s, cap1_s)


def _sweep_wave_step(tb: Tables, cry: Carry, xs, w: ScoreWeights,
                     filters: FilterFlags, block: int, kmax: int):
    """One wave segment of a sweep lane's chain: (carry, j[N] counts)."""
    g, m, cap1 = xs
    c2, j, _ = schedule_wave(
        tb, cry, g, m, cap1,
        gpu_live=False, w=w, filters=filters, block=block, kmax=kmax)
    return c2, j


@partial(jax.jit, static_argnames=("w", "filters", "block", "kmax"))
@shaped(active_s="[S, N] bool", g_sk="[S, K] i32", m_sk="[S, K] i32",
        cap1_sk="[S, K] bool")
def sweep_wave_fanout(tb: Tables, cry_s: Carry, active_s, g_sk, m_sk, cap1_sk,
                      w: ScoreWeights = DEFAULT_WEIGHTS,
                      filters: FilterFlags = DEFAULT_FILTERS,
                      block: int = WAVE_BLOCK, kmax: int = 0):
    """K chained schedule_wave segments per lane over S scenario overlays —
    simonsweep's fast lane (sweep/runner.py). Each scenario lane carries its
    OWN chain of (group, replica-count, cap1) wave segments [S, K] plus its
    own node-active overlay and seed copy, so one dispatch evaluates S
    independent cluster futures whose workloads are per-lane template x
    replica mixes. Within a lane, segment k's output carry feeds segment
    k+1 (lax.scan), exactly the engine's chained per-segment dispatch; a
    padding segment (m == 0) provably commits nothing (the wave loop never
    runs and _aggregate_commit scales every update by the zero counts).
    Returns (carry_s, counts_skn [S, K, N] i32): per-segment per-node
    placement counts — the placement census parity is asserted against a
    fresh serial run per lane (pods of one group are interchangeable, the
    engine's own stitching rule)."""

    def lane(cry: Carry, active, g_k, m_k, cap1_k):
        tbm = _mask_active(tb, active)

        def step(c: Carry, xs):
            return _sweep_wave_step(tbm, c, xs, w, filters, block, kmax)

        c2, j_k = jax.lax.scan(step, cry, (g_k, m_k, cap1_k))
        return c2, j_k

    return jax.vmap(lane)(cry_s, active_s, g_sk, m_sk, cap1_sk)


@partial(jax.jit, static_argnames=("n_zones", "enable_gpu", "enable_storage", "w", "filters"))
@shaped(active_s="[S, N] bool", pod_group_s="[S, P] i32",
        forced_node_s="[S, P] i32", valid_s="[S, P] bool")
def sweep_whatif_fanout(tb: Tables, cry_s: Carry, active_s, pod_group_s,
                        forced_node_s, valid_s, n_zones: int,
                        enable_gpu: bool = True, enable_storage: bool = True,
                        w: ScoreWeights = DEFAULT_WEIGHTS,
                        filters: FilterFlags = DEFAULT_FILTERS):
    """schedule_batch over S scenario lanes with PER-LANE pod batches —
    simonsweep's exact lane for scenarios whose groups are not all
    wave-eligible (required affinity gates, forced nodes, short mixed runs).
    Unlike serve_whatif_fanout's union batch (every lane scans the union
    length), each lane scans only the max per-lane batch length: lane i's
    rows are its own scenario's pods, invalid tail rows are provable no-ops.
    Returns (carry_s, choices_s [S, P] i32, -1 = unschedulable) — per-pod
    choices, so every lane's placements diff bit-for-bit against a fresh
    serial run."""

    def lane(cry: Carry, active, pg, fn, vd):
        c2, choices = schedule_batch(
            _mask_active(tb, active), cry, pg, fn, vd,
            n_zones=n_zones, enable_gpu=enable_gpu,
            enable_storage=enable_storage, w=w, filters=filters)
        return c2, choices

    return jax.vmap(lane)(cry_s, active_s, pod_group_s, forced_node_s,
                          valid_s)


# ---------------------------------------------------------------------------
# Auditable hot-kernel registry (simonaudit, analysis/hlo.py).
#
# Every kernel the engine/prober dispatches on a hot path is declared here so
# the compile-time auditor can lower it WITHOUT knowing each signature: the
# three dynamic args that follow the (tables, carry[, active_s]) head, the
# out-sharding tail (symbols resolved by parallel.mesh.ShardedKernels), and
# the canonical static values the engine passes on its default route. Adding
# a hot kernel without registering it here fails tests/test_audit.py's
# coverage check; changing a static default changes the audit's dispatch
# digest and trips `simon audit --check` until the goldens are reviewed.
# ---------------------------------------------------------------------------


class HotKernelSpec(NamedTuple):
    """One auditable dispatch: how to build its jit and canonical arguments.

    dyn:     the 3 dynamic-arg tokens after the head, resolved by the auditor
             ('g' / 'm' / 'cap1' / 'forced' / 'valid1' scalars, 'valid_p' /
             'pod_group' / 'forced_node' [P] arrays).
    out:     out-sharding tail symbols ('carry'/'carry_s'/'node'/'lane'/'rep');
             None marks a diagnostics kernel (fetch-to-host outputs, never
             donated, no out_shardings).
    statics: n_zones -> the canonical static tuple, in declared order — the
             values the engine's default route folds into the compiled program.
    fanout:  head is the (tables, carry_s, active_s) probe triple on a
             scenario mesh instead of the engine's (tables, carry) pair.
    """

    dyn: Tuple[str, ...]
    out: Tuple[str, ...] | None
    statics: "object"
    fanout: bool = False


HOT_KERNELS = {
    "schedule_wave": HotKernelSpec(
        ("g", "m", "cap1"), ("carry", "node", "rep"),
        lambda nz: (False, DEFAULT_WEIGHTS, DEFAULT_FILTERS, WAVE_BLOCK, 0)),
    "schedule_affinity_wave": HotKernelSpec(
        ("g", "m", "cap1"), ("carry", "node", "rep"),
        lambda nz: (False, DEFAULT_WEIGHTS, DEFAULT_FILTERS, WAVE_BLOCK, nz,
                    False)),
    "schedule_group_serial": HotKernelSpec(
        ("g", "valid_p", "cap1"), ("carry", "node", "rep"),
        lambda nz: (DEFAULT_WEIGHTS, DEFAULT_FILTERS, False, False, nz)),
    "schedule_batch": HotKernelSpec(
        ("pod_group", "forced_node", "valid_p"), ("carry", "rep"),
        lambda nz: (nz, False, False, DEFAULT_WEIGHTS, DEFAULT_FILTERS)),
    "feasibility_jit": HotKernelSpec(
        ("g", "forced", "valid1"), None,
        lambda nz: (False, False, True, True, DEFAULT_FILTERS)),
    "explain_jit": HotKernelSpec(
        ("g", "forced", "valid1"), None,
        lambda nz: (nz, False, False, DEFAULT_WEIGHTS, DEFAULT_FILTERS)),
    "probe_wave_fanout": HotKernelSpec(
        ("g", "m", "cap1"), ("carry_s", "lane"),
        lambda nz: (False, DEFAULT_WEIGHTS, DEFAULT_FILTERS, WAVE_BLOCK, 0),
        fanout=True),
    "probe_affinity_wave_fanout": HotKernelSpec(
        ("g", "m", "cap1"), ("carry_s", "lane"),
        lambda nz: (False, DEFAULT_WEIGHTS, DEFAULT_FILTERS, WAVE_BLOCK, nz),
        fanout=True),
    "probe_group_serial_fanout": HotKernelSpec(
        ("g", "valid_p", "cap1"), ("carry_s", "lane"),
        lambda nz: (DEFAULT_WEIGHTS, DEFAULT_FILTERS, False, False, nz),
        fanout=True),
    "probe_serial_fanout": HotKernelSpec(
        ("pod_group", "forced_node", "valid_p"), ("carry_s", "lane"),
        lambda nz: (nz, False, False, DEFAULT_WEIGHTS, DEFAULT_FILTERS),
        fanout=True),
    "serve_whatif_fanout": HotKernelSpec(
        ("pod_group", "forced_node", "valid_sp"), ("carry_s", "lane"),
        lambda nz: (nz, False, False, DEFAULT_WEIGHTS, DEFAULT_FILTERS),
        fanout=True),
    "serve_wave_fanout": HotKernelSpec(
        ("g_s", "m_s", "cap1_s"), ("carry_s", "lane"),
        lambda nz: (DEFAULT_WEIGHTS, DEFAULT_FILTERS, WAVE_BLOCK, 0),
        fanout=True),
    "sweep_wave_fanout": HotKernelSpec(
        ("g_sk", "m_sk", "cap1_sk"), ("carry_s", "lane_sn"),
        lambda nz: (DEFAULT_WEIGHTS, DEFAULT_FILTERS, WAVE_BLOCK, 0),
        fanout=True),
    "sweep_whatif_fanout": HotKernelSpec(
        ("pod_group_s", "forced_node_s", "valid_sp"), ("carry_s", "lane_p"),
        lambda nz: (nz, False, False, DEFAULT_WEIGHTS, DEFAULT_FILTERS),
        fanout=True),
}
