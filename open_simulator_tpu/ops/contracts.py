"""Typed kernel contracts: lightweight shape/dtype declarations for device code.

A contract is a spec string per parameter (plus optionally ``ret``), attached
with the :func:`shaped` decorator::

    @shaped(pernode="[N] f32", zones="[N] i32", ret="[N] f32")
    def selector_spread_score(pernode, F, zones, Z, maxN=None): ...

Grammar (``parse_spec``)::

    spec  ::= dims? dtype
    dims  ::= "[" (dim ("," dim)*)? "]"          # "[]" = scalar
    dim   ::= NAME | INT | "..."                 # symbolic axis, literal, rest
    dtype ::= f32 | f64 | i32 | i64 | u32 | bool | any

Symbolic axis names (``N``, ``R``, ``G`` ...) are documentation-grade: they
tie a kernel's tensors to the batch-table axes defined in encode.py. The
decorator is a **zero-cost annotation** — it validates the spec strings and
parameter names once at import time, stores the parsed contract on
``fn.__shaped__``, and returns the function unchanged (no call-time wrapper:
these functions sit inside jit traces where a Python wrapper per call would
show up in trace time).

simonlint's ``contract-spec`` rule cross-checks the same grammar statically,
so a typo'd contract fails both at import and in CI lint. ``check_args`` is
an opt-in runtime verifier for tests.

No JAX import here: the static analyzer loads this module, and it must stay
importable (fast) on lint-only hosts.
"""

from __future__ import annotations

import inspect
import re
from typing import Dict, NamedTuple, Optional, Tuple

DTYPES = ("f32", "f64", "i32", "i64", "u32", "bool", "any")

_SPEC_RE = re.compile(
    r"^\s*(?:\[(?P<dims>[^\]]*)\]\s*)?(?P<dtype>[A-Za-z0-9]+)\s*$")
_DIM_RE = re.compile(r"^(?:[A-Za-z_][A-Za-z0-9_]*|\d+|\.\.\.)$")


class Spec(NamedTuple):
    """One parsed contract entry. dims is None for 'any shape' (no brackets)."""

    dims: Optional[Tuple[str, ...]]
    dtype: str

    def __str__(self) -> str:
        d = "" if self.dims is None else "[" + ", ".join(self.dims) + "] "
        return f"{d}{self.dtype}"


def parse_spec(text: str) -> Spec:
    """Parse a contract spec string; raises ValueError with a precise reason."""
    m = _SPEC_RE.match(text)
    if not m:
        raise ValueError(f"{text!r} is not 'dims? dtype' (e.g. '[N, R] f32')")
    dtype = m.group("dtype")
    if dtype not in DTYPES:
        raise ValueError(f"unknown dtype {dtype!r}; expected one of {DTYPES}")
    raw = m.group("dims")
    if raw is None:
        return Spec(dims=None, dtype=dtype)
    dims: Tuple[str, ...] = tuple(
        d.strip() for d in raw.split(",") if d.strip()) if raw.strip() else ()
    for d in dims:
        if not _DIM_RE.match(d):
            raise ValueError(f"bad axis name {d!r} in {text!r}")
    return Spec(dims=dims, dtype=dtype)


_NP_KINDS = {  # numpy/jax dtype -> contract dtype token
    "float32": "f32", "float64": "f64",
    "int32": "i32", "int64": "i64", "uint32": "u32", "bool": "bool",
}


def shaped(**specs: str):
    """Attach shape/dtype contracts to a kernel. Validates at import time that
    every key names a real parameter (or 'ret') and every spec parses; stores
    ``fn.__shaped__ = {name: Spec}``; returns ``fn`` unchanged."""

    def deco(fn):
        params = set(inspect.signature(fn).parameters)
        parsed: Dict[str, Spec] = {}
        for name, text in specs.items():
            if name not in params and name not in ("ret", "returns"):
                raise TypeError(
                    f"@shaped on {fn.__qualname__}: {name!r} is not a parameter")
            parsed[name] = parse_spec(text)
        fn.__shaped__ = parsed
        return fn

    return deco


def contract_of(fn) -> Dict[str, Spec]:
    """The declared contract, following jit/functools wrappers if needed."""
    for obj in (fn, getattr(fn, "__wrapped__", None)):
        got = getattr(obj, "__shaped__", None)
        if got:
            return got
    return {}


def check_args(fn, *args, **kwargs) -> None:
    """Opt-in runtime verifier (used by tests, never on hot paths): binds the
    call and checks every contracted argument's rank + dtype against its spec.
    Symbolic axes must be consistent within the call; '...' matches any tail."""
    contract = contract_of(fn)
    if not contract:
        return
    bound = inspect.signature(fn).bind_partial(*args, **kwargs)
    env: Dict[str, int] = {}
    for name, spec in contract.items():
        if name in ("ret", "returns") or name not in bound.arguments:
            continue
        val = bound.arguments[name]
        shape = tuple(getattr(val, "shape", ()))
        dt = str(getattr(val, "dtype", type(val).__name__))
        want = _NP_KINDS.get(dt, dt)
        if spec.dtype not in ("any", want):
            raise TypeError(
                f"{fn.__qualname__}: {name} dtype {dt} != spec {spec}")
        if spec.dims is None or "..." in spec.dims:
            continue
        if len(shape) != len(spec.dims):
            raise TypeError(
                f"{fn.__qualname__}: {name} rank {len(shape)} != spec {spec}")
        for axis, size in zip(spec.dims, shape):
            if axis.isdigit():
                if int(axis) != size:
                    raise TypeError(
                        f"{fn.__qualname__}: {name} axis {axis} is {size}")
            elif env.setdefault(axis, size) != size:
                raise TypeError(
                    f"{fn.__qualname__}: axis {axis} = {env[axis]} but {name} "
                    f"has {size}")
