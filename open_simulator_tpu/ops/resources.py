"""Resource-axis registry: maps k8s resource names to columns of the [N, R] / [P, R]
tensors.

The first four columns are fixed (cpu in milli-cores, memory/ephemeral in bytes, pod
count); extended resources (nvidia.com/gpu, alibabacloud.com/gpu-mem, hugepages-*) get
columns in discovery order. Mirrors the Resource struct of the vendored scheduler
(framework/types.go: MilliCPU, Memory, EphemeralStorage, AllowedPodNumber, ScalarResources).
"""

from __future__ import annotations

from typing import Dict, Iterable, List

import numpy as np

from ..utils.objutil import CPU, EPHEMERAL, MEMORY, PODS, node_allocatable, pod_resource_requests
from .contracts import shaped

# DEVICE-BOUNDARY NOTE: every vector built here is float64 ON PURPOSE — k8s
# memory quantities (e.g. 16Ti = 2**44 bytes) lose integer precision in f32,
# so staging/accumulation stays 64-bit on the host. The encoder owns the one
# sanctioned narrowing to f32 when rows enter the device tables; each f64
# allocation below carries a simonlint dtype-drift waiver pointing here.

# NonZero defaults (vendored util/non_zero.go:34-37): used by LeastAllocated /
# BalancedAllocation scoring only, never by the Fit filter.
DEFAULT_MILLI_CPU = 100.0
DEFAULT_MEMORY = 200.0 * 1024 * 1024

FIXED = (CPU, MEMORY, EPHEMERAL, PODS)
CPU_I, MEM_I, EPH_I, PODS_I = 0, 1, 2, 3


class ResourceAxis:
    """Stable resource-name → column mapping for one simulation."""

    def __init__(self) -> None:
        self.names: List[str] = list(FIXED)
        self.index: Dict[str, int] = {n: i for i, n in enumerate(self.names)}

    def intern(self, name: str) -> int:
        i = self.index.get(name)
        if i is None:
            i = len(self.names)
            self.names.append(name)
            self.index[name] = i
        return i

    def discover(self, nodes: Iterable[dict], pods: Iterable[dict]) -> None:
        for node in nodes:
            for k in node_allocatable(node):
                self.intern(k)
        for pod in pods:
            for k in pod_resource_requests(pod):
                self.intern(k)

    @property
    def R(self) -> int:
        return len(self.names)

    @shaped(ret="[R] f64")
    def node_vector(self, node: dict) -> np.ndarray:
        """Allocatable as a dense row (absent resources = 0)."""
        v = np.zeros(self.R, np.float64)  # simonlint: ignore[dtype-drift] -- host staging, see device-boundary note
        for k, q in node_allocatable(node).items():
            v[self.index[k]] = q
        return v

    @shaped(ret="[R] f64")
    def pod_vector(self, pod: dict) -> np.ndarray:
        """Pod request row; the pods-count column is always 1 (one scheduling slot)."""
        v = np.zeros(self.R, np.float64)  # simonlint: ignore[dtype-drift] -- host staging, see device-boundary note
        for k, q in pod_resource_requests(pod).items():
            if k in self.index:
                v[self.index[k]] = q
            # a resource absent from every node can't be in the axis; the Fit kernel
            # treats it as unsatisfiable via the request_unknown flag set by the encoder
        v[PODS_I] = 1.0
        return v


@shaped(ret="[2] f64")
def pod_nonzero_cpu_mem(pod: dict) -> np.ndarray:
    """Scoring-side request: per-container max(request, default) summed, init containers
    taken as a per-resource max — the NonZeroRequested accumulation of the vendored
    scheduler (framework/types.go calculateResource + non_zero.go)."""
    from ..utils.quantity import parse_milli, parse_quantity

    spec = pod.get("spec") or {}
    cpu = mem = 0.0
    for c in spec.get("containers") or []:
        req = (c.get("resources") or {}).get("requests") or {}
        cpu += max(parse_milli(req["cpu"]), DEFAULT_MILLI_CPU) if "cpu" in req else DEFAULT_MILLI_CPU
        mem += max(parse_quantity(req["memory"]), DEFAULT_MEMORY) if "memory" in req else DEFAULT_MEMORY
    for c in spec.get("initContainers") or []:
        req = (c.get("resources") or {}).get("requests") or {}
        icpu = max(parse_milli(req["cpu"]), DEFAULT_MILLI_CPU) if "cpu" in req else DEFAULT_MILLI_CPU
        imem = max(parse_quantity(req["memory"]), DEFAULT_MEMORY) if "memory" in req else DEFAULT_MEMORY
        cpu = max(cpu, icpu)
        mem = max(mem, imem)
    return np.array([cpu, mem], np.float64)  # simonlint: ignore[dtype-drift] -- host staging, see device-boundary note


def pod_has_unknown_resource(pod: dict, axis: ResourceAxis) -> bool:
    """True when the pod requests a resource no node advertises — always infeasible."""
    return any(k not in axis.index for k in pod_resource_requests(pod))
