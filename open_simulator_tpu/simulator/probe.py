"""Incremental capacity-probe session: encode once, probe many candidates.

The capacity planner's search asks one question repeatedly: "would this pod
batch schedule on base + n copies of the template node?" for a sequence of
candidate n. The reference re-simulates the whole workload per candidate
(apply.go:203-259); the previous fast path (Simulator.probe_pods) already
skipped placement materialization but still built a fresh Simulator per
candidate — re-deep-copying nodes, re-discovering the resource axis,
re-encoding the (possibly 100k-pod) batch, and re-transferring every table to
the device, even though successive candidates differ only in how many copies
of ONE identical node template exist.

This module pays all of that exactly once per search:

- **Encode once.** One Simulator is built over base + n_max template copies
  (n_max sized to the node-padding bucket, so the phantom pad columns the
  engine would have added anyway become real template columns at zero extra
  memory). Bound pods commit once; the unbound run is encoded once
  (engine.encode_batch_raw); the tables transfer to the device once.
- **Candidate = mask flip.** A candidate n activates the base nodes plus the
  first n template columns; the rest stay inactive. The probe kernels fold the
  active mask into static_mask, which makes an inactive node exactly a
  pad_batch_tables phantom: infeasible for every pod, excluded from every
  feasibility-set normalizer, owner of zero placed pods and zero counter
  counts. Within one padding bucket, every candidate shares one XLA shape.
- **Multi-candidate fan-out.** kernels.probe_*_fanout evaluate S active masks
  in one dispatch (vmap over carry+mask), so the search's doubling phase and
  each refinement round are single device round-trips. With more than one
  visible device the [S] axis shards over a ('scenarios', 'nodes') mesh
  (parallel/mesh.py fan-out machinery), one candidate lane per device.
- **Node-axis extension.** When the search outgrows the encoded bucket,
  encode.extend_node_axis appends k copies of the pre-encoded template column
  (fresh hostname domains, zero seeds) instead of rebuilding
  NodeArrays/Encoder from raw node dicts.

Provable-equivalence gates (`try_build` returns None and the planner keeps
its fresh-Simulator probes when any fails):

- the node-census-dependent score/filter inputs must be candidate-invariant:
  no topologySpreadConstraints on any batch group (the DoNotSchedule eligible-
  domain minimum and the ScheduleAnyway relevant sets depend on which nodes
  exist, not just which are feasible) and no node-advertised images
  (ImageLocality's spread-scaled fraction divides by the total node count);
- no open-local storage (as in CapacityPlanner.try_build) and no pre-bound
  pod after an unbound one (probe order-inequivalence, same guard);
- every encoded template column must be bit-identical across copies (verified
  at build over the real Encoder's output, not assumed: a pathological pod
  that selects on a randomly generated simon-* name would fail this check),
  and template columns must carry zero seeds.

The existing provable-equivalence guard stays in place above this module: the
Applier re-validates the search's answer with one full fresh-`Simulator`
simulation and falls back to the reference-style full-simulation search on
any divergence (applier._plan) — the incremental path can therefore never
change an answer, only the time it takes to find it. The equivalence tests
and the CI smoke additionally re-validate answers with fresh-Simulator
probes.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..models.fakenode import new_fake_nodes
from ..obs import instruments as obs
from ..resilience import faults
from ..resilience import guard
from ..ops.resources import CPU_I, MEM_I
from .encode import (
    HOSTNAME,
    BatchTables,
    bucket_capped,
    extend_node_axis,
    pad_batch_tables,
    pad_encoder_axes,
    plugin_flags,
)

_jnp = None


def _jax():
    global _jnp
    if _jnp is None:
        import jax.numpy as jnp

        _jnp = jnp
    return _jnp


# [G, N] tables whose template columns must be copy-invariant (and are
# replicated verbatim by extend_node_axis).
_GN_FIELDS = (
    "static_mask", "mask_taint", "mask_unsched", "mask_aff", "mask_extra",
    "simon_raw", "nodeaff_raw", "taint_raw", "avoid_raw", "image_raw",
    "extra_raw",
)
# [N, *] node matrices with the same invariant.
_NROW_FIELDS = ("alloc", "dev_total", "vg_cap", "vg_nameid", "sdev_cap",
                "sdev_media")
# [N, *] seed rows that must be ZERO on template columns (no bound pod can
# name a randomly generated fake node).
_NSEED_FIELDS = ("seed_requested", "seed_nonzero", "seed_port_used",
                 "seed_dev_used", "seed_vg_req", "seed_sdev_alloc")


class ProbeSession:
    """Device-resident incremental prober for one (base, template, pods) search."""

    def __init__(self) -> None:  # built via try_build only
        raise TypeError("use ProbeSession.try_build")

    # ------------------------------------------------------------- build ------

    @classmethod
    def try_build(cls, base_nodes: List[dict], new_node: Optional[dict],
                  pods: List[dict], cluster_objects=None,
                  app_objects: Sequence = (), sched_config=None,
                  n_new: int = 2, fanout: int = 8,
                  mesh=None) -> Optional["ProbeSession"]:
        """Build a session able to probe up to (at least) n_new template
        copies, or None when the workload fails an equivalence gate."""
        from .engine import Simulator

        if new_node is None:
            return None
        if guard.default_quarantined():
            # the session uploads device-resident tables to the DEFAULT
            # backend (no fallback routing on this path): with it
            # quarantined, decline so the search runs fresh probes, which
            # the engine routes to the CPU fallback
            return None
        t0 = time.perf_counter()
        n_base = len(base_nodes)
        # Size the template axis to the engine's node-padding bucket: the
        # phantom pad columns a fresh probe would carry anyway become real,
        # probe-able template columns for free.
        n0 = max(2, int(n_new))
        n0 = bucket_capped(n_base + n0, 1024) - n_base
        sim = Simulator(base_nodes + new_fake_nodes(new_node, n0),
                        sched_config=sched_config, use_mesh=False)
        if cluster_objects is not None:
            sim.register_cluster_objects(cluster_objects)
        for rt in app_objects:
            sim.register_app_objects(rt)
        if sim.local_host.enabled:
            return None  # open-local envelope accounting (planner gate too)
        if any((n.get("status") or {}).get("images") for n in sim.na.nodes):
            return None  # ImageLocality divides by the TOTAL node count

        # The rest mutates caller-owned pods (bound commits write status) and
        # runs the faultable encode/upload path: transactional, so a failure
        # mid-build rolls the pods back before propagating (crash
        # consistency for the capacity search).
        with sim._transaction():
            return cls._try_build_encoded(sim, t0, n_base, n0, pods, fanout,
                                          mesh)

    @classmethod
    def _try_build_encoded(cls, sim, t0, n_base, n0, pods, fanout, mesh):
        # Bound pods commit once (they are cluster state every candidate
        # shares); the unbound remainder becomes the one encoded run.
        from ..utils.objutil import pod_resource_requests

        run: List[dict] = []
        bound_scheduled = 0
        bound_cpu = bound_mem = 0.0
        homeless = 0
        for pod in pods:
            node_name = (pod.get("spec") or {}).get("nodeName")
            if not node_name:
                run.append(pod)
                continue
            if run:
                return None  # bound-after-unbound: probe order-inequivalent
            ni = sim.na.index.get(node_name)
            if ni is None:
                homeless += 1
                sim.homeless.append(pod)
            else:
                sim._commit_pod(pod, ni, scheduled=False)
                bound_scheduled += 1
                req = pod_resource_requests(pod)
                bound_cpu += req.get("cpu", 0.0)
                bound_mem += req.get("memory", 0.0)

        self = object.__new__(cls)
        self._sim = sim
        self.fanout = int(fanout)
        self.n_base = n_base
        self.n_new = n0
        self.bound_scheduled = bound_scheduled
        self._bound_cpu = bound_cpu
        self._bound_mem = bound_mem
        self.total_known = len(pods) - homeless
        self._run_len = len(run)
        self.encodes = 0
        self.extensions = 0
        self._alloc = np.array(sim.na.alloc, np.float64)  # simonlint: ignore[dtype-drift] -- host-side envelope sums, mirrors probe_utilization
        self._mesh = mesh if mesh is not None else self._auto_mesh(fanout)

        if not run:
            # trivial probes: host arithmetic only (mirrors probe_pods' early
            # return, whose probe_utilization then reads a None carry as zeros)
            self._bt_raw = None
            self._segs = []
            self.encode_s = time.perf_counter() - t0
            self._record_build()
            return self

        # cheap census gate BEFORE the (dominant) batch encode: spread
        # constraints reject the session anyway, so don't pay a 100k-pod
        # encode just to discover that (the group-level check below stays as
        # the authoritative backstop)
        if any((p.get("spec") or {}).get("topologySpreadConstraints")
               for p in run):
            return None

        bt_raw = sim.encode_batch_raw(run)
        self.encodes = 1
        P = len(run)
        for gi in set(np.asarray(bt_raw.pod_group[:P]).tolist()):
            g = sim.encoder.group_list[gi]
            if g.spread_dns or g.spread_sa:
                return None  # eligible-domain sets depend on the node census
        enc = sim.encoder
        self._host_counters = [t for t, cs in enumerate(enc.counter_list)
                               if cs.topo_key == HOSTNAME]
        self._host_carriers = [t for t, cs in enumerate(enc.carrier_list)
                               if cs.topo_key == HOSTNAME]
        if not _template_columns_uniform(bt_raw, n_base, self._host_counters,
                                         self._host_carriers):
            return None
        self._bt_raw = bt_raw
        self._segs = (sim._segments(bt_raw, P) if sim.use_waves
                      else [("serial", 0, P)])
        self._upload()
        self.encode_s = time.perf_counter() - t0
        self._record_build()
        return self

    def _record_build(self) -> None:
        """Session-build accounting into the metrics registry (satellite to
        the planner's stats dict: the registry survives the search, so
        `capacity` CLI runs and the server report the same numbers)."""
        obs.PROBE_SESSIONS.inc()
        obs.PROBE_ENCODES.inc(self.encodes)
        obs.PROBE_ENCODE_SECONDS.inc(self.encode_s)

    @staticmethod
    def _auto_mesh(fanout: int):
        """Scenario mesh over all visible devices when >1 is up and divides the
        fan-out; same OPEN_SIMULATOR_MESH=0/1 override as the engine's mesh."""
        import os

        env = os.environ.get("OPEN_SIMULATOR_MESH", "")
        if env in ("0", "false", "no"):
            return None
        if guard.default_quarantined():
            return None  # degraded mode: no shardings over a wedged backend
        import jax

        n = len(jax.devices())
        if n <= 1:
            return None  # _dispatch pads lane counts to a shard multiple,
        # so any device count works once there is more than one
        from ..parallel.mesh import make_scenario_mesh

        return make_scenario_mesh(n)

    # ------------------------------------------------------------ upload ------

    def _upload(self) -> None:
        """(Re-)pad and transfer the tables; rebuild per-segment batch arrays."""
        faults.maybe_fail("to_device")
        faults.maybe_fail("oom_to_device")
        jnp = _jax()
        from .engine import batch_tables_nbytes

        bt = pad_encoder_axes(self._bt_raw)
        bt = pad_batch_tables(bt, bucket_capped(self.n_base + self.n_new, 1024))
        obs.TRANSFER_BYTES.inc(batch_tables_nbytes(bt))
        self._bt = bt
        self._n_pad = bt.alloc.shape[0]
        from ..parallel.mesh import tables_from_batch

        if self._mesh is not None:
            import jax

            from ..parallel.mesh import fanout_shardings

            ts, self._carry_sh, self._active_sh = fanout_shardings(self._mesh)
            self._tables = type(ts)(*(
                jax.device_put(np.asarray(v), s)
                for v, s in zip(tables_from_batch(bt), ts)))
        else:
            from ..ops import kernels

            self._tables = kernels.Tables(
                *(jnp.asarray(v) for v in tables_from_batch(bt)))
        # seed carry stays host-side; each dispatch broadcasts it over S lanes
        self._seeds = (bt.seed_requested, bt.seed_nonzero, bt.seed_port_used,
                       bt.seed_counter, bt.seed_carrier, bt.seed_dev_used,
                       bt.seed_vg_req, bt.seed_sdev_alloc)
        self._flags = plugin_flags(bt)

    # ---------------------------------------------------------- extension -----

    def _check_backend(self) -> None:
        """A backend quarantined AFTER this session uploaded its tables must
        not be touched again: device-resident arrays (and any mesh
        shardings) are committed to it and override jax.default_device.
        Raise the containable wedge classification so the capacity search
        falls back to fresh probes — which the engine routes to the CPU
        fallback — WITHOUT burning a watchdog timeout re-dispatching here."""
        if self._segs and guard.default_quarantined():
            raise guard.BackendWedged("dispatch", guard.current_backend(),
                                      injected=False)

    def ensure_capacity(self, n: int) -> None:
        """Grow the template axis to cover candidate n via the node-axis
        extension path (append pre-encoded template columns; no re-encode).

        When the session holds device-resident tables and the extension
        cannot widen the domain axis (no hostname-keyed counter/carrier
        rows), the growth happens SHARD-LOCALLY on the device
        (mesh.extend_tables_on_device): the template column is already
        resident, so no table bytes round-trip through the host — only the
        (numpy) host mirror is rebuilt for seeds and dispatch dims. Hostname
        rows fall back to the full host re-upload."""
        if n <= self.n_new:
            return
        self._check_backend()  # the paths below touch the session backend
        target = bucket_capped(self.n_base + n, 1024)
        k = target - (self.n_base + self.n_new)
        n_real_old = self.n_base + self.n_new
        if self._bt_raw is not None:
            self._bt_raw = extend_node_axis(
                self._bt_raw, k, self.n_base,
                self._host_counters, self._host_carriers)
        self._alloc = np.concatenate(
            [self._alloc,
             np.repeat(self._alloc[self.n_base:self.n_base + 1], k, axis=0)])
        self.n_new += k
        self.extensions += 1
        obs.PROBE_EXTENSIONS.inc()
        if self._bt_raw is None:
            return
        if not self._host_counters and not self._host_carriers:
            self._extend_device(k, n_real_old)
        else:
            self._upload()

    def _extend_device(self, k: int, n_real_old: int) -> None:
        """Shard-local growth: re-pad the HOST mirror (numpy only — seeds and
        dispatch dims read it) and extend the device tables in place from
        their own template column. simon_device_transfer_bytes_total does not
        move: zero table bytes cross the host boundary."""
        faults.maybe_fail("to_device")
        faults.maybe_fail("oom_to_device")
        from ..parallel.mesh import extend_tables_on_device

        bt = pad_encoder_axes(self._bt_raw)
        bt = pad_batch_tables(bt, bucket_capped(self.n_base + self.n_new, 1024))
        sentinel = bt.seed_counter.shape[1] - 1
        if sentinel != self._bt.seed_counter.shape[1] - 1:
            # the no-hostname gate makes this unreachable (the domain axis
            # cannot widen); if an encoder change ever breaks that, fall back
            # to the host path rather than corrupt the resident tables
            self._upload()
            return
        self._bt = bt
        self._n_pad = bt.alloc.shape[0]
        self._tables = extend_tables_on_device(
            self._tables, n_real=n_real_old, k=k, template_col=self.n_base,
            n_pad_new=self._n_pad, sentinel=sentinel, mesh=self._mesh)
        self._seeds = (bt.seed_requested, bt.seed_nonzero, bt.seed_port_used,
                       bt.seed_counter, bt.seed_carrier, bt.seed_dev_used,
                       bt.seed_vg_req, bt.seed_sdev_alloc)
        self._flags = plugin_flags(bt)

    # ------------------------------------------------------------ probing -----

    def batch_totals(self) -> Tuple[float, float, int]:
        """(cpu_used, mem_used, n_pods) over the pods the simulation accounts
        (known-bound + unbound; homeless excluded) — the planner's lower-bound
        inputs, derived from the encoded groups (one f64 template-request
        lookup per GROUP, scaled by replica counts) instead of the planner's
        100k-iteration per-pod host loop. Requests within a group are
        identical by signature, so the sums are exact."""
        from ..utils.objutil import pod_resource_requests

        cpu, mem = self._bound_cpu, self._bound_mem
        if self._bt_raw is not None and self._run_len:
            groups = self._sim.encoder.group_list
            counts = np.bincount(
                np.asarray(self._bt_raw.pod_group[:self._run_len]),
                minlength=len(groups))
            for gi, c in enumerate(counts.tolist()):
                if not c:
                    continue
                req = pod_resource_requests(groups[gi].template)
                cpu += c * req.get("cpu", 0.0)
                mem += c * req.get("memory", 0.0)
        return cpu, mem, self.total_known

    def probe_many(self, ns: Sequence[int]) -> Dict[int, Tuple[int, int, Dict[str, float]]]:
        """Evaluate candidate node counts in ONE device dispatch. Returns
        {n: (scheduled, total, utilization)} with the same semantics as
        Simulator.probe_pods + probe_utilization on a fresh simulator at n.
        len(set(ns)) must be <= fanout and every n <= current capacity."""
        order: List[int] = []
        for n in ns:
            if n not in order:
                order.append(n)
        if not order:
            return {}
        if len(order) > self.fanout:
            raise ValueError(f"{len(order)} candidates > fanout {self.fanout}")
        bad = [n for n in order if n > self.n_new]
        if bad:
            raise ValueError(f"candidates {bad} exceed capacity {self.n_new}")

        obs.PROBE_PROBES.inc(len(order))
        if not self._segs:  # no unbound pods: pure host arithmetic
            return {n: (self.bound_scheduled, self.total_known,
                        self._utilization(n, None)) for n in order}
        self._check_backend()  # never re-dispatch on a now-quarantined backend

        # Lanes cost near-linearly, so a lone lower-bound probe (the common
        # exact-arithmetic case) must not pay for fanout-1 padded copies —
        # but every distinct S is a fresh XLA compile of the whole pipeline,
        # so lane counts quantize to powers of two (1, 2, 4, 8): at most
        # log2(fanout)+1 compiled shapes per bucket, surplus lanes repeat the
        # last candidate and are sliced off.
        S = 1
        while S < len(order):
            S *= 2
        obs.PROBE_DISPATCHES.inc()
        obs.PROBE_FANOUT.observe(S)
        lanes = order + [order[-1]] * (S - len(order))
        active_s = np.zeros((S, self._n_pad), bool)
        for i, n in enumerate(lanes):
            active_s[i, :self.n_base + n] = True
        placed_s, requested_s = self._dispatch(active_s)
        out: Dict[int, Tuple[int, int, Dict[str, float]]] = {}
        for i, n in enumerate(order):
            scheduled = self.bound_scheduled + int(placed_s[i])
            out[n] = (scheduled, self.total_known,
                      self._utilization(n, requested_s[i]))
        self._xray_probes(out)
        return out

    def _xray_probes(self, out) -> None:
        """simonxray ride-along: one probe record per candidate evaluated by
        this fan-out dispatch (counts only — sessions never materialize
        placements), tagged with the session's backend."""
        from ..obs import xray

        run = xray.begin_run("probe_session")
        if run is None:
            return
        for n, (scheduled, total, _) in sorted(out.items()):
            run.add_probe(scheduled, total, candidate=n)
        xray.commit_run(run, [guard.current_backend()])

    def _dispatch(self, active_s: np.ndarray):
        from ..obs import scope

        S = active_s.shape[0]
        sc = scope.active()
        cm = (sc.span("probe.fanout", cat="dispatch", lanes=int(S))
              if sc is not None else contextlib.nullcontext())
        # The whole fan-out round — lane padding, seed broadcast, every
        # segment dispatch, the one fetch — runs as ONE supervised unit: the
        # mesh context is thread-local, so it must be entered inside the
        # watchdog's worker thread, and a wedge anywhere in the round
        # classifies the same way (the search then falls back to fresh
        # probes on the surviving backend).
        with cm:
            placed_s, requested_s = guard.supervised(
                functools.partial(self._dispatch_round, active_s),
                site="dispatch", pods=self._run_len * max(1, S))
        return placed_s[:S], requested_s[:S]

    def _dispatch_round(self, active_s: np.ndarray):
        jnp = _jax()
        from ..ops import kernels

        if self._mesh is not None:
            # the scenario axis shards evenly: round the lane count up to a
            # multiple of the mesh's device count (padding repeats the last
            # candidate; the surplus lanes are sliced off by the caller)
            from ..parallel.mesh import SCENARIO_AXIS

            shards = self._mesh.shape[SCENARIO_AXIS]
            extra = (-active_s.shape[0]) % shards
            if extra:
                active_s = np.concatenate(
                    [active_s, np.repeat(active_s[-1:], extra, axis=0)])
        carry_np = tuple(
            np.broadcast_to(a, (active_s.shape[0],) + a.shape)
            for a in self._seeds)
        if self._mesh is not None:
            import jax

            carry_s = kernels.Carry(*(
                jax.device_put(np.ascontiguousarray(v), s)
                for v, s in zip(carry_np, self._carry_sh)))
            active = jax.device_put(active_s, self._active_sh)
            ctx = self._mesh
        else:
            import contextlib

            carry_s = kernels.Carry(*(jnp.asarray(v) for v in carry_np))
            active = jnp.asarray(active_s)
            ctx = contextlib.nullcontext()

        sim, bt = self._sim, self._bt
        enable_gpu, enable_storage = self._flags
        n_real = self.n_base + self.n_new
        dims = {"S": int(active_s.shape[0]), "N": int(self._n_pad),
                "G": int(bt.static_mask.shape[0]),
                "T": int(bt.counter_dom.shape[0]),
                "mesh": self._mesh is not None,
                # w/filters are jit statics on the fan-out kernels too
                "cfg": f"{hash((sim.score_w, sim.filter_flags)) & 0xffffffff:08x}"}
        if self._mesh is not None:
            # the mesh's sharded-executable set: explicit in/out shardings
            # keep the [S]-carry in its scenario layout across chained
            # segments (zero resharding), and the donated [S]-carry chain
            # updates in place — except where dispatching donated
            # executables is unsound (multi-device CPU meshes: the factory
            # downgrades to the undonated view; see
            # parallel.mesh.donation_runtime_safe, found when this path
            # intermittently fetched garbage `requested` leaves)
            from ..parallel.mesh import donation_runtime_safe, sharded_kernels

            kns = sharded_kernels(self._mesh, donate=True)
            dims["donate"] = donation_runtime_safe(self._mesh)
        else:
            kns = kernels
        placed_parts = []
        with ctx:
            for seg in self._segs:
                faults.maybe_fail("dispatch")
                faults.maybe_fail("oom_dispatch")
                if seg[0] == "serial":
                    _, start, length = seg
                    pad = bucket_capped(length, 2048)
                    pg = np.zeros(pad, np.int32)
                    pg[:length] = bt.pod_group[start:start + length]
                    fn = np.full(pad, -1, np.int32)
                    fn[:length] = bt.forced_node[start:start + length]
                    vd = np.zeros(pad, bool)
                    vd[:length] = True
                    obs.record_dispatch(
                        "probe_serial_fanout", P=pad, zones=bt.n_zones,
                        gpu=enable_gpu, storage=enable_storage, **dims)
                    carry_s, placed = kns.probe_serial_fanout(
                        self._tables, carry_s, active,
                        jnp.asarray(pg), jnp.asarray(fn), jnp.asarray(vd),
                        n_zones=bt.n_zones, enable_gpu=enable_gpu,
                        enable_storage=enable_storage,
                        w=sim.score_w, filters=sim.filter_flags,
                    )
                elif seg[0] == "spread":
                    # dns/sa groups are gated out at build: only a live
                    # SelectorSpread counter routes here (ss_live)
                    _, start, length, g, cap1, ss_live, sa_live = seg
                    pad = bucket_capped(length, 2048)
                    vd = np.zeros(pad, bool)
                    vd[:length] = True
                    obs.record_dispatch(
                        "probe_group_serial_fanout", P=pad, ss=ss_live,
                        sa=sa_live, zones=bt.n_zones if ss_live else 2, **dims)
                    carry_s, placed = kns.probe_group_serial_fanout(
                        self._tables, carry_s, active,
                        jnp.int32(g), jnp.asarray(vd), jnp.asarray(cap1),
                        w=sim.score_w, filters=sim.filter_flags,
                        ss_live=ss_live, sa_live=sa_live,
                        n_zones=bt.n_zones if ss_live else 2,
                    )
                elif seg[0] == "affinity":
                    # counter-live predicates (dns spread is gated out at
                    # build, so: live SelectorSpread and affinity/anti gates)
                    _, start, length, g, cap1, ss_live = seg
                    block = kernels.wave_block_for(length, n_real)
                    obs.record_dispatch(
                        "probe_affinity_wave_fanout", block=block, ss=ss_live,
                        zones=bt.n_zones if ss_live else 2, **dims)
                    carry_s, placed = kns.probe_affinity_wave_fanout(
                        self._tables, carry_s, active,
                        jnp.int32(g), jnp.int32(length), jnp.asarray(cap1),
                        ss_live=ss_live, w=sim.score_w,
                        filters=sim.filter_flags, block=block,
                        n_zones=bt.n_zones if ss_live else 2,
                    )
                else:
                    _, start, length, g, cap1, gpu_live = seg
                    block = kernels.wave_block_for(length, n_real)
                    kmax = kernels.wave_kmax(length, n_real, block)
                    obs.record_dispatch("probe_wave_fanout", block=block,
                                        k=kmax, gpu_live=gpu_live, **dims)
                    carry_s, placed = kns.probe_wave_fanout(
                        self._tables, carry_s, active,
                        jnp.int32(g), jnp.int32(length), jnp.asarray(cap1),
                        kmax=kmax, gpu_live=gpu_live, w=sim.score_w,
                        filters=sim.filter_flags,
                        block=block,
                    )
                placed_parts.append(placed)
            faults.maybe_fail("fetch")
            if self._mesh is not None:
                from ..parallel.mesh import carry_reshard_bytes

                b = carry_reshard_bytes(carry_s, kns.carry_s_sh)
                if b:
                    obs.RESHARD_BYTES.inc(b)
            placed_s = np.asarray(jnp.sum(jnp.stack(placed_parts), axis=0))
            requested_s = np.asarray(carry_s.requested)
        return placed_s, requested_s

    def _utilization(self, n: int, requested_row: Optional[np.ndarray]) -> Dict[str, float]:
        """probe_utilization's aggregate totals for candidate n: f64 host sums
        over the identical per-node values a fresh probe would fetch (inactive
        and phantom columns hold zero and are sliced off anyway)."""
        m = self.n_base + n
        if requested_row is None:
            used = np.zeros((m, self._alloc.shape[1]), np.float64)  # simonlint: ignore[dtype-drift] -- host-side accumulator, mirrors probe_utilization
        else:
            used = requested_row[:m].astype(np.float64)  # simonlint: ignore[dtype-drift] -- host-side accumulator, mirrors probe_utilization
        alloc = self._alloc[:m]
        return {
            "cpu_used": float(used[:, CPU_I].sum()),
            "cpu_alloc": float(alloc[:, CPU_I].sum()),
            "mem_used": float(used[:, MEM_I].sum()),
            "mem_alloc": float(alloc[:, MEM_I].sum()),
        }


def _template_columns_uniform(bt: BatchTables, n_base: int,
                              host_counters: Sequence[int],
                              host_carriers: Sequence[int]) -> bool:
    """Verify every encoded table treats the template copies identically:
    columns n_base.. of each [G, N]/[N, *] table equal the first template
    column (hostname-keyed domain rows excepted — those are per-node by
    construction), and template seed rows are zero. This turns "fake copies
    are indistinguishable" from an argument into a checked invariant."""
    b = n_base
    for f in _GN_FIELDS:
        a = getattr(bt, f)
        if not (a[:, b + 1:] == a[:, b:b + 1]).all():
            return False
    for f in _NROW_FIELDS:
        a = getattr(bt, f)
        if not (a[b + 1:] == a[b:b + 1]).all():
            return False
    if not (bt.node_zone[b + 1:] == bt.node_zone[b]).all():
        return False
    for dom, host_rows in ((bt.counter_dom, host_counters),
                           (bt.carr_dom, host_carriers)):
        rest = np.ones(dom.shape[0], bool)
        rest[list(host_rows)] = False
        if not (dom[rest][:, b + 1:] == dom[rest][:, b:b + 1]).all():
            return False
    for f in _NSEED_FIELDS:
        if getattr(bt, f)[b:].any():
            return False
    return True
