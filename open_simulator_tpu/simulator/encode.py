"""Host-side tensorization: k8s objects → dense tables for the batched TPU scheduler.

This is the string-world ↔ tensor-world boundary (SURVEY.md §7). Everything the vendored
scheduler derives from strings — label selectors, affinity terms, taints, topology
domains, host ports — is interned and pre-evaluated here into numpy tables; the device
kernels (`open_simulator_tpu.ops.kernels`) see only integers and floats.

Key ideas:
- **Groups**: pods sharing (namespace, labels, scheduling-relevant spec) — i.e. replicas
  of one workload — share one row of every per-pod table. Static node predicates
  (unschedulable, taints, nodeSelector, required node affinity) and static score inputs
  (Simon max-share, preferred-node-affinity weights, PreferNoSchedule taint counts) are
  evaluated once per group as `[N]` vectors.
- **Counters**: every pairwise pod relation (inter-pod affinity/anti-affinity terms,
  topology-spread constraints, selector-spread) reduces to "number of placed pods
  matching selector S in topology domain d". Distinct (topologyKey, namespaces,
  selector) triples become counter rows; the device carry holds `counter_count [T, D+1]`
  (last column = sentinel for nodes missing the topology key, always zero).
- **Carriers**: the reverse direction — "placed pods *carrying* term t in domain d" —
  for existing-pod anti-affinity (interpodaffinity filtering.go
  satisfyExistingPodsAntiAffinity) and existing-pod preferred/required terms in scoring
  (scoring.go processExistingPod).

DaemonSet pods pinned via matchFields metadata.name affinity are detected and encoded as
`forced_node` so that N pinned pods don't explode the group count.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import constants as C
from ..ops.resources import (
    PODS_I,
    ResourceAxis,
    pod_has_unknown_resource,
    pod_nonzero_cpu_mem,
)
from ..utils.interning import StringTable
from ..utils.objutil import (
    annotations_of,
    labels_of,
    match_label_selector,
    name_of,
    namespace_of,
    pod_host_ports,
    pod_resource_requests,
    toleration_tolerates_taint,
)

# ----------------------------------------------------------------- node arrays --------

_UNSCHED_TAINT = {"key": C.TaintNodeUnschedulable, "effect": "NoSchedule"}


class NodeArrays:
    """Vectorized view of the node list: per-label-key interned value columns, taints,
    allocatable matrix, zone/domain interning."""

    def __init__(self, nodes, axis: ResourceAxis) -> None:
        from .store import NodeStore

        if isinstance(nodes, NodeStore):
            # columnar fast path: adopt the store's block recipes directly —
            # no per-node dict parsing, and `self.nodes` becomes a lazy view
            # that materializes dicts only on indexed access
            self._init_from_store(nodes, axis)
            return
        self.nodes = nodes
        self.axis = axis
        self.N = len(nodes)
        self.names = [name_of(n) for n in nodes]
        self.index = {nm: i for i, nm in enumerate(self.names)}
        self.values = StringTable()  # shared value interner for labels & names

        # label key → int32[N] of value ids (0 = key absent)
        self.label_vals: Dict[str, np.ndarray] = {}
        for i, node in enumerate(nodes):
            for k, v in labels_of(node).items():
                col = self.label_vals.get(k)
                if col is None:
                    col = self.label_vals[k] = np.zeros(self.N, np.int32)
                col[i] = self.values.intern(str(v))
        self.name_ids = np.array([self.values.intern(nm) for nm in self.names], np.int32)

        self.taints: List[Tuple[tuple, ...]] = [
            tuple(
                (t.get("key", ""), t.get("value", "") or "", t.get("effect", ""))
                for t in (n.get("spec") or {}).get("taints") or []
            )
            for n in nodes
        ]
        self.unschedulable = np.array(
            [bool((n.get("spec") or {}).get("unschedulable")) for n in nodes], bool
        )
        self.alloc = np.stack([axis.node_vector(n) for n in nodes]) if nodes else np.zeros((0, axis.R))

        # zone composite key (utilnode.GetZoneKey): region + zone, either label family
        self.zones = StringTable()
        zid = np.zeros(self.N, np.int32)
        for i, node in enumerate(nodes):
            lbl = labels_of(node)
            region = lbl.get(C.LabelTopologyRegion) or lbl.get("failure-domain.beta.kubernetes.io/region") or ""
            zone = lbl.get(C.LabelTopologyZone) or lbl.get(C.LabelTopologyZoneBeta) or ""
            if region or zone:
                zid[i] = self.zones.intern((region, zone))
        self.zone_id = zid  # 0 = no zone

        # topology domains: (topo key, node's value) interned globally
        self.domains = StringTable()
        self._dom_cache: Dict[str, np.ndarray] = {}

    def _init_from_store(self, store, axis: ResourceAxis) -> None:
        """Build every column from a NodeStore's block recipes. Content is
        bit-identical to parsing the materialized dicts (the store parity
        suite holds BatchTables to byte equality); internal string-table ids
        may differ numerically, which no table ever observes — only equality
        and first-appearance order matter, and both are preserved because
        blocks are visited in node order."""
        from .store import LazyNodeSeq

        self.axis = axis
        self.N = N = len(store)
        self.nodes = LazyNodeSeq(store)
        self.names = store.gen_names()
        self.index = {nm: i for i, nm in enumerate(self.names)}
        self.values = StringTable()
        self.label_vals = {}
        self.taints = []
        self.unschedulable = np.zeros(N, bool)
        alloc_rows: List[np.ndarray] = []
        zid = np.zeros(N, np.int32)
        self.zones = StringTable()
        intern = self.values.intern
        off = 0
        for blk in store.blocks:
            cnt = blk.count
            end = off + cnt
            # per-node labels first, in the same per-node visitation order a
            # dict parse would use (hostname before index labels before
            # constants matters only for interner id assignment, which is
            # unobservable — see docstring)
            host_col = self.label_vals.get(HOSTNAME)
            if host_col is None:
                host_col = self.label_vals[HOSTNAME] = np.zeros(N, np.int32)
            for i in range(off, end):
                host_col[i] = intern(self.names[i])
            for k in blk.index_labels:
                col = self.label_vals.get(k)
                if col is None:
                    col = self.label_vals[k] = np.zeros(N, np.int32)
                for i in range(off, end):
                    col[i] = intern(str(i))
            for k, v in blk.labels:
                col = self.label_vals.get(k)
                if col is None:
                    col = self.label_vals[k] = np.zeros(N, np.int32)
                col[off:end] = intern(str(v))
            if blk.zone_cycle is not None:
                key, fmt, mod = blk.zone_cycle
                col = self.label_vals.get(key)
                if col is None:
                    col = self.label_vals[key] = np.zeros(N, np.int32)
                ids = np.array([intern(fmt.format(j)) for j in range(mod)],
                               np.int32)
                col[off:end] = ids[np.arange(off, end) % mod]
            lbl = dict(blk.labels)
            region = (lbl.get(C.LabelTopologyRegion)
                      or lbl.get("failure-domain.beta.kubernetes.io/region")
                      or "")
            zone_keys = (C.LabelTopologyZone, C.LabelTopologyZoneBeta)
            if blk.zone_cycle is not None and blk.zone_cycle[0] in zone_keys:
                key, fmt, mod = blk.zone_cycle
                zids = np.array(
                    [self.zones.intern((region, fmt.format(j)))
                     for j in range(mod)], np.int32)
                zid[off:end] = zids[np.arange(off, end) % mod]
            else:
                zone = next((str(lbl[k]) for k in zone_keys if k in lbl), "")
                if region or zone:
                    zid[off:end] = self.zones.intern((region, zone))
            if blk.taint is not None:
                t, every = blk.taint
                self.taints.extend(
                    ((t,) if i % every == 0 else ())
                    for i in range(off, end))
            else:
                self.taints.extend(() for _ in range(cnt))
            self.unschedulable[off:end] = bool(
                (blk.template.get("spec") or {}).get("unschedulable"))
            alloc_rows.append(np.repeat(
                axis.node_vector(blk.template)[None, :], cnt, axis=0))
            off = end
        self.name_ids = self.label_vals[HOSTNAME].copy() if N else np.zeros(
            0, np.int32)
        self.alloc = (np.concatenate(alloc_rows) if alloc_rows
                      else np.zeros((0, axis.R)))
        self.zone_id = zid
        self.domains = StringTable()
        self._dom_cache = {}

    def extend(self, nodes: List[dict]) -> None:
        """Append nodes IN PLACE — the serving image's delta-ingest path
        (serve/image.py): a live node-add event extends the columnar node
        store by parsing ONE node dict instead of rebuilding NodeArrays over
        the whole (10k+) cluster. Interners (values/zones/domains) are
        append-only, so every existing label/zone/domain id keeps its value;
        only the per-topology domain cache resets (new nodes append fresh
        hostname domains at the END of the table, never renumbering old
        ones). Callers re-derive anything shaped [*, N] afterwards
        (Encoder group statics via rebuild_group_axes, node-side batch
        tables via build_node_axis_tables)."""
        if not nodes:
            return
        base = self.N
        k = len(nodes)
        self.nodes.extend(nodes)
        self.N = len(self.nodes)
        new_names = [name_of(n) for n in nodes]
        self.names.extend(new_names)
        for j, nm in enumerate(new_names):
            self.index[nm] = base + j
        # pad existing label columns first, THEN intern the new nodes' labels
        # (a label key first seen on a new node allocates a full-length col)
        for key in list(self.label_vals):
            self.label_vals[key] = np.concatenate(
                [self.label_vals[key], np.zeros(k, np.int32)])
        for j, node in enumerate(nodes):
            for key, v in labels_of(node).items():
                col = self.label_vals.get(key)
                if col is None:
                    col = self.label_vals[key] = np.zeros(self.N, np.int32)
                col[base + j] = self.values.intern(str(v))
        self.name_ids = np.concatenate(
            [self.name_ids,
             np.array([self.values.intern(nm) for nm in new_names], np.int32)])
        self.taints.extend(
            tuple((t.get("key", ""), t.get("value", "") or "",
                   t.get("effect", ""))
                  for t in (n.get("spec") or {}).get("taints") or [])
            for n in nodes)
        self.unschedulable = np.concatenate(
            [self.unschedulable,
             np.array([bool((n.get("spec") or {}).get("unschedulable"))
                       for n in nodes], bool)])
        self.alloc = np.concatenate(
            [self.alloc, np.stack([self.axis.node_vector(n) for n in nodes])])
        zid = np.zeros(k, np.int32)
        for j, node in enumerate(nodes):
            lbl = labels_of(node)
            region = (lbl.get(C.LabelTopologyRegion)
                      or lbl.get("failure-domain.beta.kubernetes.io/region")
                      or "")
            zone = (lbl.get(C.LabelTopologyZone)
                    or lbl.get(C.LabelTopologyZoneBeta) or "")
            if region or zone:
                zid[j] = self.zones.intern((region, zone))
        self.zone_id = np.concatenate([self.zone_id, zid])
        self._dom_cache.clear()

    def label_numeric(self, key: str) -> np.ndarray:
        out = np.full(self.N, np.nan)
        col = self.label_vals.get(key)
        if col is None:
            return out
        for i in range(self.N):
            if col[i]:
                try:
                    out[i] = int(self.values.value(col[i]))
                except (TypeError, ValueError):
                    pass
        return out

    def domain_of(self, topo_key: str) -> np.ndarray:
        """int32[N] domain index per node under topo_key; -1 where the key is absent.
        (kubernetes.io/hostname always present per MakeValidNode → per-node domains.)"""
        cached = self._dom_cache.get(topo_key)
        if cached is not None:
            return cached
        col = self.label_vals.get(topo_key)
        out = np.full(self.N, -1, np.int32)
        if col is not None:
            for i in range(self.N):
                if col[i]:
                    out[i] = self.domains.intern((topo_key, int(col[i])))
        self._dom_cache[topo_key] = out
        return out

    @property
    def D(self) -> int:
        return len(self.domains)


# ----------------------------------------------------- vectorized node matchers -------


def _expr_vec(na: NodeArrays, expr: dict) -> np.ndarray:
    """NodeSelectorRequirement over labels → bool[N] (objutil.match_expression, vectorized)."""
    key, op = expr.get("key", ""), expr.get("operator", "In")
    values = expr.get("values") or []
    col = na.label_vals.get(key)
    present = (col > 0) if col is not None else np.zeros(na.N, bool)
    if op == "Exists":
        return present
    if op == "DoesNotExist":
        return ~present
    if op in ("Gt", "Lt"):
        if len(values) != 1:
            return np.zeros(na.N, bool)
        try:
            v = int(values[0])
        except ValueError:
            return np.zeros(na.N, bool)
        num = na.label_numeric(key)
        with np.errstate(invalid="ignore"):
            return (num > v) if op == "Gt" else (num < v)
    ids = np.array([na.values.lookup(v) for v in values], np.int32)
    if col is None:
        isin = np.zeros(na.N, bool)
    else:
        isin = np.isin(col, ids[ids > 0]) & present
    return isin if op == "In" else ~isin  # NotIn: absent key also matches


def _field_expr_vec(na: NodeArrays, expr: dict) -> np.ndarray:
    if expr.get("key") != "metadata.name":
        return np.zeros(na.N, bool)
    ids = np.array([na.values.lookup(v) for v in expr.get("values") or []], np.int32)
    isin = np.isin(na.name_ids, ids[ids > 0])
    op = expr.get("operator", "In")
    return isin if op == "In" else (~isin if op == "NotIn" else np.zeros(na.N, bool))


def node_selector_term_vec(na: NodeArrays, term: dict) -> np.ndarray:
    """One NodeSelectorTerm → bool[N]; empty term matches nothing (upstream semantics)."""
    exprs = term.get("matchExpressions") or []
    fields = term.get("matchFields") or []
    if not exprs and not fields:
        return np.zeros(na.N, bool)
    m = np.ones(na.N, bool)
    for e in exprs:
        m &= _expr_vec(na, e)
    for e in fields:
        m &= _field_expr_vec(na, e)
    return m


def node_affinity_vec(na: NodeArrays, pod_spec: dict) -> np.ndarray:
    """nodeSelector map AND requiredDuringScheduling node affinity → bool[N]."""
    m = np.ones(na.N, bool)
    for k, v in (pod_spec.get("nodeSelector") or {}).items():
        col = na.label_vals.get(k)
        want = na.values.lookup(str(v))
        m &= (col == want) & (col > 0) if col is not None and want else np.zeros(na.N, bool)
    required = ((pod_spec.get("affinity") or {}).get("nodeAffinity") or {}).get(
        "requiredDuringSchedulingIgnoredDuringExecution"
    )
    if required:
        terms = required.get("nodeSelectorTerms") or []
        om = np.zeros(na.N, bool)
        for t in terms:
            om |= node_selector_term_vec(na, t)
        m &= om
    return m


def _taint_masks(na: NodeArrays, tolerations: List[dict]) -> Tuple[np.ndarray, np.ndarray]:
    """(hard_ok[N], prefer_count[N]): NoSchedule/NoExecute all tolerated, and count of
    untolerated PreferNoSchedule taints (TaintToleration filter + score inputs)."""
    hard_ok = np.ones(na.N, bool)
    prefer_cnt = np.zeros(na.N, np.float32)
    # tolerations relevant to PreferNoSchedule scoring: effect empty or PreferNoSchedule
    pref_tols = [t for t in tolerations if not t.get("effect") or t.get("effect") == "PreferNoSchedule"]
    cache: Dict[tuple, Tuple[bool, int]] = {}
    for i, taints in enumerate(na.taints):
        if not taints:
            continue
        got = cache.get(taints)
        if got is None:
            ok = True
            cnt = 0
            for key, value, effect in taints:
                taint = {"key": key, "value": value, "effect": effect}
                if effect in ("NoSchedule", "NoExecute"):
                    if not any(toleration_tolerates_taint(t, taint) for t in tolerations):
                        ok = False
                elif effect == "PreferNoSchedule":
                    if not any(toleration_tolerates_taint(t, taint) for t in pref_tols):
                        cnt += 1
            got = cache[taints] = (ok, cnt)
        hard_ok[i], prefer_cnt[i] = got
    return hard_ok, prefer_cnt


def _unschedulable_ok(na: NodeArrays, tolerations: List[dict]) -> np.ndarray:
    """NodeUnschedulable plugin: spec.unschedulable blocked unless the pod tolerates the
    node.kubernetes.io/unschedulable:NoSchedule taint."""
    tolerates = any(toleration_tolerates_taint(t, _UNSCHED_TAINT) for t in tolerations)
    return ~na.unschedulable | tolerates


# ------------------------------------------------------------- terms & counters -------

HOSTNAME = C.LabelHostname


def _canon(obj) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


@dataclass(frozen=True)
class CounterSpec:
    """Count of placed pods matching (namespaces, selector) per domain of topo_key."""

    topo_key: str
    namespaces: frozenset
    selector_canon: str

    def selector(self) -> Optional[dict]:
        return json.loads(self.selector_canon)

    def matches_pod(self, pod: dict) -> bool:
        if namespace_of(pod) not in self.namespaces:
            return False
        return match_label_selector(self.selector(), labels_of(pod))


@dataclass(frozen=True)
class CarrierSpec:
    """A term carried by placed pods: (use, topo, namespaces, selector, weight)."""

    use: str  # 'anti' (required anti-affinity), 'hard' (required affinity), 'pref'
    topo_key: str
    namespaces: frozenset
    selector_canon: str
    weight: float  # signed for 'pref'; 1 for anti/hard

    def matches_pod(self, pod: dict) -> bool:
        if namespace_of(pod) not in self.namespaces:
            return False
        return match_label_selector(json.loads(self.selector_canon), labels_of(pod))


def _affinity_terms(pod: dict):
    """Extract (required_aff, required_anti, preferred[(weight, term)]) raw term dicts."""
    aff = (pod.get("spec") or {}).get("affinity") or {}
    pa = aff.get("podAffinity") or {}
    paa = aff.get("podAntiAffinity") or {}
    req_aff = pa.get("requiredDuringSchedulingIgnoredDuringExecution") or []
    req_anti = paa.get("requiredDuringSchedulingIgnoredDuringExecution") or []
    pref = [(p.get("weight", 0), p.get("podAffinityTerm") or {}) for p in
            pa.get("preferredDuringSchedulingIgnoredDuringExecution") or []]
    pref += [(-p.get("weight", 0), p.get("podAffinityTerm") or {}) for p in
             paa.get("preferredDuringSchedulingIgnoredDuringExecution") or []]
    return req_aff, req_anti, pref


def _term_namespaces(term: dict, pod: dict) -> frozenset:
    ns = term.get("namespaces") or []
    return frozenset(ns) if ns else frozenset([namespace_of(pod)])


def _spread_constraints(pod: dict, when: str) -> List[dict]:
    return [
        c for c in (pod.get("spec") or {}).get("topologySpreadConstraints") or []
        if c.get("whenUnsatisfiable", "DoNotSchedule") == when
    ]


def carried_specs_of_pod(pod: dict) -> List[CarrierSpec]:
    """Carrier terms a pod contributes once placed (interpodaffinity's existing-pod
    directions: RequiredAntiAffinityTerms for Filter; Required/Preferred terms for Score)."""
    req_aff, req_anti, pref = _affinity_terms(pod)
    out = []
    for t in req_anti:
        out.append(CarrierSpec("anti", t.get("topologyKey", ""), _term_namespaces(t, pod),
                               _canon(t.get("labelSelector")), 1.0))
    for t in req_aff:
        out.append(CarrierSpec("hard", t.get("topologyKey", ""), _term_namespaces(t, pod),
                               _canon(t.get("labelSelector")), 1.0))
    for w, t in pref:
        if w:
            out.append(CarrierSpec("pref", t.get("topologyKey", ""), _term_namespaces(t, pod),
                                   _canon(t.get("labelSelector")), float(w)))
    return out


# --------------------------------------------------------------- group encoding -------


def _freeze(o):
    """Recursively hashable form of a JSON-ish object (much faster than json.dumps
    canonicalization on the per-pod hot path)."""
    if isinstance(o, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in o.items()))
    if isinstance(o, (list, tuple)):
        return tuple(_freeze(v) for v in o)
    return o


SIG_MEMO_KEY = "__sig_memo__"  # stamped by workload expansion; popped by the engine

_native_hash = "unresolved"

# annotation keys that change Filter/commit behavior (plugins/) — part of the
# signature subtree on both the native and computed paths
_SIG_ANNO_KEYS = (C.AnnoGpuMem, C.AnnoGpuCount, C.AnnoGpuIndex, C.AnnoPodLocalStorage)


def scheduling_signature(pod: dict):
    """Pods with equal signatures are interchangeable to every predicate and score.
    Returns an opaque hashable key.

    Fast paths, in order:
    1. workload memo — replicas of one template share a precomputed signature;
    2. native pod_sig (C++, open_simulator_tpu/native): one call that extracts
       and canonically hashes the RAW scheduling-relevant subtree — namespace,
       labels, nodeSelector, affinity, tolerations, topologySpreadConstraints,
       nodeName, hostNetwork, containers, initContainers, overhead, sorted
       owner kinds, and the extended-resource annotations. Raw hashing may
       split groups the computed form would merge (e.g. "1000m" vs "1" cpu),
       which only duplicates identical groups — never merges distinct ones;
    3. the pure-Python computed tuple.
    """
    memo = pod.get(SIG_MEMO_KEY)
    if memo is not None:
        return memo

    global _native_hash
    if _native_hash == "unresolved":
        from ..native import pod_sig_fn

        _native_hash = pod_sig_fn()
    spec = pod.get("spec") or {}
    if _native_hash is not None:
        try:
            # one C call: subtree extraction + canonical hash (native/_hashobj.cpp
            # pod_sig) — hash-identical to canon_hash over the tuple listed in
            # the docstring above, without the ~15 Python dict gets per pod
            return _native_hash(pod, _SIG_ANNO_KEYS)
        except TypeError:
            pass  # exotic object in the tree → computed tuple below
    owner_kinds = sorted({r.get("kind", "") for r in (pod.get("metadata") or {}).get("ownerReferences") or []})
    images = sorted(c.get("image", "") for c in spec.get("containers") or [])
    return (
        namespace_of(pod),
        _freeze(labels_of(pod)),
        _freeze(spec.get("nodeSelector")),
        _freeze(spec.get("affinity")),
        _freeze(spec.get("tolerations")),
        _freeze(spec.get("topologySpreadConstraints")),
        spec.get("nodeName"),
        tuple(sorted(pod_host_ports(pod))),
        tuple(sorted(pod_resource_requests(pod).items())),
        # NonZero scoring depends on the per-container split, not just the sum
        tuple(pod_nonzero_cpu_mem(pod)),
        tuple(owner_kinds),
        tuple(images),
        # extended-resource annotations change Filter/commit behavior (plugins/)
        tuple(annotations_of(pod).get(k) for k in _SIG_ANNO_KEYS),
    )


def strip_daemon_pin(pod: dict) -> Tuple[dict, Optional[str]]:
    """Detect the DaemonSet pin pattern — every required term carries matchFields
    metadata.name In [x] for one node x — and return (pod-sans-pin, node name) or
    (pod, None). The stripped pod keeps its matchExpressions so the group's static
    mask still applies (models/workloads.py set_daemon_pod_node_affinity keeps both)."""
    spec = pod.get("spec") or {}
    required = ((spec.get("affinity") or {}).get("nodeAffinity") or {}).get(
        "requiredDuringSchedulingIgnoredDuringExecution"
    )
    if not required:
        return pod, None
    terms = required.get("nodeSelectorTerms") or []
    target = None
    for t in terms:
        mf = t.get("matchFields") or []
        if len(mf) != 1 or mf[0].get("key") != "metadata.name" or mf[0].get("operator") != "In":
            return pod, None
        vals = mf[0].get("values") or []
        if len(vals) != 1 or (target is not None and vals[0] != target):
            return pod, None
        target = vals[0]
    if target is None:
        return pod, None
    import copy

    stripped = copy.deepcopy(pod)
    sterms = stripped["spec"]["affinity"]["nodeAffinity"][
        "requiredDuringSchedulingIgnoredDuringExecution"]["nodeSelectorTerms"]
    keep = []
    for t in sterms:
        t.pop("matchFields", None)
        if t.get("matchExpressions"):
            keep.append(t)
    if keep:
        stripped["spec"]["affinity"]["nodeAffinity"][
            "requiredDuringSchedulingIgnoredDuringExecution"]["nodeSelectorTerms"] = keep
    else:
        stripped["spec"]["affinity"]["nodeAffinity"].pop(
            "requiredDuringSchedulingIgnoredDuringExecution")
    return stripped, target


def extract_forced_node(pod: dict, na: NodeArrays) -> Tuple[dict, int]:
    """strip_daemon_pin resolved against the cluster: (pod-sans-pin, node index),
    or (pod, -1) when there is no pin or the target node is unknown."""
    stripped, target = strip_daemon_pin(pod)
    if target is None or target not in na.index:
        return pod, -1
    return stripped, na.index[target]


@dataclass
class GroupInfo:
    template: dict
    # per-pod static vectors
    requests: np.ndarray          # [R]
    nonzero: np.ndarray           # [2]
    ports: List[tuple]
    unknown_resource: bool
    # per-node static vectors
    static_mask: np.ndarray       # [N] bool
    mask_taint: np.ndarray        # [N] bool  (component masks kept for diagnostics)
    mask_unsched: np.ndarray      # [N] bool
    mask_aff: np.ndarray          # [N] bool
    mask_extra: np.ndarray        # [N] bool (out-of-tree plugin filters)
    simon_raw: np.ndarray         # [N] f32 (0..1+ max share)
    nodeaff_raw: np.ndarray       # [N] f32
    taint_raw: np.ndarray         # [N] f32
    avoid_raw: np.ndarray         # [N] f32 (0 or 100)
    image_raw: np.ndarray         # [N] f32 (0..100)
    extra_raw: np.ndarray         # [N] f32: out-of-tree plugin score sum
    # term slots (counter ids + params)
    req_aff: List[int] = field(default_factory=list)
    req_anti: List[int] = field(default_factory=list)
    pref: List[Tuple[int, float]] = field(default_factory=list)          # (counter, signed w)
    spread_dns: List[Tuple[int, float, float]] = field(default_factory=list)  # (counter, maxSkew, self)
    spread_sa: List[Tuple[int, float, float]] = field(default_factory=list)
    ss_counter: int = -1
    ss_skip: bool = False         # pod has explicit topologySpreadConstraints
    aff_self: bool = False        # pod matches all its own required affinity selectors
    dns_elig: Optional[np.ndarray] = None  # [N] bool: nodes counted for min-match domains
    carried: List[CarrierSpec] = field(default_factory=list)
    gpu_mem: float = 0.0          # per-GPU memory request (gpu-share annotations)
    gpu_num: float = 0.0
    gpu_pre_ids: Optional[List[int]] = None  # pre-assigned device ids (gpu-index)
    # open-local volume slots, in processing order (plugins/openlocal.py)
    lvm_sizes: List[float] = field(default_factory=list)
    lvm_vg_ids: List[int] = field(default_factory=list)   # 0 = unnamed (Binpack)
    sdev_sizes: List[float] = field(default_factory=list)
    sdev_media: List[int] = field(default_factory=list)   # 1 hdd / 2 ssd


class Encoder:
    """Builds and caches groups/counters/carriers for one Simulator instance."""

    def __init__(self, na: NodeArrays, axis: ResourceAxis, cluster_model) -> None:
        self.na = na
        self.axis = axis
        self.model = cluster_model  # owns services/rc/rs/sts lists + placed pods
        self.groups: Dict[str, int] = {}
        self.group_list: List[GroupInfo] = []
        self.counters: Dict[CounterSpec, int] = {}
        self.counter_list: List[CounterSpec] = []
        self.carriers: Dict[CarrierSpec, int] = {}
        self.carrier_list: List[CarrierSpec] = []
        self.ports = StringTable()  # (protocol, port) → id; hostIP folded (see kernels)
        self.gpu_host = None  # plugins.gpushare.GpuShareHost, set by the engine
        self.local_host = None  # plugins.openlocal.OpenLocalHost, set by the engine
        # --default-scheduler-config disables for the statically-folded filter
        # plugins (taints/unschedulable/node-affinity); set by the engine
        self.filter_disabled: frozenset = frozenset()
        # out-of-tree plugin objects (see plugins/registry.py), set by the engine
        self.extra_plugins: list = []

    # -- interning ---------------------------------------------------------------

    def counter_id(self, topo_key: str, namespaces: frozenset, selector) -> int:
        spec = CounterSpec(topo_key, namespaces, _canon(selector))
        i = self.counters.get(spec)
        if i is None:
            i = len(self.counter_list)
            self.counters[spec] = i
            self.counter_list.append(spec)
        return i

    def carrier_id(self, spec: CarrierSpec) -> int:
        i = self.carriers.get(spec)
        if i is None:
            i = len(self.carrier_list)
            self.carriers[spec] = i
            self.carrier_list.append(spec)
        return i

    def port_ids(self, ports: Sequence[tuple]) -> List[int]:
        # fold hostIP: 0.0.0.0 conflicts with everything on (proto, port); we intern
        # (proto, port) only — a deliberate simplification (distinct specific hostIPs
        # sharing a port are rare in simulation inputs; documented deviation).
        return [self.ports.intern((p[0], p[2])) for p in ports]

    # -- group construction ------------------------------------------------------

    def group_of(self, pod: dict) -> int:
        sig = scheduling_signature(pod)
        if self.extra_plugins:
            # out-of-tree plugins may read any template content; the built-in
            # signature only covers the fields the built-in plugins read, so
            # widen the group key with the full annotations (the plugin
            # contract: verdicts depend on template content — spec, labels,
            # annotations, namespace — never on pod identity like name/uid)
            sig = (sig, _freeze((pod.get("metadata") or {}).get("annotations")))
        gi = self.groups.get(sig)
        if gi is None:
            gi = len(self.group_list)
            self.groups[sig] = gi
            self.group_list.append(self._build_group(pod))
        return gi

    def rebuild_group_axes(self) -> None:
        """Recompute every interned group's node-axis statics against the
        CURRENT NodeArrays — the second half of a delta node-add
        (NodeArrays.extend): group [N] vectors (masks, raw scores, dns
        eligibility) are re-derived from each group's immutable template.
        Group/counter/carrier IDS are stable: _build_group re-interns the
        same CounterSpec/CarrierSpec keys, which the interners resolve to
        their existing slots, so every previously encoded pod_group array
        and every match_cache entry stays valid."""
        self.group_list = [self._build_group(g.template)
                           for g in self.group_list]

    def _build_group(self, pod: dict) -> GroupInfo:
        na, axis = self.na, self.axis
        spec = pod.get("spec") or {}
        tolerations = spec.get("tolerations") or []
        hard_ok, prefer_cnt = _taint_masks(na, tolerations)
        unsched_ok = _unschedulable_ok(na, tolerations)
        aff_ok = node_affinity_vec(na, spec)
        # scheduler-config filter disables (kernel-evaluated filters are
        # flagged off in kernels.FilterFlags instead); NodeName pinning is a
        # separate plugin and stays on
        if "TaintToleration" in self.filter_disabled:
            hard_ok = np.ones(na.N, bool)
        if "NodeUnschedulable" in self.filter_disabled:
            unsched_ok = np.ones(na.N, bool)
        if "NodeAffinity" in self.filter_disabled:
            aff_ok = np.ones(na.N, bool)
        if spec.get("nodeName"):
            aff_ok = aff_ok & (na.name_ids == na.values.lookup(spec["nodeName"]))
        mask = hard_ok & unsched_ok & aff_ok

        requests = axis.pod_vector(pod).astype(np.float32)
        g = GroupInfo(
            template=pod,
            requests=requests,
            nonzero=pod_nonzero_cpu_mem(pod).astype(np.float32),
            ports=pod_host_ports(pod),
            unknown_resource=pod_has_unknown_resource(pod, axis),
            static_mask=mask,
            mask_taint=hard_ok,
            mask_unsched=unsched_ok,
            mask_aff=aff_ok,
            simon_raw=self._simon_raw(requests),
            nodeaff_raw=self._nodeaff_raw(spec),
            taint_raw=prefer_cnt,
            avoid_raw=self._avoid_raw(pod),
            image_raw=self._image_raw(pod),
            extra_raw=np.zeros(na.N, np.float32),
            mask_extra=np.ones(na.N, bool),
            aff_self=True,
        )
        # out-of-tree plugins (extension point parity: the reference's library
        # API accepts extra framework registries, simulator.go:471-500). Their
        # verdicts depend only on (pod template, node), so they fold into the
        # static tables and cost nothing per scheduling step.
        for pl in self.extra_plugins:
            w = float(getattr(pl, "weight", 1.0))
            flt = getattr(pl, "filter", None)
            score = getattr(pl, "score", None)
            for i, node in enumerate(na.nodes):
                if flt is not None and not flt(pod, node):
                    g.mask_extra[i] = False
                if score is not None:
                    g.extra_raw[i] += w * float(score(pod, node))
        g.static_mask = g.static_mask & g.mask_extra

        from ..plugins.gpushare import gpu_id_str_to_list, pod_gpu_count, pod_gpu_index, pod_gpu_mem

        g.gpu_mem = float(pod_gpu_mem(pod))
        g.gpu_num = float(pod_gpu_count(pod))
        pre = pod_gpu_index(pod)
        if pre:
            try:
                ids = gpu_id_str_to_list(pre)
                g.gpu_pre_ids = ids or None
            except ValueError:
                g.gpu_pre_ids = None  # invalid id falls back to normal allocation

        if self.local_host is not None:
            # Volumes are encoded even when NO node has local storage: the filter
            # then fails everywhere, matching the reference's nil-node-cache
            # Unschedulable (open-local.go:60-70).
            from ..plugins.openlocal import resolve_pod_volumes

            lvm, dev = resolve_pod_volumes(pod, self.model.storage_classes)
            g.lvm_sizes = [float(v.size) for v in lvm]
            g.lvm_vg_ids = [
                self.local_host.vg_name_id(v.vg_name) if v.vg_name else 0 for v in lvm
            ]
            g.sdev_sizes = [float(v.size) for v in dev]
            g.sdev_media = [2 if v.media == "ssd" else 1 for v in dev]
        # inter-pod affinity terms
        req_aff, req_anti, pref = _affinity_terms(pod)
        for t in req_aff:
            nss = _term_namespaces(t, pod)
            g.req_aff.append(self.counter_id(t.get("topologyKey", ""), nss, t.get("labelSelector")))
            if namespace_of(pod) not in nss or not match_label_selector(
                t.get("labelSelector"), labels_of(pod)
            ):
                g.aff_self = False
        for t in req_anti:
            g.req_anti.append(
                self.counter_id(t.get("topologyKey", ""), _term_namespaces(t, pod), t.get("labelSelector"))
            )
        for w, t in pref:
            if w:
                g.pref.append(
                    (self.counter_id(t.get("topologyKey", ""), _term_namespaces(t, pod),
                                     t.get("labelSelector")), float(w))
                )
        # topology spread
        own_ns = frozenset([namespace_of(pod)])
        podlabels = labels_of(pod)
        for c in _spread_constraints(pod, "DoNotSchedule"):
            cid = self.counter_id(c.get("topologyKey", ""), own_ns, c.get("labelSelector"))
            selfm = 1.0 if match_label_selector(c.get("labelSelector"), podlabels) else 0.0
            g.spread_dns.append((cid, float(c.get("maxSkew", 1)), selfm))
        for c in _spread_constraints(pod, "ScheduleAnyway"):
            cid = self.counter_id(c.get("topologyKey", ""), own_ns, c.get("labelSelector"))
            selfm = 1.0 if match_label_selector(c.get("labelSelector"), podlabels) else 0.0
            g.spread_sa.append((cid, float(c.get("maxSkew", 1)), selfm))
        if g.spread_dns or g.spread_sa:
            # eligibility for min-match domains / SA counting: nodes passing the pod's
            # node affinity and carrying every constraint topo key (filtering.go
            # calPreFilterState + nodeLabelsMatchSpreadConstraints)
            elig = node_affinity_vec(na, spec)
            for cid, _, _ in g.spread_dns + g.spread_sa:
                elig &= na.domain_of(self.counter_list[cid].topo_key) >= 0
            g.dns_elig = elig
        # selector spread (only when no explicit constraints, selector_spread.go:49-51)
        g.ss_skip = bool(spec.get("topologySpreadConstraints"))
        if not g.ss_skip:
            sel = self.model.default_spread_selector(pod)
            if sel is not None:
                g.ss_counter = self.counter_id(HOSTNAME, own_ns, sel)
        g.carried = [CarrierSpec(cs.use, cs.topo_key, cs.namespaces, cs.selector_canon, cs.weight)
                     for cs in carried_specs_of_pod(pod)]
        for cs in g.carried:
            self.carrier_id(cs)
        return g

    # -- static score inputs -------------------------------------------------------

    def _simon_raw(self, requests: np.ndarray) -> np.ndarray:
        """Simon bin-packing signal (plugin/simon.go:45-68): max over requested
        resources of req/(alloc-req); Share() semantics at alloc-req == 0. Pods with no
        requests score MaxNodeScore on every node (→ constant → normalizes to 0)."""
        alloc = self.na.alloc  # [N, R]
        req = requests.astype(np.float64).copy()  # simonlint: ignore[dtype-drift] -- host-side Share() math; result narrows to f32 below
        req[PODS_I] = 0.0  # the synthetic pods-slot is not a PodRequestsAndLimits entry
        if not req.any():
            return np.ones(self.na.N, np.float32)
        avail = alloc - req[None, :]
        with np.errstate(divide="ignore", invalid="ignore"):
            share = np.where(
                avail == 0,
                np.where(req[None, :] > 0, 1.0, 0.0),
                req[None, :] / avail,
            )
        share = np.where(req[None, :] > 0, share, 0.0)  # untouched resources contribute 0
        return np.max(np.where(alloc > 0, share, 0.0), axis=1).astype(np.float32)

    def _nodeaff_raw(self, spec: dict) -> np.ndarray:
        raw = np.zeros(self.na.N, np.float32)
        prefs = ((spec.get("affinity") or {}).get("nodeAffinity") or {}).get(
            "preferredDuringSchedulingIgnoredDuringExecution"
        ) or []
        for p in prefs:
            w = p.get("weight", 0)
            if w:
                raw += w * node_selector_term_vec(self.na, p.get("preference") or {}).astype(np.float32)
        return raw

    def _avoid_raw(self, pod: dict) -> np.ndarray:
        """NodePreferAvoidPods (plugin nodepreferavoidpods): 100 unless the node's
        preferAvoidPods annotation targets the pod's RC/RS controller."""
        raw = np.full(self.na.N, 100.0, np.float32)
        owners = (pod.get("metadata") or {}).get("ownerReferences") or []
        ctrl = next((o for o in owners if o.get("controller") and o.get("kind") in
                     ("ReplicationController", "ReplicaSet")), None)
        if ctrl is None:
            return raw
        from .store import LazyNodeSeq

        if (isinstance(self.na.nodes, LazyNodeSeq)
                and not self.na.nodes.store.any_annotation(
                    "scheduler.alpha.kubernetes.io/preferAvoidPods")
                and not self.na.nodes._extra):
            return raw  # no block carries the annotation: skip the N-scan
        for i, node in enumerate(self.na.nodes):
            anno = annotations_of(node).get("scheduler.alpha.kubernetes.io/preferAvoidPods")
            if not anno:
                continue
            try:
                entries = json.loads(anno).get("preferAvoidPods") or []
            except (ValueError, AttributeError):
                continue
            for e in entries:
                pc = ((e.get("podSignature") or {}).get("podController")) or {}
                if pc.get("kind") == ctrl.get("kind") and pc.get("uid", ctrl.get("uid")) == ctrl.get("uid"):
                    raw[i] = 0.0
        return raw

    def _node_image_sizes(self) -> Tuple[List[Dict[str, float]], bool]:
        """Per-node image-name → size maps, built ONCE per encoder: they are
        group-independent, and rebuilding them per group made ImageLocality
        the dominant encode cost on many-group batches (41 groups × 5k nodes
        of dict parsing ≈ 0.75s on the hard-predicate bench)."""
        cached = getattr(self, "_image_sizes_cache", None)
        if cached is not None:
            return cached
        from .store import LazyNodeSeq

        if (isinstance(self.na.nodes, LazyNodeSeq)
                and not self.na.nodes.store.has_images
                and not self.na.nodes._extra):
            # columnar fast path: the store knows no block advertises images,
            # so don't materialize N dicts to learn the same thing
            self._image_sizes_cache = ([], False)
            return self._image_sizes_cache
        sizes: List[Dict[str, float]] = []
        have_any = False
        for node in self.na.nodes:
            m: Dict[str, float] = {}
            for img in (node.get("status") or {}).get("images") or []:
                for nm in img.get("names") or []:
                    m[nm] = float(img.get("sizeBytes", 0))
            if m:
                have_any = True
            sizes.append(m)
        self._image_sizes_cache = (sizes, have_any)
        return sizes, have_any

    def _image_raw(self, pod: dict) -> np.ndarray:
        """ImageLocality (imagelocality plugin): scaled sum of present image sizes,
        normalized over [23MB, 1000MB x numContainers] (calculatePriority scales
        the max threshold per container, image_locality.go:82-91). Zero when
        nodes advertise no images."""
        mb = 1024 * 1024
        n_containers = max(1, len((pod.get("spec") or {}).get("containers") or []))
        min_t, max_t = 23 * mb, 1000 * mb * n_containers
        sizes, have_any = self._node_image_sizes()
        raw = np.zeros(self.na.N, np.float32)
        if not have_any:
            return raw
        images = [c.get("image", "") for c in (pod.get("spec") or {}).get("containers") or []]
        total_nodes = max(1, self.na.N)
        num_nodes = {img: sum(1 for m in sizes if img in m) for img in images}
        for i, m in enumerate(sizes):
            s = 0.0
            for img in images:
                if img in m:
                    s += m[img] * (num_nodes[img] / total_nodes)
            if s < min_t:
                raw[i] = 0.0
            else:
                raw[i] = np.float32(int(100 * (min(s, max_t) - min_t) / (max_t - min_t)))
        return raw


# ------------------------------------------------------------- placed records ---------


@dataclass
class PlacedGroup:
    """Host-side memo of every bound pod sharing one scheduling signature:
    everything the batch-table seeds need, aggregated as per-node counts so
    committing a pod is a dict increment instead of an object allocation
    (the engine's commit loop runs once per pod — 100k allocations were a
    measurable slice of the headline bench)."""

    pod: dict    # representative pod (selector matching reads template fields only)
    sig: object  # opaque hashable scheduling_signature key
    req_vec: np.ndarray      # [R] f32
    nonzero: np.ndarray      # [2] f32
    port_ids: List[int]
    carrier_ids: List[int]
    node_counts: Dict[int, int] = field(default_factory=dict)  # node_i → pods placed


# ---------------------------------------------------------------- batch tables --------


@dataclass
class BatchTables:
    """Everything the device kernels need for one schedulePods batch (all numpy; the
    engine moves them to jnp). Dimension names: N nodes, R resources, G groups, T
    counter rows, Tc carrier rows, D domains (+1 sentinel col), PORT port ids (+1
    sentinel 0), P pods."""

    # node-side
    alloc: np.ndarray            # [N, R] f32
    node_zone: np.ndarray        # [N] i32, 0 = no zone
    n_zones: int
    # group-side statics
    static_mask: np.ndarray      # [G, N] bool
    mask_taint: np.ndarray       # [G, N] bool
    mask_unsched: np.ndarray     # [G, N] bool
    mask_aff: np.ndarray         # [G, N] bool
    mask_extra: np.ndarray       # [G, N] bool
    simon_raw: np.ndarray        # [G, N] f32
    nodeaff_raw: np.ndarray      # [G, N] f32
    taint_raw: np.ndarray        # [G, N] f32
    avoid_raw: np.ndarray        # [G, N] f32
    image_raw: np.ndarray        # [G, N] f32
    extra_raw: np.ndarray        # [G, N] f32: out-of-tree plugin scores
    grp_requests: np.ndarray     # [G, R] f32
    grp_nonzero: np.ndarray      # [G, 2] f32
    grp_unknown: np.ndarray      # [G] bool
    grp_ports: np.ndarray        # [G, PP] i32 (0 = pad)
    # counters
    counter_dom: np.ndarray      # [T, N] i32 (domain id; D = key-absent sentinel)
    counter_topo: np.ndarray     # [T] i32: unique-topology row per counter
    topo_dom: np.ndarray         # [U, N] i32: node→domain per unique topo key
    counter_sel_match_g: np.ndarray  # [T, G] bool: does a group pod match counter t
    req_aff_t: np.ndarray        # [G, A] i32 (-1 pad)
    grp_aff_self: np.ndarray     # [G] bool
    req_anti_t: np.ndarray       # [G, B] i32
    pref_t: np.ndarray           # [G, Cp] i32
    pref_w: np.ndarray           # [G, Cp] f32
    dns_t: np.ndarray            # [G, Sd] i32
    dns_maxskew: np.ndarray      # [G, Sd] f32
    dns_self: np.ndarray         # [G, Sd] f32
    dns_edom: np.ndarray         # [G, Sd, D+1] bool
    sa_t: np.ndarray             # [G, Ss] i32
    sa_maxskew: np.ndarray       # [G, Ss] f32
    sa_self: np.ndarray          # [G, Ss] f32
    ss_t: np.ndarray             # [G] i32 (-1 = no selector-spread counter)
    ss_skip: np.ndarray          # [G] bool (explicit constraints → plugin skipped)
    # carriers
    carr_dom: np.ndarray         # [Tc, N] i32
    carr_topo: np.ndarray        # [Tc] i32: unique-topology row per carrier
    carr_anti_t: np.ndarray      # [G, Ca] i32: anti carrier ids matching g (-1 pad)
    carr_w_t: np.ndarray         # [G, Cw] i32: weighted carrier ids for g (-1 pad)
    carr_w_w: np.ndarray         # [G, Cw] f32: those weights
    carr_sel_match_g: np.ndarray  # [Tc, G] bool
    grp_carries: np.ndarray      # [G, Tc] f32
    # gpu-share
    grp_gpu_mem: np.ndarray      # [G] f32
    grp_gpu_num: np.ndarray      # [G] f32
    grp_gpu_pre: np.ndarray      # [G] bool: pod carries a valid pre-assigned gpu-index
    grp_gpu_take: np.ndarray     # [G, MAXDEV] f32: unit counts per device when pre-assigned
    dev_total: np.ndarray        # [N, MAXDEV] f32
    # open-local
    grp_lvm_size: np.ndarray     # [G, SL] f32
    grp_lvm_vg: np.ndarray       # [G, SL] i32 (0 = unnamed)
    grp_sdev_size: np.ndarray    # [G, SD] f32
    grp_sdev_media: np.ndarray   # [G, SD] i32 (1 hdd / 2 ssd; 0 unused)
    vg_cap: np.ndarray           # [N, MAXVG] f32
    vg_nameid: np.ndarray        # [N, MAXVG] i32
    sdev_cap: np.ndarray         # [N, MAXSD] f32
    sdev_media: np.ndarray       # [N, MAXSD] i32
    # initial carry
    seed_requested: np.ndarray   # [N, R] f32
    seed_nonzero: np.ndarray     # [N, 2] f32
    seed_port_used: np.ndarray   # [N, PORT+1] bool
    seed_counter: np.ndarray     # [T, D+1] f32
    seed_carrier: np.ndarray     # [Tc, D+1] f32
    seed_dev_used: np.ndarray    # [N, MAXDEV] f32
    seed_vg_req: np.ndarray      # [N, MAXVG] f32
    seed_sdev_alloc: np.ndarray  # [N, MAXSD] f32
    # batch pods
    pod_group: np.ndarray        # [P] i32
    forced_node: np.ndarray      # [P] i32 (-1 = free)
    valid: np.ndarray            # [P] bool

    @property
    def dims(self) -> tuple:
        return (
            self.alloc.shape[0], self.alloc.shape[1], self.static_mask.shape[0],
            self.counter_dom.shape[0], self.carr_dom.shape[0],
            self.seed_counter.shape[1] - 1, self.seed_port_used.shape[1] - 1,
            self.pod_group.shape[0],
        )


def plugin_flags(bt: "BatchTables") -> Tuple[bool, bool]:
    """(enable_gpu, enable_storage): static kernel flags — True when the batch has
    any gpu / local-storage demand, so inert plugin subgraphs compile away."""
    return (
        bool(bt.grp_gpu_mem.any()),
        bool(bt.grp_lvm_size.any() or bt.grp_sdev_size.any()),
    )


def _bucket(n: int) -> int:
    """Next power of two (≥1) — the padding granularity for encoder-derived axes."""
    return 1 << max(0, (n - 1)).bit_length() if n > 1 else 1


def bucket_capped(n: int, cap: int, floor: int = 8) -> int:
    """Padding target for the pod/node axes: powers of two up to `cap`, then
    multiples of `cap` (bounds compile-cache churn at both small and large sizes)."""
    if n <= 0:
        return floor
    if n <= cap:
        return max(floor, _bucket(n))
    return ((n + cap - 1) // cap) * cap


def _pad_axis(a: np.ndarray, axis: int, target: int, fill) -> np.ndarray:
    cur = a.shape[axis]
    if cur >= target:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, target - cur)
    return np.pad(a, widths, constant_values=fill)


def pad_batch_tables(bt: "BatchTables", multiple: int) -> "BatchTables":
    """Pad the node axis of every table/seed to a multiple of `multiple` with
    phantom nodes that no pod can be placed on (static_mask False everywhere; the
    key-absent sentinel domain, so counters never move)."""
    import dataclasses

    N = bt.alloc.shape[0]
    target = N + ((-N) % multiple)
    if target == N:
        return bt
    D = bt.seed_counter.shape[1] - 1
    return dataclasses.replace(
        bt,
        alloc=_pad_axis(bt.alloc, 0, target, 0.0),
        node_zone=_pad_axis(bt.node_zone, 0, target, 0),
        static_mask=_pad_axis(bt.static_mask, 1, target, False),
        mask_taint=_pad_axis(bt.mask_taint, 1, target, False),
        mask_unsched=_pad_axis(bt.mask_unsched, 1, target, False),
        mask_aff=_pad_axis(bt.mask_aff, 1, target, False),
        mask_extra=_pad_axis(bt.mask_extra, 1, target, False),
        simon_raw=_pad_axis(bt.simon_raw, 1, target, 0.0),
        nodeaff_raw=_pad_axis(bt.nodeaff_raw, 1, target, 0.0),
        taint_raw=_pad_axis(bt.taint_raw, 1, target, 0.0),
        avoid_raw=_pad_axis(bt.avoid_raw, 1, target, 0.0),
        image_raw=_pad_axis(bt.image_raw, 1, target, 0.0),
        extra_raw=_pad_axis(bt.extra_raw, 1, target, 0.0),
        counter_dom=_pad_axis(bt.counter_dom, 1, target, D),
        topo_dom=_pad_axis(bt.topo_dom, 1, target, D),
        carr_dom=_pad_axis(bt.carr_dom, 1, target, D),
        dev_total=_pad_axis(bt.dev_total, 0, target, 0.0),
        vg_cap=_pad_axis(bt.vg_cap, 0, target, 0.0),
        vg_nameid=_pad_axis(bt.vg_nameid, 0, target, 0),
        sdev_cap=_pad_axis(bt.sdev_cap, 0, target, 0.0),
        sdev_media=_pad_axis(bt.sdev_media, 0, target, 0),
        seed_requested=_pad_axis(bt.seed_requested, 0, target, 0.0),
        seed_nonzero=_pad_axis(bt.seed_nonzero, 0, target, 0.0),
        seed_port_used=_pad_axis(bt.seed_port_used, 0, target, False),
        seed_dev_used=_pad_axis(bt.seed_dev_used, 0, target, 0.0),
        seed_vg_req=_pad_axis(bt.seed_vg_req, 0, target, 0.0),
        seed_sdev_alloc=_pad_axis(bt.seed_sdev_alloc, 0, target, 0.0),
    )


def pad_encoder_axes(bt: "BatchTables") -> "BatchTables":
    """Pad every encoder-derived axis (groups G, counters T, carriers Tc, port ids
    PORT, domains D, and the per-group term-slot axes) to power-of-two buckets with
    inert rows/columns.

    Why: the encoder interns groups/counters/domains cumulatively across apps, so
    every ScheduleApp batch otherwise gets brand-new table shapes and a fresh XLA
    compile (~20-40s on TPU). Bucketing bounds the number of distinct compiled
    shapes to a few per decade of growth. Inertness invariants:
    - pad G rows are never indexed (pod_group only holds real ids);
    - pad T/Tc rows carry the key-absent sentinel domain and match no group, so
      they never accumulate or block;
    - pad D columns sit between the real domains and the sentinel column, which
      moves from index D to index D_pad (ids in *_dom are remapped);
    - pad term slots use the same -1/0 fills as ordinary short rows.
    """
    import dataclasses

    G, N = bt.static_mask.shape
    T = bt.counter_dom.shape[0]
    Tc = bt.carr_dom.shape[0]
    D = bt.seed_counter.shape[1] - 1
    PORT = bt.seed_port_used.shape[1] - 1
    Gp, Tp, Tcp, Dp = _bucket(G), _bucket(T), _bucket(Tc), _bucket(D)
    PORTp = _bucket(PORT)
    pad_axis = _pad_axis

    def pad_dom(dom: np.ndarray) -> np.ndarray:
        # remap sentinel D -> Dp, then pad new rows entirely with the sentinel
        return np.where(dom == D, Dp, dom)

    def pad_counter_width(a: np.ndarray) -> np.ndarray:
        # [*, D+1] -> [*, Dp+1]: real cols 0..D-1 keep, sentinel col moves to Dp
        out = np.zeros(a.shape[:-1] + (Dp + 1,), a.dtype)
        out[..., :D] = a[..., :D]
        out[..., Dp] = a[..., D]
        return out

    r = dataclasses.replace(
        bt,
        # G axis
        static_mask=pad_axis(bt.static_mask, 0, Gp, False),
        mask_taint=pad_axis(bt.mask_taint, 0, Gp, False),
        mask_unsched=pad_axis(bt.mask_unsched, 0, Gp, False),
        mask_aff=pad_axis(bt.mask_aff, 0, Gp, False),
        mask_extra=pad_axis(bt.mask_extra, 0, Gp, False),
        simon_raw=pad_axis(bt.simon_raw, 0, Gp, 0.0),
        nodeaff_raw=pad_axis(bt.nodeaff_raw, 0, Gp, 0.0),
        taint_raw=pad_axis(bt.taint_raw, 0, Gp, 0.0),
        avoid_raw=pad_axis(bt.avoid_raw, 0, Gp, 0.0),
        image_raw=pad_axis(bt.image_raw, 0, Gp, 0.0),
        extra_raw=pad_axis(bt.extra_raw, 0, Gp, 0.0),
        grp_requests=pad_axis(bt.grp_requests, 0, Gp, 0.0),
        grp_nonzero=pad_axis(bt.grp_nonzero, 0, Gp, 0.0),
        grp_unknown=pad_axis(bt.grp_unknown, 0, Gp, False),
        grp_ports=pad_axis(pad_axis(bt.grp_ports, 0, Gp, 0), 1, _bucket(bt.grp_ports.shape[1]), 0),
        grp_aff_self=pad_axis(bt.grp_aff_self, 0, Gp, False),
        grp_gpu_mem=pad_axis(bt.grp_gpu_mem, 0, Gp, 0.0),
        grp_gpu_num=pad_axis(bt.grp_gpu_num, 0, Gp, 0.0),
        grp_gpu_pre=pad_axis(bt.grp_gpu_pre, 0, Gp, False),
        grp_gpu_take=pad_axis(bt.grp_gpu_take, 0, Gp, 0.0),
        grp_lvm_size=pad_axis(pad_axis(bt.grp_lvm_size, 0, Gp, 0.0), 1, _bucket(bt.grp_lvm_size.shape[1]), 0.0),
        grp_lvm_vg=pad_axis(pad_axis(bt.grp_lvm_vg, 0, Gp, 0), 1, _bucket(bt.grp_lvm_vg.shape[1]), 0),
        grp_sdev_size=pad_axis(pad_axis(bt.grp_sdev_size, 0, Gp, 0.0), 1, _bucket(bt.grp_sdev_size.shape[1]), 0.0),
        grp_sdev_media=pad_axis(pad_axis(bt.grp_sdev_media, 0, Gp, 0), 1, _bucket(bt.grp_sdev_media.shape[1]), 0),
        ss_t=pad_axis(bt.ss_t, 0, Gp, -1),
        ss_skip=pad_axis(bt.ss_skip, 0, Gp, False),
        grp_carries=pad_axis(pad_axis(bt.grp_carries, 0, Gp, 0.0), 1, Tcp, 0.0),
        # per-group term slots (pad G rows AND slot width)
        req_aff_t=pad_axis(pad_axis(bt.req_aff_t, 0, Gp, -1), 1, _bucket(bt.req_aff_t.shape[1]), -1),
        req_anti_t=pad_axis(pad_axis(bt.req_anti_t, 0, Gp, -1), 1, _bucket(bt.req_anti_t.shape[1]), -1),
        pref_t=pad_axis(pad_axis(bt.pref_t, 0, Gp, -1), 1, _bucket(bt.pref_t.shape[1]), -1),
        pref_w=pad_axis(pad_axis(bt.pref_w, 0, Gp, 0.0), 1, _bucket(bt.pref_w.shape[1]), 0.0),
        dns_t=pad_axis(pad_axis(bt.dns_t, 0, Gp, -1), 1, _bucket(bt.dns_t.shape[1]), -1),
        dns_maxskew=pad_axis(pad_axis(bt.dns_maxskew, 0, Gp, 1.0), 1, _bucket(bt.dns_maxskew.shape[1]), 1.0),
        dns_self=pad_axis(pad_axis(bt.dns_self, 0, Gp, 0.0), 1, _bucket(bt.dns_self.shape[1]), 0.0),
        dns_edom=pad_counter_width(
            pad_axis(pad_axis(bt.dns_edom, 0, Gp, False), 1, _bucket(bt.dns_edom.shape[1]), False)
        ),
        carr_anti_t=pad_axis(pad_axis(bt.carr_anti_t, 0, Gp, -1), 1, _bucket(max(1, bt.carr_anti_t.shape[1])), -1),
        carr_w_t=pad_axis(pad_axis(bt.carr_w_t, 0, Gp, -1), 1, _bucket(max(1, bt.carr_w_t.shape[1])), -1),
        carr_w_w=pad_axis(pad_axis(bt.carr_w_w, 0, Gp, 0.0), 1, _bucket(max(1, bt.carr_w_w.shape[1])), 0.0),
        sa_t=pad_axis(pad_axis(bt.sa_t, 0, Gp, -1), 1, _bucket(bt.sa_t.shape[1]), -1),
        sa_maxskew=pad_axis(pad_axis(bt.sa_maxskew, 0, Gp, 1.0), 1, _bucket(bt.sa_maxskew.shape[1]), 1.0),
        sa_self=pad_axis(pad_axis(bt.sa_self, 0, Gp, 0.0), 1, _bucket(bt.sa_self.shape[1]), 0.0),
        # T axis
        counter_dom=pad_axis(pad_dom(bt.counter_dom), 0, Tp, Dp),
        # pad counter/carrier rows point at the all-sentinel topology row
        # (the last real row by construction), pad topology rows are all-
        # sentinel themselves — neither can ever accumulate
        counter_topo=pad_axis(bt.counter_topo, 0, Tp,
                              bt.topo_dom.shape[0] - 1),
        topo_dom=pad_axis(pad_dom(bt.topo_dom), 0,
                          _bucket(bt.topo_dom.shape[0]), Dp),
        counter_sel_match_g=pad_axis(pad_axis(bt.counter_sel_match_g, 0, Tp, False), 1, Gp, False),
        seed_counter=pad_axis(pad_counter_width(bt.seed_counter), 0, Tp, 0.0),
        # Tc axis
        carr_dom=pad_axis(pad_dom(bt.carr_dom), 0, Tcp, Dp),
        carr_topo=pad_axis(bt.carr_topo, 0, Tcp, bt.topo_dom.shape[0] - 1),
        carr_sel_match_g=pad_axis(pad_axis(bt.carr_sel_match_g, 0, Tcp, False), 1, Gp, False),
        seed_carrier=pad_axis(pad_counter_width(bt.seed_carrier), 0, Tcp, 0.0),
        # PORT axis
        seed_port_used=pad_axis(bt.seed_port_used, 1, PORTp + 1, False),
    )
    return r


def _pad_slots(rows: List[List], width: int, fill, dtype) -> np.ndarray:
    out = np.full((len(rows), max(1, width)), fill, dtype)
    for i, r in enumerate(rows):
        for j, v in enumerate(r):
            out[i, j] = v
    return out


def build_pod_axis_tables(
    enc: Encoder,
    batch: List[Tuple[int, int]],          # (group_id, forced_node) per pod, in order
    pad_to: Optional[int] = None,
) -> Dict[str, np.ndarray]:
    """The node-axis-INDEPENDENT half of BatchTables: per-group statics
    (requests, term slots, selector-match matrices, gpu/storage group rows)
    and the batch pod arrays. Everything here is a function of the encoder's
    interned groups/counters/carriers and the pod order alone — the
    incremental capacity prober computes it exactly once per search and keeps
    it fixed across every candidate node count.

    Side effect: interns every group's host ports, which SIZES the port axis.
    Must therefore run before build_node_axis_tables (the seed port table
    reads len(enc.ports))."""
    G = max(1, len(enc.group_list))
    T = max(1, len(enc.counter_list))
    Tc = max(1, len(enc.carrier_list))
    R = enc.axis.R
    groups = enc.group_list or []
    # Intern every group's host ports BEFORE sizing the port axis, or new ports in this
    # batch would land out of range and clamp onto other pods' columns.
    grp_port_ids = [enc.port_ids(g.ports) for g in groups] or [[]]

    A = max((len(g.req_aff) for g in groups), default=0)
    B = max((len(g.req_anti) for g in groups), default=0)
    Cp = max((len(g.pref) for g in groups), default=0)
    Sd = max((len(g.spread_dns) for g in groups), default=0)
    Ss = max((len(g.spread_sa) for g in groups), default=0)
    PP = max((len(g.ports) for g in groups), default=0)

    carr_sel_match_g = np.zeros((Tc, G), bool)
    for t, cs in enumerate(enc.carrier_list):
        for gi, g in enumerate(groups):
            carr_sel_match_g[t, gi] = cs.matches_pod(g.template)
    # per-group carrier SLOTS: the kernels gather only these rows instead of
    # the full [Tc, N] table (Tc grows with every affinity-carrying pod)
    carr_anti_lists: List[List[int]] = []
    carr_w_lists: List[List[int]] = []
    carr_w_vals: List[List[float]] = []
    for gi in range(len(groups)):
        al: List[int] = []
        wl: List[int] = []
        wv: List[float] = []
        for t, cs in enumerate(enc.carrier_list):
            if not carr_sel_match_g[t, gi]:
                continue
            if cs.use == "anti":
                al.append(t)
            wgt = 1.0 if cs.use == "hard" else (cs.weight if cs.use == "pref" else 0.0)
            if wgt != 0.0:
                wl.append(t)
                wv.append(wgt)
        carr_anti_lists.append(al)
        carr_w_lists.append(wl)
        carr_w_vals.append(wv)
    Ca = max((len(a) for a in carr_anti_lists), default=0)
    Cw = max((len(a) for a in carr_w_lists), default=0)
    counter_sel_match_g = np.zeros((T, G), bool)
    for t, cs in enumerate(enc.counter_list):
        for gi, g in enumerate(groups):
            counter_sel_match_g[t, gi] = cs.matches_pod(g.template)
    grp_carries = np.zeros((G, Tc), np.float32)
    for gi, g in enumerate(groups):
        for cs in g.carried:
            grp_carries[gi, enc.carriers[cs]] = 1.0

    SL = max((len(g.lvm_sizes) for g in groups), default=0)
    SD = max((len(g.sdev_sizes) for g in groups), default=0)

    # ---- batch pod arrays -------------------------------------------------------
    P = len(batch)
    P_pad = max(pad_to or P, P, 1)
    pod_group = np.zeros(P_pad, np.int32)
    forced_node = np.full(P_pad, -1, np.int32)
    valid = np.zeros(P_pad, bool)
    from .store import EncodedRows

    if isinstance(batch, EncodedRows):
        # columnar fast path (simulator/store.py): the store's encode is
        # already two arrays — three vectorized copies, no per-pod loop
        pod_group[:P] = batch.pod_group
        forced_node[:P] = batch.forced_node
        valid[:P] = True
    else:
        for i, (gi, fn) in enumerate(batch):  # simonlint: ignore[per-pod-host-loop] -- legacy list-of-tuples form; EncodedRows takes the vectorized branch
            pod_group[i] = gi
            forced_node[i] = fn
            valid[i] = True

    return dict(
        grp_requests=(
            np.stack([g.requests for g in groups]) if groups else np.zeros((G, R), np.float32)
        ),
        grp_nonzero=(
            np.stack([g.nonzero for g in groups]) if groups else np.zeros((G, 2), np.float32)
        ),
        grp_unknown=np.array([g.unknown_resource for g in groups] or [False], bool),
        grp_ports=_pad_slots(grp_port_ids, PP, 0, np.int32),
        counter_sel_match_g=counter_sel_match_g,
        req_aff_t=_pad_slots([g.req_aff for g in groups] or [[]], A, -1, np.int32),
        grp_aff_self=np.array([g.aff_self for g in groups] or [False], bool),
        req_anti_t=_pad_slots([g.req_anti for g in groups] or [[]], B, -1, np.int32),
        pref_t=_pad_slots([[c for c, _ in g.pref] for g in groups] or [[]], Cp, -1, np.int32),
        pref_w=_pad_slots([[w for _, w in g.pref] for g in groups] or [[]], Cp, 0.0, np.float32),
        dns_t=_pad_slots([[c for c, _, _ in g.spread_dns] for g in groups] or [[]], Sd, -1, np.int32),
        dns_maxskew=_pad_slots([[m for _, m, _ in g.spread_dns] for g in groups] or [[]], Sd, 1.0, np.float32),
        dns_self=_pad_slots([[s for _, _, s in g.spread_dns] for g in groups] or [[]], Sd, 0.0, np.float32),
        sa_t=_pad_slots([[c for c, _, _ in g.spread_sa] for g in groups] or [[]], Ss, -1, np.int32),
        sa_maxskew=_pad_slots([[m for _, m, _ in g.spread_sa] for g in groups] or [[]], Ss, 1.0, np.float32),
        sa_self=_pad_slots([[s for _, _, s in g.spread_sa] for g in groups] or [[]], Ss, 0.0, np.float32),
        ss_t=np.array([g.ss_counter for g in groups] or [-1], np.int32),
        ss_skip=np.array([g.ss_skip for g in groups] or [False], bool),
        carr_sel_match_g=carr_sel_match_g,
        carr_anti_t=_pad_slots(carr_anti_lists or [[]], Ca, -1, np.int32),
        carr_w_t=_pad_slots(carr_w_lists or [[]], Cw, -1, np.int32),
        carr_w_w=_pad_slots(carr_w_vals or [[]], Cw, 0.0, np.float32),
        grp_carries=grp_carries,
        grp_gpu_mem=np.array([g.gpu_mem for g in groups] or [0.0], np.float32),
        grp_gpu_num=np.array([g.gpu_num for g in groups] or [0.0], np.float32),
        grp_lvm_size=_pad_slots([g.lvm_sizes for g in groups] or [[]], SL, 0.0, np.float32),
        grp_lvm_vg=_pad_slots([g.lvm_vg_ids for g in groups] or [[]], SL, 0, np.int32),
        grp_sdev_size=_pad_slots([g.sdev_sizes for g in groups] or [[]], SD, 0.0, np.float32),
        grp_sdev_media=_pad_slots([g.sdev_media for g in groups] or [[]], SD, 0, np.int32),
        pod_group=pod_group,
        forced_node=forced_node,
        valid=valid,
    )


def build_node_axis_tables(
    enc: Encoder,
    placed: Dict[object, PlacedGroup],
    match_cache: Dict[Tuple[int, str], bool],
) -> Dict[str, np.ndarray]:
    """The node-axis half of BatchTables: every [*, N] mask/raw/domain table,
    the per-node plugin matrices, and the carry seeds aggregated from
    `placed`. Reads len(enc.ports), so build_pod_axis_tables must have interned
    the batch's host ports first."""
    na = enc.na
    N, R = na.N, enc.axis.R
    G = max(1, len(enc.group_list))
    T = max(1, len(enc.counter_list))
    Tc = max(1, len(enc.carrier_list))
    groups = enc.group_list or []
    PORT = max(1, len(enc.ports))

    def stack(attr):
        if not groups:
            return np.zeros((G, N), np.float32)
        return np.stack([getattr(g, attr).astype(np.float32) for g in groups])

    static_mask = (
        np.stack([g.static_mask for g in groups]) if groups else np.zeros((G, N), bool)
    )
    # Intern every topology domain FIRST — D (and the sentinel index) depend on it.
    counter_dom_raw = [na.domain_of(cs.topo_key) for cs in enc.counter_list]
    carr_dom_raw = [na.domain_of(cs.topo_key) for cs in enc.carrier_list]
    D = max(1, na.D)  # StringTable length includes the reserved 0 slot; ids are < D

    counter_dom = np.full((T, N), D, np.int32)
    for t, dom in enumerate(counter_dom_raw):
        counter_dom[t] = np.where(dom >= 0, dom, D)
    carr_dom = np.full((Tc, N), D, np.int32)
    for t, dom in enumerate(carr_dom_raw):
        carr_dom[t] = np.where(dom >= 0, dom, D)

    # Topology group-id tensors: counters/carriers sharing a topology key
    # share their entire domain row, so the wave kernels segment-reduce
    # per-node counts once per UNIQUE topology ([U, N]) and broadcast to the
    # [T]/[Tc] rows — _aggregate_commit's per-row T×N scatter was the
    # dominant per-segment fixed cost at 5k nodes. Row U-1 is always the
    # all-sentinel topology, which pad rows and empty tables point at.
    topo_ids: Dict[str, int] = {}
    topo_rows: List[np.ndarray] = []

    def topo_of(key: str, dom_row: np.ndarray) -> int:
        got = topo_ids.get(key)
        if got is None:
            got = topo_ids[key] = len(topo_rows)
            topo_rows.append(np.where(dom_row >= 0, dom_row, D).astype(np.int32))
        return got

    counter_topo = np.zeros(T, np.int32)
    for t, cs in enumerate(enc.counter_list):
        counter_topo[t] = topo_of(cs.topo_key, counter_dom_raw[t])
    carr_topo = np.zeros(Tc, np.int32)
    for t, cs in enumerate(enc.carrier_list):
        carr_topo[t] = topo_of(cs.topo_key, carr_dom_raw[t])
    sentinel_row = len(topo_rows)
    topo_rows.append(np.full(N, D, np.int32))
    if not enc.counter_list:
        counter_topo[:] = sentinel_row
    if not enc.carrier_list:
        carr_topo[:] = sentinel_row
    topo_dom = np.stack(topo_rows)

    Sd = max((len(g.spread_dns) for g in groups), default=0)
    dns_edom = np.zeros((G, max(1, Sd), D + 1), bool)
    for gi, g in enumerate(groups):
        for si, (cid, _, _) in enumerate(g.spread_dns):
            dom = na.domain_of(enc.counter_list[cid].topo_key)
            elig = g.dns_elig if g.dns_elig is not None else np.ones(N, bool)
            dns_edom[gi, si, dom[elig & (dom >= 0)]] = True

    # ---- seeds from placed pods -----------------------------------------------
    # The resource/nonzero sums vectorize across ALL placed groups in two
    # np.add.at passes: bound pods carry per-pod signatures (spec.nodeName
    # joins the signature), so `placed` scales with the bound-pod count and
    # a per-group fancy-index add was the dominant encode cost at 10k+ nodes
    # (~9us x 5000 groups per rebuild — the serving image's churn-refresh
    # p99 spike). Entry order is placed-iteration order, and np.add.at
    # applies repeated-index adds in order of appearance, so the f32
    # accumulation sequence per node is bit-identical to the per-group loop
    # it replaces; count-scaled vectors match the wave kernel's aggregate
    # commit math.
    seed_requested = np.zeros((N, R), np.float32)
    seed_nonzero = np.zeros((N, 2), np.float32)
    seed_port_used = np.zeros((N, PORT + 1), bool)
    seed_counter = np.zeros((T, D + 1), np.float32)
    seed_carrier = np.zeros((Tc, D + 1), np.float32)
    if placed:
        g_idx: List[int] = []
        n_idx: List[int] = []
        c_val: List[float] = []
        for gi, pg in enumerate(placed.values()):
            for ni, c in pg.node_counts.items():
                g_idx.append(gi)
                n_idx.append(ni)
                c_val.append(c)
        if n_idx:
            groups_seq = list(placed.values())
            req_all = np.stack([pg.req_vec for pg in groups_seq])
            nz_all = np.stack([pg.nonzero for pg in groups_seq])
            gi_a = np.asarray(g_idx, np.int64)  # simonlint: ignore[dtype-drift] -- host-side fancy index, never shipped to device
            ni_a = np.asarray(n_idx, np.int64)  # simonlint: ignore[dtype-drift] -- host-side fancy index, never shipped to device
            c_a = np.asarray(c_val, np.float32)[:, None]
            np.add.at(seed_requested, ni_a, req_all[gi_a] * c_a)
            np.add.at(seed_nonzero, ni_a, nz_all[gi_a] * c_a)
    for pg in placed.values():
        if not (pg.port_ids or pg.carrier_ids or enc.counter_list):
            continue
        nis = np.fromiter(pg.node_counts.keys(), np.int64, len(pg.node_counts))  # simonlint: ignore[dtype-drift] -- host-side fancy index, never shipped to device
        cnts = np.fromiter(pg.node_counts.values(), np.float32, len(pg.node_counts))
        for pid in pg.port_ids:
            if pid <= PORT:
                seed_port_used[nis, pid] = True
        for t, cs in enumerate(enc.counter_list):
            key = (t, pg.sig)
            m = match_cache.get(key)
            if m is None:
                m = match_cache[key] = cs.matches_pod(pg.pod)
            if m:
                d = counter_dom[t, nis]
                ok = d < D
                np.add.at(seed_counter[t], d[ok], cnts[ok])
        for cid in pg.carrier_ids:
            d = carr_dom[cid, nis]
            ok = d < D
            np.add.at(seed_carrier[cid], d[ok], cnts[ok])

    # ---- gpu-share tables -------------------------------------------------------
    gpu_host = enc.gpu_host
    if gpu_host is not None and gpu_host.enabled:
        maxdev = _bucket(gpu_host.max_devs)
        dev_total = gpu_host.dev_total_matrix(maxdev)
        seed_dev_used = gpu_host.dev_used_matrix(maxdev)
    else:
        maxdev = 1
        dev_total = np.zeros((N, 1), np.float32)
        seed_dev_used = np.zeros((N, 1), np.float32)
    grp_gpu_pre = np.zeros(G, bool)
    grp_gpu_take = np.zeros((G, maxdev), np.float32)
    for gi, g in enumerate(groups):
        if g.gpu_pre_ids:
            grp_gpu_pre[gi] = True
            for d in g.gpu_pre_ids:
                if 0 <= d < maxdev:  # out-of-range ids are skipped (reference warns)
                    grp_gpu_take[gi, d] += 1.0

    # ---- open-local tables ------------------------------------------------------
    local_host = enc.local_host
    if local_host is not None and local_host.enabled:
        maxvg = _bucket(max(local_host.max_vgs, 1))
        maxsd = _bucket(max(local_host.max_devs, 1))
        vg_cap, vg_nameid, seed_vg_req = local_host.vg_matrices(maxvg)
        sdev_cap, sdev_media, seed_sdev_alloc = local_host.device_matrices(maxsd)
        seed_sdev_alloc = seed_sdev_alloc.astype(np.float32)
    else:
        vg_cap = seed_vg_req = np.zeros((N, 1), np.float32)
        vg_nameid = np.zeros((N, 1), np.int32)
        sdev_cap = seed_sdev_alloc = np.zeros((N, 1), np.float32)
        sdev_media = np.zeros((N, 1), np.int32)

    return dict(
        alloc=na.alloc.astype(np.float32),
        node_zone=na.zone_id.astype(np.int32),
        n_zones=len(na.zones) + 1,
        static_mask=static_mask,
        mask_taint=(np.stack([g.mask_taint for g in groups]) if groups else np.zeros((G, N), bool)),
        mask_unsched=(np.stack([g.mask_unsched for g in groups]) if groups else np.zeros((G, N), bool)),
        mask_aff=(np.stack([g.mask_aff for g in groups]) if groups else np.zeros((G, N), bool)),
        mask_extra=(np.stack([g.mask_extra for g in groups]) if groups else np.zeros((G, N), bool)),
        simon_raw=stack("simon_raw"),
        nodeaff_raw=stack("nodeaff_raw"),
        taint_raw=stack("taint_raw"),
        avoid_raw=stack("avoid_raw"),
        image_raw=stack("image_raw"),
        extra_raw=stack("extra_raw"),
        counter_dom=counter_dom,
        counter_topo=counter_topo,
        topo_dom=topo_dom,
        carr_dom=carr_dom,
        carr_topo=carr_topo,
        dns_edom=dns_edom,
        grp_gpu_pre=grp_gpu_pre,
        grp_gpu_take=grp_gpu_take,
        dev_total=dev_total,
        vg_cap=vg_cap,
        vg_nameid=vg_nameid,
        sdev_cap=sdev_cap,
        sdev_media=sdev_media,
        seed_vg_req=seed_vg_req,
        seed_sdev_alloc=seed_sdev_alloc,
        seed_dev_used=seed_dev_used,
        seed_requested=seed_requested,
        seed_nonzero=seed_nonzero,
        seed_port_used=seed_port_used,
        seed_counter=seed_counter,
        seed_carrier=seed_carrier,
    )


def build_batch_tables(
    enc: Encoder,
    batch: List[Tuple[int, int]],          # (group_id, forced_node) per pod, in order
    placed: Dict[object, PlacedGroup],
    match_cache: Dict[Tuple[int, str], bool],
    pad_to: Optional[int] = None,
) -> BatchTables:
    """Assemble numpy tables for one batch. `match_cache` memoizes counter-selector vs
    placed-pod-signature matches across batches (engine-owned).

    Construction is split along the node axis: build_pod_axis_tables is a
    function of the encoder + pod order only (computed once per capacity
    search by the incremental prober), build_node_axis_tables carries every
    [*, N] table and the seeds. The pod-axis half runs first — it interns the
    batch's host ports, which sizes the node-side seed port table."""
    from ..obs import pulse

    pod_side = build_pod_axis_tables(enc, batch, pad_to=pad_to)
    if pulse.active() is not None:
        # the ROADMAP-5 instrument: streaming chunks re-enter here once per
        # chunk, so per-chunk node-axis table-build cost shows up directly
        # as the table_build slice of the encode phase
        import time

        t0 = time.perf_counter()
        node_side = build_node_axis_tables(enc, placed, match_cache)
        pulse.phase("table_build", time.perf_counter() - t0)
    else:
        node_side = build_node_axis_tables(enc, placed, match_cache)
    return BatchTables(**pod_side, **node_side)


def extend_node_axis(
    bt: "BatchTables",
    k: int,
    template_col: int,
    hostname_counters: Sequence[int] = (),
    hostname_carriers: Sequence[int] = (),
) -> "BatchTables":
    """Append k copies of node column `template_col` to every node-axis table of
    an UNPADDED BatchTables (the pre-pad_encoder_axes form) — the incremental
    capacity prober's growth path: extending the candidate-node axis without
    rebuilding NodeArrays/Encoder from raw node dicts.

    Template copies are indistinguishable to every selector except through
    their hostname label (new_fake_nodes rewrites only kubernetes.io/hostname),
    so every appended column is a verbatim copy of the template column, EXCEPT
    the rows listed in hostname_counters/hostname_carriers: those topologies
    have one domain per node, so each appended node gets a fresh domain id.
    With hostname rows present the domain axis therefore grows by k: the
    seed/edom sentinel column moves from D to D+k and the new interior columns
    start at zero (no placed pod can be on an appended node). WITHOUT hostname
    rows the domain axis is untouched — the appended columns reuse the
    template's domain ids verbatim, so repeated extensions never widen the
    counter tables (and the device-resident growth path in probe.py can
    extend the node axis shard-locally with no sentinel remap). Seeds for the
    appended nodes are zero either way — the caller must only append nodes
    that carry no bound pods."""
    if k <= 0:
        return bt
    import dataclasses

    N = bt.alloc.shape[0]
    D = bt.seed_counter.shape[1] - 1
    per_node = bool(hostname_counters) or bool(hostname_carriers)
    newD = D + k if per_node else D

    def rep_col(a: np.ndarray) -> np.ndarray:  # [*, N, ...] along axis 1
        return np.concatenate(
            [a, np.repeat(a[:, template_col:template_col + 1], k, axis=1)], axis=1)

    def rep_row(a: np.ndarray) -> np.ndarray:  # [N, ...] along axis 0
        return np.concatenate(
            [a, np.repeat(a[template_col:template_col + 1], k, axis=0)], axis=0)

    def zero_rows(a: np.ndarray) -> np.ndarray:  # [N, ...]: appended seeds are empty
        return np.concatenate(
            [a, np.zeros((k,) + a.shape[1:], a.dtype)], axis=0)

    def widen(a: np.ndarray) -> np.ndarray:  # [*, D+1] -> [*, newD+1]
        if not per_node:
            return a  # domain axis unchanged: no widening, no sentinel move
        out = np.zeros(a.shape[:-1] + (newD + 1,), a.dtype)
        out[..., :D] = a[..., :D]
        out[..., newD] = a[..., D]  # sentinel column moves with D
        return out

    new_dom_ids = (D + np.arange(k)).astype(np.int32)

    def dom_ext(dom: np.ndarray, per_node_rows: Sequence[int]) -> np.ndarray:
        ext = rep_col(dom)
        if not per_node:
            return ext  # template domain ids replicate verbatim
        ext = np.where(ext == D, newD, ext).astype(np.int32)  # sentinel remap
        for t in per_node_rows:
            ext[t, N:] = new_dom_ids  # fresh hostname domain per appended node
        return ext

    return dataclasses.replace(
        bt,
        alloc=rep_row(bt.alloc),
        node_zone=np.concatenate(
            [bt.node_zone, np.repeat(bt.node_zone[template_col:template_col + 1], k)]),
        static_mask=rep_col(bt.static_mask),
        mask_taint=rep_col(bt.mask_taint),
        mask_unsched=rep_col(bt.mask_unsched),
        mask_aff=rep_col(bt.mask_aff),
        mask_extra=rep_col(bt.mask_extra),
        simon_raw=rep_col(bt.simon_raw),
        nodeaff_raw=rep_col(bt.nodeaff_raw),
        taint_raw=rep_col(bt.taint_raw),
        avoid_raw=rep_col(bt.avoid_raw),
        image_raw=rep_col(bt.image_raw),
        extra_raw=rep_col(bt.extra_raw),
        counter_dom=dom_ext(bt.counter_dom, hostname_counters),
        # hostname TOPOLOGY rows get the same fresh per-node domains as the
        # hostname counter/carrier rows that reference them
        topo_dom=dom_ext(bt.topo_dom, sorted({
            int(bt.counter_topo[t]) for t in hostname_counters
        } | {int(bt.carr_topo[t]) for t in hostname_carriers})),
        carr_dom=dom_ext(bt.carr_dom, hostname_carriers),
        dns_edom=widen(bt.dns_edom),
        dev_total=rep_row(bt.dev_total),
        vg_cap=rep_row(bt.vg_cap),
        vg_nameid=rep_row(bt.vg_nameid),
        sdev_cap=rep_row(bt.sdev_cap),
        sdev_media=rep_row(bt.sdev_media),
        seed_requested=zero_rows(bt.seed_requested),
        seed_nonzero=zero_rows(bt.seed_nonzero),
        seed_port_used=zero_rows(bt.seed_port_used),
        seed_dev_used=zero_rows(bt.seed_dev_used),
        seed_vg_req=zero_rows(bt.seed_vg_req),
        seed_sdev_alloc=zero_rows(bt.seed_sdev_alloc),
        seed_counter=widen(bt.seed_counter),
        seed_carrier=widen(bt.seed_carrier),
    )
