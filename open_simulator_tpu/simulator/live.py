"""Live-cluster ingestion: snapshot a real cluster's objects over the Kubernetes API.

Mirrors CreateClusterResourceFromClient (/root/reference/pkg/simulator/simulator.go:
503-601): list nodes; pods (skip DaemonSet-owned and deleting; Running first, then
Pending); PDBs; services; storage classes; PVCs; config maps; daemon sets.

Implemented against the REST API with the standard library (no kubernetes client
dependency): kubeconfig parsing supports bearer tokens, client certificates (inline
data or files), CA bundles, and insecure-skip-tls-verify.

Failure semantics (README "Failure handling", PARITY.md for the client-go
mapping): every GET classifies into the typed hierarchy below and runs under
a RetryPolicy + CircuitBreaker — transient failures (429/5xx/network) retry
with seeded-jitter backoff honoring Retry-After; auth failures (401/403)
never retry; LIST pagination restarts from scratch on 410 Gone exactly like
a client-go reflector relist on an expired continue token.
"""

from __future__ import annotations

import base64
import http.client
import json
import os
import ssl
import tempfile
import urllib.error
import urllib.request
from typing import List, Optional, Tuple

import yaml

from ..core.types import ResourceTypes
from ..resilience import faults
from ..resilience.policy import CircuitBreaker, RetryPolicy
from ..utils.objutil import is_owned_by_kind


class LiveClusterError(RuntimeError):
    """Base class for live-cluster failures (kept as the catch-all name for
    compatibility; new code should catch the typed subclasses)."""


class AuthError(LiveClusterError):
    """401/403 or a failed credential plugin — retrying cannot help."""


class TransientError(LiveClusterError):
    """429/5xx/network/timeouts — retry with backoff. `retry_after` carries
    the server's Retry-After hint (seconds, 0 when absent)."""

    def __init__(self, msg: str, retry_after: float = 0.0,
                 code: Optional[int] = None) -> None:
        super().__init__(msg)
        self.retry_after = float(retry_after)
        self.code = code


class ProtocolError(LiveClusterError):
    """The apiserver answered but not usably (unexpected 4xx, bad JSON).
    410 Gone carries `code=410` — the LIST path restarts pagination on it."""

    def __init__(self, msg: str, code: Optional[int] = None) -> None:
        super().__init__(msg)
        self.code = code


def _b64_to_tempfile(data: str, suffix: str) -> str:
    raw = base64.b64decode(data)
    f = tempfile.NamedTemporaryFile(suffix=suffix, delete=False)
    f.write(raw)
    f.close()
    return f.name


def _text_to_tempfile(text: str, suffix: str) -> str:
    f = tempfile.NamedTemporaryFile("w", suffix=suffix, delete=False)
    f.write(text)
    f.close()
    return f.name


def _retry_after(headers) -> float:
    """Parse a Retry-After header as delay-seconds (the apiserver's 429s use
    the seconds form; an unparsable/absent value means no hint)."""
    try:
        return max(0.0, float(headers.get("Retry-After", "")))
    except (TypeError, ValueError):
        return 0.0


def _run_exec_credential(exec_cfg: dict):
    """Run a client-go exec credential plugin (kubeconfig user.exec) and parse
    its ExecCredential output. Returns (token, (cert_file, key_file) | None)."""
    import subprocess

    cmd = [exec_cfg.get("command") or ""]
    cmd += list(exec_cfg.get("args") or [])
    env = dict(os.environ)
    for e in exec_cfg.get("env") or []:
        if e.get("name"):
            env[e["name"]] = e.get("value", "")
    env.setdefault(
        "KUBERNETES_EXEC_INFO",
        json.dumps({"apiVersion": exec_cfg.get("apiVersion", ""),
                    "kind": "ExecCredential", "spec": {"interactive": False}}),
    )
    try:
        out = subprocess.run(cmd, env=env, capture_output=True, text=True,
                             timeout=60, check=True)
        cred = json.loads(out.stdout)
    except Exception as e:
        raise AuthError(
            f"exec credential plugin {cmd[0]!r} failed: {e}") from e
    status = cred.get("status") or {}
    token = status.get("token")
    cert_pair = None
    if status.get("clientCertificateData") and status.get("clientKeyData"):
        cert_pair = (
            _text_to_tempfile(status["clientCertificateData"], ".crt"),
            _text_to_tempfile(status["clientKeyData"], ".key"),
        )
    return token, cert_pair


class KubeClient:
    """Minimal typed GET client for one kubeconfig context."""

    def __init__(self, kubeconfig: str, master: str = "") -> None:
        with open(kubeconfig) as f:
            cfg = yaml.safe_load(f) or {}
        ctx_name = cfg.get("current-context") or ""
        contexts = {c.get("name"): c.get("context") or {} for c in cfg.get("contexts") or []}
        ctx = contexts.get(ctx_name) or (next(iter(contexts.values())) if contexts else {})
        clusters = {c.get("name"): c.get("cluster") or {} for c in cfg.get("clusters") or []}
        users = {u.get("name"): u.get("user") or {} for u in cfg.get("users") or []}
        cluster = clusters.get(ctx.get("cluster")) or (next(iter(clusters.values())) if clusters else {})
        user = users.get(ctx.get("user")) or (next(iter(users.values())) if users else {})

        self.server = (master or cluster.get("server") or "").rstrip("/")
        if not self.server:
            raise LiveClusterError(f"no cluster server found in kubeconfig {kubeconfig}")

        self.token: Optional[str] = user.get("token")
        token_file = user.get("tokenFile")
        if not self.token and token_file and os.path.exists(token_file):
            self.token = open(token_file).read().strip()
        exec_cfg = user.get("exec")
        self._exec_cert: Optional[Tuple[str, str]] = None
        if not self.token and exec_cfg:
            # client-go exec credential plugins (the auth mode managed clouds
            # use); the plugin prints an ExecCredential whose status carries a
            # bearer token and/or a client cert pair
            self.token, self._exec_cert = _run_exec_credential(exec_cfg)

        self.ssl_ctx = ssl.create_default_context()
        if cluster.get("insecure-skip-tls-verify"):
            self.ssl_ctx.check_hostname = False
            self.ssl_ctx.verify_mode = ssl.CERT_NONE
        ca_file = cluster.get("certificate-authority")
        if cluster.get("certificate-authority-data"):
            ca_file = _b64_to_tempfile(cluster["certificate-authority-data"], ".crt")
        if ca_file:
            self.ssl_ctx.load_verify_locations(cafile=ca_file)

        cert_file = user.get("client-certificate")
        key_file = user.get("client-key")
        if user.get("client-certificate-data"):
            cert_file = _b64_to_tempfile(user["client-certificate-data"], ".crt")
        if user.get("client-key-data"):
            key_file = _b64_to_tempfile(user["client-key-data"], ".key")
        if not (cert_file and key_file) and self._exec_cert:
            cert_file, key_file = self._exec_cert
        if cert_file and key_file:
            self.ssl_ctx.load_cert_chain(certfile=cert_file, keyfile=key_file)
        self._init_policies()

    # Failure-policy knobs, overridable per client (tests pin tiny sleeps).
    # The breaker is per-client: the server's handler threads share one
    # KubeClient, so five consecutive apiserver failures fail the NEXT
    # request fast instead of stacking 30s timeout pile-ups.
    RETRY = RetryPolicy(max_attempts=4, base=0.25, mult=2.0, cap=5.0,
                        jitter=0.2, max_elapsed=30.0, seed=0)
    BREAKER_THRESHOLD = 5
    BREAKER_RESET_AFTER = 15.0
    # Bounded 410-Gone relists per LIST call (client-go reflectors relist
    # forever; a snapshotting client must eventually fail loudly instead).
    MAX_RELISTS = 2

    def _init_policies(self) -> None:
        self.retry = self.RETRY
        self.breaker = CircuitBreaker(
            "live_cluster", failure_threshold=self.BREAKER_THRESHOLD,
            reset_after=self.BREAKER_RESET_AFTER)

    def _get_once(self, path: str, timeout: float) -> dict:
        from ..resilience.policy import deadline_remaining

        faults.maybe_fail("live_get")
        req = urllib.request.Request(self.server + path)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        req.add_header("Accept", "application/json")
        # an active Deadline slices the socket timeout: a callee never blocks
        # past the caller's remaining budget
        rem = deadline_remaining()
        if rem is not None:
            timeout = min(timeout, max(rem, 0.001))
        try:
            with urllib.request.urlopen(req, timeout=timeout, context=self.ssl_ctx) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            msg = f"GET {path} failed: HTTP {e.code} {e.reason}"
            if e.code in (401, 403):
                raise AuthError(msg) from e
            if e.code == 429 or e.code >= 500:
                raise TransientError(
                    msg, retry_after=_retry_after(e.headers), code=e.code) from e
            raise ProtocolError(msg, code=e.code) from e
        except (OSError, http.client.HTTPException) as e:
            # URLError/timeouts/resets subclass OSError; a connection dropped
            # mid-body surfaces as IncompleteRead/BadStatusLine
            # (HTTPException, NOT OSError) — both classes are transient
            raise TransientError(f"GET {path} failed: {e}") from e
        except ValueError as e:  # undecodable body: answered, but not usably
            raise ProtocolError(f"GET {path} returned bad JSON: {e}") from e

    def get(self, path: str, timeout: float = 30.0) -> dict:
        """One logical GET: retried on TransientError (Retry-After honored,
        401/403 never retried), deadline-budgeted, breaker-gated."""
        return self.retry.call(
            lambda: self._get_once(path, timeout), site="live_get",
            retryable=lambda e: isinstance(e, TransientError),
            breaker=self.breaker)

    # Chunk size per LIST request: apiserver-friendly paging so 3,000+-node
    # clusters (the reference's claimed scale, changelogs/v0.1.3.md) never
    # materialize one giant response.
    PAGE_LIMIT = 500

    def list(self, path: str, **params) -> List[dict]:
        """Paginated LIST. A 410 Gone mid-pagination (continue token expired
        under churn) throws away the partial result and restarts from scratch
        — the observable behavior of a client-go reflector relist — at most
        MAX_RELISTS times before failing loudly."""
        from ..obs import instruments as obs

        restarts = 0
        while True:
            try:
                return self._list_pages(path, **params)
            except ProtocolError as e:
                if e.code != 410 or restarts >= self.MAX_RELISTS:
                    raise
                restarts += 1
                obs.RETRIES.labels(site="live_list_relist").inc()

    def _list_pages(self, path: str, **params) -> List[dict]:
        from urllib.parse import urlencode

        items: List[dict] = []
        cont: Optional[str] = None
        while True:
            q = dict(params)
            q.setdefault("limit", self.PAGE_LIMIT)
            if cont:
                q["continue"] = cont
            body = self.get(f"{path}?{urlencode(q)}")
            kind = (body.get("kind") or "").removesuffix("List")
            api_version = body.get("apiVersion", "v1")
            page = body.get("items") or []
            for it in page:  # items in a List response omit their own TypeMeta
                it.setdefault("kind", kind)
                it.setdefault("apiVersion", api_version)
            items.extend(page)
            cont = (body.get("metadata") or {}).get("continue")
            if not cont:
                return items


def create_kube_client(kubeconfig: str, master: str = "") -> KubeClient:
    return KubeClient(kubeconfig, master)


def _split_pods(pods: List[dict]) -> Tuple[List[dict], List[dict]]:
    running, pending = [], []
    for p in pods:
        if is_owned_by_kind(p, "DaemonSet") or (p.get("metadata") or {}).get("deletionTimestamp"):
            continue
        phase = (p.get("status") or {}).get("phase")
        if phase == "Running":
            running.append(p)
        elif phase == "Pending":
            pending.append(p)
    return running, pending


def _create_cluster_resource_from_client(client_or_path, master: str = "") -> ResourceTypes:
    """Snapshot the cluster objects the simulation needs. Accepts a KubeClient or a
    kubeconfig path."""
    client = (
        client_or_path
        if isinstance(client_or_path, KubeClient)
        else create_kube_client(client_or_path, master)
    )
    rt = ResourceTypes()
    rt.nodes = client.list("/api/v1/nodes")
    # no resourceVersion=0 here: the apiserver ignores `limit` for cache reads,
    # which would defeat pagination on big clusters
    running, pending = _split_pods(client.list("/api/v1/pods"))
    rt.pods = running + pending  # Running first, then Pending, like the reference
    # policy/v1beta1 (what the reference's v1.20 client uses) was removed in k8s
    # 1.25; prefer policy/v1 and fall back for old clusters.
    try:
        rt.pod_disruption_budgets = client.list("/apis/policy/v1/poddisruptionbudgets")
    except LiveClusterError:
        rt.pod_disruption_budgets = client.list("/apis/policy/v1beta1/poddisruptionbudgets")
    rt.services = client.list("/api/v1/services")
    rt.storage_classes = client.list("/apis/storage.k8s.io/v1/storageclasses")
    rt.persistent_volume_claims = client.list("/api/v1/persistentvolumeclaims")
    rt.config_maps = client.list("/api/v1/configmaps")
    rt.daemon_sets = client.list("/apis/apps/v1/daemonsets")
    return rt


__all__ = [
    "AuthError",
    "KubeClient",
    "LiveClusterError",
    "ProtocolError",
    "TransientError",
    "create_kube_client",
    "create_cluster_resource_from_client",
]


def create_cluster_resource_from_client(client_or_path, master: str = "") -> ResourceTypes:
    """Traced wrapper: the reference shows a spinner and logs slow cluster
    fetches at 100ms (simulator.go:506-512)."""
    from ..utils.trace import Span

    with Span("fetch cluster from kube-apiserver", log_if_longer=0.1):
        return _create_cluster_resource_from_client(client_or_path, master)
