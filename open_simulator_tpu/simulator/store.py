"""Columnar host stores: struct-of-arrays pod batches and node sets.

The host side of the engine was a dict-of-dicts world: every pod a Python
dict, every encode a per-pod traversal, every commit a handful of dict
mutations. That is fine at 10k pods and ruinous at 10M (ROADMAP item 2 —
~60% of the 1M-pod row's wall was Python-side encode + commit bookkeeping).
This module keeps the expensive representation staged once and lets the
engine view it zero-copy, the same move serve/image.py made on the device
side (Orca, PAPERS.md):

- **PodStore** — a pod batch as template blocks: each block is one validated
  pod template plus a replica count and a name recipe. Columns (`tmpl_of`,
  `node_of`, `commit_seq`) are numpy arrays over the whole batch; the
  scheduling-relevant content lives once per TEMPLATE, so `encode_batch_ids`
  is one group interning per template plus one vectorized gather
  (`EncodedRows`), and the engine's bulk commit writes placements as array
  ops. Per-pod dicts are materialized lazily — only for the few pods a
  caller actually reads back (failure records, preemption victims,
  `pods_on_node` listings) — and a materialized dict is cached so its
  identity is stable. A PodStore is Sequence-compatible: code that iterates
  or indexes it transparently gets pod dicts, bit-identical to the dicts the
  legacy path would have carried (the double-encode parity suite in
  tests/test_store.py holds the two encodes to byte equality).

- **NodeStore** — the node set as blocks sharing one template (allocatable,
  taint pattern, constant labels) plus indexed label recipes (hostname,
  zone cycling). `NodeArrays` adopts its columns directly instead of parsing
  N node dicts; `LazyNodeSeq` stands in for the node list and materializes
  dicts on indexed access only.

- **PodsOnNode / NodePodList** — the per-node placement registry. Committed
  store rows are recorded as SPANS (store + row ids) instead of appended
  dicts; reading a node's pod list flattens its spans through lazy
  materialization. `snapshot()`/`restore()` copy only non-empty nodes, so
  the engine's per-call transaction stays O(touched), not O(N).

Semantic boundary (PARITY.md "Columnar host path"): materialization is the
one place columnar state becomes dict state. A materialized pod reflects the
store's CURRENT columns (committed → spec.nodeName + Running status), and a
bulk-commit rollback patches any already-materialized dict back, so callers
can never observe a dict/column split-brain.
"""

from __future__ import annotations

import pickle
from typing import Dict, Iterator, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..core import constants as C
from .encode import SIG_MEMO_KEY

__all__ = [
    "EncodedRows", "PodStore", "NodeStore", "LazyNodeSeq",
    "NodePodList", "PodsOnNode", "is_pod_store",
]


class EncodedRows(Sequence):
    """The pod-axis encode of a store view: (group_id, forced_node) as
    columns. Sequence-compatible with the legacy List[(g, f)] — len,
    iteration, and indexing all yield row tuples, so lane assemblers
    (serve/sweep) consume it unchanged; the engine and
    build_pod_axis_tables use the arrays directly."""

    __slots__ = ("pod_group", "forced_node")

    def __init__(self, pod_group: np.ndarray,
                 forced_node: np.ndarray) -> None:
        self.pod_group = pod_group    # [P] i32
        self.forced_node = forced_node  # [P] i32

    def __len__(self) -> int:
        return int(self.pod_group.shape[0])

    def __iter__(self) -> Iterator[Tuple[int, int]]:
        return iter(zip(self.pod_group.tolist(), self.forced_node.tolist()))

    def __getitem__(self, i):
        if isinstance(i, slice):
            return EncodedRows(self.pod_group[i], self.forced_node[i])
        return (int(self.pod_group[i]), int(self.forced_node[i]))


def is_pod_store(obj) -> bool:
    return isinstance(obj, PodStore)


class _PodBase:
    """Shared state behind every view of one pod batch."""

    __slots__ = (
        "templates", "blobs", "sigs", "tmpl_priority", "tmpl_bound",
        "blk_tmpl", "blk_fmt", "blk_names", "blk_start", "blk_name_base",
        "tmpl_of", "node_of", "commit_seq", "cache", "row_by_id",
        "node_names", "frozen",
    )

    def __init__(self) -> None:
        self.templates: List[dict] = []
        self.blobs: List[Optional[bytes]] = []
        self.sigs: List[object] = []
        self.tmpl_priority: List[int] = []
        self.tmpl_bound: List[bool] = []
        self.blk_tmpl: List[int] = []
        self.blk_fmt: List[Optional[str]] = []
        self.blk_names: List[Optional[List[str]]] = []
        self.blk_start = np.zeros(1, np.int64)  # simonlint: ignore[dtype-drift] -- host-side row offsets, never shipped to device
        self.blk_name_base: List[Optional[int]] = []
        self.tmpl_of = np.zeros(0, np.int32)
        self.node_of = np.zeros(0, np.int32)
        self.commit_seq: Optional[np.ndarray] = None  # lazy [P] i64
        self.cache: Dict[int, dict] = {}
        self.row_by_id: Dict[int, int] = {}
        self.node_names: Optional[Sequence[str]] = None
        self.frozen = False


class PodStore(Sequence):
    """A columnar pod batch (or a contiguous view of one).

    Build with add_block(); schedule by passing the store straight to
    Simulator.schedule_pods / probe_pods. Slicing returns a view sharing the
    commit columns (the engine's OOM bisection and streaming chunks slice
    freely); copy.deepcopy returns an independent store with its own commit
    state and materialization cache (the sweep oracle's isolation contract).
    """

    def __init__(self, _base: Optional[_PodBase] = None,
                 _lo: int = 0, _hi: Optional[int] = None) -> None:
        self._b = _base if _base is not None else _PodBase()
        self._lo = _lo
        self._hi = _hi if _hi is not None else int(self._b.blk_start[-1])

    # ------------------------------------------------------------ building --

    def add_block(self, template: dict, count: int,
                  name_fmt: Optional[str] = None,
                  names: Optional[List[str]] = None,
                  name_start: Optional[int] = None) -> "PodStore":
        """Append `count` replicas of one validated pod template. Names come
        from `names` (explicit, len == count), `name_fmt` (formatted with the
        global row index, or with `name_start` + the block-local index when
        name_start is given), or the template's own metadata.name. The
        template is held by reference and must not be mutated afterwards."""
        if self._lo != 0 or self._hi != len(self._b.tmpl_of):
            raise ValueError("add_block on a view; build on the root store")
        b = self._b
        if b.frozen:
            raise ValueError("add_block after scheduling started")
        if count <= 0:
            return self
        if names is not None and len(names) != count:
            raise ValueError("names length != count")
        ti = len(b.templates)
        b.templates.append(template)
        b.blobs.append(None)  # pickled lazily on first materialization
        from .encode import scheduling_signature

        b.sigs.append(scheduling_signature(template))
        spec = template.get("spec") or {}
        try:
            b.tmpl_priority.append(int(spec.get("priority") or 0))
        except (TypeError, ValueError):
            b.tmpl_priority.append(0)
        b.tmpl_bound.append(bool(spec.get("nodeName")))
        start = int(b.blk_start[-1])
        b.blk_tmpl.append(ti)
        b.blk_fmt.append(name_fmt)
        b.blk_names.append(list(names) if names is not None else None)
        b.blk_name_base.append(name_start)  # None = global row numbering
        b.blk_start = np.append(b.blk_start, start + count)
        b.tmpl_of = np.concatenate(
            [b.tmpl_of, np.full(count, ti, np.int32)])
        b.node_of = np.concatenate(
            [b.node_of, np.full(count, -1, np.int32)])
        self._hi = start + count
        return self

    def add_pod(self, pod: dict) -> "PodStore":
        """Append one explicit pod dict (a one-row block whose template IS
        the dict): exceptional pods — pre-bound, hand-built — ride the store
        without losing their identity; they materialize to the same object."""
        self.add_block(pod, 1)
        row = int(self._b.blk_start[-1]) - 1
        self._b.cache[row] = pod
        self._b.row_by_id[id(pod)] = row
        return self

    # ----------------------------------------------------------- sequence --

    def __len__(self) -> int:
        return self._hi - self._lo

    def __getitem__(self, i):
        if isinstance(i, slice):
            start, stop, step = i.indices(len(self))
            if step != 1:
                raise ValueError("PodStore views must be contiguous")
            return PodStore(self._b, self._lo + start, self._lo + stop)
        if i < 0:
            i += len(self)
        if not 0 <= i < len(self):
            raise IndexError(i)
        return self.materialize(self._lo + i)

    def __iter__(self) -> Iterator[dict]:
        for i in range(self._lo, self._hi):
            yield self.materialize(i)

    def __deepcopy__(self, memo) -> "PodStore":
        nb = _PodBase()
        b = self._b
        nb.templates = list(b.templates)
        nb.blobs = list(b.blobs)
        nb.sigs = list(b.sigs)
        nb.tmpl_priority = list(b.tmpl_priority)
        nb.tmpl_bound = list(b.tmpl_bound)
        nb.blk_tmpl = list(b.blk_tmpl)
        nb.blk_fmt = list(b.blk_fmt)
        nb.blk_names = [list(n) if n is not None else None
                        for n in b.blk_names]
        nb.blk_start = b.blk_start.copy()
        nb.blk_name_base = list(b.blk_name_base)
        nb.tmpl_of = b.tmpl_of.copy()
        nb.node_of = b.node_of.copy()
        nb.commit_seq = (b.commit_seq.copy()
                         if b.commit_seq is not None else None)
        nb.node_names = b.node_names
        return PodStore(nb)

    # ------------------------------------------------------------- columns --

    @property
    def base(self) -> _PodBase:
        return self._b

    @property
    def lo(self) -> int:
        return self._lo

    @property
    def hi(self) -> int:
        return self._hi

    def tmpl_rows(self) -> np.ndarray:
        """[P] i32 template index per row of this view (zero-copy slice)."""
        return self._b.tmpl_of[self._lo:self._hi]

    def node_rows(self) -> np.ndarray:
        """[P] i32 committed node per row of this view (-1 = uncommitted)."""
        return self._b.node_of[self._lo:self._hi]

    def priorities_present(self) -> List[int]:
        """Distinct spec.priority values across this view's templates."""
        tis = np.unique(self.tmpl_rows())
        return sorted({self._b.tmpl_priority[int(t)] for t in tis})

    def bound_mask(self) -> Optional[np.ndarray]:
        """[P] bool of rows whose template is pre-bound (spec.nodeName set),
        or None when no template in the view is bound (the common case)."""
        b = self._b
        if not any(b.tmpl_bound[int(t)] for t in np.unique(self.tmpl_rows())):
            return None
        bound_t = np.array(b.tmpl_bound, bool)
        return bound_t[self.tmpl_rows()]

    def sig_of_row(self, abs_row: int):
        return self._b.sigs[int(self._b.tmpl_of[abs_row])]

    def template_of_row(self, abs_row: int) -> dict:
        return self._b.templates[int(self._b.tmpl_of[abs_row])]

    def ensure_commit_seq(self) -> np.ndarray:
        b = self._b
        if b.commit_seq is None:
            b.commit_seq = np.full(len(b.tmpl_of), -1, np.int64)  # simonlint: ignore[dtype-drift] -- host-side commit-order column
        return b.commit_seq

    def row_of_dict(self, pod: dict) -> Optional[int]:
        """Absolute row of a materialized pod dict, or None (identity map,
        populated at materialization)."""
        return self._b.row_by_id.get(id(pod))

    # ------------------------------------------------------ materialization --

    def name_of(self, abs_row: int) -> str:
        b = self._b
        blk = int(np.searchsorted(b.blk_start, abs_row, side="right")) - 1
        names = b.blk_names[blk]
        if names is not None:
            return names[abs_row - int(b.blk_start[blk])]
        fmt = b.blk_fmt[blk]
        if fmt is not None:
            base = b.blk_name_base[blk]
            if base is None:
                return fmt.format(abs_row)
            return fmt.format(base + abs_row - int(b.blk_start[blk]))
        return ((b.templates[b.blk_tmpl[blk]].get("metadata") or {})
                .get("name") or f"pod-{abs_row}")

    def materialize(self, abs_row: int) -> dict:
        """The lazy dict for one row: template copy + generated name, plus
        the committed nodeName/status when the row is placed. Cached — the
        dict's identity is stable and mutations stick (it IS the pod from
        then on)."""
        b = self._b
        pod = b.cache.get(abs_row)
        if pod is not None:
            return pod
        ti = int(b.tmpl_of[abs_row])
        blob = b.blobs[ti]
        if blob is None:
            blob = b.blobs[ti] = pickle.dumps(b.templates[ti], -1)
        pod = pickle.loads(blob)
        pod.pop(SIG_MEMO_KEY, None)  # defensive: never leak the marker
        pod.setdefault("metadata", {})["name"] = self.name_of(abs_row)
        ni = int(b.node_of[abs_row])
        if ni >= 0 and b.node_names is not None:
            pod.setdefault("spec", {})["nodeName"] = b.node_names[ni]
            pod["status"] = {"phase": "Running"}
        b.cache[abs_row] = pod
        b.row_by_id[id(pod)] = abs_row
        return pod

    def cached_rows_in(self, rows: np.ndarray) -> List[Tuple[int, dict]]:
        """(abs_row, dict) for the subset of `rows` already materialized —
        the bulk commit/rollback patch set (cache-sized, never O(rows))."""
        cache = self._b.cache
        if not cache:
            return []
        rs = set(rows.tolist())
        return [(r, d) for r, d in cache.items() if r in rs]


# ---------------------------------------------------------------- node store --


class _NodeBlock(NamedTuple):
    template: dict           # spec/status skeleton (no metadata.name/labels)
    count: int
    name_fmt: str
    labels: Tuple[Tuple[str, str], ...]   # constant labels
    zone_cycle: Optional[Tuple[str, str, int]]  # (label key, fmt, modulus)
    index_labels: Tuple[str, ...]         # label keys valued str(global index)
    taint: Optional[Tuple[tuple, int]]    # ((key, value, effect), every)


class NodeStore(Sequence):
    """Columnar node set: blocks of identical nodes up to indexed labels.
    NodeArrays adopts the columns directly (no per-node dict parsing); the
    `nodes` list every dict consumer sees becomes a LazyNodeSeq."""

    def __init__(self) -> None:
        self.blocks: List[_NodeBlock] = []
        self._n = 0

    def add_block(self, template: dict, count: int, name_fmt: str,
                  labels: Optional[dict] = None,
                  zone_cycle: Optional[Tuple[str, str, int]] = None,
                  index_labels: Sequence[str] = (),
                  taint: Optional[Tuple[dict, int]] = None) -> "NodeStore":
        if count <= 0:
            return self
        t = None
        if taint is not None:
            td, every = taint
            t = ((td.get("key", ""), td.get("value", "") or "",
                  td.get("effect", "")), int(every))
        self.blocks.append(_NodeBlock(
            template, int(count), name_fmt,
            tuple(sorted((labels or {}).items())), zone_cycle,
            tuple(index_labels), t))
        self._n += int(count)
        return self

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self._n))]
        if i < 0:
            i += self._n
        if not 0 <= i < self._n:
            raise IndexError(i)
        return self.materialize(i)

    def __deepcopy__(self, memo) -> "NodeStore":
        return self  # blocks are immutable by contract; views carry caches

    # block helpers -------------------------------------------------------

    def block_of(self, i: int) -> Tuple[_NodeBlock, int]:
        for blk in self.blocks:
            if i < blk.count:
                return blk, i
            i -= blk.count
        raise IndexError(i)

    def offsets(self) -> List[int]:
        out, off = [], 0
        for blk in self.blocks:
            out.append(off)
            off += blk.count
        return out

    def name_of(self, i: int) -> str:
        blk, _ = self.block_of(i)
        return blk.name_fmt.format(i)

    def gen_names(self) -> List[str]:
        out: List[str] = []
        i = 0
        for blk in self.blocks:
            fmt = blk.name_fmt
            out.extend(fmt.format(j) for j in range(i, i + blk.count))
            i += blk.count
        return out

    def materialize(self, i: int) -> dict:
        """One node dict, bit-equivalent to what the dict-path generator
        would have produced for this row."""
        import copy as _copy

        blk, local = self.block_of(i)
        node = _copy.deepcopy(blk.template)
        labels = dict(blk.labels)
        labels[C.LabelHostname] = self.name_of(i)
        for k in blk.index_labels:
            labels[k] = str(i)
        if blk.zone_cycle is not None:
            key, fmt, mod = blk.zone_cycle
            labels[key] = fmt.format(i % mod)
        md = node.setdefault("metadata", {})
        md["name"] = self.name_of(i)
        md["labels"] = labels
        if blk.taint is not None and i % blk.taint[1] == 0:
            (k, v, e), _every = blk.taint
            node.setdefault("spec", {})["taints"] = [
                {"key": k, "value": v, "effect": e}]
        return node

    # capability flags (plugin hosts and the image-locality scan consult
    # these instead of walking N dicts) ----------------------------------

    def _any_status(self, pred) -> bool:
        return any(pred((blk.template.get("status") or {}))
                   for blk in self.blocks)

    @property
    def may_have_gpu(self) -> bool:
        from ..plugins.gpushare import node_total_gpu_memory

        return any(node_total_gpu_memory(blk.template) > 0
                   for blk in self.blocks)

    @property
    def may_have_local_storage(self) -> bool:
        return self.any_annotation(C.AnnoNodeLocalStorage)

    @property
    def has_images(self) -> bool:
        return self._any_status(lambda st: bool(st.get("images")))

    def any_annotation(self, key: str) -> bool:
        return any(key in ((blk.template.get("metadata") or {})
                           .get("annotations") or {})
                   for blk in self.blocks)

    def resource_names(self) -> List[str]:
        from ..utils.objutil import node_allocatable

        out: List[str] = []
        seen = set()
        for blk in self.blocks:
            # node_allocatable, not raw status.allocatable: the axis must see
            # the same capacity fallback node_vector will read later
            for k in node_allocatable(blk.template):
                if k not in seen:
                    seen.add(k)
                    out.append(k)
        return out


class LazyNodeSeq(Sequence):
    """Stands in for `na.nodes`: indexed access materializes (and caches) a
    node dict; append/extend (the serve delta node-add path) lands in an
    overflow list of real dicts."""

    def __init__(self, store: NodeStore) -> None:
        self.store = store
        self._cache: Dict[int, dict] = {}
        self._extra: List[dict] = []

    def __len__(self) -> int:
        return len(self.store) + len(self._extra)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        if i < 0:
            i += len(self)
        ns = len(self.store)
        if i >= ns:
            return self._extra[i - ns]
        got = self._cache.get(i)
        if got is None:
            got = self._cache[i] = self.store.materialize(i)
        return got

    def append(self, node: dict) -> None:
        self._extra.append(node)

    def extend(self, nodes) -> None:
        self._extra.extend(nodes)


# ------------------------------------------------------- placement registry --


class _Span(NamedTuple):
    store: PodStore          # any view over the right base
    rows: np.ndarray         # absolute row ids, commit order


class NodePodList:
    """One node's placed-pod list: explicit dicts and columnar spans in
    commit order. Reading pods (iteration/indexing/removal) flattens spans
    through lazy materialization — the designated read-back boundary."""

    __slots__ = ("_items",)

    def __init__(self, items: Optional[list] = None) -> None:
        self._items: list = items if items is not None else []

    # -- writes -----------------------------------------------------------
    def append(self, pod: dict) -> None:
        self._items.append(pod)

    def add_span(self, store: PodStore, rows: np.ndarray) -> None:
        self._items.append(_Span(store, rows))

    # -- reads ------------------------------------------------------------
    def __len__(self) -> int:
        return sum(len(it.rows) if isinstance(it, _Span) else 1
                   for it in self._items)

    def __bool__(self) -> bool:
        return bool(self._items)

    def _flatten(self) -> list:
        if any(isinstance(it, _Span) for it in self._items):
            flat: list = []
            for it in self._items:
                if isinstance(it, _Span):
                    flat.extend(it.store.materialize(int(r))
                                for r in it.rows)
                else:
                    flat.append(it)
            self._items = flat
        return self._items

    def __iter__(self):
        return iter(self._flatten())

    def __getitem__(self, i):
        return self._flatten()[i]

    def __delitem__(self, i) -> None:
        del self._flatten()[i]

    def remove(self, pod: dict) -> None:
        self._flatten().remove(pod)

    def copy_items(self) -> list:
        return list(self._items)


class PodsOnNode:
    """The engine's `pods_on_node`, backed by a dict of non-empty nodes so
    the per-transaction snapshot is O(touched nodes), never O(N)."""

    __slots__ = ("_n", "_lists")

    def __init__(self, n: int) -> None:
        self._n = n
        self._lists: Dict[int, NodePodList] = {}

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i: int) -> NodePodList:
        # hot path first: _commit_pod indexes this once per placed pod, so
        # the existing-list case must stay a bare dict hit (the checked slow
        # path below only runs on first touch / slices / negative indexes)
        try:
            l = self._lists.get(i)
        except TypeError:  # unhashable: a slice
            l = None
        if l is not None:
            return l
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self._n))]
        if i < 0:
            i += self._n
        if not 0 <= i < self._n:
            raise IndexError(i)
        l = self._lists.get(i)
        if l is None:
            l = self._lists[i] = NodePodList()
        return l

    def __iter__(self):
        for i in range(self._n):
            yield self[i]

    def extend(self, iterable) -> None:
        """Grow the node axis (serve delta node-add): each yielded entry must
        be an empty list placeholder."""
        for entry in iterable:
            assert not entry, "extend only grows empty node slots"
            self._n += 1

    def total(self) -> int:
        """Total placed pods, without materializing anything."""
        return sum(len(l) for l in self._lists.values())

    def nonempty(self):
        return self._lists.items()

    def snapshot(self) -> dict:
        # prune empty lists while scanning: read-side iteration (reports,
        # censuses) registers an empty NodePodList per visited node, and
        # without pruning every later snapshot would re-scan those N
        # entries. In-repo call sites never hold an EMPTY list across a
        # snapshot boundary (commit/evict grab-and-mutate atomically), so
        # dropping them keeps snapshot O(touched) without losing state.
        live = {i: l for i, l in self._lists.items() if l._items}
        if len(live) != len(self._lists):
            self._lists = dict(live)
        return {"n": self._n,
                "lists": {i: l.copy_items() for i, l in live.items()}}

    def restore(self, snap: dict) -> None:
        self._n = snap["n"]
        self._lists = {i: NodePodList(list(items))
                       for i, items in snap["lists"].items()}
