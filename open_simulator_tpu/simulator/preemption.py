"""DefaultPreemption (PostFilter): victim selection for unschedulable pods.

Re-implements the semantics of the reference's default PostFilter plugin
(/root/reference/vendor/k8s.io/kubernetes/pkg/scheduler/framework/plugins/
defaultpreemption/default_preemption.go, registered by
algorithmprovider/registry.go:106-110) in the batched engine:

- When a pod fails scheduling, nodes whose failure is resolvable by removing
  pods (status Unschedulable, not UnschedulableAndUnresolvable) become
  preemption candidates (nodesWherePreemptionMightHelp, :259-271). The v1.20
  unresolvable set: NodeUnschedulable, NodeName, NodeAffinity, TaintToleration,
  required pod AFFINITY (interpodaffinity/filtering.go:389), and spread
  constraints whose topology label the node lacks (podtopologyspread/
  filtering.go:298). Resources, ports, anti-affinity, skew violations, and the
  out-of-tree Simon-family filters are resolvable.
- selectVictimsOnNode (:578-673): remove all strictly-lower-priority pods,
  check the preemptor fits; then reprieve victims most-important-first (PDB
  violators first), keeping each that still lets the preemptor fit.
- pickOneNodeForPreemption (:443-561): fewest PDB violations → lowest highest
  victim priority → lowest priority sum → fewest victims → latest earliest
  start time → first. Start times are proxied by commit order (the simulator
  sets every placed pod Running with no timestamp), and the final "sort of
  randomly" tie-break is the lowest node index — the same deterministic
  divergence the engine's selectHost uses (ops/kernels.py).
- The dry-run's fit checks rebuild the engine's own seed tables from a
  hypothetical `placed` dict with the victims decremented and re-run the
  compiled feasibility kernel — the removal semantics can never drift from
  the real seeding logic. GPU-share / Open-Local ledgers are intentionally
  NOT released in the dry run: the reference's dry run only adjusts default-
  plugin PreFilter state (RunPreFilterExtensionRemovePod), so its gpushare/
  open-local Filters also still see the victims' allocations.

Divergences from the reference, both deterministic-by-design:
- FindCandidates dry-runs ALL potential nodes from index 0 (the reference
  starts at a random offset, default_preemption.go:182-184) with the same
  candidate cap (10% of nodes, min 100) and early stop.
- What the reference observably does after a successful preemption in the
  simulator is: victims are DELETED from the fake cluster (PrepareCandidate →
  util.DeletePod) and the preemptor is still recorded unschedulable with its
  FitError and a nominated node (scheduler.go records the failure after
  PostFilter; Simon then deletes the pod, simulator.go:333-342). This module
  reproduces exactly that: victims leave their nodes (freed capacity is
  visible to every later pod), the preemptor lands in UnscheduledPods with
  status.nominatedNodeName set, and the evictions are logged on
  Simulator.preempted.

Engine integration (engine.schedule_pods): preemption needs the cluster state
AT THE FAILING POD'S SERIAL POSITION, which the batched run has already moved
past. Failures are rare, so the engine rewinds: snapshot → re-run the prefix
(placements are serial-order-deterministic, so the replay is exact) → run the
PostFilter at that state → evict → continue with the suffix.
"""

from __future__ import annotations

import functools
import os
from dataclasses import replace
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core import constants as C
from ..core.types import UnscheduledPod
from ..obs import instruments as obs
from ..resilience import guard
from ..utils.objutil import labels_of, match_label_selector, name_of, namespace_of
from .encode import (
    SIG_MEMO_KEY,
    PlacedGroup,
    bucket_capped,
    build_batch_tables,
    pad_batch_tables,
    pad_encoder_axes,
    plugin_flags,
    scheduling_signature,
)

# First-failing-stage classification, in the engine's stage order
# (engine._STAGE_ORDER). UnschedulableAndUnresolvable stages can never be
# fixed by removing pods; see module docstring for the per-plugin citations.
_STAGES = ("unsched", "taint", "affinity", "extra", "ports", "fit",
           "spread", "pod_affinity", "pod_anti", "gpu", "storage")
_UNRESOLVABLE = {"unsched", "taint", "affinity", "pod_affinity"}

# DefaultPreemptionArgs defaults (apis/config/v1beta1/defaults.go):
MIN_CANDIDATE_NODES_PERCENTAGE = 10
MIN_CANDIDATE_NODES_ABSOLUTE = 100


def pod_priority(pod: dict) -> int:
    """corev1helpers.PodPriority: spec.priority or 0."""
    try:
        return int((pod.get("spec") or {}).get("priority") or 0)
    except (TypeError, ValueError):
        return 0


def _preempt_policy_never(pod: dict) -> bool:
    """PodEligibleToPreemptOthers' only reachable gate in the simulator: the
    terminating-victims check is inert (evictions are instant deletes and the
    preemptor is never retried, simulator.go:333-342)."""
    return (pod.get("spec") or {}).get("preemptionPolicy") == "Never"


# ------------------------------------------------------------------ snapshots -----


def snapshot(sim) -> dict:
    """Copy of everything schedule runs mutate, for the rewind-and-replay."""
    return {
        "placed": {sig: dict(pg.node_counts) for sig, pg in sim.placed.items()},
        # O(touched nodes): the container copies only non-empty per-node item
        # lists (dicts by reference, columnar spans by reference)
        "pods_on_node": sim.pods_on_node.snapshot(),
        "homeless": len(sim.homeless),
        "log": len(sim._commit_log),
        "nominate": len(sim._nominate_log),
        "prio": len(sim._commits_prio),
        "preempted": len(sim.preempted),
        "gpu": sim.gpu_host.snapshot() if sim.gpu_host.enabled else None,
        "local": sim.local_host.snapshot() if sim.local_host.enabled else None,
    }


def restore(sim, snap: dict) -> None:
    # undo pod-dict mutations from commits after the snapshot (replayed
    # prefixes re-commit the same pods identically); pre-bound pods get their
    # original nodeName/status objects back (the crash-consistency rollback
    # must leave CALLER-owned pod dicts bit-identical)
    gpu_enabled = sim.gpu_host.enabled  # commit only logs annotations then
    for entry in sim._commit_log[snap["log"]:]:
        if entry[0] == sim._BULK_LOG:
            # bulk store commit: reset the columns, then restore any
            # materialized dict. Rows the commit patched carry their exact
            # pre-commit nodeName/status objects in the entry; a dict
            # materialized AFTER the commit (baked committed state at
            # materialization) falls back to the template's own view.
            _, store, rows, patched = entry
            bb = store.base
            bb.node_of[rows] = -1
            if bb.commit_seq is not None:
                bb.commit_seq[rows] = -1
            prev = {r: (nn, st) for r, nn, st in patched}
            for r, d in store.cached_rows_in(rows):
                dspec = d.get("spec")
                if r in prev:
                    nn, st = prev[r]
                    if dspec is not None:
                        if nn is None:
                            dspec.pop("nodeName", None)
                        else:
                            dspec["nodeName"] = nn
                    if st is None:
                        d.pop("status", None)
                    else:
                        d["status"] = st
                else:
                    if dspec is not None:
                        dspec.pop("nodeName", None)
                    tmpl_status = store.template_of_row(r).get("status")
                    if tmpl_status is None:
                        d.pop("status", None)
                    else:
                        import copy as _copy

                        d["status"] = _copy.deepcopy(tmpl_status)
            continue
        pod, prev_idx, prev_assume, prev_nn, prev_status = entry
        spec = pod.get("spec")
        if spec is not None:
            if prev_nn is None:
                spec.pop("nodeName", None)
            else:
                spec["nodeName"] = prev_nn
        if prev_status is None:
            pod.pop("status", None)
        else:
            pod["status"] = prev_status
        if gpu_enabled:
            anns = (pod.get("metadata") or {}).get("annotations")
            if anns is not None:
                if prev_idx is None:
                    anns.pop(C.AnnoGpuIndex, None)
                else:
                    anns[C.AnnoGpuIndex] = prev_idx
                if prev_assume is None:
                    anns.pop(C.AnnoGpuAssumeTime, None)
                else:
                    anns[C.AnnoGpuAssumeTime] = prev_assume
        sim._sig_of.pop(id(pod), None)
    # undo nominatedNodeName writes on failed preemptors (crash-consistency
    # rollbacks only: the normal loop re-snapshots after each nomination)
    for pod, had_status, prev_value, had_key in reversed(
            sim._nominate_log[snap["nominate"]:]):
        if not had_status:
            pod.pop("status", None)
        else:
            st = pod.get("status")
            if st is not None:
                if had_key:
                    st["nominatedNodeName"] = prev_value
                else:
                    st.pop("nominatedNodeName", None)
    del sim._nominate_log[snap["nominate"]:]
    rolled = len(sim._commits_prio) - snap["prio"]
    if rolled > 0:
        obs.COMMIT_ROLLBACKS.inc(rolled)
    unevicted = len(sim.preempted) - snap["preempted"]
    if unevicted > 0:
        # Only a crash-consistency rollback un-evicts (the preemption loop
        # always re-snapshots after evict): the restored victims re-enter the
        # census, so count them as commits — simon_commits_total −
        # rollbacks − victims stays bit-identical to the pre-call value.
        obs.COMMITS.inc(unevicted)
    del sim._commit_log[snap["log"]:]
    del sim._commits_prio[snap["prio"]:]
    del sim.preempted[snap["preempted"]:]
    for sig in list(sim.placed):
        nc = snap["placed"].get(sig)
        if nc is None:
            del sim.placed[sig]
        else:
            sim.placed[sig].node_counts = dict(nc)
    sim.pods_on_node.restore(snap["pods_on_node"])
    del sim.homeless[snap["homeless"]:]
    if snap["gpu"] is not None:
        sim.gpu_host.restore(snap["gpu"])
    if snap["local"] is not None:
        sim.local_host.restore(snap["local"])
    sim._last_tables = sim._last_carry = None


# ------------------------------------------------------------------- fit check ----


def _placed_minus(sim, removed: List[dict], node_i: int) -> Dict[object, PlacedGroup]:
    """Hypothetical placed dict with `removed` pods taken off node_i."""
    rm: Dict[object, int] = {}
    for p in removed:
        sig = sim._sig_rec(p)[0]
        rm[sig] = rm.get(sig, 0) + 1
    placed2 = dict(sim.placed)
    for sig, k in rm.items():
        pg = sim.placed[sig]
        nc = dict(pg.node_counts)
        left = nc.get(node_i, 0) - k
        if left > 0:
            nc[node_i] = left
        else:
            nc.pop(node_i, None)
        placed2[sig] = replace(pg, node_counts=nc)
    return placed2


def _fits(sim, g: int, node_i: int, placed2) -> bool:
    """PodPassesFiltersOnNode for the preemptor against a hypothetical placed
    state: rebuild seeds through the engine's own table builder, run the
    compiled feasibility kernel, read the one node's bit."""
    import jax.numpy as jnp

    bt = build_batch_tables(sim.encoder, [(g, -1)], placed2, sim.match_cache,
                            pad_to=1)
    bt = pad_encoder_axes(bt)
    bt = pad_batch_tables(bt, bucket_capped(sim.na.N, 1024))
    tables, carry = sim._to_device(bt)
    enable_gpu, enable_storage = plugin_flags(bt)
    kns, _ns = sim._kernel_ns(donate=False)  # diagnostics never donate
    obs.record_dispatch("feasibility_jit", gpu=enable_gpu,
                        storage=enable_storage, **sim._dispatch_dims(bt))
    feasible, _ = guard.supervised(functools.partial(
        kns.feasibility_jit,
        tables, carry, jnp.int32(g), jnp.int32(-1), jnp.asarray(True),
        enable_gpu=enable_gpu, enable_storage=enable_storage,
        filters=sim.filter_flags,
    ), site="dispatch", pods=1)
    return bool(np.asarray(feasible)[node_i])


# --------------------------------------------------------------------- PDBs -------


def _pdb_split(sim, victims: List[dict]) -> Tuple[List[dict], List[dict]]:
    """filterPodsWithPDBViolation (:736-781): stable split of the sorted victim
    list into (violating, non_violating), decrementing each matching PDB's
    status.disruptionsAllowed across the sequence."""
    pdbs = sim.model.pdbs
    allowed = []
    for pdb in pdbs:
        st = pdb.get("status") or {}
        try:
            allowed.append(int(st.get("disruptionsAllowed") or 0))
        except (TypeError, ValueError):
            allowed.append(0)
    violating: List[dict] = []
    non_violating: List[dict] = []
    for pod in victims:
        violated = False
        lbls = labels_of(pod)
        if lbls:
            for i, pdb in enumerate(pdbs):
                if namespace_of(pdb) != namespace_of(pod):
                    continue
                sel = (pdb.get("spec") or {}).get("selector")
                if not sel or not match_label_selector(sel, lbls):
                    continue
                disrupted = (pdb.get("status") or {}).get("disruptedPods") or {}
                if name_of(pod) in disrupted:
                    continue
                allowed[i] -= 1
                if allowed[i] < 0:
                    violated = True
        (violating if violated else non_violating).append(pod)
    return violating, non_violating


# ---------------------------------------------------------------- the PostFilter --


def _commit_seq(sim, pod: dict) -> int:
    """Commit-order proxy for pod start time (MoreImportantPod's second key)."""
    rec = sim._sig_rec(pod)
    return rec[2] if rec is not None else -1


def try_preempt(sim, pod: dict) -> Tuple[int, List[dict], Dict[str, int]]:
    """The preempt() pipeline at the CURRENT simulator state (the caller has
    rewound to the pod's serial position). Returns (node_i, victims, reasons):
    node_i = -1 when preemption cannot help; reasons = the per-stage FitError
    counts for the failure record either way."""
    import jax.numpy as jnp

    bt = sim.encode_batch([pod])
    pod.pop(SIG_MEMO_KEY, None)  # keep the (possibly recorded) pod dict clean
    tables, carry = sim._to_device(bt)
    enable_gpu, enable_storage = plugin_flags(bt)
    g, forced = int(bt.pod_group[0]), int(bt.forced_node[0])
    kns, _ns = sim._kernel_ns(donate=False)  # diagnostics never donate
    obs.record_dispatch("feasibility_jit", gpu=enable_gpu,
                        storage=enable_storage, **sim._dispatch_dims(bt))
    feasible, stages = guard.supervised(functools.partial(
        kns.feasibility_jit,
        tables, carry, jnp.int32(g), jnp.int32(forced), jnp.asarray(True),
        enable_gpu=enable_gpu, enable_storage=enable_storage,
        filters=sim.filter_flags,
    ), site="dispatch", pods=1)
    N = sim.na.N
    stages = {k: np.asarray(v)[:N] for k, v in stages.items()}
    reasons = sim._reasons_from_stages(pod, forced, stages)
    if _preempt_policy_never(pod):
        return -1, [], reasons

    # nodesWherePreemptionMightHelp: first-failing stage must be resolvable
    remaining = np.ones(N, bool)
    if forced >= 0:
        only = np.zeros(N, bool)
        only[forced] = True
        remaining &= only
    potential = np.zeros(N, bool)
    spread_label_missing = _spread_label_missing(sim, g)
    for stage in _STAGES:
        fail_here = remaining & ~stages[stage]
        if stage not in _UNRESOLVABLE:
            ok = fail_here
            if stage == "spread" and spread_label_missing is not None:
                ok = fail_here & ~spread_label_missing
            potential |= ok
        remaining &= stages[stage]
    idxs = np.nonzero(potential)[0]
    if len(idxs) == 0:
        return -1, [], reasons

    num_candidates = (len(idxs) * MIN_CANDIDATE_NODES_PERCENTAGE) // 100
    if num_candidates < MIN_CANDIDATE_NODES_ABSOLUTE:
        num_candidates = MIN_CANDIDATE_NODES_ABSOLUTE
    num_candidates = min(num_candidates, len(idxs))

    prio = pod_priority(pod)
    non_violating: List[dict] = []
    violating: List[dict] = []
    for n in idxs.tolist():
        cand = _select_victims_on_node(sim, g, n, prio)
        if cand is None:
            continue
        (non_violating if cand["pdb_violations"] == 0 else violating).append(cand)
        if non_violating and len(non_violating) + len(violating) >= num_candidates:
            break
    candidates = non_violating + violating
    if not candidates:
        return -1, [], reasons
    best = _pick_one_node(sim, candidates)
    return best["node"], best["victims"], reasons


def _spread_label_missing(sim, g: int) -> Optional[np.ndarray]:
    """[N] mask of nodes lacking the topology label of any of group g's hard
    spread terms — those spread failures are UnschedulableAndUnresolvable
    (podtopologyspread/filtering.go:298)."""
    grp = sim.encoder.group_list[g]
    if not grp.spread_dns:
        return None
    missing = np.zeros(sim.na.N, bool)
    for cid, _, _ in grp.spread_dns:
        dom = sim.na.domain_of(sim.encoder.counter_list[cid].topo_key)
        missing |= dom < 0
    return missing


def _select_victims_on_node(sim, g: int, node_i: int, prio: int) -> Optional[dict]:
    """selectVictimsOnNode (:578-673). Returns {node, victims, pdb_violations}
    with victims ordered by decreasing importance, or None when the node is
    not a candidate."""
    potential = [p for p in sim.pods_on_node[node_i] if pod_priority(p) < prio]
    if not potential:
        return None
    # remove ALL lower-priority pods; if the preemptor still doesn't fit, the
    # node is not a candidate (:618-635)
    if not _fits(sim, g, node_i, _placed_minus(sim, potential, node_i)):
        return None
    # MoreImportantPod order: higher priority first, then earlier start
    # (commit order proxies start time — every placed pod becomes Running
    # with no timestamp in the simulator)
    potential.sort(key=lambda p: (-pod_priority(p), _commit_seq(sim, p)))
    violating, non_violating = _pdb_split(sim, potential)
    removed = list(potential)
    victims: List[dict] = []
    pdb_violations = 0
    for batch, is_violating in ((violating, True), (non_violating, False)):
        for p in batch:
            # reprieve: add p back; keep it iff the preemptor still fits
            removed.remove(p)
            if not _fits(sim, g, node_i, _placed_minus(sim, removed, node_i)):
                removed.append(p)
                victims.append(p)
                if is_violating:
                    pdb_violations += 1
    return {"node": node_i, "victims": victims, "pdb_violations": pdb_violations}


def _pick_one_node(sim, candidates: List[dict]) -> dict:
    """pickOneNodeForPreemption (:443-561), deterministic final tie-break."""
    def min_by(cands, key):
        best = min(key(c) for c in cands)
        return [c for c in cands if key(c) == best]

    cands = min_by(candidates, lambda c: c["pdb_violations"])
    if len(cands) > 1:  # lowest highest-priority victim (victims sorted desc)
        cands = min_by(cands, lambda c: pod_priority(c["victims"][0]))
    if len(cands) > 1:  # lowest priority sum (offset like the reference)
        cands = min_by(cands, lambda c: sum(
            pod_priority(p) + (1 << 31) for p in c["victims"]))
    if len(cands) > 1:  # fewest victims
        cands = min_by(cands, lambda c: len(c["victims"]))
    if len(cands) > 1:
        # latest earliest-start among each node's highest-priority victims.
        # victims list PDB-violating pods FIRST, so victims[0] is not
        # necessarily the highest-priority one (GetEarliestPodStartTime
        # tracks the true max priority across all victims).
        def earliest(c):
            hi = max(pod_priority(p) for p in c["victims"])
            return min(_commit_seq(sim, p) for p in c["victims"]
                       if pod_priority(p) == hi)
        latest = max(earliest(c) for c in cands)
        cands = [c for c in cands if earliest(c) == latest]
    return min(cands, key=lambda c: c["node"])  # deterministic "first"


def evict(sim, victims: List[dict], node_i: int, preemptor: dict) -> None:
    """PrepareCandidate's observable effect in the simulator: victims are
    deleted from the fake cluster (util.DeletePod), freeing their capacity
    for every later pod. Ledger releases keep the gpushare/open-local node
    annotations consistent (the engine treats pods_on_node as truth)."""
    from ..resilience import faults

    faults.maybe_fail("preempt_evict")
    lst = sim.pods_on_node[node_i]
    for p in victims:
        sig = sim._sig_rec(p)[0]
        pg = sim.placed[sig]
        c = pg.node_counts.get(node_i, 0)
        if c <= 1:
            pg.node_counts.pop(node_i, None)
        else:
            pg.node_counts[node_i] = c - 1
        for k, q in enumerate(lst):
            if q is p:
                del lst[k]
                break
        if sim.gpu_host.enabled:
            sim.gpu_host.release(p, node_i)
        if sim.local_host.enabled:
            sim.local_host.release(p, node_i)
        sim.preempted.append({
            "pod": p, "node": sim.na.names[node_i], "by": name_of(preemptor),
        })
    obs.PREEMPT_VICTIMS.inc(len(victims))
    if sim.gpu_host.enabled:
        sim.gpu_host.flush()


# ------------------------------------------------------------- the outer loop -----


def _max_replays() -> int:
    """Bound on rewind/replay passes per schedule_pods call. Default is
    generous — real workloads rarely exceed a handful of distinct failing
    specs — but finite, so the O(failures × batch) corner cannot run away."""
    try:
        return max(0, int(os.environ.get(
            "OPEN_SIMULATOR_MAX_PREEMPTION_REPLAYS", "512")))
    except ValueError:  # tuning knob: fall back, don't crash the run
        return 512


def schedule_with_preemption(sim, pods: List[dict]) -> List[UnscheduledPod]:
    """schedule_pods with the PostFilter armed (mixed priorities present).

    The batched run goes first; each failure that might preempt gets the exact
    treatment: rewind to the call's start state, replay the prefix (serial-
    order determinism makes the replay exact), run the PostFilter there, evict,
    and re-run the suffix. Failures that can't preempt (no lower-priority pod
    placed anywhere, policy Never, or an identical pod already failed against
    an unchanged victim pool) never trigger a replay."""
    snap = snapshot(sim)
    failed = sim._schedule_pods_inner(pods)
    if not failed:
        return failed
    recorded: List[UnscheduledPod] = []
    remaining = list(pods)
    # (signature, priority) → len(_commits_prio) at the failed attempt. The
    # priority is part of the key because scheduling_signature excludes
    # spec.priority: a later same-spec pod with HIGHER priority sees a larger
    # victim pool and must get its own attempt.
    attempted: Dict[object, int] = {}
    # Replay-cost cap (ADVICE r5 / PARITY.md cost envelope): each loop
    # iteration is one rewind + prefix replay + suffix re-run — worst case
    # O(failures × batch) pod reschedules. The cap bounds that; beyond it the
    # remaining failures are recorded WITHOUT preemption attempts (placement
    # degrades conservatively: pods that could have preempted stay failed)
    # and the skips are visible as preemption_attempts{outcome="capped"}.
    replays = 0
    cap = _max_replays()
    while True:
        target = _select_target(sim, remaining, failed, attempted)
        if target is None:
            return recorded + failed
        if replays >= cap:
            obs.PREEMPT_ATTEMPTS.labels(outcome="capped").inc(len(failed))
            return recorded + failed
        replays += 1
        restore(sim, snap)
        obs.PREEMPT_REPLAY_PODS.inc(target)
        prefix_failed = sim._schedule_pods_inner(remaining[:target])
        pod = remaining[target]
        node_i, victims, reasons = try_preempt(sim, pod)
        obs.PREEMPT_ATTEMPTS.labels(
            outcome="nominated" if node_i >= 0 else "no_candidates").inc()
        # simonxray: the preemptor's AUTHORITATIVE reason + victim chain come
        # from this PostFilter pass, not from the discarded batched attempts
        # the rewind rolled back — record them (victims flip to 'preempted')
        sim._xray_preempt(pod, node_i, victims if node_i >= 0 else [], reasons)
        if node_i >= 0:
            evict(sim, victims, node_i, pod)
            # evictions change the victim pool WITHOUT appending to
            # _commits_prio, so the suffix-min gate can't see them —
            # invalidate every dedup entry instead of silently skipping a
            # same-signature pod that could now preempt.
            attempted.clear()
            # recordSchedulingFailure sets status.nominatedNodeName before
            # Simon deletes the pod; keep it visible on the record (logged
            # for the crash-consistency rollback — not a commit)
            st = pod.get("status")
            sim._nominate_log.append((
                pod, st is not None,
                st.get("nominatedNodeName") if st is not None else None,
                st is not None and "nominatedNodeName" in st))
            pod.setdefault("status", {})["nominatedNodeName"] = sim.na.names[node_i]
        else:
            attempted[(scheduling_signature(pod), pod_priority(pod))] = len(
                sim._commits_prio)
        recorded.extend(prefix_failed)
        recorded.append(UnscheduledPod(
            pod, sim._format_reason(pod, reasons, sim.na.N)))
        remaining = remaining[target + 1:]
        snap = snapshot(sim)
        obs.PREEMPT_REPLAY_PODS.inc(len(remaining))
        failed = sim._schedule_pods_inner(remaining)
        if not failed:
            return recorded


def _select_target(sim, remaining: List[dict], failed: List[UnscheduledPod],
                   attempted: Dict[object, int]) -> Optional[int]:
    """First failed pod worth a preemption attempt, by serial position."""
    fail_ids = {id(u.pod) for u in failed}
    prios = sim._commits_prio
    if not prios:
        return None
    global_min = min(prios)
    n = len(prios)
    # suffix minima so "did any lower-priority pod commit since the last
    # attempt against this signature" is O(1) per query
    suffix_min: Optional[List[int]] = None
    for i, p in enumerate(remaining):
        if id(p) not in fail_ids:
            continue
        prio = pod_priority(p)
        if global_min >= prio or _preempt_policy_never(p):
            continue
        at = attempted.get((scheduling_signature(p), prio))
        if at is not None:
            if at >= n:
                continue  # state rewound past the attempt point: no new info
            if suffix_min is None:
                suffix_min = list(prios)
                for k in range(n - 2, -1, -1):
                    suffix_min[k] = min(suffix_min[k], suffix_min[k + 1])
            if suffix_min[at] >= prio:
                continue  # no lower-priority commits since the failed attempt
        return i
    return None
