"""The Simulate() facade — the stable programmatic surface of the framework.

Mirrors /root/reference/pkg/simulator/core.go:67-119: expand the cluster's workloads
into pods, run the cluster sync (placing bound pods and scheduling pending ones), then
deploy each app in order, accumulating unschedulable pods.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from ..core.types import AppResource, ResourceTypes, SimulateResult
from ..models.workloads import (
    expand_workloads_excluding_daemonsets,
    pods_from_daemonset,
)
from .engine import Simulator


def simulate(
    cluster: ResourceTypes,
    apps: List[AppResource],
    disable_progress: bool = True,
    patch_pod_funcs: Optional[List[Callable]] = None,
    sched_config=None,
    extra_plugins: Optional[List] = None,
) -> SimulateResult:
    """Run one full simulation; returns placements + unschedulable pods.

    `cluster.pods` is replaced by the expansion of all cluster workloads (raw pods,
    Deployments/RS/RC/STS/Jobs/CronJobs, then DaemonSets against the node list), exactly
    like Simulate (core.go:85-96).
    """
    from ..utils.trace import Span

    with Span("Simulate", log_if_longer=1.0) as span:  # core.go:67-73 LogIfLong
        cluster = cluster.copy()
        pods = expand_workloads_excluding_daemonsets(cluster)
        for ds in cluster.daemon_sets:
            pods.extend(pods_from_daemonset(ds, cluster.nodes))
        cluster.pods = pods
        span.step("expand cluster workloads")

        sim = Simulator(cluster.nodes, disable_progress=disable_progress,
                        patch_pod_funcs=patch_pod_funcs, sched_config=sched_config,
                        extra_plugins=extra_plugins)
        result = sim.run_cluster(cluster)
        span.step("sync cluster")
        failed = list(result.unscheduled_pods)
        for app in apps:
            result = sim.schedule_app(app)
            span.step(f"schedule app {app.name}")
            failed.extend(result.unscheduled_pods)
        result.unscheduled_pods = failed
    return result
