"""The Simulator engine: owns cluster state and drives the batched device scheduler.

Plays the role of the reference's Simulator struct (pkg/simulator/simulator.go:33-57) —
fake clientset, informers, scheduler wiring, serial schedulePods loop — but TPU-native:
cluster state is a set of host tables + a device carry, and a whole batch of pods is
scheduled by one compiled `lax.scan` (ops/kernels.py) instead of one channel handshake
per pod (simulator.go:309-348).

Behavioral parity notes:
- Pods arriving with spec.nodeName are committed directly without any filter/capacity
  check, exactly like fakeclient Create + no scheduling cycle (simulator.go:326-331).
- Failed pods leave no trace on cluster state (the reference deletes them,
  simulator.go:333-342).
- ScheduleApp registers only ConfigMaps/StorageClasses/PDBs from the app — notably NOT
  Services (simulator.go:252-267), so app services never feed SelectorSpread; cluster
  services do (syncClusterResourceList:365-447).
- Unschedulable reasons are rebuilt from per-stage masks in the k8s FitError format
  ("0/N nodes are available: ..."). They are computed against the end state of the
  failing pod's SEGMENT — exact for wave/spread segments, whose failures happen at
  segment end, and at most one serial segment away from the reference's per-attempt
  state otherwise (documented deviation; placement itself is unaffected).
"""

from __future__ import annotations

import contextlib
import copy
import dataclasses
import functools
import time
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..core import constants as C
from ..obs import instruments as obs
from ..obs import pulse, xray
from ..resilience import faults
from ..resilience import guard
from ..core.types import AppResource, NodeStatus, ResourceTypes, SimulateResult, UnscheduledPod
from ..algo.queues import sort_affinity, sort_toleration
from ..models.workloads import generate_valid_pods_from_app
from ..ops import kernels
from ..ops.resources import ResourceAxis, pod_nonzero_cpu_mem
from ..utils.objutil import (
    find_untolerated_taint,
    labels_of,
    match_label_selector,
    name_of,
    namespace_of,
    pod_host_ports,
    selector_from_set,
)
from .encode import (
    SIG_MEMO_KEY,
    plugin_flags,
    BatchTables,
    Encoder,
    NodeArrays,
    PlacedGroup,
    bucket_capped,
    build_batch_tables,
    carried_specs_of_pod,
    pad_batch_tables,
    pad_encoder_axes,
    scheduling_signature,
    strip_daemon_pin,
)
from .store import EncodedRows, NodeStore, PodStore, PodsOnNode, is_pod_store

_jnp = None  # lazy jax import so host-only paths (ingestion, reports) stay jax-free

# Minimum run length of identical pods worth dispatching as a wave segment;
# shorter runs ride the serial scan (one compiled dispatch covers many runs).
WAVE_MIN = 8

_UNSET = object()  # Simulator._mesh sentinel: mesh decision not yet made


class GroupRoute(NamedTuple):
    """One group's kernel routing decision (see Simulator._wave_eligibility):
    kind "wave" → schedule_wave, "affinity" → schedule_affinity_wave,
    "spread" → schedule_group_serial, None → the general serial scan."""

    kind: Optional[str]
    cap1: bool
    gpu_live: bool
    ss_live: bool
    sa_live: bool


def _jax():
    global _jnp
    if _jnp is None:
        import jax.numpy as jnp

        _jnp = jnp
    return _jnp


def batch_tables_nbytes(bt: BatchTables) -> int:
    """Host bytes a BatchTables stages for device transfer (tables + seeds) —
    the simon_device_transfer_bytes_total accounting unit."""
    return sum(v.nbytes for f in dataclasses.fields(bt)
               if isinstance(v := getattr(bt, f.name), np.ndarray))


class ClusterModel:
    """Host registry of non-pod objects that influence scheduling."""

    def __init__(self) -> None:
        self.services: List[dict] = []
        self.replication_controllers: List[dict] = []
        self.replica_sets: List[dict] = []
        self.stateful_sets: List[dict] = []
        self.storage_classes: List[dict] = []
        self.config_maps: List[dict] = []
        self.pdbs: List[dict] = []
        self.pvcs: List[dict] = []

    def default_spread_selector(self, pod: dict) -> Optional[dict]:
        """helper.DefaultSelector (plugins/helper/spread.go:22-57): merge the selectors
        of every Service/RC (map-style) and RS/STS (set-based) selecting this pod.
        Returns a LabelSelector dict, or None when empty (SelectorSpread inert)."""
        ns, lbls = namespace_of(pod), labels_of(pod)
        merged: Dict[str, str] = {}
        exprs: List[dict] = []
        for svc in self.services:
            sel = (svc.get("spec") or {}).get("selector")
            if sel and namespace_of(svc) == ns and selector_from_set(sel, lbls):
                merged.update(sel)
        for rc in self.replication_controllers:
            sel = (rc.get("spec") or {}).get("selector")
            if sel and namespace_of(rc) == ns and selector_from_set(sel, lbls):
                merged.update(sel)
        for coll in (self.replica_sets, self.stateful_sets):
            for obj in coll:
                sel = (obj.get("spec") or {}).get("selector")
                if sel and namespace_of(obj) == ns and match_label_selector(sel, lbls):
                    merged.update(sel.get("matchLabels") or {})
                    exprs.extend(sel.get("matchExpressions") or [])
        if not merged and not exprs:
            return None
        out: dict = {}
        if merged:
            out["matchLabels"] = merged
        if exprs:
            out["matchExpressions"] = exprs
        return out


class Simulator:
    """One simulation run over a fixed node set."""

    def __init__(
        self,
        nodes: List[dict],
        disable_progress: bool = True,
        patch_pod_funcs: Optional[List[Callable]] = None,
        sched_config=None,
        use_mesh: Optional[bool] = None,
        extra_plugins: Optional[List] = None,
    ) -> None:
        """use_mesh: shard the node axis over every visible accelerator
        (parallel/mesh.py). None = auto: shard whenever >1 device is visible
        (overridable via OPEN_SIMULATOR_MESH=0/1); True/False force it. The
        sharded and single-device paths produce identical placements — the
        mesh only distributes the [*, N] tables and carry rows, and XLA
        inserts the cross-shard collectives for normalizers and argmax."""
        # The simulator owns its node objects, like the reference's fakeclient
        # (Create deep-copies): the plugins write annotations/allocatable back into
        # nodes, and repeated simulations over one caller-owned cluster (the
        # capacity planner's probes) must never see a previous run's mutations.
        # A columnar NodeStore (simulator/store.py) is immutable by contract and
        # materializes per-Simulator dict views, so the deepcopy is a no-op
        # there — UNLESS a block declares gpu/local-storage state, whose
        # host-mirrored ledgers write node annotations back: those clusters
        # materialize to real dicts up front (correctness over speed).
        if isinstance(nodes, NodeStore):
            if nodes.may_have_gpu or nodes.may_have_local_storage:
                nodes = [nodes.materialize(i) for i in range(len(nodes))]
        else:
            nodes = copy.deepcopy(nodes)
        from ..api.schedconfig import DEFAULT_SCHEDULER_CONFIG, KERNEL_FILTERS
        from ..utils.devices import enable_compilation_cache

        # persistent XLA cache: fresh processes (CLI runs, server workers)
        # reuse compiled scan kernels instead of re-paying 15-40s per shape
        enable_compilation_cache()
        # ground-truth XLA compile counting (obs/instruments.py, idempotent);
        # this constructor has already committed to importing jax
        obs.install_jax_monitoring()
        # simonpulse per-dispatch ledger (obs/pulse.py): OPEN_SIMULATOR_PULSE=1
        pulse.maybe_enable_from_env()

        self.sched_config = sched_config or DEFAULT_SCHEDULER_CONFIG
        self.score_w = kernels.ScoreWeights(**self.sched_config.weight_kwargs())
        self.filter_flags = kernels.FilterFlags(**{
            flag: name not in self.sched_config.disabled_kernel_filters
            for name, flag in KERNEL_FILTERS.items()
        })
        self.axis = ResourceAxis()
        if isinstance(nodes, NodeStore):
            for k in nodes.resource_names():
                self.axis.intern(k)
        else:
            self.axis.discover(nodes, [])
        self.model = ClusterModel()
        self.na = NodeArrays(nodes, self.axis)
        self.encoder = Encoder(self.na, self.axis, self.model)
        self.encoder.filter_disabled = self.sched_config.disabled_encoder_filters
        self.encoder.extra_plugins = list(extra_plugins or [])
        from ..plugins.gpushare import GpuShareHost
        from ..plugins.openlocal import OpenLocalHost

        self.gpu_host = GpuShareHost(self.na.nodes)
        self.encoder.gpu_host = self.gpu_host
        self.local_host = OpenLocalHost(self.na.nodes)
        self.encoder.local_host = self.local_host
        self.placed: Dict[object, PlacedGroup] = {}  # signature → aggregated commits
        # per-node placement registry: dict lists + columnar spans, lazy
        # materialization on read-back (simulator/store.py PodsOnNode)
        self.pods_on_node: PodsOnNode = PodsOnNode(self.na.N)
        # pod-store bases with bulk-committed rows: the _sig_rec fallback for
        # preemption bookkeeping the bulk path skips per-pod
        self._bulk_stores: List[object] = []
        self.homeless: List[dict] = []  # bound to a node name we don't know
        # Preemption bookkeeping (simulator/preemption.py). _sig_of and
        # _commits_prio are maintained on every commit (a dict store + int
        # append per pod): evictions must find any placed pod's signature,
        # and commit order proxies pod start time. The commit LOG (pod-dict
        # undo info for the rewind) only fills once mixed priorities arm the
        # PostFilter.
        self.preempted: List[dict] = []   # {pod, node, by} eviction records
        self._sig_of: Dict[int, tuple] = {}   # id(pod) → (sig, node_i, seq)
        self._commits_prio: List[int] = []    # spec.priority per commit, in order
        # (pod, prev_gpu_index, prev_assume, prev_node_name, prev_status)
        self._commit_log: List[tuple] = []
        # nominatedNodeName writes on failed preemptors (not commits):
        # (pod, had_status, prev_value, had_key) — undone by restore()
        self._nominate_log: List[tuple] = []
        self._preempt_armed = False
        # Crash consistency (resilience/): _transaction() arms full commit
        # logging so ANY failure rolls host state back; the two counters keep
        # the commits−rollbacks−victims metric reconciliation exact when a
        # batch dies between its commits and its batch-end COMMITS increment.
        self._txn_armed = False
        self._commit_events = 0    # _commit_pod calls, monotone
        self._commits_counted = 0  # commit events already in obs.COMMITS
        self._priority_seen: set = set()
        self.match_cache: Dict[Tuple[int, object], bool] = {}  # (counter id, sched signature)
        self.disable_progress = disable_progress
        self.patch_pod_funcs = patch_pod_funcs or []
        self._last_tables: Optional[BatchTables] = None
        self._last_carry = None
        # Wave scheduling (ops/kernels.py schedule_wave): runs of identical pods
        # whose only self-interaction is capacity commit in bulk. Settable to
        # False to force the pure serial scan (used by the parity tests).
        self.use_waves = True
        self.use_mesh = use_mesh
        self._mesh = _UNSET
        # simonguard (resilience/guard.py): backends this run executed on, in
        # order — ["tpu", "cpu"] after a mid-run failover. Seeded lazily at
        # the first device call; surfaced on SimulateResult.backend_path so
        # a degraded run is never silent. _fallback pins the rest of this
        # simulator's life to the CPU fallback after a containment.
        self.backend_path: List[str] = []
        self._fallback = False
        # routing cache, keyed by a flags/weights digest so mutating
        # filter_flags/score_w on a reused Simulator can never return stale
        # routes (_route_digest; the stale-cache regression test covers it)
        self._wave_elig_cache: Dict[int, GroupRoute] = {}
        self._wave_elig_key: tuple = ()
        self._domain_count_cache: Dict[str, int] = {}  # topo key → #domains
        import os as _os

        # Break-even fallback: live-DNS groups whose every self topology has
        # fewer than this many domains ride the fused group-serial scan
        # instead of the affinity wave. Default 0: the wave's multi-round
        # epochs amortize one sort over the whole segment, so it wins at all
        # cardinalities measured; the knob remains for backends where that
        # trade flips (placements are exact on either path).
        try:
            self._spread_wave_min_domains = int(
                _os.environ.get("OPEN_SIMULATOR_SPREAD_WAVE_MIN_DOMAINS", "0"))
        except ValueError:  # pure-performance knob: fall back, don't crash
            self._spread_wave_min_domains = 0
        # Per-segment wall-clock attribution (bench BENCH_DETAIL breakdown):
        # blocks on every segment's result, so it is OFF unless asked for.
        self._segment_timing = _os.environ.get(
            "OPEN_SIMULATOR_SEGMENT_TIMING") == "1"
        # Streaming segment encode (_schedule_run_streaming): runs longer
        # than this many pods schedule as double-buffered chunks. 0 disables
        # (monolithic runs); the default keeps every existing bench shape
        # (<=100k-pod runs) on the single-dispatch path.
        self._stream_explicit = "OPEN_SIMULATOR_STREAM_PODS" in _os.environ
        try:
            self._stream_chunk = max(0, int(_os.environ.get(
                "OPEN_SIMULATOR_STREAM_PODS", "131072")))
        except ValueError:  # pure-performance knob: fall back, don't crash
            self._stream_chunk = 131072
        # simonxray (obs/xray.py): per-attempt staging for the flight
        # recorder. None unless recording is active — the off path costs one
        # None-check per schedule/probe call and nothing else (no extra
        # dispatches, no extra fetches, unchanged dispatch signatures).
        self._xray_run = None

    # ------------------------------------------------------------- state ----------

    def _commit_pod(self, pod: dict, node_i: int, scheduled: bool = True) -> None:
        faults.maybe_fail("commit")
        self._commit_events += 1
        spec = pod.get("spec")
        if spec is None:
            spec = pod["spec"] = {}
        if self._preempt_armed or self._txn_armed:
            # rewind info BEFORE reserve() mutates the pod (preemption.restore
            # and the crash-consistency rollback share the log; pre-bound
            # commits are logged too so a rollback restores their status).
            # Annotation undo info only matters when gpushare reserve() will
            # write annotations — restore() skips them otherwise, so the
            # common path pays no metadata lookups.
            if self.gpu_host.enabled:
                anns = (pod.get("metadata") or {}).get("annotations") or {}
                prev_idx = anns.get(C.AnnoGpuIndex)
                prev_assume = anns.get(C.AnnoGpuAssumeTime)
            else:
                prev_idx = prev_assume = None
            self._commit_log.append((
                pod, prev_idx, prev_assume,
                spec.get("nodeName"), pod.get("status")))
        spec["nodeName"] = self.na.names[node_i]
        pod["status"] = {"phase": "Running"}
        # Snapshot the signature BEFORE reserve() writes gpu-index/assume-time
        # annotations, so identical pods keep one signature (match-cache key).
        # Inline the memo hit (stamped by encode_batch/workload expansion) —
        # this runs once per placed pod.
        sig = pod.get(SIG_MEMO_KEY)
        if sig is None:
            sig = scheduling_signature(pod)
        self._sig_of[id(pod)] = (sig, node_i, len(self._commits_prio))
        try:
            self._commits_prio.append(int((pod.get("spec") or {}).get("priority") or 0))
        except (TypeError, ValueError):
            self._commits_prio.append(0)
        if scheduled:
            # Open-Gpu-Share Reserve: assign device ids, write the gpu-index pod
            # annotation + simon/node-gpu-share node annotation, adjust whole-GPU
            # allocatable (open-gpu-share.go:147-188).
            if self.gpu_host.enabled:
                self.gpu_host.reserve(pod, node_i)
            # Open-Local Bind: VG requested / device allocation writeback
            # (open-local.go:215-250).
            if self.local_host.enabled:
                self.local_host.reserve(pod, node_i, self.model.storage_classes)
        elif self.gpu_host.enabled:
            # pre-bound pod with an existing gpu-index (live snapshot): account it
            self.gpu_host.seed_pod(pod, node_i)
        pg = self.placed.get(sig)
        if pg is None:
            pg = self.placed[sig] = PlacedGroup(
                pod=pod,
                sig=sig,
                req_vec=self.axis.pod_vector(pod).astype(np.float32),
                nonzero=pod_nonzero_cpu_mem(pod).astype(np.float32),
                port_ids=self.encoder.port_ids(pod_host_ports(pod)),
                carrier_ids=[self.encoder.carrier_id(cs)
                             for cs in carried_specs_of_pod(pod)],
            )
        nc = pg.node_counts
        nc[node_i] = nc.get(node_i, 0) + 1
        pod.pop(SIG_MEMO_KEY, None)  # internal marker; keep result objects clean
        self.pods_on_node[node_i].append(pod)

    # Commit-log sentinel: a bulk entry is ("__bulk__", store_view, rows) —
    # preemption.restore resets the columns instead of walking pod dicts.
    _BULK_LOG = "__bulk__"

    def _placed_group_for_template(self, b, ti: int) -> PlacedGroup:
        """The PlacedGroup for one store template (same record _commit_pod
        would create from the first committed replica — selector matching
        reads template fields only, so the shared template is an exact
        representative)."""
        sig = b.sigs[ti]
        pg = self.placed.get(sig)
        if pg is None:
            tmpl = b.templates[ti]
            pg = self.placed[sig] = PlacedGroup(
                pod=tmpl,
                sig=sig,
                req_vec=self.axis.pod_vector(tmpl).astype(np.float32),
                nonzero=pod_nonzero_cpu_mem(tmpl).astype(np.float32),
                port_ids=self.encoder.port_ids(pod_host_ports(tmpl)),
                carrier_ids=[self.encoder.carrier_id(cs)
                             for cs in carried_specs_of_pod(tmpl)],
            )
        return pg

    def _sig_rec(self, pod: dict) -> Optional[tuple]:
        """(signature, node_i, commit_seq) for any placed pod — the per-pod
        _sig_of row when one exists, else the columnar record of a
        bulk-committed store row (preemption's victim bookkeeping)."""
        rec = self._sig_of.get(id(pod))
        if rec is not None:
            return rec
        for b in self._bulk_stores:
            row = b.row_by_id.get(id(pod))
            if row is not None and b.node_of[row] >= 0:
                seq = (int(b.commit_seq[row])
                       if b.commit_seq is not None else -1)
                return (b.sigs[int(b.tmpl_of[row])], int(b.node_of[row]), seq)
        return None

    def _commit_store_bulk(self, store: PodStore, bt: BatchTables,
                           choices: np.ndarray, P: int, seg_of: np.ndarray,
                           seg_carry_of: Dict[int, object], final_carry,
                           tables) -> List[UnscheduledPod]:
        """Apply a whole run's placements to host state as array ops — the
        columnar replacement for P calls to _commit_pod. Ordering contracts
        that keep it bit-identical to the per-pod loop (the double-encode
        parity suite's commit half):
        - PlacedGroup.node_counts keys are inserted in first-appearance order
          of (template, node) over the pod sequence — exactly the order the
          per-pod loop would have inserted them, so the f32 seed accumulation
          order in build_node_axis_tables is unchanged;
        - per-node span rows are in pod order (stable sort by node);
        - _commits_prio grows by the committed rows in pod order.
        Failures materialize — an unschedulable pod is read back by
        definition (its dict rides the UnscheduledPod record)."""
        b = store.base
        failed: List[UnscheduledPod] = []
        ch = np.asarray(choices[:P])
        mask = ch >= 0
        n = int(mask.sum())
        if n:
            faults.maybe_fail_bulk("commit", n)
            self._commit_events += n
            rows_abs = np.flatnonzero(mask).astype(np.int64) + store.lo  # simonlint: ignore[dtype-drift] -- host-side fancy index, never shipped to device
            nodes = ch[mask].astype(np.int64)  # simonlint: ignore[dtype-drift] -- host-side aggregation key, never shipped to device
            b.node_of[rows_abs] = nodes.astype(np.int32)
            b.node_names = self.na.names
            b.frozen = True  # committed columns: no more add_block
            if not any(s is b for s in self._bulk_stores):
                self._bulk_stores.append(b)
            seq = store.ensure_commit_seq()
            seq0 = len(self._commits_prio)
            seq[rows_abs] = seq0 + np.arange(n, dtype=np.int64)  # simonlint: ignore[dtype-drift] -- host-side commit-order column
            tids = b.tmpl_of[rows_abs].astype(np.int64)  # simonlint: ignore[dtype-drift] -- host-side aggregation key
            utids = np.unique(tids)
            if len(utids) == 1:
                import itertools

                self._commits_prio.extend(itertools.repeat(
                    int(b.tmpl_priority[int(utids[0])]), n))
            else:
                prio_map = np.array(b.tmpl_priority, np.int64)  # simonlint: ignore[dtype-drift] -- host-side priority map
                self._commits_prio.extend(prio_map[tids].tolist())
            # a dict materialized BEFORE this commit must reflect it now;
            # its pre-commit nodeName/status ride the bulk log entry so a
            # rollback restores the exact objects (the per-pod log's
            # caller-owned-dict contract)
            patched = []
            for r, d in store.cached_rows_in(rows_abs):
                spec_d = d.setdefault("spec", {})
                patched.append((r, spec_d.get("nodeName"), d.get("status")))
                spec_d["nodeName"] = self.na.names[int(b.node_of[r])]
                d["status"] = {"phase": "Running"}
            if self._preempt_armed or self._txn_armed:
                self._commit_log.append(
                    (self._BULK_LOG, store, rows_abs, patched))
            # placed census: (template, node) counts in first-appearance order
            span = self.na.N + 1
            key = tids * span + nodes
            uniq, first, counts = np.unique(
                key, return_index=True, return_counts=True)
            for j in np.argsort(first, kind="stable").tolist():
                k = int(uniq[j])
                pg = self._placed_group_for_template(b, k // span)
                node_i = k % span
                pg.node_counts[node_i] = (
                    pg.node_counts.get(node_i, 0) + int(counts[j]))
            # per-node spans, rows in pod order within each node
            order = np.argsort(nodes, kind="stable")
            sn = nodes[order]
            sr = rows_abs[order]
            bounds = np.flatnonzero(np.diff(sn)) + 1
            starts = np.concatenate([[0], bounds])
            root = PodStore(b)
            pon = self.pods_on_node
            for nid, rows_chunk in zip(
                    sn[starts].tolist(), np.split(sr, bounds)):
                pon[int(nid)].add_span(root, rows_chunk)
        if n < P:
            reason_cache: Dict[Tuple[int, int, int], Dict[str, int]] = {}
            for i in np.flatnonzero(~mask).tolist():
                pod = store[i]
                key = (int(bt.pod_group[i]), int(bt.forced_node[i]),
                       int(seg_of[i]))
                reasons = reason_cache.get(key)
                if reasons is None:
                    reasons = reason_cache[key] = self._explain_reasons(
                        pod, key[0], key[1], tables,
                        seg_carry_of.get(key[2], final_carry))
                pod.pop(SIG_MEMO_KEY, None)
                obs.record_filter_reasons(reasons)
                failed.append(UnscheduledPod(
                    pod, self._format_reason(pod, reasons, self.na.N)))
        return failed

    def register_cluster_objects(self, rt: ResourceTypes) -> None:
        m = self.model
        m.services.extend(rt.services)
        m.replication_controllers.extend(rt.replication_controllers)
        m.replica_sets.extend(rt.replica_sets)
        m.stateful_sets.extend(rt.stateful_sets)
        m.storage_classes.extend(rt.storage_classes)
        m.config_maps.extend(rt.config_maps)
        m.pdbs.extend(rt.pod_disruption_budgets)
        m.pvcs.extend(rt.persistent_volume_claims)

    def register_app_objects(self, rt: ResourceTypes) -> None:
        """ScheduleApp only materializes CM/SC/PDB from apps (simulator.go:252-267)."""
        self.model.config_maps.extend(rt.config_maps)
        self.model.storage_classes.extend(rt.storage_classes)
        self.model.pdbs.extend(rt.pod_disruption_budgets)

    # --------------------------------------------------------- scheduling ---------

    def schedule_pods(self, pods: List[dict]) -> List[UnscheduledPod]:
        """The schedulePods loop (simulator.go:309-348), batched while preserving the
        reference's strictly serial order: runs of unbound pods become one compiled
        scan; a pre-bound pod (spec.nodeName) flushes the run first, then commits
        directly — so earlier unbound pods never see capacity a later bound pod will
        take, exactly as in the serial loop.

        When the pods seen so far carry more than one distinct spec.priority,
        the DefaultPreemption PostFilter arms (simulator/preemption.py): failed
        pods may evict strictly-lower-priority victims, exactly like the
        reference's default plugin set (algorithmprovider/registry.go:106-110).
        With uniform priorities preemption is provably inert — no victim can
        have strictly lower priority — so the single-pass batched run is used
        unchanged.

        The whole call is transactional (_transaction): any failure — an
        injected fault, a device error, a KeyboardInterrupt — rolls
        placements, census, and pod dicts back to the pre-call state.

        Containment (simonguard): a wedged backend (BackendWedged from the
        dispatch watchdog) or a device OOM that bisection could not contain
        fails the CALL over to the CPU fallback — the transaction has already
        rolled this call back, so earlier committed calls (the committed
        segments of the run) stay in place and only this batch replays, on
        CPU, to the identical placements (serial-order determinism). The
        failover is recorded on backend_path and
        simon_guard_failovers_total{cause}; it is never silent."""
        from ..obs import scope

        sc = scope.active()  # one None-check: the scope-off hot path pays
        #                      nothing (same contract as xray.begin_run)
        t0 = time.perf_counter()
        if sc is not None:
            cm = sc.span("engine.schedule_pods", cat="engine", pods=len(pods))
        else:
            cm = contextlib.nullcontext()
        with cm:
            return self._schedule_pods_timed(pods, t0)

    def _schedule_pods_timed(self, pods: List[dict], t0: float
                             ) -> List[UnscheduledPod]:
        try:
            def attempt():
                # fresh xray staging per ATTEMPT: records of a failed attempt
                # die with its rolled-back transaction, so a failover replay
                # never leaves phantom rows (committed records then carry the
                # full backend_path including the failover)
                self._xray_run = xray.begin_run("schedule")
                with self._transaction(memo_pods=pods):
                    if self._track_priorities(pods):
                        from .preemption import schedule_with_preemption

                        return schedule_with_preemption(self, pods)
                    return self._schedule_pods_inner(pods)

            result = self._run_contained(attempt)
            self._xray_commit()
            return result
        finally:
            self._xray_run = None
            obs.E2E_SECONDS.observe(time.perf_counter() - t0)

    # ------------------------------------------------ guard / failover -------

    # Bounded failover attempts per call: the initial run plus up to two
    # contained retries (default backend → CPU, and one more in case an
    # injected plan also faults the first CPU attempt). A third containment
    # propagates — persistent OOM on the host backend is a real capacity
    # problem, not a transient.
    _MAX_BACKEND_ATTEMPTS = 3

    def _run_contained(self, attempt: Callable):
        """Run one transactional scheduling/probe attempt with mid-run
        backend failover: containable failures (guard.containment_cause)
        retry on the CPU fallback; everything else propagates."""
        for k in range(self._MAX_BACKEND_ATTEMPTS):
            try:
                with self._device_scope():
                    return attempt()
            except BaseException as e:
                cause = guard.containment_cause(e)
                if cause is None or k + 1 >= self._MAX_BACKEND_ATTEMPTS:
                    raise
                self._failover(cause)

    @contextlib.contextmanager
    def _device_scope(self):
        """Route this call's device work: the default backend normally, the
        CPU fallback once this simulator failed over or the process
        quarantined the default backend. Seeds backend_path on first use."""
        use_cpu = self._fallback or guard.default_quarantined()
        if not self.backend_path:
            self.backend_path.append(
                "cpu" if use_cpu else guard.current_backend())
        if use_cpu and guard.current_backend() != "cpu":
            with guard.fallback_scope():
                yield
        else:
            yield

    def _failover(self, cause: str) -> None:
        """Commit this simulator to the CPU fallback for the rest of its
        life (the transaction already rolled the failing call back)."""
        import logging

        guard.count_failover(cause, "schedule")
        self._fallback = True
        self._mesh = None  # the fallback runs single-device; drop shardings
        self._last_tables = self._last_carry = None
        self.backend_path.append("cpu")
        logging.getLogger("open_simulator_tpu").warning(
            "device failure contained (%s); failing over to the CPU backend "
            "and replaying the rolled-back batch (backend_path=%s)",
            cause, self.backend_path)

    # ------------------------------------------------------------ xray -------

    def _cfg_digest(self) -> str:
        """Score-weight / filter-flag digest shared by the dispatch signature
        (`_dispatch_dims`) and the xray batch records."""
        return f"{hash((self.score_w, self.filter_flags)) & 0xffffffff:08x}"

    def _xray_commit(self) -> None:
        """Commit this call's staged decision records. A recorder failure
        (disk full, unwritable path) must never fail a successful scheduling
        call: it is logged loudly and recording stops."""
        run = self._xray_run
        if run is None:
            return
        try:
            xray.commit_run(run, self.backend_path, self._cfg_digest())
        except Exception:
            import logging

            logging.getLogger("open_simulator_tpu").exception(
                "xray: trace commit failed; disabling recording for this "
                "process (the scheduling result itself is unaffected)")
            xray.disable()

    def _xray_preempt(self, pod: dict, node_i: int, victims: List[dict],
                      reasons: Dict[str, int]) -> None:
        """Preemption hook (simulator/preemption.py): record the preemptor's
        authoritative reason + victim chain; victims flip to 'preempted'."""
        run = self._xray_run
        if run is None:
            return
        run.add_preempt(
            f"{namespace_of(pod)}/{name_of(pod)}",
            self.na.names[node_i] if node_i >= 0 else None,
            [f"{namespace_of(v)}/{name_of(v)}" for v in victims],
            self._format_reason(pod, reasons, self.na.N), dict(reasons),
            nominated=node_i >= 0)

    def _xray_set(self, key3: Tuple[int, int, int], tables, carry_start, bt):
        """Build one decision set — the per-stage masks, total score, and
        per-plugin components for a (group, forced, segment) key against the
        segment-START carry (the state the segment's first pick saw) — via
        ONE fused explain_pod dispatch and ONE packed fetch. Called once per
        key per batch, never per pod; this is the designated spill point the
        fetch-in-wave-loop lint rule protects."""
        g, forced, _segk = key3
        enable_gpu, enable_storage = getattr(self, "_last_flags", (True, True))
        jnp = _jax()
        dims = self._dispatch_dims(bt)
        # the xray flag joins the signature digest: explain_pod is only ever
        # compiled on recording runs and can never alias a scheduling kernel
        obs.record_dispatch("explain_pod", xray=True, zones=bt.n_zones,
                            gpu=enable_gpu, storage=enable_storage, **dims)
        kns, _ = self._kernel_ns(donate=False)  # diagnostics never donate
        feasible, stages, total, comp = guard.supervised(functools.partial(
            kns.explain_jit,
            tables, carry_start, jnp.int32(g), jnp.int32(forced),
            jnp.asarray(True), n_zones=bt.n_zones, enable_gpu=enable_gpu,
            enable_storage=enable_storage, w=self.score_w,
            filters=self.filter_flags,
        ), site="dispatch", pods=1)
        n_pad = int(total.shape[0])

        def row(x):
            # inert components can be python scalars (e.g. openlocal with
            # storage disabled): broadcast everything to one [1, Npad] row
            return jnp.broadcast_to(jnp.asarray(x, jnp.float32), (n_pad,))[None]

        rows = ([row(feasible), row(total)]
                + [row(stages[s]) for s in xray.STAGE_NAMES]
                + [row(comp[c]) for c in kernels.COMPONENT_ORDER])
        packed = guard.supervised(
            lambda: np.asarray(jnp.concatenate(rows, axis=0)),
            site="fetch", pods=1)[:, :self.na.N]
        ns = len(xray.STAGE_NAMES)
        stage_rows = {s: packed[2 + i] > 0.5
                      for i, s in enumerate(xray.STAGE_NAMES)}
        comp_rows = {c: packed[2 + ns + i]
                     for i, c in enumerate(kernels.COMPONENT_ORDER)}
        return xray.XraySet(g, forced, key3[2], stage_rows, packed[1],
                            comp_rows, packed[0] > 0.5, self.na.names)

    def _count_commits(self, n: int = 1) -> None:
        """The one COMMITS increment path: tracks how many commit events are
        already counted so _transaction can reconcile a partial batch."""
        obs.COMMITS.inc(n)
        self._commits_counted += n

    @contextlib.contextmanager
    def _transaction(self, memo_pods: Optional[List[dict]] = None):
        """Crash consistency for one scheduling/probe call: snapshot host
        state, arm full commit logging, and on ANY failure (1) count the
        partial batch's commits that died before their batch-end COMMITS
        increment, then (2) roll everything back — restore() counts the
        rolled commits as simon_commit_rollbacks_total and re-materialized
        eviction victims as commits, so commits − rollbacks − victims is
        bit-identical to the pre-call value. Placements, census, pod dicts,
        and the gpushare/open-local ledgers all return to the snapshot.

        `memo_pods`: pods to strip SIG_MEMO_KEY from on rollback — a schedule
        call never leaves the internal marker behind on any path, success or
        failure. Probe calls pass None: their pods keep memos BY DESIGN
        (repeated probes skip re-encoding), on success and failure alike."""
        from .preemption import restore, snapshot

        snap = snapshot(self)
        base_events = self._commit_events
        base_counted = self._commits_counted
        prev = self._txn_armed
        self._txn_armed = True
        try:
            yield
        except BaseException:
            uncounted = ((self._commit_events - base_events)
                         - (self._commits_counted - base_counted))
            if uncounted > 0:
                obs.COMMITS.inc(uncounted)
            restore(self, snap)
            # store batches never carry per-pod memos (templates do,
            # transiently) — iterating one here would materialize the whole
            # batch as dicts mid-failover, the exact cost the store removes
            if not is_pod_store(memo_pods):
                for p in memo_pods or ():
                    p.pop(SIG_MEMO_KEY, None)
            raise
        else:
            # rollback info is only reachable within this call's restores;
            # drop it so the logs never grow across successful calls
            del self._commit_log[snap["log"]:]
            del self._nominate_log[snap["nominate"]:]
        finally:
            self._txn_armed = prev

    def _track_priorities(self, pods: List[dict]) -> bool:
        """Arm the PostFilter when >1 distinct priority has been seen across
        ALL schedule_pods calls (cluster pods and app pods schedule in separate
        calls, and a priority gap BETWEEN those sets is exactly where the
        reference could preempt), unless the scheduler config disabled it."""
        if getattr(self.sched_config, "preemption_disabled", False):
            return False
        seen = self._priority_seen
        if is_pod_store(pods):
            seen.update(pods.priorities_present())
        else:
            seen.update((p.get("spec") or {}).get("priority") or 0
                        for p in pods)
        self._preempt_armed = len(seen) > 1
        return self._preempt_armed

    def _schedule_pods_inner(self, pods: List[dict]) -> List[UnscheduledPod]:
        if is_pod_store(pods):
            return self._schedule_store_inner(pods)
        from ..utils.trace import Progress

        failed: List[UnscheduledPod] = []
        run: List[dict] = []
        # None when disabled so the per-pod loops skip the call entirely
        # (100k no-op advance() calls are measurable on the headline bench)
        progress = Progress("Scheduling pods", len(pods),
                            enabled=not self.disable_progress)
        self._progress = progress if progress.enabled else None
        xr = self._xray_run
        direct = None  # lazy xray batch for pre-bound/homeless direct commits
        for pod in pods:  # simonlint: ignore[per-pod-host-loop] -- dict-batch run split; PodStore batches take _schedule_store_inner
            node_name = (pod.get("spec") or {}).get("nodeName")
            if not node_name:
                run.append(pod)
                continue
            failed.extend(self._schedule_run(run))
            run = []
            if self._progress is not None:
                self._progress.advance(1)
            ni = self.na.index.get(node_name)
            if xr is not None and direct is None:
                direct = xr.new_batch(self.na.names, self._cfg_digest(), [])
            if ni is None:
                # Parity: the reference's fakeclient accepts pods bound to unknown
                # nodes and getClusterNodeStatus (simulator.go:277-301) silently drops
                # them from every report; we keep them findable on self.homeless.
                pod.pop(SIG_MEMO_KEY, None)
                self.homeless.append(pod)
                obs.SCHED_ATTEMPTS.labels(result="homeless").inc()
                if direct is not None:
                    direct.add_pod(xray.pod_key(pod), xray.HOMELESS, -1, -1, -1)
            else:
                self._commit_pod(pod, ni, scheduled=False)
                obs.SCHED_ATTEMPTS.labels(result="bound").inc()
                self._count_commits()
                if direct is not None:
                    direct.add_pod(xray.pod_key(pod), xray.BOUND, ni, -1, -1)
        failed.extend(self._schedule_run(run))
        progress.close()
        if self.gpu_host.enabled:
            self.gpu_host.flush()
        return failed

    def _schedule_store_inner(self, pods: "PodStore") -> List[UnscheduledPod]:
        """The inner loop for a columnar PodStore: the run split
        (pre-bound pods flush the unbound run first — identical serial
        semantics) comes from one vectorized mask instead of a per-pod scan.
        Pre-bound rows materialize (they are read-back pods by definition:
        the direct-commit path touches their dicts); unbound stretches ride
        _schedule_run as store views."""
        failed: List[UnscheduledPod] = []
        self._progress = None  # columnar batches never render progress
        bound = pods.bound_mask()
        if bound is None:
            failed.extend(self._schedule_run(pods))
        else:
            xr = self._xray_run
            direct = None
            n_rows = len(pods)
            bound_idx = np.flatnonzero(bound)
            prev = 0
            # O(bound rows), not O(pods): each iteration is one pre-bound
            # pod plus one store-view run over the unbound stretch before it
            for bi in np.append(bound_idx, n_rows).tolist():
                if bi > prev:
                    failed.extend(self._schedule_run(pods[prev:bi]))
                prev = bi + 1
                if bi >= n_rows:
                    break
                pod = pods[bi]  # materializes: direct commits mutate dicts
                node_name = (pod.get("spec") or {}).get("nodeName")
                ni = self.na.index.get(node_name)
                if xr is not None and direct is None:
                    direct = xr.new_batch(self.na.names, self._cfg_digest(),
                                          [])
                if ni is None:
                    pod.pop(SIG_MEMO_KEY, None)
                    self.homeless.append(pod)
                    obs.SCHED_ATTEMPTS.labels(result="homeless").inc()
                    if direct is not None:
                        direct.add_pod(xray.pod_key(pod), xray.HOMELESS,
                                       -1, -1, -1)
                else:
                    self._commit_pod(pod, ni, scheduled=False)
                    obs.SCHED_ATTEMPTS.labels(result="bound").inc()
                    self._count_commits()
                    if direct is not None:
                        direct.add_pod(xray.pod_key(pod), xray.BOUND, ni,
                                       -1, -1)
        if self.gpu_host.enabled:
            self.gpu_host.flush()
        return failed

    def encode_batch(self, to_schedule: List[dict]) -> BatchTables:
        """Encode a pod batch into device-ready tables (no scheduling). Exposed for
        the bench/graft harnesses and the parallel (mesh-sharded) path."""
        bt = self.encode_batch_raw(to_schedule)
        # Pad encoder-derived axes (G/T/Tc/D/ports/term slots) to pow2 buckets: the
        # encoder interns cumulatively across apps, so without this every
        # ScheduleApp batch would get fresh shapes and a fresh XLA compile.
        bt = pad_encoder_axes(bt)
        # Pad the node axis the same way: the capacity planner re-simulates at N,
        # N+1, N+2... nodes (apply.go:203-259) — bucketed N keeps the XLA compile
        # cache warm across probes. Phantom nodes are infeasible by construction.
        target = bucket_capped(self.na.N, 1024)
        mesh = self._resolve_mesh()
        if mesh is not None:
            # pre-partition at encode time: align the padded node axis to the
            # mesh's shard count here (pow2 buckets already divide pow2 shard
            # counts; this covers the rest), so to_device_sharded's own pad is
            # provably a no-op and every table transfers pre-partitioned
            from ..parallel.mesh import NODE_AXIS

            shards = mesh.shape[NODE_AXIS]
            target += (-target) % shards
        return pad_batch_tables(bt, target)

    def encode_batch_raw(self, to_schedule: List[dict]) -> BatchTables:
        """encode_batch WITHOUT the encoder-axis/node-axis padding: the exact
        per-group/per-counter table content at this simulator's real axis sizes.
        The incremental capacity prober (simulator/probe.py) holds this form so
        its node-axis extension path can append template columns before the
        bucketed pads are applied."""
        faults.maybe_fail("encode")
        batch = self.encode_batch_ids(to_schedule)
        # Pad the scan length to bound compile-cache churn: powers of two up to 2048,
        # then multiples of 2048 (a 10k batch scans 10240 steps, not 16384).
        pad = bucket_capped(len(batch), 2048)
        return build_batch_tables(self.encoder, batch, self.placed, self.match_cache, pad_to=pad)

    def encode_batch_ids(self, to_schedule: List[dict]) -> List[Tuple[int, int]]:
        """The pod-axis half of an encode: (group_id, forced_node) per pod, in
        order, interning new groups into the shared encoder. The serving
        image's micro-batcher (serve/batch.py) calls this alone on its warm
        path — when every group is already interned, a request encode is a
        dict lookup per pod and the resident node-side tables are reused
        untouched."""
        if is_pod_store(to_schedule):
            return self._encode_store_ids(to_schedule)
        batch: List[Tuple[int, int]] = []
        for pod in to_schedule:  # simonlint: ignore[per-pod-host-loop] -- dict-batch encode; PodStore batches take _encode_store_ids
            # strip_daemon_pin can only fire on pods with node affinity; the
            # inline guard keeps the (common) affinity-less pod off the call
            if ((pod.get("spec") or {}).get("affinity")) is not None:
                stripped, target = strip_daemon_pin(pod)
            else:
                stripped, target = pod, None
            if target is None:
                forced, enc_pod = -1, pod
                if SIG_MEMO_KEY not in pod:
                    # memoize so _commit_pod (and repeated encodes) never
                    # recompute; pinned pods keep per-pod signatures below
                    pod[SIG_MEMO_KEY] = scheduling_signature(pod)
            elif target in self.na.index:
                forced, enc_pod = self.na.index[target], stripped
            else:
                # pin to a node this simulator doesn't know: the memo (stamped
                # from the UNPINNED template) must not merge this pod into the
                # unconstrained group — its required matchFields affinity is
                # unsatisfiable and the pinned signature keeps it that way
                forced, enc_pod = -1, pod
                pod.pop(SIG_MEMO_KEY, None)
            batch.append((self.encoder.group_of(enc_pod), forced))
        return batch

    def _encode_store_ids(self, store: PodStore) -> EncodedRows:
        """encode_batch_ids for a columnar store view: one group interning +
        daemon-pin decision per TEMPLATE (not per pod), then a vectorized
        gather maps the decisions over the rows. Byte-identical to the
        per-pod path: replicas of one template are scheduling-identical, so
        the per-template (group, forced) pair IS each row's pair."""
        b = store.base
        tmpl_rows = store.tmpl_rows()
        n_t = len(b.templates)
        tg = np.zeros(n_t, np.int32)
        tf = np.full(n_t, -1, np.int32)
        for ti in np.unique(tmpl_rows).tolist():
            tmpl = b.templates[ti]
            if ((tmpl.get("spec") or {}).get("affinity")) is not None:
                stripped, target = strip_daemon_pin(tmpl)
            else:
                stripped, target = tmpl, None
            if target is None:
                # transient memo: group_of must not recompute the signature,
                # and the shared template must not keep the marker (lazy
                # blobs would otherwise bake it into materialized pods)
                tmpl[SIG_MEMO_KEY] = b.sigs[ti]
                try:
                    tg[ti] = self.encoder.group_of(tmpl)
                finally:
                    tmpl.pop(SIG_MEMO_KEY, None)
            elif target in self.na.index:
                tf[ti] = self.na.index[target]
                tg[ti] = self.encoder.group_of(stripped)
            else:
                # pinned to an unknown node: the RAW template signature keeps
                # the unsatisfiable matchFields pin (engine parity — see the
                # per-pod path's memo handling)
                tg[ti] = self.encoder.group_of(tmpl)
        return EncodedRows(tg[tmpl_rows], tf[tmpl_rows])

    def _kernel_ns(self, donate: bool = True):
        """The dispatch namespace for this simulator: the plain `kernels`
        module single-device, or the mesh's cached sharded-executable set
        (parallel/mesh.py ShardedKernels — explicit in/out shardings so
        chained segments never reshard the carry, donate_argnums so the
        carry updates in place). `donate=False` keeps every dispatch's input
        carry alive — required while the xray recorder reads segment-start
        carries after the dispatch loop. Returns (namespace, sharded)."""
        mesh = self._resolve_mesh()
        if mesh is None:
            return kernels, False
        from ..parallel.mesh import sharded_kernels

        return sharded_kernels(mesh, donate=donate), True

    def _audit_reshard(self, ns, carry) -> None:
        """Count any carry leaf whose layout left the declared shardings
        (simon_reshard_bytes_total; 0 on every sharded-executable path)."""
        from ..parallel.mesh import carry_reshard_bytes

        b = carry_reshard_bytes(carry, ns.carry_sh)
        if b:
            obs.RESHARD_BYTES.inc(b)

    def _route_digest(self) -> tuple:
        """Everything _wave_eligibility reads besides the (immutable) group:
        score weights, filter flags, and the break-even knob. Routing cached
        per group must be invalidated when any of these change on a reused
        Simulator (mutating filter_flags used to return stale routing)."""
        return (self.score_w, self.filter_flags, self._spread_wave_min_domains)

    def _wave_eligibility(self, gi: int) -> "GroupRoute":
        """Route group gi to its scheduling kernel — see ops/kernels.py.

        kind="wave": the group's placements cannot change any predicate or
        score input it reads itself (no storage state, no live counter/term),
        so schedule_wave commits whole score-table prefixes. Two
        self-interactions are exactly per-node capacity-1 clamps (cap1):
        hostname-topology required self-anti-affinity, and host ports while
        NodePorts is enabled. Shared-GPU requests stay unit-countable waves
        (gpu_live) unless they carry a pre-assigned gpu-index (host-driven →
        serial).

        kind="affinity": counter-live hard predicates — self-matching
        DoNotSchedule spread terms at any topology cardinality, required
        self-affinity (aff_live), non-hostname required self-anti-affinity in
        either direction (anti_live), and/or a live SelectorSpread score on
        an unzoned cluster — ride schedule_affinity_wave's epoch-batched
        multi-round machinery. At most ONE budget-consuming live term (self
        DNS or self anti) may be present: the multi-round proof does not
        compose across interacting budgets.

        kind="spread": the fused group-serial scan — ScheduleAnyway terms
        (sa_live), zoned live SelectorSpread (the zone blend moves with
        every placement, so wave epochs degenerate to single picks), and
        multi-term live DNS groups. The knob
        OPEN_SIMULATOR_SPREAD_WAVE_MIN_DOMAINS=k also reroutes live-DNS
        groups below k domains here (break-even fallback; default 0 = the
        wave always runs, placements are exact on either path).

        kind=None: the general serial scan — the parity oracle and the home
        of storage state, self-matching PREFERRED affinity (its score term
        moves non-uniformly), gpu+counter-live combinations, and sa_live
        mixed with affinity liveness."""
        digest = self._route_digest()
        if digest != self._wave_elig_key:
            self._wave_elig_cache.clear()
            self._wave_elig_key = digest
        got = self._wave_elig_cache.get(gi)
        if got is not None:
            return got
        enc = self.encoder
        g = enc.group_list[gi]
        from .encode import HOSTNAME

        tmpl = g.template
        cap1 = False
        spread_live = (any(selfm for _, _, selfm in g.spread_dns)
                       and self.filter_flags.spread)
        # shared-GPU groups are unit-countable (kernels.schedule_wave gpu_live)
        # unless they carry a pre-assigned gpu-index (host-driven path → serial)
        gpu_live = g.gpu_mem > 0 and g.gpu_pre_ids is None
        # live SelectorSpread: the default spread selector always matches the
        # group's own pods, so the score moves with every placement. A zero
        # SelectorSpread weight makes the term inert (plain-wave eligible).
        ss_live = g.ss_counter >= 0 and self.score_w.ss != 0
        # soft (ScheduleAnyway) spread terms: counters and relevant-set
        # normalizers move with every placement — live in the fused kernel.
        sa_live = bool(g.spread_sa) and self.score_w.pts != 0
        serial = GroupRoute(None, False, False, False, False)
        if (g.gpu_mem > 0 and not gpu_live) or g.lvm_sizes or g.sdev_sizes:
            got = serial  # host-mirrored gpu/storage state → serial scan
        else:
            # host-port groups: the first copy claims the port, so the group
            # is exactly a capacity-1-per-node wave (conflicts vs other pods
            # are in the carry's port table; the aggregate commit writes bits)
            if g.ports and self.filter_flags.ports:
                cap1 = True
            aff_live = anti_live = pref_live = False
            budget_terms = sum(1 for _, _, selfm in g.spread_dns if selfm
                               ) if spread_live else 0
            if self.filter_flags.interpod:
                for cid in g.req_aff:
                    if enc.counter_list[cid].matches_pod(tmpl):
                        aff_live = True
                for cid in g.req_anti:
                    cs = enc.counter_list[cid]
                    if cs.matches_pod(tmpl):
                        if cs.topo_key == HOSTNAME:
                            cap1 = True
                        else:
                            anti_live = True
                            budget_terms += 1
                for cs in g.carried:
                    if cs.use == "anti" and cs.matches_pod(tmpl):
                        if cs.topo_key == HOSTNAME:
                            cap1 = True
                        else:
                            anti_live = True
                            budget_terms += 1
            for cid, _ in g.pref:
                if enc.counter_list[cid].matches_pod(tmpl):
                    pref_live = True  # live ip SCORE term, weight-signed
            counter_live = spread_live or ss_live or aff_live or anti_live
            # zoned SelectorSpread moves the zone blend with every placement:
            # affinity-wave epochs degenerate to single picks there, while
            # the fused scan stays one cheap step per pod
            ss_zoned = ss_live and len(self.na.zones) > 0
            low_domains = spread_live and not all(
                not selfm or self._domain_count(cid) >= self._spread_wave_min_domains
                for cid, _, selfm in g.spread_dns)
            if pref_live or (gpu_live and (counter_live or sa_live)):
                got = serial
            elif aff_live or anti_live:
                # required-affinity/anti liveness: only the affinity wave
                # evaluates these gates live; sa scoring does not compose.
                # Non-composing budget combinations (kernel budget_composes)
                # degrade to the wave's exact head-pick epochs, still no
                # worse than the serial scan's [T, N]-gather steps.
                got = (serial if sa_live
                       else GroupRoute("affinity", cap1, False, ss_live, False))
            elif sa_live or ss_zoned or budget_terms > 1 or (
                    spread_live and low_domains):
                # every disjunct implies dns/ss/sa liveness: fused group-serial
                got = GroupRoute("spread", cap1, False, ss_live, sa_live)
            elif spread_live or ss_live:
                got = GroupRoute("affinity", cap1, False, ss_live, False)
            else:
                got = GroupRoute("wave", cap1, gpu_live, False, False)
        self._wave_elig_cache[gi] = got
        return got

    def _domain_count(self, cid: int) -> int:
        """Number of distinct domains a counter's topology key has on this
        cluster (cached per topology key) — the epoch-wave routing signal."""
        key = self.encoder.counter_list[cid].topo_key
        got = self._domain_count_cache.get(key)
        if got is None:
            dom = self.na.domain_of(key)
            got = self._domain_count_cache[key] = int(len(np.unique(dom[dom >= 0])))
        return got

    def _segments(self, bt: BatchTables, P: int) -> List[tuple]:
        """Split the batch into maximal runs of one (group, forced) pair;
        routed runs of >= WAVE_MIN become ('wave', start, len, g, cap1,
        gpu_live), ('affinity', start, len, g, cap1, ss_live), or
        ('spread', start, len, g, cap1, ss_live, sa_live) segments, the rest
        coalesce into ('serial', start, len) chunks."""
        pg = np.asarray(bt.pod_group[:P])
        fn = np.asarray(bt.forced_node[:P])
        # vectorized run boundaries: one np.diff pass instead of a per-pod loop
        change = np.flatnonzero((np.diff(pg) != 0) | (np.diff(fn) != 0)) + 1
        starts = np.concatenate([[0], change])
        ends = np.concatenate([change, [P]])
        segs: List[tuple] = []
        ser_start: Optional[int] = None
        for i, j in zip(starts.tolist(), ends.tolist()):
            g, f = int(pg[i]), int(fn[i])
            run = j - i
            route = (self._wave_eligibility(g) if f < 0
                     else GroupRoute(None, False, False, False, False))
            if route.kind is not None and run >= WAVE_MIN:
                if ser_start is not None:
                    segs.append(("serial", ser_start, i - ser_start))
                    ser_start = None
                if route.kind == "spread":
                    segs.append(("spread", i, run, g, route.cap1,
                                 route.ss_live, route.sa_live))
                elif route.kind == "affinity":
                    segs.append(("affinity", i, run, g, route.cap1,
                                 route.ss_live))
                else:
                    segs.append(("wave", i, run, g, route.cap1,
                                 route.gpu_live))
            elif ser_start is None:
                ser_start = i
        if ser_start is not None:
            segs.append(("serial", ser_start, P - ser_start))
        return segs

    def _schedule_run(self, to_schedule: List[dict]) -> List[UnscheduledPod]:
        failed: List[UnscheduledPod] = []
        if not to_schedule:
            return failed

        if self.na.N == 0:
            obs.SCHED_ATTEMPTS.labels(result="unschedulable").inc(len(to_schedule))
            out = [
                UnscheduledPod(pod, self._format_reason(pod, {}, 0))
                for pod in to_schedule
            ]
            if self._xray_run is not None:
                xb = self._xray_run.new_batch([], self._cfg_digest(), [])
                for u in out:
                    xb.add_pod(xray.pod_key(u.pod), xray.UNSCHEDULABLE, -1,
                               -1, -1, reason=u.reason)
            return out
        chunk = self._stream_chunk
        if chunk and is_pod_store(to_schedule) and not self._stream_explicit:
            # Columnar batches have no per-pod host encode to overlap and
            # their per-run buffers are already O(templates) + a few [P]
            # arrays — chunking them only re-pays the node-axis table build
            # per chunk. By default stream only to bound the [P] working set
            # at extreme sizes (the 10M-pod row runs as a handful of
            # chunks); an EXPLICIT OPEN_SIMULATOR_STREAM_PODS applies as-is
            # (the bench-gate RSS workload pins a small chunk on purpose).
            chunk = max(chunk, 2_097_152)
        if chunk and len(to_schedule) > chunk:
            return self._schedule_run_streaming(to_schedule, chunk)
        try:
            return self._schedule_run_once(to_schedule)
        except BaseException as e:
            site = guard.oom_site(e)
            if site is None:
                raise
            return self._bisect_oom(to_schedule, site, e)

    def _schedule_run_streaming(self, to_schedule,
                                chunk: int) -> List[UnscheduledPod]:
        """Streaming segment encode: a run longer than
        OPEN_SIMULATOR_STREAM_PODS schedules as fixed-size chunks, each an
        ordinary _schedule_run — the OOM-bisection bit-identity argument
        (tests/test_guard.py) makes the chunked run's placements provably
        identical to the monolithic one, because chunk k's commits seed
        chunk k+1's encode exactly as the serial loop would have.

        Double buffering: while chunk k's dispatch is in flight, a worker
        thread computes chunk k+1's scheduling signatures (the dominant
        per-pod encode cost for dict batches; columnar stores need no
        prefetch — their encode is already O(templates)). The worker touches
        ONLY chunk k+1's pod dicts and only stamps the same memo the main
        thread would compute, so interning order — and therefore every table
        — is untouched. guard-compat: all device work stays on this thread
        under the usual watchdog; a failure joins the worker, then the
        transaction rolls the whole call back and failover replays it, so
        crash/failover semantics are exactly the unstreamed ones. Memory:
        per-chunk tables/choices cap the host working set instead of scaling
        with the full run (the bench-gate RSS budget leans on this)."""
        import threading

        P = len(to_schedule)
        failed: List[UnscheduledPod] = []
        starts = list(range(0, P, chunk))
        use_prefetch = not is_pod_store(to_schedule)
        worker: Optional[threading.Thread] = None

        def prefetch(pods_slice) -> None:
            try:
                for pod in pods_slice:
                    # (iter name is chunk-local, not the whole batch: this is
                    # the O(chunk) prefetch the streaming path exists for)
                    # pin-carrying pods keep their main-thread treatment
                    # (strip_daemon_pin decides their memo semantics)
                    if ((pod.get("spec") or {}).get("affinity")) is not None:
                        continue
                    if SIG_MEMO_KEY not in pod:
                        pod[SIG_MEMO_KEY] = scheduling_signature(pod)
            except Exception:  # simonlint: ignore[swallowed-exception] -- pure precompute; the main thread recomputes and raises the real error
                pass

        try:
            for k, off in enumerate(starts):
                if worker is not None:
                    worker.join()
                    worker = None
                if use_prefetch and k + 1 < len(starts):
                    nxt = to_schedule[starts[k + 1]:
                                      min(starts[k + 1] + chunk, P)]
                    worker = threading.Thread(
                        target=prefetch, args=(nxt,), daemon=True,
                        name="simon-stream-prefetch")
                    worker.start()
                obs.STREAM_CHUNKS.inc()
                failed.extend(
                    self._schedule_run(to_schedule[off:min(off + chunk, P)]))
        finally:
            if worker is not None:
                worker.join()
        return failed

    def _bisect_oom(self, to_schedule: List[dict], site: str,
                    err: BaseException) -> List[UnscheduledPod]:
        """Contain a device OOM by scheduling the batch as two halves.

        The engine's serial-order semantics make split and unsplit runs
        bit-identical: the first half's commits seed the second half's
        encode exactly as the serial loop would have (tests/test_guard.py
        proves it, odd sizes included). Recursion halves down to the
        bisection floor; an OOM that persists there is structural —
        OOMBisectionExhausted hands the call to the backend failover."""
        floor = guard.oom_bisect_floor()
        if len(to_schedule) <= floor:
            guard.record_event("oom_exhausted", site, len(to_schedule))
            raise guard.OOMBisectionExhausted(
                site, len(to_schedule), floor) from err
        obs.GUARD_OOM_BISECTIONS.labels(site=site).inc()
        guard.record_event("oom_bisect", site, len(to_schedule))
        mid = len(to_schedule) // 2
        failed = self._schedule_run(to_schedule[:mid])
        failed.extend(self._schedule_run(to_schedule[mid:]))
        return failed

    def _schedule_run_once(self, to_schedule: List[dict]) -> List[UnscheduledPod]:
        from ..utils.trace import Span

        # simonpulse run window: dispatch records inside carry this run's id;
        # the run record closes with the LIVE pod count (supervised sees
        # padded counts — useless for attempts reconciliation) and the
        # encode/to_device/dispatch/fetch/commit wall decomposition.
        with pulse.run_window(len(to_schedule)), \
                Span("schedule_run", log_if_longer=30.0) as span:
            t_enc = time.perf_counter()
            bt = self.encode_batch(to_schedule)
            dt_enc = time.perf_counter() - t_enc
            obs.ENCODE_SECONDS.observe(dt_enc)
            obs.ENCODE_BYTES.inc(batch_tables_nbytes(bt))
            obs.BATCH_PODS.observe(len(to_schedule))
            pulse.phase("encode", dt_enc)
            span.step("encode")
            t_dev = time.perf_counter()
            tables, carry = self._to_device(bt)
            pulse.phase("to_device", time.perf_counter() - t_dev)
            span.step("to_device")
            failed = self._dispatch_and_commit(to_schedule, bt, tables, carry,
                                               span)
        return failed

    def _dispatch_and_commit(self, to_schedule: List[dict], bt: BatchTables,
                             tables, carry, span) -> List[UnscheduledPod]:
        failed: List[UnscheduledPod] = []
        enable_gpu, enable_storage = plugin_flags(bt)
        self._last_flags = (enable_gpu, enable_storage)
        jnp = _jax()
        P = len(to_schedule)
        choices = np.full(P, -1, np.int32)  # node indices; matches the kernels' i32 outputs
        segs = self._segments(bt, P) if self.use_waves else [("serial", 0, P)]
        dims = self._dispatch_dims(bt)
        for seg in segs:
            obs.SEGMENTS.labels(kind=seg[0]).inc()
            obs.SEGMENT_PODS.labels(kind=seg[0]).inc(seg[2])
        # simonxray: stage one batch record per dispatch run. want_stats also
        # turns on the affinity kernel's epoch counters for the segment-timing
        # breakdown (a distinct compiled program — the flag joins its dispatch
        # signature below, so stats/no-stats shapes never alias).
        xr = self._xray_run
        want_stats = xr is not None or self._segment_timing
        aff_stats: Dict[int, object] = {}  # outs index -> [3] i32 device array
        # Sharded executables + donation: the carry buffers chain in place
        # between segments. Donation is OFF while recording — the xray
        # decision sets are evaluated against segment-START carries AFTER the
        # dispatch loop, so every segment's input must stay alive then.
        donate = xr is None
        kns, sharded = self._kernel_ns(donate=donate)
        if sharded:
            dims["donate"] = donate  # donating/kept-alive are distinct
            # executables; never alias their compile-cache signatures
        xb = (xr.new_batch(self.na.names, dims["cfg"],
                           [{"kind": s[0], "start": s[1], "len": s[2],
                             "group": (s[3] if len(s) > 3 else -1)}
                            for s in segs])
              if xr is not None else None)
        carry0 = carry  # the pre-batch carry: segment k's START state is
        #                 outs[k-1]'s end carry, or this for k == 0
        # Dispatch every segment asynchronously and fetch ONE concatenated
        # result at the end: the chip may sit behind a tunnel, so a per-segment
        # np.asarray costs a full round trip — 50 segments used to spend ~7s
        # waiting on ~35ms of actual device work. `placed` is recovered on the
        # host as sum(counts), never fetched separately.
        outs: List[tuple] = []  # (seg, device array, carry AFTER the segment)
        t_disp = time.perf_counter()
        for seg in segs:
            faults.maybe_fail("dispatch")
            faults.maybe_fail("oom_dispatch")
            t_seg = time.perf_counter() if self._segment_timing else 0.0
            if seg[0] == "serial":
                _, start, length = seg
                pad = bucket_capped(length, 2048)
                pg = np.zeros(pad, np.int32)
                pg[:length] = bt.pod_group[start:start + length]
                fn = np.full(pad, -1, np.int32)
                fn[:length] = bt.forced_node[start:start + length]
                vd = np.zeros(pad, bool)
                vd[:length] = True
                obs.record_dispatch("schedule_batch", P=pad, zones=bt.n_zones,
                                    gpu=enable_gpu, storage=enable_storage,
                                    **dims)
                call = functools.partial(
                    kns.schedule_batch,
                    tables, carry, pg, fn, vd,
                    n_zones=bt.n_zones, enable_gpu=enable_gpu,
                    enable_storage=enable_storage,
                    w=self.score_w, filters=self.filter_flags,
                )
                carry, ch = guard.supervised(call, site="dispatch", pods=pad)
                outs.append((seg, ch, carry))
            elif seg[0] == "spread":
                _, start, length, g, cap1, ss_live, sa_live = seg
                pad = bucket_capped(length, 2048)
                vd = np.zeros(pad, bool)
                vd[:length] = True
                obs.record_dispatch("schedule_group_serial", P=pad, ss=ss_live,
                                    sa=sa_live,
                                    zones=bt.n_zones if ss_live else 2, **dims)
                call = functools.partial(
                    kns.schedule_group_serial,
                    tables, carry, np.int32(g), vd, np.bool_(cap1),
                    w=self.score_w, filters=self.filter_flags,
                    # n_zones only shapes the ss_live zone table; pin it for
                    # DNS-only segments so new zone labels don't recompile them
                    ss_live=ss_live, sa_live=sa_live,
                    n_zones=bt.n_zones if ss_live else 2,
                )
                carry, counts, _ = guard.supervised(
                    call, site="dispatch", pods=pad)
                outs.append((seg, counts, carry))
            elif seg[0] == "affinity":
                # counter-live hard predicates (self spread/affinity/anti,
                # live SelectorSpread): epoch-batched affinity wave instead
                # of one pod per scan step
                _, start, length, g, cap1, ss_live = seg
                block = kernels.wave_block_for(length, self.na.N)
                obs.record_dispatch("schedule_affinity_wave", block=block,
                                    ss=ss_live,
                                    zones=bt.n_zones if ss_live else 2, **dims,
                                    **({"stats": True} if want_stats else {}))
                call = functools.partial(
                    kns.schedule_affinity_wave,
                    tables, carry, np.int32(g), np.int32(length),
                    np.bool_(cap1), ss_live=ss_live,
                    w=self.score_w, filters=self.filter_flags,
                    block=block,
                    n_zones=bt.n_zones if ss_live else 2,
                    stats=want_stats,
                )
                if want_stats:
                    carry, counts, _, stv = guard.supervised(
                        call, site="dispatch", pods=length)
                    aff_stats[len(outs)] = stv
                else:
                    carry, counts, _ = guard.supervised(
                        call, site="dispatch", pods=length)
                outs.append((seg, counts, carry))
            else:
                _, start, length, g, cap1, gpu_live = seg
                block = kernels.wave_block_for(length, self.na.N)
                kmax = kernels.wave_kmax(length, self.na.N, block)
                obs.record_dispatch("schedule_wave", block=block, k=kmax,
                                    gpu_live=gpu_live, **dims)
                call = functools.partial(
                    kns.schedule_wave,
                    tables, carry, np.int32(g), np.int32(length),
                    np.bool_(cap1), gpu_live=gpu_live,
                    w=self.score_w, filters=self.filter_flags,
                    block=block, kmax=kmax,
                )
                carry, counts, _ = guard.supervised(
                    call, site="dispatch", pods=length)
                outs.append((seg, counts, carry))
            if sharded:
                self._audit_reshard(kns, carry)
            if self._segment_timing:
                # per-kind wall attribution (bench breakdown): forces the
                # async dispatch to finish, so only ever enabled explicitly
                import jax as _jax_mod

                # simonlint: ignore[fetch-in-wave-loop] -- the per-segment block IS the measurement (OPEN_SIMULATOR_SEGMENT_TIMING bench-attribution runs only)
                _jax_mod.block_until_ready(outs[-1][1])
                obs.SEGMENT_WALL.labels(kind=seg[0]).inc(
                    time.perf_counter() - t_seg)
        t_fetch = time.perf_counter()
        pulse.phase("dispatch", t_fetch - t_disp)
        span.step("dispatch")
        final_carry = carry
        seg_of = np.zeros(P, np.int32)
        if outs:
            faults.maybe_fail("fetch")
            # every kernel returns i32 counts/choices; fetch each (one
            # pipeline drain — dispatches are async) and stitch on the host,
            # avoiding 2 eager device ops per segment
            flat = guard.supervised(
                lambda: np.concatenate(
                    [np.asarray(a, np.int32) for _, a, _ in outs]),
                site="fetch", pods=P)
            off = 0
            for k, (seg, a, _) in enumerate(outs):
                part = flat[off:off + a.shape[0]]
                off += a.shape[0]
                start, length = seg[1], seg[2]
                seg_of[start:start + length] = k
                if seg[0] == "serial":
                    choices[start:start + length] = part[:length]
                else:
                    counts = part
                    placed = int(counts.sum())
                    # pods of one group are interchangeable: assign in node
                    # order; the (length - placed) unschedulable pods stay -1
                    assign = np.repeat(np.arange(counts.shape[0]), counts)
                    choices[start:start + placed] = assign[:placed]
        if aff_stats:
            # ONE packed fetch for every affinity segment's epoch counters
            # (the designated spill point — never a fetch per segment), then
            # per-segment step events so the PR 6 fast path shows up in the
            # Chrome trace instead of one opaque dispatch block
            order = sorted(aff_stats)
            vals = guard.supervised(
                lambda: np.asarray(jnp.stack([aff_stats[k] for k in order])),
                site="fetch", pods=len(order))
            for k, v in zip(order, vals):
                st = {"epochs": int(v[0]), "head_fallbacks": int(v[1]),
                      "rounds": int(v[2])}
                g = segs[k][3]
                span.step(f"affinity[g={g}] epochs={st['epochs']} "
                          f"rounds={st['rounds']} "
                          f"head_fallbacks={st['head_fallbacks']}")
                if xb is not None:
                    xb.segments[k]["stats"] = st
        # Carry snapshots for failure diagnosis against the state the pod
        # actually failed under (the end of ITS segment) — much closer to the
        # reference's mid-batch FitErrors than end-of-batch state. Retained
        # ONLY for segments that contain a failure: holding every segment's
        # carry would multiply peak device memory by the segment count.
        fail_mask = choices[:P] < 0
        if fail_mask.any() and not (sharded and donate):
            seg_carry_of: Dict[int, object] = {
                int(k): outs[int(k)][2] for k in np.unique(seg_of[fail_mask])
            }
        else:
            # Donated chain: intermediate carry buffers were consumed in
            # place, so failure diagnosis evaluates against the end-of-batch
            # carry instead of the failing segment's end state. Reason DETAIL
            # may differ from the single-device path by the trailing
            # segments' placements (a documented deviation, like the serial
            # path's per-attempt vs segment-end gap); placement itself is
            # identical on both paths.
            seg_carry_of = {}
        if xr is not None:
            # decision sets are evaluated against segment-START state (what
            # the segment's first pick saw); keep those carries until the
            # per-pod loop below has built every referenced set
            seg_start_carry: Dict[int, object] = {
                k: (outs[k - 1][2] if k > 0 else carry0)
                for k in range(len(outs))
            }
        else:
            seg_start_carry = {}
        outs = None  # drop the per-segment carry references
        self._last_tables, self._last_carry = bt, final_carry
        pulse.phase("fetch", time.perf_counter() - t_fetch)
        span.step("fetch")

        progress = getattr(self, "_progress", None)
        reason_cache: Dict[Tuple[int, int, int], Dict[str, int]] = {}
        set_cache: Dict[Tuple[int, int, int], int] = {}  # key -> run-local sid

        def xray_sid(key: Tuple[int, int, int]) -> int:
            """Decision set for a (group, forced, segment) key, built once per
            key per batch against the segment-START carry."""
            sid = set_cache.get(key)
            if sid is None:
                s = self._xray_set(key, tables,
                                   seg_start_carry.get(key[2], carry0), bt)
                sid = set_cache[key] = xr.add_set(s)
            return sid

        t_commit = time.perf_counter()
        # Vectorized bulk commit (simulator/store.py): a columnar batch with
        # the per-pod bookkeeping provably unneeded — no flight recorder, no
        # armed preemption (which needs per-pod _sig_of rows), no
        # gpu/local-storage ledgers (whose reserve() writes per-pod
        # annotations) — applies the whole run's placements as array ops.
        # Everything else takes the per-pod loop below, which materializes
        # store rows transparently.
        if (is_pod_store(to_schedule) and xb is None
                and not self._preempt_armed
                and not self.gpu_host.enabled
                and not self.local_host.enabled):
            failed.extend(self._commit_store_bulk(
                to_schedule, bt, choices, P, seg_of, seg_carry_of,
                final_carry, tables))
        else:
            if xb is not None:
                # plain-int views once per batch: per-pod numpy-scalar casts
                # on a 100k loop are a measurable slice of recording overhead
                pg_l = bt.pod_group[:P].tolist()
                fn_l = bt.forced_node[:P].tolist()
                seg_l = seg_of.tolist()
            for i, pod in enumerate(to_schedule):  # simonlint: ignore[per-pod-host-loop] -- store-less fallback; columnar batches ride _commit_store_bulk
                if progress is not None:
                    progress.advance(1)
                node_i = int(choices[i])
                if xb is not None:
                    key = (pg_l[i], fn_l[i], seg_l[i])
                elif node_i < 0:
                    key = (int(bt.pod_group[i]), int(bt.forced_node[i]),
                           int(seg_of[i]))
                else:
                    key = None
                if node_i >= 0:
                    self._commit_pod(pod, node_i)
                    if xb is not None:
                        xb.add_pod(xray.pod_key(pod), xray.SCHEDULED, node_i,
                                   key[2], xray_sid(key), group=key[0])
                else:
                    # Pods of one group share tolerations/requests, so the
                    # per-stage failure counts are identical — diagnose once
                    # per (group, forced, segment), against that segment's
                    # end state.
                    reasons = reason_cache.get(key)
                    if reasons is None:
                        reasons = reason_cache[key] = self._explain_reasons(
                            pod, key[0], key[1], tables,
                            seg_carry_of.get(int(seg_of[i]), final_carry)
                        )
                    pod.pop(SIG_MEMO_KEY, None)
                    obs.record_filter_reasons(reasons)
                    reason = self._format_reason(pod, reasons, self.na.N)
                    if xb is not None:
                        sid = xray_sid(key)
                        xr.sets[sid][1].reasons = dict(reasons)
                        xb.add_pod(xray.pod_key(pod), xray.UNSCHEDULABLE, -1,
                                   key[2], sid, group=key[0], reason=reason)
                    failed.append(UnscheduledPod(pod, reason))
        dt_commit = time.perf_counter() - t_commit
        obs.HOST_COMMIT_SECONDS.observe(dt_commit)
        pulse.phase("commit", dt_commit)
        placed_n = P - len(failed)
        obs.SCHED_ATTEMPTS.labels(result="scheduled").inc(placed_n)
        if failed:
            obs.SCHED_ATTEMPTS.labels(result="unschedulable").inc(len(failed))
        self._count_commits(placed_n)
        span.step("commit")
        if xb is not None:
            # the schedule_run span carries this batch's decision summary
            # into /debug/vars and the Chrome trace (obs/chrome.py args)
            span.annotate("xray", {
                "pods": P, "scheduled": placed_n, "unscheduled": len(failed),
                "decision_sets": len(set_cache), "segments": xb.segments,
                "unscheduled_sample": [
                    {"pod": u.pod.get("metadata", {}).get("name"),
                     "reason": u.reason} for u in failed[:8]],
            })
        return failed

    # ------------------------------------------------------------- probing -------

    def probe_pods(self, pods: List[dict]) -> Tuple[int, int]:
        """Capacity-probe scheduling: how many of `pods` would schedule, without
        materializing placements. Pre-bound pods commit normally (they are
        cluster state the probe must account); every unbound pod joins ONE
        device run whose results are counted but never written back — no pod
        mutation, no placed records, no failure diagnosis. Pods keep their
        signature memos, so repeated probes over the same list skip the
        per-pod encoding cost. Returns (scheduled, total).

        Caveats the caller must own (CapacityPlanner.try_build guards both):
        pre-bound pods all commit BEFORE the unbound run regardless of list
        position, and pods bound to unknown nodes are dropped from the totals
        exactly as schedule_pods drops them from every report (engine.py
        homeless handling) — they are not schedulable failures.

        The capacity planner's probe loop (apply.go:203-259 re-simulates the
        whole workload per candidate node count) is the intended caller; the
        authoritative placement run remains schedule_pods. Transactional like
        schedule_pods: a failure rolls back the pre-bound commits (and their
        pod-dict status writes — probe pods belong to the CALLER).

        Containment: a wedge/OOM fails the whole probe over to the CPU
        fallback and re-runs it there (probes are never BISECTED — splitting
        a probe run would let the second half see placements the first never
        committed, changing the counted semantics)."""
        from ..obs import scope

        def attempt():
            self._xray_run = xray.begin_run("probe")
            with self._transaction():
                return self._probe_pods_inner(pods)

        sc = scope.active()
        cm = (sc.span("engine.probe_pods", cat="engine", pods=len(pods))
              if sc is not None else contextlib.nullcontext())
        try:
            with cm:
                result = self._run_contained(attempt)
            if self._xray_run is not None:
                # probes never materialize placements: one summary record
                # (counts + backend_path) per call, no per-pod rows
                self._xray_run.add_probe(result[0], result[1])
            self._xray_commit()
            return result
        finally:
            self._xray_run = None

    def _probe_pods_inner(self, pods: List[dict]) -> Tuple[int, int]:
        run: List[dict] = []
        scheduled = 0
        homeless = 0
        if is_pod_store(pods) and pods.bound_mask() is None:
            run = pods  # columnar fast path: no pre-bound rows, no per-pod scan
        else:
            for pod in pods:  # simonlint: ignore[per-pod-host-loop] -- pre-bound split for dict batches (stores carrying bound rows materialize by definition)
                node_name = (pod.get("spec") or {}).get("nodeName")
                if not node_name:
                    run.append(pod)
                    continue
                ni = self.na.index.get(node_name)
                if ni is None:
                    homeless += 1
                    self.homeless.append(pod)
                else:
                    self._commit_pod(pod, ni, scheduled=False)
                    scheduled += 1
        total_known = len(pods) - homeless
        if not run:
            return scheduled, total_known
        if self.na.N == 0:
            return scheduled, total_known
        bt = self.encode_batch(run)
        obs.ENCODE_BYTES.inc(batch_tables_nbytes(bt))
        tables, carry = self._to_device(bt)
        enable_gpu, enable_storage = plugin_flags(bt)
        jnp = _jax()
        P = len(run)
        segs = self._segments(bt, P) if self.use_waves else [("serial", 0, P)]
        dims = self._dispatch_dims(bt)
        # probes never stage xray decision sets against mid-batch carries, so
        # the donated sharded chain is always safe here
        kns, sharded = self._kernel_ns(donate=True)
        if sharded:
            dims["donate"] = True
        placed_parts = []
        for seg in segs:
            faults.maybe_fail("dispatch")
            faults.maybe_fail("oom_dispatch")
            if seg[0] == "serial":
                _, start, length = seg
                pad = bucket_capped(length, 2048)
                pg = np.zeros(pad, np.int32)
                pg[:length] = bt.pod_group[start:start + length]
                fn = np.full(pad, -1, np.int32)
                fn[:length] = bt.forced_node[start:start + length]
                vd = np.zeros(pad, bool)
                vd[:length] = True
                obs.record_dispatch("schedule_batch", P=pad, zones=bt.n_zones,
                                    gpu=enable_gpu, storage=enable_storage,
                                    **dims)
                call = functools.partial(
                    kns.schedule_batch,
                    tables, carry, pg, fn, vd,
                    n_zones=bt.n_zones, enable_gpu=enable_gpu,
                    enable_storage=enable_storage,
                    w=self.score_w, filters=self.filter_flags,
                )
                carry, ch = guard.supervised(call, site="dispatch", pods=pad)
                placed_parts.append(jnp.sum((ch >= 0).astype(jnp.int32)))
            elif seg[0] == "spread":
                _, start, length, g, cap1, ss_live, sa_live = seg
                pad = bucket_capped(length, 2048)
                vd = np.zeros(pad, bool)
                vd[:length] = True
                obs.record_dispatch("schedule_group_serial", P=pad, ss=ss_live,
                                    sa=sa_live,
                                    zones=bt.n_zones if ss_live else 2, **dims)
                call = functools.partial(
                    kns.schedule_group_serial,
                    tables, carry, np.int32(g), vd, np.bool_(cap1),
                    w=self.score_w, filters=self.filter_flags,
                    # n_zones only shapes the ss_live zone table; pin it for
                    # DNS-only segments so new zone labels don't recompile them
                    ss_live=ss_live, sa_live=sa_live,
                    n_zones=bt.n_zones if ss_live else 2,
                )
                carry, _, placed = guard.supervised(
                    call, site="dispatch", pods=pad)
                placed_parts.append(placed)
            elif seg[0] == "affinity":
                _, start, length, g, cap1, ss_live = seg
                block = kernels.wave_block_for(length, self.na.N)
                obs.record_dispatch("schedule_affinity_wave", block=block,
                                    ss=ss_live,
                                    zones=bt.n_zones if ss_live else 2, **dims)
                call = functools.partial(
                    kns.schedule_affinity_wave,
                    tables, carry, np.int32(g), np.int32(length),
                    np.bool_(cap1), ss_live=ss_live,
                    w=self.score_w, filters=self.filter_flags,
                    block=block,
                    n_zones=bt.n_zones if ss_live else 2,
                )
                carry, _, placed = guard.supervised(
                    call, site="dispatch", pods=length)
                placed_parts.append(placed)
            else:
                _, start, length, g, cap1, gpu_live = seg
                block = kernels.wave_block_for(length, self.na.N)
                kmax = kernels.wave_kmax(length, self.na.N, block)
                obs.record_dispatch("schedule_wave", block=block, k=kmax,
                                    gpu_live=gpu_live, **dims)
                call = functools.partial(
                    kns.schedule_wave,
                    tables, carry, np.int32(g), np.int32(length),
                    np.bool_(cap1), gpu_live=gpu_live,
                    w=self.score_w, filters=self.filter_flags,
                    block=block, kmax=kmax,
                )
                carry, _, placed = guard.supervised(
                    call, site="dispatch", pods=length)
                placed_parts.append(placed)
        if sharded:
            self._audit_reshard(kns, carry)
        self._last_tables, self._last_carry = bt, carry
        faults.maybe_fail("fetch")
        total = int(guard.supervised(
            lambda: np.asarray(jnp.sum(jnp.stack(placed_parts))),
            site="fetch", pods=P))  # one fetch
        return scheduled + total, total_known

    def probe_utilization(self) -> Dict[str, float]:
        """Aggregate used/allocatable totals after a probe_pods run, read from
        the device carry in one fetch — the inputs of satisfyResourceSetting
        (apply.go:689-775) without materializing node statuses. CPU in milli,
        memory in bytes (the axis units).

        The np.asarray below is an INTENTIONAL device→host boundary — the one
        sanctioned sync of this probe path (audited for PR1): it runs outside
        any jit trace, after the scan pipeline has been dispatched, so it
        costs exactly one round trip and can never bake a constant into a
        compiled program. The f64 widening is host-side on purpose: summing
        byte-quantities across thousands of nodes overflows f32 precision."""
        from ..ops.resources import CPU_I, MEM_I

        N = self.na.N
        if self._last_carry is None:
            used = np.zeros((N, self.axis.R), np.float64)  # simonlint: ignore[dtype-drift] -- host-side accumulator, see docstring
        else:
            # simonlint: ignore[dtype-drift] -- host-side accumulator, see docstring
            used = np.asarray(self._last_carry.requested)[:N].astype(np.float64)
        alloc = self.na.alloc
        return {
            "cpu_used": float(used[:, CPU_I].sum()),
            "cpu_alloc": float(alloc[:, CPU_I].sum()),
            "mem_used": float(used[:, MEM_I].sum()),
            "mem_alloc": float(alloc[:, MEM_I].sum()),
        }

    def _resolve_mesh(self):
        """Decide (once) whether to shard: use_mesh True/False forces it; None
        autodetects >1 visible device, overridable via OPEN_SIMULATOR_MESH.
        Quarantine is re-checked on EVERY access, not just the first: a mesh
        cached before ANOTHER simulator quarantined the backend carries
        explicit shardings that override jax.default_device, and keeping it
        would burn a watchdog timeout re-dispatching on the wedged backend."""
        if self._mesh is not _UNSET:
            if self._mesh is not None and (
                    self._fallback or guard.default_quarantined()):
                self._mesh = None
                # tables/carry placed through the old mesh live on the wedged
                # backend; drop them so nothing re-dispatches against them
                self._last_tables = self._last_carry = None
            return self._mesh
        if self._fallback or guard.default_quarantined():
            # degraded mode is single-device CPU: a mesh over the default
            # backend's devices would carry explicit shardings that OVERRIDE
            # jax.default_device, re-dispatching on the wedged backend and
            # burning a watchdog timeout per fresh Simulator
            self._mesh = None
            return None
        import os

        want = self.use_mesh
        env = os.environ.get("OPEN_SIMULATOR_MESH", "")
        if want is None and env:
            want = env not in ("0", "false", "no")
        mesh = None
        if want is not False:
            import jax

            n = len(jax.devices())
            if n > 1 or (want and n >= 1):
                from ..parallel.mesh import make_node_mesh

                mesh = make_node_mesh(n)
        self._mesh = mesh
        return mesh

    def _dispatch_dims(self, bt: BatchTables) -> Dict[str, object]:
        """Static shape parts shared by every kernel dispatch over this
        batch's tables — the compile-cache signature base for
        obs.record_dispatch. Only static/shape-defining values belong here;
        traced values never key a compile. `cfg` digests the score-weight and
        filter-flag NamedTuples, which are jit statics on every kernel: two
        simulators with different sched_configs must not alias signatures."""
        return {
            "N": int(bt.alloc.shape[0]),
            "G": int(bt.static_mask.shape[0]),
            "T": int(bt.counter_dom.shape[0]),
            "mesh": self._mesh is not None and self._mesh is not _UNSET,
            "cfg": self._cfg_digest(),
        }

    def _to_device(self, bt: BatchTables):
        faults.maybe_fail("to_device")
        faults.maybe_fail("oom_to_device")
        jnp = _jax()
        from ..parallel.mesh import tables_from_batch

        obs.TRANSFER_BYTES.inc(batch_tables_nbytes(bt))
        mesh = self._resolve_mesh()
        if mesh is not None:
            from ..parallel.mesh import to_device_sharded

            tables, carry, _ = to_device_sharded(bt, mesh)
            return tables, carry
        tables = kernels.Tables(*(jnp.asarray(v) for v in tables_from_batch(bt)))
        carry = kernels.Carry(
            requested=jnp.asarray(bt.seed_requested),
            nonzero=jnp.asarray(bt.seed_nonzero),
            port_used=jnp.asarray(bt.seed_port_used),
            counter=jnp.asarray(bt.seed_counter),
            carrier=jnp.asarray(bt.seed_carrier),
            dev_used=jnp.asarray(bt.seed_dev_used),
            vg_req=jnp.asarray(bt.seed_vg_req),
            sdev_alloc=jnp.asarray(bt.seed_sdev_alloc),
        )
        return tables, carry

    # ------------------------------------------------- unschedulable reasons ------

    _STAGE_ORDER = (
        ("unsched", "node(s) were unschedulable"),
        ("taint", None),  # expanded per-taint below
        ("affinity", "node(s) didn't match node selector"),
        ("extra", "node(s) were filtered out by an out-of-tree plugin"),
        ("ports", "node(s) didn't have free ports for the requested pod ports"),
        ("fit", None),  # expanded per-resource below
        ("spread", "node(s) didn't match pod topology spread constraints"),
        ("pod_affinity", "node(s) didn't match pod affinity rules"),
        ("pod_anti", "node(s) didn't match pod anti-affinity rules"),
        ("gpu", None),  # expanded per-node below (gpu-share Filter says "Node:<name>")
        ("storage", "node(s) didn't have enough local storage"),
    )

    def _explain_reasons(self, pod: dict, g: int, forced: int, tables, carry) -> Dict[str, int]:
        """Rebuild the FitError reason counts from per-stage masks
        (generic_scheduler.go findNodesThatFitPod failure accounting;
        first-failing-plugin per node)."""
        jnp = _jax()

        enable_gpu, enable_storage = getattr(self, "_last_flags", (True, True))
        kns, _ = self._kernel_ns(donate=False)  # diagnostics never donate
        bt = getattr(self, "_last_tables", None)
        obs.record_dispatch("feasibility_jit", gpu=enable_gpu,
                            storage=enable_storage,
                            **(self._dispatch_dims(bt) if bt is not None
                               else {"cfg": self._cfg_digest()}))
        feasible, stages = guard.supervised(functools.partial(
            kns.feasibility_jit,
            tables, carry, jnp.int32(g), jnp.int32(forced), jnp.asarray(True),
            enable_gpu=enable_gpu, enable_storage=enable_storage,
            filters=self.filter_flags,
        ), site="dispatch", pods=1)
        N = self.na.N  # stages arrays may carry phantom node padding; slice it off
        stages = {k: np.asarray(v)[:N] for k, v in stages.items()}
        return self._reasons_from_stages(pod, forced, stages)

    def _reasons_from_stages(self, pod: dict, forced: int,
                             stages: Dict[str, np.ndarray]) -> Dict[str, int]:
        """Reason counts from already-fetched per-stage masks ([N] each);
        shared with the preemption pass, which evaluates the stages itself."""
        N = self.na.N
        remaining = np.ones(N, bool)
        if forced >= 0:
            only = np.zeros(N, bool)
            only[forced] = True
            remaining &= only
        reasons: Dict[str, int] = {}

        def take(mask_ok: np.ndarray, label: str):
            nonlocal remaining
            fail = remaining & ~mask_ok
            n = int(fail.sum())
            if n:
                reasons[label] = reasons.get(label, 0) + n
            remaining &= mask_ok

        for stage, label in self._STAGE_ORDER:
            if stage == "taint":
                fail = remaining & ~stages["taint"]
                for i in np.nonzero(fail)[0]:
                    taint = find_untolerated_taint(self.na.nodes[i], pod, ("NoSchedule", "NoExecute"))
                    if taint is None:
                        lbl = "node(s) had taints that the pod didn't tolerate"
                    else:
                        lbl = "node(s) had taint {%s: %s}, that the pod didn't tolerate" % (
                            taint.get("key", ""), taint.get("value") or "")
                    reasons[lbl] = reasons.get(lbl, 0) + 1
                remaining &= stages["taint"]
            elif stage == "gpu":
                # Open-Gpu-Share Filter returns "Node:<name>" (open-gpu-share.go:66,76)
                fail = remaining & ~stages["gpu"]
                for i in np.nonzero(fail)[0]:
                    lbl = f"Node:{self.na.names[i]}"
                    reasons[lbl] = reasons.get(lbl, 0) + 1
                remaining &= stages["gpu"]
            elif stage == "fit":
                fit_each = stages["fit_each"]  # [N, R]
                fail = remaining & ~stages["fit"]
                for i in np.nonzero(fail)[0]:
                    bad = np.nonzero(~fit_each[i])[0]
                    res = self.axis.names[bad[0]] if len(bad) else "resources"
                    lbl = "Too many pods" if res == "pods" else f"Insufficient {res}"
                    reasons[lbl] = reasons.get(lbl, 0) + 1
                remaining &= stages["fit"]
            else:
                take(stages[stage], label)
        return reasons

    def _format_reason(self, pod: dict, reasons: Dict[str, int], n_nodes: int) -> str:
        detail = ", ".join(f"{v} {k}" for k, v in sorted(reasons.items()))
        if not detail:
            detail = "no nodes available to schedule pods"
        msg = f"0/{n_nodes} nodes are available: {detail}."
        return (
            f"failed to schedule pod ({namespace_of(pod)}/{name_of(pod)}): "
            f"{C.PodReasonUnschedulable}: {msg}"
        )

    # ----------------------------------------------------------- results ----------

    def get_cluster_node_status(self) -> List[NodeStatus]:
        return [
            NodeStatus(node=self.na.nodes[i], pods=list(self.pods_on_node[i]))
            for i in range(self.na.N)
        ]

    def schedule_app(self, app: AppResource) -> SimulateResult:
        """ScheduleApp (simulator.go:232-275): expand app, order, register CM/SC/PDB,
        schedule."""
        pods = generate_valid_pods_from_app(app.name, app.resource, self.na.nodes)
        pods = sort_toleration(sort_affinity(pods))
        for patch in self.patch_pod_funcs:
            patch(pods)
        self.register_app_objects(app.resource)
        failed = self.schedule_pods(pods)
        return SimulateResult(unscheduled_pods=failed,
                              node_status=self.get_cluster_node_status(),
                              backend_path=list(self.backend_path))

    def run_cluster(self, cluster: ResourceTypes) -> SimulateResult:
        """RunCluster + syncClusterResourceList (simulator.go:225-230,365-447)."""
        self.register_cluster_objects(cluster)
        failed = self.schedule_pods(cluster.pods)
        return SimulateResult(unscheduled_pods=failed,
                              node_status=self.get_cluster_node_status(),
                              backend_path=list(self.backend_path))
