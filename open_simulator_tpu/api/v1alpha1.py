"""The `simon/v1alpha1 Config` CR — the apply-mode configuration file.

Mirrors /root/reference/pkg/api/v1alpha1/types.go:3-29 and the Applier validation at
/root/reference/pkg/apply/apply.go:269-306, so reference config files (e.g.
example/simon-config.yaml) load unchanged.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional

import yaml


class ConfigError(ValueError):
    pass


@dataclass
class AppInfo:
    name: str
    path: str
    chart: bool = False


@dataclass
class Cluster:
    custom_cluster: str = ""   # customConfig: YAML dir describing a fake cluster
    kube_config: str = ""      # kubeConfig: path to a live cluster's kubeconfig


@dataclass
class SimonSpec:
    cluster: Cluster = field(default_factory=Cluster)
    app_list: List[AppInfo] = field(default_factory=list)
    new_node: str = ""


@dataclass
class SimonConfig:
    api_version: str = "simon/v1alpha1"
    kind: str = "Config"
    name: str = ""
    spec: SimonSpec = field(default_factory=SimonSpec)


def parse_simon_config(path: str) -> SimonConfig:
    """Load + decode a Simon config file. Relative paths inside the config are
    interpreted relative to the process CWD, as in the reference."""
    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    spec_raw = raw.get("spec") or {}
    cluster_raw = spec_raw.get("cluster") or {}
    apps = [
        AppInfo(
            name=a.get("name", ""),
            path=a.get("path", ""),
            chart=bool(a.get("chart", False)),
        )
        for a in spec_raw.get("appList") or []
    ]
    return SimonConfig(
        api_version=raw.get("apiVersion", ""),
        kind=raw.get("kind", ""),
        name=(raw.get("metadata") or {}).get("name", ""),
        spec=SimonSpec(
            cluster=Cluster(
                custom_cluster=cluster_raw.get("customConfig", "") or "",
                kube_config=cluster_raw.get("kubeConfig", "") or "",
            ),
            app_list=apps,
            new_node=spec_raw.get("newNode", "") or "",
        ),
    )


def validate_config(
    cfg: SimonConfig, scheduler_config: Optional[str] = None
) -> None:
    """The Applier validity test (apply.go:269-306): cluster source XOR + every
    referenced path must exist."""
    c = cfg.spec.cluster
    if bool(c.kube_config) == bool(c.custom_cluster):
        raise ConfigError("only one of values of both kubeConfig and customConfig must exist")
    for label, p in (("kubeConfig", c.kube_config), ("customConfig", c.custom_cluster),
                     ("scheduler config", scheduler_config or ""),
                     ("newNode", cfg.spec.new_node)):
        if p and not os.path.exists(p):
            raise ConfigError(f"invalid path of {label}: {p}")
    for app in cfg.spec.app_list:
        if not os.path.exists(app.path):
            raise ConfigError(f"invalid path of {app.name} app: {app.path}")
