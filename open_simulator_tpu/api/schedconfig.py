"""KubeSchedulerConfiguration parsing for --default-scheduler-config.

The reference threads the file through the kube-scheduler options machinery
(GetAndSetSchedulerConfig, /root/reference/pkg/simulator/utils.go:303-381 +
InitKubeSchedulerConfiguration:277-295): the file's profile replaces the
default profile, so its plugin enable/disable lists and score weights govern
scheduling. This module parses the same file into plain data the engine maps
onto its kernels: per-score-plugin weights (disable = weight 0) and the set of
disabled filter plugins.

Parity boundaries, enforced LOUDLY (a config the engine cannot honor raises
ConfigError instead of silently degrading — the failure mode round-2 shipped):
- exactly one profile, schedulerName default-scheduler;
- percentageOfNodesToScore must be absent or 100 (the simulator pins it to 100,
  utils.go:370);
- extenders / pluginConfig args / queueSort-preFilter-permit overrides are
  unsupported;
- plugin names must come from the v1.20 default registry + the Simon set
  (an unknown name fails scheduler.New in the reference too);
- volume filter plugins may be listed (enable/disable) but are inert either
  way: MakeValidPod rewrites every PVC to hostPath (see PARITY.md).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Mapping

import yaml

from .v1alpha1 import ConfigError

# score plugin name -> (engine weight key, default weight); defaults are the
# v1.20 provider registry (algorithmprovider/registry.go:118-137) with the
# Simon set appended at weight 1 (the framework's zero->1 rule).
SCORE_PLUGINS: Dict[str, tuple] = {
    "NodeResourcesLeastAllocated": ("least", 1.0),
    "NodeResourcesBalancedAllocation": ("balanced", 1.0),
    "ImageLocality": ("image", 1.0),
    "InterPodAffinity": ("interpod", 1.0),
    "NodeAffinity": ("nodeaff", 1.0),
    "NodePreferAvoidPods": ("avoid", 10000.0),
    "PodTopologySpread": ("pts", 2.0),
    "TaintToleration": ("taint", 1.0),
    "SelectorSpread": ("ss", 1.0),
    "Simon": ("simon", 1.0),
    "Open-Gpu-Share": ("gpushare", 1.0),
    "Open-Local": ("openlocal", 1.0),
}

# filter plugins the engine can disable: kernel-evaluated ones map to
# FilterFlags fields, statically-folded ones to encoder keys.
KERNEL_FILTERS = {
    "NodeResourcesFit": "fit",
    "NodePorts": "ports",
    "InterPodAffinity": "interpod",
    "PodTopologySpread": "spread",
}
ENCODER_FILTERS = {"TaintToleration", "NodeUnschedulable", "NodeAffinity"}

# default filter set members that are inert under simulator semantics, so
# enabling/disabling them changes nothing: the volume plugins act on PVCs that
# MakeValidPod rewrote to hostPath (pkg/utils/utils.go:378-463), NodeName
# pins are folded into required node affinity by the workload expansion, and
# DefaultPreemption never runs because failed pods are deleted, not retried.
INERT_FILTERS = frozenset({
    "VolumeBinding", "NodeVolumeLimits", "EBSLimits", "GCEPDLimits",
    "AzureDiskLimits", "VolumeRestrictions", "VolumeZone", "NodeName",
    "Open-Local", "Open-Gpu-Share",
})
KNOWN_FILTERS = frozenset(KERNEL_FILTERS) | ENCODER_FILTERS | INERT_FILTERS

# top-level fields that cannot affect placement in a simulator: parsed and
# ignored, matching the reference (events/leader election are stubbed out,
# utils.go:289-292).
IGNORED_TOP_LEVEL = {
    "apiVersion", "kind", "profiles", "percentageOfNodesToScore",
    "leaderElection", "clientConnection", "healthzBindAddress",
    "metricsBindAddress", "enableProfiling", "enableContentionProfiling",
    "parallelism", "podInitialBackoffSeconds", "podMaxBackoffSeconds",
}

_API_VERSIONS = {
    "kubescheduler.config.k8s.io/v1beta1",
    "kubescheduler.config.k8s.io/v1beta2",
}


@dataclass(frozen=True)
class SchedulerConfig:
    """Engine-facing result: full score weight map (0 = disabled) + disabled
    filter sets, split by where the engine applies them."""

    score_weights: Mapping[str, float] = field(
        default_factory=lambda: {k: d for k, (_, d) in SCORE_PLUGINS.items()})
    disabled_kernel_filters: FrozenSet[str] = frozenset()
    disabled_encoder_filters: FrozenSet[str] = frozenset()
    # postFilter: the default set has exactly DefaultPreemption
    # (algorithmprovider/registry.go:106-110); disabling it turns the
    # engine's preemption pass off (simulator/preemption.py)
    preemption_disabled: bool = False

    def weight_kwargs(self) -> Dict[str, float]:
        """{engine weight key: weight} for kernels.ScoreWeights(**kwargs)."""
        return {SCORE_PLUGINS[name][0]: w for name, w in self.score_weights.items()}


DEFAULT_SCHEDULER_CONFIG = SchedulerConfig()


def _plugin_list(obj, where: str) -> List[dict]:
    if obj is None:
        return []
    if not isinstance(obj, list):
        raise ConfigError(f"scheduler config: {where} must be a list")
    out = []
    for item in obj:
        if not isinstance(item, dict) or "name" not in item:
            raise ConfigError(f"scheduler config: malformed plugin entry in {where}: {item!r}")
        out.append(item)
    return out


def parse_scheduler_config(path: str) -> SchedulerConfig:
    """Load and validate a KubeSchedulerConfiguration file. Raises ConfigError
    on anything the engine cannot honor (see module docstring)."""
    with open(path) as f:
        doc = yaml.safe_load(f)
    if doc is None:
        return DEFAULT_SCHEDULER_CONFIG
    if not isinstance(doc, dict):
        raise ConfigError(f"scheduler config {path}: not a mapping")
    api = doc.get("apiVersion", "")
    if api and api not in _API_VERSIONS:
        raise ConfigError(f"scheduler config: unsupported apiVersion {api!r}")
    kind = doc.get("kind", "")
    if kind and kind != "KubeSchedulerConfiguration":
        raise ConfigError(f"scheduler config: unsupported kind {kind!r}")
    unknown = set(doc) - IGNORED_TOP_LEVEL - {"extenders"}
    if unknown:
        raise ConfigError(
            f"scheduler config: unsupported field(s) {sorted(unknown)}")
    if doc.get("extenders"):
        raise ConfigError("scheduler config: extenders are not supported")
    pct = doc.get("percentageOfNodesToScore")
    if pct not in (None, 0, 100):
        raise ConfigError(
            "scheduler config: percentageOfNodesToScore must be 100 (the "
            f"simulator pins it, utils.go:370); got {pct}")

    profiles = doc.get("profiles") or []
    if not isinstance(profiles, list) or len(profiles) > 1:
        raise ConfigError("scheduler config: exactly one profile is supported")
    if not profiles:
        return DEFAULT_SCHEDULER_CONFIG
    prof = profiles[0] or {}
    name = prof.get("schedulerName")
    if name not in (None, "default-scheduler"):
        raise ConfigError(
            f"scheduler config: schedulerName must be default-scheduler, got {name!r}")
    if prof.get("pluginConfig"):
        raise ConfigError("scheduler config: pluginConfig args are not supported")
    unknown = set(prof) - {"schedulerName", "plugins", "pluginConfig"}
    if unknown:
        raise ConfigError(
            f"scheduler config: unsupported profile field(s) {sorted(unknown)}")

    plugins = prof.get("plugins") or {}
    # extension points whose overrides the engine cannot honor; bind/reserve
    # are accepted when they only touch the Simon set (the reference itself
    # rewrites them, utils.go:321-368)
    for point in set(plugins) - {"score", "filter", "bind", "reserve", "postFilter"}:
        if (plugins.get(point) or {}).get("enabled") or (plugins.get(point) or {}).get("disabled"):
            raise ConfigError(
                f"scheduler config: overriding the {point} extension point is not supported")
    preemption_disabled = False
    pf = plugins.get("postFilter") or {}
    for entry in _plugin_list(pf.get("disabled"), "postFilter.disabled"):
        if entry["name"] in ("*", "DefaultPreemption"):
            preemption_disabled = True
        else:
            raise ConfigError(
                f"scheduler config: unknown postFilter plugin {entry['name']!r}")
    for entry in _plugin_list(pf.get("enabled"), "postFilter.enabled"):
        if entry["name"] != "DefaultPreemption":
            raise ConfigError(
                f"scheduler config: unknown postFilter plugin {entry['name']!r}")
        preemption_disabled = False
    for point in ("bind", "reserve"):
        for entry in _plugin_list((plugins.get(point) or {}).get("enabled"), point):
            if entry["name"] not in ("Simon", "Open-Local", "Open-Gpu-Share", "DefaultBinder"):
                raise ConfigError(
                    f"scheduler config: unsupported {point} plugin {entry['name']!r}")

    weights = {k: d for k, (_, d) in SCORE_PLUGINS.items()}
    score = plugins.get("score") or {}
    for entry in _plugin_list(score.get("disabled"), "score.disabled"):
        nm = entry["name"]
        if nm == "*":
            weights = {k: 0.0 for k in weights}
        elif nm in weights:
            weights[nm] = 0.0
        else:
            raise ConfigError(f"scheduler config: unknown score plugin {nm!r}")
    for entry in _plugin_list(score.get("enabled"), "score.enabled"):
        nm = entry["name"]
        if nm not in SCORE_PLUGINS:
            raise ConfigError(f"scheduler config: unknown score plugin {nm!r}")
        w = entry.get("weight", 0)
        try:
            w = float(w)
        except (TypeError, ValueError):
            raise ConfigError(f"scheduler config: bad weight for {nm!r}: {entry.get('weight')!r}")
        # the framework's zero->1 rule for enabled score plugins
        weights[nm] = w if w > 0 else 1.0

    disabled_kernel: set = set()
    disabled_encoder: set = set()
    flt = plugins.get("filter") or {}
    for entry in _plugin_list(flt.get("disabled"), "filter.disabled"):
        nm = entry["name"]
        if nm == "*":
            disabled_kernel.update(KERNEL_FILTERS)
            disabled_encoder.update(ENCODER_FILTERS)
        elif nm in KERNEL_FILTERS:
            disabled_kernel.add(nm)
        elif nm in ENCODER_FILTERS:
            disabled_encoder.add(nm)
        elif nm in INERT_FILTERS:
            pass  # inert either way, see INERT_FILTERS
        else:
            raise ConfigError(f"scheduler config: unknown filter plugin {nm!r}")
    for entry in _plugin_list(flt.get("enabled"), "filter.enabled"):
        nm = entry["name"]
        if nm not in KNOWN_FILTERS:
            raise ConfigError(f"scheduler config: unknown filter plugin {nm!r}")
        disabled_kernel.discard(nm)
        disabled_encoder.discard(nm)

    return SchedulerConfig(
        score_weights=weights,
        disabled_kernel_filters=frozenset(disabled_kernel),
        disabled_encoder_filters=frozenset(disabled_encoder),
        preemption_disabled=preemption_disabled,
    )
