"""Helm chart loading + rendering.

Mirrors ProcessChart (/root/reference/pkg/chart/chart.go:18-118): load the chart,
verify it is installable, coalesce values, render every template with the release
context, drop NOTES.txt/empty docs, and sort manifests in install order. The Go helm
engine is replaced by the gotmpl interpreter (gotmpl.py).
"""

from __future__ import annotations

import os
import tarfile
import tempfile
from typing import Dict, List, Optional

import yaml

from .gotmpl import TemplateError, parse_defines, render_template

DEFAULT_RELEASE_NAME = "simon-release"
DEFAULT_NAMESPACE = "default"

# helm releaseutil.InstallOrder
INSTALL_ORDER = [
    "Namespace", "NetworkPolicy", "ResourceQuota", "LimitRange",
    "PodSecurityPolicy", "PodDisruptionBudget", "ServiceAccount", "Secret",
    "SecretList", "ConfigMap", "StorageClass", "PersistentVolume",
    "PersistentVolumeClaim", "CustomResourceDefinition", "ClusterRole",
    "ClusterRoleList", "ClusterRoleBinding", "ClusterRoleBindingList", "Role",
    "RoleList", "RoleBinding", "RoleBindingList", "Service", "DaemonSet", "Pod",
    "ReplicationController", "ReplicaSet", "Deployment",
    "HorizontalPodAutoscaler", "StatefulSet", "Job", "CronJob", "Ingress",
    "APIService",
]
_ORDER_IDX = {k: i for i, k in enumerate(INSTALL_ORDER)}


class ChartError(ValueError):
    pass


class Chart:
    def __init__(self, root: str) -> None:
        meta_path = os.path.join(root, "Chart.yaml")
        if not os.path.exists(meta_path):
            raise ChartError(f"{root}: no Chart.yaml")
        with open(meta_path) as f:
            self.metadata: dict = yaml.safe_load(f) or {}
        values_path = os.path.join(root, "values.yaml")
        self.values: dict = {}
        if os.path.exists(values_path):
            with open(values_path) as f:
                self.values = yaml.safe_load(f) or {}
        self.templates: Dict[str, str] = {}
        tdir = os.path.join(root, "templates")
        if os.path.isdir(tdir):
            for base, _, files in os.walk(tdir):
                for fname in sorted(files):
                    if fname.endswith((".yaml", ".yml", ".tpl", ".txt")):
                        rel = os.path.relpath(os.path.join(base, fname), root)
                        with open(os.path.join(base, fname)) as f:
                            self.templates[rel] = f.read()
        self.subcharts: List[Chart] = []
        cdir = os.path.join(root, "charts")
        if os.path.isdir(cdir):
            for sub in sorted(os.listdir(cdir)):
                subpath = os.path.join(cdir, sub)
                if os.path.isdir(subpath) and os.path.exists(
                    os.path.join(subpath, "Chart.yaml")
                ):
                    self.subcharts.append(Chart(subpath))

    @property
    def name(self) -> str:
        return self.metadata.get("name", "")

    def is_installable(self) -> bool:
        # chart.go:44-52: only 'application' (or unset) type charts install
        return self.metadata.get("type", "application") in ("", "application")


def load_chart(path: str) -> Chart:
    """Load a chart directory or .tgz archive."""
    if os.path.isdir(path):
        return Chart(path)
    if path.endswith((".tgz", ".tar.gz")) and os.path.exists(path):
        tmp = tempfile.mkdtemp(prefix="simon-chart-")
        with tarfile.open(path) as tf:
            tf.extractall(tmp, filter="data")
        entries = [e for e in os.listdir(tmp) if os.path.isdir(os.path.join(tmp, e))]
        if len(entries) != 1:
            raise ChartError(f"{path}: expected a single chart root in archive")
        return Chart(os.path.join(tmp, entries[0]))
    raise ChartError(f"{path}: not a chart directory or .tgz")


def coalesce_values(chart: Chart, overrides: Optional[dict] = None) -> dict:
    """helm chartutil.CoalesceValues: overrides win over chart values; subchart
    values nest under the subchart name."""
    def deep_merge(base: dict, over: dict) -> dict:
        out = dict(base)
        for k, v in (over or {}).items():
            if k in out and isinstance(out[k], dict) and isinstance(v, dict):
                out[k] = deep_merge(out[k], v)
            else:
                out[k] = v
        return out

    values = dict(chart.values)
    for sub in chart.subcharts:
        values[sub.name] = deep_merge(sub.values, values.get(sub.name) or {})
    return deep_merge(values, overrides or {})


def _release_context(chart: Chart, values: dict, release_name: str, namespace: str) -> dict:
    return {
        "Values": values,
        "Release": {
            "Name": release_name,
            "Namespace": namespace,
            "Service": "Helm",
            "IsInstall": True,
            "IsUpgrade": False,
            "Revision": 1,
        },
        "Chart": {
            # template convention: .Chart.Name capitalized keys
            **{k[:1].upper() + k[1:]: v for k, v in chart.metadata.items()},
        },
        "Capabilities": {
            "KubeVersion": {"Version": "v1.20.5", "Major": "1", "Minor": "20"},
            "APIVersions": [],
            "HelmVersion": {"Version": "v3"},
        },
        "Template": {"Name": "", "BasePath": chart.name + "/templates"},
    }


def render_chart(
    chart: Chart,
    overrides: Optional[dict] = None,
    release_name: str = DEFAULT_RELEASE_NAME,
    namespace: str = DEFAULT_NAMESPACE,
) -> List[str]:
    """Render all templates → YAML document strings in install order (chart.go:80-118:
    NOTES.txt stripped, manifests sorted with helm's InstallOrder)."""
    if not chart.is_installable():
        raise ChartError(f"chart {chart.name} is not installable (library chart)")
    values = coalesce_values(chart, overrides)
    data = _release_context(chart, values, release_name, namespace)

    defines: Dict[str, object] = {}
    charts = [chart] + chart.subcharts
    for ch in charts:
        for tname, src in ch.templates.items():
            if tname.endswith(".tpl"):
                try:
                    defines.update(parse_defines(src, tname))
                except TemplateError as e:
                    raise ChartError(f"{chart.name}/{tname}: {e}") from e

    docs: List[str] = []
    for ch in charts:
        if ch is not chart:
            sub_values = values.get(ch.name) or {}
            sub_data = {**data, "Values": {**sub_values, "global": values.get("global") or {}},
                        "Chart": {k[:1].upper() + k[1:]: v for k, v in ch.metadata.items()}}
        else:
            sub_data = data
        for tname in sorted(ch.templates):
            base = os.path.basename(tname)
            if tname.endswith(".tpl") or base == "NOTES.txt" or base.startswith("_"):
                continue
            src = ch.templates[tname]
            try:
                rendered = render_template(src, sub_data, name=f"{ch.name}/{tname}",
                                           extra_defines=defines)
            except TemplateError as e:
                raise ChartError(f"{ch.name}/{tname}: {e}") from e
            for doc in rendered.split("\n---"):
                if doc.strip().startswith("---"):
                    doc = doc.strip()[3:]
                if doc.strip():
                    docs.append(doc)

    def order_key(doc: str):
        try:
            obj = yaml.safe_load(doc)
        except yaml.YAMLError:
            return (len(INSTALL_ORDER), "")
        kind = (obj or {}).get("kind", "")
        return (_ORDER_IDX.get(kind, len(INSTALL_ORDER)), kind)

    parsed = [(order_key(d), i, d) for i, d in enumerate(docs)]
    parsed.sort(key=lambda t: (t[0], t[1]))  # stable within same kind
    return [d for _, _, d in parsed if yaml.safe_load(d)]


def process_chart(app_name: str, path: str, overrides: Optional[dict] = None) -> List[dict]:
    """ProcessChart equivalent: chart path → decoded k8s objects, install-ordered.
    Uses the app name as the release name so generated object names are stable."""
    chart = load_chart(path)
    out: List[dict] = []
    for doc in render_chart(chart, overrides, release_name=app_name):
        obj = yaml.safe_load(doc)
        if isinstance(obj, dict) and obj.get("kind"):
            out.append(obj)
    return out
