"""A Go text/template + sprig subset interpreter, sized for rendering Helm charts.

The reference renders charts with helm.sh/helm/v3's engine
(/root/reference/pkg/chart/chart.go:81). No helm binary or Go runtime exists in this
environment, so this module interprets the template constructs real-world charts use:

- actions: `{{ expr }}` with `{{-`/`-}}` whitespace trimming
- control: if/else if/else, range (lists + dicts, with `$i, $v :=` forms), with,
  define/include/template, end
- data: .Values / .Release / .Chart / .Capabilities paths, `$` root, variables
  (`$x := ...`), string/int/float/bool literals
- pipelines `a | f b | g` and ~40 sprig/builtin functions (default, quote, toYaml,
  nindent, printf, trunc, contains, semverCompare-lite, dict/list helpers, ...)

Unsupported constructs raise TemplateError with the template name/offset so chart
authorship bugs surface clearly instead of silently mis-rendering.
"""

from __future__ import annotations

import json
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import yaml


class TemplateError(ValueError):
    pass


# ------------------------------------------------------------------ lexing ----------

_ACTION_RE = re.compile(r"\{\{-?\s*(.*?)\s*-?\}\}", re.S)


def _tokenize(src: str, name: str) -> List[Tuple[str, Any]]:
    """[('text', str) | ('action', (code, trim_before, trim_after))]."""
    out: List[Tuple[str, Any]] = []
    pos = 0
    for m in _ACTION_RE.finditer(src):
        if m.start() > pos:
            out.append(("text", src[pos : m.start()]))
        raw = src[m.start() : m.end()]
        trim_before = raw.startswith("{{-")
        trim_after = raw.endswith("-}}")
        out.append(("action", (m.group(1), trim_before, trim_after)))
        pos = m.end()
    if pos < len(src):
        out.append(("text", src[pos:]))
    return out


# ----------------------------------------------------------------- parsing ----------


class Node:
    pass


class Text(Node):
    def __init__(self, s: str) -> None:
        self.s = s


class Action(Node):
    def __init__(self, code: str) -> None:
        self.code = code


class If(Node):
    def __init__(self) -> None:
        self.branches: List[Tuple[Optional[str], List[Node]]] = []  # (cond, body); None=else


class Range(Node):
    def __init__(self, code: str) -> None:
        self.code = code
        self.body: List[Node] = []
        self.else_body: List[Node] = []


class With(Node):
    def __init__(self, code: str) -> None:
        self.code = code
        self.body: List[Node] = []
        self.else_body: List[Node] = []


class Define(Node):
    def __init__(self, name: str) -> None:
        self.name = name
        self.body: List[Node] = []


_KEYWORD_RE = re.compile(r"^(if|else if|else|range|with|define|block|template|include|end)\b\s*(.*)$", re.S)


def _parse(tokens: List[Tuple[str, Any]], name: str) -> Tuple[List[Node], Dict[str, List[Node]]]:
    defines: Dict[str, List[Node]] = {}
    root: List[Node] = []
    # stack of (node_list_to_append_to, owner) frames
    stack: List[Tuple[List[Node], Any]] = [(root, None)]

    # apply whitespace trimming first: walk tokens, mutate neighboring text
    toks = [list(t) for t in tokens]
    for i, t in enumerate(toks):
        if t[0] != "action":
            continue
        code, tb, ta = t[1]
        if tb and i > 0 and toks[i - 1][0] == "text":
            toks[i - 1][1] = toks[i - 1][1].rstrip(" \t").rstrip("\n\r\t ")
        if ta and i + 1 < len(toks) and toks[i + 1][0] == "text":
            toks[i + 1][1] = toks[i + 1][1].lstrip(" \t").lstrip("\n\r\t ")

    for t in toks:
        if t[0] == "text":
            if t[1]:
                stack[-1][0].append(Text(t[1]))
            continue
        code = t[1][0].strip()
        if code.startswith("/*") and code.endswith("*/"):
            continue  # comment
        m = _KEYWORD_RE.match(code)
        if not m:
            stack[-1][0].append(Action(code))
            continue
        kw, rest = m.group(1), m.group(2).strip()
        if kw == "if":
            node = If()
            node.branches.append((rest, []))
            stack[-1][0].append(node)
            stack.append((node.branches[-1][1], node))
        elif kw == "else if":
            owner = stack[-1][1]
            if not isinstance(owner, If):
                raise TemplateError(f"{name}: 'else if' outside if")
            stack.pop()
            owner.branches.append((rest, []))
            stack.append((owner.branches[-1][1], owner))
        elif kw == "else":
            owner = stack[-1][1]
            stack.pop()
            if isinstance(owner, If):
                owner.branches.append((None, []))
                stack.append((owner.branches[-1][1], owner))
            elif isinstance(owner, (Range, With)):
                stack.append((owner.else_body, owner))
            else:
                raise TemplateError(f"{name}: 'else' outside if/range/with")
        elif kw == "range":
            node = Range(rest)
            stack[-1][0].append(node)
            stack.append((node.body, node))
        elif kw == "with":
            node = With(rest)
            stack[-1][0].append(node)
            stack.append((node.body, node))
        elif kw in ("define", "block"):
            tpl_name = rest.strip().strip('"')
            node = Define(tpl_name)
            defines[tpl_name] = node.body
            stack.append((node.body, node))
        elif kw in ("template", "include"):
            stack[-1][0].append(Action(f"{kw} {rest}"))
        elif kw == "end":
            if len(stack) == 1:
                raise TemplateError(f"{name}: unbalanced 'end'")
            stack.pop()
    if len(stack) != 1:
        raise TemplateError(f"{name}: missing 'end'")
    return root, defines


# -------------------------------------------------------------- expressions ---------

_TOKEN_EXPR = re.compile(
    r"""
    \s*(?:
      (?P<pipe>\|)
    | (?P<lparen>\()
    | (?P<rparen>\))
    | (?P<str>"(?:\\.|[^"\\])*"|`[^`]*`)
    | (?P<num>-?\d+\.\d+|-?\d+)
    | (?P<rootpath>\$\.[A-Za-z0-9_.]*)
    | (?P<varpath>\$[A-Za-z0-9_]+\.[A-Za-z0-9_.]+)
    | (?P<var>\$[A-Za-z0-9_]*)
    | (?P<path>\.[A-Za-z0-9_.]*)
    | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
    | (?P<assign>:=|=)
    | (?P<comma>,)
    )
    """,
    re.X,
)


def _lex_expr(code: str, name: str) -> List[Tuple[str, str]]:
    toks = []
    i = 0
    while i < len(code):
        m = _TOKEN_EXPR.match(code, i)
        if not m or m.end() == i:
            if code[i:].strip() == "":
                break
            raise TemplateError(f"{name}: cannot lex expression {code[i:]!r}")
        i = m.end()
        for kind in ("pipe", "lparen", "rparen", "str", "num", "rootpath", "varpath",
                     "var", "path", "ident", "assign", "comma"):
            v = m.group(kind)
            if v is not None:
                toks.append((kind, v))
                break
    return toks


class _Scope:
    """Go text/template variable scoping (text/template/exec.go's variable stack):
    `:=` declares in the innermost block; `=` assigns to the nearest declaration;
    leaving a block (if/with/range body, template invocation) discards the
    declarations made inside it."""

    __slots__ = ("map", "parent")

    def __init__(self, parent: Optional["_Scope"] = None) -> None:
        self.map: Dict[str, Any] = {}
        self.parent = parent

    def get(self, name: str) -> Any:
        s = self
        while s is not None:
            if name in s.map:
                return s.map[name]
            s = s.parent
        return None

    def declare(self, name: str, val: Any) -> None:
        self.map[name] = val

    def assign(self, name: str, val: Any) -> None:
        s = self
        while s is not None:
            if name in s.map:
                s.map[name] = val
                return
            s = s.parent
        # text/template errors on `$x = v` without a prior `$x :=` declaration
        raise TemplateError(f"undefined variable ${name}")


class _Ctx:
    def __init__(self, root: Any, defines: Dict[str, List[Node]], funcs, name: str) -> None:
        self.root = root
        self.defines = defines
        self.funcs = funcs
        self.name = name
        self.vars = _Scope()

    def child(self) -> "_Ctx":
        sub = _Ctx.__new__(_Ctx)
        sub.root, sub.defines, sub.funcs, sub.name = (
            self.root, self.defines, self.funcs, self.name)
        sub.vars = _Scope(self.vars)
        return sub


def _resolve_path(dot: Any, root: Any, path: str):
    """Resolve `.a.b.c` against dot ('.': dot itself). Missing keys yield None,
    matching template nil semantics."""
    cur = dot if not path.startswith(".$") else root
    if path == ".":
        return dot
    for part in path.lstrip(".").split("."):
        if not part:
            continue
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            cur = getattr(cur, part, None)
        if cur is None:
            return None
    return cur


def _truthy(v: Any) -> bool:
    if v is None or v is False:
        return False
    if isinstance(v, (str, list, dict, tuple)):
        return len(v) > 0
    if isinstance(v, (int, float)):
        return v != 0
    return True


class _Evaluator:
    def __init__(self, ctx: _Ctx, dot: Any) -> None:
        self.ctx = ctx
        self.dot = dot

    def eval(self, code: str) -> Any:
        toks = _lex_expr(code, self.ctx.name)
        # variable assignment: $x := expr (declare) / $x = expr (assign outward)
        if len(toks) >= 2 and toks[0][0] == "var" and toks[1][0] == "assign":
            val = self._eval_pipeline(toks[2:])
            if toks[1][1] == ":=":
                self.ctx.vars.declare(toks[0][1], val)
            else:
                self.ctx.vars.assign(toks[0][1], val)
            return ""
        return self._eval_pipeline(toks)

    def _eval_pipeline(self, toks: List[Tuple[str, str]]) -> Any:
        stages: List[List[Tuple[str, str]]] = [[]]
        depth = 0
        for t in toks:
            if t[0] == "pipe" and depth == 0:
                stages.append([])
                continue
            if t[0] == "lparen":
                depth += 1
            elif t[0] == "rparen":
                depth -= 1
            stages[-1].append(t)
        val = None
        first = True
        for stage in stages:
            if not stage:
                raise TemplateError(f"{self.ctx.name}: empty pipeline stage")
            if first:
                val = self._eval_call(stage, piped=None)
                first = False
            else:
                val = self._eval_call(stage, piped=val)
        return val

    def _eval_call(self, toks: List[Tuple[str, str]], piped) -> Any:
        args: List[Any] = []
        i = 0
        fname: Optional[str] = None
        if toks and toks[0][0] == "ident":
            fname = toks[0][1]
            i = 1
        while i < len(toks):
            kind, v = toks[i]
            if kind == "lparen":
                depth, j = 1, i + 1
                while j < len(toks) and depth:
                    if toks[j][0] == "lparen":
                        depth += 1
                    elif toks[j][0] == "rparen":
                        depth -= 1
                    j += 1
                args.append(self._eval_pipeline(toks[i + 1 : j - 1]))
                i = j
                continue
            if kind == "str":
                s = v[1:-1]
                if v[0] == '"':
                    s = bytes(s, "utf-8").decode("unicode_escape")
                args.append(s)
            elif kind == "num":
                args.append(float(v) if "." in v else int(v))
            elif kind == "rootpath":
                args.append(_resolve_path(self.ctx.root, self.ctx.root, v[1:]))
            elif kind == "varpath":
                var, _, rest = v.partition(".")
                base = self.ctx.root if var == "$" else self.ctx.vars.get(var)
                args.append(_resolve_path(base, self.ctx.root, "." + rest))
            elif kind == "var":
                if v == "$":
                    args.append(self.ctx.root)
                else:
                    args.append(self.ctx.vars.get(v))
            elif kind == "path":
                args.append(_resolve_path(self.dot, self.ctx.root, v))
            elif kind == "ident":
                kwmap = {"true": True, "false": False, "nil": None}
                if v in kwmap:
                    args.append(kwmap[v])
                else:
                    raise TemplateError(f"{self.ctx.name}: bare identifier {v!r} mid-args")
            elif kind == "comma":
                pass
            else:
                raise TemplateError(f"{self.ctx.name}: unexpected token {v!r}")
            i += 1

        if fname is None:
            if piped is not None:
                raise TemplateError(f"{self.ctx.name}: pipeline into non-function")
            if len(args) != 1:
                raise TemplateError(f"{self.ctx.name}: expected single value, got {args!r}")
            return args[0]
        if piped is not None:
            args.append(piped)
        fn = self.ctx.funcs.get(fname)
        if fn is None:
            raise TemplateError(f"{self.ctx.name}: unknown function {fname!r}")
        try:
            return fn(self, *args)
        except TemplateError:
            raise
        except Exception as e:
            raise TemplateError(
                f"{self.ctx.name}: {fname}({', '.join(map(repr, args))}): {e}"
            ) from e


# ---------------------------------------------------------------- rendering ---------


def _to_yaml(v: Any) -> str:
    return yaml.safe_dump(v, default_flow_style=False, sort_keys=False).rstrip("\n")


def _fmt(v: Any) -> str:
    if v is None:
        return ""
    if v is True:
        return "true"
    if v is False:
        return "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


def _go_printf(fmt: str, *args) -> str:
    out, ai = [], 0
    i = 0
    while i < len(fmt):
        c = fmt[i]
        if c == "%" and i + 1 < len(fmt):
            spec = fmt[i + 1]
            if spec == "%":
                out.append("%")
            elif spec in "sdvq":
                a = args[ai] if ai < len(args) else ""
                ai += 1
                if spec == "q":
                    out.append(json.dumps(_fmt(a)))
                elif spec == "d":
                    out.append(str(int(a)))
                else:
                    out.append(_fmt(a))
            else:
                out.append(c + spec)
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


def _builtin_funcs() -> Dict[str, Callable]:
    def f(fn):
        return lambda ev, *a: fn(*a)

    funcs: Dict[str, Callable] = {
        # logic / comparison (Go template builtins)
        "eq": f(lambda a, b, *r: a == b or any(a == x for x in r)),
        "ne": f(lambda a, b: a != b),
        "lt": f(lambda a, b: a < b),
        "le": f(lambda a, b: a <= b),
        "gt": f(lambda a, b: a > b),
        "ge": f(lambda a, b: a >= b),
        "and": f(lambda *a: next((x for x in a if not _truthy(x)), a[-1] if a else None)),
        "or": f(lambda *a: next((x for x in a if _truthy(x)), a[-1] if a else None)),
        "not": f(lambda a: not _truthy(a)),
        "len": f(lambda a: len(a) if a is not None else 0),
        "index": f(lambda c, *ks: _index(c, ks)),
        "print": f(lambda *a: "".join(_fmt(x) for x in a)),
        "printf": f(_go_printf),
        # sprig: strings
        "quote": f(lambda *a: " ".join(json.dumps(_fmt(x)) for x in a)),
        "squote": f(lambda *a: " ".join("'" + _fmt(x) + "'" for x in a)),
        "upper": f(lambda s: _fmt(s).upper()),
        "lower": f(lambda s: _fmt(s).lower()),
        "title": f(lambda s: _fmt(s).title()),
        "trim": f(lambda s: _fmt(s).strip()),
        "trimSuffix": f(lambda suf, s: _fmt(s)[: -len(suf)] if _fmt(s).endswith(suf) else _fmt(s)),
        "trimPrefix": f(lambda pre, s: _fmt(s)[len(pre):] if _fmt(s).startswith(pre) else _fmt(s)),
        "trunc": f(lambda n, s: _fmt(s)[:n] if n >= 0 else _fmt(s)[n:]),
        "replace": f(lambda old, new, s: _fmt(s).replace(old, new)),
        "contains": f(lambda sub, s: sub in _fmt(s)),
        "hasPrefix": f(lambda pre, s: _fmt(s).startswith(pre)),
        "hasSuffix": f(lambda suf, s: _fmt(s).endswith(suf)),
        "repeat": f(lambda n, s: _fmt(s) * n),
        "join": f(lambda sep, lst: sep.join(_fmt(x) for x in (lst or []))),
        "split": f(lambda sep, s: {str(i): p for i, p in enumerate(_fmt(s).split(sep))}),
        "splitList": f(lambda sep, s: _fmt(s).split(sep)),
        "nospace": f(lambda s: re.sub(r"\s+", "", _fmt(s))),
        "snakecase": f(lambda s: re.sub(r"(?<=[a-z0-9])([A-Z])", r"_\1", _fmt(s)).lower()),
        "kebabcase": f(lambda s: re.sub(r"(?<=[a-z0-9])([A-Z])", r"-\1", _fmt(s)).lower()),
        "camelcase": f(lambda s: "".join(w.title() for w in re.split(r"[_-]", _fmt(s)))),
        "indent": f(lambda n, s: "\n".join(" " * n + l if l else l for l in _fmt(s).split("\n"))),
        "nindent": f(lambda n, s: "\n" + "\n".join(" " * n + l if l else l for l in _fmt(s).split("\n"))),
        # sprig: defaults & type
        "default": f(lambda d, v=None: v if _truthy(v) else d),
        "empty": f(lambda v: not _truthy(v)),
        "coalesce": f(lambda *a: next((x for x in a if _truthy(x)), None)),
        "ternary": f(lambda t, fv, c: t if _truthy(c) else fv),
        "toString": f(_fmt),
        "toJson": f(lambda v: json.dumps(v)),
        "toYaml": f(_to_yaml),
        "fromYaml": f(lambda s: yaml.safe_load(s) or {}),
        "toToml": f(lambda v: _to_yaml(v)),  # close enough for value passthrough
        "int": f(lambda v: int(float(v)) if v not in (None, "") else 0),
        "int64": f(lambda v: int(float(v)) if v not in (None, "") else 0),
        "float64": f(lambda v: float(v) if v not in (None, "") else 0.0),
        "b64enc": f(lambda s: __import__("base64").b64encode(_fmt(s).encode()).decode()),
        "b64dec": f(lambda s: __import__("base64").b64decode(_fmt(s)).decode()),
        "sha256sum": f(lambda s: __import__("hashlib").sha256(_fmt(s).encode()).hexdigest()),
        # sprig: math
        "add": f(lambda *a: sum(int(x) for x in a)),
        "add1": f(lambda a: int(a) + 1),
        "sub": f(lambda a, b: int(a) - int(b)),
        "mul": f(lambda *a: __import__("math").prod(int(x) for x in a)),
        "div": f(lambda a, b: int(int(a) / int(b))),
        "mod": f(lambda a, b: int(a) % int(b)),
        "max": f(lambda *a: max(int(x) for x in a)),
        "min": f(lambda *a: min(int(x) for x in a)),
        # sprig: collections
        "list": f(lambda *a: list(a)),
        "dict": f(lambda *a: {str(a[i]): a[i + 1] for i in range(0, len(a) - 1, 2)}),
        "get": f(lambda d, k: (d or {}).get(k)),
        "set": f(lambda d, k, v: ({**(d or {}), k: v})),
        "hasKey": f(lambda d, k: k in (d or {})),
        "keys": f(lambda d: list((d or {}).keys())),
        "values": f(lambda d: list((d or {}).values())),
        "pluck": f(lambda k, *ds: [d[k] for d in ds if isinstance(d, dict) and k in d]),
        "merge": f(_merge),
        "mergeOverwrite": f(lambda dst, *srcs: _merge(dst, *srcs, overwrite=True)),
        "deepCopy": f(lambda v: json.loads(json.dumps(v))),
        "first": f(lambda lst: (lst or [None])[0]),
        "last": f(lambda lst: (lst or [None])[-1]),
        "rest": f(lambda lst: (lst or [])[1:]),
        "initial": f(lambda lst: (lst or [])[:-1]),
        "append": f(lambda lst, v: list(lst or []) + [v]),
        "prepend": f(lambda lst, v: [v] + list(lst or [])),
        "concat": f(lambda *ls: [x for l in ls for x in (l or [])]),
        "uniq": f(lambda lst: list(dict.fromkeys(lst or []))),
        "without": f(lambda lst, *xs: [v for v in (lst or []) if v not in xs]),
        "has": f(lambda v, lst: v in (lst or [])),
        "sortAlpha": f(lambda lst: sorted(_fmt(x) for x in (lst or []))),
        "reverse": f(lambda lst: list(reversed(lst or []))),
        "until": f(lambda n: list(range(int(n)))),
        "untilStep": f(lambda a, b, s: list(range(int(a), int(b), int(s)))),
        "seq": f(lambda a, b=None: list(range(1, int(a) + 1)) if b is None else list(range(int(a), int(b) + 1))),
        # misc chart helpers
        "required": f(lambda msg, v: v if v is not None else (_ for _ in ()).throw(TemplateError(msg))),
        "fail": f(lambda msg: (_ for _ in ()).throw(TemplateError(msg))),
        "lookup": f(lambda *a: {}),  # cluster lookups resolve to empty, like helm template
        "tpl": _tpl,
        "include": _include,
        "template": _include,
        "randAlphaNum": f(lambda n: "x" * int(n)),  # deterministic: templates must not be random
        "now": f(lambda: "1970-01-01T00:00:00Z"),
        "uuidv4": f(lambda: "00000000-0000-4000-8000-000000000000"),
        "semverCompare": f(_semver_compare),
        "kindIs": f(lambda kind, v: _kind_of(v) == kind),
        "typeOf": f(lambda v: _kind_of(v)),
        "regexMatch": f(lambda pat, s: bool(re.search(pat, _fmt(s)))),
        "regexReplaceAll": f(lambda pat, s, repl: re.sub(pat, _go_repl(repl), _fmt(s))),
    }
    return funcs


def _index(c, ks):
    cur = c
    for k in ks:
        if cur is None:
            return None
        if isinstance(cur, dict):
            cur = cur.get(k)
        elif isinstance(cur, (list, tuple)):
            cur = cur[int(k)] if 0 <= int(k) < len(cur) else None
        else:
            return None
    return cur


def _merge(dst, *srcs, overwrite=False):
    out = dict(dst or {})
    for src in srcs:
        for k, v in (src or {}).items():
            if k in out and isinstance(out[k], dict) and isinstance(v, dict):
                out[k] = _merge(out[k], v, overwrite=overwrite)
            elif overwrite or k not in out or not _truthy(out[k]):
                out[k] = v
    return out


def _kind_of(v) -> str:
    if isinstance(v, bool):
        return "bool"
    if isinstance(v, int):
        return "int64"
    if isinstance(v, float):
        return "float64"
    if isinstance(v, str):
        return "string"
    if isinstance(v, list):
        return "slice"
    if isinstance(v, dict):
        return "map"
    return "invalid"


def _semver_cmp_key(v: str):
    return [int(x) for x in re.findall(r"\d+", v)[:3]] or [0]


def _semver_compare(constraint: str, version: str) -> bool:
    m = re.match(r"^\s*(>=|<=|>|<|=|\^|~)?\s*v?(.*)$", constraint.strip())
    op = m.group(1) or "="
    a, b = _semver_cmp_key(version), _semver_cmp_key(m.group(2))
    if op in ("=", "^", "~"):
        return a[:1] == b[:1] if op == "^" else (a[:2] == b[:2] if op == "~" else a == b)
    return {"<": a < b, "<=": a <= b, ">": a > b, ">=": a >= b}[op]


def _go_repl(repl: str):
    """Replacement callable implementing Go/RE2 Expand semantics: `$1`/`$name`
    (longest word-char run), `${name}`, `$$` → literal `$`; references to
    nonexistent groups expand to the empty string (regexp/regexp.go Expand)."""

    def expand(m: "re.Match[str]") -> str:
        out: List[str] = []
        i, n = 0, len(repl)
        while i < n:
            c = repl[i]
            if c != "$":
                out.append(c)
                i += 1
                continue
            if i + 1 < n and repl[i + 1] == "$":
                out.append("$")
                i += 2
                continue
            j = i + 1
            braced = j < n and repl[j] == "{"
            if braced:
                j += 1
            k = j
            while k < n and (repl[k].isalnum() or repl[k] == "_"):
                k += 1
            name = repl[j:k]
            if braced:
                if k < n and repl[k] == "}":
                    k += 1
                else:  # unclosed ${ — Go emits a literal `$` and keeps the rest
                    out.append("$")
                    i += 1
                    continue
            if not name:
                out.append("$")
                i = j
                continue
            try:
                grp = m.group(int(name) if name.isdigit() else name)
            except (IndexError, re.error):
                grp = None
            out.append(grp or "")
            i = k
        return "".join(out)

    return expand


def _include(ev: "_Evaluator", name: str, dot=None) -> str:
    """Template invocation gets a fresh variable stack (text/template exec.go
    walkTemplate: new scope with only $ = the passed argument)."""
    body = ev.ctx.defines.get(name)
    if body is None:
        raise TemplateError(f"{ev.ctx.name}: include of undefined template {name!r}")
    dot = dot if dot is not None else ev.dot
    sub = _Ctx(dot, ev.ctx.defines, ev.ctx.funcs, ev.ctx.name)
    return _render_nodes(body, sub, dot)


def _tpl(ev: "_Evaluator", src: str, dot=None) -> str:
    dot = dot if dot is not None else ev.dot
    nodes, defs = _parse(_tokenize(src, "tpl"), "tpl")
    sub = _Ctx(dot, {**ev.ctx.defines, **defs}, ev.ctx.funcs, ev.ctx.name + ":tpl")
    return _render_nodes(nodes, sub, dot)


def _render_nodes(nodes: List[Node], ctx: _Ctx, dot: Any) -> str:
    out: List[str] = []
    for node in nodes:
        if isinstance(node, Text):
            out.append(node.s)
        elif isinstance(node, Action):
            ev = _Evaluator(ctx, dot)
            out.append(_fmt(ev.eval(node.code)))
        elif isinstance(node, If):
            for cond, body in node.branches:
                child = ctx.child()
                if cond is None or _truthy(_eval_guard(cond, child, dot)):
                    out.append(_render_nodes(body, child, dot))
                    break
        elif isinstance(node, With):
            child = ctx.child()
            v = _eval_guard(node.code, child, dot)
            if _truthy(v):
                out.append(_render_nodes(node.body, child, v))
            else:
                out.append(_render_nodes(node.else_body, ctx.child(), dot))
        elif isinstance(node, Range):
            out.append(_render_range(node, ctx, dot))
        elif isinstance(node, Define):
            pass
        else:  # pragma: no cover
            raise TemplateError(f"{ctx.name}: unknown node {node!r}")
    return "".join(out)


_GUARD_RE = re.compile(r"^\s*(\$[A-Za-z0-9_]+)\s*:=\s*(.*)$", re.S)


def _eval_guard(code: str, ctx: _Ctx, dot: Any) -> Any:
    """Evaluate an if/with pipeline, supporting the `$x := pipeline` declaration
    form (text/template: the value is the pipeline's; the variable is scoped to
    the guarded block, which is why callers pass a child ctx)."""
    m = _GUARD_RE.match(code)
    if m:
        val = _Evaluator(ctx, dot).eval(m.group(2))
        ctx.vars.declare(m.group(1), val)
        return val
    return _Evaluator(ctx, dot).eval(code)


def _render_range(node: Range, ctx: _Ctx, dot: Any) -> str:
    code = node.code
    var_names: List[str] = []
    m = re.match(r"^\s*((?:\$[A-Za-z0-9_]+\s*,\s*)?\$[A-Za-z0-9_]+)\s*:=\s*(.*)$", code, re.S)
    if m:
        var_names = [v.strip() for v in m.group(1).split(",")]
        code = m.group(2)
    coll = _Evaluator(ctx, dot).eval(code)
    if not _truthy(coll):
        return _render_nodes(node.else_body, ctx.child(), dot)
    out: List[str] = []
    if isinstance(coll, dict):
        items = list(coll.items())
    else:
        items = list(enumerate(coll))
    for k, v in items:
        body_ctx = ctx.child()  # loop vars + body declarations die at each `end`
        if len(var_names) == 2:
            body_ctx.vars.declare(var_names[0], k)
            body_ctx.vars.declare(var_names[1], v)
        elif len(var_names) == 1:
            body_ctx.vars.declare(var_names[0], v)
        out.append(_render_nodes(node.body, body_ctx, v))
    return "".join(out)


def render_template(
    src: str,
    data: Any,
    name: str = "template",
    extra_defines: Optional[Dict[str, List[Node]]] = None,
) -> str:
    nodes, defines = _parse(_tokenize(src, name), name)
    if extra_defines:
        defines = {**extra_defines, **defines}
    ctx = _Ctx(data, defines, _builtin_funcs(), name)
    return _render_nodes(nodes, ctx, data)


def parse_defines(src: str, name: str) -> Dict[str, List[Node]]:
    """Collect {{ define }} blocks (e.g. from _helpers.tpl) for cross-file includes."""
    _, defines = _parse(_tokenize(src, name), name)
    return defines
