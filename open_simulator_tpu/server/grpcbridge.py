"""gRPC bridge: the host-side RPC surface for non-Python clients.

SURVEY.md §7 step 9 / §2.3 name a "Go↔Python gRPC bridge" as the distributed-
communication counterpart of the reference's gin REST server
(/root/reference/pkg/server/server.go:148-315): a Go CLI (or any gRPC client)
drives this process, which owns the TPU scheduling service. The contract is
proto/simon.proto; handlers delegate to the same `Server` the REST façade uses
(http.py), so both surfaces stay behavior-identical — TryLock→busy, snapshot,
simulate, response shaping.

Wire format: the three message types are small (an int32 field and/or one bytes
field), so this module encodes/decodes protobuf wire format directly — no
generated stubs, no protoc at runtime; `tests/test_grpcbridge.py` cross-checks
the codec against protoc-generated modules. Service dispatch uses
grpc.method_handlers_generic_handler, which needs only (de)serializer callables.
"""

from __future__ import annotations

import json
from typing import Optional, Tuple

from .http import Server

SERVICE = "simon.v1.Simon"


# ------------------------------------------------------------- wire codec ------
#
# Protobuf wire format (proto3):
#   field 1, varint  -> tag 0x08 ; field 1, bytes -> tag 0x0A
#   field 2, bytes   -> tag 0x12 ; varints are base-128 little-endian
# Unknown fields are skipped (forward compatibility); default values are
# omitted on encode, exactly like canonical protobuf serializers.


def _encode_varint(n: int) -> bytes:
    if n < 0:  # int32 negatives ride as 10-byte two's-complement varints
        n += 1 << 64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _decode_varint(data: bytes, i: int) -> Tuple[int, int]:
    shift = 0
    val = 0
    while True:
        if i >= len(data):
            raise ValueError("truncated varint")
        b = data[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _skip_field(data: bytes, i: int, wire_type: int) -> int:
    if wire_type == 0:
        _, i = _decode_varint(data, i)
        return i
    if wire_type == 1:
        i += 8
    elif wire_type == 2:
        n, i = _decode_varint(data, i)
        i += n
    elif wire_type == 5:
        i += 4
    else:
        raise ValueError(f"unsupported wire type {wire_type}")
    if i > len(data):
        raise ValueError("truncated field")
    return i


def _fields(data: bytes):
    """Yield (field_number, wire_type, value) — value is int for varint,
    bytes for length-delimited; other types are skipped."""
    i = 0
    while i < len(data):
        tag, i = _decode_varint(data, i)
        field, wt = tag >> 3, tag & 7
        if wt == 0:
            val, i = _decode_varint(data, i)
            yield field, wt, val
        elif wt == 2:
            n, i = _decode_varint(data, i)
            if i + n > len(data):  # canonical parsers reject truncation
                raise ValueError("truncated length-delimited field")
            yield field, wt, bytes(data[i:i + n])
            i += n
        else:
            i = _skip_field(data, i, wt)


def encode_simulate_request(request_json: bytes) -> bytes:
    return (b"\x0a" + _encode_varint(len(request_json)) + request_json
            if request_json else b"")


def decode_simulate_request(data: bytes) -> bytes:
    payload = b""
    for field, wt, val in _fields(data):
        if field == 1 and wt == 2:
            payload = val
    return payload


def encode_simulate_response(code: int, response_json: bytes) -> bytes:
    out = b""
    if code:
        out += b"\x08" + _encode_varint(code)
    if response_json:
        out += b"\x12" + _encode_varint(len(response_json)) + response_json
    return out


def decode_simulate_response(data: bytes) -> Tuple[int, bytes]:
    code, payload = 0, b""
    for field, wt, val in _fields(data):
        if field == 1 and wt == 0:
            # int32: the canonical encoder sign-extends to 64 bits
            code = val - (1 << 64) if val >= 1 << 63 else val
        elif field == 2 and wt == 2:
            payload = val
    return code, payload


def encode_health_response(message: str) -> bytes:
    data = message.encode()
    return b"\x0a" + _encode_varint(len(data)) + data if data else b""


def decode_health_response(data: bytes) -> str:
    for field, wt, val in _fields(data):
        if field == 1 and wt == 2:
            return val.decode()
    return ""


# -------------------------------------------------------------- service --------


class GrpcBridge:
    """gRPC façade over `Server` (http.py). Build with the same arguments —
    or an injectable snapshot_fn for tests — then `serve(port)`."""

    def __init__(self, server: Optional[Server] = None, **server_kwargs) -> None:
        self.server = server if server is not None else Server(**server_kwargs)

    # handlers: bytes-in/bytes-out via the wire codec

    def _simulate(self, handler, request: bytes, context,
                  endpoint: str = "grpc") -> bytes:
        from .http import count_http_error, error_body

        # the gRPC surface shares the REST drain gate: requests arriving
        # after SIGTERM get the same in-band structured 503
        if not self.server._begin_request():
            count_http_error("drain", 503)
            return encode_simulate_response(
                503, json.dumps(error_body(503, "server is draining")).encode())
        try:
            # simonscope edge: the gRPC bridge mints the trace id exactly
            # like the HTTP handler — the WhatIf RPC's micro-batched serve
            # path joins it downstream (WhatIfService.submit)
            from ..obs import scope as scope_mod

            sc = scope_mod.active() if getattr(
                self.server, "scope", False) else None
            try:
                req = json.loads(decode_simulate_request(request) or b"{}")
            except ValueError as e:
                # covers JSONDecodeError, invalid-UTF-8 UnicodeDecodeError, and
                # malformed protobuf framing from the decoder — the contract
                # keeps unmarshal errors in-band as structured code=400
                count_http_error("grpc", 400)
                code, body = 400, error_body(
                    400, f"fail to unmarshal content: {e}")
            else:
                if sc is not None:
                    import time as _time

                    t0 = _time.perf_counter()
                    with sc.request_span(endpoint):
                        code, body = handler(req)
                    sc.slo.record(endpoint, f"{code // 100}xx",
                                  {"total": _time.perf_counter() - t0},
                                  error=code >= 500)
                else:
                    code, body = handler(req)
            return encode_simulate_response(code, json.dumps(body).encode())
        finally:
            self.server._end_request()

    def _deploy(self, request: bytes, context) -> bytes:
        return self._simulate(self.server.handle_deploy_apps, request, context,
                              endpoint="grpc:deploy-apps")

    def _scale(self, request: bytes, context) -> bytes:
        return self._simulate(self.server.handle_scale_apps, request, context,
                              endpoint="grpc:scale-apps")

    def _whatif(self, request: bytes, context) -> bytes:
        # simonserve: same JSON-in-bytes contract as Deploy/Scale — the
        # resident micro-batched path behind both surfaces is identical
        return self._simulate(self.server.handle_whatif, request, context,
                              endpoint="grpc:whatif")

    def _health(self, request: bytes, context) -> bytes:
        return encode_health_response("ok")

    def build_grpc_server(self, port: int, host: str = "[::]", max_workers: int = 4):
        """Returns (grpc.Server, bound_port). Generic handlers keep the bytes
        payloads opaque to grpc; the codec above is the (de)serializer."""
        from concurrent import futures

        import grpc

        ident = lambda b: b  # noqa: E731 — payloads are already wire bytes
        handlers = {
            "DeployApps": grpc.unary_unary_rpc_method_handler(
                self._deploy, request_deserializer=ident, response_serializer=ident),
            "ScaleApps": grpc.unary_unary_rpc_method_handler(
                self._scale, request_deserializer=ident, response_serializer=ident),
            "WhatIf": grpc.unary_unary_rpc_method_handler(
                self._whatif, request_deserializer=ident, response_serializer=ident),
            "Health": grpc.unary_unary_rpc_method_handler(
                self._health, request_deserializer=ident, response_serializer=ident),
        }
        # no SO_REUSEPORT: a port collision must FAIL (bound == 0 below), not
        # silently split traffic with whatever already holds the port
        server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers),
                             options=(("grpc.so_reuseport", 0),))
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),))
        try:
            bound = server.add_insecure_port(f"{host}:{port}")
        except RuntimeError as e:  # newer grpc raises instead of returning 0
            raise OSError(f"failed to bind grpc port {host}:{port}: {e}") from e
        if bound == 0:  # older grpc signals bind failure by returning port 0
            raise OSError(f"failed to bind grpc port {host}:{port}")
        return server, bound

    def serve(self, port: int, host: str = "[::]") -> None:
        server, bound = self.build_grpc_server(port, host)
        server.start()
        print(f"simon grpc bridge listening on :{bound}")
        server.wait_for_termination()
