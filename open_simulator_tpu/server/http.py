"""HTTP server mode: what-if simulation API over a live cluster.

Mirrors /root/reference/pkg/server/server.go (gin REST façade):
- `GET /test`, `GET /healthz`
- `POST /api/deploy-apps` — snapshot the live cluster (Running pods, workloads,
  services, SCs, PVCs, CMs, DaemonSets), append virtual NewNodes, add the request's
  workloads as one app plus the cluster's Pending pods, re-simulate (:166-231).
- `POST /api/scale-apps` — same, but pods owned by the scaled workloads are removed
  from the snapshot first and the request's Deployments/StatefulSets re-expand
  (:233-315); request DaemonSets replace their cluster versions in place.
- per-endpoint TryLock → 503 "server is busy" (:95,167,234).

Built on http.server (stdlib) instead of gin; the live snapshot uses the REST
KubeClient (simulator/live.py) instead of informer listers — each request re-lists,
which trades the informer cache for zero dependencies.

Failure semantics (simonfault, README "Failure handling"):
- every error response is structured JSON `{"error": ..., "code": ...}` and
  counted in `simon_http_errors_total{endpoint,code}`;
- the per-endpoint lock is released on every path that acquired it (and only
  those), so one failed request can never wedge an endpoint;
- graceful drain: SIGTERM (or `Server.drain()`) stops accepting work — new
  requests get 503 — lets in-flight requests finish inside a bounded drain
  deadline, then stops the listener;
- POST /debug/fault-plan activates a deterministic resilience.FaultPlan for
  reproducing failure behavior against a running server.
"""

from __future__ import annotations

import json
import os
import sys
import time
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional, Tuple

from ..core import constants as C
from ..core.types import AppResource, ResourceTypes, SimulateResult
from ..models.fakenode import new_fake_node
from ..obs import instruments as obs
from ..simulator.core import simulate
from ..utils.objutil import labels_of, name_of, namespace_of, owner_references


def error_body(code: int, message: str) -> dict:
    """The structured error contract every non-2xx response follows."""
    return {"error": message, "code": code}


def count_http_error(endpoint: str, code: int) -> None:
    obs.HTTP_ERRORS.labels(endpoint=endpoint, code=str(code)).inc()


def owned_by_workload(refs: List[dict], kind: str, name: str) -> bool:
    """OwnedByWorkload (utils.go:840-865): owner-ref kind+name match."""
    return any(r.get("kind") == kind and r.get("name") == name for r in refs)


def sample_stacks(seconds: float, interval: float = 0.01,
                  top: int = 50) -> str:
    """Sampling profiler over sys._current_frames(): every `interval`, snap
    the stack of every thread except the caller's, aggregate identical
    stacks, and render the `top` hottest with sample counts — the
    /debug/pprof/profile payload. A sampler sees application work on ANY
    thread (request handlers, the scheduling engine, background pollers),
    which a tracing profiler enabled around a sleep never could."""
    import sys
    import traceback

    me = threading.get_ident()
    counts: dict = {}
    samples = 0
    deadline = time.perf_counter() + max(0.0, seconds)
    while True:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            stack = tuple(
                f"{os.path.basename(fs.filename)}:{fs.lineno} {fs.name}"
                for fs in traceback.extract_stack(frame))
            counts[stack] = counts.get(stack, 0) + 1
        samples += 1
        if time.perf_counter() >= deadline:
            break
        time.sleep(interval)
    lines = [
        f"stack samples: {samples} over {seconds:g}s "
        f"({len(counts)} distinct stacks, all threads except profiler)",
        "",
    ]
    for stack, n in sorted(counts.items(), key=lambda kv: -kv[1])[:top]:
        lines.append(f"{n} sample(s):")
        lines.extend(f"    {fr}" for fr in stack)
        lines.append("")
    return "\n".join(lines)


class ClusterSnapshot:
    """One consistent view of the live cluster (the reference's lister snapshot)."""

    def __init__(self, resource: ResourceTypes, replica_sets: List[dict],
                 stateful_sets: List[dict], pending_pods: List[dict]) -> None:
        self.resource = resource
        self.replica_sets = replica_sets
        self.stateful_sets = stateful_sets
        self.pending_pods = pending_pods


def snapshot_from_client(client) -> ClusterSnapshot:
    """getCurrentClusterResource + getPendingPods (:317-402): Running pods only in
    the cluster resource, Pending pods separated, DaemonSet-owned skipped."""
    from ..simulator.live import LiveClusterError, _split_pods

    rt = ResourceTypes()
    rt.nodes = client.list("/api/v1/nodes")
    running, pending = _split_pods(client.list("/api/v1/pods"))
    rt.pods = running
    try:
        rt.pod_disruption_budgets = client.list("/apis/policy/v1/poddisruptionbudgets")
    except LiveClusterError:  # pre-1.21 cluster: policy/v1 not served
        rt.pod_disruption_budgets = client.list("/apis/policy/v1beta1/poddisruptionbudgets")
    rt.services = client.list("/api/v1/services")
    rt.storage_classes = client.list("/apis/storage.k8s.io/v1/storageclasses")
    rt.persistent_volume_claims = client.list("/api/v1/persistentvolumeclaims")
    rt.config_maps = client.list("/api/v1/configmaps")
    rt.daemon_sets = client.list("/apis/apps/v1/daemonsets")
    replica_sets = client.list("/apis/apps/v1/replicasets")
    stateful_sets = client.list("/apis/apps/v1/statefulsets")
    return ClusterSnapshot(rt, replica_sets, stateful_sets, pending)


def simulate_response(result: SimulateResult) -> dict:
    """getSimulateResponse (:446-470): namespaced names; only app-labeled pods."""
    unscheduled = [
        {"pod": f"{namespace_of(u.pod)}/{name_of(u.pod)}", "reason": u.reason}
        for u in result.unscheduled_pods
    ]
    node_status = []
    for ns in result.node_status:
        pods = [
            f"{namespace_of(p)}/{name_of(p)}"
            for p in ns.pods
            if C.LabelAppName in labels_of(p)
        ]
        if pods:
            node_status.append({"node": name_of(ns.node), "pods": pods})
    return {"unscheduledPods": unscheduled, "nodeStatus": node_status}


class Server:
    """The server façade. `snapshot_fn` is injectable for tests; by default it
    re-lists from the cluster on every request."""

    def __init__(
        self,
        kubeconfig: str = "",
        master: str = "",
        snapshot_fn: Optional[Callable[[], ClusterSnapshot]] = None,
        debug_faults: Optional[bool] = None,
        xray: Optional[bool] = None,
        whatif: Optional[bool] = None,
        whatif_window_ms: Optional[float] = None,
        whatif_fanout: Optional[int] = None,
        scope: Optional[bool] = None,
        state_dir: Optional[str] = None,
        staleness_ceiling_s: Optional[float] = None,
        checkpoint_every: Optional[int] = None,
        max_queue: Optional[int] = None,
        tenant_rate: Optional[float] = None,
        ingest_max_bytes: Optional[int] = None,
        shed_seed: int = 0,
        watch: Optional[str] = None,
    ) -> None:
        # /debug/fault-plan is a process-global WRITE endpoint (testing/CI):
        # never enabled by default on a production server. Opt in explicitly
        # (constructor / `simon server --debug-faults`) or via env.
        if debug_faults is None:
            debug_faults = os.environ.get(
                "OPEN_SIMULATOR_DEBUG_FAULTS", "") not in ("", "0", "false", "no")
        self.debug_faults = debug_faults
        # simonxray: opt-in decision recording (constructor, `simon server
        # --xray`, or OPEN_SIMULATOR_XRAY=1). The server keeps an in-memory
        # recorder (bounded index, no trace file unless OPEN_SIMULATOR_
        # XRAY_OUT names one) and serves it on GET /explain/<pod>.
        if xray is None:
            xray = os.environ.get(
                "OPEN_SIMULATOR_XRAY", "") not in ("", "0", "false", "no")
        self.xray = xray
        if xray:
            from ..obs import xray as xray_mod

            xray_mod.enable(os.environ.get("OPEN_SIMULATOR_XRAY_OUT") or None)
        if snapshot_fn is None:
            from ..simulator.live import create_kube_client

            client = create_kube_client(kubeconfig, master)
            snapshot_fn = lambda: snapshot_from_client(client)  # noqa: E731
        self.snapshot_fn = snapshot_fn
        # simonserve (serve/): resident what-if serving — /v1/whatif rides a
        # persistent device-resident cluster image with micro-batched
        # dispatch instead of re-simulating the snapshot per request. Opt in
        # via constructor, `simon serve`, or OPEN_SIMULATOR_WHATIF=1; the
        # image builds lazily from the first snapshot (one-time stage cost).
        if whatif is None:
            whatif = os.environ.get(
                "OPEN_SIMULATOR_WHATIF", "") not in ("", "0", "false", "no")
        self.whatif = whatif
        self.whatif_window_ms = (
            whatif_window_ms if whatif_window_ms is not None
            else float(os.environ.get("OPEN_SIMULATOR_WHATIF_WINDOW_MS", "2")))
        self.whatif_fanout = (
            whatif_fanout if whatif_fanout is not None
            else int(os.environ.get("OPEN_SIMULATOR_WHATIF_FANOUT", "8")))
        # simonscope (obs/scope.py): request tracing + SLO engine + runtime
        # telemetry. `simon serve` turns it on by default (serving-grade
        # observability is the point of serve mode); everything else is off
        # unless OPEN_SIMULATOR_SCOPE=1 / scope=True. Library/test default
        # stays OFF so scope-off metrics remain byte-identical.
        from ..obs import scope as scope_mod

        if scope is None:
            scope = scope_mod.env_enabled(default=False)
        self.scope = scope
        # ownership: only the server that CREATED the process-global scope
        # tears it down on drain — an externally enabled scope (a test
        # harness, an embedding process) outlives any one server, exactly
        # like the xray recorder
        self._scope_owned = bool(scope) and scope_mod.active() is None
        if scope:
            scope_mod.enable(sampler=True)
        # simonpulse boots from the env here too: the serve path stages a
        # ResidentImage without ever constructing a Simulator (whose ctor is
        # the other maybe_enable_from_env site), so OPEN_SIMULATOR_PULSE=1
        # must take effect before the first supervised dispatch
        from ..obs import pulse as pulse_mod

        pulse_mod.maybe_enable_from_env()
        # simonha (serve/ha.py): crash-consistent serving. --state-dir turns
        # on the ingest WAL + checkpoint/restore; the admission knobs guard
        # the micro-batch queue whether or not state is durable. All off by
        # default so a plain Server() behaves exactly as before.
        if state_dir is None:
            state_dir = os.environ.get("OPEN_SIMULATOR_STATE_DIR") or None
        self.state_dir = state_dir
        self.staleness_ceiling_s = (
            staleness_ceiling_s if staleness_ceiling_s is not None
            else float(os.environ.get(
                "OPEN_SIMULATOR_STALENESS_CEILING_S", "120")))
        self.checkpoint_every = (
            checkpoint_every if checkpoint_every is not None
            else int(os.environ.get("OPEN_SIMULATOR_CHECKPOINT_EVERY", "64")))
        env_q = os.environ.get("OPEN_SIMULATOR_MAX_QUEUE", "")
        self.max_queue = (max_queue if max_queue is not None
                          else (int(env_q) if env_q else None))
        self.tenant_rate = (
            tenant_rate if tenant_rate is not None
            else float(os.environ.get("OPEN_SIMULATOR_TENANT_RPS", "0")))
        self.ingest_max_bytes = (
            ingest_max_bytes if ingest_max_bytes is not None
            else int(os.environ.get("OPEN_SIMULATOR_INGEST_MAX_BYTES",
                                    str(8 << 20))))
        self.shed_seed = shed_seed
        # simonsync (live/sync.py): resilient watch ingest. --watch points
        # the resident image at a delta source ("file:stream.jsonl", a
        # chunked-HTTP watch URL — optionally "watch_url|list_url" so 410
        # can relist — or "kube" for the kubeconfig cluster). Off by
        # default; ingest then stays request-driven via /v1/ingest.
        if watch is None:
            watch = os.environ.get("OPEN_SIMULATOR_WATCH") or None
        self.watch_spec = watch
        self._kubeconfig = kubeconfig
        self._master = master
        self._syncs: List = []
        self._sync_threads: List[threading.Thread] = []
        self._sync_stop = threading.Event()
        self._sync_errors: List[str] = []
        self._ha = None
        self._ingest_bytes = 0  # in-flight /v1/ingest payload bytes
        self._ingest_bytes_lock = threading.Lock()
        self._whatif_svc = None
        self._whatif_declined = False
        self._whatif_lock = threading.Lock()
        self.deploy_lock = threading.Lock()
        self.scale_lock = threading.Lock()
        # drain/in-flight accounting (graceful SIGTERM semantics)
        self._inflight = 0
        self._state_cv = threading.Condition()
        self._draining = False
        self._httpd: Optional[ThreadingHTTPServer] = None

    # ------------------------------------------------------------- handlers -------

    def handle_deploy_apps(self, req: dict) -> Tuple[int, object]:
        # TryLock BEFORE the try: the busy path must not release a lock it
        # never held; every path below the acquire releases in the finally.
        if not self.deploy_lock.acquire(blocking=False):
            count_http_error("deploy-apps", 503)
            return 503, error_body(
                503, "The server is busy, please try again later")
        try:
            snap = self.snapshot_fn()
            # copy: an injectable snapshot_fn may return shared lists, and the
            # handler appends fake nodes — never mutate the snapshot in place.
            cluster = snap.resource.copy()
            for new_node in req.get("newnodes") or []:
                cluster.nodes.append(new_fake_node(new_node))
            app = ResourceTypes(
                pods=list(req.get("pods") or []),
                deployments=list(req.get("deployments") or []),
                stateful_sets=list(req.get("statefulsets") or []),
                daemon_sets=list(req.get("daemonsets") or []),
                jobs=list(req.get("Jobs") or req.get("jobs") or []),
                config_maps=list(req.get("ConfigMaps") or req.get("configmaps") or []),
            )
            app.pods.extend(snap.pending_pods)
            result = simulate(cluster, [AppResource(name="test", resource=app)])
            return 200, simulate_response(result)
        except Exception as e:
            # the engine's transaction already rolled simulator state back;
            # report structured + counted (never a bare 500 string)
            count_http_error("deploy-apps", 500)
            return 500, error_body(500, str(e))
        finally:
            self.deploy_lock.release()

    def handle_scale_apps(self, req: dict) -> Tuple[int, object]:
        if not self.scale_lock.acquire(blocking=False):
            count_http_error("scale-apps", 503)
            return 503, error_body(
                503, "The server is busy, please try again later")
        try:
            snap = self.snapshot_fn()
            cluster = snap.resource.copy()  # see handle_deploy_apps
            for new_node in req.get("newnodes") or []:
                cluster.nodes.append(new_fake_node(new_node))
            cluster.pods = self._remove_pods_of_app(cluster.pods, req, snap)
            for req_ds in req.get("daemonsets") or []:
                for j, ds in enumerate(cluster.daemon_sets):
                    if (name_of(ds) == name_of(req_ds)
                            and namespace_of(ds) == namespace_of(req_ds)):
                        cluster.daemon_sets[j] = req_ds
                        break
            app = ResourceTypes(
                deployments=list(req.get("deployments") or []),
                stateful_sets=list(req.get("statefulsets") or []),
            )
            pending = self._remove_pods_of_app(snap.pending_pods, req, snap)
            app.pods = pending
            result = simulate(cluster, [AppResource(name="test", resource=app)])
            return 200, simulate_response(result)
        except Exception as e:
            count_http_error("scale-apps", 500)
            return 500, error_body(500, str(e))
        finally:
            self.scale_lock.release()

    def _remove_pods_of_app(
        self, pods: List[dict], req: dict, snap: ClusterSnapshot
    ) -> List[dict]:
        """removePodsOfApp (:404-444): strip pods owned by the scaled workloads
        (Deployments via their ReplicaSets; StatefulSets directly)."""
        selected: List[Tuple[str, str]] = []  # (kind, name)
        for deploy in req.get("deployments") or []:
            for rs in snap.replica_sets:
                if owned_by_workload(owner_references(rs), C.Deployment, name_of(deploy)):
                    selected.append((C.ReplicaSet, name_of(rs)))
        for sts in req.get("statefulsets") or []:
            selected.append((C.StatefulSet, name_of(sts)))
        out = []
        for pod in pods:
            refs = owner_references(pod)
            if not any(owned_by_workload(refs, k, n) for k, n in selected):
                out.append(pod)
        return out

    # ------------------------------------------------------ resident what-if ------

    def whatif_service(self):
        """The lazily-built WhatIfService (serve/batch.py), or None when the
        resident image's equivalence gates decline this cluster (gpu-share /
        open-local / node-advertised images) — /v1/whatif then reports 501
        rather than serving silently-different answers."""
        if not self.whatif:
            return None
        with self._whatif_lock:
            if self._whatif_svc is None and not self._whatif_declined:
                from ..serve import (AdmissionController, HAState,
                                     ResidentImage, WhatIfService)

                def build_image():
                    snap = self.snapshot_fn()
                    return ResidentImage.try_build(
                        snap.resource.nodes,
                        cluster_objects=snap.resource,
                        pods=list(snap.resource.pods)
                        + list(snap.pending_pods))

                if self.state_dir:
                    # simonha restore-or-build: load the checkpoint + replay
                    # the WAL tail when state exists; a lineage mismatch
                    # raises out of the first request loudly (500) rather
                    # than serving from doubted state
                    ha = HAState.open(
                        self.state_dir, build_image,
                        checkpoint_every=self.checkpoint_every,
                        staleness_ceiling_s=self.staleness_ceiling_s)
                    if ha is None:
                        self._whatif_declined = True
                        return None
                    self._ha = ha
                    image = ha.image
                else:
                    image = build_image()
                if image is None:
                    # cache the decline: try_build walks the whole cluster,
                    # and repeating that per request would turn the cheap
                    # 501 path into a serialized full re-encode per request
                    self._whatif_declined = True
                    return None
                admission = None
                if self.max_queue is not None:
                    admission = AdmissionController(
                        max_queue=self.max_queue,
                        tenant_rate=self.tenant_rate,
                        seed=self.shed_seed)
                self._whatif_svc = WhatIfService(
                    image, window_ms=self.whatif_window_ms,
                    fanout=self.whatif_fanout, admission=admission)
            return self._whatif_svc

    def start_watch(self) -> bool:
        """Start the simonsync watch loop(s) against `watch_spec`, feeding
        the resident image (through the HA WAL when --state-dir is on).
        Returns False when serving is off or the image declined."""
        if not self.watch_spec or not self.whatif:
            return False
        svc = self.whatif_service()
        if svc is None:
            return False
        with self._whatif_lock:
            ha = self._ha
        from ..live import (HttpWatchSource, RecordedSource, WatchSync,
                            kube_watch_sources)

        spec = self.watch_spec
        if spec.startswith("file:"):
            sources = [RecordedSource(path=spec[len("file:"):])]
        elif spec == "kube":
            from ..simulator.live import create_kube_client

            sources = kube_watch_sources(
                create_kube_client(self._kubeconfig, self._master))
        elif "|" in spec:
            watch_url, list_url = spec.split("|", 1)
            sources = [HttpWatchSource(watch_url, list_url=list_url)]
        else:
            sources = [HttpWatchSource(spec)]
        image = None if ha is not None else svc.image
        for i, src in enumerate(sources):
            sync = WatchSync(src, image=image, ha=ha,
                             state_dir=None if ha else self.state_dir,
                             name=f"src{i}" if len(sources) > 1 else "")
            self._syncs.append(sync)

            def _run(s=sync):
                try:
                    s.run(self._sync_stop)
                except Exception as e:  # noqa: BLE001 — surfaced via stats
                    self._sync_errors.append(f"{type(e).__name__}: {e}")
                    print(f"watch-sync died: {type(e).__name__}: {e}",
                          file=sys.stderr)

            t = threading.Thread(target=_run, name="watch-sync", daemon=True)
            t.start()
            self._sync_threads.append(t)
        return True

    def sync_stats(self) -> Optional[dict]:
        if not self._syncs:
            return None
        out: dict = {"sources": [s.stats() for s in self._syncs]}
        if self._sync_errors:
            out["errors"] = list(self._sync_errors)
        return out

    def handle_whatif(self, req: dict) -> Tuple[int, object]:
        """POST /v1/whatif: probe one what-if against the resident cluster
        image. Request: {"pods": [...], "deployments": [...],
        "statefulsets": [...], "jobs": [...], "drains": ["node", ...]}.
        Workloads expand to pods exactly like deploy-apps; `drains` overlays
        request-local node removals (the node and its pods leave) without
        mutating the shared image. Response: scheduled/total/unscheduled
        counts, cluster utilization, the image epoch the answer is consistent
        at, the micro-batch lane width, and the route taken
        (batched | fresh). With admission control on, a shed request gets a
        structured 429 carrying `retry_after_s`; with --state-dir, answers
        carry `staleness_s` (and the HTTP layer adds X-Simon-Epoch)."""
        if not self.whatif:
            count_http_error("whatif", 404)
            return 404, error_body(
                404, "resident what-if serving is off (start with "
                "`simon serve` / OPEN_SIMULATOR_WHATIF=1)")
        from ..serve.ha import ShedError

        try:
            svc = self.whatif_service()
            if svc is None:
                count_http_error("whatif", 501)
                return 501, error_body(
                    501, "resident what-if unavailable for this cluster "
                    "(gpu-share/open-local/node-images decline the image); "
                    "use /api/deploy-apps")
            from ..core.types import ResourceTypes
            from ..models.workloads import expand_workloads_excluding_daemonsets

            rt = ResourceTypes(
                pods=list(req.get("pods") or []),
                deployments=list(req.get("deployments") or []),
                stateful_sets=list(req.get("statefulsets") or []),
                jobs=list(req.get("Jobs") or req.get("jobs") or []),
            )
            pods = expand_workloads_excluding_daemonsets(rt)
            if not pods:
                count_http_error("whatif", 400)
                return 400, error_body(400, "what-if request has no pods")
            drains = [str(d) for d in (req.get("drains") or [])]
            deadline_s = req.get("deadline_s")
            resp = svc.submit(
                pods, drains, tenant=str(req.get("tenant") or "default"),
                deadline_s=float(deadline_s) if deadline_s is not None
                else None)
            if self._ha is not None:
                # mutates resp: staleness_s stamp + the wrong-epoch tripwire.
                # simonlint: ignore[race-unguarded-attr] -- _ha is written
                # exactly once, under _whatif_lock, BEFORE _whatif_svc is
                # published; this runs only after whatif_service() returned
                # non-None through that same lock, so the write
                # happens-before this read
                self._ha.stamp(resp)
            return 200, resp
        except ShedError as e:
            count_http_error("whatif", 429)
            body = error_body(429, str(e))
            body["reason"] = e.reason
            body["retry_after_s"] = round(e.retry_after, 3)
            return 429, body
        except Exception as e:
            count_http_error("whatif", 500)
            return 500, error_body(500, str(e))

    def handle_ingest(self, req: dict) -> Tuple[int, object]:
        """POST /v1/ingest: apply a batch of live watch-event deltas
        ({"events": [{"type": "pod_add"|"pod_delete"|"node_add"|
        "node_drain", ...}]}) to the resident image. The production server
        would feed this from a watch stream; the endpoint is the same code
        path, driveable by tests and the load generator."""
        if not self.whatif:
            count_http_error("ingest", 404)
            return 404, error_body(404, "resident what-if serving is off")
        try:
            svc = self.whatif_service()
            if svc is None:
                count_http_error("ingest", 501)
                return 501, error_body(
                    501, "resident what-if unavailable for this cluster")
            events = req.get("events") or []
            if not isinstance(events, list):
                count_http_error("ingest", 400)
                return 400, error_body(400, "'events' must be a list")
            if self._ha is not None:
                # WAL-ahead path: fsync'd record, then apply; any failure
                # (WalMismatch, injected fault) flips degraded mode and
                # surfaces as a structured 500 below.
                # simonlint: ignore[race-unguarded-attr] -- _ha is written
                # once, under _whatif_lock, before _whatif_svc is published;
                # this runs only after whatif_service() returned non-None
                # through that same lock, so the write happens-before it
                return 200, self._ha.ingest(events)
            return 200, svc.image.apply_events(events)
        except Exception as e:
            count_http_error("ingest", 500)
            return 500, error_body(500, str(e))

    def _shed_ingest_payload(self, length: int):
        """Bound /v1/ingest memory: over the per-request cap → 413; over the
        in-flight budget (4x the cap, summed across concurrent requests) →
        429. Returns (code, body) to shed, or None to admit — the caller
        must pair an admit with _release_ingest_bytes(length)."""
        if length > self.ingest_max_bytes:
            obs.SERVE_SHEDS.labels(reason="payload").inc()
            return 413, error_body(
                413, f"ingest payload of {length} bytes exceeds the "
                f"{self.ingest_max_bytes}-byte cap "
                f"(OPEN_SIMULATOR_INGEST_MAX_BYTES)")
        with self._ingest_bytes_lock:
            admitted = self._ingest_bytes + length <= 4 * self.ingest_max_bytes
            if admitted:
                self._ingest_bytes += length
        if not admitted:
            obs.SERVE_SHEDS.labels(reason="payload").inc()
            return 429, error_body(
                429, "too many ingest payload bytes in flight; retry")
        return None

    def _release_ingest_bytes(self, length: int) -> None:
        with self._ingest_bytes_lock:
            self._ingest_bytes -= length

    # --------------------------------------------------------------- serving ------

    # Default bounded drain: long enough for a worst-case cold-compile
    # simulation, short enough for a kube terminationGracePeriod.
    DRAIN_DEADLINE = 25.0

    def start(self, port: int = 8080, host: str = "",
              drain_deadline: Optional[float] = None) -> None:
        self._t_start = time.time()
        httpd = self.build_httpd(port, host)
        self.install_sigterm_handler(drain_deadline)
        if self.watch_spec:
            self.start_watch()
        print(f"simon server listening on :{port}")
        httpd.serve_forever()

    def install_sigterm_handler(self, drain_deadline: Optional[float] = None) -> None:
        """SIGTERM → graceful drain (kube pod-termination semantics)."""
        import signal

        def _on_term(signum, frame):
            # never drain on the signal frame itself: serve_forever must keep
            # running until the drain thread shuts it down
            threading.Thread(target=self.drain, args=(drain_deadline,),
                             name="simon-http-drain", daemon=True).start()

        try:
            signal.signal(signal.SIGTERM, _on_term)
        except ValueError:
            pass  # not the main thread (embedded use); the embedder owns signals

    # ------------------------------------------------------- drain machinery ------

    def _begin_request(self) -> bool:
        """Admit one request, or refuse (False) once draining started."""
        with self._state_cv:
            if self._draining:
                return False
            self._inflight += 1
            return True

    def _end_request(self) -> None:
        with self._state_cv:
            self._inflight -= 1
            self._state_cv.notify_all()

    @property
    def draining(self) -> bool:
        # simonlint: ignore[race-unguarded-attr] -- GIL-atomic bool read for
        # monitoring; admission itself re-checks under _state_cv in
        # _begin_request, so a stale False never admits past a drain
        return self._draining

    def drain(self, deadline: Optional[float] = None) -> int:
        """Graceful shutdown: stop admitting requests (new ones get 503),
        wait for in-flight requests up to `deadline` seconds, then stop the
        listener. Returns the number of requests still in flight when the
        deadline expired (0 = clean drain). Idempotent."""
        if deadline is None:
            deadline = self.DRAIN_DEADLINE
        until = time.monotonic() + max(0.0, deadline)
        with self._state_cv:
            self._draining = True
            while self._inflight > 0:
                left = until - time.monotonic()
                if left <= 0:
                    break
                self._state_cv.wait(timeout=min(left, 0.1))
            stranded = self._inflight
        # the watch loops stop BEFORE the HA WAL closes: a sync mid-flush
        # must not race a closed WAL handle
        self._sync_stop.set()
        for t in self._sync_threads:
            t.join(timeout=2.0)
        # read under the init lock: a request that won admission just before
        # _draining flipped may still be lazily creating the service; the
        # lock orders that creation before this read so its dispatcher is
        # stopped too instead of orphaned
        with self._whatif_lock:
            svc = self._whatif_svc
            ha = self._ha
        if svc is not None:
            svc.stop()  # wake the micro-batch dispatcher; queued requests fail fast
        if ha is not None:
            # in-flight requests finished (or were counted stranded) above;
            # close the WAL handle so the valid prefix is the final word
            ha.close()
        if self._scope_owned:
            # join the telemetry sampler and drop the trace buffer: the
            # scope this server created must not outlive it (a later
            # scope=False server in the same process would otherwise keep
            # tracing through the leftover global)
            from ..obs import scope as scope_mod

            scope_mod.disable()
            self._scope_owned = False
        httpd = self._httpd
        if httpd is not None:
            httpd.shutdown()
        return stranded

    def build_httpd(self, port: int = 8080, host: str = "") -> ThreadingHTTPServer:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet by default
                pass

            def _send(self, code: int, body: object,
                      headers: Optional[dict] = None) -> None:
                data = json.dumps(body).encode()
                self._last_code = code
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for key, value in (headers or {}).items():
                    self.send_header(key, value)
                self.end_headers()
                self.wfile.write(data)

            def _send_err(self, code: int, message: str, endpoint: str) -> None:
                count_http_error(endpoint, code)
                self._send(code, error_body(code, message))

            # Fixed route-family table for the simonscope edge: SLO/metric
            # endpoint labels must be BOUNDED (a per-pod /explain path or a
            # scanner probing random 404s must not mint unbounded label
            # children and window histograms), so paths normalize to these
            # families and everything else buckets to "other".
            _SCOPE_ROUTES = ("/v1/whatif", "/v1/ingest", "/v1/serve/stats",
                             "/v1/serve/trace", "/v1/pulse",
                             "/api/deploy-apps",
                             "/api/scale-apps", "/explain/", "/debug/vars",
                             "/debug/pprof/profile", "/debug/fault-plan")

            def _route_scoped(self, routes) -> None:
                """simonscope edge: mint the request's trace id at the HTTP
                boundary (the whatif path joins it downstream in
                WhatIfService.submit) and record per-endpoint edge latency
                into the SLO engine, labeled by status class. Scrape/health
                surfaces stay unwrapped so scraping never traces itself."""
                from ..obs import scope as scope_mod

                sc = scope_mod.active() if server.scope else None
                path = self.path.split("?")[0]
                if sc is None or path in ("/healthz", "/metrics", "/test"):
                    routes()
                    return
                family = next((r for r in self._SCOPE_ROUTES
                               if path == r or (r.endswith("/")
                                                and path.startswith(r))),
                              "other")
                endpoint = f"http:{family}"
                self._last_code = 200
                t_start = time.perf_counter()
                with sc.request_span(endpoint):
                    routes()
                total = time.perf_counter() - t_start
                sc.slo.record(endpoint, f"{self._last_code // 100}xx",
                              {"total": total},
                              error=self._last_code >= 500)

            def do_GET(self):
                # the drain gate: in-flight requests finish, new ones get 503
                if not server._begin_request():
                    self._send_err(503, "server is draining", "drain")
                    return
                try:
                    self._route_scoped(self._get_routes)
                finally:
                    server._end_request()

            def do_POST(self):
                if not server._begin_request():
                    self._send_err(503, "server is draining", "drain")
                    return
                try:
                    self._route_scoped(self._post_routes)
                finally:
                    server._end_request()

            def _get_routes(self):
                if self.path == "/healthz":
                    # simonha staleness ceiling: a degraded server keeps
                    # answering at the last consistent epoch, but past the
                    # ceiling it stops claiming health — the orchestrator's
                    # cue to restart/resync it
                    ha = server._ha
                    if ha is not None and not ha.healthy():
                        self._send(503, {
                            "message": "degraded past the staleness ceiling",
                            "reason": ha.degraded_reason(),
                            "staleness_s": round(ha.staleness_s(), 3),
                            "staleness_ceiling_s": ha.staleness_ceiling_s,
                        })
                    else:
                        self._send(200, {"message": "ok"})
                elif self.path == "/metrics" or self.path.startswith("/metrics?"):
                    # Prometheus scrape surface (the reference mounts
                    # kube-scheduler's metrics handler; server.go:152) —
                    # everything obs/instruments.py accumulates, text format.
                    # With scope on, the rolling-window quantile/burn gauges
                    # refresh first so the scrape carries current p50/p95/p99
                    # (scope off never touches those families: byte-identity).
                    from ..obs import REGISTRY
                    from ..obs import scope as scope_mod

                    sc = scope_mod.active() if server.scope else None
                    if sc is not None:
                        sc.slo.refresh_gauges()
                    data = REGISTRY.render_text().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                elif self.path.startswith("/debug/pprof/profile"):
                    # pprof-style CPU profile (server.go:152 registers pprof):
                    # sample ALL threads' stacks for ?seconds=N (default 5)
                    # and return flat hot-stack counts. The previous
                    # cProfile.enable(); sleep(); disable() only profiled the
                    # sleeping handler thread, so the dump never contained
                    # application work.
                    from urllib.parse import parse_qs, urlparse

                    q = parse_qs(urlparse(self.path).query)
                    seconds = min(float((q.get("seconds") or ["5"])[0]), 60.0)
                    data = sample_stacks(seconds).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                elif self.path.startswith("/explain/"):
                    # simonxray: one pod's decision record ('/explain/ns/name'
                    # or a bare unambiguous name), kube-parity event string
                    # included — the server-side `simon explain`
                    from urllib.parse import unquote

                    from ..obs import xray as xray_mod

                    rec = xray_mod.active() if server.xray else None
                    if rec is None:
                        self._send_err(
                            404, "xray recording is off (start the server "
                            "with --xray / OPEN_SIMULATOR_XRAY=1)", "explain")
                        return
                    pod = unquote(self.path[len("/explain/"):]).strip("/")
                    exp = rec.explain(pod)
                    if exp is None:
                        self._send_err(
                            404, f"no decision record for pod {pod!r} (use "
                            "'namespace/name'; records appear after a "
                            "deploy/scale simulation runs)", "explain")
                        return
                    self._send(200, {
                        "explanation": exp,
                        "rendered": xray_mod.render_explanation(exp),
                    })
                elif self.path == "/debug/vars":
                    # the profiling surface the reference exposes via pprof
                    # (server.go:152): uptime, rss, recent traced phases, and
                    # the flat metrics-registry view
                    import resource

                    from ..obs import REGISTRY
                    from ..obs import xray as xray_mod
                    from ..resilience import guard
                    from ..utils.trace import recent_spans

                    from ..obs import scope as scope_mod

                    started = getattr(server, "_t_start", None)
                    xrec = xray_mod.active() if server.xray else None
                    _scope = scope_mod.active() if server.scope else None
                    self._send(200, {
                        "uptime_seconds": (
                            round(time.time() - started, 3) if started else None),
                        "max_rss_kb": resource.getrusage(resource.RUSAGE_SELF).ru_maxrss,
                        "recent_traces": recent_spans(),
                        # simonguard containment state: quarantined backends,
                        # watchdog config, recent wedge/bisect/failover events
                        "guard": guard.state(),
                        # simonxray: record counts (incl. the TOTAL
                        # unscheduled count) + the most recent unscheduled
                        # pods' kube-parity reasons (bounded sample — the
                        # full set lives in the trace / `simon explain
                        # --unscheduled`)
                        **({"xray": {
                            **xrec.counts(),
                            "unscheduled_sample": xrec.unscheduled_summary(),
                        }} if xrec is not None else {}),
                        **({"scope": _scope.stats()} if _scope is not None
                           else {}),
                        "metrics": REGISTRY.values(),
                    })
                elif self.path == "/debug/fault-plan":
                    if not server.debug_faults:
                        self._send_err(403, "fault-plan endpoint disabled "
                                       "(start with --debug-faults)",
                                       "fault-plan")
                        return
                    from ..resilience import active_plan

                    plan = active_plan()
                    self._send(200, plan.to_json() if plan is not None else {})
                elif self.path == "/v1/serve/stats":
                    # simonserve: the resident image / dispatcher state —
                    # plus, with scope on, the SLO engine's rolling-window
                    # snapshot and the trace-buffer/sampler state (what
                    # `simon slo` and `simon top` render)
                    from ..obs import scope as scope_mod

                    svc = server._whatif_svc
                    if not server.whatif or svc is None:
                        self._send_err(
                            404, "resident what-if serving is off or not "
                            "yet built (POST /v1/whatif first)", "serve-stats")
                        return
                    stats = svc.stats()
                    if server._ha is not None:
                        stats["ha"] = server._ha.stats()
                    sync = server.sync_stats()
                    if sync is not None:
                        stats["sync"] = sync
                    sc = scope_mod.active() if server.scope else None
                    if sc is not None:
                        from ..obs import instruments as obs_i

                        stats["slo"] = sc.slo.snapshot()
                        stats["scope"] = sc.stats()
                        stats["scope"]["pools"] = {
                            s["labels"]["pool"]: s["value"]
                            for s in obs_i.SCOPE_POOL_BYTES.samples()}
                    self._send(200, stats)
                elif self.path == "/v1/serve/trace":
                    # simonscope: dump the in-memory request-trace buffer as
                    # perfetto-loadable Chrome trace-event JSON (spans + flow
                    # stitches + telemetry counter tracks)
                    from ..obs import REGISTRY
                    from ..obs import scope as scope_mod

                    sc = scope_mod.active() if server.scope else None
                    if sc is None:
                        self._send_err(
                            404, "simonscope is off (start with `simon "
                            "serve` or OPEN_SIMULATOR_SCOPE=1)", "serve-trace")
                        return
                    self._send(200, sc.chrome_trace(
                        metrics=REGISTRY.snapshot()))
                elif self.path == "/v1/pulse":
                    # simonpulse: the performance-ledger summary — per-
                    # (kernel, digest) warm-wall baselines, regression
                    # counts, achieved-roofline fractions, and the run-phase
                    # wall decomposition (what `simon pulse --url` renders)
                    from ..obs import pulse as pulse_mod

                    pl = pulse_mod.active()
                    if pl is None:
                        self._send_err(
                            404, "simonpulse is off (set "
                            "OPEN_SIMULATOR_PULSE=1)", "pulse")
                        return
                    self._send(200, pl.summary())
                elif self.path == "/test":
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.end_headers()
                    self.wfile.write(b"test")
                else:
                    self._send_err(404, "not found", "other")

            def _post_routes(self):
                length = int(self.headers.get("Content-Length") or 0)
                if self.path == "/v1/ingest":
                    # satellite: bound /v1/ingest memory BEFORE reading the
                    # body — an oversized or budget-busting payload is shed
                    # unread, and the connection drops (the request stream
                    # would otherwise desync on the unconsumed body)
                    shed = server._shed_ingest_payload(length)
                    if shed is not None:
                        code, body = shed
                        count_http_error("ingest", code)
                        self.close_connection = True
                        self._send(code, body,
                                   {"Retry-After": "1"} if code == 429
                                   else None)
                        return
                    try:
                        self._dispatch_post(length)
                    finally:
                        server._release_ingest_bytes(length)
                    return
                self._dispatch_post(length)

            def _dispatch_post(self, length: int) -> None:
                raw = self.rfile.read(length)
                try:
                    req = json.loads(raw or b"{}")
                except ValueError as e:  # JSONDecodeError + invalid-UTF-8
                    endpoint = self.path.rsplit("/", 1)[-1] or "other"
                    self._send_err(400, f"fail to unmarshal content: {e}",
                                   endpoint)
                    return
                if self.path == "/api/deploy-apps":
                    code, body = server.handle_deploy_apps(req)
                elif self.path == "/api/scale-apps":
                    code, body = server.handle_scale_apps(req)
                elif self.path == "/v1/whatif":
                    code, body = server.handle_whatif(req)
                elif self.path == "/v1/ingest":
                    code, body = server.handle_ingest(req)
                elif self.path == "/debug/fault-plan":
                    if not server.debug_faults:
                        self._send_err(403, "fault-plan endpoint disabled "
                                       "(start with --debug-faults)",
                                       "fault-plan")
                        return
                    code, body = server.handle_fault_plan(req)
                else:
                    self._send_err(404, "not found", "other")
                    return
                # handlers stay 2-tuple (the gRPC bridge and embedders unpack
                # them); HTTP-only headers derive from the body here
                headers = None
                if isinstance(body, dict):
                    if code == 429 and "retry_after_s" in body:
                        headers = {"Retry-After": str(max(
                            1, int(body["retry_after_s"] + 0.999)))}
                    elif (server._ha is not None and code == 200
                          and "epoch" in body):
                        headers = {"X-Simon-Epoch": str(body["epoch"])}
                self._send(code, body, headers)

        class Httpd(ThreadingHTTPServer):
            # the socketserver default backlog of 5 resets connections under
            # concurrent what-if traffic (observed at 16 simultaneous
            # clients); a serving process must absorb bursts, not RST them
            request_queue_size = 128

        httpd = Httpd((host, port), Handler)
        self._httpd = httpd
        return httpd

    # -------------------------------------------------------- debug fault plan ----

    def handle_fault_plan(self, req: dict) -> Tuple[int, object]:
        """POST /debug/fault-plan: install a deterministic FaultPlan for the
        next requests ({"seed": N} or {"faults": [{site, attempt, error}]});
        an empty object clears it. Returns the active plan as JSON — GETting
        the endpoint later shows the fired-injection trace."""
        from ..resilience import FaultPlan, clear_plan, install_plan

        if not req:
            clear_plan()
            return 200, {}
        try:
            plan = install_plan(FaultPlan.from_json(req))
        except (ValueError, KeyError, TypeError) as e:
            count_http_error("fault-plan", 400)
            return 400, error_body(400, f"bad fault plan: {e}")
        return 200, plan.to_json()
