"""Pre-scheduler pod-ordering heuristics (reference pkg/algo).

The reference's AffinityQueue/TolerationQueue Less functions ignore their second argument
(affinity.go:21-23, toleration.go:19-21), so Go's unstable sort produces an
implementation-defined permutation whose *intent* is "pods with nodeSelector (resp.
tolerations) first". We implement that intent with stable partitions — deterministic and
order-preserving within each class (documented deviation).

GreedQueue (greed.go:10-83) orders by descending max-share of cluster-total cpu/memory
(DRF-style), pods with a pre-set nodeName first.
"""

from __future__ import annotations

from typing import List

from ..utils.objutil import pod_resource_requests
from ..utils.quantity import parse_milli, parse_quantity


def sort_affinity(pods: List[dict]) -> List[dict]:
    """Pods with a nodeSelector first (stable)."""
    return sorted(pods, key=lambda p: 0 if (p.get("spec") or {}).get("nodeSelector") else 1)


def sort_toleration(pods: List[dict]) -> List[dict]:
    """Pods with tolerations first (stable)."""
    return sorted(pods, key=lambda p: 0 if (p.get("spec") or {}).get("tolerations") else 1)


def share(alloc: float, total: float) -> float:
    """algo.Share (greed.go:70-83)."""
    if total == 0:
        return 0.0 if alloc == 0 else 1.0
    return alloc / total


def pod_share(pod: dict, total_cpu_milli: float, total_mem: float) -> float:
    """Max of cpu/memory share of cluster totals (greed.go calculatePodShare)."""
    req = pod_resource_requests(pod)
    if not req:
        return 0.0
    return max(
        share(req.get("cpu", 0.0), total_cpu_milli),
        share(req.get("memory", 0.0), total_mem),
    )


def sort_greed(pods: List[dict], nodes: List[dict]) -> List[dict]:
    """Descending max-share; pods with nodeName first (stable within classes)."""
    total_cpu = sum(parse_milli(((n.get("status") or {}).get("allocatable") or {}).get("cpu", 0)) for n in nodes)
    total_mem = sum(parse_quantity(((n.get("status") or {}).get("allocatable") or {}).get("memory", 0)) for n in nodes)

    def key(p):
        bound = 0 if (p.get("spec") or {}).get("nodeName") else 1
        return (bound, -pod_share(p, total_cpu, total_mem))

    return sorted(pods, key=key)
