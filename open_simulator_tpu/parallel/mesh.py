"""Device-mesh sharding for the batched scheduler (the framework's TP/DP story).

The reference is a single-process Go binary whose only concurrency is a 16-way
goroutine fan-out over nodes inside findNodesThatFitPod
(vendor/.../generic_scheduler.go:333) — see SURVEY.md §2.3. The TPU-native
equivalent is a `jax.sharding.Mesh`:

- **node axis ("tensor parallelism")**: every [*, N] table and [N, *] carry row is
  sharded over the `nodes` mesh axis. Filtering and per-node scoring are then fully
  local to each shard; only the normalizers (max/min over the feasible set), the
  zone sums, and the winner argmax need cross-shard communication, which XLA inserts
  automatically (all-reduce over ICI) from the sharding annotations — no hand-written
  collectives, exactly the scaling-book recipe.
- **scenario axis ("data parallelism")**: independent what-if simulations (e.g. the
  capacity-planning add-node search evaluating several candidate node counts) are
  vmapped over a leading `scenarios` axis and sharded across it.

N must divide the shard count; `pad_batch_tables` appends infeasible phantom nodes
(static_mask=False everywhere) so placements can never land on padding.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import kernels
from ..simulator.encode import BatchTables, pad_batch_tables as _pad_batch_tables, plugin_flags

NODE_AXIS = "nodes"

# wave kernels that take a trailing `mesh` static: on a node-sharding mesh
# they run their epoch loop inside one shard_map region with exactly one
# all-reduce + one all-gather per epoch (see ops/kernels.py)
_MESH_STATIC_KERNELS = ("schedule_wave", "schedule_affinity_wave")
SCENARIO_AXIS = "scenarios"


def make_node_mesh(
    n_devices: Optional[int] = None, scenario_axis: int = 1, devices=None
) -> Mesh:
    """Mesh over the first `n_devices` devices. 1-D ('nodes') by default; pass
    scenario_axis>1 for a 2-D ('scenarios', 'nodes') mesh. `devices` overrides the
    default device list (e.g. jax.devices('cpu') for a virtual mesh)."""
    devs = list(devices) if devices is not None else jax.devices()
    n = n_devices if n_devices is not None else len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    devs = np.asarray(devs[:n])
    if scenario_axis > 1:
        if n % scenario_axis:
            raise ValueError(f"{n} devices not divisible by scenario axis {scenario_axis}")
        return Mesh(devs.reshape(scenario_axis, n // scenario_axis),
                    (SCENARIO_AXIS, NODE_AXIS))
    return Mesh(devs, (NODE_AXIS,))


# Node-axis padding lives with the encoder (numpy-only); re-exported here because
# the mesh path is its main consumer.
pad_batch_tables = _pad_batch_tables


def table_shardings(mesh: Mesh) -> kernels.Tables:
    """PartitionSpec per Tables field: node axis sharded, everything else replicated."""
    n = P(None, NODE_AXIS)   # [G, N] / [T, N] / [Tc, N]
    r = P()                  # replicated

    def s(spec):
        return NamedSharding(mesh, spec)

    return kernels.Tables(
        alloc=s(P(NODE_AXIS, None)),
        node_zone=s(P(NODE_AXIS)),
        static_mask=s(n), mask_taint=s(n), mask_unsched=s(n), mask_aff=s(n),
        mask_extra=s(n),
        simon_raw=s(n), nodeaff_raw=s(n), taint_raw=s(n), avoid_raw=s(n),
        image_raw=s(n), extra_raw=s(n),
        grp_requests=s(r), grp_nonzero=s(r), grp_unknown=s(r), grp_ports=s(r),
        counter_dom=s(n), counter_topo=s(r), topo_dom=s(n),
        counter_sel_match_g=s(r),
        req_aff_t=s(r), grp_aff_self=s(r), req_anti_t=s(r),
        pref_t=s(r), pref_w=s(r),
        dns_t=s(r), dns_maxskew=s(r), dns_self=s(r), dns_edom=s(r),
        sa_t=s(r), sa_maxskew=s(r), sa_self=s(r),
        ss_t=s(r), ss_skip=s(r),
        carr_dom=s(n), carr_topo=s(r),
        carr_anti_t=s(r), carr_w_t=s(r), carr_w_w=s(r),
        grp_carries=s(r),
        grp_gpu_mem=s(r), grp_gpu_num=s(r), grp_gpu_pre=s(r), grp_gpu_take=s(r),
        dev_total=s(P(NODE_AXIS, None)),
        grp_lvm_size=s(r), grp_lvm_vg=s(r), grp_sdev_size=s(r), grp_sdev_media=s(r),
        vg_cap=s(P(NODE_AXIS, None)), vg_nameid=s(P(NODE_AXIS, None)),
        sdev_cap=s(P(NODE_AXIS, None)), sdev_media=s(P(NODE_AXIS, None)),
    )


def carry_shardings(mesh: Mesh) -> kernels.Carry:
    def s(spec):
        return NamedSharding(mesh, spec)

    return kernels.Carry(
        requested=s(P(NODE_AXIS, None)),
        nonzero=s(P(NODE_AXIS, None)),
        port_used=s(P(NODE_AXIS, None)),
        counter=s(P()),   # [T, D+1] domain counters are global state → replicated
        carrier=s(P()),
        dev_used=s(P(NODE_AXIS, None)),
        vg_req=s(P(NODE_AXIS, None)),
        sdev_alloc=s(P(NODE_AXIS, None)),
    )


def to_device_sharded(
    bt: BatchTables, mesh: Mesh
) -> Tuple[kernels.Tables, kernels.Carry, BatchTables]:
    """Pad to the mesh's node-shard count and device_put with shardings committed, so
    the sharded kernel executables (`sharded_kernels`) receive inputs already in
    their declared layout — the pad is a no-op when the encoder pre-aligned the
    node axis (engine.encode_batch), and the batched device_put pre-partitions
    every table in one host→device staging pass."""
    shards = mesh.shape[NODE_AXIS]
    bt = pad_batch_tables(bt, shards)
    ts, cs = table_shardings(mesh), carry_shardings(mesh)
    # ONE batched transfer per struct: device_put over the (arrays, shardings)
    # pytree pair stages every pre-partitioned leaf together instead of paying
    # a dispatch per table
    tables = jax.device_put(
        kernels.Tables(*(np.asarray(v) for v in tables_from_batch(bt))), ts)
    carry = jax.device_put(
        kernels.Carry(
            requested=bt.seed_requested,
            nonzero=bt.seed_nonzero,
            port_used=bt.seed_port_used,
            counter=bt.seed_counter,
            carrier=bt.seed_carrier,
            dev_used=bt.seed_dev_used,
            vg_req=bt.seed_vg_req,
            sdev_alloc=bt.seed_sdev_alloc,
        ), cs)
    return tables, carry, bt


def schedule_batch_on_mesh(bt: BatchTables, mesh: Mesh):
    """Run one schedulePods batch with the node axis sharded over `mesh`,
    through the explicitly-sharded executable set (carry donated into the
    scan's output where dispatching donated executables is sound — see
    donation_runtime_safe; multi-device CPU meshes downgrade to the
    undonated view).

    Returns (final_carry, choices[P] int32). Choices index the ORIGINAL node list —
    phantom padding is infeasible by construction, so indices never exceed the real N.
    """
    tables, carry, bt = to_device_sharded(bt, mesh)
    enable_gpu, enable_storage = plugin_flags(bt)
    sk = sharded_kernels(mesh)
    final, choices = sk.schedule_batch(
        tables, carry,
        bt.pod_group, bt.forced_node, bt.valid,
        n_zones=bt.n_zones,
        enable_gpu=enable_gpu,
        enable_storage=enable_storage,
    )
    return final, choices


def schedule_scenarios_on_mesh(bt: BatchTables, mesh: Mesh, seed_requested_s: np.ndarray):
    """DP analog: evaluate S independent what-if scenarios (same cluster + pod batch,
    different starting utilization, e.g. candidate add-node states in the capacity
    planner) in one compiled program. `seed_requested_s` is [S, N, R]; the scenario
    axis shards over the mesh's 'scenarios' axis, the node axis over 'nodes'.
    Returns choices [S, P]."""
    if SCENARIO_AXIS not in mesh.shape:
        raise ValueError("mesh has no scenario axis; build with make_node_mesh(n, scenario_axis=k)")
    shards = mesh.shape[NODE_AXIS]
    bt = pad_batch_tables(bt, shards)
    # Tables are scenario-invariant: same shardings as the 1-D path (node axis
    # sharded, rest replicated over every mesh axis including 'scenarios').
    ts = table_shardings(mesh)
    tables = kernels.Tables(*(
        jax.device_put(np.asarray(v), s) for v, s in zip(tables_from_batch(bt), ts)
    ))
    S = seed_requested_s.shape[0]
    n_pad = bt.seed_requested.shape[0]
    if seed_requested_s.shape[1] > n_pad:
        raise ValueError(
            f"seed_requested_s node axis {seed_requested_s.shape[1]} exceeds the "
            f"padded node count {n_pad}; build seeds against the unpadded cluster "
            f"(or pad_batch_tables(bt, {shards}))"
        )
    if seed_requested_s.shape[1] < n_pad:
        seed_requested_s = np.pad(
            seed_requested_s, ((0, 0), (0, n_pad - seed_requested_s.shape[1]), (0, 0))
        )

    def rep(a):  # broadcast a seed over scenarios
        return np.broadcast_to(a[None], (S,) + a.shape).copy()

    def sh(spec):
        return NamedSharding(mesh, spec)

    carry = kernels.Carry(
        requested=jax.device_put(seed_requested_s.astype(np.float32),
                                 sh(P(SCENARIO_AXIS, NODE_AXIS, None))),
        nonzero=jax.device_put(rep(bt.seed_nonzero), sh(P(SCENARIO_AXIS, NODE_AXIS, None))),
        port_used=jax.device_put(rep(bt.seed_port_used), sh(P(SCENARIO_AXIS, NODE_AXIS, None))),
        counter=jax.device_put(rep(bt.seed_counter), sh(P(SCENARIO_AXIS, None, None))),
        carrier=jax.device_put(rep(bt.seed_carrier), sh(P(SCENARIO_AXIS, None, None))),
        dev_used=jax.device_put(rep(bt.seed_dev_used), sh(P(SCENARIO_AXIS, NODE_AXIS, None))),
        vg_req=jax.device_put(rep(bt.seed_vg_req), sh(P(SCENARIO_AXIS, NODE_AXIS, None))),
        sdev_alloc=jax.device_put(rep(bt.seed_sdev_alloc), sh(P(SCENARIO_AXIS, NODE_AXIS, None))),
    )
    enable_gpu, enable_storage = plugin_flags(bt)
    vmapped = jax.vmap(
        # simonlint: ignore[naked-dispatch] -- multichip dry-run harness, not
        # an engine hot path: callers own the wedge exposure (bench/tests)
        lambda c: kernels.schedule_batch(
            tables, c,
            jax.numpy.asarray(bt.pod_group),
            jax.numpy.asarray(bt.forced_node),
            jax.numpy.asarray(bt.valid),
            n_zones=bt.n_zones,
            enable_gpu=enable_gpu,
            enable_storage=enable_storage,
        )
    )
    with mesh:
        _, choices = vmapped(carry)
    return choices


def tables_from_batch(bt: BatchTables) -> kernels.Tables:
    """Assemble a kernels.Tables from a BatchTables BY FIELD NAME — the single place
    that maps between the two structs, immune to field reordering."""
    return kernels.Tables(**{f: getattr(bt, f) for f in kernels.Tables._fields})


# ----------------------------------------------------------------------------
# Multi-candidate probe fan-out (kernels.probe_*_fanout): the capacity
# planner's candidate lanes are independent what-if scenarios, so the vmapped
# [S] axis shards over the 'scenarios' mesh axis — one candidate node count
# per device — while the tables stay node-sharded/replicated as usual.
# ----------------------------------------------------------------------------


def make_scenario_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """Pure-DP mesh ('scenarios' = n, 'nodes' = 1) for the capacity prober's
    multi-candidate fan-out: each candidate lane lands on its own device and
    no cross-device collectives are needed within a lane."""
    devs = list(devices) if devices is not None else jax.devices()
    n = n_devices if n_devices is not None else len(devs)
    return make_node_mesh(n, scenario_axis=n, devices=devs)


def fanout_shardings(mesh: Mesh):
    """(tables_sharding, carry_s_sharding, active_s_sharding) for the
    probe_*_fanout kernels: tables as in table_shardings (node axis sharded —
    trivially replicated on a pure-scenario mesh), carry leaves and the active
    mask sharded over their leading [S] candidate axis."""

    def s(spec):
        return NamedSharding(mesh, spec)

    carry_s = kernels.Carry(
        requested=s(P(SCENARIO_AXIS, NODE_AXIS, None)),
        nonzero=s(P(SCENARIO_AXIS, NODE_AXIS, None)),
        port_used=s(P(SCENARIO_AXIS, NODE_AXIS, None)),
        counter=s(P(SCENARIO_AXIS, None, None)),
        carrier=s(P(SCENARIO_AXIS, None, None)),
        dev_used=s(P(SCENARIO_AXIS, NODE_AXIS, None)),
        vg_req=s(P(SCENARIO_AXIS, NODE_AXIS, None)),
        sdev_alloc=s(P(SCENARIO_AXIS, NODE_AXIS, None)),
    )
    return table_shardings(mesh), carry_s, s(P(SCENARIO_AXIS, NODE_AXIS))


def put_fanout_inputs(mesh: Mesh, bt: BatchTables, carry_s_np, active_s_np):
    """device_put the probe fan-out inputs with their mesh shardings: returns
    (tables, carry_s, active_s) ready for kernels.probe_*_fanout inside a
    `with mesh:` block. carry_s_np leaves carry a leading [S] axis; S must be
    divisible by the mesh's scenario-axis size."""
    ts, cs, as_ = fanout_shardings(mesh)
    tables = jax.device_put(
        kernels.Tables(*(np.asarray(v) for v in tables_from_batch(bt))), ts)
    carry_s = jax.device_put(
        kernels.Carry(*(np.ascontiguousarray(v) for v in carry_s_np)), cs)
    return tables, carry_s, jax.device_put(np.asarray(active_s_np), as_)


# ----------------------------------------------------------------------------
# Sharded kernel executables: explicit in/out shardings end-to-end.
#
# Committing shardings only at to_device_sharded leaves every jit free to
# re-infer (and silently re-shard) its outputs per call; chained per-segment
# dispatches then round-trip the carry through whatever layout XLA picked.
# These wrappers pin BOTH sides of every hot kernel: inputs arrive in the
# table/carry shardings, outputs leave in the SAME carry shardings, so wave
# N's output feeds wave N+1 with zero resharding collectives at the boundary
# — and the carry buffers are donated, so the per-segment/per-epoch loop
# updates cluster state in place instead of allocating a fresh [N, R] set per
# dispatch. One executable set is cached per (mesh, donate) and shared by
# every Simulator/ProbeSession over an equal mesh: a warm second dispatch is
# zero recompiles.
# ----------------------------------------------------------------------------


def _unwrap(fn):
    """The undecorated kernel (jax.jit stores it on __wrapped__): re-jitting
    the wrapped form avoids nesting one jit inside another."""
    return getattr(fn, "__wrapped__", fn)


def _mesh_key(mesh: Mesh) -> tuple:
    return (mesh.axis_names, tuple(mesh.devices.shape),
            tuple(d.id for d in mesh.devices.flat))


_SHARDED_CACHE: Dict[tuple, "ShardedKernels"] = {}


def donation_runtime_safe(mesh: Mesh) -> bool:
    """Whether DISPATCHING donated executables is sound on this mesh.

    On multi-device CPU meshes the XLA:CPU async runtime intermittently
    corrupts the in-place-aliased carry of a donated dispatch (~1/3 of
    dispatches under a warm compile cache: garbage leaves with otherwise
    correct outputs, and — worse — a watchdog-abandoned zombie dispatch
    keeps writing into donated buffers the engine still owns, which is how
    the wedge-failover smoke intermittently diverged). Observed on the
    probe fan-out, the one-shot batch helper, AND the engine chain;
    device-side copies and block_until_ready before the fetch still read
    garbage, pinning it to the aliased execution itself. Donation stays on
    for accelerator meshes (the production perf story) and single-device
    meshes; LOWERING a donated executable is always safe — simonaudit
    certifies the donated artifact without ever executing it."""
    devs = list(mesh.devices.flat)
    return len(devs) <= 1 or any(d.platform != "cpu" for d in devs)


def sharded_kernels(mesh: Mesh, donate: bool = True) -> "ShardedKernels":
    """The cached sharded-executable set for `mesh`. Instances with equal
    meshes share one jit cache (ShardedKernels caches its jitted callables
    per (kernel, donate), and jax.jit keys on sharding equality), so every
    engine batch / probe round over the same mesh reuses warm executables.

    Donation requests are downgraded to the undonated view whenever
    dispatching donated executables is unsound on this mesh
    (donation_runtime_safe): same layouts, same jit cache, inputs kept
    alive."""
    key = _mesh_key(mesh)
    got = _SHARDED_CACHE.get(key)
    if got is None:
        got = _SHARDED_CACHE[key] = ShardedKernels(mesh)
    return got if (donate and donation_runtime_safe(mesh)) else got.undonated()


class ShardedKernels:
    """Jitted variants of every hot kernel with explicit in_shardings /
    out_shardings built from table_shardings/carry_shardings (and the fan-out
    shardings on a scenario mesh), plus donate_argnums on the carry.

    Call signatures are identical to the `kernels` module functions, so the
    engine's dispatch loop and the probe fan-out swap between the two by
    swapping the namespace. Donation is an instance-level mode:
    `sharded_kernels(mesh, donate=False)` returns a view sharing this
    instance's jit cache whose dispatches keep their input carry alive (the
    xray recorder reads segment-start carries after the fact)."""

    def __init__(self, mesh: Mesh, _shared=None, _donate: bool = True) -> None:
        self.mesh = mesh
        self.donate = _donate
        self._built: Dict[tuple, object] = (
            _shared if _shared is not None else {})
        self.table_sh = table_shardings(mesh)
        self.carry_sh = carry_shardings(mesh)
        self.rep = NamedSharding(mesh, P())
        self.node_sh = NamedSharding(mesh, P(NODE_AXIS))
        if SCENARIO_AXIS in mesh.shape:
            _, self.carry_s_sh, self.active_sh = fanout_shardings(mesh)
            self.lane_sh = NamedSharding(mesh, P(SCENARIO_AXIS))
            # sweep fan-out outputs: [S, K, N] per-segment placement counts
            # and [S, P] per-lane pod choices, both lane-sharded so every
            # scenario's results stay on its own device until the fetch
            self.lane_sn_sh = NamedSharding(mesh, P(SCENARIO_AXIS, None,
                                                    NODE_AXIS))
            self.lane_p_sh = NamedSharding(mesh, P(SCENARIO_AXIS, None))
        else:
            self.carry_s_sh = self.active_sh = self.lane_sh = None
            self.lane_sn_sh = self.lane_p_sh = None

    def undonated(self) -> "ShardedKernels":
        """A view over the same jit cache whose carry inputs survive the
        dispatch (donation off) — used while the xray recorder is active."""
        view = self._built.get("__undonated_view__")
        if view is None:
            view = ShardedKernels(self.mesh, _shared=self._built,
                                  _donate=False)
            self._built["__undonated_view__"] = view
        return view

    def _jit(self, name, build, shared: bool = False):
        # `shared`: donation-independent executables (diagnostics never
        # donate), so the donating and undonated views reuse one jit
        key = name if shared else (name, self.donate)
        fn = self._built.get(key)
        if fn is None:
            fn = self._built[key] = build()
        return fn

    def _sched_jit(self, name, n_dyn, n_static, out_tail, donate_ok=True,
                   in_head=None):
        """jit one kernel with explicit shardings. pjit forbids kwargs once
        in_shardings is set, so statics are positional (static_argnums) and
        every wrapper below calls in the kernel's declared argument order.
        `n_dyn` dynamic args follow the (tables, carry) pair (or the fan-out
        (tables, carry_s, active_s) triple when in_head is given)."""
        head = in_head if in_head is not None else (self.table_sh,
                                                    self.carry_sh)
        first_static = len(head) + n_dyn
        donate = (1,) if (self.donate and donate_ok) else ()
        return jax.jit(
            _unwrap(getattr(kernels, name)),
            static_argnums=tuple(range(first_static, first_static + n_static)),
            in_shardings=head + (self.rep,) * n_dyn,
            out_shardings=out_tail,
            donate_argnums=donate,
        )

    def _tail_shardings(self, symbols):
        """Resolve a HOT_KERNELS out-tail symbol tuple to shardings."""
        table = {"carry": self.carry_sh, "carry_s": self.carry_s_sh,
                 "node": self.node_sh, "lane": self.lane_sh, "rep": self.rep,
                 "lane_sn": self.lane_sn_sh, "lane_p": self.lane_p_sh}
        return tuple(table[s] for s in symbols)

    def _kernel_jit(self, name, stats=False):
        """The cached explicitly-sharded jit for registry kernel `name` —
        the single source of truth the wrapper methods AND the simonaudit
        lowering path (analysis/hlo.py via `lowerable`) share, so the audited
        executable is byte-for-byte the one the engine dispatches."""
        if stats and name != "schedule_affinity_wave":
            # only the affinity wave has a stats output variant; silently
            # widening another kernel's out-tail would cache a wrong-arity
            # executable under its plain key
            raise ValueError(f"{name} has no stats variant")
        spec = kernels.HOT_KERNELS[name]
        n_static = len(spec.statics(2))
        if name in _MESH_STATIC_KERNELS:
            n_static += 1  # trailing static: kernel-internal shard_map mesh
        if spec.out is None:  # diagnostics: never donated, no out_shardings
            return self._jit(name, lambda: self._sched_jit(
                name, 3, n_static, None, donate_ok=False), shared=True)
        tail = spec.out + (("rep",) if stats else ())
        # the stats flag changes the output arity -> one executable per value
        key = f"{name}:{bool(stats)}" if name == "schedule_affinity_wave" \
            else name
        head = self._fanout_head(name) if spec.fanout else None
        return self._jit(key, lambda: self._sched_jit(
            name, 3, n_static, self._tail_shardings(tail), in_head=head))

    def lowerable(self, name, *, n_zones=2, stats=False):
        """(jit_fn, spec, meta) for simonaudit: the sharded executable
        builder for `name` plus everything the auditor needs to lower it
        abstractly — canonical statics, head arity, and where donation is
        declared. The jit object is the SAME cached one the dispatch
        wrappers use."""
        spec = kernels.HOT_KERNELS[name]
        statics = spec.statics(n_zones)
        if name == "schedule_affinity_wave":
            statics = statics[:-1] + (bool(stats),)
        if name in _MESH_STATIC_KERNELS:
            statics = statics + (self._wave_mesh(),)
        donated = (1,) if (spec.out is not None and self.donate) else ()
        meta = {"head": 3 if spec.fanout else 2, "statics": statics,
                "donate_argnums": donated}
        return self._kernel_jit(name, stats=stats), spec, meta

    # ------------------------------------------------- engine dispatches ----

    def _wave_mesh(self):
        """The kernel-internal shard_map mesh for the wave kernels: this
        mesh itself when its single node axis actually shards (>1 device) —
        the epoch-amortized collective path in ops/kernels.py — else None
        (serial lowering). Scenario fan-out meshes stay None: their node
        axis replicates, so there is nothing to amortize."""
        if (self.mesh.axis_names == (NODE_AXIS,)
                and self.mesh.shape[NODE_AXIS] > 1):
            return self.mesh
        return None

    def schedule_wave(self, tb, cry, g, m, cap1, *, gpu_live=False,
                      w=kernels.DEFAULT_WEIGHTS, filters=kernels.DEFAULT_FILTERS,
                      block=kernels.WAVE_BLOCK, kmax=0):
        fn = self._kernel_jit("schedule_wave")
        return fn(tb, cry, g, m, cap1, gpu_live, w, filters, block, kmax,
                  self._wave_mesh())

    def schedule_affinity_wave(self, tb, cry, g, m, cap1, *, ss_live=False,
                               w=kernels.DEFAULT_WEIGHTS,
                               filters=kernels.DEFAULT_FILTERS,
                               block=kernels.WAVE_BLOCK, n_zones=2,
                               stats=False):
        fn = self._kernel_jit("schedule_affinity_wave", stats=stats)
        return fn(tb, cry, g, m, cap1, ss_live, w, filters, block, n_zones,
                  stats, self._wave_mesh())

    def schedule_group_serial(self, tb, cry, g, valid, cap1, *,
                              w=kernels.DEFAULT_WEIGHTS,
                              filters=kernels.DEFAULT_FILTERS,
                              ss_live=False, sa_live=False, n_zones=2):
        fn = self._kernel_jit("schedule_group_serial")
        return fn(tb, cry, g, valid, cap1, w, filters, ss_live, sa_live,
                  n_zones)

    def schedule_batch(self, tb, cry, pod_group, forced_node, valid, *,
                       n_zones, enable_gpu=True, enable_storage=True,
                       w=kernels.DEFAULT_WEIGHTS,
                       filters=kernels.DEFAULT_FILTERS):
        fn = self._kernel_jit("schedule_batch")
        return fn(tb, cry, pod_group, forced_node, valid, n_zones, enable_gpu,
                  enable_storage, w, filters)

    # ------------------------------------------------------- diagnostics ----
    # in_shardings only (out_shardings=None): both are one-shot
    # fetch-to-host diagnostics whose outputs are never chained into another
    # dispatch, and some output leaves are scalars (inert score components),
    # which a node-axis out-sharding prefix cannot describe. Never donated:
    # the engine re-reads the same carry for every (group, forced, segment)
    # key.

    def feasibility_jit(self, tb, cry, g, forced, valid, *, enable_gpu=True,
                        enable_storage=True, include_dns=True,
                        include_interpod=True,
                        filters=kernels.DEFAULT_FILTERS):
        fn = self._kernel_jit("feasibility_jit")
        return fn(tb, cry, g, forced, valid, enable_gpu, enable_storage,
                  include_dns, include_interpod, filters)

    def explain_jit(self, tb, cry, g, forced, valid, *, n_zones,
                    enable_gpu=True, enable_storage=True,
                    w=kernels.DEFAULT_WEIGHTS,
                    filters=kernels.DEFAULT_FILTERS):
        fn = self._kernel_jit("explain_jit")
        return fn(tb, cry, g, forced, valid, n_zones, enable_gpu,
                  enable_storage, w, filters)

    # ------------------------------------------- probe fan-out dispatches ----
    # Scenario-mesh only (make_scenario_mesh): the [S] candidate axis shards
    # over SCENARIO_AXIS -- devices buy probe breadth, not replication -- and
    # the [S]-carry chains donated between segments exactly like the engine's.

    def _fanout_head(self, name):
        if self.carry_s_sh is None:
            raise ValueError(
                f"{name} needs a mesh with a '{SCENARIO_AXIS}' axis "
                f"(make_scenario_mesh); this mesh has {self.mesh.axis_names}")
        return (self.table_sh, self.carry_s_sh, self.active_sh)

    def probe_wave_fanout(self, tb, cry_s, active_s, g, m, cap1, *,
                          gpu_live=False, w=kernels.DEFAULT_WEIGHTS,
                          filters=kernels.DEFAULT_FILTERS,
                          block=kernels.WAVE_BLOCK, kmax=0):
        fn = self._kernel_jit("probe_wave_fanout")
        return fn(tb, cry_s, active_s, g, m, cap1, gpu_live, w, filters,
                  block, kmax)

    def probe_affinity_wave_fanout(self, tb, cry_s, active_s, g, m, cap1, *,
                                   ss_live=False, w=kernels.DEFAULT_WEIGHTS,
                                   filters=kernels.DEFAULT_FILTERS,
                                   block=kernels.WAVE_BLOCK, n_zones=2):
        fn = self._kernel_jit("probe_affinity_wave_fanout")
        return fn(tb, cry_s, active_s, g, m, cap1, ss_live, w, filters,
                  block, n_zones)

    def probe_group_serial_fanout(self, tb, cry_s, active_s, g, valid, cap1,
                                  *, w=kernels.DEFAULT_WEIGHTS,
                                  filters=kernels.DEFAULT_FILTERS,
                                  ss_live=False, sa_live=False, n_zones=2):
        fn = self._kernel_jit("probe_group_serial_fanout")
        return fn(tb, cry_s, active_s, g, valid, cap1, w, filters, ss_live,
                  sa_live, n_zones)

    def probe_serial_fanout(self, tb, cry_s, active_s, pod_group, forced_node,
                            valid, *, n_zones, enable_gpu=True,
                            enable_storage=True, w=kernels.DEFAULT_WEIGHTS,
                            filters=kernels.DEFAULT_FILTERS):
        fn = self._kernel_jit("probe_serial_fanout")
        return fn(tb, cry_s, active_s, pod_group, forced_node, valid,
                  n_zones, enable_gpu, enable_storage, w, filters)

    def serve_whatif_fanout(self, tb, cry_s, active_s, pod_group, forced_node,
                            valid_s, *, n_zones, enable_gpu=True,
                            enable_storage=True, w=kernels.DEFAULT_WEIGHTS,
                            filters=kernels.DEFAULT_FILTERS):
        fn = self._kernel_jit("serve_whatif_fanout")
        return fn(tb, cry_s, active_s, pod_group, forced_node, valid_s,
                  n_zones, enable_gpu, enable_storage, w, filters)

    def serve_wave_fanout(self, tb, cry_s, active_s, g_s, m_s, cap1_s, *,
                          w=kernels.DEFAULT_WEIGHTS,
                          filters=kernels.DEFAULT_FILTERS,
                          block=kernels.WAVE_BLOCK, kmax=0):
        fn = self._kernel_jit("serve_wave_fanout")
        return fn(tb, cry_s, active_s, g_s, m_s, cap1_s, w, filters, block,
                  kmax)

    def sweep_wave_fanout(self, tb, cry_s, active_s, g_sk, m_sk, cap1_sk, *,
                          w=kernels.DEFAULT_WEIGHTS,
                          filters=kernels.DEFAULT_FILTERS,
                          block=kernels.WAVE_BLOCK, kmax=0):
        fn = self._kernel_jit("sweep_wave_fanout")
        return fn(tb, cry_s, active_s, g_sk, m_sk, cap1_sk, w, filters,
                  block, kmax)

    def sweep_whatif_fanout(self, tb, cry_s, active_s, pod_group_s,
                            forced_node_s, valid_s, *, n_zones,
                            enable_gpu=True, enable_storage=True,
                            w=kernels.DEFAULT_WEIGHTS,
                            filters=kernels.DEFAULT_FILTERS):
        fn = self._kernel_jit("sweep_whatif_fanout")
        return fn(tb, cry_s, active_s, pod_group_s, forced_node_s, valid_s,
                  n_zones, enable_gpu, enable_storage, w, filters)


def carry_reshard_bytes(carry, shardings) -> int:
    """Bytes a chained dispatch would move to reconcile `carry`'s actual
    layout with the declared carry shardings — the regression signal behind
    simon_reshard_bytes_total and the bench mesh rows' `reshard_bytes` stat.
    With the sharded executables pinning out_shardings this is provably 0;
    anything nonzero means a dispatch path dropped its explicit shardings and
    XLA re-inferred a different layout."""
    total = 0
    for leaf, want in zip(carry, shardings):
        sh = getattr(leaf, "sharding", None)
        if sh is None:
            continue
        if not sh.is_equivalent_to(want, leaf.ndim):
            total += leaf.nbytes
    return total


# ----------------------------------------------------------------------------
# Shard-local node-axis growth: the incremental prober's template-column
# extension without a host round-trip. Every appended column is a verbatim
# copy of the template column ALREADY RESIDENT on the device (verified
# bit-identical at session build), and phantom re-padding writes constants —
# so the whole extension is one compiled concat per table, shard-local under
# the mesh shardings, transferring zero bytes from the host. Only valid when
# extend_node_axis would not widen the domain axis (no hostname-keyed
# counter/carrier rows); probe.ProbeSession falls back to the host re-upload
# otherwise.
# ----------------------------------------------------------------------------

# Phantom fills mirror pad_batch_tables exactly: a padded column must be
# indistinguishable from one it would have produced.
_EXT_GN_FILL = (
    ("static_mask", False), ("mask_taint", False), ("mask_unsched", False),
    ("mask_aff", False), ("mask_extra", False),
    ("simon_raw", 0), ("nodeaff_raw", 0), ("taint_raw", 0), ("avoid_raw", 0),
    ("image_raw", 0), ("extra_raw", 0),
)
_EXT_DOM_FIELDS = ("counter_dom", "topo_dom", "carr_dom")
_EXT_NROW_FILL = (
    ("alloc", 0), ("dev_total", 0), ("vg_cap", 0), ("vg_nameid", 0),
    ("sdev_cap", 0), ("sdev_media", 0),
)


def _extend_tables_impl(tb: kernels.Tables, n_real: int, k: int,
                        template_col: int, n_pad_new: int,
                        sentinel: int) -> kernels.Tables:
    import jax.numpy as jnp

    pad = n_pad_new - n_real - k

    def cols(a, fill):  # [*, N_old_pad] -> [*, n_pad_new] along the last axis
        parts = [a[..., :n_real],
                 jnp.repeat(a[..., template_col:template_col + 1], k, axis=-1)]
        if pad:
            parts.append(jnp.full(a.shape[:-1] + (pad,), fill, a.dtype))
        return jnp.concatenate(parts, axis=-1)

    def rows(a, fill):  # [N_old_pad, *] -> [n_pad_new, *]
        parts = [a[:n_real],
                 jnp.repeat(a[template_col:template_col + 1], k, axis=0)]
        if pad:
            parts.append(jnp.full((pad,) + a.shape[1:], fill, a.dtype))
        return jnp.concatenate(parts, axis=0)

    upd = {f: cols(getattr(tb, f), fill) for f, fill in _EXT_GN_FILL}
    upd.update({f: cols(getattr(tb, f), sentinel) for f in _EXT_DOM_FIELDS})
    upd.update({f: rows(getattr(tb, f), fill) for f, fill in _EXT_NROW_FILL})
    upd["node_zone"] = cols(tb.node_zone, 0)
    return tb._replace(**upd)


_EXTEND_JITS: Dict[object, object] = {}


def extend_tables_on_device(tables: kernels.Tables, *, n_real: int, k: int,
                            template_col: int, n_pad_new: int, sentinel: int,
                            mesh: Optional[Mesh] = None) -> kernels.Tables:
    """Grow device-resident Tables by k template-column copies (+ phantom
    re-pad to n_pad_new), entirely on device. `n_real` is the current real
    column count (old phantom columns are overwritten), `sentinel` the padded
    domain sentinel id (unchanged by gate). With `mesh`, the program runs
    under the table shardings so each shard grows locally."""
    key = _mesh_key(mesh) if mesh is not None else None
    fn = _EXTEND_JITS.get(key)
    if fn is None:
        if mesh is None:
            fn = jax.jit(_extend_tables_impl,
                         static_argnums=(1, 2, 3, 4, 5))
        else:
            ts = table_shardings(mesh)
            fn = jax.jit(_extend_tables_impl,
                         static_argnums=(1, 2, 3, 4, 5),
                         in_shardings=(ts,), out_shardings=ts)
        _EXTEND_JITS[key] = fn
    return fn(tables, n_real, k, template_col, n_pad_new, sentinel)
