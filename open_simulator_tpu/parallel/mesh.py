"""Device-mesh sharding for the batched scheduler (the framework's TP/DP story).

The reference is a single-process Go binary whose only concurrency is a 16-way
goroutine fan-out over nodes inside findNodesThatFitPod
(vendor/.../generic_scheduler.go:333) — see SURVEY.md §2.3. The TPU-native
equivalent is a `jax.sharding.Mesh`:

- **node axis ("tensor parallelism")**: every [*, N] table and [N, *] carry row is
  sharded over the `nodes` mesh axis. Filtering and per-node scoring are then fully
  local to each shard; only the normalizers (max/min over the feasible set), the
  zone sums, and the winner argmax need cross-shard communication, which XLA inserts
  automatically (all-reduce over ICI) from the sharding annotations — no hand-written
  collectives, exactly the scaling-book recipe.
- **scenario axis ("data parallelism")**: independent what-if simulations (e.g. the
  capacity-planning add-node search evaluating several candidate node counts) are
  vmapped over a leading `scenarios` axis and sharded across it.

N must divide the shard count; `pad_batch_tables` appends infeasible phantom nodes
(static_mask=False everywhere) so placements can never land on padding.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import kernels
from ..simulator.encode import BatchTables, pad_batch_tables as _pad_batch_tables, plugin_flags

NODE_AXIS = "nodes"
SCENARIO_AXIS = "scenarios"


def make_node_mesh(
    n_devices: Optional[int] = None, scenario_axis: int = 1, devices=None
) -> Mesh:
    """Mesh over the first `n_devices` devices. 1-D ('nodes') by default; pass
    scenario_axis>1 for a 2-D ('scenarios', 'nodes') mesh. `devices` overrides the
    default device list (e.g. jax.devices('cpu') for a virtual mesh)."""
    devs = list(devices) if devices is not None else jax.devices()
    n = n_devices if n_devices is not None else len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    devs = np.asarray(devs[:n])
    if scenario_axis > 1:
        if n % scenario_axis:
            raise ValueError(f"{n} devices not divisible by scenario axis {scenario_axis}")
        return Mesh(devs.reshape(scenario_axis, n // scenario_axis),
                    (SCENARIO_AXIS, NODE_AXIS))
    return Mesh(devs, (NODE_AXIS,))


# Node-axis padding lives with the encoder (numpy-only); re-exported here because
# the mesh path is its main consumer.
pad_batch_tables = _pad_batch_tables


def table_shardings(mesh: Mesh) -> kernels.Tables:
    """PartitionSpec per Tables field: node axis sharded, everything else replicated."""
    n = P(None, NODE_AXIS)   # [G, N] / [T, N] / [Tc, N]
    r = P()                  # replicated

    def s(spec):
        return NamedSharding(mesh, spec)

    return kernels.Tables(
        alloc=s(P(NODE_AXIS, None)),
        node_zone=s(P(NODE_AXIS)),
        static_mask=s(n), mask_taint=s(n), mask_unsched=s(n), mask_aff=s(n),
        mask_extra=s(n),
        simon_raw=s(n), nodeaff_raw=s(n), taint_raw=s(n), avoid_raw=s(n),
        image_raw=s(n), extra_raw=s(n),
        grp_requests=s(r), grp_nonzero=s(r), grp_unknown=s(r), grp_ports=s(r),
        counter_dom=s(n), counter_topo=s(r), topo_dom=s(n),
        counter_sel_match_g=s(r),
        req_aff_t=s(r), grp_aff_self=s(r), req_anti_t=s(r),
        pref_t=s(r), pref_w=s(r),
        dns_t=s(r), dns_maxskew=s(r), dns_self=s(r), dns_edom=s(r),
        sa_t=s(r), sa_maxskew=s(r), sa_self=s(r),
        ss_t=s(r), ss_skip=s(r),
        carr_dom=s(n), carr_topo=s(r),
        carr_anti_t=s(r), carr_w_t=s(r), carr_w_w=s(r),
        grp_carries=s(r),
        grp_gpu_mem=s(r), grp_gpu_num=s(r), grp_gpu_pre=s(r), grp_gpu_take=s(r),
        dev_total=s(P(NODE_AXIS, None)),
        grp_lvm_size=s(r), grp_lvm_vg=s(r), grp_sdev_size=s(r), grp_sdev_media=s(r),
        vg_cap=s(P(NODE_AXIS, None)), vg_nameid=s(P(NODE_AXIS, None)),
        sdev_cap=s(P(NODE_AXIS, None)), sdev_media=s(P(NODE_AXIS, None)),
    )


def carry_shardings(mesh: Mesh) -> kernels.Carry:
    def s(spec):
        return NamedSharding(mesh, spec)

    return kernels.Carry(
        requested=s(P(NODE_AXIS, None)),
        nonzero=s(P(NODE_AXIS, None)),
        port_used=s(P(NODE_AXIS, None)),
        counter=s(P()),   # [T, D+1] domain counters are global state → replicated
        carrier=s(P()),
        dev_used=s(P(NODE_AXIS, None)),
        vg_req=s(P(NODE_AXIS, None)),
        sdev_alloc=s(P(NODE_AXIS, None)),
    )


def to_device_sharded(
    bt: BatchTables, mesh: Mesh
) -> Tuple[kernels.Tables, kernels.Carry, BatchTables]:
    """Pad to the mesh's node-shard count and device_put with shardings committed, so
    `kernels.schedule_batch` compiles a distributed program (XLA propagates the
    shardings through the scan and inserts the ICI collectives)."""
    shards = mesh.shape[NODE_AXIS]
    bt = pad_batch_tables(bt, shards)
    ts, cs = table_shardings(mesh), carry_shardings(mesh)
    tables = kernels.Tables(*(
        jax.device_put(np.asarray(v), s) for v, s in zip(tables_from_batch(bt), ts)
    ))
    carry = kernels.Carry(
        requested=jax.device_put(bt.seed_requested, cs.requested),
        nonzero=jax.device_put(bt.seed_nonzero, cs.nonzero),
        port_used=jax.device_put(bt.seed_port_used, cs.port_used),
        counter=jax.device_put(bt.seed_counter, cs.counter),
        carrier=jax.device_put(bt.seed_carrier, cs.carrier),
        dev_used=jax.device_put(bt.seed_dev_used, cs.dev_used),
        vg_req=jax.device_put(bt.seed_vg_req, cs.vg_req),
        sdev_alloc=jax.device_put(bt.seed_sdev_alloc, cs.sdev_alloc),
    )
    return tables, carry, bt


def schedule_batch_on_mesh(bt: BatchTables, mesh: Mesh):
    """Run one schedulePods batch with the node axis sharded over `mesh`.

    Returns (final_carry, choices[P] int32). Choices index the ORIGINAL node list —
    phantom padding is infeasible by construction, so indices never exceed the real N.
    """
    tables, carry, bt = to_device_sharded(bt, mesh)
    enable_gpu, enable_storage = plugin_flags(bt)
    with mesh:
        # simonlint: ignore[naked-dispatch] -- multichip dry-run harness, not
        # an engine hot path: callers own the wedge exposure (bench/tests)
        final, choices = kernels.schedule_batch(
            tables, carry,
            jax.numpy.asarray(bt.pod_group),
            jax.numpy.asarray(bt.forced_node),
            jax.numpy.asarray(bt.valid),
            n_zones=bt.n_zones,
            enable_gpu=enable_gpu,
            enable_storage=enable_storage,
        )
    return final, choices


def schedule_scenarios_on_mesh(bt: BatchTables, mesh: Mesh, seed_requested_s: np.ndarray):
    """DP analog: evaluate S independent what-if scenarios (same cluster + pod batch,
    different starting utilization, e.g. candidate add-node states in the capacity
    planner) in one compiled program. `seed_requested_s` is [S, N, R]; the scenario
    axis shards over the mesh's 'scenarios' axis, the node axis over 'nodes'.
    Returns choices [S, P]."""
    if SCENARIO_AXIS not in mesh.shape:
        raise ValueError("mesh has no scenario axis; build with make_node_mesh(n, scenario_axis=k)")
    shards = mesh.shape[NODE_AXIS]
    bt = pad_batch_tables(bt, shards)
    # Tables are scenario-invariant: same shardings as the 1-D path (node axis
    # sharded, rest replicated over every mesh axis including 'scenarios').
    ts = table_shardings(mesh)
    tables = kernels.Tables(*(
        jax.device_put(np.asarray(v), s) for v, s in zip(tables_from_batch(bt), ts)
    ))
    S = seed_requested_s.shape[0]
    n_pad = bt.seed_requested.shape[0]
    if seed_requested_s.shape[1] > n_pad:
        raise ValueError(
            f"seed_requested_s node axis {seed_requested_s.shape[1]} exceeds the "
            f"padded node count {n_pad}; build seeds against the unpadded cluster "
            f"(or pad_batch_tables(bt, {shards}))"
        )
    if seed_requested_s.shape[1] < n_pad:
        seed_requested_s = np.pad(
            seed_requested_s, ((0, 0), (0, n_pad - seed_requested_s.shape[1]), (0, 0))
        )

    def rep(a):  # broadcast a seed over scenarios
        return np.broadcast_to(a[None], (S,) + a.shape).copy()

    def sh(spec):
        return NamedSharding(mesh, spec)

    carry = kernels.Carry(
        requested=jax.device_put(seed_requested_s.astype(np.float32),
                                 sh(P(SCENARIO_AXIS, NODE_AXIS, None))),
        nonzero=jax.device_put(rep(bt.seed_nonzero), sh(P(SCENARIO_AXIS, NODE_AXIS, None))),
        port_used=jax.device_put(rep(bt.seed_port_used), sh(P(SCENARIO_AXIS, NODE_AXIS, None))),
        counter=jax.device_put(rep(bt.seed_counter), sh(P(SCENARIO_AXIS, None, None))),
        carrier=jax.device_put(rep(bt.seed_carrier), sh(P(SCENARIO_AXIS, None, None))),
        dev_used=jax.device_put(rep(bt.seed_dev_used), sh(P(SCENARIO_AXIS, NODE_AXIS, None))),
        vg_req=jax.device_put(rep(bt.seed_vg_req), sh(P(SCENARIO_AXIS, NODE_AXIS, None))),
        sdev_alloc=jax.device_put(rep(bt.seed_sdev_alloc), sh(P(SCENARIO_AXIS, NODE_AXIS, None))),
    )
    enable_gpu, enable_storage = plugin_flags(bt)
    vmapped = jax.vmap(
        # simonlint: ignore[naked-dispatch] -- multichip dry-run harness, not
        # an engine hot path: callers own the wedge exposure (bench/tests)
        lambda c: kernels.schedule_batch(
            tables, c,
            jax.numpy.asarray(bt.pod_group),
            jax.numpy.asarray(bt.forced_node),
            jax.numpy.asarray(bt.valid),
            n_zones=bt.n_zones,
            enable_gpu=enable_gpu,
            enable_storage=enable_storage,
        )
    )
    with mesh:
        _, choices = vmapped(carry)
    return choices


def tables_from_batch(bt: BatchTables) -> kernels.Tables:
    """Assemble a kernels.Tables from a BatchTables BY FIELD NAME — the single place
    that maps between the two structs, immune to field reordering."""
    return kernels.Tables(**{f: getattr(bt, f) for f in kernels.Tables._fields})


# ----------------------------------------------------------------------------
# Multi-candidate probe fan-out (kernels.probe_*_fanout): the capacity
# planner's candidate lanes are independent what-if scenarios, so the vmapped
# [S] axis shards over the 'scenarios' mesh axis — one candidate node count
# per device — while the tables stay node-sharded/replicated as usual.
# ----------------------------------------------------------------------------


def make_scenario_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """Pure-DP mesh ('scenarios' = n, 'nodes' = 1) for the capacity prober's
    multi-candidate fan-out: each candidate lane lands on its own device and
    no cross-device collectives are needed within a lane."""
    devs = list(devices) if devices is not None else jax.devices()
    n = n_devices if n_devices is not None else len(devs)
    return make_node_mesh(n, scenario_axis=n, devices=devs)


def fanout_shardings(mesh: Mesh):
    """(tables_sharding, carry_s_sharding, active_s_sharding) for the
    probe_*_fanout kernels: tables as in table_shardings (node axis sharded —
    trivially replicated on a pure-scenario mesh), carry leaves and the active
    mask sharded over their leading [S] candidate axis."""

    def s(spec):
        return NamedSharding(mesh, spec)

    carry_s = kernels.Carry(
        requested=s(P(SCENARIO_AXIS, NODE_AXIS, None)),
        nonzero=s(P(SCENARIO_AXIS, NODE_AXIS, None)),
        port_used=s(P(SCENARIO_AXIS, NODE_AXIS, None)),
        counter=s(P(SCENARIO_AXIS, None, None)),
        carrier=s(P(SCENARIO_AXIS, None, None)),
        dev_used=s(P(SCENARIO_AXIS, NODE_AXIS, None)),
        vg_req=s(P(SCENARIO_AXIS, NODE_AXIS, None)),
        sdev_alloc=s(P(SCENARIO_AXIS, NODE_AXIS, None)),
    )
    return table_shardings(mesh), carry_s, s(P(SCENARIO_AXIS, NODE_AXIS))


def put_fanout_inputs(mesh: Mesh, bt: BatchTables, carry_s_np, active_s_np):
    """device_put the probe fan-out inputs with their mesh shardings: returns
    (tables, carry_s, active_s) ready for kernels.probe_*_fanout inside a
    `with mesh:` block. carry_s_np leaves carry a leading [S] axis; S must be
    divisible by the mesh's scenario-axis size."""
    ts, cs, as_ = fanout_shardings(mesh)
    tables = kernels.Tables(*(
        jax.device_put(np.asarray(v), s) for v, s in zip(tables_from_batch(bt), ts)
    ))
    carry_s = kernels.Carry(*(
        jax.device_put(np.asarray(v), s) for v, s in zip(carry_s_np, cs)
    ))
    return tables, carry_s, jax.device_put(np.asarray(active_s_np), as_)
