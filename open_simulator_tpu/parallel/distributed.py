"""Multi-host scaling: jax.distributed + DCN-aware meshes.

The reference is a single Go process; SURVEY.md §2.3 and BASELINE's north star
ask this framework to scale past one host the way distributed schedulers do.
The TPU-native design has two parallel axes with very different communication
profiles, and the mesh layout maps each onto the right fabric:

- **node axis ("nodes")**: the cluster's node dimension. Filtering/scoring is
  embarrassingly parallel per node; the per-step collectives (score
  normalizer min/max, winner argmax, counter broadcasts) are small
  all-reduces that must be CHEAP -> this axis lives on ICI (the chips within
  one slice/host).
- **scenario axis ("scenarios")**: independent what-if simulations (capacity
  probes, the server's concurrent requests). ZERO cross-scenario
  communication -> this axis rides DCN across hosts, where bandwidth is
  scarce but independence makes that irrelevant.

`initialize()` wraps jax.distributed.initialize with the standard env
conventions; `make_global_mesh()` builds the (scenarios, nodes) mesh with the
scenario axis over hosts (DCN) and the node axis within each host (ICI),
falling back to a flat single-host mesh when there is one process. The layout
recipe is the scaling-book one: pick the mesh, annotate shardings
(parallel/mesh.py), let XLA insert the collectives.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from .mesh import NODE_AXIS, SCENARIO_AXIS


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join (or form) a multi-host JAX cluster. Arguments fall back to the
    standard env vars (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID, or the TPU pod metadata on Cloud TPU). Returns True when
    running distributed (process_count > 1), False for single-process runs —
    in which case this is a no-op, so callers can invoke it unconditionally."""
    import jax

    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address or (num_processes or 0) > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    return jax.process_count() > 1


def make_global_mesh(scenario_axis: Optional[int] = None, devices=None):
    """A (scenarios, nodes) jax.sharding.Mesh over every device in the job.

    Multi-process: the scenario axis spans process groups (DCN) and the node
    axis the devices within each process (ICI) — jax.devices() orders devices
    by process, so reshaping to (n_procs * k, per_proc // k) keeps each node
    shard intra-host. Single-process: scenario_axis (default 1) splits the
    local devices. Returns a Mesh usable by schedule_batch_on_mesh /
    schedule_scenarios_on_mesh and the engine's product path."""
    import jax
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    n_procs = getattr(jax, "process_count", lambda: 1)()
    if scenario_axis is None:
        scenario_axis = n_procs if n_procs > 1 else 1
    if n % scenario_axis:
        raise ValueError(
            f"{n} devices not divisible by scenario axis {scenario_axis}")
    grid = np.asarray(devs).reshape(scenario_axis, n // scenario_axis)
    return Mesh(grid, (SCENARIO_AXIS, NODE_AXIS))


def node_mesh_local(devices=None):
    """The single-axis node mesh over this process's addressable devices —
    what the engine uses per-host when scenarios are farmed out at a higher
    level (one capacity probe per host)."""
    import jax

    from .mesh import make_node_mesh

    devs = list(devices) if devices is not None else jax.local_devices()
    return make_node_mesh(len(devs), devices=devs)
