"""Device-mesh parallelism: shard the node axis (and scenario axis) of the batched
scheduler over a jax.sharding.Mesh. See mesh.py for the single-host design notes
and distributed.py for the multi-host (jax.distributed + DCN) layout."""

from .distributed import initialize as initialize_distributed
from .distributed import make_global_mesh, node_mesh_local
from .mesh import (
    NODE_AXIS,
    SCENARIO_AXIS,
    ShardedKernels,
    carry_reshard_bytes,
    extend_tables_on_device,
    fanout_shardings,
    make_node_mesh,
    make_scenario_mesh,
    pad_batch_tables,
    put_fanout_inputs,
    schedule_batch_on_mesh,
    schedule_scenarios_on_mesh,
    sharded_kernels,
    table_shardings,
    carry_shardings,
    tables_from_batch,
    to_device_sharded,
)

__all__ = [
    "initialize_distributed",
    "make_global_mesh",
    "node_mesh_local",
    "NODE_AXIS",
    "SCENARIO_AXIS",
    "ShardedKernels",
    "carry_reshard_bytes",
    "extend_tables_on_device",
    "fanout_shardings",
    "make_node_mesh",
    "make_scenario_mesh",
    "pad_batch_tables",
    "put_fanout_inputs",
    "schedule_batch_on_mesh",
    "schedule_scenarios_on_mesh",
    "sharded_kernels",
    "table_shardings",
    "carry_shardings",
    "tables_from_batch",
    "to_device_sharded",
]
