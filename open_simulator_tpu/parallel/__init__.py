"""Device-mesh parallelism: shard the node axis (and scenario axis) of the batched
scheduler over a jax.sharding.Mesh. See mesh.py for the design notes."""

from .mesh import (
    NODE_AXIS,
    SCENARIO_AXIS,
    make_node_mesh,
    pad_batch_tables,
    schedule_batch_on_mesh,
    schedule_scenarios_on_mesh,
    table_shardings,
    carry_shardings,
    tables_from_batch,
    to_device_sharded,
)

__all__ = [
    "NODE_AXIS",
    "SCENARIO_AXIS",
    "make_node_mesh",
    "pad_batch_tables",
    "schedule_batch_on_mesh",
    "schedule_scenarios_on_mesh",
    "table_shardings",
    "carry_shardings",
    "tables_from_batch",
    "to_device_sharded",
]
