"""Fake-node factory for the capacity planner.

Mirrors NewFakeNodes/NewFakeNode/MakeValidNodeByNode
(/root/reference/pkg/utils/utils.go:885-915,473-492): clone a template node N times
under `simon-<rand5>` names with the hostname label rewritten and the
`simon/new-node` marker label set.
"""

from __future__ import annotations

import copy
import random
from typing import List, Optional

from ..core import constants as C
from ..utils.validate import validate_node


def _rand5(rng: random.Random) -> str:
    # k8s rand.String uses lowercase alphanumerics minus confusables; close enough
    alphabet = "bcdfghjklmnpqrstvwxz2456789"
    return "".join(rng.choice(alphabet) for _ in range(5))


def make_valid_node_by_node(node: dict, nodename: str) -> dict:
    out = copy.deepcopy(node)
    md = out.setdefault("metadata", {})
    md["name"] = nodename
    # Quirk parity with MakeValidNodeByNode: the hostname label is only rewritten
    # when the template had a labels map at all (Go nil-map check, not emptiness).
    if md.get("labels") is None:
        md["labels"] = {}
    else:
        md["labels"][C.LabelHostname] = nodename
    if md.get("annotations") is None:
        md["annotations"] = {}
    md.pop("managedFields", None)
    validate_node(out)
    return out


def new_fake_nodes(
    node: Optional[dict], node_count: int, seed: Optional[int] = None
) -> List[dict]:
    """Clone `node` node_count times with fresh names. `seed` makes names
    deterministic (tests); default is time-seeded like the reference."""
    if node is None and node_count != 0:
        raise ValueError(
            "new node is nil when adding node to cluster, please check whether "
            "newNode in configuration file is empty"
        )
    rng = random.Random(seed)
    nodes = []
    taken = set()
    for _ in range(node_count):
        while True:
            hostname = f"{C.NewNodeNamePrefix}-{_rand5(rng)}"
            if hostname not in taken:
                taken.add(hostname)
                break
        valid = make_valid_node_by_node(node, hostname)
        valid["metadata"].setdefault("labels", {})[C.LabelNewNode] = ""
        nodes.append(valid)
    return nodes


def new_fake_node(node: Optional[dict]) -> dict:
    """Single fake node keeping its own name (server mode's NewNodes handling)."""
    if node is None:
        raise ValueError("new node is nil")
    valid = make_valid_node_by_node(node, (node.get("metadata") or {}).get("name", ""))
    valid["metadata"].setdefault("labels", {})[C.LabelNewNode] = ""
    return valid
