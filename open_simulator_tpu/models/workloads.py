"""Controller simulation: expand workload objects into the pods kube-controller-manager
would create.

Mirrors /root/reference/pkg/utils/utils.go:
- Deployment → synthetic ReplicaSet → pods (:132-171)
- ReplicaSet/ReplicationController → pods (:137-159)
- StatefulSet → ordinal-named pods + volumeClaimTemplates → local-storage annotation
  (:219-292)
- Job / CronJob → `completions` pods (:173-203)
- DaemonSet → one pod per eligible node with node-name matchFields affinity
  (:325-366, :770-815; eligibility = daemon.Predicates, daemon_controller.go:1251-1258)
- MakeValidPod defaulting/sanitization (:378-463)

Pod names follow the reference convention `<owner>-<suffix>` (SetObjectMetaFromObject,
utils.go:295-323); suffixes here are deterministic (monotone counter rendered as 10
lowercase alnum chars) instead of random, which keeps simulations reproducible.
"""

from __future__ import annotations

import copy
import itertools
import json
from typing import List

from ..core import constants as C
from ..utils.objutil import (
    find_untolerated_taint,
    name_of,
    namespace_of,
    pod_matches_node_affinity,
    set_annotation,
    set_label,
)
from ..utils.quantity import parse_quantity
from ..utils.validate import validate_pod

_counter = itertools.count(1)
_ALPHABET = "abcdefghijklmnopqrstuvwxyz0123456789"


def _suffix() -> str:
    """Deterministic 10-char suffix (stands in for apimachinery rand.String(10))."""
    n = next(_counter)
    chars = []
    for _ in range(10):
        n, r = divmod(n, len(_ALPHABET))
        chars.append(_ALPHABET[r])
    return "".join(chars)


def reset_name_counter() -> None:
    """Test hook: restart suffix sequence."""
    global _counter
    _counter = itertools.count(1)


def _uid() -> str:
    return f"uid-{next(_counter):08d}"


def _object_meta_from(owner: dict, template: dict, kind: str) -> dict:
    """ObjectMeta for a controller-created pod (SetObjectMetaFromObject, utils.go:295-323)."""
    tmeta = template.get("metadata") or {}
    return {
        "name": f"{name_of(owner)}-{_suffix()}",
        "namespace": namespace_of(owner),
        "uid": _uid(),
        "generateName": name_of(owner),
        "labels": copy.deepcopy(tmeta.get("labels") or {}),
        "annotations": copy.deepcopy(tmeta.get("annotations") or {}),
        "ownerReferences": [
            {
                "apiVersion": owner.get("apiVersion", "apps/v1"),
                "kind": kind,
                "name": name_of(owner),
                "uid": (owner.get("metadata") or {}).get("uid", ""),
                "controller": True,
                "blockOwnerDeletion": True,
            }
        ],
    }


def make_valid_pod(pod: dict) -> dict:
    """Defaulting + sanitization (MakeValidPod, utils.go:378-463): default namespace/
    dnsPolicy/restartPolicy/schedulerName; strip env/mounts/probes/imagePullSecrets/
    managedFields/status; PVC volumes become hostPath /tmp; then validate."""
    pod = copy.deepcopy(pod)
    meta = pod.setdefault("metadata", {})
    meta.setdefault("labels", {})
    meta.setdefault("annotations", {})
    if not meta.get("namespace"):
        meta["namespace"] = "default"
    meta.pop("managedFields", None)
    spec = pod.setdefault("spec", {})
    spec.setdefault("dnsPolicy", "ClusterFirst")
    spec.setdefault("restartPolicy", "Always")
    spec.setdefault("schedulerName", C.DefaultSchedulerName)
    spec.pop("imagePullSecrets", None)
    for key in ("containers", "initContainers"):
        for c in spec.get(key) or []:
            c.setdefault("terminationMessagePolicy", "FallbackToLogsOnError")
            c.setdefault("imagePullPolicy", "IfNotPresent")
            if (c.get("securityContext") or {}).get("privileged") is not None:
                c["securityContext"]["privileged"] = False
            c.pop("volumeMounts", None)
            c.pop("env", None)
            if key == "containers":
                c.pop("livenessProbe", None)
                c.pop("readinessProbe", None)
                c.pop("startupProbe", None)
    for v in spec.get("volumes") or []:
        if "persistentVolumeClaim" in v:
            v.pop("persistentVolumeClaim")
            v["hostPath"] = {"path": "/tmp"}
    pod["status"] = {}
    validate_pod(pod)
    return pod


def make_valid_pod_by_pod(pod: dict) -> dict:
    """MakeValidPodByPod (utils.go:368-376): fresh UID + sanitize."""
    pod = copy.deepcopy(pod)
    pod.setdefault("metadata", {})["uid"] = _uid()
    return make_valid_pod(pod)


def _add_workload_info(pod: dict, kind: str, name: str, namespace: str) -> dict:
    set_annotation(pod, C.AnnoWorkloadKind, kind)
    set_annotation(pod, C.AnnoWorkloadName, name)
    set_annotation(pod, C.AnnoWorkloadNamespace, namespace)
    return pod


def _stamp_sig_memo(pods: List[dict]) -> List[dict]:
    """Pods expanded from one workload template are scheduling-identical: compute
    the group signature once and memoize it on every replica (the engine pops the
    marker when emitting results). Cuts the per-pod host encode cost for large
    replica counts to O(1) per workload."""
    if len(pods) > 1:
        from ..simulator.encode import scheduling_signature

        sig = scheduling_signature(pods[0])
        for p in pods:
            p["__sig_memo__"] = sig
    return pods


def _pods_from_template(owner: dict, kind: str, replicas: int, template: dict) -> List[dict]:
    """Replicas of one template differ only in metadata.name/uid (generated
    here), so defaulting + sanitization + validation run ONCE on a prototype
    and the remaining replicas are byte-copies with fresh name/uid — the
    reference fans this out over goroutines instead
    (pkg/simulator/utils.go:77-115); one validated prototype is both faster
    and equally exact, since make_valid_pod is deterministic and never reads
    the generated name."""
    if replicas <= 0:
        return _stamp_sig_memo([])
    proto = {
        "metadata": _object_meta_from(owner, template, kind),
        "spec": copy.deepcopy(template.get("spec") or {}),
    }
    proto = make_valid_pod(proto)
    _add_workload_info(proto, kind, name_of(owner), namespace_of(owner))
    pods = [proto]
    if replicas > 1:
        import pickle

        owner_name = name_of(owner)
        blob = pickle.dumps(proto, -1)  # ~3x faster than deepcopy for dicts
        for _ in range(replicas - 1):
            pod = pickle.loads(blob)
            md = pod["metadata"]
            md["name"] = f"{owner_name}-{_suffix()}"
            md["uid"] = _uid()
            pods.append(pod)
    return _stamp_sig_memo(pods)


def pods_from_replicaset(rs: dict) -> List[dict]:
    spec = rs.get("spec") or {}
    replicas = spec.get("replicas", 1)
    if replicas is None:
        replicas = 1
    return _pods_from_template(rs, C.ReplicaSet, int(replicas), spec.get("template") or {})


def pods_from_replicationcontroller(rc: dict) -> List[dict]:
    spec = rc.get("spec") or {}
    replicas = spec.get("replicas", 1)
    if replicas is None:
        replicas = 1
    return _pods_from_template(rc, C.ReplicationController, int(replicas), spec.get("template") or {})


def pods_from_deployment(deploy: dict) -> List[dict]:
    """Deployment → synthetic RS (name `<deploy>-<suffix>`) → pods (utils.go:132-171)."""
    spec = deploy.get("spec") or {}
    rs = {
        "apiVersion": "apps/v1",
        "kind": C.ReplicaSet,
        "metadata": _object_meta_from(deploy, spec.get("template") or {}, C.Deployment),
        "spec": {
            "selector": spec.get("selector"),
            "replicas": spec.get("replicas", 1),
            "template": spec.get("template") or {},
        },
    }
    return pods_from_replicaset(rs)


def pods_from_statefulset(sts: dict) -> List[dict]:
    """STS pods are renamed `<sts>-<ordinal>`; volumeClaimTemplates with open-local/yoda
    storage classes are serialized into the pod local-storage annotation
    (utils.go:219-292)."""
    spec = sts.get("spec") or {}
    replicas = spec.get("replicas", 1)
    if replicas is None:
        replicas = 1
    pods = _pods_from_template(sts, C.StatefulSet, int(replicas), spec.get("template") or {})
    for ordinal, pod in enumerate(pods):
        pod["metadata"]["name"] = f"{name_of(sts)}-{ordinal}"
    _set_storage_annotation(pods, spec.get("volumeClaimTemplates") or [], name_of(sts))
    # the storage annotation is signature-relevant: re-stamp after writing it
    for pod in pods:
        pod.pop("__sig_memo__", None)
    return _stamp_sig_memo(pods)


_LVM_SCS = {C.OpenLocalSCNameLVM, C.YodaSCNameLVM}
_SSD_SCS = {C.OpenLocalSCNameDeviceSSD, C.OpenLocalSCNameMountPointSSD, C.YodaSCNameDeviceSSD, C.YodaSCNameMountPointSSD}
_HDD_SCS = {C.OpenLocalSCNameDeviceHDD, C.OpenLocalSCNameMountPointHDD, C.YodaSCNameDeviceHDD, C.YodaSCNameMountPointHDD}


def _set_storage_annotation(pods: List[dict], volume_claim_templates: List[dict], sts_name: str) -> None:
    # Wire format matches the reference's Volume struct (utils.go:515-521): size is a
    # string-encoded int64 (json:"size,string"), storage class under "scName".
    volumes = []
    for pvc in volume_claim_templates:
        sc = (pvc.get("spec") or {}).get("storageClassName")
        size = parse_quantity(
            (((pvc.get("spec") or {}).get("resources") or {}).get("requests") or {}).get("storage", 0)
        )
        if sc in _LVM_SCS:
            volumes.append({"size": str(int(size)), "kind": "LVM", "scName": sc})
        elif sc in _SSD_SCS:
            volumes.append({"size": str(int(size)), "kind": "SSD", "scName": sc})
        elif sc in _HDD_SCS:
            volumes.append({"size": str(int(size)), "kind": "HDD", "scName": sc})
        # unknown storage classes are logged-and-skipped by the reference
    payload = json.dumps({"volumes": volumes})
    for pod in pods:
        set_annotation(pod, C.AnnoPodLocalStorage, payload)


def pods_from_job(job: dict) -> List[dict]:
    spec = job.get("spec") or {}
    completions = spec.get("completions", 1)
    if completions is None:
        completions = 1
    return _pods_from_template(job, C.Job, int(completions), spec.get("template") or {})


def pods_from_cronjob(cronjob: dict) -> List[dict]:
    """CronJob → one synthetic Job instance (utils.go:173-218)."""
    spec = cronjob.get("spec") or {}
    job_template = (spec.get("jobTemplate") or {}).get("spec") or {}
    tmpl = job_template.get("template") or {}
    job = {
        "apiVersion": "batch/v1",
        "kind": C.Job,
        "metadata": _object_meta_from(cronjob, tmpl, C.CronJob),
        "spec": job_template,
    }
    return pods_from_job(job)


# ------------------------------------------------------------------ DaemonSet ----------


def set_daemon_pod_node_affinity(pod: dict, node_name: str) -> None:
    """Pin a daemon pod to one node via matchFields metadata.name affinity, preserving
    each existing required term's matchExpressions (utils.go:770-815)."""
    req = {"key": "metadata.name", "operator": "In", "values": [node_name]}
    spec = pod.setdefault("spec", {})
    affinity = spec.setdefault("affinity", {})
    node_aff = affinity.setdefault("nodeAffinity", {})
    required = node_aff.get("requiredDuringSchedulingIgnoredDuringExecution")
    if not required or not required.get("nodeSelectorTerms"):
        node_aff["requiredDuringSchedulingIgnoredDuringExecution"] = {
            "nodeSelectorTerms": [{"matchFields": [req]}]
        }
        return
    for term in required["nodeSelectorTerms"]:
        term["matchFields"] = [req]


def node_should_run_pod(node: dict, pod: dict) -> bool:
    """daemon.Predicates (daemon_controller.go:1251-1258): nodeName fit, nodeSelector +
    required affinity fit, and NoSchedule/NoExecute taints tolerated."""
    node_name = (pod.get("spec") or {}).get("nodeName")
    if node_name and node_name != name_of(node):
        return False
    if not pod_matches_node_affinity(pod, node):
        return False
    if find_untolerated_taint(node, pod, ("NoSchedule", "NoExecute")) is not None:
        return False
    return True


def pods_from_daemonset(ds: dict, nodes: List[dict]) -> List[dict]:
    """One pinned pod per node passing daemon.Predicates (utils.go:337-366)."""
    pods = []
    spec = ds.get("spec") or {}
    template = spec.get("template") or {}
    for node in nodes:
        pod = {
            "metadata": _object_meta_from(ds, template, C.DaemonSet),
            "spec": copy.deepcopy(template.get("spec") or {}),
        }
        set_daemon_pod_node_affinity(pod, name_of(node))
        pod = make_valid_pod(pod)
        _add_workload_info(pod, C.DaemonSet, name_of(ds), namespace_of(ds))
        if node_should_run_pod(node, pod):
            pods.append(pod)
    if len(pods) > 1:
        # DS pods differ only by their per-node pin, which the engine strips
        # before grouping; the shared signature is the UNPINNED template's.
        tmpl_pod = make_valid_pod({
            "metadata": _object_meta_from(ds, template, C.DaemonSet),
            "spec": copy.deepcopy(template.get("spec") or {}),
        })
        _add_workload_info(tmpl_pod, C.DaemonSet, name_of(ds), namespace_of(ds))
        from ..simulator.encode import scheduling_signature

        sig = scheduling_signature(tmpl_pod)
        for p in pods:
            p["__sig_memo__"] = sig
    return pods


# --------------------------------------------------------------- fake nodes -----------


def make_valid_node(node: dict, node_name: str) -> dict:
    """Rename + hostname label + UID + validate (MakeValidNodeByNode, utils.go:473-492)."""
    node = copy.deepcopy(node)
    meta = node.setdefault("metadata", {})
    meta["name"] = node_name
    meta["uid"] = _uid()
    meta.setdefault("labels", {})[C.LabelHostname] = node_name
    meta.setdefault("annotations", {})
    meta.pop("managedFields", None)
    from ..utils.validate import validate_node

    validate_node(node)
    return node


# ---------------------------------------------------------- app/cluster expand --------


def expand_workloads_excluding_daemonsets(rt) -> List[dict]:
    """GetValidPodExcludeDaemonSet (pkg/simulator/utils.go:79-230): raw pods + every
    workload kind except DaemonSet, which needs the node list."""
    pods: List[dict] = []
    for pod in rt.pods:
        pods.append(make_valid_pod_by_pod(pod))
    for deploy in rt.deployments:
        pods.extend(pods_from_deployment(deploy))
    for rs in rt.replica_sets:
        pods.extend(pods_from_replicaset(rs))
    for rc in rt.replication_controllers:
        pods.extend(pods_from_replicationcontroller(rc))
    for sts in rt.stateful_sets:
        pods.extend(pods_from_statefulset(sts))
    for job in rt.jobs:
        pods.extend(pods_from_job(job))
    for cj in rt.cron_jobs:
        pods.extend(pods_from_cronjob(cj))
    return pods


def generate_valid_pods_from_app(app_name: str, rt, nodes: List[dict]) -> List[dict]:
    """GenerateValidPodsFromAppResources (pkg/simulator/utils.go:37-74): expand all
    workloads, pin DaemonSet pods per node, then stamp the app-name label."""
    pods = expand_workloads_excluding_daemonsets(rt)
    for ds in rt.daemon_sets:
        pods.extend(pods_from_daemonset(ds, nodes))
    # The app-name label lands AFTER expansion stamped the signature memos, and
    # labels are part of the scheduling signature — refresh each workload's memo
    # (one recompute per distinct old memo, still O(1) per replica) so identical
    # templates from different apps never share a scheduling group. DaemonSet
    # memos keep the documented invariant of being the UNPINNED template's
    # signature (pods_from_daemonset), so the per-node pin is stripped first.
    from ..simulator.encode import SIG_MEMO_KEY, scheduling_signature, strip_daemon_pin

    remapped: dict = {}
    for pod in pods:
        set_label(pod, C.LabelAppName, app_name)
        old = pod.pop(SIG_MEMO_KEY, None)
        if old is not None:
            new = remapped.get(old)
            if new is None:
                stripped, target = strip_daemon_pin(pod)
                new = remapped[old] = scheduling_signature(
                    stripped if target is not None else pod
                )
            pod[SIG_MEMO_KEY] = new
    return pods
