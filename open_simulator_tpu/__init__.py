"""tpu-simon: a TPU-native Kubernetes cluster simulator.

Same capabilities as alibaba/open-simulator — fake cluster from YAML/kubeconfig,
controller simulation, full kube-scheduler placement semantics, capacity planning,
GPU-share / local-storage extended resources — with a batched JAX/XLA scheduling core.
"""

from .core.types import AppResource, NodeStatus, ResourceTypes, SimulateResult, UnscheduledPod
from .simulator.core import simulate

__version__ = "0.1.0"

__all__ = [
    "AppResource",
    "NodeStatus",
    "ResourceTypes",
    "SimulateResult",
    "UnscheduledPod",
    "simulate",
    "__version__",
]
