"""simonfault + simonguard: first-party robustness layer — policies, fault
injection, crash-consistent simulation state, and mid-run device-failure
containment.

The reference inherits its failure behavior from client-go and kube-scheduler
for free (informer relists, rate-limited retries, the scheduler's error
funnel); this rebuild owns every network call and device dispatch itself, so
it owns the failure semantics too. Four parts:

- `policy` — composable `RetryPolicy` (exponential backoff, deterministic
  seeded jitter, max-attempts/max-elapsed), `Deadline` (contextvar-propagated
  budget that callees slice), and a `CircuitBreaker` for the live-cluster
  client. All instrumented via obs/instruments.py.
- `faults` — named fault sites threaded through the hot paths with a seeded
  `FaultPlan` (fail arrival k at site s with error class e), activatable from
  tests, `simon apply --fault-plan`, and the server's /debug/fault-plan
  endpoint. Injection is reproducible bit-for-bit: a seeded plan fires the
  same (site, arrival) pairs on every replay.
- crash consistency lives in the engine itself (simulator/engine.py
  `Simulator._transaction`): any failure — injected or real — after partial
  device work rolls host-visible state (placements, census, commit/rollback
  metric reconciliation) back to exactly the pre-call state.
- `guard` (simonguard) — what happens NEXT after the rollback: watchdog-
  supervised dispatch (wedged backends are quarantined and the run fails
  over to CPU, resuming from the last committed segment; a real — not
  injected — wedge may later be lifted by a bounded subprocess re-probe,
  once per OPEN_SIMULATOR_QUARANTINE_REPROBE_S window), device-OOM
  containment by pod-batch bisection (split-vs-unsplit placements are
  bit-identical), and a crash-consistent fsync'd capacity-search journal
  (`simon apply --resume-journal` skips completed probes; a digest guard
  rejects a stale journal).

Fault-site catalog (the injection error class and the invariant the tests
assert for each; README "Failure handling" carries the same table):

  site            injected as            invariant asserted
  --------------  ---------------------  ------------------------------------
  live_get        Transient/Auth/        retried per policy (Retry-After
                  Protocol error         floors honored); 401 never retried
  encode          FaultInjected          rollback: census/pod dicts/metric
                                         reconciliation bit-identical
  to_device       FaultInjected          same rollback invariant
  dispatch        FaultInjected          same rollback invariant
  fetch           FaultInjected          same rollback invariant
  commit          FaultInjected          partial batch (k-1 commits) fully
                                         rolled back, counters reconciled
  preempt_evict   FaultInjected          evictions undone, victims restored
  watchdog_wedge  BackendWedged (via     quarantine + CPU failover resumes
                  guard.supervised)      from the committed prefix; final
                                         placements == fault-free run
  oom_to_device   FaultInjected,         batch bisected in halves; split
                  classified as OOM      placements bit-identical to unsplit
  oom_dispatch    FaultInjected,         same bisection invariant; floor
                  classified as OOM      exhaustion fails over to CPU
  journal_write   FaultInjected          journal's valid prefix survives; a
                                         resumed search reaches the same
                                         nodes_added without re-probing
"""

from .faults import (
    SITES,
    FaultInjected,
    FaultPlan,
    FaultSpec,
    active_plan,
    clear_plan,
    install_plan,
    installed,
    maybe_fail,
)
from .guard import (
    BackendWedged,
    GuardError,
    JournalMismatch,
    OOMBisectionExhausted,
    SearchJournal,
    containment_cause,
    oom_site,
    supervised,
)
from .policy import (
    BreakerOpen,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    check_deadline,
    deadline_remaining,
)

__all__ = [
    "SITES",
    "FaultInjected",
    "FaultPlan",
    "FaultSpec",
    "active_plan",
    "clear_plan",
    "install_plan",
    "installed",
    "maybe_fail",
    "BackendWedged",
    "GuardError",
    "JournalMismatch",
    "OOMBisectionExhausted",
    "SearchJournal",
    "containment_cause",
    "oom_site",
    "supervised",
    "BreakerOpen",
    "CircuitBreaker",
    "Deadline",
    "DeadlineExceeded",
    "RetryPolicy",
    "check_deadline",
    "deadline_remaining",
]
