"""simonfault: first-party robustness layer — policies, fault injection,
crash-consistent simulation state.

The reference inherits its failure behavior from client-go and kube-scheduler
for free (informer relists, rate-limited retries, the scheduler's error
funnel); this rebuild owns every network call and device dispatch itself, so
it owns the failure semantics too. Three parts:

- `policy` — composable `RetryPolicy` (exponential backoff, deterministic
  seeded jitter, max-attempts/max-elapsed), `Deadline` (contextvar-propagated
  budget that callees slice), and a `CircuitBreaker` for the live-cluster
  client. All instrumented via obs/instruments.py.
- `faults` — named fault sites threaded through the hot paths with a seeded
  `FaultPlan` (fail arrival k at site s with error class e), activatable from
  tests, `simon apply --fault-plan`, and the server's /debug/fault-plan
  endpoint. Injection is reproducible bit-for-bit: a seeded plan fires the
  same (site, arrival) pairs on every replay.
- crash consistency lives in the engine itself (simulator/engine.py
  `Simulator._transaction`): any failure — injected or real — after partial
  device work rolls host-visible state (placements, census, commit/rollback
  metric reconciliation) back to exactly the pre-call state.
"""

from .faults import (
    SITES,
    FaultInjected,
    FaultPlan,
    FaultSpec,
    active_plan,
    clear_plan,
    install_plan,
    installed,
    maybe_fail,
)
from .policy import (
    BreakerOpen,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    check_deadline,
    deadline_remaining,
)

__all__ = [
    "SITES",
    "FaultInjected",
    "FaultPlan",
    "FaultSpec",
    "active_plan",
    "clear_plan",
    "install_plan",
    "installed",
    "maybe_fail",
    "BreakerOpen",
    "CircuitBreaker",
    "Deadline",
    "DeadlineExceeded",
    "RetryPolicy",
    "check_deadline",
    "deadline_remaining",
]
